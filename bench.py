"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.md): p50 gang-allocate latency for a
256-host vcjob onto a simulated TPU slice (driver target < 2s), plus
chip utilization under 2-queue contention (target >= 0.95) in the same
line.  vs_baseline = target_latency / measured_p50 (>1 beats target).

Mirrors the reference's benchmark/ KWOK harness: fake slice hosts,
real scheduler, wall-clock latency of the full scheduling cycle
(snapshot -> enqueue -> allocate -> bind flush).
"""

from __future__ import annotations

import json
import statistics
import time


BENCH_CONF = {
    "actions": "enqueue, allocate, backfill",
    "tiers": [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "conformance"}]},
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"}, {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "binpack"},
                     {"name": "deviceshare"},
                     {"name": "network-topology-aware"}]},
    ],
}

TARGET_P50_S = 2.0
TRIALS = 12


def bench_gang_allocate_latency() -> float:
    """p50 wall-clock of one full cycle placing a 256-host gang onto a
    v5p-1024 slice (256 hosts x 4 chips) amid competing slices."""
    from volcano_tpu.api.podgroup import NetworkTopologySpec
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import NetworkTopologyMode
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job

    latencies = []
    for trial in range(TRIALS):
        cluster = make_tpu_cluster([
            ("target", "v5p-1024"),     # 256 hosts
            ("noise-a", "v5e-256"),     # 64 hosts
            ("noise-b", "v5e-64"),      # 16 hosts
        ])
        pg, pods = gang_job(
            f"train-{trial}", replicas=256, requests={"cpu": 8, TPU: 4},
            network_topology=NetworkTopologySpec(
                NetworkTopologyMode.HARD, 1))
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
        sched = Scheduler(cluster, conf=BENCH_CONF, schedule_period=0)
        t0 = time.perf_counter()
        sched.run_once()
        dt = time.perf_counter() - t0
        assert len(cluster.binds) == 256, \
            f"gang did not fully place: {len(cluster.binds)}/256"
        latencies.append(dt)
    return statistics.median(latencies)


def bench_utilization_under_contention() -> float:
    """Two queues (3:1) flooding a 2-slice cluster with gang jobs sized
    to their shares; steady-state chip utilization after 4 cycles."""
    from volcano_tpu.api.queue import Queue
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job
    from volcano_tpu.api.types import TaskStatus

    cluster = make_tpu_cluster([("sa", "v5e-64"), ("sb", "v5e-64")])
    total_chips = 2 * 64  # 2 slices x 16 hosts x 4 chips
    cluster.add_queue(Queue(name="prod", weight=3))
    cluster.add_queue(Queue(name="dev", weight=1))
    # prod: 6 jobs x 4 hosts; dev: 6 jobs x 2 hosts -> demand 144 chips
    # over 128 available => sustained contention
    jobs = [("prod", 4, 6), ("dev", 2, 6)]
    for queue, hosts, count in jobs:
        for i in range(count):
            pg, pods = gang_job(f"{queue}-j{i}", queue=queue,
                                replicas=hosts,
                                requests={"cpu": 8, TPU: 4})
            cluster.add_podgroup(pg)
            for p in pods:
                cluster.add_pod(p)

    sched = Scheduler(cluster, conf=BENCH_CONF, schedule_period=0)
    for _ in range(4):
        sched.run_once()
        cluster.tick()

    used = sum(
        p.resource_requests().get(TPU) for p in cluster.pods.values()
        if p.node_name and p.phase in (TaskStatus.RUNNING, TaskStatus.BOUND))
    return used / total_chips


def bench_reference_gang_shape() -> float:
    """The reference harness's default gang scenario (benchmark/README
    JOBS=10, REPLICAS=100, MIN_AVAILABLE=100 over 100 nodes): seconds
    until all 1000 pods are bound."""
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.uthelper import gang_job

    cluster = FakeCluster()
    for i in range(100):
        cluster.add_node(Node(name=f"n{i}",
                              allocatable={"cpu": 112, "pods": 256}))
    for j in range(10):
        pg, pods = gang_job(f"job{j}", replicas=100, requests={"cpu": 1})
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
    sched = Scheduler(cluster, conf=BENCH_CONF, schedule_period=0)
    t0 = time.perf_counter()
    for _ in range(50):  # bounded: a stall must fail, not hang the driver
        sched.run_once()
        cluster.tick()
        if len(cluster.binds) >= 1000:
            break
    assert len(cluster.binds) >= 1000, \
        f"gang shape stalled at {len(cluster.binds)}/1000 binds"
    return time.perf_counter() - t0


def bench_agent_scheduler_throughput() -> float:
    """Fast-path pods/second over a 500-pod burst (the reference's
    bare-pod benchmark default, benchmark/README PODS=500)."""
    from volcano_tpu.agentscheduler import AgentScheduler
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.shard import AGENT_SCHEDULER
    from volcano_tpu.cache.fake_cluster import FakeCluster

    cluster = FakeCluster()
    for i in range(20):
        cluster.add_node(Node(name=f"n{i}",
                              allocatable={"cpu": 64, "pods": 256}))
    sched = AgentScheduler(cluster)
    for i in range(500):
        pod = make_pod(f"a{i}", requests={"cpu": "100m"})
        pod.scheduler_name = AGENT_SCHEDULER
        cluster.add_pod(pod)
    t0 = time.perf_counter()
    bound = sched.run_until_drained()
    dt = time.perf_counter() - t0
    assert bound == 500, f"agent bound {bound}/500"
    return bound / dt


def main():
    p50 = bench_gang_allocate_latency()
    utilization = bench_utilization_under_contention()
    gang_shape_s = bench_reference_gang_shape()
    agent_pps = bench_agent_scheduler_throughput()
    print(json.dumps({
        "metric": "p50_gang_allocate_latency_256host_v5p1024",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": round(TARGET_P50_S / p50, 2),
        "extra": {
            "chip_utilization_under_contention": round(utilization, 4),
            "utilization_target": 0.95,
            "reference_gang_shape_1000pods_s": round(gang_shape_s, 4),
            "agent_scheduler_pods_per_s": round(agent_pps),
            "trials": TRIALS,
            "cluster_hosts": 256 + 64 + 16,
        },
    }))


if __name__ == "__main__":
    main()
