"""Benchmark harness — prints ONE JSON line for the driver.

Headline metric (BASELINE.md): p50 gang-allocate latency for a
256-host vcjob onto a simulated TPU slice (driver target < 2s), plus
chip utilization under 2-queue contention (target >= 0.95) in the same
line.  vs_baseline = target_latency / measured_p50 (>1 beats target).

Mirrors the reference's benchmark/ KWOK harness: fake slice hosts,
real scheduler, wall-clock latency of the full scheduling cycle
(snapshot -> enqueue -> allocate -> bind flush).
"""

from __future__ import annotations

import json
import random
import statistics
import time
from typing import Optional, Tuple

from volcano_tpu.api import elastic as eapi


BENCH_CONF = {
    "actions": "enqueue, allocate, backfill",
    "tiers": [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "conformance"}]},
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"}, {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "binpack"},
                     {"name": "deviceshare"},
                     {"name": "network-topology-aware"}]},
    ],
}

TARGET_P50_S = 2.0
TRIALS = 12

# bf16 peak FLOP/s per chip, for MFU (shared by both TPU children)
# bf16 MXU peak per chip — the MFU denominator.  v5e's bf16 peak is
# 197 TFLOP/s (394 is its INT8 TOPS figure; rounds 1-4 used 394 here,
# halving every reported v5e MFU — the r3 builder-observed "MFU 0.31"
# is 0.62 against the correct bf16 peak; see docs/MFU_PLAN.md).
TPU_PEAK_FLOPS = {"TPU v5e": 197e12, "TPU v5 lite": 197e12,
                  "TPU v5p": 459e12, "TPU v4": 275e12,
                  "TPU v6e": 918e12}


def bench_gang_allocate_latency() -> float:
    """p50 wall-clock of one full cycle placing a 256-host gang onto a
    v5p-1024 slice (256 hosts x 4 chips) amid competing slices."""
    from volcano_tpu.api.podgroup import NetworkTopologySpec
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import NetworkTopologyMode
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job

    latencies = []
    for trial in range(TRIALS):
        cluster = make_tpu_cluster([
            ("target", "v5p-1024"),     # 256 hosts
            ("noise-a", "v5e-256"),     # 64 hosts
            ("noise-b", "v5e-64"),      # 16 hosts
        ])
        pg, pods = gang_job(
            f"train-{trial}", replicas=256, requests={"cpu": 8, TPU: 4},
            network_topology=NetworkTopologySpec(
                NetworkTopologyMode.HARD, 1))
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
        sched = Scheduler(cluster, conf=BENCH_CONF, schedule_period=0)
        t0 = time.perf_counter()
        sched.run_once()
        dt = time.perf_counter() - t0
        assert len(cluster.binds) == 256, \
            f"gang did not fully place: {len(cluster.binds)}/256"
        latencies.append(dt)
    return statistics.median(latencies)


def bench_utilization_under_contention() -> float:
    """Fragmented-slice contention (VERDICT r3 next-round #5: the old
    2-queue scenario pinned at 1.0 and stopped discriminating).

    Two v5e-64 multi-host slices (whole-host atomic) + a bank of 8
    single-host v5e-4 slices (sub-host packable): dev floods BOTH —
    1-host whole jobs scattered across the big slices, 1-2 chip packs
    fragmenting the bank — then prod (weight 3) submits slice-LOCAL
    4-host gangs (hard tier-1), so reclaim must free four hosts in
    the SAME slice, not just anywhere; dev churn (random completions
    + replacement arrivals every other cycle) keeps flipping the
    picture.  Reported number = MEAN chip utilization sampled at
    every cycle of the churn window — reclaim evictions, topology-
    blocked gangs and bank fragmentation all show up as sub-1.0
    headroom (target >= 0.95)."""
    import random as _random

    from volcano_tpu.api.podgroup import NetworkTopologySpec
    from volcano_tpu.api.queue import Queue
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import NetworkTopologyMode, TaskStatus
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job

    rng = _random.Random(7)
    cluster = make_tpu_cluster(
        [("sa", "v5e-64"), ("sb", "v5e-64")] +
        [(f"bank{i}", "v5e-4") for i in range(8)])
    total_chips = 2 * 64 + 8 * 4       # 160
    cluster.add_queue(Queue(name="prod", weight=3))
    cluster.add_queue(Queue(name="dev", weight=1, reclaimable=True))

    conf = {
        # gangreclaim owns hard-topology jobs (plain reclaim skips
        # them): freeing four hosts in ONE slice is its job
        "actions": "enqueue, allocate, preempt, reclaim, "
                   "gangreclaim, backfill",
        "tiers": BENCH_CONF["tiers"],
    }
    sched = Scheduler(cluster, conf=conf, schedule_period=0)

    dev_seq = 0

    def submit_dev(hosts_jobs, packs):
        nonlocal dev_seq
        for _ in range(hosts_jobs):    # whole-host single jobs
            pg, pods = gang_job(f"dev-{dev_seq}", queue="dev",
                                replicas=1,
                                requests={"cpu": 8, TPU: 4})
            dev_seq += 1
            cluster.add_podgroup(pg)
            for p in pods:
                cluster.add_pod(p)
        for _ in range(packs):         # sub-host packs (bank only)
            pg, pods = gang_job(f"dev-{dev_seq}", queue="dev",
                                replicas=1,
                                requests={"cpu": 2,
                                          TPU: rng.choice((1, 1, 2))})
            dev_seq += 1
            cluster.add_podgroup(pg)
            for p in pods:
                cluster.add_pod(p)

    def running_dev():
        return [p for p in cluster.pods.values()
                if p.name.startswith("dev-")
                and p.phase is TaskStatus.RUNNING]

    def utilization():
        used = sum(p.resource_requests().get(TPU)
                   for p in cluster.pods.values()
                   if p.node_name and p.phase in (TaskStatus.RUNNING,
                                                  TaskStatus.BOUND))
        return used / total_chips

    # phase 1: dev saturates — 28 whole hosts scattered over the big
    # slices + 16 sub-host packs fragmenting the bank
    submit_dev(28, 16)
    for _ in range(3):
        sched.run_once()
        cluster.tick()

    # phase 2: prod slice-local gangs demand 96 of the 128 big-slice
    # chips; freeing four hosts in ONE slice forces targeted reclaim
    for i in range(6):
        pg, pods = gang_job(
            f"prod-j{i}", queue="prod", replicas=4,
            requests={"cpu": 8, TPU: 4},
            network_topology=NetworkTopologySpec(
                NetworkTopologyMode.HARD, 1))
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)

    samples = []
    for cycle in range(14):
        if cycle % 2 == 1:
            # churn: ~20% of running dev work finishes; replacements
            # arrive (half whole-host, half packs)
            done = [p for p in running_dev() if rng.random() < 0.2]
            for p in done:
                cluster.complete_pod(p.key)
            submit_dev(len(done) // 2, len(done) - len(done) // 2)
        sched.run_once()
        cluster.tick()
        samples.append(utilization())
    return sum(samples) / len(samples)


# -- wire-path benchmarks ---------------------------------------------
#
# The reference derives its entire latency methodology from apiserver
# audit logs (third_party/kube-apiserver-audit-exporter/exporter/
# metrics.go:32-38); every headline number above is an in-process
# function call that never pays admission, serialization or watch
# fan-out.  These scenarios boot the REAL control plane — state-server
# process, leader-elected scheduler process, controller-manager
# process — submit work through the wire client, and report latency
# derived from the server's audit trail (server/audit_exporter.py),
# i.e. measured OUTSIDE the scheduler at the product's own wire
# boundary.

class _WirePlane:
    """Boots and reaps the control-plane OS processes (the bench-side
    analogue of tests/test_multiprocess_e2e.Plane)."""

    def __init__(self):
        import os
        import socket
        import tempfile
        self.repo = os.path.dirname(os.path.abspath(__file__))
        self.logdir = tempfile.mkdtemp(prefix="wire-bench-")
        self.procs = {}
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            self.port = s.getsockname()[1]
        self.url = f"http://127.0.0.1:{self.port}"

    def spawn(self, name, *argv):
        import os
        import subprocess
        import sys
        logf = open(os.path.join(self.logdir, f"{name}.log"), "w")
        env = dict(os.environ, PYTHONPATH=self.repo,
                   JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        self.procs[name] = subprocess.Popen(
            [sys.executable, *argv], stdout=logf, stderr=logf,
            env=env, cwd=self.repo)

    def start(self, tick=0.05, period=0.05):
        import urllib.request
        self.spawn("server", "-m", "volcano_tpu.server",
                   "--port", str(self.port),
                   "--tick-period", str(tick))

        def up():
            try:
                with urllib.request.urlopen(self.url + "/healthz",
                                            timeout=1):
                    return True
            except OSError:
                return False
        _wire_wait(up, 20, "state server /healthz")
        self.spawn("controllers", "-m", "volcano_tpu",
                   "--cluster-url", self.url,
                   "--components", "controllers",
                   "--period", str(period))
        self.spawn("scheduler", "-m", "volcano_tpu",
                   "--cluster-url", self.url,
                   "--components", "scheduler", "--period", str(period),
                   "--leader-elect", "--holder", "bench-sched",
                   "--lease-ttl", "2.0")

    def log_tails(self, n=1500) -> str:
        import glob
        import os
        out = []
        for f in sorted(glob.glob(os.path.join(self.logdir, "*.log"))):
            try:
                with open(f, encoding="utf-8", errors="replace") as fh:
                    out.append(f"== {os.path.basename(f)} ==\n"
                               + fh.read()[-n:])
            except OSError:
                pass
        return "\n".join(out)

    def shutdown(self):
        for proc in self.procs.values():
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs.values():
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                proc.kill()


def _wire_wait(cond, timeout, msg):
    """msg may be a callable: evaluated ONLY on timeout, so log tails
    in the diagnostic are captured at failure time (not when the wait
    starts) and successful waits never pay the log read."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.03)
    raise AssertionError("wire bench: timed out waiting for "
                         + (msg() if callable(msg) else msg))


def _wire_gang_job(name, replicas, run_ticks=2):
    """Hard tier-1 (slice-local) TPU gang, finite workload — the
    topology-gang shape the in-process headline uses, submitted as a
    vcjob so controllers materialize it over the wire."""
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.podgroup import NetworkTopologySpec
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import (NetworkTopologyMode,
                                       RUN_TICKS_ANNOTATION)
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    return VCJob(
        name=name, min_available=replicas,
        network_topology=NetworkTopologySpec(
            NetworkTopologyMode.HARD, highest_tier_allowed=1),
        tasks=[TaskSpec(
            name="w", replicas=replicas,
            template=make_pod(
                "t", requests={"cpu": 8, TPU: 4},
                annotations={RUN_TICKS_ANNOTATION: str(run_ticks)}))])


def _job_running(cluster, job_name, want):
    from volcano_tpu.api.types import TaskStatus
    return sum(1 for p in cluster.pods.values()
               if p.labels.get("volcano-tpu.io/job-name") == job_name
               and p.phase in (TaskStatus.BOUND, TaskStatus.RUNNING,
                               TaskStatus.SUCCEEDED)) >= want


def _job_completed(cluster, job_name):
    from volcano_tpu.api.types import JobPhase
    j = cluster.vcjobs.get(f"default/{job_name}")
    return j is not None and j.phase is JobPhase.COMPLETED


def bench_wire_gang(smoke: bool = False) -> dict:
    """wire_gang_p50_s: p50 pod scheduling latency of topology gangs
    scheduled through the REAL multi-process control plane, derived
    from the server's audit trail (creation->bind timestamps, the
    reference's pods/binding methodology) — no scheduler cooperation.
    Also reports the client-observed submit->all-bound wall time."""
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.server.audit_exporter import AuditExporter
    from volcano_tpu.simulator import slice_nodes

    slices = [("target", "v5e-16")] if smoke else \
        [("target", "v5e-64"), ("noise", "v5e-16")]
    replicas = 4 if smoke else 16
    trials = 1 if smoke else 5

    plane = _WirePlane()
    kubectl = None
    try:
        plane.start()
        exp = AuditExporter(plane.url)
        exp.poll()                  # enable audit BEFORE the workload
        kubectl = RemoteCluster(plane.url)
        for sname, kind in slices:
            for node in slice_nodes(slice_for(sname, kind),
                                    dcn_pod="dcn-0"):
                kubectl.add_node(node)

        walls = []
        for t in range(trials):
            name = f"wiregang-{t}"
            t0 = time.perf_counter()
            kubectl.add_vcjob(_wire_gang_job(name, replicas))
            _wire_wait(lambda: _job_running(kubectl, name, replicas),
                       45, lambda: f"{name} bound ({plane.log_tails()[-800:]})")
            walls.append(time.perf_counter() - t0)
            # job completes (RUN_TICKS) and frees the slice for the
            # next trial: identical capacity per trial
            _wire_wait(lambda: _job_completed(kubectl, name),
                       45, f"{name} completed")
        exp.poll()
        lats = sorted(v for k, v in exp.pod_latencies().items()
                      if "/wiregang-" in k)
        assert len(lats) >= replicas * trials, \
            f"audit saw {len(lats)} gang pods"
        return {
            "wire_gang_p50_s": round(statistics.median(lats), 4),
            "wire_gang_p95_s": round(
                lats[max(0, -(-len(lats) * 95 // 100) - 1)], 4),
            "wire_gang_submit_to_bound_p50_s": round(
                statistics.median(walls), 4),
            "gang_replicas": replicas, "trials": trials,
            "hosts": sum(len(slice_nodes(slice_for(s, k)))
                         for s, k in slices),
            "audit_pods_measured": len(lats),
        }
    finally:
        if kubectl is not None:
            kubectl.close()
        plane.shutdown()


def bench_wire_scale(smoke: bool = False) -> dict:
    """Wire-mode scale row: a >=1k-host cluster mirrored through the
    state server with churn riding the watch streams (VERDICT r5 weak
    #4: wire-mode scale was unmeasured beyond 100 jobs on a toy
    cluster).  Reports mirror bootstrap cost, a 64-host topology gang
    through the wire at scale, churn convergence across multiple
    watch streams, and delta-vs-full resync cost — the O(churn) vs
    O(cluster) proof for the new /delta lane."""
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.server.audit_exporter import AuditExporter
    from volcano_tpu.simulator import slice_nodes

    n_slices = 1 if smoke else 16           # 16 x v5e-256 = 1024 hosts
    slice_kind = "v5e-16" if smoke else "v5e-256"
    gang_hosts = 4 if smoke else 64
    churn_jobs = 3 if smoke else 24

    plane = _WirePlane()
    mirrors = []
    try:
        plane.start()
        exp = AuditExporter(plane.url)
        exp.poll()
        t0 = time.perf_counter()
        kubectl = RemoteCluster(plane.url)
        mirrors.append(kubectl)
        for i in range(n_slices):
            for node in slice_nodes(slice_for(f"t{i:02d}", slice_kind),
                                    dcn_pod=f"dcn-{i % 4}"):
                kubectl.add_node(node)
        provision_s = time.perf_counter() - t0
        hosts = len(kubectl.nodes)

        # cold mirror bootstrap: one full LIST of the whole cluster
        # (codec fast path + gzip are exactly what this pays for)
        t0 = time.perf_counter()
        obs1 = RemoteCluster(plane.url)
        bootstrap_s = time.perf_counter() - t0
        obs2 = RemoteCluster(plane.url)
        mirrors += [obs1, obs2]
        # frozen pre-churn mirror: the delta-resync measurand
        stale = RemoteCluster(plane.url, start_watch=False)
        mirrors.append(stale)

        # 64-host hard-topology gang through the wire at scale
        t0 = time.perf_counter()
        kubectl.add_vcjob(_wire_gang_job("scalegang", gang_hosts))
        _wire_wait(lambda: _job_running(kubectl, "scalegang",
                                        gang_hosts),
                   90, lambda: "scale gang bound (" + plane.log_tails()[-800:] + ")")
        gang_wall_s = time.perf_counter() - t0

        # churn burst: small cpu gangs completing in waves, fanning
        # out over every watch stream (5 mirrors incl. scheduler +
        # controllers)
        t0 = time.perf_counter()
        for i in range(churn_jobs):
            kubectl.add_vcjob(_wire_cpu_job(f"churn-{i}"))

        def churned(c):
            from volcano_tpu.api.types import JobPhase
            return sum(1 for j in c.vcjobs.values()
                       if j.name.startswith("churn-")
                       and j.phase is JobPhase.COMPLETED) >= churn_jobs
        _wire_wait(lambda: churned(kubectl) and churned(obs1)
                   and churned(obs2),
                   120, lambda: "churn convergence (" + plane.log_tails()[-800:] + ")")
        churn_s = time.perf_counter() - t0

        # delta resync: O(churn window); full re-list: O(cluster)
        t0 = time.perf_counter()
        stale.resync()
        delta_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        stale._full_resync()
        full_s = time.perf_counter() - t0
        assert len(stale.nodes) == hosts

        exp.poll()
        gang_lats = sorted(v for k, v in exp.pod_latencies().items()
                           if "/scalegang-" in k)
        return {
            "hosts": hosts,
            "provision_s": round(provision_s, 4),
            "mirror_bootstrap_s": round(bootstrap_s, 4),
            f"gang{gang_hosts}_submit_to_bound_s": round(gang_wall_s, 4),
            f"gang{gang_hosts}_audit_p50_s": round(
                statistics.median(gang_lats), 4) if gang_lats else None,
            "churn_jobs": churn_jobs,
            "churn_converge_s": round(churn_s, 4),
            "watch_streams": 5,     # kubectl, 2 observers, sched, ctrl
            "delta_resync_s": round(delta_s, 4),
            "full_resync_s": round(full_s, 4),
            "audit_lost_records": exp.lost_records,
        }
    finally:
        for m in mirrors:
            m.close()
        plane.shutdown()


def _wire_cpu_job(name, replicas=2, run_ticks=2):
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import RUN_TICKS_ANNOTATION
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    return VCJob(name=name, min_available=replicas,
                 tasks=[TaskSpec(
                     name="w", replicas=replicas,
                     template=make_pod(
                         "t", requests={"cpu": 4},
                         annotations={RUN_TICKS_ANNOTATION:
                                      str(run_ticks)}))])


def bench_wire_usage_roundtrip() -> dict:
    """Round-trip ONE bandwidth usage report + violation event through
    the real state-server process: a node agent on its own wire mirror
    measures an over-watermark offline pod (fake cgroup counters), the
    server folds the report into node annotations, and a SECOND wire
    mirror observes the violation — accounting traffic proven on the
    wire, not just in-process (tier-1 via --wire-smoke)."""
    import os
    import shutil
    import tempfile
    import urllib.request

    from volcano_tpu.agent.agent import (DCN_BANDWIDTH_ANNOTATION,
                                         FakeUsageProvider, NodeAgent)
    from volcano_tpu.agent.collect import NetAccountingCollector
    from volcano_tpu.agent.enforcer import CgroupV2Enforcer
    from volcano_tpu.api.netusage import (NODE_SATURATED_ANNOTATION,
                                          POD_VIOLATING_ANNOTATION)
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import (QOS_BEST_EFFORT,
                                       QOS_LEVEL_ANNOTATION, TaskStatus)
    from volcano_tpu.cache.remote_cluster import RemoteCluster

    plane = _WirePlane()
    mirrors = []
    tmp = tempfile.mkdtemp(prefix="wire-netacct-")
    try:
        plane.spawn("server", "-m", "volcano_tpu.server",
                    "--port", str(plane.port))

        def up():
            try:
                with urllib.request.urlopen(plane.url + "/healthz",
                                            timeout=1):
                    return True
            except OSError:
                return False
        _wire_wait(up, 20, "state server /healthz")
        kubectl = RemoteCluster(plane.url)
        mirrors.append(kubectl)
        kubectl.add_node(Node(
            name="n0", allocatable={"cpu": "64", "pods": 110},
            annotations={DCN_BANDWIDTH_ANNOTATION: "1000"}))
        hog = make_pod("hog", requests={"cpu": 1}, node_name="n0",
                       phase=TaskStatus.RUNNING,
                       annotations={QOS_LEVEL_ANNOTATION:
                                    QOS_BEST_EFFORT})
        kubectl.add_pod(hog)

        agent_view = RemoteCluster(plane.url)
        mirrors.append(agent_view)
        _wire_wait(lambda: "default/hog" in agent_view.pods, 10,
                   "agent mirror sees pod")
        provider = FakeUsageProvider()
        provider.set("n0", cpu_fraction=0.2)
        cg = CgroupV2Enforcer(os.path.join(tmp, "cg"))
        col = NetAccountingCollector(cg.root)
        agent = NodeAgent(agent_view, "n0", provider, enforcer=cg,
                          net_collector=col)
        uid = agent_view.pods["default/hog"].uid

        t0 = time.perf_counter()
        agent.sync()                   # tag the cgroup
        tx = 0
        pod_dir = os.path.join(
            cg.root, CgroupV2Enforcer.POD_DIR_PREFIX + uid)

        def advance(n_bytes):
            nonlocal tx
            tx += n_bytes
            with open(os.path.join(pod_dir, "net_stat.tx_bytes"),
                      "w") as f:
                f.write(str(tx))

        advance(0)
        time.sleep(0.06)
        agent.sync()                   # baseline reading
        for _ in range(4):             # far over the 400 mbps cap
            advance(67_500_000)
            time.sleep(0.06)
            agent.sync()

        obs = RemoteCluster(plane.url)
        mirrors.append(obs)
        _wire_wait(
            lambda: obs.bandwidthreports.get("n0") is not None
            and obs.bandwidthreports["n0"].violations == 1
            and obs.nodes["n0"].annotations.get(
                NODE_SATURATED_ANNOTATION) == "true"
            and obs.pods["default/hog"].annotations.get(
                POD_VIOLATING_ANNOTATION) == "true",
            15, "violation visible on observer mirror")
        return {"usage_report_roundtrip_ok": True,
                "violation_roundtrip_ok": True,
                "measure_to_observe_s": round(
                    time.perf_counter() - t0, 4)}
    finally:
        for m in mirrors:
            m.close()
        plane.shutdown()
        shutil.rmtree(tmp, ignore_errors=True)


def run_wire_benchmarks(smoke: bool = False) -> dict:
    """The wire scenarios, each failure-isolated: a wire stall must
    report itself in the JSON, never kill the in-process numbers."""
    out = {}
    try:
        out.update(bench_wire_gang(smoke))
    except Exception as e:  # noqa: BLE001 — report, don't die
        out["wire_gang_error"] = str(e)[-600:]
    try:
        out["scale"] = bench_wire_scale(smoke)
    except Exception as e:  # noqa: BLE001
        out["scale"] = {"error": str(e)[-600:]}
    try:
        out["usage_roundtrip"] = bench_wire_usage_roundtrip()
    except Exception as e:  # noqa: BLE001
        out["usage_roundtrip"] = {"error": str(e)[-600:]}
    return out


def bench_reference_gang_shape() -> float:
    """The reference harness's default gang scenario (benchmark/README
    JOBS=10, REPLICAS=100, MIN_AVAILABLE=100 over 100 nodes): seconds
    until all 1000 pods are bound."""
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.uthelper import gang_job

    cluster = FakeCluster()
    for i in range(100):
        cluster.add_node(Node(name=f"n{i}",
                              allocatable={"cpu": 112, "pods": 256}))
    for j in range(10):
        pg, pods = gang_job(f"job{j}", replicas=100, requests={"cpu": 1})
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
    sched = Scheduler(cluster, conf=BENCH_CONF, schedule_period=0)
    t0 = time.perf_counter()
    for _ in range(50):  # bounded: a stall must fail, not hang the driver
        sched.run_once()
        cluster.tick()
        if len(cluster.binds) >= 1000:
            break
    assert len(cluster.binds) >= 1000, \
        f"gang shape stalled at {len(cluster.binds)}/1000 binds"
    return time.perf_counter() - t0


def bench_agent_scheduler_throughput() -> float:
    """Fast-path pods/second over a 500-pod burst (the reference's
    bare-pod benchmark default, benchmark/README PODS=500)."""
    from volcano_tpu.agentscheduler import AgentScheduler
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.shard import AGENT_SCHEDULER
    from volcano_tpu.cache.fake_cluster import FakeCluster

    def one_burst() -> float:
        """One 500-pod burst on a FRESH cluster + scheduler (identical
        conditions per trial; teardown-free — delete events would
        trigger untimed full cache refreshes)."""
        cluster = FakeCluster()
        for i in range(20):
            cluster.add_node(Node(name=f"n{i}",
                                  allocatable={"cpu": 64, "pods": 256}))
        sched = AgentScheduler(cluster)
        # throughput with the batch-parity predicate chain DISABLED is
        # not a result (VERDICT r2 item 3): prove the default chain
        assert [p.name for p in sched.plugins] == \
            ["predicates", "resources", "deviceshare", "leastalloc"], \
            f"parity chain not enabled: {[p.name for p in sched.plugins]}"
        # warmup: first-touch imports and spec-cache build are startup
        # costs, not steady-state throughput
        for i in range(50):
            pod = make_pod(f"warm{i}", requests={"cpu": "100m"})
            pod.scheduler_name = AGENT_SCHEDULER
            cluster.add_pod(pod)
        assert sched.run_until_drained() == 50
        for i in range(500):
            pod = make_pod(f"a{i}", requests={"cpu": "100m"})
            pod.scheduler_name = AGENT_SCHEDULER
            cluster.add_pod(pod)
        t0 = time.perf_counter()
        bound = sched.run_until_drained()
        dt = time.perf_counter() - t0
        assert bound == 500, f"agent bound {bound}/500"
        return bound / dt

    # median of 3 independent trials: robust to one driver-machine
    # stall while staying comparable to earlier single-run rounds
    # (each trial matches the old methodology exactly)
    return statistics.median(one_burst() for _ in range(3))


def bench_gangpreempt_latency() -> float:
    """p50 wall-clock for a high-priority 64-host hard-topology gang to
    displace a low-priority tenant occupying a full v5p-256 slice: the
    two-cycle evict -> nominate -> allocate handshake, measured from
    submission to the 64th bind (VERDICT r1 item 3a; scenario shape
    mirrors the reference's preempt benchmark, benchmark/README.md)."""
    from volcano_tpu.api.podgroup import NetworkTopologySpec
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import NetworkTopologyMode, PodGroupPhase
    from volcano_tpu.cache.cluster import PriorityClass
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job

    conf = {
        "actions": "enqueue, allocate, gangpreempt, backfill",
        "tiers": [
            {"plugins": [{"name": "priority"}, {"name": "gang"},
                         {"name": "conformance"}]},
            {"plugins": [{"name": "predicates"}, {"name": "proportion"},
                         {"name": "nodeorder"}, {"name": "deviceshare"},
                         {"name": "network-topology-aware"}]},
        ],
    }
    latencies = []
    for trial in range(max(3, TRIALS // 2)):
        cluster = make_tpu_cluster([("target", "v5p-256"),   # 64 hosts
                                    ("noise", "v5e-64")])
        cluster.add_priority_class(PriorityClass("high", 1000))
        # low-priority elastic tenant holds the whole target slice
        pg_lo, pods_lo = gang_job(
            "tenant", replicas=64, min_available=1,
            requests={"cpu": 8, TPU: 4},
            running_on=[f"target-w{i}" for i in range(64)],
            pg_phase=PodGroupPhase.RUNNING)
        cluster.add_podgroup(pg_lo)
        for p in pods_lo:
            cluster.add_pod(p)
        pg_hi, pods_hi = gang_job(
            "train-hi", replicas=64, requests={"cpu": 8, TPU: 4},
            priority_class="high",
            network_topology=NetworkTopologySpec(
                NetworkTopologyMode.HARD, 1),
            pg_phase=PodGroupPhase.INQUEUE)
        sched = Scheduler(cluster, conf=conf, schedule_period=0)
        sched.run_once()   # warm (tenant steady state)

        t0 = time.perf_counter()
        cluster.add_podgroup(pg_hi)
        for p in pods_hi:
            cluster.add_pod(p)
        for _ in range(10):
            sched.run_once()
            cluster.tick()
            hi = {k for k, _ in cluster.binds
                  if k.startswith("default/train-hi")}
            if len(hi) >= 64:
                break
        dt = time.perf_counter() - t0
        assert len(hi) >= 64, f"gangpreempt stalled: {len(hi)}/64 bound"
        latencies.append(dt)
    return statistics.median(latencies)


def bench_reclaim_convergence() -> float:
    """Seconds for a 2-queue overcommit flip to converge: queue
    'greedy' holds the whole 2-slice cluster; queue 'owed' submits
    demand for its half; reclaim must evict greedy's surplus and bind
    owed's jobs to its full deserved share (VERDICT r1 item 3b)."""
    from volcano_tpu.api.queue import Queue
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import PodGroupPhase
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job

    conf = {
        "actions": "enqueue, allocate, reclaim, backfill",
        "tiers": [
            {"plugins": [{"name": "priority"}, {"name": "gang"},
                         {"name": "conformance"}]},
            {"plugins": [{"name": "predicates"}, {"name": "proportion"},
                         {"name": "nodeorder"}, {"name": "deviceshare"}]},
        ],
    }
    cluster = make_tpu_cluster([("sa", "v5e-64"), ("sb", "v5e-64")])
    cluster.add_queue(Queue(name="greedy", weight=1))
    cluster.add_queue(Queue(name="owed", weight=1))
    # greedy: 8 elastic 4-host gangs = all 32 hosts
    hosts = sorted(cluster.nodes)
    for i in range(8):
        mine = hosts[i * 4:(i + 1) * 4]
        pg, pods = gang_job(f"greedy-{i}", queue="greedy", replicas=4,
                            min_available=1, requests={"cpu": 8, TPU: 4},
                            running_on=mine,
                            pg_phase=PodGroupPhase.RUNNING)
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
    sched = Scheduler(cluster, conf=conf, schedule_period=0)
    sched.run_once()

    # the flip: owed demands exactly its deserved half (16 hosts)
    t0 = time.perf_counter()
    for i in range(4):
        pg, pods = gang_job(f"owed-{i}", queue="owed", replicas=4,
                            requests={"cpu": 8, TPU: 4})
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
    for _ in range(20):
        sched.run_once()
        cluster.tick()
        owed = {k for k, _ in cluster.binds
                if k.startswith("default/owed-")}
        if len(owed) >= 16:
            break
    dt = time.perf_counter() - t0
    assert len(owed) >= 16, f"reclaim stalled: {len(owed)}/16 bound"
    return dt


def bench_5k_host_scale() -> dict:
    """5,000-host scale headroom: idle-cycle seconds + one-cycle
    latency for a 1024-host gang (VERDICT r1 item 2)."""
    return _scale_gang_probe(78, 1024)


def _build_scale_cluster(n_slices: int, busy_fraction: float = 0.6):
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.podgroup import PodGroup
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import (GROUP_NAME_ANNOTATION,
                                       PodGroupPhase, TaskStatus)
    from volcano_tpu.simulator import make_tpu_cluster

    slices = [(f"t{i:03d}", "v5e-256") for i in range(n_slices)]
    cluster = make_tpu_cluster(slices)
    names = sorted(cluster.nodes)
    busy = names[: int(len(names) * busy_fraction)]
    for j, start in enumerate(range(0, len(busy), 64)):
        hosts = busy[start:start + 64]
        pg = PodGroup(name=f"pg{j}", min_member=len(hosts),
                      phase=PodGroupPhase.RUNNING)
        cluster.add_podgroup(pg)
        for i, node in enumerate(hosts):
            cluster.add_pod(make_pod(
                f"j{j}-{i}", requests={"cpu": 8, TPU: 4},
                annotations={GROUP_NAME_ANNOTATION: pg.key},
                node_name=node, phase=TaskStatus.RUNNING))
    return cluster


def _scale_gang_probe(n_slices: int, gang: int) -> dict:
    """Idle-cycle + one-cycle gang latency on an n_slices x v5e-256
    cluster, 60% pre-occupied.  The steady cluster graph is
    gc.freeze()-d before the timed cycles: gen-2 collections scanning
    a 10k-host object graph added up to 0.3s of per-run variance
    (the r4 '0.7-1.3s' spread) that says nothing about the scheduler.
    Production guidance is the same — freeze the post-LIST graph."""
    import gc

    from volcano_tpu.api.resource import TPU
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.uthelper import gang_job

    cluster = _build_scale_cluster(n_slices)
    sched = Scheduler(cluster, conf=BENCH_CONF, schedule_period=0)
    sched.run_once()                   # warm-up
    gc.collect()
    gc.freeze()
    try:
        t0 = time.perf_counter()
        sched.run_once()
        idle_s = time.perf_counter() - t0
        pg, pods = gang_job(f"g{gang}", replicas=gang,
                            min_available=gang,
                            requests={"cpu": 8, TPU: 4})
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
        t0 = time.perf_counter()
        sched.run_once()
        gang_s = time.perf_counter() - t0
    finally:
        gc.unfreeze()
    bound = sum(1 for k, _ in cluster.binds
                if k.startswith(f"default/g{gang}"))
    assert bound == gang, f"scale gang bound {bound}/{gang}"
    return {"hosts": len(cluster.nodes),
            "idle_cycle_s": round(idle_s, 4),
            f"gang{gang}_cycle_s": round(gang_s, 4)}


def bench_10k_host_scale() -> dict:
    """10,000-host headroom probe (VERDICT r3 next-round #10: 5k is
    comfortable — find the knee): 157 v5e-256 slices (10,048 hosts),
    60% pre-occupied; idle-cycle seconds + one-cycle latency for a
    2048-host v5p-8192-shaped gang."""
    return _scale_gang_probe(157, 2048)


def _scale_knee(s5k: dict, s10k: dict, s20k: dict,
                s40k: Optional[dict] = None) -> dict:
    """Per-gang-member cycle cost at each scale point.  Flat =
    linear scaling (no knee yet); a bend marks where superlinear
    costs start."""
    def per_member(d, gang):
        v = d.get(f"gang{gang}_cycle_s")
        return round(v / gang * 1000, 4) if isinstance(v, (int, float)) \
            else None
    out = {"ms_per_member_5k": per_member(s5k, 1024),
           "ms_per_member_10k": per_member(s10k, 2048),
           "ms_per_member_20k": per_member(s20k, 4096)}
    if s40k is not None:
        out["ms_per_member_40k"] = per_member(s40k, 8192)
    return out


def bench_20k_host_scale() -> dict:
    """20,000-host knee probe (VERDICT r4 weak #5): 313 slices
    (20,032 hosts), 4096-host gang.  Establishes where the per-cycle
    cost curve bends — see BENCH extra.scale_knee."""
    return _scale_gang_probe(313, 4096)


def bench_40k_host_scale() -> dict:
    """40,000-host probe as a REPEATABLE bench output (VERDICT r5
    missing #3: README used to cite a one-off builder observation):
    625 slices (40,000 hosts), 8192-host gang.  Also exposed as
    `python bench.py --scale-40k` so the row can be regenerated
    standalone without the full suite."""
    return _scale_gang_probe(625, 8192)


# -- process-parallel scheduler cycle (ROADMAP item 3) -----------------

SWEEP_WORKER_STEPS = (1, 2, 4, 8)


def _sweep_entry_bench(ssn, nodes, task, backend: str, workers: int,
                       reps: int = 3):
    """Best-of-reps build_entry wall time under the given sweep
    backend ('' = the serial fallback path)."""
    from volcano_tpu.actions.sweep import SpecCache
    conf = ssn.conf.configurations.setdefault("allocate", {})
    conf["parallelPredicates"] = backend if backend else False
    conf["parallelPredicates.workers"] = workers or 1
    best, entry = float("inf"), None
    for _ in range(reps):
        cache = SpecCache(ssn, nodes, record_errors=False)
        t0 = time.perf_counter()
        entry = cache.build_entry(task)
        best = min(best, time.perf_counter() - t0)
    return best, entry


def _entries_identical(a, b) -> bool:
    return (a["fits"].keys() == b["fits"].keys()
            and a["scores"] == b["scores"]
            and a["meta"] == b["meta"])


def _span_waterfall(doc: Optional[dict]) -> dict:
    """Flatten a kept session trace into {span_name: seconds} for the
    parallel-cycle attribution spans (summed over occurrences), so the
    SCALE artifact shows where a cycle's time went."""
    names = ("snapshot_build", "open_session", "delta_ship",
             "sweep_fanout", "sweep_merge", "allocate", "enqueue",
             "backfill", "close_session")
    out: dict = {}

    def walk(s):
        if s["name"] in names:
            out[s["name"]] = round(
                out.get(s["name"], 0.0) + s["dur"], 4)
        for c in s.get("children", ()):
            walk(c)

    if doc and doc.get("root"):
        walk(doc["root"])
        out["session"] = round(doc["root"]["dur"], 4)
    return out


def _sweep_entry_matrix(ssn, nodes, task, reps: int) -> Tuple[list, bool]:
    """Serial baseline + thread/process rows at every worker count;
    returns (rows, all_identical)."""
    serial_s, serial_entry = _sweep_entry_bench(ssn, nodes, task, "",
                                                0, reps)
    rows = [{"backend": "serial", "workers": 0,
             "ms": round(serial_s * 1000, 2), "speedup_vs_serial": 1.0,
             "entry_identical_to_serial": True}]
    all_ok = True
    for backend in ("thread", "process"):
        for w in SWEEP_WORKER_STEPS:
            if backend == "process":
                # the process-wide pool grows and never shrinks — a
                # fresh pool per step keeps the row at EXACTLY w
                # workers (first rep pays the bootstrap, best-of-reps
                # reports the synced steady state)
                from volcano_tpu.actions import procpool
                procpool.shutdown()
            t, entry = _sweep_entry_bench(ssn, nodes, task, backend,
                                          w, reps)
            identical = _entries_identical(entry, serial_entry)
            all_ok &= identical
            rows.append({
                "backend": backend, "workers": w,
                "ms": round(t * 1000, 2),
                "speedup_vs_serial": round(serial_s / t, 2),
                "entry_identical_to_serial": identical})
            print(f"  {backend}@{w}: {t*1000:.1f} ms "
                  f"({serial_s/t:.2f}x, identical={identical})",
                  flush=True)
    return rows, all_ok


def bench_scale_100k(n_slices: int = 1563, gang: int = 8192,
                     include_40k: bool = True) -> dict:
    """The SCALE100K artifact (ROADMAP item 3): a 100k-host cluster
    (1563 x v5e-256 = 100,032 hosts, 60% pre-occupied) measured
    through the incremental-snapshot cycle with all three sweep
    backends.

    Sections:
      cycles        idle + 8192-gang cycle seconds per backend
                    (serial / thread@8 / process@8), with the
                    process cycle's flight-recorder waterfall
                    (snapshot_build / delta_ship / sweep_fanout /
                    sweep_merge / allocate) — where the time goes;
      entry_rows    per-spec build_entry sweep at every worker count
                    for both pools, bit-identity asserted against the
                    serial entry (disarmed, then ARMED under the
                    freeze auditor, mirroring tools/race_bench.py);
      idle_40k      the acceptance row: incremental snapshot reuse
                    must hold the 40k idle cycle at or under 0.1s
                    (0.52s at the PR 2 seed).
    """
    import copy
    import gc
    import os as _os

    from volcano_tpu import trace
    from volcano_tpu.actions import procpool
    from volcano_tpu.analysis import freezeaudit
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import TaskStatus
    from volcano_tpu.framework.framework import (close_session,
                                                 open_session)
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.uthelper import gang_job

    t_build = time.perf_counter()
    cluster = _build_scale_cluster(n_slices)
    conf = copy.deepcopy(BENCH_CONF)
    sched = Scheduler(cluster, conf=conf, schedule_period=0)
    sched.run_once()                       # warm-up full snapshot
    build_s = time.perf_counter() - t_build
    print(f"built {len(cluster.nodes)} hosts in {build_s:.1f}s",
          flush=True)
    gc.collect()
    gc.freeze()

    backends = (("serial", False, 0), ("thread", "thread", 8),
                ("process", "process", 8))
    cycles = {}
    waterfall = {}
    try:
        for label, raw, workers in backends:
            sched.conf.configurations["allocate"] = {
                "parallelPredicates": raw,
                "parallelPredicates.workers": workers}
            sched.run_once()               # absorb prior dirty state
            if label == "process":
                # pre-warm: production runs a PERSISTENT pool — the
                # worker spawn + bootstrap full sync happens once per
                # scheduler lifetime, not inside a measured cycle;
                # the timed gang cycle below ships only the delta
                ssn = open_session(sched.cache, sched.conf)
                procpool.pool(workers).ensure_sync(ssn)
                close_session(ssn)
                gc.collect()   # bootstrap pickle garbage, not the
                gc.freeze()    # timed cycles', pays the GC bill here
            t0 = time.perf_counter()
            sched.run_once()               # steady idle cycle
            idle_s = time.perf_counter() - t0
            pg, pods = gang_job(f"g-{label}", replicas=gang,
                                min_available=gang,
                                requests={"cpu": 8, TPU: 4})
            cluster.add_podgroup(pg)
            for p in pods:
                cluster.add_pod(p)
            trace.reset()                  # first session is kept
            t0 = time.perf_counter()
            sched.run_once()
            gang_s = time.perf_counter() - t0
            bound = sum(1 for k, _ in cluster.binds
                        if k.startswith(f"default/g-{label}"))
            assert bound == gang, \
                f"{label}: gang bound {bound}/{gang}"
            cycles[label] = {"idle_cycle_s": round(idle_s, 4),
                             f"gang{gang}_cycle_s": round(gang_s, 4)}
            kept = trace.recent_traces(limit=1)
            if kept:
                waterfall[label] = _span_waterfall(kept[-1])
            print(f"  {label}: idle {idle_s:.4f}s "
                  f"gang{gang} {gang_s:.3f}s", flush=True)
            # advance the bound gang to Running so the next backend's
            # idle row measures a STEADY fleet (a Bound gang keeps
            # its job non-steady, which forces the incremental — not
            # the reuse — path every cycle, by design)
            cluster.tick()

        # -- batched gang commit row (docs/design/sharding.md) ---------
        # same gang, same fleet, but the allocator drains the whole
        # spec through one heap + fill-to-capacity statement instead
        # of the per-pod walk: the serial row above is its baseline
        sched.conf.configurations["allocate"] = {"gangCommit": "batch"}
        sched.run_once()                   # absorb prior dirty state
        pg, pods = gang_job("g-gc", replicas=gang, min_available=gang,
                            requests={"cpu": 8, TPU: 4})
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
        trace.reset()
        t0 = time.perf_counter()
        sched.run_once()
        gc_s = time.perf_counter() - t0
        bound = sum(1 for k, _ in cluster.binds
                    if k.startswith("default/g-gc"))
        assert bound == gang, f"gang commit bound {bound}/{gang}"
        walk_s = cycles["serial"][f"gang{gang}_cycle_s"]
        cycles["gang_commit_batch"] = {
            f"gang{gang}_cycle_s": round(gc_s, 4),
            "speedup_vs_walk": round(walk_s / gc_s, 2)}
        kept = trace.recent_traces(limit=1)
        if kept:
            waterfall["gang_commit_batch"] = _span_waterfall(kept[-1])
        print(f"  gang_commit_batch: gang{gang} {gc_s:.3f}s "
              f"({walk_s / gc_s:.2f}x vs walk)", flush=True)
        cluster.tick()

        # -- per-spec sweep rows: disarmed then armed ------------------
        pg, pods = gang_job("probe", replicas=gang,
                            min_available=gang,
                            requests={"cpu": 8, TPU: 4})
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
        ssn = open_session(sched.cache, sched.conf)
        task = next(t for j in ssn.jobs.values()
                    for t in j.tasks_in_status(TaskStatus.PENDING))
        nodes = list(ssn.nodes.values())
        print("entry sweep (disarmed):", flush=True)
        rows, ok_disarmed = _sweep_entry_matrix(ssn, nodes, task, 2)
        close_session(ssn)

        freezeaudit.install()
        freezeaudit.reset()
        ssn = open_session(sched.cache, sched.conf)
        task = next(t for j in ssn.jobs.values()
                    for t in j.tasks_in_status(TaskStatus.PENDING))
        nodes = list(ssn.nodes.values())
        print("entry sweep (ARMED):", flush=True)
        armed_rows, ok_armed = _sweep_entry_matrix(ssn, nodes, task, 1)
        close_session(ssn)
        audit = freezeaudit.report()
        freezeaudit.uninstall()

        # -- sharded plane rows (docs/design/sharding.md) --------------
        # N subtree-sharded schedulers over the SAME 100k-host fleet,
        # batched commit on, the 8192-pod load split into 8 gangs of
        # 1024 so the stable job->shard hash spreads them.  Each
        # shard's cycle is timed on its own: on a real plane the
        # shards run on separate hosts in parallel, so
        # max_shard_cycle_s is the plane's wall-clock; here they
        # serialize on one core (host_cpus recorded per row).
        from volcano_tpu import shardmap

        def _drain_gang(prefix):
            # free the chips a finished bench gang holds: the fleet
            # only has 40% headroom, and each plane below needs the
            # full 8192x4 chips back
            for key in [k for k in cluster.pods
                        if k.startswith(f"default/{prefix}")]:
                cluster.delete_object("pod", key)
            for key in [k for k in cluster.podgroups
                        if k.startswith(f"default/{prefix}")]:
                cluster.delete_object("podgroup", key)

        for prefix in ("g-serial", "g-thread", "g-process", "g-gc",
                       "probe"):
            _drain_gang(prefix)
        sharded = {}
        for count in (2, 4):
            scheds = []
            for si in range(count):
                sconf = copy.deepcopy(BENCH_CONF)
                sconf["configurations"] = {"allocate": {
                    "gangCommit": "batch", "shard-spill": "soft"}}
                s = Scheduler(cluster, conf=sconf, schedule_period=0,
                              shard_index=si, shard_count=count)
                s.run_once()             # warm full snapshot
                scheds.append(s)
            njobs = 8
            names = [f"gs{count}-{i}" for i in range(njobs)]
            homes = {n: shardmap.home_shard(f"default/{n}", count)
                     for n in names}
            for n in names:
                pg, pods = gang_job(n, replicas=gang // njobs,
                                    min_available=gang // njobs,
                                    requests={"cpu": 8, TPU: 4})
                cluster.add_podgroup(pg)
                for p in pods:
                    cluster.add_pod(p)
            srows = []
            bound_total = 0
            for si, s in enumerate(scheds):
                mine = [n for n in names if homes[n] == si]
                t0 = time.perf_counter()
                s.run_once()
                dt = time.perf_counter() - t0
                bound = sum(1 for k, _ in cluster.binds
                            if any(k.startswith(f"default/{n}-")
                                   for n in mine))
                bound_total += bound
                srows.append({"shard": f"{si}/{count}",
                              "gangs_homed": len(mine),
                              "pods_bound": bound,
                              "cycle_s": round(dt, 4),
                              "host_cpus": _os.cpu_count()})
                print(f"  shard {si}/{count}: {len(mine)} gangs, "
                      f"{bound} pods, cycle {dt:.3f}s", flush=True)
            assert bound_total == gang, \
                f"sharded plane bound {bound_total}/{gang}"
            sharded[str(count)] = {
                "per_shard": srows,
                "max_shard_cycle_s": max(r["cycle_s"] for r in srows),
                "sum_shard_cycle_s": round(
                    sum(r["cycle_s"] for r in srows), 4)}
            cluster.tick()
            for s in scheds:
                cluster.unwatch(s.cache._on_cluster_event)
            del scheds
            _drain_gang(f"gs{count}-")
            gc.collect()
    finally:
        gc.unfreeze()
        procpool.shutdown()

    out = {
        "hosts": len(cluster.nodes),
        "host_cpus": _os.cpu_count(),
        "gang": gang,
        "cycles": cycles,
        "waterfall_s": waterfall,
        "sharded_plane": sharded,
        "entry_rows_disarmed": rows,
        "entry_rows_armed": armed_rows,
        "entries_identical_all_backends_all_worker_counts":
            ok_disarmed and ok_armed,
        "freeze_audit": {
            "sessions_frozen": audit["sessions_frozen"],
            "fanout_regions": audit["fanout_regions"],
            "tracked_stores": audit["tracked_stores"],
            "violations": audit["violations"],
        },
        "note": ("single-CPU host: process/thread rows measure the "
                 "batched prepared-form sweep plus the mirror "
                 "protocol's IPC overhead, serialized by one core — "
                 "host_cpus recorded so a multi-core replay separates "
                 "the batching win from hardware parallelism"),
    }
    if include_40k:
        print("40k idle-cycle acceptance row:", flush=True)
        s40 = bench_40k_host_scale()
        s40["idle_le_0.1s"] = s40["idle_cycle_s"] <= 0.1
        out["idle_40k"] = s40
    out["ok"] = bool(
        out["entries_identical_all_backends_all_worker_counts"]
        and not audit["violations"]
        and (not include_40k or out["idle_40k"]["idle_le_0.1s"]))
    return out


def bench_sweep_smoke() -> dict:
    """Tier-1 smoke for the process-pool sweep: REAL worker OS
    processes on a small cluster — entry bit-identity vs serial,
    full-cycle placement identity vs serial, mirror full->delta sync
    order, distinct worker pids."""
    import copy
    import os as _os

    from volcano_tpu import metrics
    from volcano_tpu.actions import procpool
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import TaskStatus
    from volcano_tpu.framework.framework import (close_session,
                                                 open_session)
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job

    def decisions(cluster):
        return sorted((k.rsplit("-", 1)[0], node)
                      for k, node in cluster.binds)

    def run(backend):
        cluster = make_tpu_cluster(
            [(f"s{i}", "v5e-16") for i in range(4)])
        conf = copy.deepcopy(BENCH_CONF)
        if backend:
            conf["configurations"] = {"allocate": {
                "parallelPredicates": backend,
                "parallelPredicates.workers": 2}}
        sched = Scheduler(cluster, conf=conf, schedule_period=0)
        for g in range(2):
            pg, pods = gang_job(f"g{g}", replicas=4, min_available=4,
                                requests={"cpu": 2, TPU: 4})
            cluster.add_podgroup(pg)
            for p in pods:
                cluster.add_pod(p)
        sched.run_once()
        cluster.tick()
        pg, pods = gang_job("late", replicas=4, min_available=4,
                            requests={"cpu": 2, TPU: 4})
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
        sched.run_once()               # second cycle: delta-synced
        return cluster, sched, decisions(cluster)

    try:
        _c, _s, serial = run("")
        cluster, sched, proc = run("process")
        pool = procpool.pool(2)
        pids = {pid for _w, pid, _g, _o in pool.ping()}
        full = metrics._counters.get(
            ("sweep_snapshot_delta_bytes_total",
             (("kind", "full"),)), 0.0)
        delta = metrics._counters.get(
            ("sweep_snapshot_delta_bytes_total",
             (("kind", "delta"),)), 0.0)

        # entry-level bit identity on the live session
        pg, pods = gang_job("probe", replicas=2, min_available=2,
                            requests={"cpu": 2, TPU: 4})
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
        ssn = open_session(sched.cache, sched.conf)
        task = next(t for j in ssn.jobs.values()
                    for t in j.tasks_in_status(TaskStatus.PENDING))
        nodes = list(ssn.nodes.values())
        _, serial_entry = _sweep_entry_bench(ssn, nodes, task, "", 0,
                                             reps=1)
        _, proc_entry = _sweep_entry_bench(ssn, nodes, task,
                                           "process", 2, reps=1)
        close_session(ssn)
        return {
            "placements_identical": proc == serial,
            "entry_identical": _entries_identical(proc_entry,
                                                  serial_entry),
            "real_worker_processes":
                len(pids) == 2 and _os.getpid() not in pids,
            "full_sync_bytes": int(full),
            "delta_sync_bytes": int(delta),
            "synced_full_then_delta": full > 0 and delta > 0,
            "placements": len(serial),
        }
    finally:
        procpool.shutdown()


def sweep_smoke() -> int:
    """CLI wrapper for tier-1 (tests/test_procpool.py), mirroring
    --wire-smoke: prints one JSON line, exit 0 only when every check
    holds."""
    try:
        out = bench_sweep_smoke()
    except Exception as e:  # noqa: BLE001 - smoke must report, not die
        print(json.dumps({"metric": "sweep_smoke", "ok": False,
                          "error": repr(e)}))
        return 1
    ok = (out["placements_identical"] and out["entry_identical"]
          and out["real_worker_processes"]
          and out["synced_full_then_delta"])
    print(json.dumps({"metric": "sweep_smoke", "ok": ok, **out}))
    return 0 if ok else 1


def bench_net_accounting_overhead(pods_per_host: int = 120,
                                  ticks: int = 20) -> dict:
    """Per-tick cost of the DCN accounting subsystem at 100+ pods on
    one host: a fake cgroup fs with *pods_per_host* BE pods whose
    tx counters advance every tick, measured two ways — the collector
    walk alone, and the full agent sync including the netaccounting
    handler (watermarks, hysteresis, report build)."""
    import os
    import shutil
    import tempfile

    from volcano_tpu.agent.agent import (DCN_BANDWIDTH_ANNOTATION,
                                         FakeUsageProvider, NodeAgent)
    from volcano_tpu.agent.collect import NetAccountingCollector
    from volcano_tpu.agent.enforcer import CgroupV2Enforcer
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import (QOS_BEST_EFFORT,
                                       QOS_LEVEL_ANNOTATION, TaskStatus)
    from volcano_tpu.simulator import make_tpu_cluster

    tmp = tempfile.mkdtemp(prefix="netacct-bench-")
    try:
        cluster = make_tpu_cluster([("sa", "v5e-4")])
        node = sorted(cluster.nodes)[0]
        cluster.nodes[node].annotations[DCN_BANDWIDTH_ANNOTATION] = \
            "100000"
        pods = [make_pod(f"be-{i}", node_name=node,
                         phase=TaskStatus.RUNNING,
                         requests={"cpu": "100m"},
                         annotations={QOS_LEVEL_ANNOTATION:
                                      QOS_BEST_EFFORT})
                for i in range(pods_per_host)]
        for p in pods:
            cluster.add_pod(p)
        provider = FakeUsageProvider()
        provider.set(node, cpu_fraction=0.3)
        cg = CgroupV2Enforcer(tmp)
        col = NetAccountingCollector(cg.root)
        agent = NodeAgent(cluster, node, provider, enforcer=cg,
                          net_collector=col)
        agent.sync()                       # tag cgroups, create dirs
        tx = 0

        def advance_counters():
            nonlocal tx
            tx += 1_000_000
            for p in pods:
                path = os.path.join(
                    cg.root, CgroupV2Enforcer.POD_DIR_PREFIX + p.uid,
                    "net_stat.tx_bytes")
                with open(path, "w") as f:
                    f.write(str(tx))

        advance_counters()
        agent.sync()                       # baseline readings
        walk_s = []
        for _ in range(ticks):
            advance_counters()
            time.sleep(NetAccountingCollector.MIN_INTERVAL_S + 0.01)
            t0 = time.perf_counter()
            col.collect(node)
            walk_s.append(time.perf_counter() - t0)
        sync_s = []
        for _ in range(ticks):
            advance_counters()
            time.sleep(NetAccountingCollector.MIN_INTERVAL_S + 0.01)
            t0 = time.perf_counter()
            agent.sync()
            sync_s.append(time.perf_counter() - t0)
        # baseline: the SAME pipeline minus accounting (enforcer knob
        # writes dominate on slow filesystems; the delta is what the
        # subsystem actually costs per tick)
        base_agent = NodeAgent(cluster, node, provider, enforcer=cg)
        base_s = []
        for _ in range(ticks):
            t0 = time.perf_counter()
            base_agent.sync()
            base_s.append(time.perf_counter() - t0)
        with_ms = statistics.median(sync_s) * 1e3
        base_ms = statistics.median(base_s) * 1e3
        return {
            "pods_per_host": pods_per_host,
            "collector_walk_p50_ms": round(
                statistics.median(walk_s) * 1e3, 3),
            "agent_sync_with_accounting_p50_ms": round(with_ms, 3),
            "agent_sync_baseline_p50_ms": round(base_ms, 3),
            "accounting_overhead_p50_ms": round(with_ms - base_ms, 3),
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


# -- slice-failover chaos ----------------------------------------------

FAILOVER_CONF = {
    "actions": "enqueue, allocate, backfill",
    "tiers": [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "failover"}, {"name": "conformance"}]},
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"}, {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "binpack"},
                     {"name": "deviceshare"},
                     {"name": "network-topology-aware"}]},
    ],
}


def bench_failover_chaos(smoke: bool = False) -> dict:
    """Chaos scenario for the failover subsystem: a hard-topology gang
    trains on one slice of a 1k-host cluster, one of its hosts dies
    (chip telemetry flips; the agent's K-tick hysteresis detects it),
    and the detect → declare → drain → reschedule → resume loop runs
    through the REAL control path (agent handler → SliceHealthReport →
    failover controller → RestartJob → scheduler with quarantine
    filter).  Reports the wall-clock MTTR p50/p95 with the per-phase
    breakdown from the failover_* metric families, plus the control-
    cycle count to recovery.  Committed as FAILOVER_r07.json."""
    from volcano_tpu import metrics
    from volcano_tpu.agent.agent import FakeUsageProvider, NodeAgent
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.podgroup import NetworkTopologySpec
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.slicehealth import (
        CHECKPOINT_DIR_ANNOTATION, FAILOVER_GENERATION_ANNOTATION,
        LAST_STEP_ANNOTATION)
    from volcano_tpu.api.types import (JobPhase, NetworkTopologyMode,
                                       TPU_SLICE_LABEL, TaskStatus)
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    from volcano_tpu.controllers import ControllerManager
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import fail_host, make_tpu_cluster

    slice_kind = "v5e-16" if smoke else "v5e-256"    # 4 / 64 hosts
    n_slices = 2 if smoke else 16                    # 8 / 1024 hosts
    gang = 4 if smoke else 64                        # one whole slice
    trials = 1 if smoke else 5
    cycle_budget = 40

    phases = {k: [] for k in ("detect", "drain", "reschedule",
                              "resume", "mttr", "step_gap")}
    cycles_to_recover = []
    hosts = None
    for trial in range(trials):
        cluster = make_tpu_cluster(
            [(f"t{trial}s{i}", slice_kind) for i in range(n_slices)])
        hosts = len(cluster.nodes)
        mgr = ControllerManager(cluster, enabled=[
            "job", "podgroup", "queue", "failover"])
        sched = Scheduler(cluster, conf=FAILOVER_CONF,
                          schedule_period=0)

        def cycle(agent=None):
            if agent is not None:
                agent.sync()
            mgr.sync_all()
            sched.run_once()
            cluster.tick()

        job = VCJob(
            name="train", min_available=gang,
            annotations={CHECKPOINT_DIR_ANNOTATION: "/ckpt/train",
                         LAST_STEP_ANNOTATION: "1000"},
            network_topology=NetworkTopologySpec(
                NetworkTopologyMode.HARD, 1),
            plugins={"jax": []},
            tasks=[TaskSpec(name="worker", replicas=gang,
                            template=make_pod(
                                "t", requests={"cpu": 8, TPU: 4}))])
        cluster.add_vcjob(job)
        for _ in range(10):
            cycle()
            j = cluster.vcjobs["default/train"]
            if j.phase is JobPhase.RUNNING:
                break
        assert j.phase is JobPhase.RUNNING, \
            f"gang never started: {j.phase}"
        victim = sorted(p.node_name for p in cluster.pods.values()
                        if p.owner == j.uid)[0]
        victim_slice = cluster.nodes[victim].labels[TPU_SLICE_LABEL]

        counts = {k: len(metrics.get_observations(
            f"failover_{k}_seconds", slice=victim_slice))
            for k in ("detect", "drain", "reschedule", "resume",
                      "mttr")}
        provider = FakeUsageProvider()
        agent = NodeAgent(cluster, victim, provider)
        agent.sync()
        fail_host(cluster, victim, provider=provider)
        recovered_at = None
        for i in range(cycle_budget):
            cycle(agent)
            j = cluster.vcjobs["default/train"]
            done = len(metrics.get_observations(
                "failover_mttr_seconds", slice=victim_slice)) \
                > counts["mttr"]
            if done:
                recovered_at = i + 1
                break
        assert recovered_at is not None, (
            f"failover did not complete in {cycle_budget} cycles "
            f"(job {j.phase}, gen "
            f"{j.annotations.get(FAILOVER_GENERATION_ANNOTATION)})")
        assert j.phase is JobPhase.RUNNING
        assert j.annotations.get(FAILOVER_GENERATION_ANNOTATION) == "1"
        new_homes = {cluster.nodes[p.node_name].labels[TPU_SLICE_LABEL]
                     for p in cluster.pods.values()
                     if p.owner == j.uid and p.node_name
                     and p.phase in (TaskStatus.BOUND,
                                     TaskStatus.RUNNING)}
        assert victim_slice not in new_homes, \
            f"gang re-landed on the failed slice {victim_slice}"
        cycles_to_recover.append(recovered_at)
        for k in ("detect", "drain", "reschedule", "resume", "mttr"):
            obs = metrics.get_observations(f"failover_{k}_seconds",
                                           slice=victim_slice)
            phases[k].extend(obs[counts[k]:])
        phases["step_gap"].extend(metrics.get_observations(
            "failover_resume_step_gap", slice=victim_slice))
        mgr.stop()

    def pct(vals, q):
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1,
                              int(q * len(vals)))], 4) if vals else None

    out = {
        "hosts": hosts, "gang_hosts": gang, "trials": trials,
        "mttr_p50_s": pct(phases["mttr"], 0.5),
        "mttr_p95_s": pct(phases["mttr"], 0.95),
        "breakdown_p50_s": {
            k: pct(phases[k], 0.5)
            for k in ("detect", "drain", "reschedule", "resume")},
        "breakdown_p95_s": {
            k: pct(phases[k], 0.95)
            for k in ("detect", "drain", "reschedule", "resume")},
        "resume_step_gap_max": (max(phases["step_gap"])
                                if phases["step_gap"] else None),
        "cycles_to_recover": cycles_to_recover,
        "detection_syncs": 3,     # TpuHealthHandler.FAIL_SYNCS
    }
    return out


def failover_smoke() -> int:
    """Seconds-scale failover chaos (tiny shapes) for tier-1: kills
    one fake host and asserts the gang re-reaches Running with a
    bumped failover generation inside the cycle budget — the whole
    detect→drain→reschedule→resume loop guarded on every commit,
    mirroring --wire-smoke.  Prints one JSON line."""
    try:
        out = bench_failover_chaos(smoke=True)
        ok = out["mttr_p50_s"] is not None and \
            all(c <= 40 for c in out["cycles_to_recover"])
    except AssertionError as e:
        out, ok = {"error": str(e)[-600:]}, False
    print(json.dumps({"metric": "failover_smoke", "ok": ok, **out}))
    return 0 if ok else 1


# -- elastic gangs: shrink/grow/migrate as a scheduler decision --------

ELASTIC_CONF = {
    "actions": "enqueue, allocate, elastic, gangpreempt, backfill",
    "tiers": [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "failover"}, {"name": "elastic"},
                     {"name": "conformance"}]},
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"}, {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "binpack"},
                     {"name": "deviceshare"},
                     {"name": "network-topology-aware"}]},
    ],
    "configurations": {"elastic": {"elastic.cooldownSeconds": 0}},
}


def _elastic_vcjob(name, slices, lo, hi, pods_per_slice,
                   run_ticks=None):
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import RUN_TICKS_ANNOTATION
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    pod_ann = {} if run_ticks is None else \
        {RUN_TICKS_ANNOTATION: str(run_ticks)}
    return VCJob(
        name=name, min_available=slices * pods_per_slice,
        annotations={
            eapi.ELASTIC_MIN_SLICES_ANNOTATION: str(lo),
            eapi.ELASTIC_MAX_SLICES_ANNOTATION: str(hi),
            eapi.ELASTIC_SLICES_ANNOTATION: str(slices),
        },
        plugins={"jax": []},
        tasks=[TaskSpec(name="worker",
                        replicas=slices * pods_per_slice,
                        template=make_pod(
                            "t", requests={"cpu": 8, TPU: 4},
                            annotations=pod_ann))])


def _fixed_vcjob(name, replicas, run_ticks=None):
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import RUN_TICKS_ANNOTATION
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    pod_ann = {} if run_ticks is None else \
        {RUN_TICKS_ANNOTATION: str(run_ticks)}
    return VCJob(
        name=name, min_available=replicas,
        tasks=[TaskSpec(name="worker", replicas=replicas,
                        template=make_pod(
                            "t", requests={"cpu": 8, TPU: 4},
                            annotations=pod_ann))])


def _chip_utilization(cluster) -> float:
    """Fraction of the cluster's TPU chips held by BOUND/RUNNING pods."""
    from volcano_tpu.api.resource import Resource, TPU
    from volcano_tpu.api.types import TaskStatus
    total = used = 0.0
    for node in cluster.nodes.values():
        total += float(Resource.from_resource_list(
            node.allocatable).get(TPU))
    for pod in cluster.pods.values():
        if pod.node_name and pod.phase in (TaskStatus.BOUND,
                                           TaskStatus.RUNNING):
            used += float(pod.resource_requests().get(TPU) or 0)
    return used / total if total else 0.0


def bench_elastic(smoke: bool = False) -> dict:
    """Elastic-gang chaos on a contended cluster (ISSUE 6 acceptance):
    fixed gangs pin most slices, elastic jobs absorb EVERY idle slice
    (utilization >= 0.99), a burst of fixed demand forces shrinks
    (latency measured decision -> slices freed), and a live migration
    moves a gang between slices through the same drain/resume path
    (MTTR measured decision -> running on the new slices).  Committed
    as ELASTIC_r10.json together with the dp-resize loss-continuity
    dryrun (--elastic-child)."""
    from volcano_tpu import metrics
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api.types import JobPhase, TPU_SLICE_LABEL
    from volcano_tpu.controllers import ControllerManager
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.webhooks import default_admission

    slice_kind = "v5e-16" if smoke else "v5e-256"   # 4 / 64 hosts
    n_slices = 4 if smoke else 16                   # 16 / 1024 hosts
    pods_per_slice = 4 if smoke else 64
    n_fixed = 1 if smoke else 10
    n_elastic = 1 if smoke else 2
    elastic_start = 1 if smoke else 2
    trials = 1 if smoke else 5
    cycle_budget = 60

    shrink_lat, grow_lat, migrate_lat = [], [], []
    utilizations, grow_cycles = [], []
    hosts = None
    for trial in range(trials):
        cluster = make_tpu_cluster(
            [(f"t{trial}s{i:02d}", slice_kind)
             for i in range(n_slices)])
        cluster.admission = default_admission()
        hosts = len(cluster.nodes)
        mgr = ControllerManager(cluster, enabled=[
            "job", "podgroup", "queue", "failover", "elastic"])
        sched = Scheduler(cluster, conf=ELASTIC_CONF,
                          schedule_period=0)

        def cycle(n=1):
            for _ in range(n):
                mgr.sync_all()
                sched.run_once()
                cluster.tick()

        def job_slices(name):
            j = cluster.vcjobs[f"default/{name}"]
            return sorted({
                cluster.nodes[p.node_name].labels[TPU_SLICE_LABEL]
                for p in cluster.pods.values()
                if p.owner == j.uid and p.node_name})

        # fixed load pins most of the cluster; elastic jobs start
        # small — the leftover slices are the utilization gap
        grow0 = len(metrics.get_observations("elastic_resize_seconds",
                                             kind="grow"))
        for i in range(n_fixed):
            cluster.add_vcjob(_fixed_vcjob(f"fixed-{i}",
                                           pods_per_slice))
        for i in range(n_elastic):
            cluster.add_vcjob(_elastic_vcjob(
                f"elastic-{i}", elastic_start, 1, n_slices,
                pods_per_slice))

        # phase 1: place everything, grow until every chip is busy
        util = 0.0
        for i in range(cycle_budget):
            cycle()
            util = _chip_utilization(cluster)
            if util >= 0.99:
                grow_cycles.append(i + 1)
                break
        assert util >= 0.99, \
            f"elastic growth stalled at utilization {util:.3f}"
        utilizations.append(round(util, 4))
        # the grow EPISODE resumes (pods running) a cycle or two
        # after utilization peaks (pods bound): settle before reading
        # the latency observations
        for _ in range(cycle_budget):
            if len(metrics.get_observations(
                    "elastic_resize_seconds", kind="grow")) > grow0:
                break
            cycle()
        grow_lat.extend(metrics.get_observations(
            "elastic_resize_seconds", kind="grow")[grow0:])

        # phase 2: burst fixed demand -> shrink frees the slices
        shrink0 = len(metrics.get_observations(
            "elastic_shrink_seconds"))
        burst = 1 if smoke else 2
        for i in range(burst):
            cluster.add_vcjob(_fixed_vcjob(
                f"burst-{i}", pods_per_slice, run_ticks=24))
        for i in range(cycle_budget):
            cycle()
            if all(cluster.vcjobs[f"default/burst-{i}"].phase
                   is JobPhase.RUNNING for i in range(burst)):
                break
        assert all(cluster.vcjobs[f"default/burst-{i}"].phase
                   is JobPhase.RUNNING for i in range(burst)), \
            "burst gangs never scheduled (shrink did not free slices)"
        assert not cluster.evictions, \
            f"shrink path evicted pods: {cluster.evictions[:4]}"
        shrink_lat.extend(metrics.get_observations(
            "elastic_shrink_seconds")[shrink0:])

        # phase 3: the burst completes, then live-migrate one gang
        # onto the freed slices (policy-initiated, same drain path)
        for i in range(cycle_budget):
            cycle()
            if all(cluster.vcjobs[f"default/burst-{i}"].phase
                   is JobPhase.COMPLETED for i in range(burst)):
                break
        mig0 = len(metrics.get_observations(
            "elastic_migration_mttr_seconds"))
        victim = "elastic-0"
        old_homes = job_slices(victim)
        pg = cluster.podgroups[f"default/{victim}"]
        pg.annotations[eapi.ELASTIC_DESIRED_SLICES_ANNOTATION] = \
            str(eapi.current_slices(pg))
        pg.annotations[eapi.ELASTIC_RESIZE_REASON_ANNOTATION] = \
            eapi.RESIZE_MIGRATE
        pg.annotations[eapi.ELASTIC_AVOID_SLICES_ANNOTATION] = \
            ",".join(old_homes)
        for i in range(cycle_budget):
            cycle()
            if len(metrics.get_observations(
                    "elastic_migration_mttr_seconds")) > mig0:
                break
        new_homes = job_slices(victim)
        assert not (set(new_homes) & set(old_homes)), \
            f"migration landed back on {old_homes}"
        migrate_lat.extend(metrics.get_observations(
            "elastic_migration_mttr_seconds")[mig0:])
        mgr.stop()

    def pct(vals, q):
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1,
                              int(q * len(vals)))], 4) if vals else None

    return {
        "hosts": hosts, "slices": n_slices,
        "pods_per_slice": pods_per_slice, "trials": trials,
        "fixed_jobs": n_fixed, "elastic_jobs": n_elastic,
        "utilization": min(utilizations),
        "utilization_target": 0.99,
        "grow_cycles_to_full": grow_cycles,
        "grow_latency_p50_s": pct(grow_lat, 0.5),
        "grow_latency_p95_s": pct(grow_lat, 0.95),
        "shrink_latency_p50_s": pct(shrink_lat, 0.5),
        "shrink_latency_p95_s": pct(shrink_lat, 0.95),
        "migration_mttr_p50_s": pct(migrate_lat, 0.5),
        "migration_mttr_p95_s": pct(migrate_lat, 0.95),
        "shrink_samples": len(shrink_lat),
        "migration_samples": len(migrate_lat),
        "evictions": 0,
    }


def _elastic_child():
    """Child process for the dp-resize loss-continuity dryrun (needs
    its own XLA_FLAGS device count): train at dp=2 over 8 devices
    with a fixed global batch, checkpoint, resume at dp=1 over 4
    devices, compare the post-resize losses against the fixed-size
    trajectory.  Prints ONE JSON line."""
    import jax

    from volcano_tpu.workloads import checkpoint, model as model_lib, \
        train
    from volcano_tpu.workloads.mesh import make_mesh

    import tempfile
    devices = jax.devices()
    mesh_big = make_mesh({"dp": 2, "fsdp": 2, "tp": 2, "sp": 1},
                         devices[:8])
    mesh_small = make_mesh({"dp": 1, "fsdp": 2, "tp": 2, "sp": 1},
                           devices[:4])
    cfg = model_lib.tiny_config()
    opt = train.make_optimizer(lr=1e-2, warmup_steps=1)
    params, state, _ = train.init_sharded(jax.random.key(0), cfg,
                                          mesh_big, opt)
    step_big = train.make_train_step(cfg, mesh_big, opt)
    batch = train.synthetic_batch(jax.random.key(1), cfg, 4, 64,
                                  mesh_big)
    ckpt = tempfile.mkdtemp(prefix="elastic-ckpt-")
    losses = {}
    for step in range(1, 6):
        params, state, m = step_big(params, state, batch)
        losses[step] = float(m["loss"])
        if step == 3:
            checkpoint.save(ckpt, step=step, params=params,
                            opt_state=state)
    env = {"VTP_CHECKPOINT_DIR": ckpt, "VTP_RESUME_STEP": "3"}
    p2, s2, _ = train.init_sharded(jax.random.key(99), cfg,
                                   mesh_small, opt)
    p2, s2, start = checkpoint.resume_state(p2, s2, environ=env)
    step_small = train.make_train_step(cfg, mesh_small, opt)
    batch_small = train.synthetic_batch(jax.random.key(1), cfg, 4, 64,
                                        mesh_small)
    diffs = []
    for step in range(start + 1, 6):
        p2, s2, m = step_small(p2, s2, batch_small)
        base = losses[step]
        diffs.append(abs(float(m["loss"]) - base) / max(abs(base),
                                                        1e-9))
    out = {
        "world_before_devices": 8, "world_after_devices": 4,
        "dp_before": 2, "dp_after": 1, "global_batch": 4,
        "resume_step": start,
        "resume_step_never_rewinds": start == 3,
        "max_rel_loss_diff": round(max(diffs), 8),
        "tolerance": 1e-3,
        "loss_continuous": start == 3 and max(diffs) < 1e-3,
    }
    print(json.dumps(out), flush=True)


def _run_elastic_child(timeout_s: float = 600.0) -> dict:
    """Run --elastic-child in a subprocess with an 8-device CPU mesh."""
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--elastic-child"],
        capture_output=True, text=True, timeout=timeout_s, env=env,
        cwd=repo)
    for line in reversed((proc.stdout or "").strip().splitlines()
                         or [""]):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return {"loss_continuous": False,
            "error": (proc.stderr or "no output")[-500:]}


def bench_elastic_wire_smoke() -> dict:
    """One grow + one shrink through the REAL process control plane
    (state server + scheduler + controllers as OS processes) — the
    tier-1 guard that the elastic loop works over the wire, not just
    in-process."""
    import os

    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.api.types import JobPhase
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes

    plane = _WirePlane()
    # the scheduler process needs the elastic action + zero cooldown
    conf_path = os.path.join(plane.logdir, "elastic-conf.yaml")
    with open(conf_path, "w") as f:
        json.dump(ELASTIC_CONF, f)     # JSON is valid YAML
    kubectl = None
    try:
        plane.spawn("server", "-m", "volcano_tpu.server",
                    "--port", str(plane.port), "--tick-period", "0.05")
        import urllib.request

        def up():
            try:
                with urllib.request.urlopen(plane.url + "/healthz",
                                            timeout=1):
                    return True
            except OSError:
                return False
        _wire_wait(up, 20, "state server /healthz")
        plane.spawn("controllers", "-m", "volcano_tpu",
                    "--cluster-url", plane.url,
                    "--components", "controllers", "--period", "0.05")
        plane.spawn("scheduler", "-m", "volcano_tpu",
                    "--cluster-url", plane.url,
                    "--components", "scheduler", "--period", "0.05",
                    "--conf", conf_path)
        kubectl = RemoteCluster(plane.url)
        for i in range(3):
            for node in slice_nodes(slice_for(f"s{i}", "v5e-16"),
                                    dcn_pod="dcn-0"):
                kubectl.add_node(node)

        kubectl.add_vcjob(_fixed_vcjob("pin", 4))
        kubectl.add_vcjob(_elastic_vcjob("egang", 1, 1, 2, 4))

        def gen_at_least(n):
            pg = kubectl.podgroups.get("default/egang")
            j = kubectl.vcjobs.get("default/egang")
            return (pg is not None and j is not None
                    and j.phase is JobPhase.RUNNING
                    and int(pg.annotations.get(
                        eapi.ELASTIC_GENERATION_ANNOTATION, 0)) >= n)

        # grow: the idle third slice is absorbed
        _wire_wait(lambda: gen_at_least(1)
                   and eapi.current_slices(
                       kubectl.podgroups["default/egang"]) == 2,
                   60, lambda: "elastic grow over the wire "
                   f"({plane.log_tails()[-900:]})")
        grow_ok = True
        util_at_grow = _chip_utilization(kubectl)

        # shrink: new fixed demand reclaims the slice
        kubectl.add_vcjob(_fixed_vcjob("burst", 4))
        _wire_wait(lambda: gen_at_least(2)
                   and eapi.current_slices(
                       kubectl.podgroups["default/egang"]) == 1
                   and (kubectl.vcjobs.get("default/burst") is not None
                        and kubectl.vcjobs["default/burst"].phase
                        is JobPhase.RUNNING),
                   60, lambda: "elastic shrink over the wire "
                   f"({plane.log_tails()[-900:]})")
        shrink_ok = True
        pg = kubectl.podgroups["default/egang"]
        hist = eapi.resize_history(pg)
        return {
            "grow_ok": grow_ok, "shrink_ok": shrink_ok,
            "utilization": round(util_at_grow, 4),
            "resize_history": hist[-2:],
            "hosts": 12,
        }
    finally:
        if kubectl is not None:
            kubectl.close()
        plane.shutdown()


def elastic_smoke() -> int:
    """Seconds-scale elastic drill for tier-1: one grow + one shrink
    through the real process control plane, mirroring --wire-smoke /
    --failover-smoke.  Prints one JSON line."""
    try:
        out = bench_elastic_wire_smoke()
        ok = out["grow_ok"] and out["shrink_ok"]
    except AssertionError as e:
        out, ok = {"error": str(e)[-900:]}, False
    print(json.dumps({"metric": "elastic_smoke", "ok": ok, **out}))
    return 0 if ok else 1


# -- goodput observatory (ISSUE 9) -------------------------------------


def _post_job_reports(cluster, job_name, rate, ts, ledger, dt=1.0,
                      productive_frac=1.0, step=0):
    """Simulate the agent fleet for one gang: one GoodputReport per
    hosting node, entries carrying the simulated step rate and the
    CUMULATIVE per-pod ledger (*ledger*: uid -> (alloc, prod),
    advanced by one dt window per call — the store diffs against the
    node's previous report exactly as it would for real agents)."""
    from volcano_tpu.api import goodput as gapi
    from volcano_tpu.api.types import TaskStatus
    j = cluster.vcjobs[f"default/{job_name}"]
    by_node = {}
    for p in cluster.pods.values():
        if p.owner == j.uid and p.node_name and \
                p.phase in (TaskStatus.BOUND, TaskStatus.RUNNING):
            by_node.setdefault(p.node_name, []).append(p)
    for node, pods in by_node.items():
        gen = gapi.generation_of(cluster.nodes[node].labels)
        usages = []
        for p in pods:
            alloc, prod = ledger.get(p.uid, (0.0, 0.0))
            alloc += dt
            prod += dt * productive_frac
            ledger[p.uid] = (alloc, prod)
            usages.append(gapi.PodGoodput(
                pod_key=p.key, uid=p.uid, job=f"default/{job_name}",
                generation=gen, step=step,
                steps_per_s=round(rate, 4),
                goodput=productive_frac,
                allocated_s=round(alloc, 4),
                productive_s=round(prod, 4)))
        cluster.put_object("goodputreport", gapi.GoodputReport(
            node=node, ts=ts, usages=usages))
    return len(by_node)


def bench_goodput(smoke: bool = False) -> dict:
    """Goodput observatory acceptance (ISSUE 9), three committed
    claims in one artifact (GOODPUT_r{N}.json):

      1. learned per-(job, generation) throughput vectors converge to
         the simulator's ground-truth step rates within 10% on a
         1k-host contended cluster with deliberately heterogeneous
         rates (v5e vs v5p generations, per-job multipliers);
      2. the goodput ledger (REAL GoodputCollector over a synthetic
         progress filesystem with an injected clock) reconciles with
         wall-clock allocated time within 5% across a stall + a
         resize-epoch restart;
      3. gating elastic grow on measured marginal throughput (the
         minimal Pollux step) lifts aggregate ground-truth steps/s
         vs greedy absorption on the same scenario.
    """
    import time as _time

    from volcano_tpu import metrics
    from volcano_tpu.api import goodput as gapi
    from volcano_tpu.api.types import JobPhase
    from volcano_tpu.controllers import ControllerManager
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.webhooks import default_admission

    # -- phase 1: vector convergence at scale --------------------------
    n_per_gen = 2 if smoke else 8
    kinds = ("v5e-16", "v5p-128") if smoke else ("v5e-256", "v5p-256")
    pods_per_job = 4 if smoke else 64
    # more jobs than one generation holds, so placement spreads the
    # fleet across BOTH generations and the learned vectors prove the
    # per-generation split (v5p ground truth is 2.2x v5e)
    n_jobs = 2 if smoke else 10
    rounds = 6
    cluster = make_tpu_cluster(
        [(f"e{i:02d}", kinds[0]) for i in range(n_per_gen)]
        + [(f"p{i:02d}", kinds[1]) for i in range(n_per_gen)])
    cluster.admission = default_admission()
    hosts = len(cluster.nodes)
    sched = Scheduler(cluster, schedule_period=0)
    mgr = ControllerManager(cluster, enabled=["job", "podgroup",
                                              "queue"])
    for j in range(n_jobs):
        cluster.add_vcjob(_fixed_vcjob(f"gp-{j}", pods_per_job))
    for _ in range(20):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
        if all(cluster.vcjobs[f"default/gp-{j}"].phase
               is JobPhase.RUNNING for j in range(n_jobs)):
            break
    assert all(cluster.vcjobs[f"default/gp-{j}"].phase
               is JobPhase.RUNNING for j in range(n_jobs)), \
        "goodput bench jobs never all ran"

    # deliberately heterogeneous ground truth: per-job base rate,
    # v5p 2.2x faster than v5e — the vector the estimator must learn
    def gt_rate(j, gen):
        base = 5.0 + 2.0 * j
        return base * (2.2 if gen == "v5p" else 1.0)

    def job_generation(j):
        jj = cluster.vcjobs[f"default/gp-{j}"]
        for p in cluster.pods.values():
            if p.owner == jj.uid and p.node_name:
                return gapi.generation_of(
                    cluster.nodes[p.node_name].labels)
        return "other"

    rng = random.Random(11)
    dt = 1.0
    ledger = {}
    for r in range(rounds):
        for j in range(n_jobs):
            gen = job_generation(j)
            noisy = gt_rate(j, gen) * rng.uniform(0.97, 1.03)
            _post_job_reports(cluster, f"gp-{j}", noisy,
                              ts=1000.0 + r, ledger=ledger, dt=dt,
                              step=10 * (r + 1))
    book = sched.cache.goodput_book
    vec_errs = []
    for j in range(n_jobs):
        gen = job_generation(j)
        learned = book.vector(f"default/gp-{j}").get(gen, 0.0)
        truth = gt_rate(j, gen)
        vec_errs.append(abs(learned - truth) / truth)
    vector_max_rel_err = round(max(vec_errs), 4)
    assert vector_max_rel_err < 0.10, \
        f"learned vectors off by {vector_max_rel_err}"

    # the scale-side ledger: every pod contributed dt per round; the
    # accumulated podgroup ledger must equal pods x rounds x dt
    ledger_errs = []
    for j in range(n_jobs):
        pg = cluster.podgroups[f"default/gp-{j}"]
        acc = gapi.ann_float(pg.annotations,
                             gapi.PG_ALLOCATED_S_ANNOTATION)
        want = pods_per_job * rounds * dt
        ledger_errs.append(abs(acc - want) / want)
    ssn = sched.run_once()          # export the session gauges
    fleet_rate = metrics.get_gauge("goodput_fleet_steps_per_second")
    mgr.stop()

    # -- phase 2: ledger vs wall clock (REAL collector, fake clock) ----
    import tempfile

    from volcano_tpu.agent.collect import GoodputCollector
    from volcano_tpu.workloads.progress import ProgressReporter
    root = tempfile.mkdtemp(prefix="goodput-bench-")
    t = [0.0]
    col = GoodputCollector(root, now=lambda: t[0])
    n_pods = 4

    def write(uid, step, epoch):
        ProgressReporter(gapi.progress_file_for(root, uid),
                         epoch=epoch,
                         now=lambda: t[0]).report(step=step)

    # timeline per pod: 60s stepping at 1 step/s, 20s stalled
    # (drain), epoch bump + resume from the checkpoint floor (step
    # 30), 40s stepping — sampled every 2s like an agent sync
    for uid in range(n_pods):
        write(f"u{uid}", 0, 0)
    col.collect("n0")
    while t[0] < 120.0:
        t[0] += 2.0
        for uid in range(n_pods):
            if t[0] <= 60.0:
                write(f"u{uid}", int(t[0]), 0)
            elif t[0] <= 80.0:
                pass                      # stalled: file untouched
            else:
                write(f"u{uid}", 30 + int(t[0] - 80), 1)
        col.collect("n0")
    wall_alloc = 120.0 * n_pods
    acc_alloc = sum(st.allocated_s for st in col.rates().values())
    acc_prod = sum(st.productive_s for st in col.rates().values())
    reconcile_err = abs(acc_alloc - wall_alloc) / wall_alloc
    # expected productive: 60s stepping + 40s resumed, minus the one
    # post-epoch boundary window that earns no credit
    expected_prod = (60.0 + 40.0 - 2.0) * n_pods
    goodput_err = abs(acc_prod - expected_prod) / expected_prod
    assert reconcile_err < 0.05, \
        f"ledger {acc_alloc} vs wall {wall_alloc}"
    assert goodput_err < 0.05, \
        f"productive {acc_prod} vs expected {expected_prod}"
    rate_after = max(st.steps_per_s for st in col.rates().values())
    assert rate_after <= 1.1, \
        f"post-restart rate spiked to {rate_after}"

    # -- phase 3: goodput-gated grow vs greedy absorption --------------
    def run_grow_scenario(gate_on: bool):
        slice_kind = "v5e-16"
        conf = json.loads(json.dumps(ELASTIC_CONF))
        conf["configurations"]["elastic"][
            "elastic.goodputGateGrow"] = "true" if gate_on else "false"
        from volcano_tpu.cache.cluster import PriorityClass
        c = make_tpu_cluster([(f"g{i}", slice_kind) for i in range(4)])
        c.admission = default_admission()
        m = ControllerManager(c, enabled=["job", "podgroup", "queue",
                                          "failover", "elastic"])
        s = Scheduler(c, conf=conf, schedule_period=0)
        # the bad scaler outranks the good one in job order (priority
        # class), so GREEDY absorption always hands it the next idle
        # slice — only the measured-throughput gate redirects capacity
        c.add_priority_class(PriorityClass(name="hot", value=1000))
        bad = _elastic_vcjob("bad", 1, 1, 3, 4)
        bad.priority_class = "hot"
        c.add_vcjob(bad)
        c.add_vcjob(_elastic_vcjob("good", 1, 1, 3, 4))
        c.add_vcjob(_fixed_vcjob("pin-a", 4, run_ticks=8))
        c.add_vcjob(_fixed_vcjob("pin-b", 4, run_ticks=30))

        def rate_of(name, slices):
            # bad: near-flat scaling; good: linear
            return 10.0 * (slices ** 0.1) if name == "bad" \
                else 10.0 * slices

        ts = [2000.0]
        run_ledger = {}
        for cycle in range(120):
            m.sync_all()
            s.run_once()
            c.tick()
            ts[0] += 1.0
            for name in ("bad", "good"):
                pg = c.podgroups.get(f"default/{name}")
                jj = c.vcjobs.get(f"default/{name}")
                if pg is None or jj is None or \
                        jj.phase is not JobPhase.RUNNING:
                    continue
                cur = eapi.current_slices(pg)
                _post_job_reports(c, name, rate_of(name, cur),
                                  ts=ts[0], ledger=run_ledger,
                                  step=cycle)
            if c.vcjobs["default/pin-b"].phase is JobPhase.COMPLETED \
                    and cycle > 60:
                break
        sizes = {name: eapi.current_slices(
            c.podgroups[f"default/{name}"]) for name in ("bad",
                                                         "good")}
        agg = sum(rate_of(name, n) for name, n in sizes.items())
        m.stop()
        return sizes, round(agg, 3)

    gated_sizes, gated_agg = run_grow_scenario(gate_on=True)
    greedy_sizes, greedy_agg = run_grow_scenario(gate_on=False)
    lift = round(gated_agg / greedy_agg, 4) if greedy_agg else None
    assert lift and lift > 1.0, \
        f"goodput gating did not beat greedy: {gated_agg} vs " \
        f"{greedy_agg} ({gated_sizes} vs {greedy_sizes})"

    return {
        "hosts": hosts,
        "generations": sorted({job_generation(j)
                               for j in range(n_jobs)}),
        "jobs": n_jobs, "report_rounds": rounds,
        "vector_max_rel_err": vector_max_rel_err,
        "vector_err_target": 0.10,
        "ledger_scale_max_rel_err": round(max(ledger_errs), 6),
        "fleet_steps_per_second_gauge": round(fleet_rate, 3),
        "reconcile": {
            "pods": n_pods,
            "allocated_wall_s": wall_alloc,
            "allocated_accounted_s": round(acc_alloc, 3),
            "rel_err": round(reconcile_err, 6),
            "target": 0.05,
            "productive_accounted_s": round(acc_prod, 3),
            "productive_expected_s": expected_prod,
            "goodput_measured": round(acc_prod / acc_alloc, 4),
            "post_restart_rate_max": round(rate_after, 4),
        },
        "grow_gating": {
            "gated_slices": gated_sizes,
            "greedy_slices": greedy_sizes,
            "gated_agg_steps_per_s": gated_agg,
            "greedy_agg_steps_per_s": greedy_agg,
            "aggregate_lift": lift,
        },
    }


def bench_goodput_wire_smoke() -> dict:
    """Worker progress files -> REAL agent collector/handler ->
    GoodputReport over the wire -> store fold -> podgroup annotations,
    through the real process control plane (state server + scheduler +
    controllers as OS processes) — the tier-1 guard that the goodput
    stream works over the wire, not just in-process."""
    import os
    import time as _time

    from volcano_tpu.agent.agent import FakeUsageProvider, NodeAgent
    from volcano_tpu.agent.collect import GoodputCollector
    from volcano_tpu.agent.handlers import GoodputHandler
    from volcano_tpu.api import goodput as gapi
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import JobPhase, TaskStatus
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes

    plane = _WirePlane()
    kubectl = None
    agents = []
    try:
        plane.spawn("server", "-m", "volcano_tpu.server",
                    "--port", str(plane.port), "--tick-period", "0.05")
        import urllib.request

        def up():
            try:
                with urllib.request.urlopen(plane.url + "/healthz",
                                            timeout=1):
                    return True
            except OSError:
                return False
        _wire_wait(up, 20, "state server /healthz")
        plane.spawn("controllers", "-m", "volcano_tpu",
                    "--cluster-url", plane.url,
                    "--components", "controllers", "--period", "0.05")
        plane.spawn("scheduler", "-m", "volcano_tpu",
                    "--cluster-url", plane.url,
                    "--components", "scheduler", "--period", "0.05")
        kubectl = RemoteCluster(plane.url)
        for node in slice_nodes(slice_for("sa", "v5e-16"),
                                dcn_pod="dcn-0"):
            kubectl.add_node(node)

        progress_dir = os.path.join(plane.logdir, "progress")
        os.makedirs(progress_dir, exist_ok=True)
        kubectl.add_vcjob(VCJob(
            name="gp", min_available=4,
            annotations={gapi.PROGRESS_DIR_ANNOTATION: progress_dir},
            plugins={"jax": []},
            tasks=[TaskSpec(name="worker", replicas=4,
                            template=make_pod(
                                "t", requests={"cpu": 4, TPU: 4}))]))

        def workers_running():
            j = kubectl.vcjobs.get("default/gp")
            if j is None or j.phase is not JobPhase.RUNNING:
                return False
            pods = [p for p in kubectl.pods.values()
                    if p.owner == j.uid
                    and p.phase is TaskStatus.RUNNING and p.node_name]
            return len(pods) == 4
        _wire_wait(workers_running, 60,
                   lambda: "goodput smoke workers never ran "
                   f"({plane.log_tails()[-900:]})")

        j = kubectl.vcjobs["default/gp"]
        pods = [p for p in kubectl.pods.values() if p.owner == j.uid]
        env_ok = all(
            gapi.ENV_PROGRESS_FILE in p.containers[0].env
            for p in pods)

        # one REAL agent per host, sharing the collector over the
        # progress root (each handler pairs only its node's pods)
        col = GoodputCollector(progress_dir)
        for p in sorted(pods, key=lambda p: p.node_name):
            agents.append(NodeAgent(
                kubectl, p.node_name, FakeUsageProvider(),
                handlers=[GoodputHandler], goodput_collector=col))

        step = 0
        for _ in range(8):
            step += 2
            for p in pods:
                ProgressReporterFor(progress_dir, p.uid, step)
            for a in agents:
                a.sync()
            _time.sleep(0.25)

        def folded():
            pg = kubectl.podgroups.get("default/gp")
            return pg is not None and \
                gapi.ann_float(pg.annotations,
                               gapi.PG_STEP_RATE_ANNOTATION) > 0
        _wire_wait(folded, 30,
                   lambda: "goodput fold never reached the podgroup "
                   f"({plane.log_tails()[-900:]})")
        pg = kubectl.podgroups["default/gp"]
        ann = pg.annotations
        return {
            "fold_ok": True,
            "env_ok": env_ok,
            "steps_per_s": gapi.ann_float(
                ann, gapi.PG_STEP_RATE_ANNOTATION),
            "step": int(gapi.ann_float(ann, gapi.PG_STEP_ANNOTATION)),
            "goodput": gapi.ann_float(ann, gapi.PG_GOODPUT_ANNOTATION),
            "allocated_pod_s": gapi.ann_float(
                ann, gapi.PG_ALLOCATED_S_ANNOTATION),
            "reports": len(getattr(kubectl, "goodputreports", {})),
            "generation": ann.get(gapi.PG_GENERATION_ANNOTATION, ""),
            "hosts": 4,
        }
    finally:
        if kubectl is not None:
            kubectl.close()
        plane.shutdown()


def ProgressReporterFor(root, uid, step):
    from volcano_tpu.api import goodput as gapi
    from volcano_tpu.workloads.progress import ProgressReporter
    ProgressReporter(gapi.progress_file_for(root, uid)).report(
        step=step, examples=step * 32.0)


def goodput_smoke() -> int:
    """Seconds-scale goodput drill for tier-1: progress files ->
    real agents -> wire -> fold, mirroring --wire-smoke /
    --elastic-smoke.  Prints one JSON line."""
    try:
        out = bench_goodput_wire_smoke()
        ok = (out["fold_ok"] and out["env_ok"]
              and out["steps_per_s"] > 0 and 0 < out["goodput"] <= 1
              and out["step"] > 0)
    except AssertionError as e:
        out, ok = {"error": str(e)[-900:]}, False
    print(json.dumps({"metric": "goodput_smoke", "ok": ok, **out}))
    return 0 if ok else 1


# -- inference serving plane (ISSUE 17) --------------------------------


SERVE_CONF = {
    "actions": "enqueue, allocate, elastic, gangpreempt, backfill",
    "tiers": [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "failover"}, {"name": "elastic"},
                     {"name": "serving"}, {"name": "conformance"}]},
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"}, {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "binpack"},
                     {"name": "deviceshare"},
                     {"name": "network-topology-aware"}]},
    ],
    # unlike ELASTIC_CONF this plane wants a real cooldown: a serving
    # gang the autoscaler just grew must not be handed back by
    # shrink-pending-to-fit one session later
    "configurations": {"elastic": {"elastic.cooldownSeconds": 5}},
}


def _serving_vcjob(name, slices, lo, hi, pods_per_slice, stats_dir,
                   slo_ms=50.0, target_qps=100.0):
    """Serving replica group = elastic gang + the SLO contract
    (api/serving.py): min/max replicas ride the elastic min/max-slices
    annotations, one slice per replica."""
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api import serving as sapi
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    return VCJob(
        name=name, min_available=slices * pods_per_slice,
        annotations={
            sapi.SLO_P99_MS_ANNOTATION: str(slo_ms),
            sapi.MIN_REPLICAS_ANNOTATION: str(lo),
            sapi.MAX_REPLICAS_ANNOTATION: str(hi),
            sapi.TARGET_QPS_ANNOTATION: str(target_qps),
            sapi.STATS_DIR_ANNOTATION: stats_dir,
            eapi.ELASTIC_MIN_SLICES_ANNOTATION: str(lo),
            eapi.ELASTIC_MAX_SLICES_ANNOTATION: str(hi),
            eapi.ELASTIC_SLICES_ANNOTATION: str(slices),
        },
        plugins={"jax": []},
        tasks=[TaskSpec(name="replica",
                        replicas=slices * pods_per_slice,
                        template=make_pod(
                            "s", requests={"cpu": 8, TPU: 4}))])


def _serve_pool_tiers(kubectl, pool, gang_slices):
    """min hypernode-LCA tier between a gang's slices and the serving
    pool — the bench-side replica of the scheduler's victim score
    (actions/elastic.py), computed from the SAME hypernode objects so
    the adjacency assertion audits the scheduler from outside."""
    from volcano_tpu.api.hypernode import HyperNodesInfo
    hni = HyperNodesInfo(kubectl.hypernodes.values(),
                         real_nodes=list(kubectl.nodes.keys()))
    best = None
    for gs in gang_slices:
        for ps in pool:
            if gs in hni.members and ps in hni.members:
                tier = hni.lca_tier_of_leaves(gs, ps)
            else:
                tier = 99
            best = tier if best is None else min(best, tier)
    return 99 if best is None else best


def _job_slices_now(kubectl, job_key):
    from volcano_tpu.api.types import TPU_SLICE_LABEL, TaskStatus
    j = kubectl.vcjobs.get(job_key)
    if j is None:
        return []
    out = set()
    for p in kubectl.pods.values():
        if p.owner == j.uid and p.node_name \
                and p.phase in (TaskStatus.BOUND, TaskStatus.RUNNING) \
                and p.node_name in kubectl.nodes:
            s = kubectl.nodes[p.node_name].labels.get(TPU_SLICE_LABEL)
            if s:
                out.add(s)
    return sorted(out)


def bench_serving_wire_smoke() -> dict:
    """Traffic step -> replica stats -> REAL agents -> wire -> store
    fold -> autoscaler scale-up -> topology-aware burst preemption
    (the training gang shrinks, steered off the freed block) through
    the REAL process control plane — the tier-1 guard that the
    serving loop works over the wire, not just in-process."""
    import os
    import time as _time

    from volcano_tpu.agent.agent import FakeUsageProvider, NodeAgent
    from volcano_tpu.agent.collect import ServingCollector
    from volcano_tpu.agent.handlers import ServingHandler
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api import serving as sapi
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.api.types import JobPhase, TaskStatus
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes
    from volcano_tpu.workloads.serve import ServingStatsReporter

    plane = _WirePlane()
    conf_path = os.path.join(plane.logdir, "serve-conf.yaml")
    with open(conf_path, "w") as f:
        json.dump(SERVE_CONF, f)     # JSON is valid YAML
    kubectl = None
    agents = {}
    try:
        plane.spawn("server", "-m", "volcano_tpu.server",
                    "--port", str(plane.port), "--tick-period", "0.05")
        import urllib.request

        def up():
            try:
                with urllib.request.urlopen(plane.url + "/healthz",
                                            timeout=1):
                    return True
            except OSError:
                return False
        _wire_wait(up, 20, "state server /healthz")
        plane.spawn("controllers", "-m", "volcano_tpu",
                    "--cluster-url", plane.url,
                    "--components", "controllers", "--period", "0.05")
        plane.spawn("scheduler", "-m", "volcano_tpu",
                    "--cluster-url", plane.url,
                    "--components", "scheduler", "--period", "0.05",
                    "--conf", conf_path)
        kubectl = RemoteCluster(plane.url)
        # sa/sb share the serving DCN pod, sc sits across the DCN —
        # the distance differential the victim score ranks on
        for sname, dcn in (("sa", "dcn-0"), ("sb", "dcn-0"),
                           ("sc", "dcn-1")):
            for node in slice_nodes(slice_for(sname, "v5e-16"),
                                    dcn_pod=dcn):
                kubectl.add_node(node)

        stats_dir = os.path.join(plane.logdir, "serving")
        os.makedirs(stats_dir, exist_ok=True)
        kubectl.add_vcjob(_serving_vcjob(
            "infer", 1, 1, 2, 4, stats_dir, slo_ms=50.0,
            target_qps=100.0))
        kubectl.add_vcjob(_elastic_vcjob("train", 2, 1, 2, 4))

        def running(jname, want):
            j = kubectl.vcjobs.get(f"default/{jname}")
            if j is None or j.phase is not JobPhase.RUNNING:
                return False
            return sum(1 for p in kubectl.pods.values()
                       if p.owner == j.uid and p.node_name
                       and p.phase is TaskStatus.RUNNING) >= want
        _wire_wait(lambda: running("infer", 4) and running("train", 8),
                   60, lambda: "serve smoke gangs never ran "
                   f"({plane.log_tails()[-900:]})")

        j = kubectl.vcjobs["default/infer"]
        env_ok = all(
            sapi.ENV_STATS_FILE in p.containers[0].env
            for p in kubectl.pods.values() if p.owner == j.uid)

        col = ServingCollector(stats_dir)
        served = {"n": 0.0}
        pod_req = {}                 # uid -> cumulative requests
        flags = {"victim_marker": False, "victim_avoid": []}

        def feed(qps, dt):
            """One replica beat: the offered rate split across the
            group's pods (each replica serves its share, as a load
            balancer would spread it), cumulative stats -> REAL
            per-host agents -> ServingReport over the wire.  The
            store folds the group QPS back as the SUM of the shares."""
            served["n"] += qps * dt
            pg = kubectl.podgroups.get("default/infer")
            sj = kubectl.vcjobs.get("default/infer")
            if pg is None or sj is None:
                return
            epoch = int(pg.annotations.get(
                eapi.ELASTIC_GENERATION_ANNOTATION, 0) or 0)
            pods = [p for p in kubectl.pods.values()
                    if p.owner == sj.uid and p.node_name
                    and p.phase is TaskStatus.RUNNING]
            for p in pods:
                pod_req[p.uid] = pod_req.get(p.uid, 0.0) + \
                    qps * dt / max(1, len(pods))
                n = int(pod_req[p.uid])
                ServingStatsReporter(
                    sapi.stats_file_for(stats_dir, p.uid),
                    epoch=epoch).report(
                        requests=n, slo_ok=n,
                        p50_ms=4.0, p99_ms=30.0)
                if p.node_name not in agents:
                    agents[p.node_name] = NodeAgent(
                        kubectl, p.node_name, FakeUsageProvider(),
                        handlers=[ServingHandler],
                        serving_collector=col)
            for a in agents.values():
                a.sync()
            tpg = kubectl.podgroups.get("default/train")
            if tpg is not None and \
                    tpg.annotations.get(sapi.VICTIM_ANNOTATION):
                flags["victim_marker"] = True
                flags["victim_avoid"] = list(
                    eapi.avoid_slices(tpg))

        def wait_feed(cond, timeout, msg, qps):
            deadline = _time.monotonic() + timeout
            while _time.monotonic() < deadline:
                feed(qps, 0.25)
                if cond():
                    return
                _time.sleep(0.25)
            raise AssertionError(
                "serve smoke: timed out waiting for "
                + (msg() if callable(msg) else msg))

        # phase 1: cruise below the scale-up threshold — the
        # hysteresis must HOLD (no decision on quiet traffic)
        for _ in range(8):
            feed(60.0, 0.25)
            _time.sleep(0.25)
        pg = kubectl.podgroups["default/infer"]
        no_premature = sapi.PG_LAST_DECISION_ANNOTATION \
            not in pg.annotations
        qps_low = sapi.ann_float(pg.annotations,
                                 sapi.PG_QPS_ANNOTATION)

        # phase 2: the traffic step — ONE decision sized for the
        # burst, then the funded preemption frees the chips
        t_step = _time.monotonic()
        state = {}

        def decision_seen():
            g = kubectl.podgroups.get("default/infer")
            d = "" if g is None else g.annotations.get(
                sapi.PG_LAST_DECISION_ANNOTATION, "")
            if d.startswith("scale-up") and "t" not in state:
                state["t"] = _time.monotonic()
                state["decision"] = d
            return "t" in state
        wait_feed(decision_seen, 30,
                  lambda: "autoscaler decision after the step "
                  f"({plane.log_tails()[-900:]})", 180.0)

        def train_shrunk():
            g = kubectl.podgroups.get("default/train")
            if g is None or eapi.current_slices(g) != 1:
                return False
            if "t_free" not in state:
                state["t_free"] = _time.monotonic()
            return True
        wait_feed(train_shrunk, 60,
                  lambda: "victim shrink to free the burst chips "
                  f"({plane.log_tails()[-900:]})", 180.0)

        def serving_at_2():
            g = kubectl.podgroups.get("default/infer")
            return (g is not None and eapi.current_slices(g) == 2
                    and running("infer", 8))
        wait_feed(serving_at_2, 60,
                  lambda: "serving gang running at 2 replicas "
                  f"({plane.log_tails()[-900:]})", 180.0)
        t_serving = _time.monotonic()

        pg = kubectl.podgroups["default/infer"]
        tpg = kubectl.podgroups["default/train"]
        pool = sapi.pool_slices(pg)
        train_slices = _job_slices_now(kubectl, "default/train")
        hist = eapi.resize_history(tpg)
        shrink_rec = [r for r in hist if r.get("kind") == "shrink"]
        return {
            "scale_up_ok": True,
            "preempt_ok": bool(shrink_rec)
            and all(int(r.get("to", 0)) >= 1 for r in hist),
            "env_ok": env_ok,
            "no_premature_decision": no_premature,
            "victim_marker_seen": flags["victim_marker"],
            "victim_avoid_slices": flags["victim_avoid"],
            "qps_low": round(qps_low, 1),
            "qps_high": round(sapi.ann_float(
                pg.annotations, sapi.PG_QPS_ANNOTATION), 1),
            "decision": state.get("decision", ""),
            "step_to_decision_s": round(state["t"] - t_step, 3),
            "decision_to_chips_free_s": round(
                state["t_free"] - state["t"], 3),
            "decision_to_serving_s": round(t_serving - state["t"], 3),
            "replicas_final": eapi.current_slices(pg),
            "pool_slices": pool,
            "train_slices_final": train_slices,
            "pool_disjoint_from_victim": not (
                set(pool) & set(train_slices)),
            "hosts": 12,
        }
    finally:
        if kubectl is not None:
            kubectl.close()
        plane.shutdown()


def serve_smoke() -> int:
    """Seconds-scale serving drill for tier-1: one scale-up on a
    traffic step + one topology-aware burst preemption through the
    real process control plane, mirroring --elastic-smoke /
    --goodput-smoke.  Prints one JSON line."""
    try:
        out = bench_serving_wire_smoke()
        ok = (out["scale_up_ok"] and out["preempt_ok"]
              and out["env_ok"] and out["no_premature_decision"]
              and out["victim_marker_seen"]
              and out["pool_disjoint_from_victim"]
              and out["replicas_final"] == 2)
    except AssertionError as e:
        out, ok = {"error": str(e)[-900:]}, False
    print(json.dumps({"metric": "serve_smoke", "ok": ok, **out}))
    return 0 if ok else 1


def bench_serving() -> dict:
    """One compressed diurnal day against the REAL process plane:
    REAL batched-forward serving replicas (workloads/serve.py
    subprocesses) behind a bench-side load balancer, the SLO-driven
    autoscaler riding the folded QPS/p99, topology-aware burst
    preemption funding the scale-ups out of the nearest training
    gang, and the elastic reabsorption on the descent — committed as
    SERVE_r{N}.json with the serving/training Pareto row."""
    import os
    import subprocess
    import sys as _sys
    import time as _time

    from volcano_tpu.agent.agent import FakeUsageProvider, NodeAgent
    from volcano_tpu.agent.collect import (GoodputCollector,
                                           ServingCollector)
    from volcano_tpu.agent.handlers import (GoodputHandler,
                                            ServingHandler)
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api import goodput as gapi
    from volcano_tpu.api import serving as sapi
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.api.slicehealth import (
        FAILOVER_GENERATION_ANNOTATION, LAST_STEP_ANNOTATION,
        RESUME_STEP_ANNOTATION)
    from volcano_tpu.api.types import JobPhase, TaskStatus
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes
    from volcano_tpu.workloads.progress import ProgressReporter
    from volcano_tpu.workloads.serve import (DiurnalTraffic,
                                             WeightedLoadBalancer)

    DAY_S = 45.0
    BASE_QPS, PEAK_QPS = 400.0, 3000.0
    TARGET_QPS, SLO_MS = 800.0, 50.0
    CANARY_QPS = 150.0      # flat offered load on the second group
    FLOOR_STEP = 500
    BEAT_S = 0.25

    plane = _WirePlane()
    conf_path = os.path.join(plane.logdir, "serve-conf.yaml")
    with open(conf_path, "w") as f:
        json.dump(SERVE_CONF, f)
    kubectl = None
    agents = {}
    workers = {}        # serving pod uid -> (Popen, logf)
    try:
        plane.spawn("server", "-m", "volcano_tpu.server",
                    "--port", str(plane.port), "--tick-period", "0.05")
        import urllib.request

        def up():
            try:
                with urllib.request.urlopen(plane.url + "/healthz",
                                            timeout=1):
                    return True
            except OSError:
                return False
        _wire_wait(up, 20, "state server /healthz")
        plane.spawn("controllers", "-m", "volcano_tpu",
                    "--cluster-url", plane.url,
                    "--components", "controllers", "--period", "0.05")
        plane.spawn("scheduler", "-m", "volcano_tpu",
                    "--cluster-url", plane.url,
                    "--components", "scheduler", "--period", "0.05",
                    "--conf", conf_path)
        kubectl = RemoteCluster(plane.url)
        for sname, dcn in (("sa", "dcn-0"), ("sb", "dcn-0"),
                           ("sc", "dcn-0"), ("sd", "dcn-1"),
                           ("se", "dcn-1"), ("sf", "dcn-1")):
            for node in slice_nodes(slice_for(sname, "v5e-16"),
                                    dcn_pod=dcn):
                kubectl.add_node(node)

        stats_dir = os.path.join(plane.logdir, "serving")
        progress_dir = os.path.join(plane.logdir, "progress")
        traffic_dir = os.path.join(plane.logdir, "traffic")
        for d in (stats_dir, progress_dir, traffic_dir):
            os.makedirs(d, exist_ok=True)

        kubectl.add_vcjob(_serving_vcjob(
            "infer", 1, 1, 3, 4, stats_dir, slo_ms=SLO_MS,
            target_qps=TARGET_QPS))
        # the contending group: a fixed-size (lo == hi == 1) canary
        # replica group behind the SAME front-end LB — multi-group
        # serving contention: it shares the fleet and the balancer
        # with `infer` but its traffic must never bleed across, and
        # the burst preemption funding infer's scale-up must come out
        # of the training gangs, not the other serving group
        kubectl.add_vcjob(_serving_vcjob(
            "canary", 1, 1, 1, 4, stats_dir, slo_ms=SLO_MS,
            target_qps=TARGET_QPS))
        for tname in ("ta", "tb"):
            tj = _elastic_vcjob(tname, 2, 1, 3, 4)
            tj.annotations[LAST_STEP_ANNOTATION] = str(FLOOR_STEP)
            tj.annotations[gapi.PROGRESS_DIR_ANNOTATION] = progress_dir
            kubectl.add_vcjob(tj)

        def running(jname, want):
            j = kubectl.vcjobs.get(f"default/{jname}")
            if j is None or j.phase is not JobPhase.RUNNING:
                return False
            return sum(1 for p in kubectl.pods.values()
                       if p.owner == j.uid and p.node_name
                       and p.phase is TaskStatus.RUNNING) >= want
        # both serving groups up + training absorbed every idle slice
        _wire_wait(lambda: running("infer", 4) and running("canary", 4)
                   and _chip_utilization(kubectl) >= 0.99, 90,
                   lambda: "serve bench gangs never filled the fleet "
                   f"({plane.log_tails()[-900:]})")

        scol = ServingCollector(stats_dir)
        gcol = GoodputCollector(progress_dir)
        for node in kubectl.nodes:
            agents[node] = NodeAgent(
                kubectl, node, FakeUsageProvider(),
                handlers=[GoodputHandler, ServingHandler],
                goodput_collector=gcol, serving_collector=scol)

        traffic = DiurnalTraffic(base_qps=BASE_QPS,
                                 peak_qps=PEAK_QPS, day_s=DAY_S,
                                 seed=7)
        fed = {g: {"step": FLOOR_STEP, "epoch": 0, "max_resume": 0}
               for g in ("ta", "tb")}
        floor_violations = 0
        step_regressions = 0

        def serving_pods(jname):
            sj = kubectl.vcjobs.get(f"default/{jname}")
            if sj is None:
                return []
            return [p for p in kubectl.pods.values()
                    if p.owner == sj.uid and p.node_name
                    and p.phase is TaskStatus.RUNNING]

        lb = WeightedLoadBalancer()

        def lb_beat(t_rel):
            """The front-end driver: evaluate the diurnal curve and
            route BOTH groups' offered load across their RUNNING
            replicas weighted by each replica's OBSERVED p99 (read
            back from the stats file it publishes — the same feedback
            the autoscaler folds), reconciling one REAL serve.py
            subprocess per replica (env straight off the pod's
            injected container env — the jax-plugin contract)."""
            total = traffic.qps_at(t_rel)
            by_group = {"infer": serving_pods("infer"),
                        "canary": serving_pods("canary")}
            pods = [p for ps in by_group.values() for p in ps]
            live = {p.uid for p in pods}
            for uid in [u for u in workers if u not in live]:
                proc, logf = workers.pop(uid)
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except Exception:  # noqa: BLE001
                    proc.kill()
                logf.close()
                lb.forget(uid)
            for p in pods:
                try:
                    with open(sapi.stats_file_for(stats_dir, p.uid),
                              encoding="utf-8") as f:
                        lb.observe(p.uid,
                                   float(json.load(f).get("p99_ms", 0)))
                except (OSError, ValueError, TypeError):
                    pass     # cold replica: priced at the group mean
            shares = lb.route(
                {"infer": total, "canary": CANARY_QPS},
                {g: [p.uid for p in ps]
                 for g, ps in by_group.items()})
            for p in pods:
                tf = os.path.join(traffic_dir, f"lb-{p.uid}.json")
                tmp = tf + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump({"qps": shares.get(p.uid, 0.0)}, f)
                os.replace(tmp, tf)
                if p.uid not in workers:
                    env = dict(os.environ, PYTHONPATH=plane.repo,
                               JAX_PLATFORMS="cpu")
                    env.pop("XLA_FLAGS", None)
                    env.update(p.containers[0].env)
                    env.update(SERVE_DURATION_S="600",
                               SERVE_BEAT_S="0.2",
                               SERVE_SLO_MS=str(SLO_MS),
                               SERVE_TRAFFIC_FILE=tf,
                               SERVE_MODE="synthetic")
                    logf = open(os.path.join(
                        plane.logdir, f"serve-{p.uid[:8]}.log"), "w")
                    workers[p.uid] = (subprocess.Popen(
                        [_sys.executable, "-m",
                         "volcano_tpu.workloads.serve"],
                        env=env, stdout=logf, stderr=logf,
                        cwd=plane.repo), logf)
            infer_shares = [shares[p.uid] for p in by_group["infer"]]
            skew = (max(infer_shares) / max(min(infer_shares), 1e-9)) \
                if len(infer_shares) > 1 else 1.0
            return total, len(by_group["infer"]), skew

        def feed_training():
            """Epoch-aware training progress (the chaos-conductor
            contract): a resize drain resumes from the stamped floor,
            never below it, and the fed step never rewinds."""
            nonlocal floor_violations, step_regressions
            for g in ("ta", "tb"):
                pg = kubectl.podgroups.get(f"default/{g}")
                tj = kubectl.vcjobs.get(f"default/{g}")
                if pg is None or tj is None:
                    continue

                def _i(key):
                    try:
                        return int(pg.annotations.get(key, 0) or 0)
                    except (TypeError, ValueError):
                        return 0
                epoch = _i(FAILOVER_GENERATION_ANNOTATION) + \
                    _i(eapi.ELASTIC_GENERATION_ANNOTATION)
                st = fed[g]
                if epoch != st["epoch"]:
                    st["epoch"] = epoch
                    resume = _i(RESUME_STEP_ANNOTATION)
                    if resume and resume < FLOOR_STEP:
                        floor_violations += 1
                    if resume and resume < st["max_resume"]:
                        step_regressions += 1
                    st["max_resume"] = max(st["max_resume"], resume)
                    st["step"] = max(FLOOR_STEP, resume, st["step"])
                st["step"] += 1
                for p in kubectl.pods.values():
                    if p.owner == tj.uid and p.node_name and \
                            p.phase is TaskStatus.RUNNING:
                        ProgressReporter(
                            gapi.progress_file_for(progress_dir,
                                                   p.uid),
                            epoch=epoch).report(
                                step=st["step"],
                                examples=st["step"] * 8.0)

        timeline = []
        decisions = []
        episodes = []           # completed scale-up episodes
        pending_up = None
        victims = {}      # (gang, freed slices) -> adjacency audit
        # multi-group guard: the burst preemption funding infer must
        # never take the OTHER serving group as its victim
        canary_victimized = False
        decision_snap = None      # holdings + pool at decision time
        t0 = _time.monotonic()
        horizon = DAY_S + 30.0      # one day + the descent tail
        while _time.monotonic() - t0 < horizon:
            t_rel = _time.monotonic() - t0
            total, nrep, lb_skew = lb_beat(min(t_rel, DAY_S + 29.0))
            feed_training()
            for a in agents.values():
                try:
                    a.sync()
                except Exception:  # noqa: BLE001 — resize churn
                    pass
            pg = kubectl.podgroups.get("default/infer")
            if pg is None:
                _time.sleep(BEAT_S)
                continue
            cpg = kubectl.podgroups.get("default/canary")
            if cpg is not None and \
                    cpg.annotations.get(sapi.VICTIM_ANNOTATION):
                canary_victimized = True
            cur = eapi.current_slices(pg)
            ta_s = _job_slices_now(kubectl, "default/ta")
            tb_s = _job_slices_now(kubectl, "default/tb")
            timeline.append({
                "t": round(t_rel, 2), "qps_offered": round(total, 1),
                "replicas": cur, "replicas_running": nrep,
                "lb_skew": round(lb_skew, 3),
                "ta_slices": len(ta_s), "tb_slices": len(tb_s),
                "qps_folded": round(sapi.ann_float(
                    pg.annotations, sapi.PG_QPS_ANNOTATION), 1),
                "p99_folded_ms": round(sapi.ann_float(
                    pg.annotations, sapi.PG_P99_MS_ANNOTATION), 2),
            })
            d = pg.annotations.get(sapi.PG_LAST_DECISION_ANNOTATION)
            if d and (not decisions or decisions[-1]["text"] != d):
                decisions.append({"t": round(t_rel, 2), "text": d})
                if d.startswith("scale-up"):
                    pending_up = {"t": _time.monotonic(),
                                  "text": d,
                                  "ta": len(ta_s), "tb": len(tb_s),
                                  "t_free": None}
                    # decision-time snapshot: the candidate holdings
                    # and pool the scheduler's victim ranking will
                    # see — the audit must score THESE, not whatever
                    # placements exist after the post-episode churn
                    decision_snap = {
                        "ta": ta_s, "tb": tb_s,
                        "pool": sapi.pool_slices(pg)}
            if pending_up is not None:
                if pending_up["t_free"] is None and (
                        len(ta_s) < pending_up["ta"]
                        or len(tb_s) < pending_up["tb"]):
                    pending_up["t_free"] = _time.monotonic()
                want = int(pending_up["text"].split("->")[1]
                           .split(" ")[0].rstrip(")"))
                if cur == want and running("infer", want * 4):
                    now = _time.monotonic()
                    episodes.append({
                        "decision": pending_up["text"],
                        "decision_to_chips_free_s": round(
                            pending_up["t_free"] - pending_up["t"], 3)
                        if pending_up["t_free"] else None,
                        "decision_to_serving_s": round(
                            now - pending_up["t"], 3),
                    })
                    pending_up = None
            # the victim audit: catch the marker mid-episode and
            # score the FREED block (the stamped avoid-slices — the
            # victim's own placements are already draining) against
            # the pool, vs the slices the OTHER candidate holds, from
            # the same hypernode objects the scheduler used.  The
            # assertion: the eviction freed a block at least as close
            # to the serving pool as anything the alternative victim
            # could have offered.
            snap = decision_snap or {"ta": ta_s, "tb": tb_s,
                                     "pool": sapi.pool_slices(pg)}
            pool = snap["pool"] or sapi.pool_slices(pg)
            for g in ("ta", "tb"):
                tpg = kubectl.podgroups.get(f"default/{g}")
                if tpg is None or not pool:
                    continue
                freed = list(eapi.avoid_slices(tpg))
                if not tpg.annotations.get(sapi.VICTIM_ANNOTATION) \
                        or not freed:
                    continue
                episode_key = (g, tuple(freed))
                if episode_key in victims:
                    continue
                other = "tb" if g == "ta" else "ta"
                ft = _serve_pool_tiers(kubectl, pool, freed)
                ot = _serve_pool_tiers(kubectl, pool, snap[other])
                victims[episode_key] = {
                    "victim": g, "t": round(t_rel, 2),
                    "freed_slices": freed,
                    "freed_pool_tier": ft,
                    "other": other, "other_pool_tier": ot,
                    "ici_adjacent_ok": ft <= ot,
                    "pool": pool,
                }
            _time.sleep(max(0.0, BEAT_S - 0.05))

        pg = kubectl.podgroups["default/infer"]
        reqs = sapi.ann_float(pg.annotations,
                              sapi.PG_REQUESTS_ANNOTATION)
        ok_n = sapi.ann_float(pg.annotations,
                              sapi.PG_SLO_OK_ANNOTATION)
        attainment = (ok_n / reqs) if reqs > 0 else 0.0
        cpg = kubectl.podgroups.get("default/canary")
        c_reqs = sapi.ann_float(cpg.annotations,
                                sapi.PG_REQUESTS_ANNOTATION) \
            if cpg is not None else 0.0
        c_ok = sapi.ann_float(cpg.annotations,
                              sapi.PG_SLO_OK_ANNOTATION) \
            if cpg is not None else 0.0
        max_rep = max(r["replicas"] for r in timeline)
        min_rep_after_peak = min(
            r["replicas"] for r in timeline
            if r["t"] > DAY_S)
        train_rows = {}
        floors_held = True
        for g in ("ta", "tb"):
            tpg = kubectl.podgroups.get(f"default/{g}")
            hist = eapi.resize_history(tpg) if tpg is not None else []
            if any(int(r.get("to", 9)) < 1 for r in hist):
                floors_held = False
            avg_slices = sum(
                r[f"{g}_slices"] for r in timeline) / len(timeline)
            train_rows[g] = {
                "goodput": gapi.ann_float(
                    tpg.annotations, gapi.PG_GOODPUT_ANNOTATION)
                if tpg is not None else 0.0,
                "final_step": int(gapi.ann_float(
                    tpg.annotations, gapi.PG_STEP_ANNOTATION))
                if tpg is not None else 0,
                "avg_slices": round(avg_slices, 2),
                "resize_history": hist,
            }
        return {
            "hosts": 24,
            "day_s": DAY_S,
            "slo_ms": SLO_MS,
            "target_qps_per_replica": TARGET_QPS,
            "requests_served": int(reqs),
            "slo_attainment": round(attainment, 4),
            "slo_attainment_ok": attainment >= 0.99,
            "replicas_max": max_rep,
            "replicas_after_descent": min_rep_after_peak,
            "scaled_down_after_peak": min_rep_after_peak < max_rep,
            "decisions": decisions,
            "burst_preemption_episodes": episodes,
            "victim_audit": sorted(victims.values(),
                                   key=lambda v: v["t"]),
            "victim_ici_adjacent_all": bool(victims) and all(
                v["ici_adjacent_ok"] for v in victims.values()),
            "training_floors_held": floors_held
            and floor_violations == 0,
            "training_step_regressions": step_regressions,
            "lb": {
                "policy": "p99-weighted",
                "skew_max": round(max(
                    r["lb_skew"] for r in timeline), 3),
                "replica_p99_ewma_ms": {
                    u[:8]: round(v, 2)
                    for u, v in lb.latencies().items()},
            },
            "contention": {
                "canary_qps_offered": CANARY_QPS,
                "canary_requests": int(c_reqs),
                "canary_slo_attainment": round(
                    (c_ok / c_reqs) if c_reqs > 0 else 0.0, 4),
                "canary_slices_final": eapi.current_slices(cpg)
                if cpg is not None else 0,
                "canary_never_victimized": not canary_victimized,
            },
            "pareto": {
                "serving_slo_attainment": round(attainment, 4),
                "serving_replicas_avg": round(sum(
                    r["replicas"] for r in timeline)
                    / len(timeline), 2),
                "training": train_rows,
            },
            "timeline_tail": timeline[-8:],
        }
    finally:
        for proc, logf in workers.values():
            proc.terminate()
        for proc, logf in workers.values():
            try:
                proc.wait(timeout=5)
            except Exception:  # noqa: BLE001
                proc.kill()
            logf.close()
        if kubectl is not None:
            kubectl.close()
        plane.shutdown()


# -- federation: multi-region fleet behind one global queue ------------


class _FederationFleet:
    """N regional control planes (each a full _WirePlane: server +
    controllers + elastic scheduler as OS processes) plus one GLOBAL
    state server holding the job queue + region registry, with the
    FederationRouter reconciling over the real wire (RemoteCluster
    writes, RegionMirror tailing /wal?mirror=1)."""

    def __init__(self, regions, ttl=3.0, arbitrage_after=4.0,
                 poll_s=0.3, sync_s=0.25, router_procs=0,
                 lease_ttl=2.0):
        import os
        import threading

        from volcano_tpu.api import federation as fedapi
        from volcano_tpu.api.devices.tpu.topology import slice_for
        from volcano_tpu.cache.remote_cluster import RemoteCluster
        from volcano_tpu.federation.mirror import RegionMirror
        from volcano_tpu.federation.router import FederationRouter
        from volcano_tpu.simulator import slice_nodes

        self.gplane = _WirePlane()
        conf_path = os.path.join(self.gplane.logdir, "elastic.yaml")
        with open(conf_path, "w") as f:
            json.dump(ELASTIC_CONF, f)     # JSON is valid YAML
        # the global store runs NO scheduler and NO controllers —
        # it is a queue + registry, not a control plane
        self.gplane.spawn("server", "-m", "volcano_tpu.server",
                          "--port", str(self.gplane.port),
                          "--tick-period", "0.05", "--data-dir",
                          os.path.join(self.gplane.logdir, "state"))
        _wire_wait(lambda: _healthz(self.gplane.url), 20,
                   "global state server /healthz")
        self.g = RemoteCluster(self.gplane.url)
        self.planes = {}
        self.clients = {}
        self.hosts = 0
        # router_procs > 0 = the HA replica-set topology: N router OS
        # processes contending for the term-fenced lease in the global
        # store (each with its own clients + mirrors, regions attached
        # lazily off the registry).  0 = the embedded single router.
        self._router_procs = router_procs
        self._ttl, self._sync_s = ttl, sync_s
        self._arbitrage_after, self._poll_s = arbitrage_after, poll_s
        self._lease_ttl = lease_ttl
        self.router_holders = []
        self._routers_spawned = 0
        self.router = None if router_procs else FederationRouter(
            self.g, ttl=ttl, arbitrage_after=arbitrage_after,
            start_mirrors=False)
        for name, n_slices, price in regions:
            p = _WirePlane()
            # --data-dir makes the region durable: the mirror lane
            # (/replica_snapshot + /wal?mirror=1) only ships a WAL
            p.spawn("server", "-m", "volcano_tpu.server",
                    "--port", str(p.port), "--tick-period", "0.05",
                    "--data-dir", os.path.join(p.logdir, "state"))
            _wire_wait(lambda: _healthz(p.url), 20,
                       f"region {name} server /healthz")
            p.spawn("controllers", "-m", "volcano_tpu",
                    "--cluster-url", p.url,
                    "--components", "controllers", "--period", "0.05")
            p.spawn("scheduler", "-m", "volcano_tpu",
                    "--cluster-url", p.url,
                    "--components", "scheduler", "--period", "0.05",
                    "--conf", conf_path)
            client = RemoteCluster(p.url, tolerate_unreachable=True)
            for i in range(n_slices):
                for node in slice_nodes(
                        slice_for(f"{name}-s{i}", "v5e-16"),
                        dcn_pod=f"{name}-dcn"):
                    client.add_node(node)
                    self.hosts += 1
            if router_procs:
                # router processes build their own clients + mirrors
                # off this registry record (lazy attach)
                self.g.put_object(
                    "region",
                    fedapi.region_record(name, p.url, price=price),
                    key=name)
            else:
                mirror = RegionMirror(name, p.url)
                mirror.start(poll_s=poll_s)
                self.router.attach_region(
                    fedapi.region_record(name, p.url, price=price),
                    client=client, mirror=mirror)
            self.planes[name] = p
            self.clients[name] = client
        self._stop = threading.Event()
        self.paused = threading.Event()
        self.sync_errors = []
        self._thread = None
        if router_procs:
            for _ in range(router_procs):
                self.spawn_router()
            return
        # the router loop runs on its own thread (exactly what
        # `python -m volcano_tpu.federation.router` does), pausable so
        # scenarios can stage multi-job races into ONE sync pass

        def _route():
            while not self._stop.wait(sync_s):
                if self.paused.is_set():
                    continue
                try:
                    self.router.sync()
                except Exception as e:  # noqa: BLE001 — keep going
                    self.sync_errors.append(repr(e)[-200:])
        self._thread = threading.Thread(target=_route, daemon=True,
                                        name="fed-router")
        self._thread.start()

    # -- HA router replica set (router_procs mode) ---------------------

    def spawn_router(self, holder=""):
        """One more contender for the router lease — a real
        `python -m volcano_tpu.federation.router` OS process."""
        self._routers_spawned += 1
        holder = holder or f"rt{self._routers_spawned}"
        self.gplane.spawn(
            f"router-{holder}", "-m", "volcano_tpu.federation.router",
            "--store", self.gplane.url, "--holder", holder,
            "--sync-s", str(self._sync_s),
            "--ttl-s", str(self._ttl),
            "--arbitrage-s", str(self._arbitrage_after),
            "--lease-ttl-s", str(self._lease_ttl),
            "--mirror-poll-s", str(self._poll_s))
        self.router_holders.append(holder)
        return holder

    def leaseholder(self):
        """The holder of the router lease right now (None while the
        lease is vacant/expired), straight off the global store."""
        from volcano_tpu.api import federation as fedapi
        try:
            rec = self.g.leases().get(fedapi.ROUTER_LEASE_NAME)
        except OSError:
            return None
        if not rec or float(rec.get("expires_in", 0)) <= 0:
            return None
        return rec.get("holder")

    def router_term(self):
        from volcano_tpu.api import federation as fedapi
        try:
            rec = self.g.leases().get(fedapi.ROUTER_LEASE_NAME) or {}
        except OSError:
            return 0
        return int(rec.get("term", 0) or 0)

    def _router_proc(self, holder):
        return self.gplane.procs.get(f"router-{holder}")

    def kill_router(self, holder):
        """SIGKILL one router process — the crash the lease + fence
        machinery must absorb."""
        import signal as _signal
        proc = self._router_proc(holder)
        if proc is not None and proc.poll() is None:
            proc.send_signal(_signal.SIGKILL)
            proc.wait(timeout=10)

    def sigstop_router(self, holder):
        """SIGSTOP = the router<->fleet partition / GC-pause model:
        the process is alive but can neither renew its lease nor see
        that it lost it."""
        import signal as _signal
        proc = self._router_proc(holder)
        if proc is not None and proc.poll() is None:
            proc.send_signal(_signal.SIGSTOP)

    def sigcont_router(self, holder):
        import signal as _signal
        proc = self._router_proc(holder)
        if proc is not None and proc.poll() is None:
            proc.send_signal(_signal.SIGCONT)

    def kill_region(self, name):
        """SIGKILL every process of one regional plane — whole-region
        loss, the blast radius the global queue must absorb."""
        import signal as _signal
        plane = self.planes[name]
        for proc in plane.procs.values():
            if proc.poll() is None:
                proc.send_signal(_signal.SIGKILL)
        for proc in plane.procs.values():
            try:
                proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                proc.kill()

    def set_region_state(self, name, state):
        """Registry write, exactly what `vtpctl federate --drain`
        issues."""
        rec = dict(self.g.regions[name])
        rec["state"] = state
        self.g.put_object("region", rec, key=name)

    def log_tails(self, n=900):
        out = [self.gplane.log_tails(n)]
        out += [p.log_tails(n) for p in self.planes.values()]
        return "\n".join(out)[-4 * n:]

    def shutdown(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self.router is not None:
            self.router.close()
        for holder in self.router_holders:
            # SIGCONT first: a SIGSTOP'd router ignores SIGTERM
            self.sigcont_router(holder)
        for client in self.clients.values():
            client.close()
        self.g.close()
        for plane in self.planes.values():
            plane.shutdown()
        self.gplane.shutdown()


def _fed_job(name, slices=1, locality=""):
    """A global elastic gang: ordinary vcjob + locality preference —
    the submitter's whole contract with the federation tier."""
    from volcano_tpu.api import federation as fedapi
    job = _elastic_vcjob(name, slices, 1, slices, 4)
    if locality:
        job.annotations[fedapi.FED_DATA_LOCALITY_ANNOTATION] = locality
    return job


def _fed_view(g, jname):
    """(admitted region, folded regional phase) off the GLOBAL record
    alone — what `vtpctl federate` renders."""
    from volcano_tpu.api import federation as fedapi
    j = g.vcjobs.get(f"default/{jname}")
    if j is None:
        return None, None
    return (fedapi.admitted_region(j),
            j.annotations.get(fedapi.FED_REGIONAL_PHASE_ANNOTATION))


def _fed_running(g, jname, region=None):
    adm, phase = _fed_view(g, jname)
    return (adm is not None and phase == "Running"
            and (region is None or adm == region))


def _fed_stamp_steps(client, jname, step):
    """What the regional progress fold does in production: acked
    checkpoint metadata lands on the regional job's annotations."""
    from volcano_tpu.api.slicehealth import (
        CHECKPOINT_DIR_ANNOTATION, LAST_STEP_ANNOTATION,
        RESUME_STEP_ANNOTATION)
    j = client.vcjobs.get(f"default/{jname}")
    if j is None:
        return False
    j.annotations[LAST_STEP_ANNOTATION] = str(step)
    j.annotations[RESUME_STEP_ANNOTATION] = str(step)
    j.annotations[CHECKPOINT_DIR_ANNOTATION] = f"gs://ckpt/{jname}"
    client.update_vcjob(j)
    return True


def _fed_stamp_and_fold(fleet, region, jname, step, timeout=30):
    """Stamp acked steps on the regional copy and wait until the
    router folds them onto the GLOBAL record, re-stamping on retry (a
    concurrent controller status flush can clobber one write)."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        assert _fed_stamp_steps(fleet.clients[region], jname, step), \
            f"no regional copy of {jname} in {region} to stamp"
        inner = time.monotonic() + 3
        while time.monotonic() < inner:
            if _fed_folded_step(fleet.g, jname) == step:
                return
            time.sleep(0.05)
    raise AssertionError(
        f"acked step {step} of {jname} never folded globally "
        f"({fleet.log_tails()})")


def _fed_finish(fleet, region, jname, timeout=30):
    """Retire a gang (the submitter cancels it): the global record
    plus the regional copy with its podgroup and pods — the chips
    return to the region's idle pool.  Deletes retry until the
    objects STAY gone: a deleted RUNNING job has no finished-TTL (no
    gc cascade), and a concurrent controller status flush is an
    upsert that can resurrect a just-deleted record."""
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION
    key = f"default/{jname}"
    client = fleet.clients[region]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        gone = True
        try:
            for pod in list(client.pods.values()):
                if pod.annotations.get(
                        GROUP_NAME_ANNOTATION) == jname:
                    client.delete_pod(pod.key)
                    gone = False
            for cl in (client, fleet.g):
                if cl.vcjobs.get(key) is not None:
                    cl.delete_vcjob(key)
                    gone = False
            if client.podgroups.get(key) is not None:
                client.delete_podgroup(key)
                gone = False
        except OSError:
            gone = False            # transient wire hiccup: retry
        if gone:
            return
        time.sleep(0.2)
    raise AssertionError(f"gang {jname} would not stay deleted in "
                         f"{region} ({fleet.log_tails()})")


def _fed_folded_step(g, jname):
    from volcano_tpu.api.slicehealth import LAST_STEP_ANNOTATION
    j = g.vcjobs.get(f"default/{jname}")
    try:
        return int(j.annotations.get(LAST_STEP_ANNOTATION, 0) or 0)
    except (AttributeError, TypeError, ValueError):
        return 0


def bench_federation() -> dict:
    """The three federation headlines against a REAL 3-region process
    fleet (each region: state server + controllers + elastic
    scheduler; one global store; the router reconciling over the
    wire):

      placement      one global queue, goodput/locality/price-routed
                     admission — every gang placed while the silo
                     baseline (jobs pinned to their home region)
                     strands most of them in one queue;
      follow-the-sun `vtpctl federate --drain`-style region drain: the
                     RUNNING gangs checkpoint-drain (elastic evacuate),
                     park under the evacuated hold, and cut over to
                     another region carrying their resume step;
      region loss    SIGKILL a whole regional plane: its gangs requeue
                     GLOBALLY with the folded checkpoint metadata —
                     zero acked state lost — and re-run elsewhere
                     (MTTR measured), plus burst arbitrage: a gang
                     stuck PENDING behind a full regional queue
                     migrates to idle capacity instead of waiting.

    Committed as FED_r{N}.json."""
    import time as _time

    from volcano_tpu import metrics as _metrics
    from volcano_tpu.api import federation as fedapi

    STAMP_DRAIN = 7000       # acked step before the drain
    STAMP_KILL = 9000        # acked step before the region kill
    fleet = _FederationFleet(
        (("ra", 2, 1.0), ("rb", 2, 0.7), ("rc", 2, 0.9)))
    g = fleet.g
    try:
        # -- phase A: spread admission + utilization vs silos ----------
        jobs = [f"j{i}" for i in range(1, 7)]
        admission_s = {}
        for jname in jobs:       # staggered: registry refresh between
            g.add_vcjob(_fed_job(jname, 1, locality="ra"))
            t0 = _time.monotonic()
            _wire_wait(lambda j=jname: _fed_running(g, j), 60,
                       lambda j=jname: f"federated admission of {j} "
                       f"({fleet.log_tails()})")
            admission_s[jname] = round(_time.monotonic() - t0, 3)
        placed = {j: _fed_view(g, j)[0] for j in jobs}
        by_region = {}
        for jname, region in placed.items():
            by_region.setdefault(region, []).append(jname)
        # silo baseline: every job pinned to its home (locality)
        # region, no cross-region queue — ra fits 2 of the 6
        silo_placed = min(len(jobs), 2)
        placement = {
            "jobs": len(jobs),
            "by_region": {r: sorted(v)
                          for r, v in sorted(by_region.items())},
            "placed_federated": len([r for r in placed.values() if r]),
            "placed_silo_homed": silo_placed,
            "placed_fraction_federated": 1.0,
            "placed_fraction_silo": round(silo_placed / len(jobs), 3),
            "admission_s": admission_s,
        }
        assert all(placed.values()), f"unplaced: {placed}"
        assert len(by_region) == 3, \
            f"no spread (one queue collapsed): {by_region}"

        # -- phase B: follow-the-sun drain out of ra -------------------
        # free rc first so the drain has somewhere to land: finish
        # (cancel) the two gangs it took
        for jname in list(by_region.get("rc", [])):
            _fed_finish(fleet, "rc", jname)
            jobs.remove(jname)
        ra_jobs = sorted(by_region.get("ra", []))
        for jname in ra_jobs:
            # two ascending stamps teach the router a steps/sec/chip
            # rate for ra (the learned-goodput input), then the final
            # stamp is the drain's resume floor
            _fed_stamp_and_fold(fleet, "ra", jname, STAMP_DRAIN - 500)
            _fed_stamp_and_fold(fleet, "ra", jname, STAMP_DRAIN)
        fleet.set_region_state("ra", fedapi.REGION_STATE_DRAINING)
        t_drain = _time.monotonic()
        _wire_wait(lambda: all(_fed_running(g, j) and
                               _fed_view(g, j)[0] != "ra"
                               for j in ra_jobs), 120,
                   lambda: "follow-the-sun migration out of ra "
                   f"({[_fed_view(g, j) for j in ra_jobs]}) "
                   f"({fleet.log_tails()})")
        sun_s = round(_time.monotonic() - t_drain, 3)
        from volcano_tpu.api.slicehealth import RESUME_STEP_ANNOTATION
        resume_ok = []
        for jname in ra_jobs:
            region = _fed_view(g, jname)[0]
            copy = fleet.clients[region].vcjobs[f"default/{jname}"]
            resume_ok.append(int(copy.annotations.get(
                RESUME_STEP_ANNOTATION, 0)) >= STAMP_DRAIN)
        cutovers = _metrics.get_observations(
            "federation_cutover_seconds")
        follow_the_sun = {
            "drained_region": "ra",
            "jobs_migrated": len(ra_jobs),
            "dest_regions": sorted({_fed_view(g, j)[0]
                                    for j in ra_jobs}),
            "drain_to_running_s": sun_s,
            "cutover_s": [round(c, 3) for c in cutovers],
            "resume_continuity_ok": all(resume_ok),
            "cutover_refusals": int(sum(
                _metrics.get_counter(
                    "federation_cutover_refusals_total", region=r)
                for r in ("ra", "rb", "rc"))),
        }

        # -- phase C: whole-region loss (SIGKILL rb's plane) -----------
        rb_jobs = sorted(by_region.get("rb", []))
        for jname in rb_jobs:
            _fed_stamp_and_fold(fleet, "rb", jname, STAMP_KILL - 500)
            _fed_stamp_and_fold(fleet, "rb", jname, STAMP_KILL)
        # ra drained empty above: reopen it as the failover target
        fleet.set_region_state("ra", fedapi.REGION_STATE_READY)
        fleet.kill_region("rb")
        t_kill = _time.monotonic()
        mttr = {}

        def _replaced(jname):
            if not _fed_running(g, jname):
                return False
            if _fed_view(g, jname)[0] == "rb":
                return False
            mttr.setdefault(jname,
                            round(_time.monotonic() - t_kill, 3))
            return True
        _wire_wait(lambda: all(_replaced(j) for j in rb_jobs), 120,
                   lambda: "global requeue out of the dead region "
                   f"({[_fed_view(g, j) for j in rb_jobs]}) "
                   f"({fleet.log_tails()})")
        lost_folds = [j for j in rb_jobs
                      if _fed_folded_step(g, j) != STAMP_KILL]
        region_loss = {
            "killed_region": "rb",
            "detected_lost": g.regions["rb"]["state"] == "lost",
            "jobs_requeued": len(rb_jobs),
            "mttr_s": mttr,
            "acked_steps_lost": len(lost_folds),
            "requeue_attempt_bumped": all(
                int(g.vcjobs[f"default/{j}"].annotations.get(
                    fedapi.FED_ATTEMPT_ANNOTATION, 0)) >= 1
                for j in rb_jobs),
        }

        # -- phase D: burst arbitrage ----------------------------------
        # leave exactly one idle slice (in the region hosting the
        # ex-ra gangs), then race TWO one-slice gangs into one router
        # pass: both admit there, one runs, one sits PENDING — and
        # must migrate as soon as a freed region scores better
        sun_dest = follow_the_sun["dest_regions"][0]
        victim = sorted(j for j in jobs
                        if _fed_view(g, j)[0] == sun_dest)[0]
        _fed_finish(fleet, sun_dest, victim)
        jobs.remove(victim)
        _wire_wait(lambda: float(g.regions[sun_dest].get(
            "idle_chips", 0)) >= 16.0, 30,
            f"freed slice visible in {sun_dest}'s registry record")
        fleet.paused.set()       # stage both into ONE admit pass
        g.add_vcjob(_fed_job("jx", 1))
        g.add_vcjob(_fed_job("jy", 1))
        _time.sleep(0.5)
        fleet.paused.clear()
        _wire_wait(lambda: all(_fed_view(g, j)[0] is not None
                               for j in ("jx", "jy")), 60,
                   lambda: "race pair admission "
                   f"({fleet.log_tails()})")
        _wire_wait(lambda: sum(1 for j in ("jx", "jy")
                               if _fed_running(g, j)) >= 1, 60,
                   "one of the race pair running")
        # free a slice in ANOTHER region: the pending gang must beat
        # its local queue by migrating, not by waiting
        other = sorted(j for j in jobs
                       if _fed_view(g, j)[0] not in (None, sun_dest))
        freed_from = _fed_view(g, other[0])[0]
        _fed_finish(fleet, freed_from, other[0])
        jobs.remove(other[0])
        t_arb = _time.monotonic()
        _wire_wait(lambda: all(_fed_running(g, j)
                               for j in ("jx", "jy")), 90,
                   lambda: "arbitrage migration of the pending gang "
                   f"({[_fed_view(g, j) for j in ('jx', 'jy')]}) "
                   f"({fleet.log_tails()})")
        arbitrage = {
            "race_pair_regions": {j: _fed_view(g, j)[0]
                                  for j in ("jx", "jy")},
            "pending_migrations": int(_metrics.get_counter(
                "federation_migrations_total", kind="pending")),
            "pending_to_running_s": round(
                _time.monotonic() - t_arb, 3),
        }

        util = {name: round(_chip_utilization(
            fleet.clients[name]), 4)
            for name in ("ra", "rc")}
        return {
            "hosts": fleet.hosts,
            "regions": {n: {"price": p, "slices": s}
                        for n, s, p in (("ra", 2, 1.0), ("rb", 2, 0.7),
                                        ("rc", 2, 0.9))},
            "placement": placement,
            "follow_the_sun": follow_the_sun,
            "region_loss": region_loss,
            "arbitrage": arbitrage,
            "surviving_region_utilization": util,
            "learned_goodput": {f"{r}/{gen}": round(v, 4)
                                for (r, gen), v in
                                fleet.router._goodput.items()},
            "router_sync_errors": fleet.sync_errors[-5:],
        }
    finally:
        fleet.shutdown()


def bench_federation_wire_smoke() -> dict:
    """Seconds-scale federation drill for tier-1: locality-routed
    admission across two REAL regional planes, then whole-region loss
    — the dead region's gang requeues globally, lands in the survivor
    and resumes from the folded step (zero acked state lost)."""
    import time as _time

    from volcano_tpu.api import federation as fedapi

    STAMP = 4200
    fleet = _FederationFleet(
        (("ra", 2, 1.0), ("rb", 1, 0.7)), ttl=2.0)
    g = fleet.g
    try:
        g.add_vcjob(_fed_job("anchor", 1, locality="ra"))
        g.add_vcjob(_fed_job("roamer", 1, locality="rb"))
        _wire_wait(lambda: _fed_running(g, "anchor", "ra")
                   and _fed_running(g, "roamer", "rb"), 60,
                   lambda: "locality-routed admission "
                   f"({_fed_view(g, 'anchor')} "
                   f"{_fed_view(g, 'roamer')}) ({fleet.log_tails()})")
        locality_ok = True
        _fed_stamp_and_fold(fleet, "rb", "roamer", STAMP)
        fleet.kill_region("rb")
        t_kill = _time.monotonic()
        _wire_wait(lambda: _fed_running(g, "roamer", "ra"), 90,
                   lambda: "requeue into the surviving region "
                   f"({_fed_view(g, 'roamer')}) ({fleet.log_tails()})")
        mttr = round(_time.monotonic() - t_kill, 3)
        from volcano_tpu.api.slicehealth import RESUME_STEP_ANNOTATION
        copy = fleet.clients["ra"].vcjobs["default/roamer"]
        gjob = g.vcjobs["default/roamer"]
        return {
            "regions": 2, "hosts": fleet.hosts,
            "locality_routed_ok": locality_ok,
            "region_detected_lost":
                g.regions["rb"]["state"] == "lost",
            "requeue_mttr_s": mttr,
            "folded_step_survived":
                _fed_folded_step(g, "roamer") == STAMP,
            "resume_step_in_survivor": int(copy.annotations.get(
                RESUME_STEP_ANNOTATION, 0)),
            "attempt": int(gjob.annotations.get(
                fedapi.FED_ATTEMPT_ANNOTATION, 0)),
            "migrated_from": gjob.annotations.get(
                fedapi.FED_MIGRATED_FROM_ANNOTATION, ""),
            "router_sync_errors": fleet.sync_errors[-3:],
        }
    finally:
        fleet.shutdown()


def federation_smoke() -> int:
    """Tier-1 federation drill, mirroring --elastic-smoke /
    --serve-smoke.  Prints one JSON line."""
    try:
        out = bench_federation_wire_smoke()
        ok = (out["locality_routed_ok"]
              and out["region_detected_lost"]
              and out["folded_step_survived"]
              and out["resume_step_in_survivor"] >= 4200
              and out["migrated_from"] == "rb"
              and not out["router_sync_errors"])
    except AssertionError as e:
        out, ok = {"error": str(e)[-900:]}, False
    print(json.dumps({"metric": "federation_smoke", "ok": ok, **out}))
    return 0 if ok else 1


# -- fleet-wide causal timeline: one episode ID end to end -------------


def _timeline_drill(regions, dwell_s=8.0, jname="tj") -> dict:
    """Follow-the-sun migration reconstructed from ONE episode ID:
    submit a gang with source locality, let it train through the sun
    window, drain the source region, wait for the cross-region
    cutover to land it Running elsewhere — then assert the
    leaseholder's stitched fleet trace tells the WHOLE story from a
    single `GET /fleet_trace?episode=`: every fragment a complete
    span (trace.is_complete_span), router decision + source drain +
    destination placement + resume all covered, >= 2 hops, and a
    stitched segment sum that reconciles with the measured
    submit->running wall within 5%."""
    import time as _time

    from volcano_tpu import trace as trace_mod
    from volcano_tpu.api import federation as fedapi
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION

    STAMP = 6000
    src = regions[0][0]
    fleet = _FederationFleet(regions, ttl=2.0, sync_s=0.2)
    g = fleet.g
    key = f"default/{jname}"
    try:
        t_submit = _time.time()
        g.add_vcjob(_fed_job(jname, 1, locality=src))
        _wire_wait(lambda: _fed_running(g, jname, src), 60,
                   lambda: f"admission of {jname} into {src} "
                   f"({_fed_view(g, jname)}) ({fleet.log_tails()})")
        episode = fedapi.episode_of(g.vcjobs[key]) or ""
        assert episode.startswith("ep-"), \
            f"no episode minted at admission: " \
            f"{g.vcjobs[key].annotations}"

        # the sun window: the gang trains in the source region,
        # stamping acked steps (the goodput input AND the resume
        # floor) — long enough that mint/fold lag is noise against
        # the 5% reconciliation budget
        t_end = _time.monotonic() + dwell_s
        step = STAMP
        while True:
            _fed_stamp_and_fold(fleet, src, jname, step)
            left = t_end - _time.monotonic()
            if left <= 0:
                break
            _time.sleep(min(1.0, left))
            step += 100

        fleet.set_region_state(src, fedapi.REGION_STATE_DRAINING)
        _wire_wait(lambda: _fed_running(g, jname)
                   and _fed_view(g, jname)[0] != src, 120,
                   lambda: f"follow-the-sun migration of {jname} out "
                   f"of {src} ({_fed_view(g, jname)}) "
                   f"({fleet.log_tails()})")
        dest = _fed_view(g, jname)[0]

        # ground truth for the reconciliation: the destination copy's
        # own `running` phase stamp (wall clock, written by the
        # destination controller the moment the gang ran) — NOT our
        # detection time, which trails it by a fold + a poll
        run_ts = []

        def _dest_running_stamp():
            c = fleet.clients[dest]
            stamps = []
            pg = c.podgroups.get(key)
            if pg is not None:
                ts = trace_mod.phase_ts(pg.annotations, "running")
                if ts is not None:
                    stamps.append(ts)
            for pod in list(c.pods.values()):
                if pod.annotations.get(
                        GROUP_NAME_ANNOTATION) != jname:
                    continue
                ts = trace_mod.phase_ts(pod.annotations, "running")
                if ts is not None:
                    stamps.append(ts)
            if not stamps:
                return False
            run_ts[:] = [min(stamps)]
            return True
        _wire_wait(_dest_running_stamp, 30,
                   lambda: f"running stamp on {dest}'s copy "
                   f"({fleet.log_tails()})")
        measured_s = run_ts[0] - t_submit
        assert measured_s > 0, (run_ts, t_submit)

        # ONE episode ID reconstructs the whole story: poll the wire
        # endpoint until the stitcher folded the final fragments (it
        # stitches once per leaseholder pass, so the stitched wall
        # GROWS toward the measured wall and then stops)
        state = {}

        def _coverage(doc):
            frags = list((doc.get("root") or {}).get("children", ()))
            names = [f.get("name", "") for f in frags]
            dest_lc = [f for f in frags
                       if f.get("name", "").startswith("lifecycle")
                       and (f.get("labels") or {}).get("plane")
                       == f"region-{dest}"]
            return {
                "router_decision": any(
                    n.startswith(("router-cutover", "router-requeue"))
                    for n in names),
                "source_drain": any(
                    n.startswith("elastic-evacuate-drain")
                    for n in names),
                "destination_placement": bool(dest_lc),
                "resume": any(
                    c.get("name") == "running"
                    for f in dest_lc
                    for c in f.get("children", ())),
            }

        def _stitched():
            try:
                doc = g._request(
                    "GET",
                    f"/fleet_trace?episode={episode}").get("trace")
            except OSError:
                return False
            if not isinstance(doc, dict):
                return False
            root = doc.get("root") or {}
            frags = list(root.get("children") or ())
            if not frags or not all(
                    trace_mod.is_complete_span(s)
                    for s in [root] + frags):
                return False
            wall = float(doc.get("wall_s") or 0.0)
            if not (all(_coverage(doc).values())
                    and len(doc.get("hops") or ()) >= 2
                    and abs(wall - measured_s)
                    <= 0.05 * measured_s):
                return False
            state["doc"] = doc
            return True
        _wire_wait(
            _stitched, 60,
            lambda: "stitched episode reconciliation (measured="
            f"{measured_s:.3f}s stitched="
            f"{(g.fleet_traces.get(episode) or {}).get('wall_s')} "
            f"coverage={_coverage(g.fleet_traces.get(episode) or {})}"
            f" hops="
            f"{(g.fleet_traces.get(episode) or {}).get('hops')})"
            f" ({fleet.log_tails()})")
        doc = state["doc"]
        wall = float(doc["wall_s"])
        skew_clamps = [
            {"fragment": f.get("name"),
             "clamp_s": float(f["labels"]["skew_clamp_s"])}
            for f in doc["root"]["children"]
            if (f.get("labels") or {}).get("skew_clamp_s")]
        return {
            "regions": len(regions), "hosts": fleet.hosts,
            "episode": episode,
            "source": src, "destination": dest,
            "measured_submit_to_running_s": round(measured_s, 3),
            "stitched_wall_s": round(wall, 3),
            "reconcile_pct": round(
                100.0 * abs(wall - measured_s) / measured_s, 2),
            "reconciled_within_5pct": True,
            "all_fragments_complete": True,
            "coverage": _coverage(doc),
            "planes": doc["planes"], "hops": doc["hops"],
            "fragments": len(doc["root"]["children"]),
            "segments": doc["segments"],
            "skew_clamps": skew_clamps,
            "resume_floor_step": step,
            "router_sync_errors": fleet.sync_errors[-5:],
        }
    finally:
        fleet.shutdown()


def bench_timeline() -> dict:
    """The TIMELINE_r{N}.json artifact: a 3-region fleet, one gang
    following the sun out of its home region, the whole causal story
    reconstructed from its single episode ID."""
    return _timeline_drill(
        (("ra", 1, 1.0), ("rb", 1, 0.7), ("rc", 1, 0.9)),
        dwell_s=15.0)


def timeline_smoke() -> int:
    """Tier-1 causal-timeline drill, mirroring --federation-smoke:
    2 regions, seconds-scale sun window.  Prints one JSON line."""
    try:
        out = _timeline_drill((("ra", 1, 1.0), ("rb", 1, 0.7)),
                              dwell_s=6.0)
        ok = (out["reconciled_within_5pct"]
              and out["all_fragments_complete"]
              and all(out["coverage"].values())
              and len(out["hops"]) >= 2
              and not out["router_sync_errors"])
    except AssertionError as e:
        out, ok = {"error": str(e)[-900:]}, False
    print(json.dumps({"metric": "timeline_smoke", "ok": ok, **out}))
    return 0 if ok else 1


# -- federation HA: leased router replica set --------------------------


def _fed_copy_regions(fleet, jname):
    """Regions currently holding a copy of the gang (each client's
    watch mirror — the exactly-once census)."""
    return sorted(r for r, c in fleet.clients.items()
                  if f"default/{jname}" in c.vcjobs)


def _fed_regions_ready(g, names):
    """Every named region is ready WITH capacity folded into the
    registry (a fresh mirror poll stamped it).  Submitting before
    this is a race: admission scores only the regions that have
    folded, the gang lands in whichever region's mirror won the
    boot race, and admission is sticky — a locality assertion then
    times out on a perfectly healthy fleet."""
    from volcano_tpu.api import federation as fedapi
    regs = getattr(g, "regions", None) or {}
    return all(
        (regs.get(n) or {}).get("state") == fedapi.REGION_STATE_READY
        and float((regs.get(n) or {}).get("capacity_chips", 0) or 0) > 0
        for n in names)


def _fed_dual_sampler(fleet, jobs, violations, stop):
    """Continuously assert the no-dual-placement invariant: a gang
    never has LIVE PLACED PODS in two regions at once, sampled
    through every region's watch mirror while routers crash and fail
    over.  The census is pods, not the vcjob phase field: a drained
    source husk awaiting the create-then-delete reap keeps its stale
    Running phase for a beat after its pods are gone — execution is
    what must never be doubled."""
    import threading

    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION

    def _live(c, jname):
        return any(
            p.annotations.get(GROUP_NAME_ANNOTATION) == jname
            and p.node_name and not p.is_terminated()
            for p in c.pods.values())

    def _sample():
        while not stop.wait(0.1):
            for jname in jobs:
                running = [region
                           for region, c in fleet.clients.items()
                           if _live(c, jname)]
                if len(running) > 1:
                    violations.append(
                        {"job": jname, "regions": running})
    th = threading.Thread(target=_sample, daemon=True,
                          name="fed-dual-sampler")
    th.start()
    return th


def bench_federation_ha() -> dict:
    """The router-HA headlines against a REAL fleet: 2 regional
    control planes, one global store, and a 2-process router replica
    set contending for the term-fenced lease.  Four episodes:

      kill_admission   SIGKILL the leaseholder right after a gang
                       enters the global queue — the standby promotes
                       (new term), fences the regions, adopts, and
                       the gang lands in EXACTLY one region
      kill_cutover     SIGKILL the leaseholder mid-migration (source
                       drained, evacuating-to stamped, cutover not
                       driven) — the promoted router resumes the
                       create-then-delete cutover idempotently with
                       the folded checkpoint step intact
      partition        SIGSTOP the leaseholder (the GC-pause / router
                       <->fleet partition model): the standby takes
                       over, and a write stamped with the deposed
                       term is REFUSED 409 by the regional plane
                       (counted on /fences)
      vacancy          kill every router: regions run autonomously,
                       the global queue accumulates (admission
                       delayed, never lost), and one fresh router
                       drains the backlog

    The no-dual-placement invariant is sampled at 10Hz through every
    region's live mirror for the whole run.  Committed as
    FEDHA_r{N}.json."""
    import threading
    import time as _time

    from volcano_tpu.api import federation as fedapi
    from volcano_tpu.api.slicehealth import RESUME_STEP_ANNOTATION

    STAMP = 7000
    fleet = _FederationFleet(
        (("ra", 2, 1.0), ("rb", 2, 0.7)), ttl=4.0,
        arbitrage_after=60.0, router_procs=2, lease_ttl=2.0)
    g = fleet.g
    dual, stop = [], threading.Event()
    sampler = _fed_dual_sampler(
        fleet, ("anchor", "j-adm", "roamer", "j-queue"), dual, stop)
    try:
        # -- baseline: a leaseholder emerges and routes by locality --
        _wire_wait(lambda: fleet.leaseholder() is not None, 30,
                   lambda: f"router lease acquisition "
                   f"({fleet.log_tails()})")
        h0, term0 = fleet.leaseholder(), fleet.router_term()
        _wire_wait(lambda: _fed_regions_ready(g, ("ra", "rb")), 30,
                   lambda: f"region capacity folded "
                   f"({dict(getattr(g, 'regions', {}))})")
        g.add_vcjob(_fed_job("anchor", 1, locality="ra"))
        _wire_wait(lambda: _fed_running(g, "anchor", "ra"), 60,
                   lambda: f"anchor admission "
                   f"({_fed_view(g, 'anchor')}) ({fleet.log_tails()})")

        # -- episode 1: SIGKILL the leaseholder mid-admission --------
        g.add_vcjob(_fed_job("j-adm", 1, locality="rb"))
        fleet.kill_router(h0)
        t_kill = _time.monotonic()
        _wire_wait(lambda: fleet.leaseholder() not in (None, h0), 30,
                   lambda: f"standby promotion after SIGKILL "
                   f"({fleet.log_tails()})")
        promo_adm = round(_time.monotonic() - t_kill, 3)
        term1 = fleet.router_term()
        _wire_wait(lambda: _fed_running(g, "j-adm", "rb"), 60,
                   lambda: "adopted admission "
                   f"({_fed_view(g, 'j-adm')}) ({fleet.log_tails()})")
        mttr_adm = round(_time.monotonic() - t_kill, 3)
        adm_copies = _fed_copy_regions(fleet, "j-adm")

        # -- episode 2: SIGKILL the leaseholder mid-cutover ----------
        fleet.spawn_router()            # keep the replica set at 2
        g.add_vcjob(_fed_job("roamer", 1, locality="rb"))
        _wire_wait(lambda: _fed_running(g, "roamer", "rb"), 60,
                   lambda: "roamer admission "
                   f"({_fed_view(g, 'roamer')}) ({fleet.log_tails()})")
        _fed_stamp_and_fold(fleet, "rb", "roamer", STAMP)
        gj = g.vcjobs["default/roamer"]
        gj.annotations[fedapi.FED_EVACUATE_ANNOTATION] = "ra"
        g.update_vcjob(gj)
        _wire_wait(lambda: (g.vcjobs["default/roamer"].annotations.get(
                       fedapi.FED_EVACUATING_TO_ANNOTATION)) == "ra",
                   60, lambda: f"evacuation start "
                   f"({fleet.log_tails()})")
        h_cut = fleet.leaseholder()
        fleet.kill_router(h_cut)
        t_kill2 = _time.monotonic()
        _wire_wait(lambda: _fed_running(g, "roamer", "ra"), 90,
                   lambda: "adopted cutover "
                   f"({_fed_view(g, 'roamer')}) ({fleet.log_tails()})")
        mttr_cut = round(_time.monotonic() - t_kill2, 3)
        _wire_wait(lambda: _fed_copy_regions(fleet, "roamer") ==
                   ["ra"], 60,
                   lambda: "source residual reap "
                   f"({_fed_copy_regions(fleet, 'roamer')}) "
                   f"({fleet.log_tails()})")
        gj = g.vcjobs["default/roamer"]
        cut_migrations = fedapi.migration_count(gj)
        cut_folded = _fed_folded_step(g, "roamer")
        racopy = fleet.clients["ra"].vcjobs["default/roamer"]
        cut_resume = int(racopy.annotations.get(
            RESUME_STEP_ANNOTATION, 0) or 0)

        # -- episode 3: partition (SIGSTOP) + fenced stale write -----
        fleet.spawn_router()
        _wire_wait(lambda: fleet.leaseholder() is not None, 30,
                   "leaseholder before partition")
        h2, term2 = fleet.leaseholder(), fleet.router_term()
        fleet.sigstop_router(h2)
        t_stop = _time.monotonic()
        _wire_wait(lambda: fleet.leaseholder() not in (None, h2), 30,
                   lambda: f"takeover from partitioned holder "
                   f"({fleet.log_tails()})")
        mttr_part = round(_time.monotonic() - t_stop, 3)
        term3 = fleet.router_term()
        rbc = fleet.clients["rb"]
        _wire_wait(lambda: int(rbc.fences().get(
                       fedapi.ROUTER_LEASE_NAME, {}).get("term", 0)
                   ) >= term3, 30,
                   lambda: f"fence advance to term {term3} "
                   f"({rbc.fences()})")
        fleet.sigcont_router(h2)
        # the deposed holder's write, replayed deterministically from
        # the conductor: stamped with the old term, it must be 409'd
        stale_refused = False
        rbc.set_fence(fedapi.ROUTER_LEASE_NAME, term2)
        try:
            rbc.add_vcjob(_fed_job("stale-probe", 1))
        except ValueError as e:
            stale_refused = str(e).startswith("fenced")
        finally:
            rbc.set_fence("", 0)
        fenced_count = int(rbc.fences().get(
            fedapi.ROUTER_LEASE_NAME, {}).get("refused", 0) or 0)

        # -- episode 4: total router vacancy -------------------------
        for holder in list(fleet.router_holders):
            fleet.kill_router(holder)
        _wire_wait(lambda: fleet.leaseholder() is None, 30,
                   "lease vacancy after killing every router")
        g.add_vcjob(_fed_job("j-queue", 1))
        _time.sleep(2.0)
        queued_while_vacant = fedapi.admitted_region(
            g.vcjobs["default/j-queue"]) is None
        anchor_through_vacancy = _fed_running(g, "anchor", "ra")
        fleet.spawn_router()
        t_fresh = _time.monotonic()
        _wire_wait(lambda: _fed_running(g, "j-queue"), 90,
                   lambda: "backlog drain by the fresh router "
                   f"({_fed_view(g, 'j-queue')}) "
                   f"({fleet.log_tails()})")
        mttr_vacancy = round(_time.monotonic() - t_fresh, 3)
        term_final = fleet.router_term()
        result = {
            "hosts": fleet.hosts, "regions": 2,
            "routers_spawned": fleet._routers_spawned,
            "lease_ttl_s": fleet._lease_ttl,
            "terms": {"initial": term0, "after_kill": term1,
                      "before_partition": term2,
                      "after_partition": term3, "final": term_final},
            "terms_strictly_monotonic":
                term0 < term1 <= term2 < term3 <= term_final,
            "kill_admission": {
                "promotion_s": promo_adm, "mttr_s": mttr_adm,
                "copy_regions": adm_copies,
                "exactly_once": adm_copies == ["rb"]},
            "kill_cutover": {
                "mttr_s": mttr_cut,
                "migrations": cut_migrations,
                "folded_step": cut_folded,
                "resume_step": cut_resume,
                "exactly_once": cut_migrations == 1 and
                    _fed_copy_regions(fleet, "roamer") == ["ra"],
                "acked_step_survived": cut_folded == STAMP and
                    cut_resume >= STAMP},
            "partition": {
                "takeover_s": mttr_part,
                "stale_fence_refused": stale_refused,
                "fenced_writes_counted": fenced_count},
            "vacancy": {
                "queued_while_vacant": queued_while_vacant,
                "anchor_ran_through": anchor_through_vacancy,
                "backlog_drain_s": mttr_vacancy},
            "no_dual_placement": not dual,
            "dual_placement_violations": dual[:5],
            "router_sync_errors": fleet.sync_errors[-3:],
        }
    finally:
        stop.set()
        sampler.join(timeout=2)
        fleet.shutdown()
    # the seeded router fault matrix (same scenario engine the chaos
    # conductor exposes as --classes router) rides in the artifact so
    # the committed row proves the invariants across DIFFERENT seeded
    # kill/partition timings, not one lucky schedule
    from tools import chaos_conductor
    matrix = []
    for seed in (1, 2):
        row = chaos_conductor.run_router_failover(seed, 30.0,
                                                  {"router"})
        matrix.append({"seed": seed, "ok": row["ok"],
                       "windows": row["windows"],
                       "failover_mttr_s": row["failover_mttr_s"],
                       "violations": row["violations"]})
    result["mttr_bound_s"] = chaos_conductor.ROUTER_MTTR_BOUND_S
    result["mttr_within_bound"] = all(
        m <= chaos_conductor.ROUTER_MTTR_BOUND_S for m in (
            result["kill_admission"]["mttr_s"],
            result["kill_cutover"]["mttr_s"],
            result["partition"]["takeover_s"],
            result["vacancy"]["backlog_drain_s"]))
    result["chaos_matrix"] = matrix
    result["chaos_matrix_green"] = all(r["ok"] for r in matrix)
    return result


def bench_federation_ha_wire_smoke() -> dict:
    """Seconds-scale router-HA drill for tier-1: two router
    processes, SIGKILL the leaseholder mid-cutover — the standby
    promotes under a higher term, adopts the half-done migration and
    completes it exactly once; a write stamped with the dead router's
    term is refused by the regional plane."""
    import time as _time

    from volcano_tpu.api import federation as fedapi
    from volcano_tpu.api.slicehealth import RESUME_STEP_ANNOTATION

    STAMP = 4200
    fleet = _FederationFleet(
        (("ra", 2, 1.0), ("rb", 1, 0.7)), ttl=4.0,
        arbitrage_after=60.0, router_procs=2, lease_ttl=2.0)
    g = fleet.g
    try:
        _wire_wait(lambda: fleet.leaseholder() is not None, 30,
                   lambda: f"router lease acquisition "
                   f"({fleet.log_tails()})")
        h0, term0 = fleet.leaseholder(), fleet.router_term()
        _wire_wait(lambda: _fed_regions_ready(g, ("ra", "rb")), 30,
                   lambda: f"region capacity folded "
                   f"({dict(getattr(g, 'regions', {}))})")
        g.add_vcjob(_fed_job("anchor", 1, locality="ra"))
        g.add_vcjob(_fed_job("roamer", 1, locality="rb"))
        _wire_wait(lambda: _fed_running(g, "anchor", "ra")
                   and _fed_running(g, "roamer", "rb"), 60,
                   lambda: "locality-routed admission "
                   f"({_fed_view(g, 'anchor')} "
                   f"{_fed_view(g, 'roamer')}) ({fleet.log_tails()})")
        _fed_stamp_and_fold(fleet, "rb", "roamer", STAMP)
        gj = g.vcjobs["default/roamer"]
        gj.annotations[fedapi.FED_EVACUATE_ANNOTATION] = "ra"
        g.update_vcjob(gj)
        _wire_wait(lambda: (g.vcjobs["default/roamer"].annotations.get(
                       fedapi.FED_EVACUATING_TO_ANNOTATION)) == "ra",
                   60, lambda: f"evacuation start "
                   f"({fleet.log_tails()})")
        holder_kill = fleet.leaseholder()
        fleet.kill_router(holder_kill)
        t_kill = _time.monotonic()
        _wire_wait(lambda: fleet.leaseholder()
                   not in (None, holder_kill), 30,
                   lambda: f"standby promotion ({fleet.log_tails()})")
        term1 = fleet.router_term()
        _wire_wait(lambda: _fed_running(g, "roamer", "ra"), 90,
                   lambda: "adopted cutover "
                   f"({_fed_view(g, 'roamer')}) ({fleet.log_tails()})")
        mttr = round(_time.monotonic() - t_kill, 3)
        _wire_wait(lambda: _fed_copy_regions(fleet, "roamer") ==
                   ["ra"], 60,
                   lambda: "source residual reap "
                   f"({_fed_copy_regions(fleet, 'roamer')})")
        # the dead leaseholder's late write, stamped with its term
        rbc = fleet.clients["rb"]
        stale_refused = False
        rbc.set_fence(fedapi.ROUTER_LEASE_NAME, term0)
        try:
            rbc.add_vcjob(_fed_job("stale-probe", 1))
        except ValueError as e:
            stale_refused = str(e).startswith("fenced")
        finally:
            rbc.set_fence("", 0)
        gj = g.vcjobs["default/roamer"]
        racopy = fleet.clients["ra"].vcjobs["default/roamer"]
        return {
            "regions": 2, "hosts": fleet.hosts,
            "routers": 2, "killed_holder": holder_kill,
            "term_before": term0, "term_after": term1,
            "term_bumped": term1 > term0,
            "failover_mttr_s": mttr,
            "migrations": fedapi.migration_count(gj),
            "cutover_exactly_once":
                fedapi.migration_count(gj) == 1 and
                _fed_copy_regions(fleet, "roamer") == ["ra"],
            "folded_step_survived":
                _fed_folded_step(g, "roamer") == STAMP,
            "resume_step_in_dest": int(racopy.annotations.get(
                RESUME_STEP_ANNOTATION, 0) or 0),
            "stale_fence_refused": stale_refused,
            "fenced_writes_counted": int(rbc.fences().get(
                fedapi.ROUTER_LEASE_NAME, {}).get("refused", 0) or 0),
            "anchor_untouched": _fed_running(g, "anchor", "ra"),
        }
    finally:
        fleet.shutdown()


def federation_ha_smoke() -> int:
    """Tier-1 router-HA drill, mirroring --federation-smoke.  Prints
    one JSON line."""
    try:
        out = bench_federation_ha_wire_smoke()
        ok = (out["term_bumped"]
              and out["cutover_exactly_once"]
              and out["folded_step_survived"]
              and out["resume_step_in_dest"] >= 4200
              and out["stale_fence_refused"]
              and out["fenced_writes_counted"] >= 1
              and out["anchor_untouched"])
    except AssertionError as e:
        out, ok = {"error": str(e)[-900:]}, False
    print(json.dumps({"metric": "federation_ha_smoke", "ok": ok,
                      **out}))
    return 0 if ok else 1


# -- control-plane crash chaos (kill -9 + WAL recovery) ----------------


class _CrashServer:
    """One state-server OS process over a durable --data-dir that the
    scenario can SIGKILL and respawn in place (same port, same dir) —
    the supervisor's restart loop, minus the supervisor."""

    def __init__(self, data_dir: str, port: int, logdir: str):
        self.data_dir = data_dir
        self.port = port
        self.url = f"http://127.0.0.1:{port}"
        self.logdir = logdir
        self.proc = None
        self.boots = 0

    def spawn(self):
        import os
        import subprocess
        import sys
        self.boots += 1
        repo = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu")
        env.pop("XLA_FLAGS", None)
        logf = open(os.path.join(self.logdir,
                                 f"server-boot{self.boots}.log"), "w")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "volcano_tpu.server",
             "--port", str(self.port), "--data-dir", self.data_dir],
            stdout=logf, stderr=logf, env=env, cwd=repo)

    def wait_ready(self, timeout: float = 30.0):
        import urllib.request

        def up():
            try:
                with urllib.request.urlopen(self.url + "/healthz",
                                            timeout=1):
                    return True
            except OSError:
                return False
        _wire_wait(up, timeout, "state server /healthz after (re)boot")

    def durability(self) -> dict:
        import urllib.request
        with urllib.request.urlopen(self.url + "/durability",
                                    timeout=5) as r:
            return json.loads(r.read())

    def kill9(self):
        import os
        import signal
        os.kill(self.proc.pid, signal.SIGKILL)
        self.proc.wait()

    def shutdown(self):
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except Exception:  # noqa: BLE001
                self.proc.kill()


def _snapshot_stores(url: str) -> dict:
    """Ground truth decoded straight off GET /snapshot (no mirror in
    the middle): {kind: {key: obj}}."""
    import urllib.request

    from volcano_tpu.api import codec
    from volcano_tpu.cache.kinds import KINDS
    req = urllib.request.Request(url + "/snapshot",
                                 headers={"Accept-Encoding": "gzip"})
    with urllib.request.urlopen(req, timeout=30) as r:
        from volcano_tpu.server.httputil import read_json_body
        payload = read_json_body(r)
    out = {}
    for kind, spec in KINDS.items():
        out[kind] = {k: codec.decode(v)
                     for k, v in payload["stores"].get(kind, {}).items()}
    return out


def _mirror_divergence(mirror, truth: dict) -> int:
    """Entries where a live mirror disagrees with the server's own
    snapshot: missing/extra keys per kind, or a pod whose binding
    (node, phase) differs.  Zero is the no-silent-divergence
    contract."""
    from volcano_tpu.cache.kinds import KINDS
    diverged = 0
    for kind, spec in KINDS.items():
        mine = getattr(mirror, spec.attr, {})
        theirs = truth[kind]
        diverged += len(set(mine) ^ set(theirs))
        if kind == "pod":
            for k in set(mine) & set(theirs):
                if mine[k].node_name != theirs[k].node_name or \
                        mine[k].phase is not theirs[k].phase:
                    diverged += 1
    return diverged


def bench_crash_recovery(smoke: bool = False) -> dict:
    """Chaos scenario for the durable control plane: a 1k-host
    cluster's state server takes a bind burst, gets SIGKILLed (not
    SIGTERMed — no goodbye pickle) mid-flight, restarts from
    snapshot+WAL, and the scenario measures the recovery time (RTO)
    and proves the two safety invariants: zero ACKED writes lost
    across the kill, and zero divergence between live watch mirrors
    and the recovered server (delta resync across the restart — the
    epoch BASE survives a durable boot).  Committed as
    CRASH_r{N}.json."""
    import shutil
    import tempfile
    import threading

    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes

    n_slices = 1 if smoke else 16            # 16 x v5e-256 = 1024 hosts
    slice_kind = "v5e-16" if smoke else "v5e-256"
    trials = 1 if smoke else 5
    kills_per_trial = 1 if smoke else 3
    kill_after_s = 0.2 if smoke else 0.5
    total_acked = 0

    rtos, client_gaps, replays = [], [], []
    acked_lost = 0
    divergence = 0
    rv_regressions = 0
    hosts = None
    logroot = tempfile.mkdtemp(prefix="crash-bench-")
    for trial in range(trials):
        data_dir = tempfile.mkdtemp(prefix="crash-wal-")
        import socket
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        server = _CrashServer(data_dir, port, logroot)
        kubectl = mirror = stale = None
        try:
            server.spawn()
            server.wait_ready()
            kubectl = RemoteCluster(server.url, start_watch=False)
            node_names = []
            for i in range(n_slices):
                for node in slice_nodes(
                        slice_for(f"c{trial}s{i:02d}", slice_kind),
                        dcn_pod=f"dcn-{i % 4}"):
                    kubectl.add_node(node)
                    node_names.append(node.name)
            hosts = len(node_names)
            # live mirror watching THROUGH the crash (delta path) and
            # a frozen one resynced only after recovery
            mirror = RemoteCluster(server.url)
            stale = RemoteCluster(server.url, start_watch=False)

            for round_ in range(kills_per_trial):
                # a CONTINUOUS create+bind burst that the kill lands
                # inside: chunks of pods created and gang-bound until
                # the stop mark (set a couple of seconds past the
                # kill, so acks must resume THROUGH the recovered
                # server for the tail of the burst)
                acked: dict = {}     # pod key -> node acked ok
                ack_times: list = []
                stop_mark = [float("inf")]

                def burst():
                    chunk = 16 if smoke else 64
                    i = 0
                    while time.monotonic() < stop_mark[0]:
                        names = [f"burst-t{trial}r{round_}-{i + j}"
                                 for j in range(chunk)]
                        i += chunk
                        try:
                            for j, name in enumerate(names):
                                pod = make_pod("t", requests={"cpu": 1})
                                pod.name = name
                                pod.namespace = "default"
                                kubectl.put_object("pod", pod)
                            binds = [("default", n,
                                      node_names[(i + j)
                                                 % len(node_names)])
                                     for j, n in enumerate(names)]
                            errs = kubectl.bind_pods(binds)
                        except Exception:  # noqa: BLE001 — outage ate
                            continue       # the whole retry budget
                        now = time.monotonic()
                        for (ns, n, node), err in zip(binds, errs):
                            if err is None:
                                acked[f"{ns}/{n}"] = node
                                ack_times.append(now)

                burster = threading.Thread(target=burst)
                burster.start()
                time.sleep(kill_after_s)
                # durable-rv checkpoint just before the kill: recovery
                # must come back at or past it (monotonic across boots)
                rv_before = server.durability()["visible_rv"]
                t_kill = time.monotonic()
                server.kill9()
                stop_mark[0] = t_kill + (1.0 if smoke else 2.0)
                server.spawn()
                server.wait_ready()
                rtos.append(time.monotonic() - t_kill)
                dur = server.durability()
                replays.append({"wal_records": dur["replay_records"],
                                "replay_s": dur["replay_seconds"]})
                if dur["rv"] < rv_before:
                    rv_regressions += 1
                burster.join(timeout=90)
                total_acked += len(acked)
                # ground truth vs every acked bind
                truth = _snapshot_stores(server.url)
                for key, node in acked.items():
                    pod = truth["pod"].get(key)
                    if pod is None or pod.node_name != node:
                        acked_lost += 1
                if ack_times:
                    before = [t for t in ack_times if t <= t_kill]
                    after = [t for t in ack_times if t > t_kill]
                    if before and after:
                        client_gaps.append(min(after) - max(before))
                # the watching mirror must converge with zero
                # divergence (its watch loop delta-resyncs across the
                # restart: same epoch BASE, bumped boot).  Writes are
                # quiet now (burster joined), so: catch the revision
                # first (cheap), then ONE deep compare.
                settle_rv = server.durability()["visible_rv"]
                _wire_wait(lambda: mirror._rv >= settle_rv, 30,
                           "mirror caught up to the recovered rv")
                divergence += _mirror_divergence(
                    mirror, _snapshot_stores(server.url))
                # frozen mirror: explicit resync after recovery must
                # also land exactly (delta when the WAL tail covers
                # its revision, full re-list otherwise — never stale)
                stale.resync()
                divergence += _mirror_divergence(
                    stale, _snapshot_stores(server.url))
        finally:
            for c in (kubectl, mirror, stale):
                if c is not None:
                    c.close()
            server.shutdown()
            shutil.rmtree(data_dir, ignore_errors=True)
    shutil.rmtree(logroot, ignore_errors=True)

    def pct(vals, q):
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1,
                              int(q * len(vals)))], 4) if vals else None

    return {
        "hosts": hosts, "trials": trials,
        "kills_per_trial": kills_per_trial,
        "binds_acked_total": total_acked,
        "rto_p50_s": pct(rtos, 0.5),
        "rto_p95_s": pct(rtos, 0.95),
        "client_ack_gap_p50_s": pct(client_gaps, 0.5),
        "replay": replays,
        "replay_p50_s": pct([r["replay_s"] for r in replays], 0.5),
        "wal_records_p50": pct(
            [float(r["wal_records"]) for r in replays], 0.5),
        "acked_writes_lost": acked_lost,
        "mirror_divergence": divergence,
        "rv_regressions": rv_regressions,
    }


def crash_smoke() -> int:
    """Seconds-scale kill -9 + WAL-replay cycle for tier-1 (small
    cluster, one kill), mirroring --wire-smoke/--failover-smoke: the
    crash-safety contract — acked writes survive, mirrors converge,
    rv monotonic — guarded on every commit.  Prints one JSON line."""
    try:
        out = bench_crash_recovery(smoke=True)
        ok = (out["acked_writes_lost"] == 0
              and out["mirror_divergence"] == 0
              and out["rv_regressions"] == 0
              and out["rto_p50_s"] is not None)
    except AssertionError as e:
        out, ok = {"error": str(e)[-600:]}, False
    print(json.dumps({"metric": "crash_smoke", "ok": ok, **out}))
    return 0 if ok else 1


# -- gray-failure chaos smoke (wire + disk faults, real processes) -----


def bench_chaos_smoke() -> dict:
    """The gray-failure contract on every commit, seconds-scale,
    through real OS processes (docs/design/chaos.md):

      1. ACK-LOST BIND: the server commits a /bind and DROPS the
         response (seeded fault plan, exactly one injection); the
         client's retry must converge by state-compare — bound once,
         no double effects.
      2. ENOSPC DEGRADE-AND-RECOVER: an injected ENOSPC window poisons
         the WAL; writes must 503 with Retry-After (read-only
         degrade), reads and leases must keep serving, and once the
         window passes the heal loop must make the server writable
         again with the rv monotonic across the whole episode.
      3. CRC-CORRUPT REPLAY: kill -9, flip one bit mid-WAL, reboot —
         the server must REFUSE to start (exit 3, CRC detection);
         rebooting with --wal-force-truncate must come up with every
         record before the corruption intact.
    """
    import os
    import shutil
    import tempfile
    import urllib.error
    import urllib.request

    from tools import chaoslib
    from volcano_tpu import faults as faults_mod
    from volcano_tpu import metrics
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes

    logdir = tempfile.mkdtemp(prefix="chaos-smoke-")
    data_dir = os.path.join(logdir, "state")
    port = chaoslib.free_port()
    url = f"http://127.0.0.1:{port}"
    # the seeded plan: exactly one dropped /bind ack + one ENOSPC
    # window a few seconds after boot
    plan_doc = {"seed": 12, "rules": [
        {"site": "server", "kind": "drop_response", "route": "/bind",
         "max_injections": 1},
        {"site": "disk", "kind": "enospc_append",
         "after_s": 3.0, "until_s": 5.0},
    ]}
    plan_path = os.path.join(logdir, "plan.json")
    with open(plan_path, "w", encoding="utf-8") as f:
        json.dump(plan_doc, f)
    zoo = chaoslib.ProcessZoo(logdir)
    out = {"seed": plan_doc["seed"]}
    kubectl = None
    try:
        t_boot = time.monotonic()
        zoo.spawn_server(port, "--data-dir", data_dir,
                         "--fault-plan", f"@{plan_path}")
        chaoslib.wait_server(url)
        kubectl = RemoteCluster(url, start_watch=False)
        node = next(iter(slice_nodes(slice_for("sa", "v5e-4"),
                                     dcn_pod="d0")))
        kubectl.add_node(node)

        # (1) the ack-lost bind: commit lands, response dropped, the
        # client retry must converge (state-compare rebind)
        pod = make_pod("t", requests={"cpu": 1})
        pod.name, pod.namespace = "p0", "default"
        kubectl.put_object("pod", pod)
        retries_before = metrics.get_counter("client_retries_total",
                                             route="/bind")
        kubectl.bind_pod("default", "p0", node.name)
        faults_fired = {r["kind"]: r["injected"]
                        for r in (chaoslib.http_json(url + "/faults")
                                  or {}).get("rules", [])}
        truth = chaoslib.snapshot_stores(url)
        out["ack_lost_bind"] = {
            "fault_injected": faults_fired.get("drop_response", 0),
            "client_retried": metrics.get_counter(
                "client_retries_total", route="/bind")
            > retries_before,
            "bound_once": truth["pod"]["default/p0"].node_name
            == node.name,
        }

        # (2) ENOSPC degrade-and-recover: inside the window writes
        # must 503 (readonly) while reads + leases still serve; after
        # it the heal loop must restore writability, rv monotonic
        rv_before = int(kubectl._request(
            "GET", "/durability")["visible_rv"])
        degrade = {"writes_503": False, "reads_served": False,
                   "leases_served": False, "retry_after": None}
        while time.monotonic() - t_boot < 10.0:
            body = json.dumps({"namespace": "default", "name": "p0",
                               "node_name": node.name}).encode()
            req = urllib.request.Request(
                url + "/bind", data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=5).read()
            except urllib.error.HTTPError as e:
                if e.code == 503 and json.loads(
                        e.read()).get("readonly"):
                    degrade["writes_503"] = True
                    degrade["retry_after"] = e.headers.get(
                        "Retry-After")
                    break
            time.sleep(0.15)
        # mid-degrade: reads and leases must still answer
        ro = kubectl._request("GET", "/durability")
        degrade["readonly_reason"] = ro.get("readonly") or ""
        degrade["reads_served"] = bool(ro)
        degrade["leases_served"] = bool(kubectl.lease(
            "chaos-smoke", "smoker", ttl=5.0).get("acquired"))
        # a write WITH the retry policy must wait out the heal
        # (Retry-After honoured) and land once writable again
        pod2 = make_pod("t", requests={"cpu": 1})
        pod2.name, pod2.namespace = "p1", "default"
        kubectl.put_object("pod", pod2)
        chaoslib.wait_for(
            lambda: not (kubectl._request("GET", "/durability")
                         .get("readonly") or ""),
            20, "server healed back to writable")
        dur = kubectl._request("GET", "/durability")
        degrade["healed_writable"] = not (dur.get("readonly") or "")
        degrade["rv_monotonic"] = int(dur["visible_rv"]) >= rv_before
        truth = chaoslib.snapshot_stores(url)
        degrade["post_heal_write_durable"] = "default/p1" in truth["pod"]
        out["enospc_degrade"] = degrade

        # a little more WAL tail so the bit flip below is mid-segment
        for i in range(4):
            p = make_pod("t", requests={"cpu": 1})
            p.name, p.namespace = f"tail{i}", "default"
            kubectl.put_object("pod", p)

        # (3) CRC-corrupt replay: bit-rot one mid-WAL record; boot
        # must refuse; --wal-force-truncate must keep the prefix
        zoo.kill9("server")
        seg = idx = None
        for name in sorted(os.listdir(data_dir)):
            if name.startswith("wal-") and name.endswith(".log"):
                path = os.path.join(data_dir, name)
                with open(path, "rb") as f:
                    n = sum(1 for ln in f if ln.strip())
                if n >= 3:
                    seg, idx = path, n // 2
                    break
        assert seg is not None, "no WAL segment thick enough to flip"
        faults_mod.flip_record_bit(seg, idx)
        zoo.spawn("server2", "-m", "volcano_tpu.server",
                  "--port", str(port), "--data-dir", data_dir)
        code = zoo.wait_exit("server2", timeout=30)
        crc = {"refused": code == 3 and bool(
            zoo.scrape("server2", "refusing to boot"))}
        zoo.spawn("server3", "-m", "volcano_tpu.server",
                  "--port", str(port), "--data-dir", data_dir,
                  "--wal-force-truncate")
        chaoslib.wait_server(url)
        crc["force_truncate_boots"] = True
        truth = chaoslib.snapshot_stores(url)
        # everything acked BEFORE the flipped record must be intact
        # (p0's bind + p1 landed well before the tail writes)
        crc["prefix_intact"] = (
            truth["pod"].get("default/p0") is not None
            and truth["pod"]["default/p0"].node_name == node.name
            and "default/p1" in truth["pod"])
        out["crc_corrupt_replay"] = crc
        out["ok"] = (
            out["ack_lost_bind"]["fault_injected"] == 1
            and out["ack_lost_bind"]["bound_once"]
            and degrade["writes_503"]
            and degrade["reads_served"] and degrade["leases_served"]
            and degrade["healed_writable"] and degrade["rv_monotonic"]
            and degrade["post_heal_write_durable"]
            and crc["refused"] and crc["prefix_intact"])
        return out
    finally:
        if kubectl is not None:
            kubectl.close()
        zoo.terminate_all()
        shutil.rmtree(logdir, ignore_errors=True)


def chaos_smoke() -> int:
    """Seconds-scale gray-failure drill for tier-1 (one ack-lost
    bind, one ENOSPC degrade-and-recover, one CRC-corrupt replay
    refusal through real processes), mirroring --crash-smoke.  Prints
    one JSON line."""
    try:
        out = bench_chaos_smoke()
        ok = out.get("ok", False)
    except AssertionError as e:
        out, ok = {"error": str(e)[-600:]}, False
    print(json.dumps({"metric": "chaos_smoke", "ok": ok, **out}))
    return 0 if ok else 1


# ---------------------------------------------------------------------
# Sharded both planes (docs/design/sharding.md): 2 subtree-partitioned
# scheduler processes over 2 keyspace-partitioned leader groups, all
# real OS processes.  One gang per home shard, then one cross-shard
# gang (homed to the full shard, soft-spilled onto the other shard's
# subtree).  The same workload replays on a single-shard plane and
# the per-job node placements must be IDENTICAL — sharding buys
# parallelism, never a different answer.

def _shard_smoke_conf(logdir: str) -> str:
    import copy
    import os

    conf = copy.deepcopy(BENCH_CONF)
    conf["configurations"] = {"allocate": {"gangCommit": "batch",
                                           "shard-spill": "soft"}}
    path = os.path.join(logdir, "conf.json")   # JSON is valid YAML
    with open(path, "w") as f:
        json.dump(conf, f)
    return path


def _shard_smoke_topology(kubectl) -> int:
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.controllers.hypernode import LabelDiscoverer
    from volcano_tpu.simulator import slice_nodes

    nodes = []
    for name in ("sa", "sb", "sc"):
        nodes.extend(slice_nodes(slice_for(name, "v5e-16")))
    for n in nodes:
        kubectl.add_node(n)
    # hypernodes via the label-discovery derivation the controller
    # itself would run
    for hn in LabelDiscoverer().discover(nodes):
        kubectl.add_hypernode(hn)
    return len(nodes)


def _shard_smoke_submit(kubectl, name: str, replicas: int) -> None:
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import PodGroupPhase
    from volcano_tpu.uthelper import gang_job

    pg, pods = gang_job(name, replicas=replicas,
                        requests={"cpu": 1, TPU: 4},
                        pg_phase=PodGroupPhase.INQUEUE)
    kubectl.add_podgroup(pg)
    for p in pods:
        kubectl.add_pod(p)


def _shard_smoke_wait_bound(kubectl, name: str, replicas: int,
                            plane, timeout: float = 40.0) -> dict:
    from volcano_tpu.api.types import TaskStatus

    want = {f"default/{name}-{i}" for i in range(replicas)}

    def bound():
        pods = kubectl.pods
        return all(
            k in pods and pods[k].node_name
            and pods[k].phase in (TaskStatus.BOUND, TaskStatus.RUNNING)
            for k in want)
    _wire_wait(bound, timeout,
               lambda: f"{name} bound ({plane.log_tails()[-1200:]})")
    pods = kubectl.pods
    return {k: pods[k].node_name for k in want}


def _healthz(url: str) -> bool:
    import urllib.request
    try:
        with urllib.request.urlopen(url + "/healthz", timeout=1):
            return True
    except OSError:
        return False


def _shard_smoke_run_plane(sharded: bool) -> dict:
    """Boot one plane (2 leader groups + 2 sharded schedulers, or
    1 server + 1 scheduler), run the 3-gang workload, return per-job
    sorted placements plus plane observables."""
    import socket

    from volcano_tpu import shardmap
    from volcano_tpu.cache.partitioned import PartitionedCluster
    from volcano_tpu.cache.remote_cluster import RemoteCluster

    plane = _WirePlane()
    kubectl = None
    try:
        conf_path = _shard_smoke_conf(plane.logdir)
        urls = [plane.url]
        plane.spawn("server-g0", "-m", "volcano_tpu.server",
                    "--port", str(plane.port), "--tick-period", "0.05")
        if sharded:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port2 = s.getsockname()[1]
            urls.append(f"http://127.0.0.1:{port2}")
            plane.spawn("server-g1", "-m", "volcano_tpu.server",
                        "--port", str(port2), "--tick-period", "0.05")
        for u in urls:
            _wire_wait(lambda u=u: _healthz(u), 20,
                       f"state server {u}")
        endpoints = ";".join(urls)
        if sharded:
            for idx in (0, 1):
                plane.spawn(f"sched-{idx}", "-m", "volcano_tpu",
                            "--cluster-url", endpoints,
                            "--components", "scheduler",
                            "--period", "0.05", "--conf", conf_path,
                            "--shard-index", str(idx),
                            "--shard-count", "2")
            kubectl = PartitionedCluster(endpoints)
        else:
            plane.spawn("sched", "-m", "volcano_tpu",
                        "--cluster-url", endpoints,
                        "--components", "scheduler",
                        "--period", "0.05", "--conf", conf_path)
            kubectl = RemoteCluster(endpoints)

        hosts = _shard_smoke_topology(kubectl)
        out = {"hosts": hosts, "sharded": sharded, "jobs": {}}
        # ga is homed to shard 0 (owns sa+sc), gb to shard 1 (owns
        # sb) — stable-hash facts asserted, not assumed; gx is the
        # cross-shard gang: its home subtree is full by the time it
        # arrives, so the home shard soft-spills it wholly onto the
        # other shard's free subtree
        assert shardmap.home_shard("default/ga", 2) == 0
        assert shardmap.home_shard("default/gb", 2) == 1
        plan = shardmap.plan_partition(
            shardmap.subtree_map(kubectl.nodes.values()), 2)
        assert plan[0]["subtrees"] == ["sa", "sc"], plan
        assert plan[1]["subtrees"] == ["sb"], plan

        rv0 = None
        if sharded:
            rv0 = [g._request("GET", "/durability").get("rv", 0)
                   for g in kubectl.groups]
        for name, replicas in (("ga", 4), ("gb", 4), ("gx", 4)):
            _shard_smoke_submit(kubectl, name, replicas)
            placed = _shard_smoke_wait_bound(kubectl, name, replicas,
                                             plane)
            out["jobs"][name] = sorted(placed.values())
        # the workload's shape proves the contract: ga fills its home
        # subtree, gb fills its OWN home subtree (not spillover), gx
        # lands wholly on the foreign free subtree
        assert all(n.startswith("sa-") for n in out["jobs"]["ga"]), out
        assert all(n.startswith("sb-") for n in out["jobs"]["gb"]), out
        assert all(n.startswith("sc-") for n in out["jobs"]["gx"]), out

        if sharded:
            # both shards scheduled (their stamped cycle traces made
            # it to the meta ring) ...
            traces = kubectl._request(
                "GET", "/traces?limit=64").get("traces", [])
            shards_seen = {(t.get("root", {}).get("labels") or {})
                           .get("shard") for t in traces}
            out["sched_shards_traced"] = sorted(
                s for s in shards_seen if s)
            assert {"0/2", "1/2"} <= shards_seen, shards_seen
            # ... and BOTH leader groups absorbed writes: gb's binds
            # relocated its pods onto group 1's keyspace
            rv1 = [g._request("GET", "/durability").get("rv", 0)
                   for g in kubectl.groups]
            out["leader_group_rv_delta"] = [
                b - a for a, b in zip(rv0, rv1)]
            assert all(d > 0 for d in out["leader_group_rv_delta"]), \
                out["leader_group_rv_delta"]
            out["endpoints_shape"] = "g0;g1"
        return out
    finally:
        if kubectl is not None:
            kubectl.close()
        plane.shutdown()


def bench_shard_smoke() -> dict:
    sharded = _shard_smoke_run_plane(sharded=True)
    single = _shard_smoke_run_plane(sharded=False)
    identical = sharded["jobs"] == single["jobs"]
    return {
        "ok": identical,
        "placements_identical": identical,
        "sharded": sharded,
        "single": single,
    }


_QPS_WRITE_WORKER = r'''
import sys, time
spec, subtree, dur = sys.argv[1], sys.argv[2], float(sys.argv[3])
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.types import TaskStatus
if ";" in spec:
    from volcano_tpu.cache.partitioned import PartitionedCluster
    c = PartitionedCluster(spec)
else:
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    c = RemoteCluster(spec)
t_end = time.monotonic() + 10
nodes = []
while time.monotonic() < t_end:
    nodes = sorted(n for n in c.nodes if n.startswith(subtree + "-"))
    if len(nodes) >= 4:
        break
    time.sleep(0.05)
assert nodes, f"no {subtree} nodes visible"
n = 0
t_end = time.monotonic() + dur
while time.monotonic() < t_end:
    p = make_pod("t", requests={"cpu": 1})
    p.name = f"qw-{subtree}-{n % 64}"
    p.namespace = "default"
    p.node_name = nodes[n % len(nodes)]
    p.phase = TaskStatus.BOUND
    try:
        c.put_object("pod", p)
        n += 1
    except Exception:
        pass
c.close()
print(n)
'''


def bench_leader_write_qps(groups: int = 3, writers: int = 3,
                           measure_s: float = 5.0) -> dict:
    """The write-capacity row (docs/design/sharding.md): the same
    keyed pod-status churn — the dominant production write — pushed
    by N writer OS processes against ONE write leader, then against
    the keyspace split across `groups` single-leader groups.  Each
    writer churns one subtree, so under the partitioned config its
    writes route to that subtree's owner group; aggregate QPS is
    measured server-side as sum(rv delta)/window, never from client
    counters.  host_cpus recorded per row: on a single core the
    groups serialize, so this row measures protocol capacity split,
    not hardware parallelism."""
    import json as _json
    import os as _os
    import socket
    import subprocess
    import sys as _sys
    import urllib.request

    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.cache.partitioned import PartitionedCluster
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes

    subtrees = [f"q{chr(ord('a') + i)}" for i in range(writers)]

    def rv_of(url):
        with urllib.request.urlopen(url + "/durability",
                                    timeout=5) as r:
            return int(_json.loads(r.read()).get("rv") or 0)

    def run_config(n_groups):
        plane = _WirePlane()
        kubectl = None
        procs = []
        try:
            urls = []
            for gi in range(n_groups):
                if gi == 0:
                    port = plane.port
                else:
                    with socket.socket() as s:
                        s.bind(("127.0.0.1", 0))
                        port = s.getsockname()[1]
                plane.spawn(f"server-g{gi}", "-m",
                            "volcano_tpu.server", "--port", str(port),
                            "--tick-period", "0.2")
                urls.append(f"http://127.0.0.1:{port}")
            for u in urls:
                _wire_wait(lambda u=u: _healthz(u), 20,
                           f"state server {u}")
            spec = ";".join(urls)
            kubectl = PartitionedCluster(spec) if n_groups > 1 \
                else RemoteCluster(spec)
            for sname in subtrees:
                for node in slice_nodes(slice_for(sname, "v5e-16"),
                                        dcn_pod="d0"):
                    kubectl.put_object("node", node)
            env = dict(_os.environ, PYTHONPATH=plane.repo,
                       JAX_PLATFORMS="cpu")
            procs = [subprocess.Popen(
                [_sys.executable, "-c", _QPS_WRITE_WORKER, spec,
                 subtrees[w % len(subtrees)], str(measure_s + 3.0)],
                stdout=subprocess.PIPE, text=True, env=env,
                cwd=plane.repo) for w in range(writers)]
            time.sleep(2.0)        # workers connect + mirrors sync
            rv0 = [rv_of(u) for u in urls]
            t0 = time.monotonic()
            time.sleep(measure_s)
            dt = time.monotonic() - t0
            rv1 = [rv_of(u) for u in urls]
            ops = sum(int(p.communicate()[0].strip() or 0)
                      for p in procs)
            deltas = [b - a for a, b in zip(rv0, rv1)]
            row = {"groups": n_groups, "writers": writers,
                   "host_cpus": _os.cpu_count(),
                   "per_group_rv_delta": deltas,
                   "write_qps": round(sum(deltas) / dt, 1),
                   "writer_ops_total": ops}
            if n_groups > 1:
                row["layout"] = kubectl.shard_layout()
            return row
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            if kubectl is not None:
                kubectl.close()
            plane.shutdown()

    one = run_config(1)
    split = run_config(groups)
    return {
        "write_load": f"{writers} writer processes, keyed bound-pod "
                      "status churn, one subtree each",
        "measure_s": measure_s,
        "single_leader": one,
        "partitioned_leaders": split,
        "scaling": round(split["write_qps"] / one["write_qps"], 2)
        if one["write_qps"] else None,
        "note": ("single-CPU host: all leader groups share one core, "
                 "so this row proves the keyspace split carries the "
                 "full write stream with per-group leaders — the "
                 "hardware-parallel win needs a multi-core replay"),
    }


def shard_smoke() -> int:
    """Seconds-scale sharded-plane drill for tier-1: 2 scheduler
    shards + 2 leader groups as real OS processes, one cross-shard
    gang, placements identical to the single-shard plane.  Prints one
    JSON line."""
    try:
        out = bench_shard_smoke()
        ok = out.get("ok", False)
    except AssertionError as e:
        out, ok = {"error": str(e)[-600:]}, False
    print(json.dumps({"metric": "shard_smoke", "ok": ok, **out}))
    return 0 if ok else 1


# ---------------------------------------------------------------------
# Replicated control plane (server/replication.py): WAL-shipped
# follower reads, quorum commit, kill-promote.  The tier-1 smoke runs
# leader + 1 follower as real OS processes (~20s): continuous keyed
# writes and follower reads, SIGKILL the leader mid-burst, the
# follower promotes, the multi-endpoint client re-routes, the deposed
# leader rejoins as a follower — zero acked writes lost, follower
# reads continuous throughout.  The full matrix + read-QPS scaling
# lives in tools/chaos_conductor.py --classes replication
# (CONTROL_r{N}.json).

def bench_replication_smoke() -> dict:
    import os
    import shutil
    import tempfile
    import threading
    import urllib.request

    from tools import chaoslib
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes

    logdir = tempfile.mkdtemp(prefix="repl-smoke-")
    ports = [chaoslib.free_port(), chaoslib.free_port()]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    dirs = [os.path.join(logdir, f"state-{i}") for i in range(2)]
    zoo = chaoslib.ProcessZoo(logdir)
    out = {}
    kubectl = None
    reader_stop = threading.Event()
    try:
        # 2-node lab group: commit quorum 2 (every ack durable on BOTH
        # replicas — which is what makes the lone survivor's promotion
        # lossless), election quorum 1 (a 2-node group cannot form an
        # election majority; see docs/design/replication.md on the
        # split-brain tradeoff this accepts)
        chaoslib.spawn_replica(zoo, "leader", ports[0], dirs[0], "r1",
                               [urls[1]], commit_quorum=2,
                               election_quorum=1)
        chaoslib.wait_server(urls[0])
        chaoslib.spawn_replica(zoo, "follower", ports[1], dirs[1],
                               "r2", [urls[0]], replicate_from=urls[0],
                               commit_quorum=2, election_quorum=1)
        chaoslib.wait_server(urls[1])
        chaoslib.wait_role(urls[0], "leader")
        chaoslib.wait_role(urls[1], "follower")

        kubectl = RemoteCluster(",".join(urls), start_watch=False)
        node_names = []
        for node in slice_nodes(slice_for("sa", "v5e-16"),
                                dcn_pod="d0"):
            kubectl.add_node(node)
            node_names.append(node.name)
        chaoslib.wait_follower_caught_up(urls[1], urls[0])

        # follower reads, continuously, on a dedicated thread: the
        # max gap between successful reads is the availability number
        # the whole exercise is about
        read_ok = [0]
        read_fail = [0]
        read_gaps = []
        last_ok = [time.monotonic()]

        def reader():
            while not reader_stop.is_set():
                try:
                    with urllib.request.urlopen(
                            urls[1] + "/durability", timeout=2) as r:
                        json.loads(r.read())
                    now = time.monotonic()
                    read_gaps.append(now - last_ok[0])
                    last_ok[0] = now
                    read_ok[0] += 1
                except OSError:
                    read_fail[0] += 1
                time.sleep(0.05)

        threading.Thread(target=reader, daemon=True).start()

        # keyed write burst: pods created + gang-bound through the
        # multi-endpoint client, acks recorded; the SIGKILL lands
        # mid-burst and the client must re-route to the promoted
        # follower without double-applying (idempotency keys ship in
        # the WAL, so the new leader replays recorded verdicts)
        acked = {}
        stop_mark = [float("inf")]
        t_kill_holder = [None]
        acks_after_kill = [0]

        def burst():
            i = 0
            while time.monotonic() < stop_mark[0]:
                names = [f"rp{i + j}" for j in range(8)]
                i += 8
                try:
                    for name in names:
                        pod = make_pod("t", requests={"cpu": 1})
                        pod.name, pod.namespace = name, "default"
                        kubectl.put_object("pod", pod)
                    binds = [("default", n,
                              node_names[(i + j) % len(node_names)])
                             for j, n in enumerate(names)]
                    errs = kubectl.bind_pods(binds)
                except Exception:  # noqa: BLE001 — failover window
                    continue
                for (ns, n, node), err in zip(binds, errs):
                    if err is None:
                        acked[f"{ns}/{n}"] = node
                        if t_kill_holder[0] is not None:
                            acks_after_kill[0] += 1

        burster = threading.Thread(target=burst)
        burster.start()
        time.sleep(3.0)
        acked_before_kill = len(acked)
        t_kill = time.monotonic()
        t_kill_holder[0] = t_kill
        zoo.kill9("leader")
        chaoslib.wait_role(urls[1], "leader", timeout=30)
        promote_s = time.monotonic() - t_kill
        # the deposed leader rejoins as a follower over its old dir:
        # its term is stale, so the tail forces the full re-sync
        chaoslib.spawn_replica(zoo, "leader-rejoin", ports[0],
                               dirs[0], "r1", [urls[1]],
                               replicate_from="auto", commit_quorum=2,
                               election_quorum=1)
        chaoslib.wait_server(urls[0])
        chaoslib.wait_role(urls[0], "follower", timeout=30)
        stop_mark[0] = time.monotonic() + 3.0
        burster.join(timeout=60)
        reader_stop.set()

        # ground truth off the promoted leader: every acked bind
        # exactly as acked — nothing lost, nothing moved
        truth = _snapshot_stores(urls[1])
        lost = [k for k, node in acked.items()
                if k not in truth["pod"]
                or truth["pod"][k].node_name != node]
        chaoslib.wait_follower_caught_up(urls[0], urls[1])
        rejoin = chaoslib.replication_status(urls[0]) or {}
        out = {
            "acked_binds": len(acked),
            "acked_before_kill": acked_before_kill,
            "acked_after_promote": acks_after_kill[0],
            "acked_lost": len(lost),
            "lost_sample": lost[:5],
            "promote_s": round(promote_s, 3),
            "follower_reads_ok": read_ok[0],
            "follower_reads_failed": read_fail[0],
            "follower_read_gap_max_s": round(max(read_gaps), 3)
            if read_gaps else None,
            "rejoin_role": rejoin.get("role"),
            "rejoin_bootstraps": rejoin.get("bootstraps"),
            "new_leader_term": (chaoslib.replication_status(urls[1])
                                or {}).get("term"),
        }
        out["ok"] = (
            out["acked_lost"] == 0
            and out["acked_before_kill"] > 0
            and out["acked_after_promote"] > 0
            and out["promote_s"] < 20
            and out["follower_reads_ok"] > 0
            and out["follower_reads_failed"] == 0
            and out["rejoin_role"] == "follower")
        return out
    finally:
        reader_stop.set()
        if kubectl is not None:
            kubectl.close()
        zoo.terminate_all()
        shutil.rmtree(logdir, ignore_errors=True)


def replication_smoke() -> int:
    """Leader + 1 follower + kill-promote through real OS processes
    for tier-1 (~20s), mirroring --crash-smoke: zero acked writes
    lost across the promotion, continuous follower reads, the deposed
    leader re-syncs back in.  Prints one JSON line."""
    try:
        out = bench_replication_smoke()
        ok = out.get("ok", False)
    except AssertionError as e:
        out, ok = {"error": str(e)[-600:]}, False
    print(json.dumps({"metric": "replication_smoke", "ok": ok, **out}))
    return 0 if ok else 1


# ---------------------------------------------------------------------
# Scheduling flight recorder: per-phase latency attribution through
# the REAL process control plane (volcano_tpu/trace.py).  Gang jobs
# run create->running over the wire; every lifecycle stamp is read
# back from the stamped pod/podgroup annotations, decomposed into
# phase segments, and reconciled against the measured end-to-end
# latency (the telescoping invariant: segments must sum to the total
# within 5%).  The server's /traces ring proves session span trees
# flow through the same wire.  Committed as TRACE_r{N}.json.

def bench_trace(smoke: bool = False) -> dict:
    from volcano_tpu import trace as trace_mod
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes

    n_slices = 1 if smoke else 16           # 16 x v5e-256 = 1024 hosts
    slice_kind = "v5e-16" if smoke else "v5e-256"
    gang = 4 if smoke else 64
    trials = 1 if smoke else 5

    plane = _WirePlane()
    kubectl = None
    try:
        plane.start()
        kubectl = RemoteCluster(plane.url)
        for i in range(n_slices):
            for node in slice_nodes(slice_for(f"t{i:02d}", slice_kind),
                                    dcn_pod=f"dcn-{i % 4}"):
                kubectl.add_node(node)
        hosts = len(kubectl.nodes)

        gangs = []
        for t in range(trials):
            name = f"tracegang-{t}"
            kubectl.add_vcjob(_wire_gang_job(name, gang, run_ticks=2))
            _wire_wait(lambda: _job_running(kubectl, name, gang), 90,
                       lambda: f"{name} bound "
                               f"({plane.log_tails()[-800:]})")
            # completion frees the slice: identical capacity per trial
            _wire_wait(lambda: _job_completed(kubectl, name), 90,
                       f"{name} completed")
            gangs.append(name)
        # let the final watch events land, then read stamps from a
        # fresh resync of the mirror
        time.sleep(0.3)
        kubectl.resync()

        seg_samples = {seg: [] for seg, _f, _t in trace_mod.SEGMENTS}
        pod_e2es, reconcile_errs = [], []
        gang_rows = []
        for name in gangs:
            pg = kubectl.podgroups.get(f"default/{name}")
            pg_ann = pg.annotations if pg is not None else None
            pods = [p for p in kubectl.pods.values()
                    if p.labels.get("volcano-tpu.io/job-name") == name]
            assert len(pods) >= gang, \
                f"{name}: {len(pods)} pods visible"
            stamps = {ph: [] for ph in trace_mod.PHASES}
            for p in pods:
                segs = trace_mod.phase_segments(p.annotations, pg_ann)
                created = trace_mod.phase_ts(p.annotations, "created")
                running = trace_mod.phase_ts(p.annotations, "running")
                assert created is not None and running is not None, \
                    f"{p.key} missing lifecycle stamps"
                e2e = running - created
                pod_e2es.append(e2e)
                if e2e > 1e-9:
                    # the reconciliation invariant, per pod: clamped
                    # segments must telescope back to the total
                    reconcile_errs.append(
                        abs(sum(segs.values()) - e2e) / e2e * 100.0)
                for seg, dur in segs.items():
                    seg_samples[seg].append(dur)
                for ph in trace_mod.PHASES:
                    ts = trace_mod.phase_ts(p.annotations, ph)
                    if ts is None and pg_ann is not None:
                        ts = trace_mod.phase_ts(pg_ann, ph)
                    if ts is not None:
                        stamps[ph].append(ts)
            # gang-level breakdown from edge stamps: created = first
            # pod created, every later phase = LAST pod through it, so
            # the segments telescope to the measured gang e2e
            edges = {}
            for ph in trace_mod.PHASES:
                if not stamps[ph]:
                    continue
                edges[ph] = (min(stamps[ph]) if ph == "created"
                             else max(stamps[ph]))
            gang_e2e = edges["running"] - edges["created"]
            gsegs, prev = {}, edges["created"]
            for seg, _f, to in trace_mod.SEGMENTS:
                if to not in edges:
                    continue
                gsegs[seg] = round(max(0.0, edges[to] - prev), 4)
                prev = max(prev, edges[to])
            gang_rows.append({"job": name,
                              "gang_e2e_s": round(gang_e2e, 4),
                              "segments_s": gsegs,
                              "reconcile_err_pct": round(
                                  abs(sum(gsegs.values()) - gang_e2e)
                                  / max(gang_e2e, 1e-9) * 100.0, 3)})

        # the flight recorder's query surface, through the same wire
        traces = kubectl._request(
            "GET", "/traces?limit=64").get("traces", [])
        span_actions = {}
        for t in traces:
            for child in (t.get("root") or {}).get("children", ()):
                if child.get("kind") == "action":
                    span_actions.setdefault(child["name"], []).append(
                        child.get("dur", 0.0))

        def pct(vals, q):
            vals = sorted(vals)
            return round(vals[min(len(vals) - 1,
                                  int(q * len(vals)))], 4) \
                if vals else None

        return {
            "hosts": hosts, "gang_replicas": gang, "trials": trials,
            "pods_measured": len(pod_e2es),
            "pod_e2e_p50_s": pct(pod_e2es, 0.5),
            "pod_e2e_p95_s": pct(pod_e2es, 0.95),
            "phase_p50_s": {seg: pct(vals, 0.5)
                            for seg, vals in seg_samples.items()},
            "phase_p95_s": {seg: pct(vals, 0.95)
                            for seg, vals in seg_samples.items()},
            "gangs": gang_rows,
            "gang_e2e_p50_s": pct(
                [g["gang_e2e_s"] for g in gang_rows], 0.5),
            "reconcile_err_max_pct": round(max(
                [g["reconcile_err_pct"] for g in gang_rows]
                + reconcile_errs), 3),
            "traces_captured": len(traces),
            "trace_span_p50_s": {name: pct(vals, 0.5)
                                 for name, vals in
                                 sorted(span_actions.items())},
        }
    finally:
        if kubectl is not None:
            kubectl.close()
        plane.shutdown()


def trace_smoke() -> int:
    """Seconds-scale flight-recorder drill for tier-1 (small cluster,
    one gang), mirroring --wire-smoke/--crash-smoke: lifecycle stamps
    present on every gang pod, phase segments reconcile with the
    measured e2e within 5%, and session span trees reach the server's
    /traces ring.  Prints one JSON line."""
    try:
        out = bench_trace(smoke=True)
        ok = (out["pods_measured"] >= out["gang_replicas"]
              and out["reconcile_err_max_pct"] is not None
              and out["reconcile_err_max_pct"] < 5.0
              and out["traces_captured"] > 0
              and out["pod_e2e_p50_s"] is not None
              and out["pod_e2e_p50_s"] > 0)
    except AssertionError as e:
        out, ok = {"error": str(e)[-600:]}, False
    print(json.dumps({"metric": "trace_smoke", "ok": ok, **out}))
    return 0 if ok else 1


def _flash_child():
    """Runs in a SUBPROCESS on the real TPU (the axon tunnel hangs at
    backend init when dead — the parent enforces the timeout): time the
    Pallas flash-attention kernel vs the jnp reference, fwd and
    fwd+bwd, and report rough MFU.

    Methodology: the tunnel adds ~80ms per host round-trip and its
    completion signaling makes single-dispatch wall times meaningless
    (sub-physical readings), so each measurement chains the kernel N
    times inside ONE jit (output feeds the next iteration's q) and the
    per-iteration cost is the SLOPE between a short and a long chain —
    dispatch overhead and the final device->host sum cancel out."""
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    b, t, h, d = 4, 2048, 8, 128
    from volcano_tpu.workloads.ops.flash_attention import (
        _reference, flash_attention)

    q, k, v = (jax.random.normal(jax.random.key(i), (b, t, h, d),
                                 dtype=jnp.bfloat16) for i in range(3))

    def chain(step_fn, n):
        @jax.jit
        def run(q, k, v):
            out = jax.lax.fori_loop(
                0, n, lambda i, acc: step_fn(acc, k, v), q)
            return out.astype(jnp.float32).sum()
        return run

    def slope_s(step_fn, n1=10, n2=110, reps=4):
        f1, f2 = chain(step_fn, n1), chain(step_fn, n2)
        float(f1(q, k, v))
        float(f2(q, k, v))                  # compile + warm
        best_a = best_c = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            float(f1(q, k, v))
            best_a = min(best_a, time.perf_counter() - t0)
            t0 = time.perf_counter()
            float(f2(q, k, v))
            best_c = min(best_c, time.perf_counter() - t0)
        # min per chain independently: a noisy-slow short run paired
        # with a clean long run must not produce a sub-physical slope
        return (best_c - best_a) / (n2 - n1)

    def grad_step(fn):
        g = jax.grad(lambda q, k, v: fn(q, k, v).astype(
            jnp.float32).sum())
        return lambda q, k, v: g(q, k, v).astype(q.dtype)

    pallas = lambda q, k, v: flash_attention(q, k, v)
    pallas_b256 = lambda q, k, v: flash_attention(
        q, k, v, block_q_bwd=256, block_k_bwd=256)
    # mismatched bwd pair: a tall dq tile (q256) against wide k/v
    # reads (k512) — the r4 gradient-exactness tests cover exactly
    # this shape family, so the sweep may pick it safely
    pallas_bmix = lambda q, k, v: flash_attention(
        q, k, v, block_q_bwd=256, block_k_bwd=512)
    ref = lambda q, k, v: _reference(q, k, v, True).astype(q.dtype)

    fwd_flops = 4.0 * b * h * t * t * d / 2    # causal: half the pairs
    peak = TPU_PEAK_FLOPS.get(dev.device_kind)
    t_p = slope_s(pallas)
    t_r = slope_s(ref)
    t_pb = slope_s(grad_step(pallas), n1=5, n2=45)
    t_pb256 = slope_s(grad_step(pallas_b256), n1=5, n2=45)
    t_pbmix = slope_s(grad_step(pallas_bmix), n1=5, n2=45)
    t_rb = slope_s(grad_step(ref), n1=5, n2=45)
    best_pb = min(t_pb, t_pb256, t_pbmix)
    print(json.dumps({
        "tpu_available": True, "device_kind": dev.device_kind,
        "shape_bthd": [b, t, h, d],
        "pallas_fwd_ms": round(t_p * 1e3, 3),
        "jnp_fwd_ms": round(t_r * 1e3, 3),
        "pallas_fwd_bwd_ms": round(best_pb * 1e3, 3),
        "pallas_fwd_bwd_ms_bwd512": round(t_pb * 1e3, 3),
        "pallas_fwd_bwd_ms_bwd256": round(t_pb256 * 1e3, 3),
        "pallas_fwd_bwd_ms_bwd256x512": round(t_pbmix * 1e3, 3),
        "jnp_fwd_bwd_ms": round(t_rb * 1e3, 3),
        "fwd_speedup": round(t_r / t_p, 2),
        "fwd_bwd_speedup": round(t_rb / best_pb, 2),
        "pallas_fwd_tflops": round(fwd_flops / t_p / 1e12, 1),
        "pallas_fwd_mfu": (round(fwd_flops / t_p / peak, 3)
                           if peak else None),
    }))


def _train_one_config(cfg, b, t, opt):
    """Measure one (model, batch) combo; returns (step_s, loss, flops,
    params_m).  Slope methodology as in _flash_child: K steps chained
    inside one jit via lax.scan, marginal cost from a short/long chain
    pair."""
    import jax
    import jax.numpy as jnp

    from volcano_tpu.workloads import model as model_lib
    from volcano_tpu.workloads import train

    params = model_lib.init_params(jax.random.key(0), cfg)
    opt_state = opt.init(params)
    batch = train.synthetic_batch(jax.random.key(1), cfg, b, t)

    def chain(n):
        @jax.jit
        def run(params, opt_state):
            def body(carry, _):
                p, o = carry
                p, o, m = train.train_step(p, o, batch, cfg, opt)
                return (p, o), m["loss"]
            _, losses = jax.lax.scan(body, (params, opt_state),
                                     None, length=n)
            return losses[-1].astype(jnp.float32)
        return run

    n1, n2 = 2, 12
    f1, f2 = chain(n1), chain(n2)
    float(f1(params, opt_state))
    float(f2(params, opt_state))           # compile + warm
    best1 = best2 = float("inf")
    loss = float("nan")
    for _ in range(3):
        t0 = time.perf_counter()
        float(f1(params, opt_state))
        best1 = min(best1, time.perf_counter() - t0)
        t0 = time.perf_counter()
        loss = float(f2(params, opt_state))
        best2 = min(best2, time.perf_counter() - t0)
    step_s = (best2 - best1) / (n2 - n1)

    total = sum(jax.tree.leaves(jax.tree.map(lambda x: x.size, params)))
    # standard MFU accounting (PaLM appendix): the input embedding is a
    # lookup (excluded); the output head IS a matmul (included)
    matmul_params = total - cfg.vocab_size * cfg.d_model
    # 6ND matmul flops + causal attention (fwd 4bht^2*hd/2, bwd ~2x)
    attn_fwd = cfg.n_layers * 4.0 * b * cfg.n_heads * t * t * \
        cfg.head_dim / 2
    flops = 6.0 * matmul_params * b * t + 3.0 * attn_fwd
    return step_s, loss, flops, total / 1e6


def _train_child():
    """Full training-step throughput on ONE real TPU chip (bf16, flash
    attention): the framework-trains-on-TPU proof.  Sweeps a small set
    of model/batch shapes inside one backend session and reports the
    best-MFU point plus the whole sweep (VERDICT r2 item 2: push MFU
    >= 0.40 via batch/width tuning — wide d_model keeps the MXU full
    where the old 1024-wide config left it starved)."""
    import os

    import jax
    import jax.numpy as jnp

    from volcano_tpu.workloads import model as model_lib

    from volcano_tpu.workloads import train

    dev = jax.devices()[0]
    peak = TPU_PEAK_FLOPS.get(dev.device_kind)
    t = int(os.environ.get("BENCH_TRAIN_SEQ", "2048"))
    opt = train.make_optimizer()
    opt_mu16 = train.make_optimizer(mu_dtype=jnp.bfloat16)

    def cfg_of(d_model, n_layers, d_ff, n_heads, remat):
        return model_lib.ModelConfig(
            vocab_size=32000, d_model=d_model, n_layers=n_layers,
            n_heads=n_heads, d_ff=d_ff, max_seq=t, dtype=jnp.bfloat16,
            use_flash_attention=True, remat=remat)

    # (tag, cfg, batch, optimizer, env overrides) — the env column
    # sweeps flash bwd block shapes (read at trace time, VERDICT r3
    # next-round #2: tune dq/dk/dv blocks + optimizer dtypes)
    wide = cfg_of(2048, 8, 8192, 16, False)
    sweep = [
        ("d2048-L8-b8", wide, 8, opt, {}),
        ("d2048-L8-b8-bwd256", wide, 8, opt,
         {"FLASH_BLOCK_BWD": "256"}),
        ("d2048-L8-b8-mu16", wide, 8, opt_mu16, {}),
        ("d2048-L8-b16-remat", cfg_of(2048, 8, 8192, 16, True), 16,
         opt, {}),
        ("d2048-L8-b16-remat-bwd256", cfg_of(2048, 8, 8192, 16, True),
         16, opt, {"FLASH_BLOCK_BWD": "256"}),
        ("d1024-L8-b8", cfg_of(1024, 8, 4096, 8, False), 8, opt, {}),
    ]
    if os.environ.get("BENCH_TRAIN_BATCH"):
        b = int(os.environ["BENCH_TRAIN_BATCH"])
        sweep = [(f"d2048-L8-b{b}", wide, b, opt, {})]

    def emit(results):
        """Cumulative line after EVERY config: a parent-side timeout
        mid-sweep salvages the best-so-far instead of losing all."""
        ok = [r for r in results if "error" not in r]
        if not ok:
            return
        best = max(ok, key=lambda r: r["mfu"] or 0)
        out = {"tpu_available": True, "device_kind": dev.device_kind,
               "sweep": results}
        out.update(best)
        print(json.dumps(out), flush=True)

    results = []
    for tag, cfg, b, opt_i, env_over in sweep:
        saved = {k: os.environ.get(k) for k in env_over}
        os.environ.update(env_over)
        try:
            step_s, loss, flops, params_m = _train_one_config(
                cfg, b, t, opt_i)
        except Exception as e:  # noqa: BLE001 — e.g. OOM on one shape
            results.append({"config": tag, "error": str(e)[-200:]})
            emit(results)
            continue
        finally:
            for k, old in saved.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
        results.append({
            "config": tag, "params_m": round(params_m, 1),
            "batch_tokens": b * t,
            "step_ms": round(step_s * 1e3, 1),
            "tokens_per_s": round(b * t / step_s),
            "loss": round(loss, 3),
            "model_tflops": round(flops / step_s / 1e12, 1),
            "mfu": round(flops / step_s / peak, 3) if peak else None,
        })
        emit(results)
    if not [r for r in results if "error" not in r]:
        raise RuntimeError(f"every sweep point failed: {results}")


def _probe_child():
    """Cheapest possible real-TPU liveness check: init the backend, run
    one tiny matmul."""
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    x = jnp.ones((256, 256), dtype=jnp.bfloat16)
    float((x @ x).astype(jnp.float32).sum())
    print(json.dumps({"tpu_available": True,
                      "device_kind": dev.device_kind}))


def bench_train_step_tpu(timeout_s: float = 780.0) -> dict:
    """Real-chip train-step throughput in a subprocess with a hard
    timeout (the axon tunnel can hang at backend init)."""
    return _tpu_subprocess("--train-child", timeout_s)


def bench_flash_attention_tpu(timeout_s: float = 240.0) -> dict:
    """Attempt the real-TPU Pallas kernel timing in a subprocess with a
    hard timeout (VERDICT r1 item 7: the axon tunnel is known to hang
    at backend init when dead — record the attempt either way so the
    gap is visible, never silent)."""
    return _tpu_subprocess("--flash-child", timeout_s)


def _with_retry(fn, *args) -> dict:
    """Run a TPU benchmark, retrying ONCE on any failure (VERDICT r2
    item 2: a transient tunnel blip must not wipe a benchmark)."""
    out = fn(*args)
    if out.get("tpu_available"):
        return out
    retry = fn(*args)
    if retry.get("tpu_available"):
        retry["retried"] = True
        return retry
    out["retried"] = True
    return out


def run_tpu_benchmarks() -> Tuple[dict, dict, dict]:
    """(probe, flash, train) — each independently bounded + retried.

    The probe (cheap backend-init + matmul, 120s) decides reachability
    ONCE; when it fails twice both benchmarks report unreachable in
    ~4 min total.  When it succeeds, flash and train each run in their
    OWN subprocess with their OWN retry — a flash-side failure can
    never erase the train-step evidence again (r2 shipped with
    train_step_tpu: skipped because the probe serialized them)."""
    probe = _with_retry(_tpu_subprocess, "--probe-child", 150.0)
    if not probe.get("tpu_available"):
        down = {"tpu_available": False, "attempted": True,
                "tpu_unreachable": True,
                "error": "liveness probe failed twice: "
                         + str(probe.get("error", "timeout"))}
        flash, train = dict(down), dict(down)
        # the tunnel dies for hours at a time (r02+r03 both hit it):
        # carry the committed last-known-good capture from the
        # tpu_watch daemon so a dead tunnel at bench time can never
        # erase real-chip evidence again (VERDICT r3 next-round #1)
        lkg = _last_known_good()
        if lkg:
            flash["last_known_good"] = lkg["flash"]
            train["last_known_good"] = lkg["train"]
            probe = dict(probe)
            probe["last_known_good"] = lkg["meta"]
        return probe, flash, train
    flash = _with_retry(bench_flash_attention_tpu)
    train = _with_retry(bench_train_step_tpu)
    return probe, flash, train


def _last_known_good() -> dict:
    """Summarize TPU_RESULTS.json (tools/tpu_watch.py capture) for
    embedding when the live tunnel is dead.  Everything is marked
    stale=true; the raw evidence stays in the committed artifact."""
    import os
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_RESULTS.json")
    try:
        with open(path, encoding="utf-8") as f:
            art = json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}
    meta = {"stale": True, "captured_utc": art.get("captured_utc"),
            "git_head": art.get("git_head"),
            "device_kind": art.get("device_kind"),
            "evidence": "TPU_RESULTS.json"}
    flash = dict((art.get("flash_attention") or {}).get("parsed") or {})
    train = dict((art.get("train_step") or {}).get("parsed") or {})
    flash.update(meta)
    train.update(meta)
    return {"meta": meta, "flash": flash, "train": train}


def _tpu_subprocess(flag: str, timeout_s: float) -> dict:
    import os
    import subprocess
    import sys
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)      # let the TPU platform load
    env.pop("XLA_FLAGS", None)
    child = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), flag],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=os.path.dirname(os.path.abspath(__file__)))
    try:
        out, err = child.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # a long sweep may time out mid-run: kill, DRAIN the pipes
        # (subprocess.run discards them on POSIX timeouts), and
        # salvage the child's last COMPLETE cumulative JSON line —
        # the kill can truncate the final line mid-write, so keep
        # scanning upward past a fragment
        child.kill()
        try:
            partial, _ = child.communicate(timeout=10)
        except Exception:  # noqa: BLE001
            partial = ""
        for line in reversed((partial or "").strip().splitlines()
                             or [""]):
            if line.startswith("{"):
                try:
                    salvaged = json.loads(line)
                    salvaged["timed_out_after_s"] = timeout_s
                    return salvaged
                except json.JSONDecodeError:
                    continue
        return {"tpu_available": False, "attempted": True,
                "tpu_unreachable": True,
                "error": f"TPU backend init exceeded {timeout_s:g}s "
                         f"(axon tunnel dead/hung)"}
    for line in reversed((out or "").strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return {"tpu_available": False, "attempted": True,
            "error": (err or out or "no output").strip()[-400:]}


def main():
    import gc

    def isolated(fn):
        """Collect garbage from the previous scenario before timing
        the next: a 5k-host object graph awaiting collection taxes an
        unrelated benchmark's allocations."""
        gc.collect()
        return fn()

    p50 = isolated(bench_gang_allocate_latency)
    utilization = isolated(bench_utilization_under_contention)
    gang_shape_s = isolated(bench_reference_gang_shape)
    agent_pps = isolated(bench_agent_scheduler_throughput)
    gangpreempt_p50 = isolated(bench_gangpreempt_latency)
    reclaim_s = isolated(bench_reclaim_convergence)
    scale = isolated(bench_5k_host_scale)
    scale10k = isolated(bench_10k_host_scale)
    scale20k = isolated(bench_20k_host_scale)
    scale40k = isolated(bench_40k_host_scale)
    net_acct = isolated(bench_net_accounting_overhead)
    failover = isolated(bench_failover_chaos)
    elastic = isolated(bench_elastic)
    goodput = isolated(bench_goodput)
    crash = isolated(bench_crash_recovery)
    wire = isolated(run_wire_benchmarks)
    probe, flash, train_tpu = run_tpu_benchmarks()
    print(json.dumps({
        "metric": "p50_gang_allocate_latency_256host_v5p1024",
        "value": round(p50, 4),
        "unit": "s",
        "vs_baseline": round(TARGET_P50_S / p50, 2),
        "extra": {
            "chip_utilization_under_contention": round(utilization, 4),
            "utilization_target": 0.95,
            "reference_gang_shape_1000pods_s": round(gang_shape_s, 4),
            "agent_scheduler_pods_per_s": round(agent_pps),
            "gangpreempt_p50_64host_displace_s": round(gangpreempt_p50, 4),
            "reclaim_convergence_2queue_flip_s": round(reclaim_s, 4),
            "scale_5k_hosts": scale,
            "scale_10k_hosts": scale10k,
            "scale_20k_hosts": scale20k,
            # the 40k row is a committed repeatable output now
            # (VERDICT r5 missing #3); `--scale-40k` regenerates it
            # standalone
            "scale_40k_hosts": scale40k,
            # DCN accounting subsystem overhead: per-tick cost at
            # 100+ pods/host (collector walk + full agent sync)
            "net_accounting": net_acct,
            # slice-failure chaos: kill a host in a 1k-host cluster,
            # MTTR p50/p95 with detect/drain/reschedule/resume
            # breakdown (`--failover` regenerates standalone ->
            # FAILOVER_r{N}.json)
            "failover": failover,
            # elastic gangs on a contended cluster: idle capacity
            # absorbed (utilization >= 0.99), shrink-latency +
            # migration-MTTR percentiles (`--elastic` regenerates
            # standalone -> ELASTIC_r{N}.json)
            "elastic": elastic,
            # goodput observatory: learned throughput vectors, ledger
            # reconciliation, grow gating (`--goodput` regenerates
            # standalone -> GOODPUT_r{N}.json)
            "goodput": goodput,
            # state-server kill -9 chaos: RTO + WAL replay + the
            # zero-acked-writes-lost / zero-mirror-divergence
            # invariants (`--crash` regenerates standalone ->
            # CRASH_r{N}.json)
            "crash_recovery": crash,
            # audit-trail-derived latency through the REAL multi-
            # process control plane (state server + leader-elected
            # scheduler + controllers), next to the in-process
            # headline above — the reference's apiserver-audit
            # methodology at this repo's own wire boundary
            "wire_gang_p50_s": wire.get("wire_gang_p50_s"),
            "wire_control_plane": {
                k: v for k, v in wire.items() if k != "scale"},
            "wire_scale_1k_hosts": wire.get("scale"),
            "inprocess_gang_p50_s": round(p50, 4),
            # where the cost curve bends: per-gang-member cycle cost
            # at each scale point (ms/member), from this run
            "scale_knee": _scale_knee(scale, scale10k, scale20k,
                                      scale40k),
            "tpu_probe": probe,
            "flash_attention_tpu": flash,
            "train_step_tpu": train_tpu,
            "trials": TRIALS,
            "cluster_hosts": 256 + 64 + 16,
        },
    }))


def wire_smoke():
    """Seconds-scale wire scenario (real processes, tiny shapes) so a
    tier-1 test can run the wire path on every commit and the wire
    benchmark can never silently rot.  Since round 6 it also
    round-trips one bandwidth usage report + violation event through
    the state server (the accounting subsystem's wire traffic is
    tier-1 guarded too).  Prints one JSON line with the same key
    names the full scenario reports."""
    out = run_wire_benchmarks(smoke=True)
    ok = "wire_gang_error" not in out and \
        "error" not in (out.get("scale") or {}) and \
        (out.get("usage_roundtrip") or {}).get(
            "violation_roundtrip_ok") is True
    print(json.dumps({"metric": "wire_smoke", "ok": ok, **out}))
    return 0 if ok and out.get("wire_gang_p50_s") is not None else 1


if __name__ == "__main__":
    import sys
    if "--flash-child" in sys.argv:
        _flash_child()
    elif "--train-child" in sys.argv:
        _train_child()
    elif "--probe-child" in sys.argv:
        _probe_child()
    elif "--wire-smoke" in sys.argv:
        sys.exit(wire_smoke())
    elif "--failover-smoke" in sys.argv:
        sys.exit(failover_smoke())
    elif "--elastic-smoke" in sys.argv:
        sys.exit(elastic_smoke())
    elif "--goodput-smoke" in sys.argv:
        sys.exit(goodput_smoke())
    elif "--serve-smoke" in sys.argv:
        sys.exit(serve_smoke())
    elif "--federation-smoke" in sys.argv:
        sys.exit(federation_smoke())
    elif "--timeline-smoke" in sys.argv:
        sys.exit(timeline_smoke())
    elif "--timeline" in sys.argv:
        # the fleet-wide causal-tracing row committed as
        # TIMELINE_r{N}.json: a follow-the-sun migration on a 3-region
        # fleet reconstructed from ONE episode ID — stitched span tree
        # complete, router decision + source drain + destination
        # placement + resume covered, segment sum reconciling with the
        # measured submit->running wall within 5%
        print(json.dumps({"metric": "fleet_causal_timeline",
                          **bench_timeline()}))
    elif "--federation-ha-smoke" in sys.argv:
        sys.exit(federation_ha_smoke())
    elif "--federation-ha" in sys.argv:
        # the router-HA row committed as FEDHA_r{N}.json: a 2-process
        # router replica set over 2 REAL regional planes — SIGKILL
        # the leaseholder mid-admission and mid-cutover, SIGSTOP
        # partition with a fenced stale-term write, and total router
        # vacancy, with the no-dual-placement invariant sampled at
        # 10Hz the whole run
        print(json.dumps({"metric": "federation_router_ha",
                          **bench_federation_ha()}))
    elif "--federation" in sys.argv:
        # the standalone federation-tier row committed as
        # FED_r{N}.json: 3 REAL regional control planes behind one
        # global queue — goodput/locality/price-routed placement vs
        # the silo baseline, follow-the-sun drain with checkpoint
        # resume continuity, whole-region SIGKILL with zero acked
        # state lost + global-requeue MTTR, and burst arbitrage of a
        # pending gang onto freed capacity
        print(json.dumps({"metric": "federation_3region_fleet",
                          **bench_federation()}))
    elif "--serve" in sys.argv:
        # the standalone serving-plane row committed as
        # SERVE_r{N}.json: diurnal day against the real process
        # plane, p99 SLO attainment >= 99%, topology-aware burst
        # preemption latencies, training floors held, victim ICI
        # adjacency audited from the scheduler's own hypernodes
        print(json.dumps({"metric": "serving_diurnal_day",
                          **bench_serving()}))
    elif "--goodput" in sys.argv:
        # the standalone goodput-observatory row committed as
        # GOODPUT_r{N}.json: learned throughput vectors within 10% of
        # simulator ground truth, ledger reconciles with wall-clock
        # allocated time within 5%, goodput-gated grow beats greedy
        print(json.dumps({"metric": "goodput_observatory_1k_hosts",
                          **bench_goodput()}))
    elif "--elastic-child" in sys.argv:
        _elastic_child()
    elif "--elastic" in sys.argv:
        # the standalone elastic chaos row committed as
        # ELASTIC_r{N}.json: contended 1k-host cluster, elastic jobs
        # absorb all idle capacity (utilization >= 0.99), shrink
        # latency + migration MTTR percentiles, and the dp-resize
        # loss-continuity dryrun
        out = bench_elastic()
        out["loss_continuity"] = _run_elastic_child()
        print(json.dumps({"metric": "elastic_gangs_1k_hosts", **out}))
    elif "--crash-smoke" in sys.argv:
        sys.exit(crash_smoke())
    elif "--chaos-smoke" in sys.argv:
        sys.exit(chaos_smoke())
    elif "--shard-smoke" in sys.argv:
        sys.exit(shard_smoke())
    elif "--replication-smoke" in sys.argv:
        sys.exit(replication_smoke())
    elif "--trace-smoke" in sys.argv:
        sys.exit(trace_smoke())
    elif "--trace" in sys.argv:
        # the standalone flight-recorder row committed as
        # TRACE_r{N}.json: 1k-host wire run, per-phase p50/p95 whose
        # segment sums reconcile with the measured gang e2e latency
        print(json.dumps({"metric": "trace_phase_breakdown_1k_hosts",
                          **bench_trace()}))
    elif "--crash" in sys.argv:
        # the standalone kill -9 durability row committed as
        # CRASH_r{N}.json: bind burst in flight, SIGKILL the state
        # server, restart from WAL — RTO p50/p95 + zero-acked-writes-
        # lost + zero-mirror-divergence
        print(json.dumps({"metric": "crash_recovery_1k_hosts",
                          **bench_crash_recovery()}))
    elif "--failover" in sys.argv:
        # the standalone chaos row committed as FAILOVER_r{N}.json:
        # kill a host in the 1k-host simulator, p50/p95 MTTR breakdown
        print(json.dumps({"metric": "failover_mttr_1k_hosts",
                          **bench_failover_chaos()}))
    elif "--scale-40k" in sys.argv:
        # the standalone 40k-host row (VERDICT r5 missing #3): same
        # probe main() embeds as extra.scale_40k_hosts, regenerable
        # without the full suite
        print(json.dumps({"metric": "scale_40k_hosts",
                          **bench_40k_host_scale()}))
    elif "--sweep-smoke" in sys.argv:
        sys.exit(sweep_smoke())
    elif "--scale-100k" in sys.argv:
        # the SCALE100K_r{N}.json artifact (ROADMAP item 3): 100k
        # hosts, idle + 8192-gang cycles per sweep backend with
        # flight-recorder waterfalls, the batched gang-commit row,
        # per-shard cycle rows under 2- and 4-shard planes,
        # per-worker-count entry rows bit-identical to serial
        # (disarmed + armed), the 40k idle-cycle acceptance row, and
        # the leader-group write-QPS scaling row (real OS servers)
        doc = {"metric": "scale_100k_hosts", **bench_scale_100k()}
        print("leader write-QPS scaling (3 groups vs 1)...",
              flush=True)
        doc["leader_write_qps"] = bench_leader_write_qps()
        print(json.dumps(doc))
    else:
        main()
