"""Chaos with leader election: 2 schedulers contend on a lease; the
LEADER is SIGKILLed every ~20s; the follower must take over.

Five-minute run: gang jobs stream continuously, the current lease
holder is identified via GET /leases and SIGKILLed, the follower must
acquire the lease (bounded by the 1.5s TTL) and keep scheduling; the
killed replica restarts and rejoins as follower.

Round-4 result on the dev machine: 393/393 jobs Completed across 13
leader SIGKILLs, follower takeover in 1.4-2.2s each time (lease TTL
1.5s), zero chip overcommit.

A thin schedule over tools/chaoslib.py (shared proxy/zoo/audit
plumbing).

Usage:  python tools/chaos_leader.py     # logs to /tmp/chaos2/
"""
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools import chaoslib  # noqa: E402

port = chaoslib.free_port()
url = f"http://127.0.0.1:{port}"
zoo = chaoslib.ProcessZoo("/tmp/chaos2")
zoo.spawn_server(port)
chaoslib.wait_server(url)
zoo.spawn_plane("ctrl", url, "controllers")


def spawn_sched(name):
    zoo.spawn_plane(name, url, "scheduler", "--leader-elect",
                    "--holder", name, "--lease-ttl", "1.5")


spawn_sched("s1")
spawn_sched("s2")

from volcano_tpu.cache.remote_cluster import RemoteCluster  # noqa: E402

c = RemoteCluster(url)
chaoslib.seed_slices(c, ("sa", "sb"))

rng = random.Random(7)
submitted = kills = 0
takeovers = []
t_end = time.time() + 300
last_kill = time.time()
i = 0
while time.time() < t_end:
    n = rng.choice((1, 2, 4))
    try:
        c.add_vcjob(chaoslib.gang_job(f"le-{i}", n))
        submitted += 1
    except Exception as e:  # noqa: BLE001
        print("submit failed:", e, flush=True)
    i += 1
    time.sleep(rng.uniform(0.4, 1.0))
    if time.time() - last_kill > 20:
        ldr = chaoslib.leader(url)
        if ldr in ("s1", "s2"):
            zoo.kill9(ldr)
            kills += 1
            # wait for the OTHER one to take the lease
            other = "s2" if ldr == "s1" else "s1"
            t0 = time.time()
            while time.time() - t0 < 15:
                if chaoslib.leader(url) == other:
                    takeovers.append(round(time.time() - t0, 2))
                    break
                time.sleep(0.2)
            spawn_sched(ldr)          # restart the killed one
        last_kill = time.time()

time.sleep(20)
c.resync()
print(json.dumps({
    "submitted": submitted, "leader_kills": kills,
    "takeover_s": takeovers,
    "phases": chaoslib.phase_counts(c),
    "overcommitted_nodes": chaoslib.overcommit_audit(c)}))
zoo.terminate_all()
