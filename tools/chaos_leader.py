"""Chaos with leader election: 2 schedulers contend on a lease; the
LEADER is SIGKILLed every ~20s; the follower must take over.

Five-minute run: gang jobs stream continuously, the current lease
holder is identified via GET /leases and SIGKILLed, the follower must
acquire the lease (bounded by the 1.5s TTL) and keep scheduling; the
killed replica restarts and rejoins as follower.

Round-4 result on the dev machine: 393/393 jobs Completed across 13
leader SIGKILLs, follower takeover in 1.4-2.2s each time (lease TTL
1.5s), zero chip overcommit.

Usage:  python tools/chaos_leader.py     # logs to /tmp/chaos2/
"""
import json, os, random, signal, socket, subprocess, sys, time, urllib.request
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")

def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0)); return s.getsockname()[1]

port = free_port()
url = f"http://127.0.0.1:{port}"
server = subprocess.Popen(
    [sys.executable, "-m", "volcano_tpu.server", "--port", str(port),
     "--tick-period", "0.2"], env=env, cwd=REPO,
    stdout=open("/tmp/chaos2/server.log", "w"), stderr=subprocess.STDOUT)
time.sleep(2)
ctrl = subprocess.Popen(
    [sys.executable, "-m", "volcano_tpu", "--cluster-url", url,
     "--components", "controllers", "--period", "0.2"], env=env, cwd=REPO,
    stdout=open("/tmp/chaos2/ctrl.log", "w"), stderr=subprocess.STDOUT)

scheds = {}
def spawn_sched(name):
    scheds[name] = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu", "--cluster-url", url,
         "--components", "scheduler", "--period", "0.2",
         "--leader-elect", "--holder", name, "--lease-ttl", "1.5"],
        env=env, cwd=REPO,
        stdout=open(f"/tmp/chaos2/{name}.log", "a"), stderr=subprocess.STDOUT)

spawn_sched("s1")
spawn_sched("s2")

def leader():
    try:
        with urllib.request.urlopen(url + "/leases", timeout=2) as r:
            leases = json.loads(r.read())
        return leases.get("scheduler", {}).get("holder")
    except Exception:
        return None

from volcano_tpu.cache.remote_cluster import RemoteCluster
from volcano_tpu.api.devices.tpu.topology import slice_for
from volcano_tpu.simulator import slice_nodes
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import RUN_TICKS_ANNOTATION

c = RemoteCluster(url)
for sname in ("sa", "sb"):
    for node in slice_nodes(slice_for(sname, "v5e-16"), dcn_pod="d0"):
        c.put_object("node", node)

rng = random.Random(7)
submitted = kills = 0
takeovers = []
t_end = time.time() + 300
last_kill = time.time()
i = 0
while time.time() < t_end:
    n = rng.choice((1, 2, 4))
    job = VCJob(name=f"le-{i}", min_available=n,
                tasks=[TaskSpec(name="worker", replicas=n,
                                template=make_pod("t", requests={"cpu": 4, TPU: 4},
                                                  annotations={RUN_TICKS_ANNOTATION: "3"}))],
                plugins={"jax": [], "svc": []})
    try:
        c.add_vcjob(job); submitted += 1
    except Exception as e:
        print("submit failed:", e, flush=True)
    i += 1
    time.sleep(rng.uniform(0.4, 1.0))
    if time.time() - last_kill > 20:
        ldr = leader()
        if ldr in scheds:
            os.kill(scheds[ldr].pid, signal.SIGKILL)
            scheds[ldr].wait()
            kills += 1
            # wait for the OTHER one to take the lease
            other = "s2" if ldr == "s1" else "s1"
            t0 = time.time()
            while time.time() - t0 < 15:
                if leader() == other:
                    takeovers.append(round(time.time() - t0, 2))
                    break
                time.sleep(0.2)
            spawn_sched(ldr)          # restart the killed one
        last_kill = time.time()

time.sleep(20)
c.resync()
phases = {}
for j in c.vcjobs.values():
    ph = getattr(j.phase, "value", str(j.phase))
    phases[ph] = phases.get(ph, 0) + 1
overcommit = []
node_chips = {}
for p in c.pods.values():
    if p.node_name and getattr(p.phase, "value", "") in ("Running", "Bound"):
        node_chips[p.node_name] = node_chips.get(p.node_name, 0) + \
            p.resource_requests().get(TPU)
overcommit = [(n, u) for n, u in node_chips.items() if u > 4.01]
print(json.dumps({"submitted": submitted, "leader_kills": kills,
                  "takeover_s": takeovers, "phases": phases,
                  "overcommitted_nodes": overcommit}))
for p in [server, ctrl] + list(scheds.values()):
    p.terminate()
