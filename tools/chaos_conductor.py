"""Randomized gray-failure conductor: mixed wire+disk+clock fault
schedules against the REAL process plane, with the safety invariants
checked continuously.

Where tools/chaos.py kills processes (the clean failure), this drives
the faults that merely make infrastructure SICK — dropped acks,
duplicated retries, injected 503s/resets/reorders/trickle on the
wire; ENOSPC and lying fsyncs on the WAL (the read-only degrade +
heal path); wall-clock jumps under live leases — all drawn
deterministically from ONE seed (volcano_tpu/faults.py), so any
failing run is replayed exactly:

    python tools/chaos_conductor.py --seed 7 --duration 30

The invariants, checked while the faults fly and audited at the end:

    acked_durable     every acked vcjob create survives to the final
                      snapshot (and every reboot in between)
    rv_monotonic      the durable revision never goes backwards —
                      polled across degrade, heal, and reboots
    no_overcommit     no node's bound/running pods exceed its chips
    no_double_bind    no pod silently moves nodes while bound/running
                      (same uid, no drain in between)
    resume_floor      failover.volcano-tpu.io/resume-step never
                      rewinds (elastic/failover churn on a long gang)
    goodput_monotonic the folded goodput ledger never regresses
                      (progress files -> real agents -> wire -> fold)
    serving_monotonic (``serving`` class) the folded serving request
                      ledger never regresses while the autoscaler +
                      elastic controller churn the replica group
                      (stats files -> real agents -> wire -> fold)
    mirror_converged  a live mirror that watched THROUGH all faults
                      matches the server's snapshot exactly at the end
    clock_lease       the lease holder stays stable across the
                      injected wall jump (monotonic-clock leases)
    crc_refusal       a mid-WAL bit flip is detected by CRC at the
                      next boot and REFUSED (exit 3), not silently
                      replayed; restoring the byte boots cleanly
    bounded_staleness (``replication`` class) no follower ever SERVES
                      an rv it has not durably applied, its visible
                      horizon never regresses, and its advertised lag
                      is truthful — audited from outside against
                      /watch + /durability

The ``replication`` class runs the plane against a 3-replica state
server (server/replication.py): the fault-armed leader plus two
WAL-shipping followers, one behind a partition-able proxy.  Scheduled
faults: a leader<->follower shipping partition, a shipping-lag window
(delay on /wal), low-probability shipped-record corruption (refused
by the follower's per-record CRC), and a late leader SIGKILL — a
follower must promote without losing an acked write, the multi-
endpoint client must re-route, and the deposed leader must rejoin by
full re-sync.  The matrix run appends the read-QPS scaling row
(leader-only vs follower reads under write churn).

``--matrix N`` runs seeds 1..N and writes the committed artifact
(CHAOS_r{NN}.json shape): per-fault-class recovery latencies and the
invariant pass matrix.  ``--print-schedule`` dumps the derived plan
without booting anything (reproducibility is testable offline).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools import chaoslib  # noqa: E402

DEFAULT_CLASSES = "wire,disk,clock"
FLOOR_STEP = 500           # checkpoint floor stamped on the long gang


def build_plan(seed: int, duration: float, classes) -> dict:
    """Derive the deterministic fault plan for one run.  Everything —
    probabilities, window placement, delay magnitudes, clock offset —
    comes off random.Random(seed), so the same seed always produces
    the same plan doc (tested offline via --print-schedule)."""
    import random
    rng = random.Random(seed)
    rules = []
    if "wire" in classes:
        d = duration
        rules += [
            # the ack-lost case: committed, response dropped
            {"site": "server", "kind": "drop_response", "route": "*",
             "prob": round(rng.uniform(0.02, 0.05), 3), "until_s": d},
            {"site": "server", "kind": "drop_request", "route": "*",
             "prob": round(rng.uniform(0.01, 0.03), 3), "until_s": d},
            {"site": "server", "kind": "delay", "route": "*",
             "prob": round(rng.uniform(0.05, 0.10), 3),
             "ms": round(rng.uniform(20, 80), 1), "until_s": d},
            {"site": "server", "kind": "duplicate", "route": "*",
             "prob": round(rng.uniform(0.02, 0.05), 3), "until_s": d},
            {"site": "server", "kind": "reorder", "route": "*",
             "prob": round(rng.uniform(0.02, 0.04), 3),
             "ms": 120.0, "until_s": d},
            {"site": "server", "kind": "http_503", "route": "*",
             "prob": round(rng.uniform(0.02, 0.04), 3), "until_s": d},
            {"site": "server", "kind": "reset", "route": "*",
             "prob": round(rng.uniform(0.01, 0.03), 3), "until_s": d},
            {"site": "server", "kind": "trickle", "route": "*",
             "prob": round(rng.uniform(0.005, 0.02), 3),
             "ms": 10.0, "until_s": d},
        ]
    windows = {}
    if "disk" in classes:
        # one ENOSPC brownout and one lying-fsync episode, placed so
        # both end well before the settle phase
        w0 = round(duration * rng.uniform(0.15, 0.25), 2)
        w1 = round(w0 + min(4.0, duration * 0.12), 2)
        rules.append({"site": "disk", "kind": "enospc_append",
                      "after_s": w0, "until_s": w1})
        windows["enospc"] = (w0, w1)
        f0 = round(duration * rng.uniform(0.45, 0.55), 2)
        f1 = round(f0 + min(3.0, duration * 0.08), 2)
        rules.append({"site": "disk", "kind": "eio_fsync",
                      "after_s": f0, "until_s": f1})
        windows["eio"] = (f0, f1)
    if "clock" in classes:
        j0 = round(duration * rng.uniform(0.65, 0.75), 2)
        j1 = round(min(duration * 0.9, j0 + duration * 0.15), 2)
        off = rng.choice((-1, 1)) * rng.uniform(600.0, 3600.0)
        rules.append({"site": "clock", "kind": "wall_jump",
                      "after_s": j0, "until_s": j1,
                      "offset_s": round(off, 1)})
        windows["clock_jump"] = (j0, j1)
    slice_kill_at = None
    if "slice" in classes:
        slice_kill_at = round(duration * rng.uniform(0.3, 0.45), 2)
    repl = None
    if "replication" in classes:
        # the replication fault schedule (drawn AFTER the classic
        # classes so their plans stay byte-identical across versions):
        # a leader<->follower partition, a shipping-lag window, low-
        # probability shipped-record corruption all run, and a leader
        # SIGKILL late enough that the classic disk/clock windows (on
        # the original leader) complete first
        p0 = round(duration * rng.uniform(0.26, 0.32), 2)
        p1 = round(p0 + duration * rng.uniform(0.10, 0.14), 2)
        l0 = round(duration * rng.uniform(0.52, 0.58), 2)
        l1 = round(l0 + duration * rng.uniform(0.08, 0.12), 2)
        rules.append({"site": "server", "kind": "delay",
                      "route": "/wal", "prob": 1.0,
                      "ms": round(rng.uniform(150, 400), 1),
                      "after_s": l0, "until_s": l1})
        rules.append({"site": "server", "kind": "corrupt_ship",
                      "route": "/wal",
                      "prob": round(rng.uniform(0.02, 0.05), 3),
                      "max_injections": 3, "until_s": duration})
        repl = {"partition": (p0, p1),
                "kill_leader_at": round(duration *
                                        rng.uniform(0.78, 0.85), 2)}
        windows["repl_partition"] = repl["partition"]
        windows["repl_lag"] = (l0, l1)
    region = None
    if "region" in classes:
        # whole-region loss under the federation tier: the kill lands
        # mid-run, after acked progress has climbed (drawn AFTER every
        # other class so their plans stay byte-identical)
        region = {"victim": "rb",
                  "kill_at": round(duration * rng.uniform(0.35, 0.55),
                                   2)}
        windows["region_kill"] = (region["kill_at"],
                                  region["kill_at"])
    router = None
    if "router" in classes:
        # federation-router replica-set failover: SIGKILL the
        # leaseholder mid-admission, SIGKILL it again mid-cutover,
        # then SIGSTOP it (the partition / GC-pause model) late in
        # the run and replay a write stamped with the deposed term
        # (drawn AFTER every other class so their plans stay
        # byte-identical)
        router = {
            "kill_admission_at":
                round(duration * rng.uniform(0.18, 0.28), 2),
            "kill_cutover_at":
                round(duration * rng.uniform(0.48, 0.58), 2),
            "partition_at":
                round(duration * rng.uniform(0.75, 0.85), 2),
        }
        for k, at in router.items():
            windows["router_" + k[:-3]] = (at, at)
    return {"seed": seed, "rules": rules, "windows": windows,
            "slice_kill_at": slice_kill_at, "replication": repl,
            "region": region, "router": router}


def _iann(ann, key, default=0):
    try:
        return int(ann.get(key, default) or default)
    except (TypeError, ValueError):
        return default


class InvariantTracker:
    """Continuous safety checks over the conductor's live mirror +
    the server's /durability endpoint."""

    def __init__(self, cluster, url: str, floor_key: str,
                 repl: dict = None, serving_key: str = ""):
        self.c = cluster
        self.url = url
        self.floor_key = floor_key
        self.serving_key = serving_key
        # replication topology, kept current by the conductor as roles
        # change: {"leader": url, "followers": [urls]}.  None = the
        # classic single-server plane.
        self.repl = repl
        self.violations = []
        self.max_rv = 0
        self.max_resume = 0
        self.max_alloc = 0.0
        self.resume_seen = False
        self.goodput_seen = False
        self.serving_seen = False
        self.max_serving_requests = 0.0
        self._pod_nodes = {}
        self._max_visible = {}          # replica url -> max visible_rv
        self._prev_leader_visible = 0
        self.follower_lag_max = {}      # replica url -> max lag_s seen
        self.staleness_checks = 0

    def note(self, inv: str, detail: str):
        if any(v["invariant"] == inv and v["detail"] == detail
               for v in self.violations):
            return          # same finding, next poll — log once
        self.violations.append({"invariant": inv, "detail": detail})
        print(f"INVARIANT VIOLATION [{inv}]: {detail}", flush=True)

    def _node_forensics(self, node: str) -> str:
        return "; ".join(
            f"{p.key} uid={getattr(p, 'uid', '')[:8]} "
            f"phase={getattr(p.phase, 'value', p.phase)} "
            f"owner={getattr(p, 'owner', '')[:8]}"
            for p in self.c.pods.values() if p.node_name == node)

    def poll_replication(self):
        """The tenth invariant — bounded staleness: no follower ever
        SERVES an rv it has not durably applied (what /watch returns
        is audited against what /durability admits), a replica's
        visible horizon never regresses, and the advertised lag is
        truthful (a follower claiming to be caught up must hold at
        least what the leader had visible a poll ago)."""
        if not self.repl:
            return
        for furl in self.repl.get("followers", []):
            since = self._max_visible.get(furl, 0)
            w = chaoslib.http_json(
                furl + f"/watch?since={since}&timeout=0", timeout=2)
            d = chaoslib.http_json(furl + "/durability", timeout=2)
            if not d:
                continue
            self.staleness_checks += 1
            vis = int(d.get("visible_rv") or 0)
            synced = int(d.get("synced_rv") or 0)
            rep = d.get("replication") or {}
            applied = int(rep.get("applied_rv") or 0)
            if w is not None and not w.get("resync") and \
                    int(w.get("rv") or 0) > max(applied, vis):
                # the durability doc was read AFTER the watch: a
                # served rv past the admitted applied horizon means
                # the follower served state it cannot prove durable
                self.note("bounded_staleness",
                          f"{furl} served rv {w.get('rv')} beyond "
                          f"durably applied {applied}")
            if vis > synced:
                self.note("bounded_staleness",
                          f"{furl} visible_rv {vis} beyond fsync "
                          f"horizon {synced}")
            prev = self._max_visible.get(furl, 0)
            if vis < prev:
                self.note("bounded_staleness",
                          f"{furl} visible_rv regressed {prev} -> "
                          f"{vis}")
            self._max_visible[furl] = max(prev, vis)
            lag = float(rep.get("lag_s") or 0.0)
            if lag < 0:
                self.note("bounded_staleness",
                          f"{furl} advertised negative lag {lag}")
            self.follower_lag_max[furl] = max(
                self.follower_lag_max.get(furl, 0.0), lag)
            if lag < 0.25 and self._prev_leader_visible and \
                    applied < self._prev_leader_visible:
                self.note("bounded_staleness",
                          f"{furl} claims lag {lag}s but applied rv "
                          f"{applied} trails the leader's horizon "
                          f"{self._prev_leader_visible} from the "
                          "previous poll — the advertised lag lies")
        leader_url = self.repl.get("leader")
        if leader_url:
            d = chaoslib.http_json(leader_url + "/durability",
                                   timeout=2)
            if d:
                self._prev_leader_visible = int(
                    d.get("visible_rv") or 0)

    def poll(self):
        self.poll_replication()
        dur = chaoslib.http_json(self.url + "/durability", timeout=2)
        if dur:
            rv = int(dur.get("visible_rv") or 0)
            if rv < self.max_rv:
                self.note("rv_monotonic",
                          f"visible_rv {rv} < seen {self.max_rv}")
            self.max_rv = max(self.max_rv, rv)
        over = chaoslib.overcommit_audit(self.c)
        if over:
            # the mirror can run seconds stale under injected faults
            # (that is the point of them): only a double-booking the
            # SERVER's own snapshot confirms is a safety violation.
            # Unconfirmable (snapshot 503 during a degrade window) =
            # recheck next poll; staleness that truth refutes = noise.
            import types
            try:
                truth = chaoslib.snapshot_stores(self.url)
                confirmed = chaoslib.overcommit_audit(
                    types.SimpleNamespace(pods=truth["pod"]))
            except Exception:  # noqa: BLE001 — degrade window
                confirmed = None
            if confirmed:
                self.note("no_overcommit",
                          f"{confirmed} :: " + " | ".join(
                              self._node_forensics(n)
                              for n, _u in confirmed))
        for p in list(self.c.pods.values()):
            ph = getattr(p.phase, "value", "")
            key = (p.key, getattr(p, "uid", ""))
            if ph in ("Bound", "Running") and p.node_name:
                prev = self._pod_nodes.get(key)
                if prev is not None and prev != p.node_name:
                    self.note("no_double_bind",
                              f"{p.key} moved {prev} -> "
                              f"{p.node_name} while {ph}")
                self._pod_nodes[key] = p.node_name
            elif ph in ("Releasing", "Succeeded", "Failed"):
                self._pod_nodes.pop(key, None)
        pg = self.c.podgroups.get(self.floor_key)
        if pg is not None:
            resume = _iann(pg.annotations,
                           "failover.volcano-tpu.io/resume-step", -1)
            if resume >= 0:
                self.resume_seen = True
                if resume < self.max_resume:
                    self.note("resume_floor",
                              f"resume-step {resume} < seen "
                              f"{self.max_resume}")
                if resume < FLOOR_STEP:
                    self.note("resume_floor",
                              f"resume-step {resume} below the "
                              f"stamped checkpoint {FLOOR_STEP}")
                self.max_resume = max(self.max_resume, resume)
            from volcano_tpu.api import goodput as gapi
            alloc = gapi.ann_float(pg.annotations,
                                   gapi.PG_ALLOCATED_S_ANNOTATION)
            if alloc > 0:
                self.goodput_seen = True
                if alloc + 1e-6 < self.max_alloc:
                    self.note("goodput_monotonic",
                              f"allocated ledger {alloc} < seen "
                              f"{self.max_alloc} (pg uid="
                              f"{getattr(pg, 'uid', '')[:8]} ann="
                              f"{dict(pg.annotations)})")
                self.max_alloc = max(self.max_alloc, alloc)
        if self.serving_key:
            spg = self.c.podgroups.get(self.serving_key)
            if spg is not None:
                from volcano_tpu.api import serving as sapi
                reqs = sapi.ann_float(spg.annotations,
                                      sapi.PG_REQUESTS_ANNOTATION)
                if reqs > 0:
                    self.serving_seen = True
                    if reqs + 1e-6 < self.max_serving_requests:
                        self.note("serving_monotonic",
                                  f"request ledger {reqs} < seen "
                                  f"{self.max_serving_requests} (pg "
                                  f"uid={getattr(spg, 'uid', '')[:8]})")
                    self.max_serving_requests = max(
                        self.max_serving_requests, reqs)

    def summary(self) -> dict:
        failed = {v["invariant"] for v in self.violations}
        return {
            "violations": self.violations,
            "passed": {inv: inv not in failed for inv in (
                "acked_durable", "rv_monotonic", "no_overcommit",
                "no_double_bind", "resume_floor", "goodput_monotonic",
                "serving_monotonic", "mirror_converged", "crc_refusal",
                "clock_lease", "bounded_staleness")},
            "resume_floor_exercised": self.resume_seen,
            "goodput_ledger_exercised": self.goodput_seen,
            "serving_ledger_exercised": self.serving_seen,
            "staleness_checks": self.staleness_checks,
        }


def run_region_kill(seed: int, duration: float, classes,
                    logdir: str = "") -> dict:
    """The ``region`` fault class: whole-region loss under the
    federation tier.  Boots bench.py's 2-region process fleet (each
    region a REAL server + controllers + elastic scheduler plane, one
    global store, the router reconciling over the wire), lets acked
    training progress climb, then SIGKILLs every process of the
    victim region at the seeded kill time.  Invariants:

        requeued_globally   every gang admitted to the dead region is
                            re-admitted into a survivor and reaches
                            Running (MTTR reported)
        region_lost         the registry record flips to state=lost
        resume_floor        the globally folded resume step never
                            rewinds — before, across, or after the
                            kill
        acked_durable       the re-admitted copy resumes at >= the
                            last step acked to the global store
                            before the kill (zero acked state lost)
        survivor_untouched  the surviving region's resident gang
                            stays Running through the whole episode
    """
    import bench
    from volcano_tpu.api.slicehealth import RESUME_STEP_ANNOTATION
    classes = set(classes.split(",")) if isinstance(classes, str) \
        else set(classes)
    sched = build_plan(seed, duration, classes)
    kill_at = sched["region"]["kill_at"]
    print(f"chaos conductor: seed={seed} duration={duration}s "
          f"classes={sorted(classes)} (federation fleet, "
          f"region kill at t+{kill_at}s)", flush=True)
    violations = []

    def note(inv: str, detail: str):
        violations.append({"invariant": inv, "detail": detail})
        print(f"INVARIANT VIOLATION [{inv}]: {detail}", flush=True)

    t0 = time.monotonic()
    fleet = bench._FederationFleet(
        (("ra", 2, 1.0), ("rb", 1, 0.7)), ttl=2.0)
    g = fleet.g
    mttr = acked = resume = -1
    try:
        g.add_vcjob(bench._fed_job("anchor", 1, locality="ra"))
        g.add_vcjob(bench._fed_job("roamer", 1, locality="rb"))
        try:
            chaoslib.wait_for(
                lambda: bench._fed_running(g, "anchor", "ra")
                and bench._fed_running(g, "roamer", "rb"), 60,
                "locality-routed admission")
        except AssertionError as e:
            note("requeued_globally", f"admission never settled: {e}")
            raise
        # acked progress climbs until the kill window; the globally
        # folded floor must never rewind while the faults fly
        step, floor = 1000, 0
        while time.monotonic() - t0 < kill_at:
            bench._fed_stamp_and_fold(fleet, "rb", "roamer", step)
            f = bench._fed_folded_step(g, "roamer")
            if f < floor:
                note("resume_floor",
                     f"folded step rewound {floor} -> {f}")
            floor = max(floor, f)
            step += 500
            time.sleep(0.3)
        acked = floor
        fleet.kill_region("rb")
        t_kill = time.monotonic()
        try:
            chaoslib.wait_for(
                lambda: bench._fed_running(g, "roamer", "ra"), 90,
                "global requeue into the survivor")
            mttr = round(time.monotonic() - t_kill, 3)
        except AssertionError:
            note("requeued_globally",
                 f"gang never re-ran after the region kill "
                 f"({bench._fed_view(g, 'roamer')})")
        if g.regions.get("rb", {}).get("state") != "lost":
            note("region_lost",
                 f"registry state: {g.regions.get('rb', {})}")
        folded = bench._fed_folded_step(g, "roamer")
        if folded < acked:
            note("resume_floor",
                 f"fold rewound across the kill: {acked} -> {folded}")
        copy = fleet.clients["ra"].vcjobs.get("default/roamer")
        resume = int(copy.annotations.get(RESUME_STEP_ANNOTATION, 0)
                     ) if copy is not None else -1
        if resume < acked:
            note("acked_durable",
                 f"survivor resumes at {resume} < acked {acked}")
        if not bench._fed_running(g, "anchor", "ra"):
            note("survivor_untouched",
                 f"anchor left Running: {bench._fed_view(g, 'anchor')}")
        if fleet.sync_errors:
            note("router_sync", "; ".join(fleet.sync_errors[-3:]))
    finally:
        fleet.shutdown()
    result = {"seed": seed, "duration_s": duration,
              "classes": sorted(classes),
              "windows": sched["windows"],
              "region_kill_at_s": kill_at,
              "region_mttr_s": mttr,
              "acked_step_before_kill": acked,
              "resume_step_in_survivor": resume,
              "violations": violations, "ok": not violations}
    print(f"REPRODUCE: python tools/chaos_conductor.py "
          f"--seed {seed} --duration {duration:g} "
          f"--classes {','.join(sorted(classes))}", flush=True)
    return result


# the failover MTTR budget for the router class: 2x the whole-region
# loss MTTR measured in FED_r19.json (~6.7s) — losing ONE router out
# of a replica set must never cost more than twice losing a region
ROUTER_MTTR_BOUND_S = 13.4


def run_router_failover(seed: int, duration: float, classes,
                        logdir: str = "") -> dict:
    """The ``router`` fault class: the federation router replica set
    under crash + partition fire.  Boots bench.py's 2-region process
    fleet with TWO router OS processes contending for the term-fenced
    lease, then fires the seeded schedule: SIGKILL the leaseholder
    right after a gang enters the global queue, SIGKILL its successor
    mid-cutover (source drained, evacuating-to stamped), and SIGSTOP
    the next one so a standby takes over while the old holder still
    believes it leads.  Invariants:

        no_dual_placement        a gang is never RUNNING in two
                                 regions at once (sampled at 10Hz
                                 through every region's live mirror)
        cutover_exactly_once     the adopted migration lands exactly
                                 one destination copy, reaps the
                                 source, and counts ONE migration
        acked_admissions_durable every acked admission reaches
                                 Running despite the crashes, and the
                                 globally folded step floor never
                                 rewinds
        stale_fence_refused      a write stamped with the deposed
                                 holder's term is refused 409 by the
                                 regional plane and counted on
                                 /fences
        failover_mttr            every kill/partition-to-recovery
                                 interval stays under
                                 ROUTER_MTTR_BOUND_S
    """
    import threading

    import bench
    from volcano_tpu.api import federation as fedapi
    from volcano_tpu.api.slicehealth import RESUME_STEP_ANNOTATION
    classes = set(classes.split(",")) if isinstance(classes, str) \
        else set(classes)
    sched = build_plan(seed, duration, classes)
    plan = sched["router"]
    # diagnostics go to stderr: bench --federation-ha embeds this run
    # in-process and its stdout must stay one parseable JSON document
    print(f"chaos conductor: seed={seed} duration={duration}s "
          f"classes={sorted(classes)} (federation fleet, 2-router "
          f"replica set; kills at t+{plan['kill_admission_at']}s / "
          f"t+{plan['kill_cutover_at']}s, partition at "
          f"t+{plan['partition_at']}s)", file=sys.stderr, flush=True)
    violations = []

    def note(inv: str, detail: str):
        violations.append({"invariant": inv, "detail": detail})
        print(f"INVARIANT VIOLATION [{inv}]: {detail}", flush=True)

    t0 = time.monotonic()
    fleet = bench._FederationFleet(
        (("ra", 2, 1.0), ("rb", 2, 0.7)), ttl=4.0,
        arbitrage_after=60.0, router_procs=2, lease_ttl=2.0)
    g = fleet.g
    dual, stop = [], threading.Event()
    sampler = bench._fed_dual_sampler(
        fleet, ("anchor", "j-adm", "roamer"), dual, stop)
    mttrs = {}
    terms = []
    step, floor = 1000, 0
    fenced_count = 0

    def pump():
        # acked progress keeps climbing on the survivor gang; the
        # globally folded floor must never rewind across failovers
        nonlocal step, floor
        bench._fed_stamp_and_fold(fleet, "ra", "anchor", step)
        f = bench._fed_folded_step(g, "anchor")
        if f < floor:
            note("acked_admissions_durable",
                 f"folded step rewound {floor} -> {f}")
        floor = max(floor, f)
        step += 500

    def sleep_until(at):
        while time.monotonic() - t0 < at:
            pump()
            time.sleep(0.3)

    try:
        chaoslib.wait_for(lambda: fleet.leaseholder() is not None,
                          30, "router lease acquisition")
        terms.append(fleet.router_term())
        chaoslib.wait_for(
            lambda: bench._fed_regions_ready(g, ("ra", "rb")), 30,
            "region capacity folded before the first submit")
        g.add_vcjob(bench._fed_job("anchor", 1, locality="ra"))
        try:
            chaoslib.wait_for(
                lambda: bench._fed_running(g, "anchor", "ra"), 60,
                "locality-routed admission")
        except AssertionError as e:
            note("acked_admissions_durable",
                 f"admission never settled: {e}")
            raise

        # -- SIGKILL the leaseholder mid-admission -------------------
        sleep_until(plan["kill_admission_at"])
        h0 = fleet.leaseholder()
        g.add_vcjob(bench._fed_job("j-adm", 1, locality="rb"))
        fleet.kill_router(h0)
        t_kill = time.monotonic()
        try:
            chaoslib.wait_for(
                lambda: bench._fed_running(g, "j-adm", "rb"), 60,
                "adoption of the in-flight admission")
            mttrs["kill_admission"] = round(
                time.monotonic() - t_kill, 3)
        except AssertionError:
            note("acked_admissions_durable",
                 f"gang never ran after the leaseholder SIGKILL "
                 f"({bench._fed_view(g, 'j-adm')})")
        terms.append(fleet.router_term())
        copies = bench._fed_copy_regions(fleet, "j-adm")
        if copies != ["rb"]:
            note("no_dual_placement", f"j-adm copies: {copies}")
        fleet.spawn_router()        # keep the replica set at 2

        # -- SIGKILL the leaseholder mid-cutover ---------------------
        sleep_until(plan["kill_cutover_at"])
        g.add_vcjob(bench._fed_job("roamer", 1, locality="rb"))
        chaoslib.wait_for(
            lambda: bench._fed_running(g, "roamer", "rb"), 60,
            "roamer admission")
        acked = step
        bench._fed_stamp_and_fold(fleet, "rb", "roamer", acked)
        gj = g.vcjobs["default/roamer"]
        gj.annotations[fedapi.FED_EVACUATE_ANNOTATION] = "ra"
        g.update_vcjob(gj)
        chaoslib.wait_for(
            lambda: g.vcjobs["default/roamer"].annotations.get(
                fedapi.FED_EVACUATING_TO_ANNOTATION) == "ra", 60,
            "evacuation start")
        fleet.kill_router(fleet.leaseholder())
        t_kill = time.monotonic()
        try:
            chaoslib.wait_for(
                lambda: bench._fed_running(g, "roamer", "ra"), 90,
                "adopted cutover")
            mttrs["kill_cutover"] = round(
                time.monotonic() - t_kill, 3)
        except AssertionError:
            note("cutover_exactly_once",
                 f"cutover never completed "
                 f"({bench._fed_view(g, 'roamer')})")
        try:
            chaoslib.wait_for(
                lambda: bench._fed_copy_regions(fleet, "roamer") ==
                ["ra"], 60, "source residual reap")
        except AssertionError:
            note("cutover_exactly_once",
                 f"roamer copies: "
                 f"{bench._fed_copy_regions(fleet, 'roamer')}")
        terms.append(fleet.router_term())
        gj = g.vcjobs["default/roamer"]
        if fedapi.migration_count(gj) != 1:
            note("cutover_exactly_once",
                 f"migrations={fedapi.migration_count(gj)} "
                 f"(want exactly 1)")
        racopy = fleet.clients["ra"].vcjobs.get("default/roamer")
        rstep = int(racopy.annotations.get(
            RESUME_STEP_ANNOTATION, 0) or 0) if racopy else -1
        if rstep < acked:
            note("acked_admissions_durable",
                 f"cutover resume step {rstep} < acked {acked}")
        fleet.spawn_router()

        # -- SIGSTOP partition + fenced stale-term write -------------
        sleep_until(plan["partition_at"])
        chaoslib.wait_for(lambda: fleet.leaseholder() is not None,
                          30, "leaseholder before the partition")
        h2, stale_term = fleet.leaseholder(), fleet.router_term()
        fleet.sigstop_router(h2)
        t_stop = time.monotonic()
        try:
            chaoslib.wait_for(
                lambda: fleet.leaseholder() not in (None, h2), 30,
                "takeover from the partitioned holder")
            mttrs["partition"] = round(time.monotonic() - t_stop, 3)
        except AssertionError:
            note("failover_mttr",
                 "standby never took over from the SIGSTOP'd holder")
        new_term = fleet.router_term()
        terms.append(new_term)
        rbc = fleet.clients["rb"]
        try:
            chaoslib.wait_for(
                lambda: int(rbc.fences().get(
                    fedapi.ROUTER_LEASE_NAME, {}).get("term", 0)
                ) >= new_term, 30, "fence advance")
        except AssertionError:
            note("stale_fence_refused",
                 f"fence floor never reached term {new_term}: "
                 f"{rbc.fences()}")
        fleet.sigcont_router(h2)
        # the partitioned holder's write, replayed deterministically
        # from the conductor with the deposed term
        rbc.set_fence(fedapi.ROUTER_LEASE_NAME, stale_term)
        try:
            rbc.add_vcjob(bench._fed_job("stale-probe", 1))
            note("stale_fence_refused",
                 f"write stamped with deposed term {stale_term} "
                 f"was ACCEPTED")
        except ValueError as e:
            if not str(e).startswith("fenced"):
                note("stale_fence_refused",
                     f"refused for the wrong reason: {e}")
        finally:
            rbc.set_fence("", 0)
        fenced_count = int(rbc.fences().get(
            fedapi.ROUTER_LEASE_NAME, {}).get("refused", 0) or 0)
        if fenced_count < 1:
            note("stale_fence_refused",
                 f"refusal not counted on /fences: {rbc.fences()}")

        # -- settle: run out the clock under a healthy leaseholder ---
        sleep_until(duration)
        if not bench._fed_running(g, "anchor", "ra"):
            note("acked_admissions_durable",
                 f"anchor left Running: {bench._fed_view(g, 'anchor')}")
        for name, m in mttrs.items():
            if m > ROUTER_MTTR_BOUND_S:
                note("failover_mttr",
                     f"{name} MTTR {m}s > bound "
                     f"{ROUTER_MTTR_BOUND_S}s")
        if dual:
            note("no_dual_placement", f"{dual[:3]}")
        if not all(a < b for a, b in zip(terms, terms[1:])):
            note("stale_fence_refused",
                 f"lease terms not strictly monotonic: {terms}")
    finally:
        stop.set()
        sampler.join(timeout=2)
        fleet.shutdown()
    result = {"seed": seed, "duration_s": duration,
              "classes": sorted(classes),
              "windows": sched["windows"],
              "routers_spawned": fleet._routers_spawned,
              "lease_terms": terms,
              "failover_mttr_s": mttrs,
              "mttr_bound_s": ROUTER_MTTR_BOUND_S,
              "acked_step_floor": floor,
              "fenced_writes_counted": fenced_count,
              "violations": violations, "ok": not violations}
    print(f"REPRODUCE: python tools/chaos_conductor.py "
          f"--seed {seed} --duration {duration:g} "
          f"--classes {','.join(sorted(classes))}",
          file=sys.stderr, flush=True)
    return result


def run_conductor(seed: int, duration: float,
                  classes=DEFAULT_CLASSES, logdir: str = "",
                  lock_audit: bool = False,
                  race_audit: bool = False,
                  sweep_backend: str = "thread",
                  scheduler_shards: int = 1,
                  leader_groups: int = 1) -> dict:
    classes = set(classes.split(",")) if isinstance(classes, str) \
        else set(classes)
    if "router" in classes:
        # router replica-set failover runs on the federation fleet
        # with router OS processes — its own scenario, like region
        return run_router_failover(seed, duration, classes, logdir)
    if "region" in classes:
        # whole-region loss runs on a different topology entirely
        # (the federation fleet: 2 regions behind one global queue),
        # so like the replication class it gets its own scenario
        return run_region_kill(seed, duration, classes, logdir)
    sched = build_plan(seed, duration, classes)
    plan_doc = {"seed": seed, "rules": sched["rules"]}
    logdir = logdir or f"/tmp/chaos_conductor/seed-{seed}"
    import shutil
    shutil.rmtree(logdir, ignore_errors=True)
    audit_dir = os.path.join(logdir, "lockaudit")
    race_dir = os.path.join(logdir, "raceaudit")
    audit_env = {}
    if lock_audit:
        # arm the runtime lock-order auditor (analysis/lockaudit.py)
        # in EVERY child process (server, replicas, scheduler,
        # controllers, agents) and in this conductor too: each
        # process flushes its acquisition graph + violations to
        # audit_dir at 2Hz and at exit, so even a SIGKILL'd server
        # incarnation leaves its last graph behind
        os.makedirs(audit_dir, exist_ok=True)
        from volcano_tpu.analysis import lockaudit
        lockaudit.install()
        audit_env.update(VTP_LOCK_AUDIT="1",
                         VTP_LOCK_AUDIT_OUT=audit_dir)
    if race_audit:
        # arm the snapshot-freeze/data-race auditor the same way
        # (analysis/freezeaudit.py): every scheduler session in the
        # plane deep-freezes its snapshot, and the scheduler child
        # additionally runs the PARALLEL predicate sweep so the
        # fan-out is certified against real chaos traffic, not just
        # tier-1 fixtures
        os.makedirs(race_dir, exist_ok=True)
        from volcano_tpu.analysis import freezeaudit
        freezeaudit.install()
        audit_env.update(VTP_RACE_AUDIT="1",
                         VTP_RACE_AUDIT_OUT=race_dir)
    if audit_env:
        zoo = chaoslib.ProcessZoo(logdir, env=chaoslib.repo_env(
            **audit_env))
    else:
        zoo = chaoslib.ProcessZoo(logdir)
    data_dir = os.path.join(logdir, "state")
    progress_root = os.path.join(logdir, "progress")
    os.makedirs(progress_root, exist_ok=True)
    plan_path = os.path.join(logdir, "fault_plan.json")
    with open(plan_path, "w", encoding="utf-8") as f:
        json.dump(plan_doc, f)
    port = chaoslib.free_port()
    url = f"http://127.0.0.1:{port}"
    server_faulted = ["--data-dir", data_dir,
                      "--fault-plan", f"@{plan_path}"]
    server_clean = ["--data-dir", data_dir]

    print(f"chaos conductor: seed={seed} duration={duration}s "
          f"classes={sorted(classes)} logs={logdir}", flush=True)
    print(f"  schedule: {json.dumps(sched['windows'])} "
          f"{len(sched['rules'])} rules", flush=True)

    result = {"seed": seed, "duration_s": duration,
              "classes": sorted(classes),
              "scheduler_shards": scheduler_shards,
              "leader_groups": leader_groups,
              "windows": sched["windows"]}
    c = None
    proxy = None
    replication = sched.get("replication")
    repl_topology = None
    f_urls = []
    f_dirs = []
    plane_url = url
    try:
        if replication:
            # 3-replica group: the fault-armed leader plus two
            # followers; f1 ships THROUGH a ChaosProxy (the
            # partition-able link), f2 direct.  Campaign/peer traffic
            # stays on the direct URLs, so a shipping partition is a
            # partition, not a total disappearance.
            f_ports = [chaoslib.free_port(), chaoslib.free_port()]
            f_urls = [f"http://127.0.0.1:{p}" for p in f_ports]
            f_dirs = [os.path.join(logdir, f"state-f{i + 1}")
                      for i in range(2)]
            proxy = chaoslib.ChaosProxy(port)
            proxy.start()
            proxy_url = f"http://127.0.0.1:{proxy.port}"
            zoo.spawn_server(port, *server_faulted, "--replica-id",
                             "r1", "--peers", ",".join(f_urls),
                             "--repl-ttl", "1.5")
            chaoslib.wait_server(url)
            chaoslib.spawn_replica(
                zoo, "f1", f_ports[0], f_dirs[0], "r2",
                [url, f_urls[1]], replicate_from=proxy_url)
            chaoslib.spawn_replica(
                zoo, "f2", f_ports[1], f_dirs[1], "r3",
                [url, f_urls[0]], replicate_from=url)
            for u in f_urls:
                chaoslib.wait_server(u)
            chaoslib.wait_role(url, "leader")
            repl_topology = {"leader": url, "followers": list(f_urls)}
            plane_url = ",".join([url] + f_urls)
        else:
            zoo.spawn_server(port, *server_faulted)
            chaoslib.wait_server(url)
        # keyspace-partitioned write leaders (docs/design/sharding.md):
        # group 0 is the fault-armed plane above (meta keyspace + its
        # node subtrees); groups 1.. are clean single-server leaders
        # owning the remaining subtrees.  Faults stay on group 0 — the
        # invariants keep polling the faulted group while keyed writes
        # (binds, pod status) split across every group.
        g_urls = []
        client_spec = plane_url
        if leader_groups > 1:
            for gi in range(1, leader_groups):
                gp = chaoslib.free_port()
                zoo.spawn_server(
                    gp, "--data-dir",
                    os.path.join(logdir, f"state-g{gi}"),
                    name=f"server-g{gi}")
                g_urls.append(f"http://127.0.0.1:{gp}")
            for gu in g_urls:
                chaoslib.wait_server(gu)
            client_spec = ";".join([plane_url] + g_urls)
        t_plan0 = time.monotonic()     # ~ the server plan's t0
        # leader-elected scheduler(s): the clock-jump invariant is
        # about the LEASE surviving a wall step — there must be a
        # lease.  With --scheduler-shards N, N schedulers each own a
        # disjoint subtree shard and elect on their own per-shard
        # lease ("scheduler-shard0", ...); the clock invariant tracks
        # shard 0's lease.
        sched_extra = []
        if race_audit or scheduler_shards > 1:
            conf_path = os.path.join(logdir, "sched_conf.yaml")
            import yaml
            from volcano_tpu.conf import DEFAULT_SCHEDULER_CONF
            conf_doc = dict(DEFAULT_SCHEDULER_CONF)
            alloc_conf = {}
            if race_audit:
                # the pilot under certification: default conf + the
                # parallel leaf-shard predicate sweep
                alloc_conf.update(
                    {"parallelPredicates": sweep_backend,
                     "parallelPredicates.workers": 8})
            if scheduler_shards > 1:
                # the sharded plane runs the batched gang commit and
                # soft cross-shard spill — the production shape the
                # chaos certification is for
                alloc_conf.update({"gangCommit": "batch",
                                   "shard-spill": "soft"})
            conf_doc["configurations"] = {"allocate": alloc_conf}
            with open(conf_path, "w", encoding="utf-8") as f:
                yaml.safe_dump(conf_doc, f)
            sched_extra = ["--conf", conf_path]
        sched_lease = "scheduler-shard0" if scheduler_shards > 1 \
            else "scheduler"
        for si in range(scheduler_shards):
            shard_flags = list(sched_extra)
            if scheduler_shards > 1:
                shard_flags += ["--shard-index", str(si),
                                "--shard-count", str(scheduler_shards)]
            zoo.spawn_plane(
                f"sched-{si}" if scheduler_shards > 1 else "sched",
                client_spec, "scheduler",
                "--leader-elect", "--holder", f"s{si + 1}",
                "--lease-ttl", "1.5", *shard_flags)
        zoo.spawn_plane("ctrl", client_spec, "controllers")

        # high-rate sampler: the main loop slows down under injected
        # faults (that is the point), so the degrade/heal windows and
        # the lease holder are sampled on their own 100ms thread
        import threading
        samples = []            # (t_rel, readonly_reason, visible_rv)
        leader_track = []       # (t_rel, holder)
        repl_reads = []         # (t_rel, ok) — follower read liveness
        inv = None              # InvariantTracker, created below
        sampler_stop = threading.Event()
        # with replication on, the lease/rv sampling moves to f2 (a
        # replica that lives through the whole run): leases are
        # WAL-shipped, so the follower's view IS the group's, and its
        # 10Hz answers double as the continuous-follower-reads proof
        sample_url = f_urls[1] if replication else url

        def sampler():
            while not sampler_stop.wait(0.1):
                t_rel = time.monotonic() - t_plan0
                dur = chaoslib.http_json(url + "/durability",
                                         timeout=2)
                if dur:
                    samples.append((t_rel, dur.get("readonly") or "",
                                    int(dur.get("visible_rv") or 0)))
                leader_track.append((t_rel, chaoslib.leader(
                    sample_url, sched_lease)))
                if replication:
                    repl_reads.append(
                        (t_rel, chaoslib.http_json(
                            sample_url + "/durability", timeout=2)
                         is not None))
                    # the partitioned follower's advertised lag, at
                    # 10Hz: the churn loop can stall for seconds on a
                    # faulted submit and miss a whole lag window
                    d = chaoslib.http_json(
                        f_urls[0] + "/durability", timeout=2)
                    if d and inv is not None:
                        lag = float((d.get("replication") or {})
                                    .get("lag_s") or 0.0)
                        inv.follower_lag_max[f_urls[0]] = max(
                            inv.follower_lag_max.get(f_urls[0], 0.0),
                            lag)

        threading.Thread(target=sampler, daemon=True).start()

        from volcano_tpu.api import goodput as gapi
        from volcano_tpu.api import elastic as eapi
        from volcano_tpu.api.pod import make_pod
        from volcano_tpu.api.resource import TPU
        from volcano_tpu.api.types import RUN_TICKS_ANNOTATION
        from volcano_tpu.api.vcjob import TaskSpec, VCJob
        from volcano_tpu.cache.remote_cluster import RemoteCluster

        # watches THROUGH every fault; with replication the client is
        # multi-endpoint — writes follow the leader across the kill,
        # reads stick to one replica.  With partitioned leaders the
        # mirror is the keyspace-routing client: one watch per group,
        # merged reads, binds relocating pods to their owner group.
        if leader_groups > 1:
            from volcano_tpu.cache.partitioned import \
                PartitionedCluster
            c = PartitionedCluster(client_spec)
        else:
            c = RemoteCluster(plane_url)
        chaoslib.seed_slices(c, ("sa", "sb", "sc"))
        acked_jobs = set()

        # the long elastic gang: resizes + failover churn exercise
        # the resume-step floor; its progress stream (real agents)
        # exercises the goodput ledger
        elastic_key = "default/echaos"
        c.add_vcjob(VCJob(
            name="echaos", min_available=4,
            annotations={
                eapi.ELASTIC_MIN_SLICES_ANNOTATION: "1",
                eapi.ELASTIC_MAX_SLICES_ANNOTATION: "2",
                eapi.ELASTIC_SLICES_ANNOTATION: "1",
                "failover.volcano-tpu.io/last-checkpoint-step":
                    str(FLOOR_STEP),
                gapi.PROGRESS_DIR_ANNOTATION: progress_root,
            },
            plugins={"jax": []},
            tasks=[TaskSpec(name="worker", replicas=4,
                            template=make_pod(
                                "t", requests={"cpu": 4, TPU: 4},
                                annotations={RUN_TICKS_ANNOTATION:
                                             "1000000"}))]))
        acked_jobs.add(elastic_key)

        # the serving churn class: a serving-class elastic gang whose
        # replica stats (REAL ServingCollector/Handler -> wire -> fold)
        # feed the autoscaler while the classic faults fly — decisions
        # ride the same elastic resize path the echaos gang churns
        serving_key = ""
        serving_root = os.path.join(logdir, "serving")
        if "serving" in classes:
            from volcano_tpu.api import serving as sapi
            os.makedirs(serving_root, exist_ok=True)
            serving_key = "default/schaos"
            c.add_vcjob(VCJob(
                name="schaos", min_available=4,
                annotations={
                    sapi.SLO_P99_MS_ANNOTATION: "50",
                    sapi.MIN_REPLICAS_ANNOTATION: "1",
                    sapi.MAX_REPLICAS_ANNOTATION: "2",
                    sapi.TARGET_QPS_ANNOTATION: "100",
                    sapi.STATS_DIR_ANNOTATION: serving_root,
                    eapi.ELASTIC_SLICES_ANNOTATION: "1",
                },
                plugins={"jax": []},
                tasks=[TaskSpec(name="replica", replicas=4,
                                template=make_pod(
                                    "s", requests={"cpu": 4, TPU: 4},
                                    annotations={RUN_TICKS_ANNOTATION:
                                                 "1000000"}))]))
            acked_jobs.add(serving_key)

        from volcano_tpu.agent.agent import FakeUsageProvider, NodeAgent
        from volcano_tpu.agent.collect import GoodputCollector
        from volcano_tpu.agent.handlers import GoodputHandler
        from volcano_tpu.workloads.progress import ProgressReporter

        goodput_col = GoodputCollector(progress_root)
        goodput_agents = {}
        fed = {"step": FLOOR_STEP, "epoch": 0}

        def feed_goodput():
            """Play the long gang's workers + node agents for one
            beat (the soak.py contract: epoch-aware progress files ->
            REAL GoodputCollector/Handler -> wire -> store fold)."""
            epg = c.podgroups.get(elastic_key)
            ej = c.vcjobs.get(elastic_key)
            if epg is None or ej is None:
                return
            epoch = _iann(epg.annotations,
                          "failover.volcano-tpu.io/generation") + \
                _iann(epg.annotations, eapi.ELASTIC_GENERATION_ANNOTATION)
            if epoch != fed["epoch"]:
                fed["epoch"] = epoch
                fed["step"] = max(FLOOR_STEP, _iann(
                    epg.annotations,
                    "failover.volcano-tpu.io/resume-step"))
            fed["step"] += 1
            pods = [p for p in c.pods.values()
                    if p.owner == ej.uid and p.node_name
                    and getattr(p.phase, "value", p.phase) == "Running"]
            for p in pods:
                ProgressReporter(
                    gapi.progress_file_for(progress_root, p.uid),
                    epoch=fed["epoch"]).report(
                        step=fed["step"], examples=fed["step"] * 8.0)
                if p.node_name not in goodput_agents:
                    goodput_agents[p.node_name] = NodeAgent(
                        c, p.node_name, FakeUsageProvider(),
                        handlers=[GoodputHandler],
                        goodput_collector=goodput_col)
            for agent in goodput_agents.values():
                try:
                    agent.sync()
                except Exception as e:  # noqa: BLE001 — chaos is on
                    print("goodput agent sync failed:", e, flush=True)

        serving_agents = {}
        served = {"requests": 0, "slo_ok": 0}

        def feed_serving():
            """Play the serving gang's replicas + node agents for one
            beat: cumulative stats files (epoch = elastic generation,
            so a resize restart reads as a ledger restart, not a
            regression) -> REAL ServingCollector/Handler -> wire ->
            store fold the serving_monotonic invariant audits."""
            if not serving_key:
                return
            from volcano_tpu.agent.collect import ServingCollector
            from volcano_tpu.agent.handlers import ServingHandler
            from volcano_tpu.api import serving as sapi
            from volcano_tpu.workloads.serve import \
                ServingStatsReporter
            spg = c.podgroups.get(serving_key)
            sj = c.vcjobs.get(serving_key)
            if spg is None or sj is None:
                return
            epoch = _iann(spg.annotations,
                          eapi.ELASTIC_GENERATION_ANNOTATION)
            served["requests"] += 30
            served["slo_ok"] += 30
            pods = [p for p in c.pods.values()
                    if p.owner == sj.uid and p.node_name
                    and getattr(p.phase, "value", p.phase)
                    == "Running"]
            for p in pods:
                ServingStatsReporter(
                    sapi.stats_file_for(serving_root, p.uid),
                    epoch=epoch).report(
                        requests=served["requests"],
                        slo_ok=served["slo_ok"],
                        p50_ms=4.0, p99_ms=30.0)
                if p.node_name not in serving_agents:
                    serving_agents[p.node_name] = NodeAgent(
                        c, p.node_name, FakeUsageProvider(),
                        handlers=[ServingHandler],
                        serving_collector=ServingCollector(
                            serving_root))
            for agent in serving_agents.values():
                try:
                    agent.sync()
                except Exception as e:  # noqa: BLE001 — chaos is on
                    print("serving agent sync failed:", e, flush=True)

        inv = InvariantTracker(c, url, elastic_key,
                               repl=repl_topology,
                               serving_key=serving_key)
        import random as _random
        churn_rng = _random.Random(seed * 7919 + 13)
        submit_latencies = []
        submit_failures = 0
        submitted = 1    # the elastic gang
        killed_host = None
        # replication event state
        partitioned = False
        leader_killed_at = None
        leader_respawned = False
        promote_s = None
        new_leader_url = None
        faults_before_kill = None
        repl_state = {"partitioned": partitioned,
                      "killed_at": leader_killed_at,
                      "respawned": leader_respawned,
                      "promote_s": promote_s,
                      "new_leader": new_leader_url,
                      "faults_before_kill": faults_before_kill}
        # serializes the one-shot kill/respawn steps: the tick thread
        # and the post-settle direct call may otherwise interleave
        repl_tick_lock = threading.Lock()

        def replication_tick(now_s: float) -> None:
            """Drive the replication fault schedule (called from the
            tick thread AND once after settle — the kill lands late,
            so the promotion/rejoin tail often completes during
            settle).  Serialized: the one-shot steps are guarded by
            plain flags."""
            if not replication or not repl_tick_lock.acquire(
                    timeout=10.0):
                return
            try:
                _replication_tick_locked(now_s)
            finally:
                repl_tick_lock.release()

        def _replication_tick_locked(now_s: float) -> None:
            p0, p1 = replication["partition"]
            if not repl_state["partitioned"] and p0 <= now_s < p1:
                repl_state["partitioned"] = True
                proxy.set_mode("blackhole")
                print(f"replication fault: f1<->leader shipping "
                      f"PARTITIONED at t={now_s:.1f}s", flush=True)
            elif repl_state["partitioned"] and now_s >= p1:
                repl_state["partitioned"] = False
                proxy.set_mode("pass")
                print(f"replication fault: partition healed at "
                      f"t={now_s:.1f}s", flush=True)
            if repl_state["killed_at"] is None and \
                    now_s >= replication["kill_leader_at"]:
                repl_state["faults_before_kill"] = chaoslib.http_json(
                    url + "/faults") or {}
                zoo.kill9("server")
                repl_state["killed_at"] = time.monotonic()
                print(f"replication fault: leader SIGKILLed at "
                      f"t={now_s:.1f}s", flush=True)
            if repl_state["killed_at"] is not None and \
                    repl_state["new_leader"] is None:
                for u in f_urls:
                    st_r = chaoslib.replication_status(u)
                    if st_r and st_r.get("role") == "leader":
                        repl_state["new_leader"] = u
                        repl_state["promote_s"] = \
                            time.monotonic() - repl_state["killed_at"]
                        inv.repl["leader"] = u
                        inv.repl["followers"] = [
                            x for x in f_urls if x != u]
                        inv.url = u
                        print(f"replication: {u} PROMOTED "
                              f"{repl_state['promote_s']:.2f}s after "
                              f"the kill (term {st_r.get('term')})",
                              flush=True)
                        break
            if repl_state["new_leader"] is not None and \
                    not repl_state["respawned"]:
                # the deposed leader rejoins over its old dir: its
                # stale term forces the full re-sync
                chaoslib.spawn_replica(
                    zoo, "server-rejoin", port, data_dir, "r1",
                    f_urls, replicate_from="auto")
                repl_state["respawned"] = True
                inv.repl["followers"].append(url)

        # the replication fault schedule runs on its own 100ms thread:
        # the churn loop can block for seconds inside a submit retry
        # (that is the point of the wire faults), and a partition that
        # starts late because a submit was stuck would smear the
        # windows the recovery audit measures
        repl_tick_stop = threading.Event()
        if replication:
            def repl_tick_loop():
                while not repl_tick_stop.wait(0.1):
                    try:
                        replication_tick(time.monotonic() - t_plan0)
                    except Exception as e:  # noqa: BLE001
                        print("replication tick failed:", e,
                              flush=True)
            threading.Thread(target=repl_tick_loop,
                             daemon=True).start()

        i = 0
        t_end = time.monotonic() + duration
        while time.monotonic() < t_end:
            now_s = time.monotonic() - t_plan0
            n = churn_rng.choice((1, 2, 4))
            t0 = time.monotonic()
            try:
                c.add_vcjob(chaoslib.gang_job(f"cj-{seed}-{i}", n))
                acked_jobs.add(f"default/cj-{seed}-{i}")
                submitted += 1
                submit_latencies.append(time.monotonic() - t0)
            except Exception as e:  # noqa: BLE001 — chaos is on
                submit_failures += 1
                print(f"submit failed at t={now_s:.1f}s: {e}",
                      flush=True)
            i += 1
            if sched["slice_kill_at"] is not None and \
                    killed_host is None and \
                    now_s >= sched["slice_kill_at"]:
                from volcano_tpu.simulator import fail_host
                killed_host = "sc-w0"
                try:
                    fail_host(c, killed_host)
                    print(f"slice fault: killed {killed_host} at "
                          f"t={now_s:.1f}s", flush=True)
                except Exception as e:  # noqa: BLE001
                    print("fail_host failed:", e, flush=True)
                    killed_host = None
            feed_goodput()
            feed_serving()
            inv.poll()
            time.sleep(churn_rng.uniform(0.25, 0.6))

        # settle: every fault window is over (until_s <= duration);
        # give the plane a fault-free tail to finish the short gangs
        settle_until = time.monotonic() + min(30.0, duration)
        while time.monotonic() < settle_until:
            feed_goodput()
            feed_serving()
            inv.poll()
            done = sum(1 for j in c.vcjobs.values()
                       if getattr(j.phase, "value", j.phase)
                       == "Completed")
            if done >= submitted - 1 and (
                    not replication or
                    repl_state["new_leader"] is not None):
                break               # all short gangs (+ promotion)
            time.sleep(0.5)
        if replication:
            # the promotion tail must complete before the audits:
            # new leader elected, deposed leader re-synced back in
            chaoslib.wait_for(
                lambda: repl_state["new_leader"] is not None,
                60, "a follower promoting after the leader kill")
            repl_tick_stop.set()
            replication_tick(time.monotonic() - t_plan0)
            truth_url = repl_state["new_leader"]
            chaoslib.wait_role(url, "follower", timeout=60)
            chaoslib.wait_follower_caught_up(url, truth_url,
                                             timeout=60)
        else:
            truth_url = url

        # -- end-of-run audits ---------------------------------------
        sampler_stop.set()
        time.sleep(1.0)
        c.resync()
        inv.poll()
        phases = chaoslib.phase_counts(c)
        truth = _truth_stores([truth_url] + g_urls)
        missing = [k for k in acked_jobs if k not in truth["vcjob"]]
        if missing:
            inv.note("acked_durable",
                     f"{len(missing)} acked vcjobs missing: "
                     f"{missing[:5]}")
        # the mirror that watched THROUGH everything must converge.
        # The plane is still live (ticks, status flushes), so compare
        # snapshot-vs-mirror repeatedly until a quiescent pair
        # matches — only a divergence that never settles is real.
        final_rv = int((chaoslib.http_json(truth_url + "/durability")
                        or {}).get("visible_rv") or 0)
        meta_mirror = c.groups[0] if leader_groups > 1 else c
        try:
            chaoslib.wait_for(lambda: meta_mirror._rv >= final_rv, 20,
                              "mirror caught up after heal")
        except AssertionError as e:
            inv.note("mirror_converged", str(e))
        div = None
        for _ in range(8):
            truth = _truth_stores([truth_url] + g_urls)
            div = chaoslib.mirror_divergence(c, truth)
            if div == 0:
                break
            time.sleep(0.5)
        if div:
            inv.note("mirror_converged", f"{div} diverged entries "
                     "(stable across 8 compares)")
        faults_fired = repl_state["faults_before_kill"] if replication \
            else chaoslib.http_json(url + "/faults") or {}
        if scheduler_shards > 1:
            # every shard's cycles stamp labels.shard on the meta
            # /traces ring — the run only counts as sharded if every
            # shard actually scheduled through the faults
            tr = chaoslib.http_json(url + "/traces?limit=128") or {}
            result["sched_shards_traced"] = sorted(
                {(t.get("root", {}).get("labels") or {}).get("shard")
                 for t in tr.get("traces", [])} - {None})
            want = {f"{i}/{scheduler_shards}"
                    for i in range(scheduler_shards)}
            if not want <= set(result["sched_shards_traced"]):
                inv.note("sharded_plane",
                         f"sharded plane incomplete: traced "
                         f"{result['sched_shards_traced']}, "
                         f"wanted {sorted(want)}")
        if leader_groups > 1:
            result["leader_group_rv"] = [
                int((chaoslib.http_json(u + "/durability") or {})
                    .get("rv") or 0) for u in [url] + g_urls]
            result["leader_group_layout"] = c.shard_layout()

        # -- CRC bit-rot drill: kill -9, flip one bit mid-WAL, boot
        # must REFUSE (exit 3); restore the byte, boot must recover —
        # then every acked job must still be there
        crc = {"checked": False}
        if replication:
            # replication flavor of the drill: a FOLLOWER's local WAL
            # must be a complete recovery point — kill a current
            # follower, flip one bit mid-WAL, a standalone boot over
            # its dir must REFUSE (per-record CRC); restore the byte
            # and every acked job must be in ITS recovered store.
            drill_url = [u for u in f_urls
                         if u != repl_state["new_leader"]][0]
            fi = f_urls.index(drill_url)
            drill_name, drill_dir = f"f{fi + 1}", f_dirs[fi]
            drill_port = int(drill_url.rsplit(":", 1)[1])
            rv_before = inv.max_rv
            chaoslib.wait_follower_caught_up(drill_url, truth_url,
                                             timeout=60)
            zoo.kill9(drill_name)
            seg, idx = _flippable_record(drill_dir)
            if seg is not None:
                from volcano_tpu import faults as faults_mod
                off = faults_mod.flip_record_bit(seg, idx)
                crc.update({"checked": True, "replica": drill_name,
                            "segment": os.path.basename(seg),
                            "record": idx})
                zoo.spawn(f"{drill_name}-crc", "-m",
                          "volcano_tpu.server", "--port",
                          str(drill_port), "--data-dir", drill_dir)
                code = zoo.wait_exit(f"{drill_name}-crc", timeout=30)
                refused = code == 3 and bool(zoo.scrape(
                    f"{drill_name}-crc", "refusing to boot"))
                crc["refused"] = refused
                if not refused:
                    inv.note("crc_refusal",
                             f"corrupt follower WAL boot exit={code},"
                             " no refusal banner")
                faults_mod.flip_bit(seg, off)
                zoo.spawn(f"{drill_name}-crc2", "-m",
                          "volcano_tpu.server", "--port",
                          str(drill_port), "--data-dir", drill_dir)
                chaoslib.wait_server(drill_url)
                dur = chaoslib.http_json(drill_url + "/durability") \
                    or {}
                crc["recovered_rv"] = int(dur.get("rv") or 0)
                if crc["recovered_rv"] < rv_before:
                    inv.note("rv_monotonic",
                             f"follower post-restore rv "
                             f"{crc['recovered_rv']} < {rv_before}")
                truth2 = chaoslib.snapshot_stores(drill_url)
                missing2 = [k for k in acked_jobs
                            if k not in truth2["vcjob"]]
                if missing2:
                    inv.note("acked_durable",
                             f"{len(missing2)} acked vcjobs missing "
                             "from the follower's own recovery")
            else:
                crc["skipped"] = "no follower WAL segment with >=3 " \
                                 "records"
        elif "disk" in classes or "wire" in classes:
            rv_before = inv.max_rv
            zoo.kill9("server")
            seg, idx = _flippable_record(data_dir)
            if seg is not None:
                from volcano_tpu import faults as faults_mod
                off = faults_mod.flip_record_bit(seg, idx)
                crc["checked"] = True
                crc["segment"] = os.path.basename(seg)
                crc["record"] = idx
                zoo.spawn("server", "-m", "volcano_tpu.server",
                          "--port", str(port), "--tick-period", "0.2",
                          *server_clean)
                code = zoo.wait_exit("server", timeout=30)
                refused = code == 3 and bool(zoo.scrape(
                    "server", "refusing to boot"))
                crc["refused"] = refused
                if not refused:
                    inv.note("crc_refusal",
                             f"corrupt WAL boot exit={code}, "
                             "no refusal banner")
                # restore the flipped byte: the log is whole again
                faults_mod.flip_bit(seg, off)
                zoo.spawn("server", "-m", "volcano_tpu.server",
                          "--port", str(port), "--tick-period", "0.2",
                          *server_clean)
                chaoslib.wait_server(url)
                dur = chaoslib.http_json(url + "/durability") or {}
                crc["recovered_rv"] = int(dur.get("rv") or 0)
                if crc["recovered_rv"] < rv_before:
                    inv.note("rv_monotonic",
                             f"post-restore rv {crc['recovered_rv']} "
                             f"< {rv_before}")
                truth2 = chaoslib.snapshot_stores(url)
                missing2 = [k for k in acked_jobs
                            if k not in truth2["vcjob"]]
                if missing2:
                    inv.note("acked_durable",
                             f"{len(missing2)} acked vcjobs lost "
                             "across the CRC drill")
            else:
                crc["skipped"] = "no WAL segment with >=3 records"

        # the sampler saw the durable revision at 10Hz: it must never
        # have gone backwards, degrade or not
        rv_seen = 0
        for t_rel, _ro, rv in samples:
            if rv < rv_seen:
                inv.note("rv_monotonic",
                         f"sampler saw rv {rv} < {rv_seen} at "
                         f"t={t_rel:.1f}s")
            rv_seen = max(rv_seen, rv)

        summary = inv.summary()
        recovery = {}
        for wname, (w0, w1) in sched["windows"].items():
            if wname == "clock_jump" or wname.startswith("repl_"):
                continue    # not disk-degrade windows
            # 10Hz readonly trace: degrade must have been observable
            # inside the window (+heal slack), and the first writable
            # sample after the last readonly one dates the recovery
            ro_ts = [t for t, ro, _rv in samples
                     if ro and w0 <= t <= w1 + 3.0]
            ep = {"window": [w0, w1],
                  "degrade_observed": bool(ro_ts)}
            if ro_ts:
                after = [t for t, ro, _rv in samples
                         if not ro and t > max(ro_ts)]
                if after:
                    ep["readonly_recover_s"] = round(
                        min(after) - w1, 3)
            recovery[wname] = ep
        if "clock" in classes and "clock_jump" in sched["windows"]:
            j0, j1 = sched["windows"]["clock_jump"]
            during = {l for t, l in leader_track
                      if j0 <= t <= j1 and l}
            before = {l for t, l in leader_track if t < j0 and l}
            recovery["clock_jump"] = {
                "window": [j0, j1],
                "leaders_during_jump": sorted(during),
                "leader_stable": bool(during) and
                len(during | before) <= 1}
            if not recovery["clock_jump"]["leader_stable"]:
                inv.note("clock_lease",
                         f"lease holder changed across the wall jump:"
                         f" before={sorted(before)} "
                         f"during={sorted(during)}")
        if submit_latencies:
            sl = sorted(submit_latencies)
            recovery["wire"] = {
                "submit_p50_s": round(sl[len(sl) // 2], 4),
                "submit_p95_s": round(
                    sl[min(len(sl) - 1, int(0.95 * len(sl)))], 4),
                "submit_failures": submit_failures}
        if replication:
            # follower-read liveness at 10Hz across partition, lag
            # window, leader kill and promotion: the max gap between
            # consecutive successful /durability answers from f2
            gaps, last_ok = [], None
            for t_rel, ok in repl_reads:
                if ok:
                    if last_ok is not None:
                        gaps.append(t_rel - last_ok)
                    last_ok = t_rel
            p0, p1 = replication["partition"]
            f1_lag = inv.follower_lag_max.get(f_urls[0], 0.0)
            recovery["replication"] = {
                "kill_leader_at": replication["kill_leader_at"],
                "promote_s": round(repl_state["promote_s"], 3)
                if repl_state["promote_s"] is not None else None,
                "new_leader": repl_state["new_leader"],
                "deposed_leader_rejoined": repl_state["respawned"],
                "partition_window": [p0, p1],
                "partitioned_follower_lag_max_s": round(f1_lag, 3),
                "partition_lag_observed": f1_lag >=
                (p1 - p0) * 0.5,
                "follower_reads_total": sum(
                    1 for _t, ok in repl_reads if ok),
                "follower_read_gap_max_s": round(max(gaps), 3)
                if gaps else None,
                "staleness_checks": inv.staleness_checks,
            }

        result.update({
            "submitted": submitted,
            "phases": phases,
            "completed": phases.get("Completed", 0),
            "killed_host": killed_host,
            "faults_injected": faults_fired.get("rules"),
            "invariants": summary,
            "recovery": recovery,
            "crc_drill": crc,
            "ok": not summary["violations"],
        })
        if lock_audit or race_audit:
            # terminate the plane BEFORE merging: SIGTERM triggers
            # each child's audit flush handlers (atexit never runs
            # under signals), so violations recorded after the last
            # 2Hz flush — the shutdown window where ordering races
            # live — still reach the merged report.  terminate_all is
            # idempotent; the finally's call becomes a no-op.
            zoo.terminate_all()
        if lock_audit:
            result["lock_audit"] = _collect_lock_audit(audit_dir)
            result["ok"] = result["ok"] and not \
                result["lock_audit"]["violations"]
        if race_audit:
            result["race_audit"] = _collect_race_audit(race_dir)
            result["race_audit"]["sweep_backend"] = sweep_backend
            result["ok"] = result["ok"] and not \
                result["race_audit"]["violations"]
        if not result["ok"]:
            # the full plane layout rides along: shard count and
            # leader-group layout change which scheduler binds what
            # and which server absorbs which write, so a replay
            # without them is a different run
            flag = (" --lock-audit" if lock_audit else "") + \
                (" --race-audit" if race_audit else "") + \
                (f" --sweep-backend {sweep_backend}"
                 if race_audit and sweep_backend != "thread" else "") + \
                (f" --scheduler-shards {scheduler_shards}"
                 if scheduler_shards != 1 else "") + \
                (f" --leader-groups {leader_groups}"
                 if leader_groups != 1 else "")
            print(f"\nREPRODUCE: python tools/chaos_conductor.py "
                  f"--seed {seed} --duration {duration} "
                  f"--classes {','.join(sorted(classes))}{flag}",
                  flush=True)
        return result
    finally:
        if c is not None:
            c.close()
        if proxy is not None:
            proxy.close()
        zoo.terminate_all()


def _collect_lock_audit(audit_dir: str) -> dict:
    """Merge every process's flushed lockaudit report (plus this
    conductor's own, in-process) into one graph summary: unique lock
    sites, merged edges, all violations, all cycles."""
    import glob

    from volcano_tpu.analysis import lockaudit
    lockaudit.flush(audit_dir)          # the conductor's own report
    locks, edges, violations, cycles = {}, {}, [], []
    same_site = {}
    reports = sorted(glob.glob(os.path.join(audit_dir, "*.json")))
    for path in reports:
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            # vtplint: disable=except-pass (a report torn mid-flush by the 2Hz writer; the process's atexit flush supersedes it)
            continue
        for name, n in doc.get("locks", {}).items():
            locks[name] = locks.get(name, 0) + n
        for a, b, n in doc.get("edges", []):
            edges[(a, b)] = edges.get((a, b), 0) + n
        for name, n in doc.get("same_site_nestings", {}).items():
            same_site[name] = same_site.get(name, 0) + n
        violations.extend(doc.get("violations", []))
        for cyc in doc.get("cycles", []):
            if cyc not in cycles:
                cycles.append(cyc)
    return {
        "processes_reporting": len(reports),
        "lock_sites": len(locks),
        "acquisitions_total": sum(locks.values()),
        "edges": sorted([[a, b, n] for (a, b), n in edges.items()]),
        "same_site_nestings": same_site,
        "cycles": cycles,
        "violations": violations,
    }


def _collect_race_audit(race_dir: str) -> dict:
    """Merge every process's flushed freeze-audit report (plus this
    conductor's own, in-process) into one summary: frozen sessions,
    fan-out regions, tracked stores, all violations."""
    import glob

    from volcano_tpu.analysis import freezeaudit
    freezeaudit.flush(race_dir)         # the conductor's own report
    sessions = objects = fanouts = 0
    tracked = {}
    violations = []
    reports = sorted(glob.glob(os.path.join(race_dir, "*.json")))
    for path in reports:
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            # vtplint: disable=except-pass (a report torn mid-flush by the 2Hz writer; the process's atexit flush supersedes it)
            continue
        sessions += doc.get("sessions_frozen", 0)
        objects += doc.get("objects_frozen", 0)
        fanouts += doc.get("fanout_regions", 0)
        for name, n in doc.get("tracked_stores", {}).items():
            tracked[name] = tracked.get(name, 0) + n
        violations.extend(doc.get("violations", []))
    return {
        "processes_reporting": len(reports),
        "sessions_frozen": sessions,
        "objects_frozen": objects,
        "fanout_regions": fanouts,
        "tracked_stores": tracked,
        "violations": violations,
    }


def _truth_stores(urls) -> dict:
    """Ground truth across every leader group: group 0 (meta) first,
    then the node groups layered over it — the same merge order the
    partitioned client reads with, so a relocated pod's bound copy
    wins over a benign leftover meta copy."""
    truth = chaoslib.snapshot_stores(urls[0])
    for u in urls[1:]:
        extra = chaoslib.snapshot_stores(u)
        for kind, objs in extra.items():
            truth.setdefault(kind, {}).update(objs)
    return truth


def _flippable_record(data_dir: str):
    """A (segment, record_index) whose corruption is unambiguously
    MID-segment: at least 3 records, index in the middle."""
    try:
        segs = sorted(n for n in os.listdir(data_dir)
                      if n.startswith("wal-") and n.endswith(".log"))
    except OSError:
        return None, None
    for name in segs:
        path = os.path.join(data_dir, name)
        with open(path, "rb") as f:
            n = sum(1 for ln in f if ln.strip())
        if n >= 3:
            return path, n // 2
    return None, None


_READ_WORKER = r'''
import sys, time, urllib.request
url, dur = sys.argv[1], float(sys.argv[2])
t_end = time.monotonic() + dur
n = 0
paths = ["/durability", "/leases", "/watch?since=0&timeout=0"]
i = 0
while time.monotonic() < t_end:
    try:
        with urllib.request.urlopen(url + paths[i % 3],
                                    timeout=3) as r:
            r.read()
        n += 1
    except OSError:
        pass
    i += 1
print(n)
'''


def read_qps_scaling(n_readers: int = 6, measure_s: float = 4.0,
                     logdir: str = "") -> dict:
    """The read-capacity row: aggregate read QPS against a leader
    under sustained keyed write churn, vs the same reads spread over
    its followers — real OS processes end to end (server replicas AND
    reader workers; a single threaded client would GIL-cap the very
    number being measured).  This is the deployment the whole feature
    exists for: dashboards/vtpctl/watch mirrors polling while the
    single writer is busy."""
    import shutil
    import subprocess
    import tempfile
    import threading

    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.simulator import slice_nodes

    logdir = logdir or tempfile.mkdtemp(prefix="repl-qps-")
    ports = [chaoslib.free_port() for _ in range(3)]
    urls = [f"http://127.0.0.1:{p}" for p in ports]
    dirs = [os.path.join(logdir, f"qps-s{i}") for i in range(3)]
    zoo = chaoslib.ProcessZoo(logdir)
    stop = [False]
    writers = []
    try:
        chaoslib.spawn_replica(zoo, "qps-leader", ports[0], dirs[0],
                               "r1", urls[1:], tick_period=0.2)
        chaoslib.wait_server(urls[0])
        for i in (1, 2):
            chaoslib.spawn_replica(
                zoo, f"qps-f{i}", ports[i], dirs[i], f"r{i + 1}",
                [urls[0], urls[3 - i]], replicate_from=urls[0],
                tick_period=0.0)
            chaoslib.wait_server(urls[i])
        chaoslib.wait_role(urls[0], "leader")
        seed_c = RemoteCluster(urls[0], start_watch=False)
        node_names = []
        for node in slice_nodes(slice_for("qa", "v5e-16"),
                                dcn_pod="d0"):
            seed_c.put_object("node", node)
            node_names.append(node.name)
        seed_c.close()

        def writer(tid: int):
            cw = RemoteCluster(urls[0], start_watch=False)
            i = 0
            while not stop[0]:
                try:
                    p = make_pod("t", requests={"cpu": 1})
                    p.name = f"qw{tid}-{i}"
                    p.namespace = "default"
                    cw.put_object("pod", p)
                    cw.bind_pods([("default", p.name,
                                   node_names[i % len(node_names)])])
                except Exception:  # noqa: BLE001 — churn is load
                    pass
                i += 1
            cw.close()

        for t in range(3):
            th = threading.Thread(target=writer, args=(t,),
                                  daemon=True)
            th.start()
            writers.append(th)
        time.sleep(1.0)

        def measure_once(endpoints) -> float:
            procs = [subprocess.Popen(
                [sys.executable, "-c", _READ_WORKER,
                 endpoints[w % len(endpoints)], str(measure_s)],
                stdout=subprocess.PIPE, text=True,
                env=chaoslib.repo_env())
                for w in range(n_readers)]
            total = sum(int(p.communicate()[0].strip() or 0)
                        for p in procs)
            return round(total / measure_s, 1)

        def measure(endpoints) -> float:
            # median of 3 windows: a single window on a busy box
            # (this runs right after five chaos seeds) is noisy
            runs = sorted(measure_once(endpoints) for _ in range(3))
            return runs[1]

        leader_only = measure([urls[0]])
        one_follower = measure([urls[1]])
        two_followers = measure([urls[1], urls[2]])
        return {
            "readers": n_readers, "measure_s": measure_s,
            "windows_per_config": 3, "statistic": "median",
            "write_load": "3 writer threads, keyed put+bind churn "
                          "at the leader throughout",
            "read_mix": "/durability + /leases + /watch delta",
            "leader_only_qps": leader_only,
            "one_follower_qps": one_follower,
            "two_followers_qps": two_followers,
            "scaling_1f": round(one_follower / leader_only, 2)
            if leader_only else None,
            "scaling_2f": round(two_followers / leader_only, 2)
            if leader_only else None,
        }
    finally:
        stop[0] = True
        for th in writers:
            th.join(timeout=5)
        zoo.terminate_all()
        shutil.rmtree(logdir, ignore_errors=True)


def run_matrix(seeds, duration: float, classes: str,
               out: str = "", lock_audit: bool = False,
               race_audit: bool = False,
               sweep_backend: str = "thread",
               scheduler_shards: int = 1,
               leader_groups: int = 1) -> dict:
    rows = []
    for seed in seeds:
        rows.append(run_conductor(seed, duration, classes,
                                  lock_audit=lock_audit,
                                  race_audit=race_audit,
                                  sweep_backend=sweep_backend,
                                  scheduler_shards=scheduler_shards,
                                  leader_groups=leader_groups))
        print(json.dumps({"seed": seed, "ok": rows[-1]["ok"]}),
              flush=True)
    invariant_names = sorted(rows[0]["invariants"]["passed"])
    matrix = {inv: all(r["invariants"]["passed"][inv] for r in rows)
              for inv in invariant_names}
    recover = [r["recovery"].get("enospc", {}).get("readonly_recover_s")
               for r in rows]
    recover = sorted(x for x in recover if x is not None)
    eio = sorted(x for x in (
        r["recovery"].get("eio", {}).get("readonly_recover_s")
        for r in rows) if x is not None)
    doc = {
        "metric": "gray_failure_chaos_matrix",
        "seeds": [r["seed"] for r in rows],
        "duration_s": duration,
        "classes": rows[0]["classes"],
        "scheduler_shards": scheduler_shards,
        "leader_groups": leader_groups,
        "hosts": 12,
        "invariant_matrix": matrix,
        "zero_violations": all(r["ok"] for r in rows),
        "total_faults_injected": sum(
            sum(rule.get("injected", 0)
                for rule in (r.get("faults_injected") or []))
            for r in rows),
        "submitted_total": sum(r["submitted"] for r in rows),
        "completed_total": sum(r["completed"] for r in rows),
        "enospc_readonly_recover_s": {
            "p50": recover[len(recover) // 2] if recover else None,
            "max": recover[-1] if recover else None},
        "eio_readonly_recover_s": {
            "p50": eio[len(eio) // 2] if eio else None,
            "max": eio[-1] if eio else None},
        "crc_refusals": sum(
            1 for r in rows if r["crc_drill"].get("refused")),
        "clock_jump_leader_stable": all(
            r["recovery"].get("clock_jump", {}).get("leader_stable",
                                                    True)
            for r in rows),
        "wire_submit_p95_s": max(
            (r["recovery"].get("wire", {}).get("submit_p95_s") or 0)
            for r in rows),
        "resume_floor_exercised": any(
            r["invariants"]["resume_floor_exercised"] for r in rows),
        "goodput_ledger_exercised": any(
            r["invariants"]["goodput_ledger_exercised"] for r in rows),
        "serving_ledger_exercised": any(
            r["invariants"].get("serving_ledger_exercised")
            for r in rows),
        "per_seed": rows,
    }
    if "replication" in rows[0]["classes"]:
        promotes = sorted(
            r["recovery"]["replication"]["promote_s"]
            for r in rows
            if r["recovery"].get("replication", {}).get("promote_s")
            is not None)
        doc["replication"] = {
            "replicas": 3,
            "promotions": len(promotes),
            "promote_p50_s": promotes[len(promotes) // 2]
            if promotes else None,
            "promote_max_s": promotes[-1] if promotes else None,
            "acked_writes_lost_across_promotions": 0 if all(
                r["invariants"]["passed"]["acked_durable"]
                for r in rows) else "SEE per_seed",
            "deposed_leader_rejoined_all": all(
                r["recovery"].get("replication", {}).get(
                    "deposed_leader_rejoined") for r in rows),
            "partition_lag_observed_all": all(
                r["recovery"].get("replication", {}).get(
                    "partition_lag_observed") for r in rows),
            "follower_read_gap_max_s": max(
                (r["recovery"].get("replication", {}).get(
                    "follower_read_gap_max_s") or 0) for r in rows),
            "staleness_checks_total": sum(
                r["invariants"].get("staleness_checks", 0)
                for r in rows),
            "corrupt_ship_injected_total": sum(
                sum(rule.get("injected", 0)
                    for rule in (r.get("faults_injected") or [])
                    if rule.get("kind") == "corrupt_ship")
                for r in rows),
        }
        print("measuring read-QPS scaling row "
              "(leader+2 followers, write churn)...", flush=True)
        doc["read_qps_scaling"] = read_qps_scaling()
    if scheduler_shards > 1:
        doc["sched_shards_traced_all_seeds"] = all(
            set(r.get("sched_shards_traced") or []) >=
            {f"{i}/{scheduler_shards}"
             for i in range(scheduler_shards)} for r in rows)
    if leader_groups > 1:
        doc["leader_groups_all_absorbed_writes"] = all(
            (r.get("leader_group_rv") or []) and
            all(rv > 0 for rv in r["leader_group_rv"]) for r in rows)
    if out:
        with open(out, "w", encoding="utf-8") as f:
            json.dump(doc, f, indent=1)
        print(f"wrote {out}", flush=True)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="chaos-conductor")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--duration", type=float, default=30.0)
    ap.add_argument("--classes", default=DEFAULT_CLASSES,
                    help="comma set of wire,disk,clock,slice,"
                         "replication,serving,region,router")
    ap.add_argument("--logdir", default="")
    ap.add_argument("--matrix", type=int, default=0,
                    help="run seeds 1..N and aggregate the "
                         "invariant pass matrix")
    ap.add_argument("--out", default="",
                    help="write the matrix JSON here")
    ap.add_argument("--print-schedule", action="store_true",
                    help="dump the derived fault plan for --seed and "
                         "exit (no processes; reproducibility check)")
    ap.add_argument("--lock-audit", action="store_true",
                    help="arm analysis/lockaudit.py in every process "
                         "and fail the run on any lock-order/guarded-"
                         "store violation (the vtplint runtime smoke)")
    ap.add_argument("--race-audit", action="store_true",
                    help="arm analysis/freezeaudit.py in every "
                         "process (snapshot deep-freeze + unsync-pair "
                         "tracking), run the scheduler with the "
                         "parallel predicate sweep, and fail the run "
                         "on any race/freeze violation")
    ap.add_argument("--scheduler-shards", type=int, default=1,
                    help="run N subtree-sharded schedulers (each "
                         "leader-elected on its own per-shard lease) "
                         "instead of the single plane scheduler; "
                         "carried on the REPRODUCE line")
    ap.add_argument("--leader-groups", type=int, default=1,
                    help="split the keyspace across N write-leader "
                         "groups (group 0 keeps the fault plan + meta "
                         "keyspace); carried on the REPRODUCE line")
    ap.add_argument("--sweep-backend", default="thread",
                    choices=("thread", "process"),
                    help="which parallel sweep backend the "
                         "--race-audit scheduler runs: the GIL-bound "
                         "thread pool (PR 11's pilot) or the "
                         "mirror-worker process pool "
                         "(actions/procpool.py)")
    args = ap.parse_args(argv)
    classes = args.classes
    if args.print_schedule:
        print(json.dumps(build_plan(
            args.seed, args.duration, set(classes.split(","))),
            indent=1, sort_keys=True))
        return 0
    if args.matrix:
        doc = run_matrix(range(1, args.matrix + 1), args.duration,
                         classes, out=args.out,
                         lock_audit=args.lock_audit,
                         race_audit=args.race_audit,
                         sweep_backend=args.sweep_backend,
                         scheduler_shards=args.scheduler_shards,
                         leader_groups=args.leader_groups)
        print(json.dumps({k: v for k, v in doc.items()
                          if k != "per_seed"}, indent=1))
        return 0 if doc["zero_violations"] else 1
    out = run_conductor(args.seed, args.duration, classes,
                        logdir=args.logdir,
                        lock_audit=args.lock_audit,
                        race_audit=args.race_audit,
                        sweep_backend=args.sweep_backend,
                        scheduler_shards=args.scheduler_shards,
                        leader_groups=args.leader_groups)
    print(json.dumps(out))
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
