"""Control-plane soak: real processes, sustained churn, leak watch.

Ten-minute endurance run of the full wire control plane (state server
+ scheduler/controller process) under continuous short-gang-job churn:
submit every 0.3-1.2s, jobs complete via the kubelet-sim run-ticks
contract, watch for process deaths, stuck jobs and RSS trends.

Round-4 result on the dev machine: 796/796 jobs Completed over 600s,
zero process deaths, completions tracked submissions 1:1 throughout;
server RSS 31->122MB — linear in RETAINED completed jobs (~115KB/job:
ttlSecondsAfterFinished unset keeps finished jobs, matching k8s/
reference semantics), not a leak.

Usage:  python tools/soak.py [seconds] [--kill-slice]
                             [--kill-server[=EVERY_S]]
                             [--kill-leader[=EVERY_S]]
        # default 600s; logs /tmp/soak/; --kill-slice injects a slice
        # failure (simulator.fail_host through the wire) ~40% in and
        # requires the failover loop to quarantine the slice and keep
        # jobs completing.  With --kill-slice a long-running ELASTIC
        # gang also rides the soak (min 1 / max 2 slices): the
        # elastic controller must keep resizing it around the churn
        # and the slice death without ever regressing its resume
        # step (the resize-vs-failover race, ISSUE 6).  --kill-server SIGKILLs (never SIGTERMs —
        # no goodbye save) the state server every EVERY_S seconds
        # (default 20) and respawns it on the same port over the same
        # --data-dir: the WAL replay must bring back every acked
        # write, the scheduler/controller processes must stand by
        # through each outage (client retry layer + leader lease),
        # and jobs must keep completing — the control-plane crash
        # drill for docs/design/durability.md.  --kill-leader runs a
        # REPLICATED control plane (two state-server replicas,
        # server/replication.py: commit quorum 2, so every ack is
        # durable on both) and SIGKILLs whichever replica currently
        # LEADS every EVERY_S seconds (default 25), respawning it as
        # a follower of the promoted survivor: zero acked-write loss
        # across every promotion, reads served continuously from the
        # surviving replica, scheduler/controllers riding the
        # multi-endpoint client across each failover — the drill for
        # docs/design/replication.md
"""
import json, os, random, signal, socket, subprocess, sys, time, urllib.request
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")

def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0)); return s.getsockname()[1]

os.makedirs("/tmp/soak", exist_ok=True)
port = free_port()
procs = {}
def spawn(name, *argv):
    procs[name] = subprocess.Popen(
        [sys.executable, *argv], env=env, cwd=REPO,
        stdout=open(f"/tmp/soak/{name}.log", "a"), stderr=subprocess.STDOUT)

kill_server_every = None
kill_leader_every = None
for a in sys.argv[1:]:
    if a == "--kill-server":
        kill_server_every = 20.0
    elif a.startswith("--kill-server="):
        kill_server_every = float(a.split("=", 1)[1])
    elif a == "--kill-leader":
        kill_leader_every = 25.0
    elif a.startswith("--kill-leader="):
        kill_leader_every = float(a.split("=", 1)[1])

import shutil
import urllib.error

if kill_leader_every:
    # replicated control plane: two replicas, commit quorum 2 (every
    # ack durable on BOTH before the client sees it — what makes a
    # lone survivor's promotion lossless), election quorum 1 (2-node
    # lab; docs/design/replication.md on the split-brain tradeoff)
    port2 = free_port()
    repl_urls = [f"http://127.0.0.1:{port}",
                 f"http://127.0.0.1:{port2}"]
    repl_ports = {repl_urls[0]: port, repl_urls[1]: port2}
    repl_names = {repl_urls[0]: "r1", repl_urls[1]: "r2"}
    repl_dirs = {repl_urls[0]: "/tmp/soak/state-r1",
                 repl_urls[1]: "/tmp/soak/state-r2"}
    for d in repl_dirs.values():
        shutil.rmtree(d, ignore_errors=True)

    def replica_args(url, follow=""):
        args = ["-m", "volcano_tpu.server", "--port",
                str(repl_ports[url]), "--data-dir", repl_dirs[url],
                "--replica-id", repl_names[url], "--peers",
                [u for u in repl_urls if u != url][0],
                "--commit-quorum", "2", "--election-quorum", "1",
                "--repl-ttl", "1.5", "--tick-period", "0.2"]
        if follow:
            args += ["--replicate-from", follow]
        return args
    spawn("r1", *replica_args(repl_urls[0]))
    time.sleep(2)
    spawn("r2", *replica_args(repl_urls[1], follow=repl_urls[0]))
    time.sleep(2)
    cluster_url = ",".join(repl_urls)
else:
    server_args = ["-m", "volcano_tpu.server", "--port", str(port),
                   "--tick-period", "0.2"]
    if kill_server_every:
        # durable mode: the whole point is recovering from SIGKILL.
        # Fresh dir per soak — replaying last week's run would skew
        # the completion accounting.
        shutil.rmtree("/tmp/soak/state", ignore_errors=True)
        server_args += ["--data-dir", "/tmp/soak/state"]
    spawn("server", *server_args)
    time.sleep(2)
    cluster_url = f"http://127.0.0.1:{port}"
spawn("plane", "-m", "volcano_tpu", "--cluster-url", cluster_url,
      "--components", "scheduler,controllers", "--period", "0.2")


# shared drill plumbing (free ports, http_json, replication status)
from tools import chaoslib


def http_json(url, timeout=2.0):
    return chaoslib.http_json(url, timeout=timeout)


def current_leader():
    for u in repl_urls:
        doc = chaoslib.replication_status(u)
        if doc and doc.get("role") == "leader":
            return u
    return None

from volcano_tpu.cache.remote_cluster import RemoteCluster
from volcano_tpu.api.devices.tpu.topology import slice_for
from volcano_tpu.simulator import slice_nodes
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import RUN_TICKS_ANNOTATION

c = RemoteCluster(cluster_url)
for sname in ("sa", "sb", "sc"):
    for node in slice_nodes(slice_for(sname, "v5e-16"), dcn_pod="d0"):
        c.put_object("node", node)

rng = random.Random(42)
submitted = completed_seen = 0
elastic_key = None
kill_slice_mode = "--kill-slice" in sys.argv[1:]
if kill_slice_mode or kill_server_every or kill_leader_every:
    # one long-running elastic gang in the mix: grows into idle,
    # shrinks under churn pressure, and must survive the slice kill
    # AND every server kill -9.  Its goodput stream (progress files ->
    # real agents -> GoodputReport -> podgroup fold) rides the soak:
    # the accumulated ledger must never regress across a server
    # respawn (WAL persistence of the folded annotations) and the
    # measured step rate must never spike after a resize restart
    # (the collector's epoch-aware window restart).
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api import goodput as gapi
    elastic_key = "default/esoak"
    progress_root = "/tmp/soak/progress"
    os.makedirs(progress_root, exist_ok=True)
    c.add_vcjob(VCJob(
        name="esoak", min_available=4,
        annotations={
            eapi.ELASTIC_MIN_SLICES_ANNOTATION: "1",
            eapi.ELASTIC_MAX_SLICES_ANNOTATION: "2",
            eapi.ELASTIC_SLICES_ANNOTATION: "1",
            "failover.volcano-tpu.io/last-checkpoint-step": "500",
            gapi.PROGRESS_DIR_ANNOTATION: progress_root,
        },
        plugins={"jax": []},
        tasks=[TaskSpec(name="worker", replicas=4,
                        template=make_pod(
                            "t", requests={"cpu": 4, TPU: 4},
                            annotations={RUN_TICKS_ANNOTATION:
                                         "1000000"}))]))

from volcano_tpu.agent.agent import FakeUsageProvider, NodeAgent
from volcano_tpu.agent.collect import GoodputCollector
from volcano_tpu.agent.handlers import GoodputHandler
from volcano_tpu.workloads.progress import ProgressReporter

goodput_agents = {}
goodput_col = None
fed = {"step": 500, "epoch": 0, "rate_max": 0.0, "alloc": 0.0,
       "alloc_monotonic": True}


def _iann(ann, key):
    try:
        return int(ann.get(key, 0) or 0)
    except (TypeError, ValueError):
        return 0


def feed_goodput():
    """One soak iteration of the goodput loop: play the workers
    (write progress records, epoch-aware across resize/failover
    drains) and the node agents (REAL GoodputCollector + handler
    posting over the wire), then sample the folded podgroup ledger."""
    global goodput_col
    if elastic_key is None:
        return
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api import goodput as gapi
    epg = c.podgroups.get(elastic_key)
    ej = c.vcjobs.get(elastic_key)
    if epg is None or ej is None:
        return
    if goodput_col is None:
        goodput_col = GoodputCollector(progress_root)
    epoch = _iann(epg.annotations,
                  "failover.volcano-tpu.io/generation") + \
        _iann(epg.annotations, eapi.ELASTIC_GENERATION_ANNOTATION)
    if epoch != fed["epoch"]:
        # drained + rebuilt: resume from the stamped floor, exactly
        # like a real worker restoring its checkpoint
        fed["epoch"] = epoch
        fed["step"] = max(500, _iann(
            epg.annotations, "failover.volcano-tpu.io/resume-step"))
    fed["step"] += 1
    pods = [p for p in c.pods.values()
            if p.owner == ej.uid and p.node_name
            and getattr(p.phase, "value", p.phase) == "Running"]
    for p in pods:
        ProgressReporter(
            gapi.progress_file_for(progress_root, p.uid),
            epoch=fed["epoch"]).report(step=fed["step"],
                                       examples=fed["step"] * 8.0)
        if p.node_name not in goodput_agents:
            goodput_agents[p.node_name] = NodeAgent(
                c, p.node_name, FakeUsageProvider(),
                handlers=[GoodputHandler],
                goodput_collector=goodput_col)
    for agent in goodput_agents.values():
        try:
            agent.sync()
        except Exception as e:  # noqa: BLE001 — soak must keep going
            print("goodput agent sync failed:", e, flush=True)
    epg = c.podgroups.get(elastic_key) or epg
    rate = gapi.ann_float(epg.annotations,
                          gapi.PG_STEP_RATE_ANNOTATION)
    fed["rate_max"] = max(fed["rate_max"], rate)
    alloc = gapi.ann_float(epg.annotations,
                           gapi.PG_ALLOCATED_S_ANNOTATION)
    if alloc + 1e-6 < fed["alloc"]:
        fed["alloc_monotonic"] = False   # a kill -9 ate acked ledger
    fed["alloc"] = max(fed["alloc"], alloc)
argv = [a for a in sys.argv[1:]
        if not a.startswith("--kill-")]
kill_slice = "--kill-slice" in sys.argv[1:]
duration = float(argv[0]) if argv else 600
t_start = time.time()
t_end = t_start + duration
t_kill = t_start + duration * 0.4
killed = None
server_kills = 0
next_server_kill = (t_start + kill_server_every
                    if kill_server_every else None)
leader_kills = 0
acked_job_keys = set()
next_leader_kill = (t_start + kill_leader_every
                    if kill_leader_every else None)
follower_read_fails = 0
follower_reads = 0
if kill_leader_every:
    # continuous follower reads on a side thread: at every beat SOME
    # replica must answer /durability — through every kill-promote
    import threading
    read_stop = threading.Event()

    def read_sampler():
        global follower_reads, follower_read_fails
        while not read_stop.wait(0.25):
            if any(http_json(u + "/durability") is not None
                   for u in repl_urls):
                follower_reads += 1
            else:
                follower_read_fails += 1
    threading.Thread(target=read_sampler, daemon=True).start()
i = 0
rss_samples = []
def server_rss():
    try:
        name = "server" if "server" in procs else "r1"
        with open(f"/proc/{procs[name].pid}/status") as f:
            for ln in f:
                if ln.startswith("VmRSS"):
                    return int(ln.split()[1])
    except OSError:
        return -1
while time.time() < t_end:
    if next_leader_kill is not None and time.time() >= next_leader_kill:
        # SIGKILL whichever replica currently LEADS; the survivor
        # must promote (it holds every acked write: commit quorum 2)
        # and the deposed one rejoins as its follower via full
        # re-sync (--replicate-from auto + stale term)
        lu = current_leader()
        if lu is not None:
            name = repl_names[lu]
            os.kill(procs[name].pid, signal.SIGKILL)
            procs[name].wait()
            t0 = time.time()
            survivor = [u for u in repl_urls if u != lu][0]
            while time.time() - t0 < 30:
                st_s = http_json(survivor + "/replication")
                if st_s and st_s.get("role") == "leader":
                    break
                time.sleep(0.2)
            spawn(name, *replica_args(lu, follow="auto"))
            leader_kills += 1
            print(f"kill -9 leader {name} (#{leader_kills}); "
                  f"{survivor} promoted in {time.time() - t0:.1f}s; "
                  f"{name} respawned as follower", flush=True)
        next_leader_kill = time.time() + kill_leader_every
    if next_server_kill is not None and time.time() >= next_server_kill:
        # kill -9 and respawn in place: WAL replay + mirror delta
        # resync must carry every live component across the outage
        os.kill(procs["server"].pid, signal.SIGKILL)
        procs["server"].wait()
        spawn("server", *server_args)
        server_kills += 1
        next_server_kill = time.time() + kill_server_every
        print(f"kill -9 state server (#{server_kills}); respawned",
              flush=True)
    if kill_slice and killed is None and time.time() >= t_kill:
        # chaos: one host of slice sc dies mid-soak; the failover
        # controller in the plane process must quarantine the slice
        # and the churn must keep completing on sa/sb
        from volcano_tpu.simulator import fail_host
        c.resync()
        killed = "sc-w0"
        fail_host(c, killed)
        print(f"killed {killed} (slice sc)", flush=True)
    # submit a short gang job
    n = rng.choice((1, 2, 4))
    job = VCJob(name=f"soak-{i}", min_available=n,
                tasks=[TaskSpec(name="worker", replicas=n,
                                template=make_pod("t", requests={"cpu": 4, TPU: 4},
                                                  annotations={RUN_TICKS_ANNOTATION: "3"}))],
                plugins={"jax": [], "svc": []})
    try:
        c.add_vcjob(job)
        submitted += 1
        acked_job_keys.add(job.key)
    except Exception as e:
        print("submit failed:", e, flush=True)
    i += 1
    feed_goodput()
    time.sleep(rng.uniform(0.3, 1.2))
    if i % 20 == 0:
        done = sum(1 for j in c.vcjobs.values()
                   if getattr(j.phase, "value", j.phase) == "Completed")
        rss = server_rss()
        rss_samples.append(rss)
        dead = [n for n, p in procs.items() if p.poll() is not None]
        print(f"t={int(t_end - time.time())}s left submitted={submitted} "
              f"completed={done} server_rss={rss}K dead={dead}", flush=True)
        if dead:
            break

def fetch_traces():
    """GET /traces from the live server — (epoch, complete traces)."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/traces?limit=64",
                timeout=5) as r:
            payload = json.loads(r.read())
        return payload.get("epoch", ""), payload.get("traces", [])
    except OSError:
        return "", None


def trace_complete(doc):
    """Every span in the tree closed — the server must never serve
    half a tree (same definition the server's POST gate enforces)."""
    from volcano_tpu import trace
    return trace.is_complete_span(doc.get("root"))


time.sleep(5)
c.resync()
phases = {}
for j in c.vcjobs.values():
    ph = getattr(j.phase, "value", str(j.phase))
    phases[ph] = phases.get(ph, 0) + 1
dead = [n for n, p in procs.items() if p.poll() is not None]
out = {"submitted": submitted, "phases": phases,
       "dead_processes": dead,
       "rss_first": rss_samples[0] if rss_samples else None,
       "rss_last": rss_samples[-1] if rss_samples else None}
if kill_leader_every:
    read_stop.set()
    # zero acked-write loss across every promotion: every job whose
    # create was ACKED must exist in the final (resynced) state
    lost_jobs = [k for k in acked_job_keys if k not in c.vcjobs]
    out["leader_kills"] = leader_kills
    out["acked_jobs"] = len(acked_job_keys)
    out["acked_jobs_lost"] = len(lost_jobs)
    out["lost_sample"] = lost_jobs[:5]
    out["follower_reads"] = follower_reads
    out["follower_read_fails"] = follower_read_fails
    out["final_leader"] = current_leader()
    out["kill_leader_ok"] = (
        leader_kills > 0 and not lost_jobs and not dead
        and follower_read_fails == 0
        and phases.get("Completed", 0) > 0)
if kill_server_every:
    out["server_kills"] = server_kills
    out["kill_server_ok"] = (server_kills > 0 and not dead
                             and phases.get("Completed", 0) > 0)
    # the flight recorder must keep flowing across every kill -9: the
    # server ring is in-memory, so after the LAST respawn it reset
    # with the new epoch — the scheduler must have refilled it, and
    # every served trace must be a complete tree (the ring either
    # resets cleanly or serves whole spans, never a half tree)
    epoch, traces = fetch_traces()
    out["traces_after_last_kill"] = (len(traces)
                                     if traces is not None else -1)
    out["traces_ok"] = bool(traces) and all(
        trace_complete(t) for t in traces)
    out["trace_ring_epoch"] = epoch
if killed is not None:
    from volcano_tpu.api.slicehealth import (
        NODE_QUARANTINED_UNTIL_ANNOTATION)
    quarantined = [n.name for n in c.nodes.values()
                   if n.annotations.get(
                       NODE_QUARANTINED_UNTIL_ANNOTATION)]
    out["killed_host"] = killed
    out["quarantined_hosts"] = sorted(quarantined)
    out["failover_ok"] = any(q.startswith("sc-") for q in quarantined)
if elastic_key is not None:
    from volcano_tpu.api import elastic as eapi
    epg = c.podgroups.get(elastic_key)
    ej = c.vcjobs.get(elastic_key)
    resume = (epg.annotations.get(
        "failover.volcano-tpu.io/resume-step") if epg else None)
    out["elastic_history"] = eapi.resize_history(epg) if epg else []
    out["elastic_slices"] = eapi.current_slices(epg) if epg else 0
    out["elastic_resume_step"] = resume
    # alive at the end, and the resume-step floor never regressed
    # below the stamped checkpoint step despite resize+failover churn
    out["elastic_ok"] = (
        ej is not None
        and getattr(ej.phase, "value", str(ej.phase))
        in ("Running", "Pending", "Restarting")
        and (resume is None or int(resume) >= 500))
if elastic_key is not None:
    # the goodput stream survived the drill: the podgroup ledger only
    # ever grew (folded annotations are WAL-durable — a server kill -9
    # must not roll back acked accounting) and the measured step rate
    # never spiked past the fed cadence (a resize restart resets the
    # window via the epoch, it must not read the resumed absolute
    # counter as rate).  Feeder cadence is ~1 step / 0.3-1.2s loop.
    out["goodput_allocated_pod_s"] = round(fed["alloc"], 3)
    out["goodput_rate_max"] = round(fed["rate_max"], 3)
    out["goodput_alloc_monotonic"] = fed["alloc_monotonic"]
    out["goodput_ok"] = (fed["alloc"] > 0
                         and fed["alloc_monotonic"]
                         and 0 < fed["rate_max"] <= 5.0)
print(json.dumps(out))
for p in procs.values():
    p.terminate()
