"""Control-plane soak: real processes, sustained churn, leak watch.

Ten-minute endurance run of the full wire control plane (state server
+ scheduler/controller process) under continuous short-gang-job churn:
submit every 0.3-1.2s, jobs complete via the kubelet-sim run-ticks
contract, watch for process deaths, stuck jobs and RSS trends.

Round-4 result on the dev machine: 796/796 jobs Completed over 600s,
zero process deaths, completions tracked submissions 1:1 throughout;
server RSS 31->122MB — linear in RETAINED completed jobs (~115KB/job:
ttlSecondsAfterFinished unset keeps finished jobs, matching k8s/
reference semantics), not a leak.

Usage:  python tools/soak.py [seconds] [--kill-slice]
        # default 600s; logs /tmp/soak/; --kill-slice injects a slice
        # failure (simulator.fail_host through the wire) ~40% in and
        # requires the failover loop to quarantine the slice and keep
        # jobs completing
"""
import json, os, random, socket, subprocess, sys, time
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")

def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0)); return s.getsockname()[1]

port = free_port()
procs = {}
def spawn(name, *argv):
    procs[name] = subprocess.Popen(
        [sys.executable, *argv], env=env, cwd=REPO,
        stdout=open(f"/tmp/soak/{name}.log", "w"), stderr=subprocess.STDOUT)

spawn("server", "-m", "volcano_tpu.server", "--port", str(port),
      "--tick-period", "0.2")
time.sleep(2)
spawn("plane", "-m", "volcano_tpu", "--cluster-url",
      f"http://127.0.0.1:{port}", "--components", "scheduler,controllers",
      "--period", "0.2")

from volcano_tpu.cache.remote_cluster import RemoteCluster
from volcano_tpu.api.devices.tpu.topology import slice_for
from volcano_tpu.simulator import slice_nodes
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import RUN_TICKS_ANNOTATION

c = RemoteCluster(f"http://127.0.0.1:{port}")
for sname in ("sa", "sb", "sc"):
    for node in slice_nodes(slice_for(sname, "v5e-16"), dcn_pod="d0"):
        c.put_object("node", node)

rng = random.Random(42)
submitted = completed_seen = 0
argv = [a for a in sys.argv[1:] if a != "--kill-slice"]
kill_slice = "--kill-slice" in sys.argv[1:]
duration = float(argv[0]) if argv else 600
t_start = time.time()
t_end = t_start + duration
t_kill = t_start + duration * 0.4
killed = None
i = 0
rss_samples = []
def server_rss():
    try:
        with open(f"/proc/{procs['server'].pid}/status") as f:
            for ln in f:
                if ln.startswith("VmRSS"):
                    return int(ln.split()[1])
    except OSError:
        return -1
while time.time() < t_end:
    if kill_slice and killed is None and time.time() >= t_kill:
        # chaos: one host of slice sc dies mid-soak; the failover
        # controller in the plane process must quarantine the slice
        # and the churn must keep completing on sa/sb
        from volcano_tpu.simulator import fail_host
        c.resync()
        killed = "sc-w0"
        fail_host(c, killed)
        print(f"killed {killed} (slice sc)", flush=True)
    # submit a short gang job
    n = rng.choice((1, 2, 4))
    job = VCJob(name=f"soak-{i}", min_available=n,
                tasks=[TaskSpec(name="worker", replicas=n,
                                template=make_pod("t", requests={"cpu": 4, TPU: 4},
                                                  annotations={RUN_TICKS_ANNOTATION: "3"}))],
                plugins={"jax": [], "svc": []})
    try:
        c.add_vcjob(job)
        submitted += 1
    except Exception as e:
        print("submit failed:", e, flush=True)
    i += 1
    time.sleep(rng.uniform(0.3, 1.2))
    if i % 20 == 0:
        done = sum(1 for j in c.vcjobs.values()
                   if getattr(j.phase, "value", j.phase) == "Completed")
        rss = server_rss()
        rss_samples.append(rss)
        dead = [n for n, p in procs.items() if p.poll() is not None]
        print(f"t={int(t_end - time.time())}s left submitted={submitted} "
              f"completed={done} server_rss={rss}K dead={dead}", flush=True)
        if dead:
            break

time.sleep(5)
c.resync()
phases = {}
for j in c.vcjobs.values():
    ph = getattr(j.phase, "value", str(j.phase))
    phases[ph] = phases.get(ph, 0) + 1
dead = [n for n, p in procs.items() if p.poll() is not None]
out = {"submitted": submitted, "phases": phases,
       "dead_processes": dead,
       "rss_first": rss_samples[0] if rss_samples else None,
       "rss_last": rss_samples[-1] if rss_samples else None}
if killed is not None:
    from volcano_tpu.api.slicehealth import (
        NODE_QUARANTINED_UNTIL_ANNOTATION)
    quarantined = [n.name for n in c.nodes.values()
                   if n.annotations.get(
                       NODE_QUARANTINED_UNTIL_ANNOTATION)]
    out["killed_host"] = killed
    out["quarantined_hosts"] = sorted(quarantined)
    out["failover_ok"] = any(q.startswith("sc-") for q in quarantined)
print(json.dumps(out))
for p in procs.values():
    p.terminate()
