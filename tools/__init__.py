# makes tools/ importable (tools.chaoslib) from the repo root —
# the scripts themselves still run standalone (python tools/chaos.py)
