"""Chrome-trace / Perfetto exporter for the scheduling flight recorder.

Turns captured session traces (volcano_tpu/trace.py span trees) into
the Chrome trace event format — load the output at chrome://tracing or
https://ui.perfetto.dev to scrub through a scheduler cycle visually.

Input sources (first match wins):
  --url URL      fetch GET /traces from a live state server
                 (optionally --token / --job / --limit); with
                 --episode, fetch the stitched cross-plane tree from
                 GET /fleet_trace?episode= instead
  --in FILE      a JSON file holding any of:
                   * a GET /traces payload   ({"traces": [...]})
                   * a GET /fleet_trace payload ({"episode": ...,
                     "trace": {...}}) or a bare stitched doc
                     (kept_because == "stitched")
                   * a SIGUSR2 dumper file   ({"trace": {"recent_traces"
                     : [...]}})
                   * a bare list of trace docs, or a single trace doc

A stitched fleet trace renders as one Perfetto process (pid) PER
PLANE — router / region-* / controllers-* — with one thread per hop
and a flow arrow at every cross-region hop boundary; an incomplete
stitched tree fails loudly instead of rendering a partial (and
misleadingly fast) episode.

Usage:
  python tools/trace_report.py --url http://127.0.0.1:8700 \
      --job default/train --out timeline.json
  python tools/trace_report.py --url http://127.0.0.1:8700 \
      --episode ep-0123456789abcdef --out fleet.json
  python tools/trace_report.py --in /tmp/volcano-tpu-dump.json \
      --out timeline.json
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def is_stitched(doc) -> bool:
    return isinstance(doc, dict) and \
        doc.get("kept_because") == "stitched"


def load_traces(path: str) -> list:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return doc
    if isinstance(doc, dict):
        if is_stitched(doc):
            return [doc]
        if "traces" in doc:
            return doc["traces"]
        if "trace" in doc and isinstance(doc["trace"], dict):
            # GET /fleet_trace payload wraps ONE stitched doc; the
            # SIGUSR2 dumper wraps a recent_traces list
            if is_stitched(doc["trace"]):
                return [doc["trace"]]
            return doc["trace"].get("recent_traces", [])
        if "root" in doc:
            return [doc]
    raise SystemExit(f"unrecognized trace JSON shape in {path}")


def _get(url: str, token: str, path: str) -> dict:
    import urllib.request
    req = urllib.request.Request(url.rstrip("/") + path)
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def fetch_traces(url: str, token: str, job: str, limit: int) -> list:
    from urllib.parse import quote
    return _get(url, token,
                f"/traces?job={quote(job, safe='')}"
                f"&limit={limit}").get("traces", [])


def fetch_fleet_trace(url: str, token: str, episode: str) -> dict:
    doc = _get(url, token, f"/fleet_trace?episode={episode}")
    trace = doc.get("trace")
    if not is_stitched(trace):
        raise SystemExit(
            f"no stitched trace for episode {episode} (the "
            f"leaseholder router stitches once per pass)")
    return trace


def fleet_chrome_trace(doc: dict) -> dict:
    """Chrome-trace JSON for ONE stitched fleet episode: a Perfetto
    process per plane, a thread per hop, and a flow arrow from the
    end of each hop to the start of the next — the cross-region
    handoff made scrubbable.  Refuses an incomplete tree: a partial
    stitch rendered silently reads as a fast episode."""
    from volcano_tpu import trace as trace_mod
    root = doc.get("root") or {}
    frags = list(root.get("children") or ())
    incomplete = [f.get("name", "?") for f in [root] + frags
                  if not trace_mod.is_complete_span(f)]
    if incomplete:
        raise SystemExit(
            "incomplete stitched tree — refusing to render a partial "
            "episode (missing/zero-span fragments: "
            + ", ".join(incomplete) + ")")
    if not frags:
        raise SystemExit("stitched tree holds no fragments")

    planes = sorted({(f.get("labels") or {}).get("plane", "?")
                     for f in frags})
    pid_of = {plane: i + 1 for i, plane in enumerate(planes)}
    events = []
    for plane, pid in pid_of.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"plane {plane}"}})

    def walk(span: dict, pid: int, tid: int) -> None:
        args = {k: v for k, v in (span.get("labels") or {}).items()
                if v}
        events.append({
            "name": span.get("name", "?"),
            "cat": span.get("kind", "span"), "ph": "X",
            "ts": round(span.get("start", 0.0) * 1e6, 1),
            "dur": round(span.get("dur", 0.0) * 1e6, 1),
            "pid": pid, "tid": tid, "args": args,
        })
        for child in span.get("children", ()):
            walk(child, pid, tid)

    by_hop = {}
    named = set()
    for f in frags:
        lbl = f.get("labels") or {}
        plane = lbl.get("plane", "?")
        try:
            hop = int(lbl.get("hop", 0) or 0)
        except (TypeError, ValueError):
            hop = 0
        pid = pid_of[plane]
        walk(f, pid, hop + 1)
        if (pid, hop) not in named:
            named.add((pid, hop))
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": hop + 1,
                           "args": {"name": f"hop {hop}"}})
        by_hop.setdefault(hop, []).append((f, pid))

    def span_end(f: dict) -> float:
        return f.get("start", 0.0) + f.get("dur", 0.0)

    hops = sorted(by_hop)
    for arrow_id, (a, b) in enumerate(zip(hops, hops[1:]), start=1):
        src, spid = max(by_hop[a], key=lambda t: span_end(t[0]))
        dst, dpid = min(by_hop[b],
                        key=lambda t: t[0].get("start", 0.0))
        events.append({"name": f"hop {a}->{b}", "cat": "hop",
                       "ph": "s", "id": arrow_id, "pid": spid,
                       "tid": a + 1,
                       "ts": round(span_end(src) * 1e6, 1)})
        events.append({"name": f"hop {a}->{b}", "cat": "hop",
                       "ph": "f", "bp": "e", "id": arrow_id,
                       "pid": dpid, "tid": b + 1,
                       "ts": round(dst.get("start", 0.0) * 1e6, 1)})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="export scheduler session traces as a Chrome-trace"
                    " timeline")
    parser.add_argument("--in", dest="infile", default="",
                        help="trace JSON file (GET /traces payload, "
                             "dumper output, or trace doc list)")
    parser.add_argument("--url", default="",
                        help="live state-server URL to fetch from")
    parser.add_argument("--token", default="")
    parser.add_argument("--job", default="",
                        help="filter to traces touching this job key")
    parser.add_argument("--episode", default="",
                        help="with --url: fetch this episode's "
                             "stitched fleet trace (/fleet_trace)")
    parser.add_argument("--limit", type=int, default=32)
    parser.add_argument("--out", default="timeline.json")
    args = parser.parse_args(argv)

    from volcano_tpu import trace as trace_mod
    if args.url and args.episode:
        traces = [fetch_fleet_trace(args.url, args.token,
                                    args.episode)]
    elif args.url:
        traces = fetch_traces(args.url, args.token, args.job,
                              args.limit)
    elif args.infile:
        traces = load_traces(args.infile)
        if args.job:
            traces = [t for t in traces
                      if trace_mod.matches_job(t, args.job)]
        traces = traces[-args.limit:]
    else:
        parser.error("need --url or --in")
    if not traces:
        print("no traces matched", file=sys.stderr)
        return 1
    if len(traces) == 1 and is_stitched(traces[0]):
        doc = fleet_chrome_trace(traces[0])
        kind = f"stitched fleet trace ({traces[0].get('episode')})"
    else:
        doc = trace_mod.to_chrome_trace(traces)
        kind = f"{len(traces)} session trace(s)"
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    print(f"{kind}, {len(doc['traceEvents'])} events -> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
