"""Chrome-trace / Perfetto exporter for the scheduling flight recorder.

Turns captured session traces (volcano_tpu/trace.py span trees) into
the Chrome trace event format — load the output at chrome://tracing or
https://ui.perfetto.dev to scrub through a scheduler cycle visually.

Input sources (first match wins):
  --url URL      fetch GET /traces from a live state server
                 (optionally --token / --job / --limit)
  --in FILE      a JSON file holding any of:
                   * a GET /traces payload   ({"traces": [...]})
                   * a SIGUSR2 dumper file   ({"trace": {"recent_traces"
                     : [...]}})
                   * a bare list of trace docs, or a single trace doc

Usage:
  python tools/trace_report.py --url http://127.0.0.1:8700 \
      --job default/train --out timeline.json
  python tools/trace_report.py --in /tmp/volcano-tpu-dump.json \
      --out timeline.json
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def load_traces(path: str) -> list:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if isinstance(doc, list):
        return doc
    if isinstance(doc, dict):
        if "traces" in doc:
            return doc["traces"]
        if "trace" in doc and isinstance(doc["trace"], dict):
            return doc["trace"].get("recent_traces", [])
        if "root" in doc:
            return [doc]
    raise SystemExit(f"unrecognized trace JSON shape in {path}")


def fetch_traces(url: str, token: str, job: str, limit: int) -> list:
    import urllib.request
    from urllib.parse import quote
    req = urllib.request.Request(
        url.rstrip("/") + f"/traces?job={quote(job, safe='')}"
                          f"&limit={limit}")
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read()).get("traces", [])


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="export scheduler session traces as a Chrome-trace"
                    " timeline")
    parser.add_argument("--in", dest="infile", default="",
                        help="trace JSON file (GET /traces payload, "
                             "dumper output, or trace doc list)")
    parser.add_argument("--url", default="",
                        help="live state-server URL to fetch from")
    parser.add_argument("--token", default="")
    parser.add_argument("--job", default="",
                        help="filter to traces touching this job key")
    parser.add_argument("--limit", type=int, default=32)
    parser.add_argument("--out", default="timeline.json")
    args = parser.parse_args(argv)

    from volcano_tpu import trace as trace_mod
    if args.url:
        traces = fetch_traces(args.url, args.token, args.job,
                              args.limit)
    elif args.infile:
        traces = load_traces(args.infile)
        if args.job:
            traces = [t for t in traces
                      if trace_mod.matches_job(t, args.job)]
        traces = traces[-args.limit:]
    else:
        parser.error("need --url or --in")
    if not traces:
        print("no traces matched", file=sys.stderr)
        return 1
    doc = trace_mod.to_chrome_trace(traces)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    print(f"{len(traces)} session trace(s), "
          f"{len(doc['traceEvents'])} events -> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
