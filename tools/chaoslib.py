"""Shared chaos-drill plumbing: proxy, process zoo, invariant audits.

Every chaos tool used to hand-roll the same four things — a free-port
helper, a TCP proxy with switchable fault modes, a subprocess zoo for
the real process plane (state server + scheduler + controllers), and
the end-of-run safety audit (phase summary, chip overcommit).  They
now live here once; tools/chaos.py, tools/chaos_leader.py and
tools/chaos_partition.py are thin schedules over this module, and the
randomized conductor (tools/chaos_conductor.py) composes the same
parts with the seeded fault plans from volcano_tpu/faults.py.

Importable two ways: ``from tools import chaoslib`` from the repo
root, or run a tool standalone (each inserts the repo root on
sys.path first).
"""

from __future__ import annotations

import json
import os
import select
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_env(**extra) -> dict:
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.update({k: str(v) for k, v in extra.items()})
    return env


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_for(cond, timeout: float = 30.0, msg: str = "condition",
             interval: float = 0.05) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# -- process zoo ------------------------------------------------------

class ProcessZoo:
    """Named subprocesses of the real control plane, each with an
    append-mode log under *logdir* — spawn, SIGKILL, respawn, scrape.
    """

    def __init__(self, logdir: str, env: Optional[dict] = None):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        self.env = env or repo_env()
        self.procs: Dict[str, subprocess.Popen] = {}
        self.argvs: Dict[str, List[str]] = {}

    def log_path(self, name: str) -> str:
        return os.path.join(self.logdir, f"{name}.log")

    def spawn(self, name: str, *argv: str,
              env: Optional[dict] = None) -> subprocess.Popen:
        logf = open(self.log_path(name), "a")
        proc = subprocess.Popen(
            [sys.executable, *argv], env=env or self.env, cwd=REPO,
            stdout=logf, stderr=subprocess.STDOUT)
        self.procs[name] = proc
        self.argvs[name] = list(argv)
        return proc

    def spawn_server(self, port: int, *extra: str, name: str = "server",
                     env: Optional[dict] = None,
                     tick_period: float = 0.2) -> subprocess.Popen:
        args = ["-m", "volcano_tpu.server", "--port", str(port)]
        if tick_period:
            args += ["--tick-period", str(tick_period)]
        return self.spawn(name, *args, *extra, env=env)

    def spawn_plane(self, name: str, url: str,
                    components: str = "scheduler", *extra: str,
                    period: float = 0.2) -> subprocess.Popen:
        return self.spawn(
            name, "-m", "volcano_tpu", "--cluster-url", url,
            "--components", components, "--period", str(period),
            *extra)

    def kill9(self, name: str) -> None:
        proc = self.procs[name]
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()

    def respawn(self, name: str,
                env: Optional[dict] = None) -> subprocess.Popen:
        return self.spawn(name, *self.argvs[name], env=env)

    def dead(self) -> List[str]:
        return [n for n, p in self.procs.items()
                if p.poll() is not None]

    def poll(self, name: str):
        return self.procs[name].poll()

    def wait_exit(self, name: str, timeout: float = 20.0) -> int:
        return self.procs[name].wait(timeout=timeout)

    def scrape(self, name: str, pattern: str) -> List[str]:
        """Log lines containing *pattern* (the poor scheduler's
        structured-event bus: refusal banners, fault-injection lines,
        heal notices all land in the process logs)."""
        try:
            with open(self.log_path(name), encoding="utf-8",
                      errors="replace") as f:
                return [ln.rstrip("\n") for ln in f if pattern in ln]
        except OSError:
            return []

    def terminate_all(self, timeout: float = 5.0) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.terminate()
        for p in self.procs.values():
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()    # a blackholed client can be stuck in a read


def wait_server(url: str, timeout: float = 30.0) -> None:
    def up():
        try:
            with urllib.request.urlopen(url + "/healthz", timeout=1):
                return True
        except OSError:
            return False
    wait_for(up, timeout, f"server /healthz at {url}")


def http_json(url: str, timeout: float = 5.0) -> Optional[dict]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read())
    except (OSError, ValueError):
        return None


def leader(url: str, lease: str = "scheduler") -> Optional[str]:
    doc = http_json(url + "/leases", timeout=2)
    if not doc:
        return None
    return (doc.get(lease) or {}).get("holder")


# -- replicated control plane (server/replication.py) ------------------

def spawn_replica(zoo: "ProcessZoo", name: str, port: int,
                  data_dir: str, replica_id: str, peers,
                  replicate_from: str = "", commit_quorum: int = 0,
                  election_quorum: int = 0, ttl: float = 1.5,
                  tick_period: float = 0.2, *extra: str):
    """One replica of a state-server group as a real OS process.  The
    seed leader passes no replicate_from; followers point at the
    leader (or 'auto' to discover among the peers — how a deposed
    leader rejoins after its SIGKILL).  Give followers the SAME
    tick_period as the leader: the server's tick loop is gated on
    leadership, so it lies dormant until a promotion — a promoted
    follower spawned without it never advances the kubelet sim and
    every post-failover pod sticks at Bound."""
    args = ["-m", "volcano_tpu.server", "--port", str(port),
            "--data-dir", data_dir, "--replica-id", replica_id,
            "--peers", ",".join(peers), "--repl-ttl", str(ttl)]
    if commit_quorum:
        args += ["--commit-quorum", str(commit_quorum)]
    if election_quorum:
        args += ["--election-quorum", str(election_quorum)]
    if replicate_from:
        args += ["--replicate-from", replicate_from]
    if tick_period:
        args += ["--tick-period", str(tick_period)]
    return zoo.spawn(name, *args, *extra)


def replication_status(url: str) -> Optional[dict]:
    return http_json(url + "/replication", timeout=2)


def wait_role(url: str, role: str, timeout: float = 30.0) -> None:
    wait_for(lambda: (replication_status(url) or {}).get("role")
             == role, timeout, f"{url} reaching role {role}")


def wait_follower_caught_up(url: str, leader_url: str,
                            timeout: float = 30.0) -> None:
    def caught():
        f = replication_status(url)
        l = http_json(leader_url + "/durability", timeout=2)
        return bool(f and l and
                    f.get("applied_rv", -1) >= int(
                        l.get("visible_rv") or 0))
    wait_for(caught, timeout, f"{url} catching up to {leader_url}")


# -- TCP proxy with switchable fault modes ----------------------------

class ChaosProxy(threading.Thread):
    """TCP proxy with a switchable fault mode — the reusable wire
    middlebox every chaos tool sticks between a component and the
    state server.

        pass       — forward bytes both ways
        blackhole  — accept then stall (connect succeeds, requests
                     hang: the worst partition shape — timeouts, not
                     errors)
        latency    — forward with +latency_s per chunk (slow-link
                     brownout)
        reset      — kill every connection as soon as bytes flow (the
                     connection-reset storm)
        trickle    — forward at a few bytes per beat (slow-loris)

    An optional faults.FaultPlan (site="proxy") draws a per-connection
    mode from the seeded stream instead of the static one, so a
    conductor schedule replays exactly.
    """

    def __init__(self, upstream_port: int, latency_s: float = 0.15,
                 plan=None):
        super().__init__(daemon=True)
        self.upstream_port = upstream_port
        self.latency_s = latency_s
        self.plan = plan
        self.mode = "pass"
        self.listener = socket.socket()
        self.listener.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_REUSEADDR, 1)
        self.listener.bind(("127.0.0.1", 0))
        self.listener.listen(64)
        self.port = self.listener.getsockname()[1]
        self._conns: list = []
        self._lock = threading.Lock()

    def run(self):
        while True:
            try:
                client, _ = self.listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(client,),
                             daemon=True).start()

    def _conn_mode(self) -> str:
        if self.plan is not None:
            rule = self.plan.decide("proxy", "connect")
            if rule is not None:
                return rule.kind
        return self.mode

    def _serve(self, client):
        with self._lock:
            self._conns.append(client)
        mode = self._conn_mode()
        upstream = None
        try:
            if mode == "blackhole":
                # connect succeeds, bytes go nowhere: the client's
                # request hangs until ITS timeout fires (mirrors a
                # mid-network partition, not a refused connection).
                # A plan-drawn blackhole stalls a bounded while (per-
                # connection fault); a static one lasts until healed.
                stall_until = time.monotonic() + (
                    5.0 if self.plan is not None else float("inf"))
                while (self.mode == "blackhole" or self.plan is not None) \
                        and time.monotonic() < stall_until:
                    r, _, _ = select.select([client], [], [], 0.2)
                    if r and not client.recv(65536):
                        return
                # healed mid-connection: drop it; the client retries
                return
            if mode == "reset":
                # read a first chunk then slam the door with an RST
                select.select([client], [], [], 1.0)
                import struct
                client.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                  struct.pack("ii", 1, 0))
                return
            upstream = socket.create_connection(
                ("127.0.0.1", self.upstream_port), timeout=5)
            with self._lock:
                self._conns.append(upstream)
            socks = [client, upstream]
            peer = {client: upstream, upstream: client}
            while True:
                try:
                    r, _, _ = select.select(socks, [], [], 1.0)
                except (ValueError, OSError):
                    # proxy.close() raced us: a socket in the set was
                    # closed (fd -1) mid-select — clean teardown, not
                    # an error to leak into the pytest thread reaper
                    return
                if self.mode == "blackhole":
                    return      # partition started mid-flight: cut it
                for s in r:
                    data = s.recv(65536)
                    if not data:
                        return
                    live = self.mode if self.plan is None else mode
                    if live == "latency":
                        time.sleep(self.latency_s)
                        peer[s].sendall(data)
                    elif live == "trickle":
                        for i in range(0, len(data), 128):
                            peer[s].sendall(data[i:i + 128])
                            time.sleep(0.02)
                    else:
                        peer[s].sendall(data)
        except OSError:
            # vtplint: disable=except-pass (chaos proxy data pump: a torn connection IS the injected fault; the finally closes both sides)
            pass
        finally:
            for s in (client, upstream):
                if s is None:
                    continue
                try:
                    s.close()
                except OSError:
                    pass

    def set_mode(self, mode: str):
        self.mode = mode
        if mode in ("blackhole", "reset"):
            # sever in-flight connections so keep-alive sockets don't
            # tunnel through the partition
            with self._lock:
                for s in self._conns:
                    try:
                        s.close()
                    except OSError:
                        pass
                self._conns.clear()

    def close(self):
        try:
            self.listener.close()
        except OSError:
            pass


# -- workload + audits ------------------------------------------------

def gang_job(name: str, n: int, run_ticks: int = 3):
    """The standard short chaos gang: n workers, 4 TPU chips each,
    completes after run_ticks kubelet ticks."""
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.types import RUN_TICKS_ANNOTATION
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    return VCJob(
        name=name, min_available=n,
        tasks=[TaskSpec(name="worker", replicas=n,
                        template=make_pod(
                            "t", requests={"cpu": 4, TPU: 4},
                            annotations={RUN_TICKS_ANNOTATION:
                                         str(run_ticks)}))],
        plugins={"jax": [], "svc": []})


def seed_slices(cluster, slice_names, kind: str = "v5e-16",
                dcn_pod: str = "d0") -> List[str]:
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.simulator import slice_nodes
    names = []
    for sname in slice_names:
        for node in slice_nodes(slice_for(sname, kind),
                                dcn_pod=dcn_pod):
            cluster.put_object("node", node)
            names.append(node.name)
    return names


def phase_counts(cluster) -> Dict[str, int]:
    phases: Dict[str, int] = {}
    for j in cluster.vcjobs.values():
        ph = getattr(j.phase, "value", str(j.phase))
        phases[ph] = phases.get(ph, 0) + 1
    return phases


def overcommit_audit(cluster, cap: float = 4.01) -> List[Tuple[str, float]]:
    """Nodes whose bound/running pods sum past the chip capacity —
    the no-double-booking safety invariant every chaos drill checks.
    """
    from volcano_tpu.api.resource import TPU
    node_chips: Dict[str, float] = {}
    for p in cluster.pods.values():
        if p.node_name and getattr(p.phase, "value", "") in (
                "Running", "Bound"):
            node_chips[p.node_name] = node_chips.get(p.node_name, 0) + \
                (p.resource_requests().get(TPU) or 0)
    return [(n, used) for n, used in sorted(node_chips.items())
            if used > cap]


def straggler_report(cluster, job) -> dict:
    """Forensic dump for a job that did not complete: what does the
    control plane think is blocking it?"""
    ph = getattr(job.phase, "value", str(job.phase))
    pg = cluster.podgroups.get(job.key)
    pods = {p.name: (getattr(p.phase, "value", str(p.phase)),
                     p.node_name)
            for p in cluster.pods.values() if p.owner == job.uid}
    return {
        "straggler": job.key, "phase": ph,
        "pg_phase": getattr(getattr(pg, "phase", None), "value", None),
        "pg_conditions": [
            {"type": cond.type, "reason": cond.reason,
             "message": cond.message[:300]}
            for cond in getattr(pg, "conditions", [])],
        "pods": pods}


def snapshot_stores(url: str) -> dict:
    """Ground truth decoded straight off GET /snapshot (no mirror in
    the middle): {kind: {key: obj}}."""
    from volcano_tpu.api import codec
    from volcano_tpu.cache.kinds import KINDS
    from volcano_tpu.server.httputil import read_json_body
    req = urllib.request.Request(url + "/snapshot",
                                 headers={"Accept-Encoding": "gzip"})
    with urllib.request.urlopen(req, timeout=30) as r:
        payload = read_json_body(r)
    out = {}
    for kind in KINDS:
        out[kind] = {k: codec.decode(v)
                     for k, v in payload["stores"].get(kind, {}).items()}
    return out


def mirror_divergence(mirror, truth: dict) -> int:
    """Entries where a live mirror disagrees with the server's own
    snapshot: missing/extra keys per kind, or a pod whose binding
    (node, phase) differs.  Zero is the no-silent-divergence
    contract."""
    from volcano_tpu.cache.kinds import KINDS
    diverged = 0
    for kind, spec in KINDS.items():
        mine = getattr(mirror, spec.attr, {})
        theirs = truth[kind]
        diverged += len(set(mine) ^ set(theirs))
        if kind == "pod":
            for k in set(mine) & set(theirs):
                if mine[k].node_name != theirs[k].node_name or \
                        mine[k].phase is not theirs[k].phase:
                    diverged += 1
    return diverged
