"""Partition/latency chaos: isolate the LEADER scheduler from the
state server mid-churn (VERDICT r4 weak #4 second half).

kill -9 chaos (tools/chaos.py, tools/chaos_leader.py) removes a
process; partitions are nastier — the old leader keeps RUNNING but
cannot reach the server, so the safety property under test is the
lease design: a leader that cannot renew must stand by (stop binding)
BEFORE the standby acquires the lease, or the healed partition would
replay stale binds into double-bookings.

Each scheduler talks to the server through its own in-process TCP
proxy with three modes:
    pass       — forward bytes both ways
    blackhole  — accept then stall (connect succeeds, requests hang:
                 the worst partition shape — timeouts, not errors)
    latency    — forward with +LAT_S per chunk (slow-link brownout)

Every ~20s the CURRENT leader's proxy is blackholed for ~2x the lease
TTL (forcing a takeover while the old leader is alive-but-dark), then
healed; between partitions both proxies take short latency brownouts.
Pass criteria: every job completes, no chip overcommit, at least one
takeover per partition, and the healed ex-leader rejoins as standby.

Usage:  python tools/chaos_partition.py [seconds]   # logs /tmp/chaos3/
"""
import json
import os
import random
import select
import socket
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
os.makedirs("/tmp/chaos3", exist_ok=True)
DURATION = float(sys.argv[1]) if len(sys.argv) > 1 else 300.0
LEASE_TTL = 1.5
LAT_S = 0.15


def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ChaosProxy(threading.Thread):
    """TCP proxy with a switchable fault mode."""

    def __init__(self, upstream_port: int):
        super().__init__(daemon=True)
        self.upstream_port = upstream_port
        self.mode = "pass"
        self.listener = socket.socket()
        self.listener.setsockopt(socket.SOL_SOCKET,
                                 socket.SO_REUSEADDR, 1)
        self.listener.bind(("127.0.0.1", 0))
        self.listener.listen(64)
        self.port = self.listener.getsockname()[1]
        self._conns = []
        self._lock = threading.Lock()

    def run(self):
        while True:
            try:
                client, _ = self.listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(client,),
                             daemon=True).start()

    def _serve(self, client):
        with self._lock:
            self._conns.append(client)
        try:
            if self.mode == "blackhole":
                # connect succeeds, bytes go nowhere: the client's
                # request hangs until ITS timeout fires (mirrors a
                # mid-network partition, not a refused connection)
                while self.mode == "blackhole":
                    r, _, _ = select.select([client], [], [], 0.2)
                    if r and not client.recv(65536):
                        return
                # healed mid-connection: drop it; the client retries
                return
            upstream = socket.create_connection(
                ("127.0.0.1", self.upstream_port), timeout=5)
            with self._lock:
                self._conns.append(upstream)
            socks = [client, upstream]
            peer = {client: upstream, upstream: client}
            while True:
                r, _, _ = select.select(socks, [], [], 1.0)
                if self.mode == "blackhole":
                    return      # partition started mid-flight: cut it
                for s in r:
                    data = s.recv(65536)
                    if not data:
                        return
                    if self.mode == "latency":
                        time.sleep(LAT_S)
                    peer[s].sendall(data)
        except OSError:
            pass
        finally:
            for s in (client,) + tuple(
                    x for x in (locals().get("upstream"),) if x):
                try:
                    s.close()
                except OSError:
                    pass

    def set_mode(self, mode: str):
        self.mode = mode
        if mode == "blackhole":
            # sever in-flight connections so keep-alive sockets don't
            # tunnel through the partition
            with self._lock:
                for s in self._conns:
                    try:
                        s.close()
                    except OSError:
                        pass
                self._conns.clear()


port = free_port()
url = f"http://127.0.0.1:{port}"
server = subprocess.Popen(
    [sys.executable, "-m", "volcano_tpu.server", "--port", str(port),
     "--tick-period", "0.2"], env=env, cwd=REPO,
    stdout=open("/tmp/chaos3/server.log", "w"), stderr=subprocess.STDOUT)
time.sleep(2)
ctrl = subprocess.Popen(
    [sys.executable, "-m", "volcano_tpu", "--cluster-url", url,
     "--components", "controllers", "--period", "0.2"], env=env,
    cwd=REPO, stdout=open("/tmp/chaos3/ctrl.log", "w"),
    stderr=subprocess.STDOUT)

proxies = {"s1": ChaosProxy(port), "s2": ChaosProxy(port)}
for p in proxies.values():
    p.start()

scheds = {}


def spawn_sched(name):
    scheds[name] = subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu", "--cluster-url",
         f"http://127.0.0.1:{proxies[name].port}",
         "--components", "scheduler", "--period", "0.2",
         "--leader-elect", "--holder", name,
         "--lease-ttl", str(LEASE_TTL)],
        env=env, cwd=REPO,
        stdout=open(f"/tmp/chaos3/{name}.log", "a"),
        stderr=subprocess.STDOUT)


spawn_sched("s1")
spawn_sched("s2")


def leader():
    try:
        with urllib.request.urlopen(url + "/leases", timeout=2) as r:
            return json.loads(r.read()).get("scheduler", {}).get("holder")
    except Exception:
        return None


from volcano_tpu.api.devices.tpu.topology import slice_for  # noqa: E402
from volcano_tpu.api.pod import make_pod  # noqa: E402
from volcano_tpu.api.resource import TPU  # noqa: E402
from volcano_tpu.api.types import RUN_TICKS_ANNOTATION  # noqa: E402
from volcano_tpu.api.vcjob import TaskSpec, VCJob  # noqa: E402
from volcano_tpu.cache.remote_cluster import RemoteCluster  # noqa: E402
from volcano_tpu.simulator import slice_nodes  # noqa: E402

c = RemoteCluster(url)
for sname in ("sa", "sb"):
    for node in slice_nodes(slice_for(sname, "v5e-16"), dcn_pod="d0"):
        c.put_object("node", node)

rng = random.Random(23)
submitted = partitions = brownouts = 0
takeovers = []
t_end = time.time() + DURATION
last_fault = time.time()
i = 0
while time.time() < t_end:
    n = rng.choice((1, 2, 4))
    job = VCJob(name=f"part-{i}", min_available=n,
                tasks=[TaskSpec(
                    name="worker", replicas=n,
                    template=make_pod(
                        "t", requests={"cpu": 4, TPU: 4},
                        annotations={RUN_TICKS_ANNOTATION: "3"}))],
                plugins={"jax": [], "svc": []})
    try:
        c.add_vcjob(job)
        submitted += 1
    except Exception as e:  # noqa: BLE001
        print("submit failed:", e, flush=True)
    i += 1
    time.sleep(rng.uniform(0.4, 1.0))
    if time.time() - last_fault <= 20:
        continue
    victim = leader()
    if victim not in proxies:
        last_fault = time.time()
        continue
    if rng.random() < 0.33:
        # latency brownout on BOTH links: no takeover expected, just
        # slow progress with the lease held
        for p in proxies.values():
            p.set_mode("latency")
        time.sleep(4)
        for p in proxies.values():
            p.set_mode("pass")
        brownouts += 1
        last_fault = time.time()
        continue
    t0 = time.time()
    proxies[victim].set_mode("blackhole")
    partitions += 1
    # the standby must take the lease within ~2 TTLs of expiry
    new_leader, deadline = None, time.time() + 4 * LEASE_TTL + 2
    while time.time() < deadline:
        cur = leader()
        if cur and cur != victim:
            new_leader = cur
            break
        time.sleep(0.1)
    takeovers.append({
        "victim": victim, "new_leader": new_leader,
        "takeover_s": round(time.time() - t0, 2)})
    time.sleep(rng.uniform(1.0, 3.0))   # old leader stays dark a bit
    proxies[victim].set_mode("pass")    # heal: rejoins as standby
    last_fault = time.time()
    print(f"partition #{partitions}: {victim} dark, "
          f"{new_leader} took over in {takeovers[-1]['takeover_s']}s",
          flush=True)

# settle and audit
time.sleep(25)
c.resync()
phases = {}
for j in c.vcjobs.values():
    ph = getattr(j.phase, "value", str(j.phase))
    phases[ph] = phases.get(ph, 0) + 1
    if ph not in ("Completed",):
        # forensic dump for any straggler: what does the control
        # plane think is blocking it?
        pg = c.podgroups.get(j.key)
        pods = {p.name: (getattr(p.phase, "value", str(p.phase)),
                         p.node_name)
                for p in c.pods.values() if p.owner == j.uid}
        print(json.dumps({
            "straggler": j.key, "phase": ph,
            "pg_phase": getattr(getattr(pg, "phase", None), "value",
                                None),
            "pg_conditions": [
                {"type": cond.type, "reason": cond.reason,
                 "message": cond.message[:300]}
                for cond in getattr(pg, "conditions", [])],
            "pods": pods}), flush=True)
overcommit = []
node_chips = {}
for p in c.pods.values():
    if p.node_name and getattr(p.phase, "value", "") in ("Running",
                                                         "Bound"):
        node_chips[p.node_name] = node_chips.get(p.node_name, 0) + \
            p.resource_requests().get(TPU)
for nname, used in node_chips.items():
    if used > 4.01:
        overcommit.append((nname, used))
failed_takeovers = [t for t in takeovers if not t["new_leader"]]
print(json.dumps({
    "submitted": submitted, "partitions": partitions,
    "latency_brownouts": brownouts, "takeovers": takeovers,
    "failed_takeovers": len(failed_takeovers), "phases": phases,
    "overcommitted_nodes": overcommit}))
for proc in (server, ctrl, *scheds.values()):
    proc.terminate()
for proc in (server, ctrl, *scheds.values()):
    try:
        proc.wait(timeout=5)
    except subprocess.TimeoutExpired:
        proc.kill()     # a blackholed client can be stuck in a read
ok = (not overcommit and not failed_takeovers
      and phases.get("Completed", 0) == submitted)
sys.exit(0 if ok else 1)
