"""Partition/latency chaos: isolate the LEADER scheduler from the
state server mid-churn (VERDICT r4 weak #4 second half).

kill -9 chaos (tools/chaos.py, tools/chaos_leader.py) removes a
process; partitions are nastier — the old leader keeps RUNNING but
cannot reach the server, so the safety property under test is the
lease design: a leader that cannot renew must stand by (stop binding)
BEFORE the standby acquires the lease, or the healed partition would
replay stale binds into double-bookings.

Each scheduler talks to the server through its own chaoslib.ChaosProxy
(pass / blackhole / latency — see tools/chaoslib.py for the shared
proxy).  Every ~20s the CURRENT leader's proxy is blackholed for ~2x
the lease TTL (forcing a takeover while the old leader is
alive-but-dark), then healed; between partitions both proxies take
short latency brownouts.  Pass criteria: every job completes, no chip
overcommit, at least one takeover per partition, and the healed
ex-leader rejoins as standby.

Usage:  python tools/chaos_partition.py [seconds]   # logs /tmp/chaos3/
"""
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools import chaoslib  # noqa: E402

DURATION = float(sys.argv[1]) if len(sys.argv) > 1 else 300.0
LEASE_TTL = 1.5
LAT_S = 0.15

port = chaoslib.free_port()
url = f"http://127.0.0.1:{port}"
zoo = chaoslib.ProcessZoo("/tmp/chaos3")
zoo.spawn_server(port)
chaoslib.wait_server(url)
zoo.spawn_plane("ctrl", url, "controllers")

proxies = {"s1": chaoslib.ChaosProxy(port, latency_s=LAT_S),
           "s2": chaoslib.ChaosProxy(port, latency_s=LAT_S)}
for p in proxies.values():
    p.start()


def spawn_sched(name):
    zoo.spawn_plane(name, f"http://127.0.0.1:{proxies[name].port}",
                    "scheduler", "--leader-elect", "--holder", name,
                    "--lease-ttl", str(LEASE_TTL))


spawn_sched("s1")
spawn_sched("s2")

from volcano_tpu.cache.remote_cluster import RemoteCluster  # noqa: E402

c = RemoteCluster(url)
chaoslib.seed_slices(c, ("sa", "sb"))

rng = random.Random(23)
submitted = partitions = brownouts = 0
takeovers = []
t_end = time.time() + DURATION
last_fault = time.time()
i = 0
while time.time() < t_end:
    n = rng.choice((1, 2, 4))
    try:
        c.add_vcjob(chaoslib.gang_job(f"part-{i}", n))
        submitted += 1
    except Exception as e:  # noqa: BLE001
        print("submit failed:", e, flush=True)
    i += 1
    time.sleep(rng.uniform(0.4, 1.0))
    if time.time() - last_fault <= 20:
        continue
    victim = chaoslib.leader(url)
    if victim not in proxies:
        last_fault = time.time()
        continue
    if rng.random() < 0.33:
        # latency brownout on BOTH links: no takeover expected, just
        # slow progress with the lease held
        for p in proxies.values():
            p.set_mode("latency")
        time.sleep(4)
        for p in proxies.values():
            p.set_mode("pass")
        brownouts += 1
        last_fault = time.time()
        continue
    t0 = time.time()
    proxies[victim].set_mode("blackhole")
    partitions += 1
    # the standby must take the lease within ~2 TTLs of expiry
    new_leader, deadline = None, time.time() + 4 * LEASE_TTL + 2
    while time.time() < deadline:
        cur = chaoslib.leader(url)
        if cur and cur != victim:
            new_leader = cur
            break
        time.sleep(0.1)
    takeovers.append({
        "victim": victim, "new_leader": new_leader,
        "takeover_s": round(time.time() - t0, 2)})
    time.sleep(rng.uniform(1.0, 3.0))   # old leader stays dark a bit
    proxies[victim].set_mode("pass")    # heal: rejoins as standby
    last_fault = time.time()
    print(f"partition #{partitions}: {victim} dark, "
          f"{new_leader} took over in {takeovers[-1]['takeover_s']}s",
          flush=True)

# settle and audit
time.sleep(25)
c.resync()
phases = chaoslib.phase_counts(c)
for j in c.vcjobs.values():
    if getattr(j.phase, "value", str(j.phase)) not in ("Completed",):
        # forensic dump for any straggler: what does the control
        # plane think is blocking it?
        print(json.dumps(chaoslib.straggler_report(c, j)), flush=True)
overcommit = chaoslib.overcommit_audit(c)
failed_takeovers = [t for t in takeovers if not t["new_leader"]]
print(json.dumps({
    "submitted": submitted, "partitions": partitions,
    "latency_brownouts": brownouts, "takeovers": takeovers,
    "failed_takeovers": len(failed_takeovers), "phases": phases,
    "overcommitted_nodes": overcommit}))
zoo.terminate_all()
ok = (not overcommit and not failed_takeovers
      and phases.get("Completed", 0) == submitted)
sys.exit(0 if ok else 1)
