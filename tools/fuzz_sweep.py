"""Extended churn-fuzz sweep — many seeds of the whole-scheduler
contention pipeline with per-cycle invariants.

tests/test_fuzz_scheduler.py runs 4 fixed seeds in CI; this tool
widens the search (hundreds of seeds, longer episodes, a mixed-queue
weight flip thrown in) for soak-style bug hunting between rounds.
Any violation prints the seed + step so the failure is replayable in
the unit test by adding that seed.

Usage: python tools/fuzz_sweep.py [n_seeds] [steps]   # default 150 80
"""
import os
import random
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from tests.test_fuzz_scheduler import churn_episode  # noqa: E402


def main() -> int:
    n_seeds = int(sys.argv[1]) if len(sys.argv) > 1 else 150
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 80
    t0 = time.time()
    base = random.Random(int(os.environ.get("FUZZ_BASE", "515")))
    seeds = [base.randrange(1 << 30) for _ in range(n_seeds)]
    for i, seed in enumerate(seeds):
        try:
            churn_episode(seed, steps=steps,
                          gang_sizes=(1, 2, 4, 4, 8, 16),
                          p_new=0.5, p_del=0.7, p_prio=0.8,
                          p_weight=0.88)
        except Exception:
            # ANY crash gets the replay line, not just invariant
            # assertions — the seed is otherwise unrecoverable
            print(f"VIOLATION seed={seed}", flush=True)
            raise
        if (i + 1) % 10 == 0:
            print(f"{i + 1}/{n_seeds} seeds clean "
                  f"({time.time() - t0:.0f}s)", flush=True)
    print(f"OK: {n_seeds} seeds x {steps} steps, no invariant "
          f"violations ({time.time() - t0:.0f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
