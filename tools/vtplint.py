#!/usr/bin/env python
"""vtplint — the project-native invariant linter (CLI).

Runs four passes over the tree and prints one merged report:

  rules      AST project rules (volcano_tpu/analysis/astlint.py):
             req-id, wall-clock, metric-family, metric-labels,
             append-lock, except-pass — plus unexplained-suppression
             for any waiver without a reason.
  race       the snapshot-ownership pass (analysis/racecheck.py):
             functions reachable from the predicate/nodeOrder/
             batchNodeOrder/fit_class call trees are classified
             snapshot-readers; snapshot-write and
             shared-cache-unkeyed flag mutations that would race the
             parallel sweep.
  flakes     pyflakes when installed, the conservative built-in
             fallback otherwise (syntax errors, unused imports).
  registry   runtime cross-checks: codec wire round-trips, store
             kind registry, metric family/label-schema coverage.

Results are cached in .vtplint_cache/ keyed by file mtime+size and
the toolchain's own sources (analysis/lintcache.py), so the growing
rule set keeps the tier-1 gate's wall time flat: an unchanged tree
replays instantly, an edit re-lints just that file (plus the
whole-program race pass).

Usage:
    python tools/vtplint.py [--strict] [--json] [--report OUT.json]
                            [--no-flakes] [--no-registry] [--no-race]
                            [--no-cache] [paths...]

--strict exits 1 on ANY unsuppressed finding (tier-1 runs this via
tests/test_lint.py).  Suppressed findings are listed as the
suppression inventory — an explained waiver is part of the contract,
an unexplained one fails strict.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_PATHS = ("volcano_tpu", "tools")


def _py_files(paths):
    files = []
    for path in paths:
        if os.path.isfile(path):
            files.append(path)
            continue
        for root, dirs, fnames in os.walk(path):
            dirs[:] = [d for d in dirs if d != "__pycache__"]
            files.extend(os.path.join(root, f) for f in sorted(fnames)
                         if f.endswith(".py"))
    return files


def run(paths, flakes: bool = True, registry: bool = True,
        race: bool = True, cache=None):
    """(active findings, suppressed findings) over the given paths."""
    from volcano_tpu.analysis import astlint
    from volcano_tpu.analysis import flakes as flakes_mod
    from volcano_tpu.analysis import racecheck
    from volcano_tpu.analysis import registry as registry_mod

    files = _py_files(paths)
    findings = []
    linter = astlint.Linter()
    for fpath in files:
        per_file = None
        if cache is not None:
            per_file = cache.get_file("rules", fpath)
        if per_file is None:
            per_file = linter.lint_file(fpath)
            if cache is not None:
                cache.put_file("rules", fpath, per_file)
        findings.extend(per_file)
        if flakes:
            fl = cache.get_file("flakes", fpath) \
                if cache is not None else None
            if fl is None:
                with open(fpath, encoding="utf-8") as f:
                    fl = flakes_mod.check_source(f.read(), fpath)
                if cache is not None:
                    cache.put_file("flakes", fpath, fl)
            findings.extend(fl)
    if race:
        domain = [f for f in files if racecheck.in_domain(f)]
        rf = None
        sig = ""
        if cache is not None:
            sig = cache.tree_sig(domain)
            rf = cache.get_tree("race", sig)
        if rf is None:
            rf = racecheck.check_paths(paths)
            if cache is not None:
                cache.put_tree("race", sig, rf)
        findings.extend(rf)
    if registry:
        findings += registry_mod.check_all()
    if cache is not None:
        cache.save()
    active = [f for f in findings if f.suppressed is None]
    suppressed = [f for f in findings if f.suppressed is not None]
    return active, suppressed


def doc(active, suppressed) -> dict:
    return {
        "findings": len(active),
        "rule_counts": dict(sorted(Counter(
            f.rule for f in active).items())),
        "suppressions": [
            {"rule": f.rule, "site": f"{f.path}:{f.line}",
             "reason": f.suppressed} for f in suppressed],
        "details": [
            {"rule": f.rule, "site": f"{f.path}:{f.line}",
             "msg": f.msg} for f in active],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="vtplint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS))
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 on any unsuppressed finding")
    ap.add_argument("--json", action="store_true",
                    help="print the JSON report instead of lines")
    ap.add_argument("--report", default="",
                    help="also write the JSON report to this path")
    ap.add_argument("--no-flakes", action="store_true")
    ap.add_argument("--no-registry", action="store_true")
    ap.add_argument("--no-race", action="store_true")
    ap.add_argument("--no-cache", action="store_true",
                    help="bypass .vtplint_cache/ (cold full run)")
    args = ap.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.chdir(repo)
    cache = None
    if not args.no_cache:
        from volcano_tpu.analysis.lintcache import LintCache
        cache = LintCache(repo)
    active, suppressed = run(args.paths or list(DEFAULT_PATHS),
                             flakes=not args.no_flakes,
                             registry=not args.no_registry,
                             race=not args.no_race,
                             cache=cache)
    report = doc(active, suppressed)
    if args.report:
        with open(args.report, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        for f in active:
            print(f.format())
        if suppressed:
            print(f"-- {len(suppressed)} suppressed "
                  f"(explained waivers):")
            for f in suppressed:
                print(f"   {f.format()}")
        print(f"vtplint: {len(active)} finding(s), "
              f"{len(suppressed)} suppression(s)")
    if args.strict and active:
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
