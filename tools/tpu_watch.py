"""Background TPU capture daemon (VERDICT r3 weak #1 / next-round #1).

The axon tunnel to the real chip dies for hours at a time — both the
r02 and r03 driver bench runs found it dead, so three rounds shipped
with zero driver-verifiable TPU evidence.  This watcher turns any
mid-round window of tunnel liveness into a COMMITTED artifact:

  loop:
    probe (cheap matmul, bounded)            -- bench.py --probe-child
    on success:
      run flash + train benches               -- bench.py --*-child
      write TPU_RESULTS.json with RAW timestamped subprocess output
      exit 0 (the builder commits the artifact)
    on failure: sleep with capped backoff, try again

bench.py embeds TPU_RESULTS.json as `last_known_good` (marked stale)
whenever its own live probe fails, so the driver's end-of-round bench
always carries the freshest real-chip numbers that existed this round.

Run detached:  nohup python tools/tpu_watch.py > /tmp/tpu_watch.log 2>&1 &
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")
OUT = os.path.join(REPO, "TPU_RESULTS.json")

PROBE_TIMEOUT_S = 150.0
FLASH_TIMEOUT_S = 420.0
TRAIN_TIMEOUT_S = 900.0
SLEEP_MIN_S, SLEEP_MAX_S = 120, 600


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


def _child(flag: str, timeout_s: float) -> dict:
    """One bench.py child on the real TPU: returns the parsed JSON
    line plus the raw stdout/stderr and wall time (the raw output IS
    the evidence — the artifact preserves it verbatim)."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    t0 = time.time()
    started = _utcnow()
    proc = subprocess.Popen(
        [sys.executable, BENCH, flag], stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, env=env, cwd=REPO)
    try:
        raw_out, raw_err = proc.communicate(timeout=timeout_s)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        # kill then DRAIN (subprocess.run loses the pipes on POSIX
        # timeouts): a mid-sweep timeout still yields the cumulative
        # lines printed so far
        proc.kill()
        try:
            raw_out, _ = proc.communicate(timeout=10)
        except Exception:  # noqa: BLE001
            raw_out = ""
        raw_err = f"timeout after {timeout_s:g}s"
        rc = -1
    wall = round(time.time() - t0, 1)
    parsed = None
    for line in reversed((raw_out or "").strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
                break
            except json.JSONDecodeError:
                continue    # the kill can truncate the final line
    return {"flag": flag, "started_utc": started, "wall_s": wall,
            "rc": rc, "parsed": parsed,
            "raw_stdout": (raw_out or "")[-4000:],
            "raw_stderr": (raw_err or "")[-2000:]}


def capture() -> dict | None:
    """One full capture attempt; returns the artifact on success."""
    probe = _child("--probe-child", PROBE_TIMEOUT_S)
    if not (probe["parsed"] or {}).get("tpu_available"):
        print(f"[{_utcnow()}] probe down: rc={probe['rc']} "
              f"err={probe['raw_stderr'][-120:]!r}", flush=True)
        return None
    print(f"[{_utcnow()}] TPU ALIVE ({probe['parsed'].get('device_kind')})"
          f" — running benches", flush=True)
    flash = _child("--flash-child", FLASH_TIMEOUT_S)
    train = _child("--train-child", TRAIN_TIMEOUT_S)
    head = subprocess.run(["git", "rev-parse", "HEAD"], cwd=REPO,
                          capture_output=True, text=True).stdout.strip()
    return {
        "captured_utc": _utcnow(),
        "git_head": head,
        "device_kind": probe["parsed"].get("device_kind"),
        "probe": probe, "flash_attention": flash, "train_step": train,
    }


def main() -> int:
    once = "--once" in sys.argv
    sleep_s = SLEEP_MIN_S
    while True:
        art = capture()
        if art is not None:
            tmp = OUT + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(art, f, indent=1)
            os.replace(tmp, OUT)
            flash_p = art["flash_attention"]["parsed"] or {}
            train_p = art["train_step"]["parsed"] or {}
            print(f"[{_utcnow()}] captured -> {OUT}: "
                  f"flash_mfu={flash_p.get('pallas_fwd_mfu')} "
                  f"train_mfu={train_p.get('mfu')} "
                  f"tokens/s={train_p.get('tokens_per_s')}", flush=True)
            return 0
        if once:
            return 1
        time.sleep(sleep_s)
        sleep_s = min(SLEEP_MAX_S, int(sleep_s * 1.7))


if __name__ == "__main__":
    sys.exit(main())
