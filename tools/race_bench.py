#!/usr/bin/env python
"""race_bench — measure + certify the parallel predicate sweep pilot.

Produces the RACE_r{NN}.json artifact for ISSUE 14 / ROADMAP item 3's
first step: a 1k-host scenario where the per-spec ``build_entry``
sweep runs (a) through the legacy serial dispatch path and (b) through
the batched leaf-shard fan-out (actions/sweep.py) at several worker
counts, under the ARMED freeze auditor (analysis/freezeaudit.py).

Two phases, mirroring how Go separates ``go test -bench`` from
``go test -race`` (the sanitizer taxes every access; nobody quotes
benchmark numbers taken under it):

  measure    auditor DISARMED.  The serial row is the shipped
             fallback path (tiered Session dispatch per node with
             per-plugin trace timing); the parallel rows run the
             same plugins' prepared (PreFilter/PreScore) forms over
             leaf-group shards on a thread pool.  Under CPython's
             GIL the speedup comes from the batched form the fan-out
             architecture demands (task-side hoisting, no per-node
             dispatch), NOT from hardware parallelism —
             ``host_cpus`` is recorded so a multi-core replay can
             separate the two effects.
  certify    auditor ARMED (freeze barriers + fan-out regions).  The
             same sweeps re-run at every worker count plus full
             scheduler cycles; zero race/freeze violations required,
             and every parallel entry is asserted BIT-IDENTICAL to
             the serial entry (fits, scores, heap metadata), or this
             tool exits 1.

Usage:
    python tools/race_bench.py [--hosts 1024] [--out RACE_r15.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WORKER_STEPS = (1, 2, 4, 8)


def build_scenario(hosts: int):
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job
    n_slices = max(1, hosts // 4)            # v5e-16 => 4 hosts/slice
    cluster = make_tpu_cluster(
        [(f"s{i:03d}", "v5e-16") for i in range(n_slices)])
    pg, pods = gang_job("bench", replicas=64,
                        requests={"cpu": 4, "google.com/tpu": 4})
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    return Scheduler(cluster, schedule_period=0)


def bench_entry(ssn, nodes, task, workers: int, reps: int = 9,
                backend: str = "thread"):
    """Best-of-reps build_entry wall time at the given worker count
    (0 = the serial fallback path; backend selects the thread pool or
    the mirror-worker process pool for workers > 0)."""
    from volcano_tpu.actions.sweep import SpecCache
    conf = ssn.conf.configurations.setdefault("allocate", {})
    conf["parallelPredicates"] = backend if workers else False
    conf["parallelPredicates.workers"] = workers or 1
    best, entry = float("inf"), None
    for _ in range(reps):
        cache = SpecCache(ssn, nodes, record_errors=False)
        t0 = time.perf_counter()
        entry = cache.build_entry(task)
        best = min(best, time.perf_counter() - t0)
    return best, entry


def entries_identical(a, b) -> bool:
    return (a["fits"].keys() == b["fits"].keys()
            and a["scores"] == b["scores"]
            and a["meta"] == b["meta"])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="race_bench",
                                 description=__doc__)
    ap.add_argument("--hosts", type=int, default=1024)
    ap.add_argument("--reps", type=int, default=9)
    ap.add_argument("--backends", default="thread",
                    help="comma list of pool backends to row "
                         "(thread, process); RACE_r15.json was "
                         "thread-only, the mirror-worker process "
                         "pool rows land in SCALE100K via bench.py "
                         "--scale-100k")
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    backends = [b.strip() for b in args.backends.split(",")
                if b.strip()]

    from volcano_tpu.analysis import freezeaudit, racecheck
    from volcano_tpu.api.types import TaskStatus
    from volcano_tpu.framework.framework import (close_session,
                                                 open_session)

    # -- phase 1: measure (auditor disarmed) --------------------------
    sched = build_scenario(args.hosts)
    ssn = open_session(sched.cache, sched.conf)
    task = next(t for j in ssn.jobs.values()
                for t in j.tasks_in_status(TaskStatus.PENDING))
    nodes = list(ssn.nodes.values())
    print(f"scenario: {len(nodes)} hosts, spec {task.task_spec!r}",
          flush=True)

    serial_s, serial_entry = bench_entry(ssn, nodes, task, 0,
                                         args.reps)
    rows = []
    for backend in backends:
        for w in WORKER_STEPS:
            t, entry = bench_entry(ssn, nodes, task, w, args.reps,
                                   backend=backend)
            identical = entries_identical(entry, serial_entry)
            rows.append({"backend": backend, "workers": w,
                         "ms": round(t * 1000, 2),
                         "speedup_vs_serial": round(serial_s / t, 2),
                         "entry_identical_to_serial": identical})
            print(f"  {backend} w={w}: {t*1000:.2f} ms "
                  f"({serial_s/t:.2f}x, identical={identical})",
                  flush=True)
    close_session(ssn)

    # -- phase 2: certify (auditor armed) -----------------------------
    freezeaudit.install()
    freezeaudit.reset()
    ssn = open_session(sched.cache, sched.conf)
    ctask = next(t for j in ssn.jobs.values()
                 for t in j.tasks_in_status(TaskStatus.PENDING))
    cnodes = list(ssn.nodes.values())
    _, armed_serial = bench_entry(ssn, cnodes, ctask, 0, reps=1)
    armed_identical = True
    for backend in backends:
        for w in WORKER_STEPS:
            _, entry = bench_entry(ssn, cnodes, ctask, w, reps=2,
                                   backend=backend)
            armed_identical &= entries_identical(entry, armed_serial)
    close_session(ssn)
    # ...and three full scheduler cycles with the parallel sweep on,
    # so the freeze window sees real Statement commits
    conf = sched.conf.configurations.setdefault("allocate", {})
    conf["parallelPredicates"] = True
    conf["parallelPredicates.workers"] = 8
    for _ in range(3):
        sched.run_once()
        sched.cluster.tick()
    audit = freezeaudit.report()
    freezeaudit.uninstall()
    print(f"certify: sessions={audit['sessions_frozen']} "
          f"fanouts={audit['fanout_regions']} "
          f"violations={len(audit['violations'])} "
          f"identical={armed_identical}", flush=True)

    # the static half: reader census + the reasoned waiver inventory
    static = racecheck.build_program(["volcano_tpu", "tools"])
    findings = static.analyze()
    active = [f for f in findings if f.suppressed is None]
    waivers = [{"rule": f.rule, "site": f"{f.path}:{f.line}",
                "reason": f.suppressed}
               for f in findings if f.suppressed is not None]

    doc = {
        "metric": "race_certified_parallel_predicate_sweep",
        "scenario": {
            "hosts": len(nodes), "gang_replicas": 64,
            "spec": task.task_spec,
            "plugins": sorted(ssn.plugins),
        },
        "host_cpus": os.cpu_count(),
        "serial_build_entry_ms": round(serial_s * 1000, 2),
        "parallel": rows,
        "speedup_at_8_workers": next(
            r["speedup_vs_serial"] for r in rows
            if r["workers"] == 8 and r["backend"] == backends[0]),
        "note": ("single-CPU host: the measured speedup is the "
                 "batched prepared-sweep form the fan-out "
                 "architecture enables (task-side hoisting, no "
                 "per-node dispatch), serialized by the GIL; rerun "
                 "on a multi-core host to add hardware parallelism "
                 "on top"),
        "freeze_audit": {
            "sessions_frozen": audit["sessions_frozen"],
            "objects_frozen": audit["objects_frozen"],
            "fanout_regions": audit["fanout_regions"],
            # the TSan-lite half's coverage: the owner-confined
            # stores recorded accesses, so "zero unsync-pairs" below
            # is a certified claim, not a vacuous one
            "tracked_stores": audit["tracked_stores"],
            "entries_identical_under_audit": armed_identical,
            "violations": audit["violations"],
        },
        "static_pass": {
            "snapshot_readers": len(static.readers()),
            "active_findings": len(active),
            "waivers": waivers,
        },
        "ok": (not audit["violations"] and not active
               and armed_identical
               and all(r["entry_identical_to_serial"] for r in rows)),
    }
    if "process" in backends:
        from volcano_tpu.actions import procpool
        procpool.shutdown()
    out = args.out or "RACE_r15.json"
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, default=str)
    print(f"wrote {out}: ok={doc['ok']} "
          f"speedup@8={doc['speedup_at_8_workers']}x", flush=True)
    return 0 if doc["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
