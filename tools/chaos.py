"""Chaos: kill -9 the scheduler process every ~15s under churn.

Five-minute crash-resilience run of the wire control plane: gang jobs
stream in continuously while the scheduler process is SIGKILLed and
restarted every ~15s (with a 0-2s dead window).  The pass criteria:
every submitted job still completes, and no node is ever chip-
overcommitted (the stateless-scheduler + nomination-recovery design,
SURVEY §5).

Round-4 result on the dev machine: 404/404 jobs Completed across 18
scheduler SIGKILLs, zero overcommitted nodes.

Usage:  python tools/chaos.py          # logs to /tmp/chaos/
"""
import json, os, random, signal, socket, subprocess, sys, time
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")

def free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0)); return s.getsockname()[1]

port = free_port()
server = subprocess.Popen(
    [sys.executable, "-m", "volcano_tpu.server", "--port", str(port),
     "--tick-period", "0.2"], env=env, cwd=REPO,
    stdout=open("/tmp/chaos/server.log", "w"), stderr=subprocess.STDOUT)
time.sleep(2)
ctrl = subprocess.Popen(
    [sys.executable, "-m", "volcano_tpu", "--cluster-url",
     f"http://127.0.0.1:{port}", "--components", "controllers",
     "--period", "0.2"], env=env, cwd=REPO,
    stdout=open("/tmp/chaos/ctrl.log", "w"), stderr=subprocess.STDOUT)

def spawn_sched():
    return subprocess.Popen(
        [sys.executable, "-m", "volcano_tpu", "--cluster-url",
         f"http://127.0.0.1:{port}", "--components", "scheduler",
         "--period", "0.2"], env=env, cwd=REPO,
        stdout=open("/tmp/chaos/sched.log", "a"), stderr=subprocess.STDOUT)

sched = spawn_sched()

from volcano_tpu.cache.remote_cluster import RemoteCluster
from volcano_tpu.api.devices.tpu.topology import slice_for
from volcano_tpu.simulator import slice_nodes
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import RUN_TICKS_ANNOTATION

c = RemoteCluster(f"http://127.0.0.1:{port}")
for sname in ("sa", "sb"):
    for node in slice_nodes(slice_for(sname, "v5e-16"), dcn_pod="d0"):
        c.put_object("node", node)

rng = random.Random(99)
submitted = 0
kills = 0
t_end = time.time() + 300
last_kill = time.time()
i = 0
while time.time() < t_end:
    n = rng.choice((1, 2, 4))
    job = VCJob(name=f"chaos-{i}", min_available=n,
                tasks=[TaskSpec(name="worker", replicas=n,
                                template=make_pod("t", requests={"cpu": 4, TPU: 4},
                                                  annotations={RUN_TICKS_ANNOTATION: "3"}))],
                plugins={"jax": [], "svc": []})
    try:
        c.add_vcjob(job); submitted += 1
    except Exception as e:
        print("submit failed:", e, flush=True)
    i += 1
    time.sleep(rng.uniform(0.4, 1.0))
    if time.time() - last_kill > 15:
        os.kill(sched.pid, signal.SIGKILL)
        sched.wait()
        kills += 1
        time.sleep(rng.uniform(0.0, 2.0))   # dead window
        sched = spawn_sched()
        last_kill = time.time()

# let the dust settle
time.sleep(20)
c.resync()
phases = {}
for j in c.vcjobs.values():
    ph = getattr(j.phase, "value", str(j.phase))
    phases[ph] = phases.get(ph, 0) + 1
# double-bind check: every bound/running pod appears on exactly one node,
# and no node exceeds its chip capacity
overcommit = []
node_chips = {}
for p in c.pods.values():
    if p.node_name and getattr(p.phase, "value", "") in ("Running", "Bound"):
        node_chips[p.node_name] = node_chips.get(p.node_name, 0) + \
            p.resource_requests().get(TPU)
for n, used in node_chips.items():
    if used > 4.01:
        overcommit.append((n, used))
print(json.dumps({"submitted": submitted, "kills": kills,
                  "phases": phases, "overcommitted_nodes": overcommit}))
for p in (server, ctrl, sched):
    p.terminate()
