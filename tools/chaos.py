"""Chaos: kill -9 the scheduler process every ~15s under churn.

Five-minute crash-resilience run of the wire control plane: gang jobs
stream in continuously while the scheduler process is SIGKILLed and
restarted every ~15s (with a 0-2s dead window).  The pass criteria:
every submitted job still completes, and no node is ever chip-
overcommitted (the stateless-scheduler + nomination-recovery design,
SURVEY §5).

Round-4 result on the dev machine: 404/404 jobs Completed across 18
scheduler SIGKILLs, zero overcommitted nodes.

A thin schedule over tools/chaoslib.py (shared proxy/zoo/audit
plumbing); the randomized gray-failure conductor lives in
tools/chaos_conductor.py.

Usage:  python tools/chaos.py          # logs to /tmp/chaos/
"""
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools import chaoslib  # noqa: E402

port = chaoslib.free_port()
url = f"http://127.0.0.1:{port}"
zoo = chaoslib.ProcessZoo("/tmp/chaos")
zoo.spawn_server(port)
chaoslib.wait_server(url)
zoo.spawn_plane("ctrl", url, "controllers")
zoo.spawn_plane("sched", url, "scheduler")

from volcano_tpu.cache.remote_cluster import RemoteCluster  # noqa: E402

c = RemoteCluster(url)
chaoslib.seed_slices(c, ("sa", "sb"))

rng = random.Random(99)
submitted = 0
kills = 0
t_end = time.time() + 300
last_kill = time.time()
i = 0
while time.time() < t_end:
    n = rng.choice((1, 2, 4))
    try:
        c.add_vcjob(chaoslib.gang_job(f"chaos-{i}", n))
        submitted += 1
    except Exception as e:  # noqa: BLE001
        print("submit failed:", e, flush=True)
    i += 1
    time.sleep(rng.uniform(0.4, 1.0))
    if time.time() - last_kill > 15:
        zoo.kill9("sched")
        kills += 1
        time.sleep(rng.uniform(0.0, 2.0))   # dead window
        zoo.respawn("sched")
        last_kill = time.time()

# let the dust settle
time.sleep(20)
c.resync()
print(json.dumps({
    "submitted": submitted, "kills": kills,
    "phases": chaoslib.phase_counts(c),
    "overcommitted_nodes": chaoslib.overcommit_audit(c)}))
zoo.terminate_all()
