"""JobFlow DAGs, cronjobs, node agent, cache dumper, CLI."""

import json
import os
import subprocess
import sys
import time

from volcano_tpu.api.jobflow import Flow, FlowDependsOn, JobFlow, \
    JobFlowPhase, JobTemplate
from volcano_tpu.api.pod import Container, Pod
from volcano_tpu.api.types import JobPhase, TaskStatus
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.controllers.cronjob import CronJob, cron_matches
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.webhooks import default_admission


def template(name, replicas=1):
    return JobTemplate(name=name, job=VCJob(
        name=name, min_available=replicas,
        tasks=[TaskSpec(name="w", replicas=replicas,
                        template=Pod(name="t", containers=[
                            Container(requests={"cpu": 1})]))]))


def mk_stack():
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=[
        "job", "jobflow", "cronjob", "garbagecollector"])
    sched = Scheduler(cluster, schedule_period=0)
    return cluster, mgr, sched


def pump(cluster, mgr, sched, n=3):
    for _ in range(n):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()


def test_jobflow_dag_executes_in_dependency_order():
    cluster, mgr, sched = mk_stack()
    cluster.jobtemplates = {"default/prep": template("prep"),
                            "default/train": template("train"),
                            "default/eval": template("eval")}
    flow = JobFlow(name="pipeline", flows=[
        Flow(name="prep"),
        Flow(name="train", depends_on=FlowDependsOn(targets=["prep"])),
        Flow(name="eval", depends_on=FlowDependsOn(targets=["train"])),
    ])
    cluster.jobflows = {flow.key: flow}

    pump(cluster, mgr, sched)
    assert "default/pipeline-prep" in cluster.vcjobs
    assert "default/pipeline-train" not in cluster.vcjobs  # dep not done

    # finish prep -> train deploys; finish train -> eval deploys
    for pod in list(cluster.pods.values()):
        if pod.name.startswith("pipeline-prep"):
            cluster.complete_pod(pod.key)
    pump(cluster, mgr, sched)
    assert "default/pipeline-train" in cluster.vcjobs
    for pod in list(cluster.pods.values()):
        if pod.name.startswith("pipeline-train") and not pod.is_terminated():
            cluster.complete_pod(pod.key)
    pump(cluster, mgr, sched)
    assert "default/pipeline-eval" in cluster.vcjobs
    for pod in list(cluster.pods.values()):
        if pod.name.startswith("pipeline-eval") and not pod.is_terminated():
            cluster.complete_pod(pod.key)
    pump(cluster, mgr, sched)
    assert cluster.jobflows[flow.key].phase is JobFlowPhase.SUCCEED


def test_cron_matcher():
    # 2026-07-28 is a Tuesday
    ts = time.mktime((2026, 7, 28, 3, 15, 0, 0, 0, -1))
    assert cron_matches("15 3 * * *", ts)
    assert cron_matches("*/5 * * * *", ts)
    assert not cron_matches("16 3 * * *", ts)
    assert cron_matches("* * 28 7 *", ts)
    assert cron_matches("* * * * 2", ts)      # Tuesday
    assert not cron_matches("* * * * 0", ts)  # not Sunday
    assert cron_matches("0-30 * * * *", ts)


def test_cronjob_fires_and_respects_forbid():
    cluster, mgr, sched = mk_stack()
    cron = CronJob(name="nightly", schedule="* * * * *",
                   concurrency_policy="Forbid",
                   job_template=template("nightly").job)
    cluster.cronjobs = {cron.key: cron}
    ctrl = next(c for c in mgr.controllers if c.name == "cronjob")
    now = time.time()
    ctrl.sync_cron(cron, now)
    assert len(cron.active_jobs) == 1
    # same minute: no double fire; next minute with active job: Forbid
    ctrl.sync_cron(cron, now + 1)
    ctrl.sync_cron(cron, now + 61)
    assert len(cron.active_jobs) == 1


def test_node_agent_reports_and_cordons_unhealthy_tpu():
    """Chip health cordons with K-consecutive-ticks hysteresis BOTH
    directions: one bad telemetry sample no longer cordons (and one
    good one no longer uncordons) — a flapping exporter used to bounce
    the host in and out of rotation every sync."""
    from volcano_tpu.agent import FakeUsageProvider, NodeAgent
    from volcano_tpu.agent.handlers import TpuHealthHandler
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=0.5, tpu_chips_detected=4,
                 tpu_chips_healthy=3)   # one sick chip
    agent = NodeAgent(cluster, "sa-w0", provider)
    node = cluster.nodes["sa-w0"]
    for _ in range(TpuHealthHandler.FAIL_SYNCS - 1):
        agent.sync()
        assert node.unschedulable is False          # suspect, not out
        assert node.labels["volcano-tpu.io/tpu-healthy"] == "true"
    agent.sync()                       # Kth consecutive bad -> Failed
    assert node.unschedulable is True
    assert node.labels["volcano-tpu.io/tpu-healthy"] == "false"
    assert node.annotations["volcano-tpu.io/tpu-chips"] == "3/4"
    # chip recovers: one good sample must NOT uncordon...
    provider.set("sa-w0", cpu_fraction=0.5, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    agent.sync()
    assert node.unschedulable is True
    # ...K consecutive good ones do
    for _ in range(TpuHealthHandler.RECOVER_SYNCS - 1):
        agent.sync()
    assert cluster.nodes["sa-w0"].unschedulable is False
    assert node.labels["volcano-tpu.io/tpu-healthy"] == "true"


def test_node_agent_oversubscription_and_pressure_eviction():
    from volcano_tpu.agent import FakeUsageProvider, NodeAgent
    from volcano_tpu.api.pod import make_pod
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    be_pod = make_pod("be", node_name="sa-w1", phase=TaskStatus.RUNNING,
                      annotations={"volcano-tpu.io/qos-level": "BE"})
    cluster.add_pod(be_pod)
    provider = FakeUsageProvider()
    provider.set("sa-w1", cpu_fraction=0.98, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    NodeAgent(cluster, "sa-w1", provider).sync()
    assert "default/be" in cluster.evictions
    assert cluster.nodes["sa-w1"].annotations[
        "oversubscription.volcano-tpu.io/cpu-millis"] == "0"


def test_cache_dumper(tmp_path):
    from volcano_tpu.dumper import Dumper
    cluster, mgr, sched = mk_stack()
    cluster.add_vcjob(template("dumpme").job)
    pump(cluster, mgr, sched, n=2)
    path = str(tmp_path / "dump.json")
    out = Dumper(sched, path).dump()
    data = json.loads(open(out).read())
    assert "default/dumpme" in data["jobs"]
    assert len(data["nodes"]) == 4


def test_cli_end_to_end(tmp_path):
    state = str(tmp_path / "cluster.pkl")
    env = dict(os.environ, PYTHONPATH=os.getcwd())

    def run(*args):
        return subprocess.run(
            [sys.executable, "-m", "volcano_tpu.cli.vtpctl",
             "--state", state, *args],
            capture_output=True, text=True, env=env, check=True).stdout

    run("init", "--slices", "sa=v5e-16")
    run("queue", "create", "-N", "research", "--weight", "3")
    run("job", "run", "-N", "train", "--replicas", "4", "--tpu", "4",
        "--cpu", "4", "--queue", "research", "--plugins", "jax,svc")
    run("tick", "--cycles", "3")
    listing = run("job", "list")
    assert "train" in listing and "Running" in listing
    view = json.loads(run("job", "view", "-N", "train"))
    assert view["status"]["running"] == 4
    assert all(p["node"].startswith("sa-") for p in view["pods"])
    queues = run("vqueues")
    assert "research" in queues


def test_jobtemplate_cli_feeds_jobflow(tmp_path):
    """jobtemplate create -f + jobflow create drive a DAG end-to-end
    through the CLI."""
    state = str(tmp_path / "c.pkl")
    env = dict(os.environ, PYTHONPATH=os.getcwd())

    def run(*args):
        return subprocess.run(
            [sys.executable, "-m", "volcano_tpu.cli.vtpctl",
             "--state", state, *args],
            capture_output=True, text=True, env=env, check=True).stdout

    manifest = tmp_path / "steps.yaml"
    manifest.write_text("""
kind: Job
metadata: {name: prep}
spec:
  minAvailable: 1
  tasks:
    - name: w
      replicas: 1
      template:
        spec:
          containers: [{resources: {requests: {cpu: "1"}}}]
---
kind: Job
metadata: {name: train}
spec:
  minAvailable: 1
  tasks:
    - name: w
      replicas: 1
      template:
        spec:
          containers: [{resources: {requests: {cpu: "1"}}}]
""")
    run("init", "--slices", "sa=v5e-16")
    run("jobtemplate", "create", "-f", str(manifest))
    assert "prep" in run("jobtemplate", "list")
    run("jobflow", "create", "-N", "pipe", "--flows", "prep",
        "train:prep")
    run("tick", "--cycles", "2")
    assert "pipe-prep" in run("job", "list")


def test_scheduler_conf_hot_reload(tmp_path):
    """Editing the conf file mid-run changes the actions on the next
    cycle (reference: fsnotify hot reload)."""
    conf_path = tmp_path / "conf.yaml"
    conf_path.write_text(
        "actions: \"enqueue, allocate\"\n"
        "tiers:\n  - plugins:\n      - name: gang\n"
        "      - name: predicates\n      - name: nodeorder\n")
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    sched = Scheduler(cluster, conf_path=str(conf_path),
                      schedule_period=0)
    sched.run_once()
    assert sched.conf.actions == ["enqueue", "allocate"]
    conf_path.write_text(
        "actions: \"allocate, backfill\"\n"
        "tiers:\n  - plugins:\n      - name: gang\n"
        "      - name: predicates\n      - name: nodeorder\n")
    os.utime(conf_path, (time.time() + 2, time.time() + 2))
    sched.run_once()
    assert sched.conf.actions == ["allocate", "backfill"]


def test_scheduling_gate_lifted_on_admission():
    """Pods gated on queue admission schedule only after their
    PodGroup leaves Pending (SchGateManager analogue)."""
    from volcano_tpu.uthelper import TestContext, gang_job
    from volcano_tpu.framework.job_updater import QUEUE_ADMISSION_GATE
    from volcano_tpu.api.node_info import Node
    pg, pods = gang_job("gated", replicas=2, requests={"cpu": 1})
    for p in pods:
        p.scheduling_gates.append(QUEUE_ADMISSION_GATE)
    ctx = TestContext(nodes=[Node(name="n0", allocatable={"cpu": 8})],
                      podgroups=[pg], pods=pods)
    ctx.run()
    # cycle 1: enqueue admits, gates lifted at close — no binds yet
    assert all(not p.scheduling_gates for p in pods)
    ctx.expect_bind_num(0)
    ctx.run()
    ctx.expect_bind_num(2)  # cycle 2: gates gone, gang binds


def test_prometheus_usage_source_feeds_agent():
    """The Prometheus client source scrapes a live endpoint and drives
    the node agent's usage annotations."""
    import urllib.request
    from volcano_tpu import metrics
    from volcano_tpu.agent import NodeAgent
    from volcano_tpu.metrics_source import PrometheusUsageSource

    metrics.reset()
    metrics.set_gauge("node_cpu_usage_fraction", 0.77, node="sa-w0")
    metrics.set_gauge("node_memory_usage_fraction", 0.33, node="sa-w0")
    server = metrics.serve(port=0)
    try:
        url = f"http://127.0.0.1:{server.server_address[1]}/metrics"
        source = PrometheusUsageSource(url)
        assert source.refresh()
        cluster = make_tpu_cluster([("sa", "v5e-16")])
        NodeAgent(cluster, "sa-w0", source).sync()
        node = cluster.nodes["sa-w0"]
        assert node.annotations["usage.volcano-tpu.io/cpu"] == "0.770"
        assert node.annotations["usage.volcano-tpu.io/memory"] == "0.330"
    finally:
        server.shutdown()


def test_prometheus_source_degrades_on_unreachable_endpoint():
    from volcano_tpu.metrics_source import PrometheusUsageSource
    source = PrometheusUsageSource("http://127.0.0.1:1/metrics",
                                   timeout=0.2)
    assert source.refresh() is False
    assert source.usage("any").cpu_fraction == 0.0


def test_agent_cpu_and_network_qos_handlers():
    """Burst/throttle + DCN split published from real usage."""
    from volcano_tpu.agent import FakeUsageProvider, NodeAgent
    from volcano_tpu.api.pod import make_pod
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    be = make_pod("be", node_name="sa-w0", phase=TaskStatus.RUNNING,
                  requests={"cpu": 2},
                  annotations={"volcano-tpu.io/qos-level": "BE"})
    guaranteed = make_pod("g", node_name="sa-w0",
                          phase=TaskStatus.RUNNING,
                          requests={"cpu": 4})
    cluster.add_pod(be)
    cluster.add_pod(guaranteed)
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=0.5, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    NodeAgent(cluster, "sa-w0", provider).sync()

    # BE: burst sized from the NODE's idle (112 cpu * 0.5), not the
    # pod's request (true best-effort pods request nothing)
    assert be.annotations["qos.volcano-tpu.io/cpu-burst-millis"] == "56000"
    assert be.annotations["qos.volcano-tpu.io/cpu-throttled"] == "false"
    # guaranteed: fixed headroom, no throttle key
    assert guaranteed.annotations[
        "qos.volcano-tpu.io/cpu-burst-millis"] == "800"
    assert "qos.volcano-tpu.io/cpu-throttled" not in guaranteed.annotations
    # DCN split: 40% offline at low pressure, BE pod gets its share
    node = cluster.nodes["sa-w0"]
    assert node.annotations[
        "networkqos.volcano-tpu.io/offline-limit-mbps"] == "40000"
    assert node.annotations[
        "networkqos.volcano-tpu.io/online-guarantee-mbps"] == "60000"
    assert be.annotations[
        "networkqos.volcano-tpu.io/pod-limit-mbps"] == "40000"

    # pressure shrinks the offline share and throttles BE bursting
    provider.set("sa-w0", cpu_fraction=0.9, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    NodeAgent(cluster, "sa-w0", provider).sync()
    assert node.annotations[
        "networkqos.volcano-tpu.io/offline-limit-mbps"] == "10000"
    assert be.annotations["qos.volcano-tpu.io/cpu-throttled"] == "true"
    # throttled => burst zeroed (no contradictory signals)
    assert be.annotations["qos.volcano-tpu.io/cpu-burst-millis"] == "0"


def test_elasticsearch_usage_source_end_to_end():
    """ES aggregation query -> per-node usage -> agent annotations."""
    import http.server
    import json as _json
    import threading
    from volcano_tpu.agent import NodeAgent
    from volcano_tpu.metrics_source import ElasticsearchUsageSource

    seen = {}

    class FakeES(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = _json.loads(
                self.rfile.read(int(self.headers["Content-Length"])))
            seen["path"] = self.path
            seen["query"] = body
            resp = _json.dumps({"aggregations": {"nodes": {"buckets": [
                {"key": "sa-w0", "cpu": {"value": 0.66},
                 "mem": {"value": 0.25}},
                {"key": "sa-w1", "cpu": {"value": None},
                 "mem": {"value": 0.10}},
            ]}}}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(resp)))
            self.end_headers()
            self.wfile.write(resp)

        def log_message(self, *a):  # quiet
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeES)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        source = ElasticsearchUsageSource(
            f"http://127.0.0.1:{server.server_address[1]}")
        assert source.refresh()
        # one terms-by-host search against the configured index
        assert seen["path"] == "/metricbeat-*/_search"
        assert seen["query"]["aggs"]["nodes"]["terms"]["field"] == \
            "host.hostname"
        assert source.usage("sa-w0").cpu_fraction == 0.66
        assert source.usage("sa-w1").cpu_fraction == 0.0  # null avg
        assert source.usage("missing").cpu_fraction == 0.0

        cluster = make_tpu_cluster([("sa", "v5e-16")])
        NodeAgent(cluster, "sa-w0", source).sync()
        assert cluster.nodes["sa-w0"].annotations[
            "usage.volcano-tpu.io/cpu"] == "0.660"
    finally:
        server.shutdown()


def test_elasticsearch_source_degrades_and_goes_stale():
    from volcano_tpu.metrics_source import ElasticsearchUsageSource
    source = ElasticsearchUsageSource("http://127.0.0.1:1", timeout=0.2)
    assert source.refresh() is False
    assert source.usage("any").cpu_fraction == 0.0
    # a successful past refresh past its TTL reads as unknown too
    source._usage = {"n": __import__(
        "volcano_tpu.agent.agent", fromlist=["NodeUsage"]
    ).NodeUsage(cpu_fraction=0.9)}
    source._last_success = 1.0  # epoch: long past stale_after
    assert source.usage("n").cpu_fraction == 0.0


def test_cli_node_list_and_view(tmp_path, capsys):
    """vtpctl node list/view over a provisioned slice with agent data."""
    import pickle
    from volcano_tpu.agent import FakeUsageProvider, NodeAgent
    from volcano_tpu.api.numatopology import tpu_host_numatopology
    from volcano_tpu.cli.vtpctl import main
    state = str(tmp_path / "c.pkl")
    assert main(["--state", state, "init", "--slices", "sa=v5e-16"]) == 0
    c = pickle.load(open(state, "rb"))
    c.add_numatopology(tpu_host_numatopology("sa-w0", 112000, 4))
    prov = FakeUsageProvider()
    prov.set("sa-w0", cpu_fraction=0.5, tpu_chips_detected=4,
             tpu_chips_healthy=4)
    NodeAgent(c, "sa-w0", prov).sync()
    pickle.dump(c, open(state, "wb"))
    capsys.readouterr()
    assert main(["--state", state, "node", "list"]) == 0
    out = capsys.readouterr().out
    assert "sa-w0" in out and "ready" in out and "0.500" in out
    assert main(["--state", state, "node", "view", "-N", "sa-w0"]) == 0
    out = capsys.readouterr().out
    assert "NUMA topology" in out and "TopologyManagerPolicy" in out
    import pytest
    with pytest.raises(SystemExit):
        main(["--state", state, "node", "view", "-N", "nosuch"])


def test_slurm_shortcuts_and_agent_healthz(tmp_path):
    """vsub/vcancel/vsuspend/vresume aliases (reference standalone
    binaries, Makefile:281) + the node agent /healthz endpoint
    (reference pkg/agent/healthcheck)."""
    state = str(tmp_path / "cluster.pkl")
    env = dict(os.environ, PYTHONPATH=os.getcwd())

    def run(*args):
        return subprocess.run(
            [sys.executable, "-m", "volcano_tpu.cli.vtpctl",
             "--state", state, *args],
            capture_output=True, text=True, env=env, check=True).stdout

    run("init", "--slices", "sa=v5e-16")
    run("vsub", "-N", "train", "--replicas", "2", "--tpu", "4")
    run("tick", "--cycles", "3")
    assert "Running" in run("vjobs")
    run("vsuspend", "-N", "train")
    run("tick", "--cycles", "2")
    assert "Abort" in run("vjobs")
    run("vresume", "-N", "train")
    run("vcancel", "-N", "train")
    assert "train" not in run("vjobs")

    # agent healthz: 503 before first sync, 200 after
    import json as _json
    import urllib.request

    from volcano_tpu.agent import NodeAgent
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    agent = NodeAgent(cluster, "sa-w0")
    server = agent.serve_health(port=0)
    port = server.server_address[1]
    try:
        try:
            urllib.request.urlopen(f"http://127.0.0.1:{port}/healthz")
            raise AssertionError("expected 503 before first sync")
        except urllib.error.HTTPError as e:
            assert e.code == 503
        agent.sync()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz") as resp:
            body = _json.loads(resp.read())
        assert body["healthy"] and body["node"] == "sa-w0"
    finally:
        server.shutdown()


def test_mpi_admission_mutate_adds_depends_on():
    """The MPI mutating admission plugin defaults the master task's
    dependsOn to the worker task (reference admission/jobs/plugins/
    mpi)."""
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    from volcano_tpu.webhooks.admission import mutate_job

    job = VCJob(name="mpijob", plugins={"mpi": []}, tasks=[
        TaskSpec(name="master", replicas=1),
        TaskSpec(name="worker", replicas=4),
    ])
    mutate_job(job)
    assert job.tasks[0].depends_on is not None
    assert job.tasks[0].depends_on.name == ["worker"]
    # explicit dependsOn is left alone; custom names honored
    job2 = VCJob(name="m2", plugins={"mpi": ["--master=launcher",
                                             "--worker=trainer"]},
                 tasks=[TaskSpec(name="launcher", replicas=1),
                        TaskSpec(name="trainer", replicas=2)])
    mutate_job(job2)
    assert job2.tasks[0].depends_on.name == ["trainer"]


def test_cli_get_describe_delete_verbs(tmp_path):
    """queue get/delete + jobflow/jobtemplate get/describe/delete
    (reference pkg/cli/{queue,jobflow,jobtemplate}/{get,describe,delete}.go)."""
    state = str(tmp_path / "c.pkl")
    env = dict(os.environ, PYTHONPATH=os.getcwd())

    def run(*args, ok=True):
        r = subprocess.run(
            [sys.executable, "-m", "volcano_tpu.cli.vtpctl",
             "--state", state, *args],
            capture_output=True, text=True, env=env)
        if ok:
            assert r.returncode == 0, r.stderr
        return r

    run("init", "--slices", "sa=v5e-4")
    run("queue", "create", "-N", "research", "--weight", "3")
    out = run("queue", "get", "-N", "research").stdout
    assert "weight: 3" in out and "state: Open" in out

    manifest = tmp_path / "t.yaml"
    manifest.write_text("""
kind: Job
metadata: {name: step}
spec:
  minAvailable: 1
  tasks:
    - name: w
      replicas: 2
      template:
        spec:
          containers:
            - resources:
                requests: {cpu: 1}
""")
    run("jobtemplate", "create", "-f", str(manifest))
    out = run("jobtemplate", "describe", "-N", "step").stdout
    assert "replicas: 2" in out
    out = run("jobtemplate", "get", "-N", "step").stdout
    assert "step" in out

    run("jobflow", "create", "-N", "fl", "--flows", "step")
    out = run("jobflow", "describe", "-N", "fl").stdout
    assert "name: step" in out and "state: pending" in out
    # tick lets the jobflow controller deploy the dependency-free step;
    # describe must report it deployed (keys are "<ns>/<flow>-<step>")
    run("tick", "--cycles", "2")
    out = run("jobflow", "describe", "-N", "fl").stdout
    assert "state: deployed" in out
    assert "fl" in run("jobflow", "get", "-N", "fl").stdout

    # queue with podgroups refuses delete without --force
    run("job", "run", "-N", "j1", "--replicas", "1", "--cpu", "1",
        "--queue", "research")
    r = run("queue", "delete", "-N", "research", ok=False)
    assert r.returncode != 0 and "podgroup" in r.stderr
    run("queue", "delete", "-N", "research", "--force")
    assert "research" not in run("queue", "list").stdout

    run("jobflow", "delete", "-N", "fl")
    assert "fl" not in run("jobflow", "list").stdout
    run("jobtemplate", "delete", "-N", "step")
    assert "step" not in run("jobtemplate", "list").stdout


def test_jobflow_delete_reaps_jobs_with_delete_retain_policy():
    """Deleting a flow whose job_retain_policy is 'delete' reaps the
    stamped jobs/podgroups/pods (ownerReference-GC analogue); 'retain'
    (the default) leaves them running."""
    from volcano_tpu.cache.fake_cluster import FakeCluster

    def build(retain):
        cluster = FakeCluster()
        cluster.put_object("jobtemplate", template("step"))
        flow = JobFlow(name="fl", flows=[Flow(name="step")],
                       job_retain_policy=retain)
        cluster.put_object("jobflow", flow)
        mgr = ControllerManager(cluster, enabled=["job", "jobflow"])
        mgr.sync_all()
        assert "default/fl-step" in cluster.vcjobs
        cluster.delete_object("jobflow", "default/fl")
        mgr.stop()
        return cluster

    reaped = build("delete")
    assert "default/fl-step" not in reaped.vcjobs
    assert "default/fl-step" not in reaped.podgroups
    retained = build("retain")
    assert "default/fl-step" in retained.vcjobs


def test_pod_describe_and_reason_column(tmp_path, capsys):
    """`pod describe` surfaces state + scheduling reason; `pod list`
    grows a REASON column for pending pods (scheduling-reason.md
    triage surface)."""
    import json as _json
    from volcano_tpu.cli import vtpctl
    state = str(tmp_path / "c.pkl")
    assert vtpctl.main(["--state", state, "init",
                        "--slices", "sa=v5e-16"]) == 0
    assert vtpctl.main(["--state", state, "job", "run", "-N", "big",
                        "--replicas", "5", "--min-available", "5",
                        "--cpu", "8", "--tpu", "4"]) == 0
    assert vtpctl.main(["--state", state, "tick"]) == 0
    capsys.readouterr()
    assert vtpctl.main(["--state", state, "pod", "describe",
                        "-N", "big-worker-4"]) == 0
    out = _json.loads(capsys.readouterr().out)
    assert out["phase"] == "Pending"
    assert out.get("schedulingReason") in ("Unschedulable",
                                           "Schedulable")
    assert out.get("message")
    assert vtpctl.main(["--state", state, "pod", "list"]) == 0
    listing = capsys.readouterr().out
    assert "REASON" in listing and "Unschedulable" in listing
