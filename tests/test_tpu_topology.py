"""ICI mesh math + TPU device semantics."""

import pytest

from volcano_tpu.api.devices.tpu.topology import (
    SliceTopology, chips_in, diameter, host_coords, host_grid,
    ici_distance, parse_topology, slice_for,
)


def test_parse_topology():
    assert parse_topology("16x16") == (16, 16)
    assert parse_topology("4x4x8") == (4, 4, 8)
    assert parse_topology("") == ()
    assert parse_topology("axb") == ()


def test_v5e_256_shape():
    s = slice_for("s", "v5e-256")
    assert s.num_chips == 256
    assert s.chips_per_host == 4
    assert s.num_hosts == 64
    assert s.is_multi_host
    assert host_grid(s.topology) == (8, 8)


def test_v5p_3d_shape():
    s = slice_for("s", "v5p-256")
    assert s.topology == (4, 8, 8)
    assert s.num_chips == 256
    assert s.num_hosts == 64
    assert host_grid(s.topology) == (2, 4, 8)


def test_host_coords_row_major_and_distance():
    topo = (4, 4)  # v5e-16: host grid 2x2
    assert host_coords(0, topo) == (0, 0)
    assert host_coords(1, topo) == (0, 1)
    assert host_coords(2, topo) == (1, 0)
    assert host_coords(3, topo) == (1, 1)
    assert ici_distance((0, 0), (1, 1)) == 2
    # torus wraparound halves long hops
    assert ici_distance((0,), (3,), torus=(4,)) == 1


def test_diameter():
    assert diameter((16, 16)) == 14  # 8x8 host mesh
    assert diameter((4, 4)) == 2


def test_single_host_slice():
    s = slice_for("s", "v5e-4")
    assert not s.is_multi_host
    assert s.num_hosts == 1
