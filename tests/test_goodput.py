"""Goodput observatory (ISSUE 9): measured step rates, learned
per-generation throughput vectors, fragmentation/starvation gauges.

The subsystem spans four layers:

  workload   (workloads/progress.py + worker.py): per-step progress
      records written atomically to the jax-plugin-injected
      VTP_PROGRESS_FILE, stamped with the control plane's
      restart/resize epoch;
  agent      (agent/collect.py GoodputCollector + handlers.py
      GoodputHandler): EWMA step rates off the SHARED RateWindow
      machinery (util.py — the netaccounting counter logic, factored),
      a productive-vs-allocated time ledger, one GoodputReport per
      node per sync;
  store      (cache/fake_cluster.py): the report folds into PODGROUP
      annotations, accumulating the ledger across nodes and sticking
      across whole-podgroup writes from stale mirrors;
  scheduler  (volcano_tpu/goodput.py + cache/cache.py): the
      ThroughputBook learns per-(job, generation) vectors from watch
      events, sessions export frag_*/starvation_*/goodput_* gauges,
      and the elastic action's grow gate declines a grow whose last
      measured speedup fell below threshold (the minimal Pollux step).
"""

import json
import os
import pickle
import subprocess
import sys
import time

import pytest

from volcano_tpu import goodput as gp
from volcano_tpu import metrics, trace
from volcano_tpu.agent.agent import FakeUsageProvider, NodeAgent
from volcano_tpu.agent.collect import GoodputCollector
from volcano_tpu.agent.handlers import GoodputHandler
from volcano_tpu.api import elastic as eapi
from volcano_tpu.api import goodput as gapi
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.podgroup import PodGroup
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (
    GROUP_NAME_ANNOTATION,
    JobPhase,
    PodGroupPhase,
    TaskStatus,
)
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.util import RateWindow
from volcano_tpu.webhooks import default_admission

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ELASTIC_CONF = {
    "actions": "enqueue, allocate, elastic, gangpreempt, backfill",
    "tiers": [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "failover"}, {"name": "elastic"},
                     {"name": "conformance"}]},
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"}, {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "binpack"},
                     {"name": "deviceshare"},
                     {"name": "network-topology-aware"}]},
    ],
    "configurations": {"elastic": {"elastic.cooldownSeconds": 0}},
}


class Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float = 1.0) -> None:
        self.t += dt


def write_progress(root, uid, step, ts, epoch=0, examples=0.0):
    from volcano_tpu.workloads.progress import ProgressReporter
    r = ProgressReporter(gapi.progress_file_for(root, uid),
                        epoch=epoch, now=lambda: ts)
    assert r.report(step=step, examples=examples)


# -- shared RateWindow helper (satellite: one copy of the EWMA /
#    counter-reset machinery) ------------------------------------------

def test_rate_window_policies():
    w = RateWindow(alpha=0.5, reset="absolute", scale=1.0)
    assert w.fold(0, 0.0) == 0.0            # opens the window
    assert w.fold(100, 10.0) == pytest.approx(10.0)
    # EWMA folds the next window
    assert w.fold(100, 20.0) == pytest.approx(5.0)
    # absolute reset: the new value IS the delta
    assert w.fold(50, 30.0) == pytest.approx(0.5 * 5 + 0.5 * 5.0)

    r = RateWindow(alpha=0.5, reset="restart")
    r.fold(100, 0.0)
    assert r.fold(110, 10.0) == pytest.approx(1.0)
    # restart policy: a lower reading re-opens the window, NO delta
    assert r.fold(40, 20.0) == pytest.approx(1.0)
    assert r.fold(50, 30.0) == pytest.approx(1.0)   # 10/10 folded
    # a None reading leaves the window untouched (spans to next read)
    assert r.fold(None, 40.0) == pytest.approx(1.0)
    assert r.fold(70, 50.0) == pytest.approx(
        0.5 * (20 / 20) + 0.5 * 1.0)
    # explicit restart (epoch signal) drops the window, keeps the rate
    r.restart()
    assert r.last is None and r.rate > 0

    # net-accounting parity: the refactored collector still computes
    # the exact rates the pre-refactor inline fold did (tested in
    # test_net_accounting.py against the fake cgroup fs)
    with pytest.raises(ValueError):
        RateWindow(reset="bogus")


# -- collector: progress files -> rates + goodput ledger ---------------

def test_collector_step_rate_and_goodput_ledger(tmp_path):
    root = str(tmp_path)
    clock = Clock()
    col = GoodputCollector(root, now=clock)
    write_progress(root, "u1", step=100, ts=1000.0)
    col.collect("n0")                        # baseline
    clock.tick(10)
    write_progress(root, "u1", step=110, ts=1010.0)
    totals = col.collect("n0")
    st = col.rates()["u1"]
    assert st.steps_per_s == pytest.approx(1.0)
    assert totals["goodput_steps_per_s"] == pytest.approx(1.0)
    assert st.allocated_s == pytest.approx(10.0)
    assert st.productive_s == pytest.approx(10.0)
    assert st.goodput == pytest.approx(1.0)
    assert not st.stalled

    # a stalled window (no step advance) is allocated-but-unproductive
    clock.tick(10)
    col.collect("n0")
    st = col.rates()["u1"]
    assert st.allocated_s == pytest.approx(20.0)
    assert st.productive_s == pytest.approx(10.0)
    assert st.goodput == pytest.approx(0.5)
    assert st.stalled

    # the ledger reconciles: productive + unproductive == allocated
    assert st.allocated_s == pytest.approx(
        st.productive_s + (st.allocated_s - st.productive_s))

    # productive credit is bounded by the WORKER's own clock: a pod
    # that stepped for 2s of a 10s window gets 2s, not 10
    clock.tick(10)
    write_progress(root, "u1", step=111, ts=1012.0)
    col.collect("n0")
    st = col.rates()["u1"]
    assert st.productive_s == pytest.approx(12.0)
    assert st.allocated_s == pytest.approx(30.0)

    # a vanished file drops its state
    os.unlink(gapi.progress_file_for(root, "u1"))
    clock.tick(1)
    col.collect("n0")
    assert "u1" not in col.rates()


def test_counter_reset_and_resize_epoch_never_inflate(tmp_path):
    """Satellite acceptance: a restarted worker (checkpoint-floor
    step count, bumped resize epoch) never produces a negative or
    inflated step rate — with OR without the epoch signal."""
    root = str(tmp_path)
    clock = Clock()
    col = GoodputCollector(root, now=clock)
    write_progress(root, "u1", step=100, ts=1000.0, epoch=0)
    col.collect("n0")
    clock.tick(10)
    write_progress(root, "u1", step=110, ts=1010.0, epoch=0)
    col.collect("n0")
    steady = col.rates()["u1"].steps_per_s
    assert steady == pytest.approx(1.0)

    # elastic resize: worker resumes from the checkpoint floor (40 <
    # 110) with the epoch bumped — the window restarts, the rate
    # neither goes negative nor spikes from the absolute counter
    clock.tick(5)
    write_progress(root, "u1", step=40, ts=1015.0, epoch=1)
    col.collect("n0")
    st = col.rates()["u1"]
    assert st.restarts == 1
    assert 0 <= st.steps_per_s <= steady * 1.01
    # and the restart window granted no phantom productive credit
    assert st.productive_s == pytest.approx(10.0)

    # epoch bumped AGAIN but the resumed counter happens to be HIGHER
    # (resume step past the old step): the out-of-band epoch still
    # restarts the window — a 500-step jump must not read as rate
    clock.tick(5)
    write_progress(root, "u1", step=600, ts=1020.0, epoch=2)
    col.collect("n0")
    st = col.rates()["u1"]
    assert st.restarts == 2
    assert 0 <= st.steps_per_s <= steady * 1.01

    # same-epoch regress (writer crash without control-plane drain):
    # the "restart" reset policy still refuses the absolute delta
    clock.tick(5)
    write_progress(root, "u1", step=10, ts=1025.0, epoch=2)
    col.collect("n0")
    st = col.rates()["u1"]
    assert 0 <= st.steps_per_s <= steady * 1.01

    # ... and steady stepping after the chaos converges back to 1/s
    for i in range(1, 9):
        clock.tick(10)
        write_progress(root, "u1", step=10 + 10 * i,
                       ts=1025.0 + 10 * i, epoch=2)
        col.collect("n0")
    assert col.rates()["u1"].steps_per_s == pytest.approx(1.0,
                                                          rel=0.05)


# -- agent handler + store fold ----------------------------------------

def agent_with_goodput(cluster, node, root, clock):
    provider = FakeUsageProvider()
    provider.set(node, cpu_fraction=0.2, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    return NodeAgent(cluster, node, provider,
                     handlers=[GoodputHandler],
                     goodput_collector=GoodputCollector(
                         root, now=clock))


def running_pod(name, node, uid, job="tj"):
    return make_pod(name, requests={"cpu": 4, TPU: 4},
                    node_name=node, phase=TaskStatus.RUNNING,
                    uid=uid,
                    annotations={GROUP_NAME_ANNOTATION: job})


def test_handler_posts_and_store_folds_into_podgroup(tmp_path):
    root = str(tmp_path)
    clock = Clock()
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.add_podgroup(PodGroup(name="tj", namespace="default"))
    cluster.add_pod(running_pod("tj-w0", "sa-w0", "u1"))
    agent = agent_with_goodput(cluster, "sa-w0", root, clock)

    write_progress(root, "u1", step=100, ts=1000.0)
    agent.sync()
    clock.tick(10)
    write_progress(root, "u1", step=110, ts=1010.0)
    agent.sync()

    # pod annotations carry step + published rate
    pod = cluster.pods["default/tj-w0"]
    assert pod.annotations[gapi.POD_STEP_ANNOTATION] == "110"
    assert float(pod.annotations[gapi.POD_STEP_RATE_ANNOTATION]) == \
        pytest.approx(1.0)

    # the report reached the store and folded into the PODGROUP
    rep = cluster.goodputreports["sa-w0"]
    assert rep.usages[0].job == "default/tj"
    assert rep.usages[0].generation == "v5e"
    pg = cluster.podgroups["default/tj"]
    ann = pg.annotations
    assert ann[gapi.PG_STEP_ANNOTATION] == "110"
    assert float(ann[gapi.PG_STEP_RATE_ANNOTATION]) == \
        pytest.approx(1.0)
    assert float(ann[gapi.PG_ALLOCATED_S_ANNOTATION]) == \
        pytest.approx(10.0)
    assert float(ann[gapi.PG_PRODUCTIVE_S_ANNOTATION]) == \
        pytest.approx(10.0)
    assert float(ann[gapi.PG_GOODPUT_ANNOTATION]) == pytest.approx(1.0)
    assert ann[gapi.PG_GENERATION_ANNOTATION] == "v5e"

    # a stalled sync accumulates allocated, not productive; goodput
    # debits toward 0.5
    clock.tick(10)
    agent.sync()
    ann = cluster.podgroups["default/tj"].annotations
    assert float(ann[gapi.PG_ALLOCATED_S_ANNOTATION]) == \
        pytest.approx(20.0)
    assert float(ann[gapi.PG_PRODUCTIVE_S_ANNOTATION]) == \
        pytest.approx(10.0)
    assert float(ann[gapi.PG_GOODPUT_ANNOTATION]) == pytest.approx(0.5)

    # the node's report dies with the node (never resurrected onto a
    # replacement host registering under the same name)
    cluster.remove_node("sa-w0")
    assert "sa-w0" not in cluster.goodputreports


def test_fold_accumulates_across_nodes_and_sticks(tmp_path):
    """Two nodes hosting one gang accumulate the ledger without
    double counting (the store diffs each report against THAT node's
    previous one), a RE-POSTED report after a lost ack is idempotent,
    and a whole-podgroup write from a mirror that predates the fold
    keeps the accounting (sticky re-apply)."""
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.add_podgroup(PodGroup(name="tj", namespace="default"))

    def report(node, uid, alloc, prod, ts):
        return gapi.GoodputReport(node=node, ts=ts, usages=[
            gapi.PodGoodput(
                pod_key=f"default/{uid}", uid=uid, job="default/tj",
                generation="v5e", step=50, steps_per_s=2.0,
                goodput=1.0, allocated_s=alloc, productive_s=prod)])

    cluster.put_object("goodputreport",
                       report("sa-w0", "u1", 10.0, 8.0, 1000.0))
    cluster.put_object("goodputreport",
                       report("sa-w1", "u2", 10.0, 8.0, 1000.0))
    ann = cluster.podgroups["default/tj"].annotations
    assert float(ann[gapi.PG_ALLOCATED_S_ANNOTATION]) == \
        pytest.approx(20.0)
    assert float(ann[gapi.PG_PRODUCTIVE_S_ANNOTATION]) == \
        pytest.approx(16.0)
    assert float(ann[gapi.PG_GOODPUT_ANNOTATION]) == pytest.approx(0.8)

    # lost-ack retry: the agent re-sends the SAME cumulative values —
    # the fold contributes nothing new (no double count)
    cluster.put_object("goodputreport",
                       report("sa-w0", "u1", 10.0, 8.0, 1001.0))
    ann = cluster.podgroups["default/tj"].annotations
    assert float(ann[gapi.PG_ALLOCATED_S_ANNOTATION]) == \
        pytest.approx(20.0)
    # ... and the next grown cumulative contributes only the growth
    cluster.put_object("goodputreport",
                       report("sa-w0", "u1", 15.0, 12.0, 1002.0))
    ann = cluster.podgroups["default/tj"].annotations
    assert float(ann[gapi.PG_ALLOCATED_S_ANNOTATION]) == \
        pytest.approx(25.0)
    assert float(ann[gapi.PG_PRODUCTIVE_S_ANNOTATION]) == \
        pytest.approx(20.0)

    # a restarted collector (cumulative below the previous report)
    # contributes its new absolute value, never a negative
    cluster.put_object("goodputreport",
                       report("sa-w0", "u1", 2.0, 1.0, 1003.0))
    ann = cluster.podgroups["default/tj"].annotations
    assert float(ann[gapi.PG_ALLOCATED_S_ANNOTATION]) == \
        pytest.approx(27.0)

    # stale-mirror whole write: no goodput keys on the incoming copy
    stale = PodGroup(name="tj", namespace="default")
    cluster.put_object("podgroup", stale)
    ann = cluster.podgroups["default/tj"].annotations
    assert float(ann[gapi.PG_ALLOCATED_S_ANNOTATION]) == \
        pytest.approx(27.0)

    # the SCHEDULER'S status-flush lane is a whole-podgroup write
    # too: a stale copy (old ledger values, seconds behind under
    # gray failure) must not rewind the folds that landed in between
    # — found by the chaos conductor (goodput_monotonic violation),
    # fixed by applying the same stick in update_podgroup_status
    stale2 = PodGroup(name="tj", namespace="default")
    stale2.annotations[gapi.PG_ALLOCATED_S_ANNOTATION] = "1.5"
    stale2.annotations[gapi.PG_STEP_ANNOTATION] = "1"
    cluster.update_podgroup_status(stale2)
    ann = cluster.podgroups["default/tj"].annotations
    assert float(ann[gapi.PG_ALLOCATED_S_ANNOTATION]) == \
        pytest.approx(27.0)
    assert float(ann[gapi.PG_STEP_ANNOTATION]) == pytest.approx(50.0)


def test_goodput_report_codec_roundtrip():
    from volcano_tpu.api import codec
    rep = gapi.GoodputReport(node="n1", ts=123.0, usages=[
        gapi.PodGoodput(pod_key="d/p", uid="u1", job="d/j",
                        generation="v5p", epoch=2, step=42,
                        steps_per_s=3.25, examples_per_s=13.0,
                        goodput=0.75, allocated_s=4.0,
                        productive_s=3.0, stalled=True)])
    back = codec.loads(codec.dumps(rep))
    assert back.node == "n1" and back.usages[0].step == 42
    assert back.usages[0].stalled is True
    assert back.usages[0].generation == "v5p"


# -- scheduler cache: the learned throughput vectors -------------------

def test_book_learns_vectors_and_sessions_see_them():
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    sched = Scheduler(cluster, schedule_period=0)
    cluster.add_podgroup(PodGroup(name="tj", namespace="default"))
    for i, rate in enumerate((4.0, 4.0, 4.0)):
        cluster.put_object("goodputreport", gapi.GoodputReport(
            node="sa-w0", ts=1000.0 + i, usages=[gapi.PodGoodput(
                pod_key="default/p", uid="u1", job="default/tj",
                generation="v5e", step=10 * (i + 1),
                steps_per_s=rate, allocated_s=1.0 * (i + 1),
                productive_s=1.0 * (i + 1))]))
    book = sched.cache.goodput_book
    assert book.vector("default/tj")["v5e"] == pytest.approx(4.0)
    assert book.rate("default/tj") == pytest.approx(4.0)
    # re-delivering the SAME fold timestamp is deduped (watch churn
    # must not over-weight one observation)
    updates_before = book._vectors["default/tj"]["v5e"].updates
    cluster.put_object(
        "podgroup", cluster.podgroups["default/tj"])
    assert book._vectors["default/tj"]["v5e"].updates == \
        updates_before

    # sessions expose the book to plugins/actions via the snapshot
    ssn = sched.run_once()
    assert ssn.goodput is book

    # deleted podgroups are forgotten (no leak across job churn)
    cluster.delete_podgroup("default/tj")
    assert "default/tj" not in book.jobs()


# -- session gauges: fragmentation + starvation ------------------------

def test_session_gauges_fragmentation_and_starvation():
    metrics.reset()
    trace.reset()
    # sa+sb share DCN pod d0 (whole idle); sc alone in d1 with one
    # busy host -> 12 stranded idle chips there
    cluster = make_tpu_cluster(
        [("sa", "v5e-16"), ("sb", "v5e-16"), ("sc", "v5e-16")],
        dcn_pods={"sa": "d0", "sb": "d0", "sc": "d1"})
    cluster.add_pod(make_pod("busy", requests={"cpu": 4, TPU: 4},
                             node_name="sc-w0",
                             phase=TaskStatus.RUNNING))
    # a feasible-but-pending gang: 48 chips demanded == total, only
    # 44 idle -> waits; its age feeds starvation_age_seconds{queue=}
    pg = PodGroup(name="starved", namespace="default",
                  min_member=12)
    pg.phase = PodGroupPhase.PENDING
    cluster.add_podgroup(pg)
    for i in range(12):
        cluster.add_pod(make_pod(
            f"starved-{i}", requests={"cpu": 4, TPU: 4},
            annotations={GROUP_NAME_ANNOTATION: "starved"}))
    sched = Scheduler(cluster, schedule_period=0)
    time.sleep(0.02)
    sched.run_once()

    assert metrics.get_gauge("frag_idle_chips",
                             generation="v5e") == pytest.approx(44.0)
    assert metrics.get_gauge("frag_largest_block_chips",
                             generation="v5e") == pytest.approx(32.0)
    assert metrics.get_gauge("frag_index", generation="v5e") == \
        pytest.approx(1 - 32 / 44, abs=1e-3)
    assert metrics.get_gauge("starvation_age_seconds",
                             queue="default") > 0
    assert metrics.get_gauge("starvation_pending_gangs",
                             queue="default") == 1

    # an INFEASIBLE gang (demand beyond total capacity) never counts
    # as starving — waiting cannot fix it
    ssn = sched.run_once()
    ages = gp.starvation_ages(ssn)
    big = PodGroup(name="impossible", namespace="default",
                   min_member=100)
    big.phase = PodGroupPhase.PENDING
    cluster.add_podgroup(big)
    for i in range(100):
        cluster.add_pod(make_pod(
            f"impossible-{i}", requests={"cpu": 4, TPU: 4},
            annotations={GROUP_NAME_ANNOTATION: "impossible"}))
    ssn = sched.run_once()
    ages2 = gp.starvation_ages(ssn)
    assert ages2["default"]["gangs"] == ages["default"]["gangs"]


# metric-label cardinality: the per-family copy of this test moved to
# tests/test_lint.py::test_live_exposition_honours_label_schema — one
# linter-driven check over the WHOLE exposition against
# bundle.FAMILY_LABELS (goodput_*/frag_*/starvation_* included).


# -- the closed loop: goodput-gated elastic grow -----------------------

def elastic_job(name="etrain", slices=1, lo=1, hi=3, pods_per_slice=4):
    return VCJob(
        name=name, min_available=slices * pods_per_slice,
        annotations={
            eapi.ELASTIC_MIN_SLICES_ANNOTATION: str(lo),
            eapi.ELASTIC_MAX_SLICES_ANNOTATION: str(hi),
            eapi.ELASTIC_SLICES_ANNOTATION: str(slices),
        },
        plugins={"jax": []},
        tasks=[TaskSpec(name="worker",
                        replicas=slices * pods_per_slice,
                        template=make_pod("t",
                                          requests={"cpu": 8,
                                                    TPU: 4}))])


def drive(cluster, mgr, sched, n=1):
    for _ in range(n):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()


def test_grow_gate_declines_poor_scaler_then_reopens():
    """The minimal Pollux step: an elastic job whose last grow bought
    almost no measured speedup is DECLINED further grows (idle
    capacity left for better scalers); once the measured rate at the
    current size improves, the gate reopens and the grow proceeds."""
    metrics.reset()
    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16"),
                                ("sc", "v5e-16")])
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=[
        "job", "podgroup", "queue", "failover", "elastic"])
    sched = Scheduler(cluster, conf=ELASTIC_CONF, schedule_period=0)
    from volcano_tpu.api.types import RUN_TICKS_ANNOTATION
    cluster.add_vcjob(VCJob(
        name="pin", min_available=4,
        tasks=[TaskSpec(name="worker", replicas=4,
                        template=make_pod(
                            "t", requests={"cpu": 8, TPU: 4},
                            annotations={RUN_TICKS_ANNOTATION:
                                         "60"}))]))
    cluster.add_vcjob(elastic_job())
    # elastic grows 1 -> 2 into the one idle slice (the pin holds sc)
    for _ in range(30):
        drive(cluster, mgr, sched)
        pg = cluster.podgroups["default/etrain"]
        if eapi.current_slices(pg) == 2 and \
                eapi.ELASTIC_RESIZING_ANNOTATION not in pg.annotations \
                and cluster.vcjobs["default/etrain"].phase \
                is JobPhase.RUNNING:
            break
    pg = cluster.podgroups["default/etrain"]
    assert eapi.current_slices(pg) == 2

    # observatory verdict: the 1 -> 2 grow bought 10 -> 11 steps/s
    # (speedup 1.1 < required 1.5) — decline the third slice
    book = sched.cache.goodput_book
    for _ in range(2):
        book.note("default/etrain", "v5e", 10.0, slices=1)
        book.note("default/etrain", "v5e", 11.0, slices=2)
    assert book.grow_verdict("default/etrain", 2) is False

    # free the pinned slice, give the action cycles to (not) grow
    for _ in range(80):
        drive(cluster, mgr, sched)
        if cluster.vcjobs["default/pin"].phase is JobPhase.COMPLETED:
            break
    assert cluster.vcjobs["default/pin"].phase is JobPhase.COMPLETED
    drive(cluster, mgr, sched, 5)
    pg = cluster.podgroups["default/etrain"]
    assert eapi.current_slices(pg) == 2       # grow declined
    assert metrics.get_counter("goodput_gated_grows_total",
                               decision="declined") > 0
    assert any(r == "ElasticGrowDeclined"
               for _, r, _ in cluster.events)

    # measured rate at 2 slices improves -> the gate reopens
    for _ in range(6):
        book.note("default/etrain", "v5e", 25.0, slices=2)
    assert book.grow_verdict("default/etrain", 2) is True
    for _ in range(30):
        drive(cluster, mgr, sched)
        pg = cluster.podgroups["default/etrain"]
        if eapi.current_slices(pg) == 3:
            break
    assert eapi.current_slices(pg) == 3
    assert metrics.get_counter("goodput_gated_grows_total",
                               decision="allowed") > 0
    mgr.stop()


def test_grow_verdict_shapes():
    book = gp.ThroughputBook()
    # no data -> no opinion (cold start stays greedy)
    assert book.grow_verdict("j", 2) is None
    book.note("j", "v5e", 10.0, slices=1)
    book.note("j", "v5e", 10.0, slices=1)
    # current size unmeasured -> still no opinion
    assert book.grow_verdict("j", 2) is None
    book.note("j", "v5e", 19.0, slices=2)
    book.note("j", "v5e", 19.0, slices=2)
    # 1.9x of linear 2.0x beats the 1.5 threshold
    assert book.grow_verdict("j", 2) is True
    assert book.grow_verdict("j", 2, frac=0.95) is False
    # per-world-size rates are tracked separately from the
    # per-generation vector (which EWMAs across sizes)
    assert book.rate_at("j", 2)[0] == pytest.approx(19.0)
    assert book.rate_at("j", 1)[0] == pytest.approx(10.0)
    # vectors per generation stay separate
    book.note("j", "v5p", 40.0, slices=2)
    assert book.vector("j")["v5p"] == pytest.approx(40.0)
    assert "v5e" in book.vector("j")


# -- surfaces: vtpctl, dumper ------------------------------------------

def test_vtpctl_goodput_and_fleet_views(tmp_path, capsys):
    from volcano_tpu.cli.vtpctl import main as vtpctl
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.add_podgroup(PodGroup(name="tj", namespace="default"))
    cluster.put_object("goodputreport", gapi.GoodputReport(
        node="sa-w0", ts=time.time(), usages=[gapi.PodGoodput(
            pod_key="default/tj-w0", uid="u1", job="default/tj",
            generation="v5e", step=1042, steps_per_s=3.5,
            goodput=0.9, allocated_s=10.0,
            productive_s=9.0)]))
    path = str(tmp_path / "c.pkl")
    with open(path, "wb") as f:
        pickle.dump(cluster, f)

    assert vtpctl(["--state", path, "goodput", "tj"]) == 0
    out = capsys.readouterr().out
    assert "1042" in out and "3.5" in out and "0.9" in out
    assert "v5e" in out

    assert vtpctl(["--state", path, "fleet"]) == 0
    out = capsys.readouterr().out
    assert "default/tj" in out
    assert "FRAG-INDEX" in out and "v5e" in out


def test_dumper_embeds_goodput_section(tmp_path):
    from volcano_tpu.dumper import Dumper
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    sched = Scheduler(cluster, schedule_period=0)
    sched.cache.goodput_book.note("default/tj", "v5e", 5.0, slices=2)
    path = str(tmp_path / "dump.json")
    Dumper(sched, path).dump()
    doc = json.load(open(path))
    assert doc["goodput"]["vectors"]["default/tj"]["v5e"] == \
        pytest.approx(5.0)
    assert doc["goodput"]["rates_by_world_size"]["default/tj"]["2"] \
        == pytest.approx(5.0)


# -- workload contract -------------------------------------------------

def test_progress_reporter_and_jax_plugin_env(tmp_path):
    from volcano_tpu.controllers.job.plugins.jax_plugin import JaxPlugin
    from volcano_tpu.workloads import bootstrap
    from volcano_tpu.workloads.progress import ProgressReporter

    # atomic write + record shape
    path = str(tmp_path / "p" / "vtp-u1.json")
    r = ProgressReporter(path, epoch=3, now=lambda: 123.5)
    assert r.report(step=7, examples=112.0)
    rec = json.load(open(path))
    assert rec == {"step": 7, "examples": 112.0, "ts": 123.5,
                   "epoch": 3}
    assert not os.path.exists(path + f".tmp.{os.getpid()}")

    # the jax plugin injects the per-pod path + the combined
    # failover+elastic epoch when the job declares a progress dir
    from volcano_tpu.api.slicehealth import (
        FAILOVER_GENERATION_ANNOTATION)
    job = VCJob(
        name="tj",
        annotations={
            gapi.PROGRESS_DIR_ANNOTATION: str(tmp_path),
            FAILOVER_GENERATION_ANNOTATION: "2",
            eapi.ELASTIC_GENERATION_ANNOTATION: "3",
        },
        tasks=[TaskSpec(name="worker", replicas=1,
                        template=make_pod("t", requests={TPU: 4}))])
    pod = make_pod("tj-worker-0", requests={TPU: 4}, uid="u9",
                   task_spec="worker", task_index=0)
    JaxPlugin().on_pod_create(pod, job)
    env = pod.containers[0].env
    assert env[gapi.ENV_PROGRESS_FILE] == \
        gapi.progress_file_for(str(tmp_path), "u9")
    assert env[gapi.ENV_EPOCH] == "5"

    # bootstrap parses the same contract
    info = bootstrap.from_env({gapi.ENV_PROGRESS_FILE: "/x/y.json",
                               gapi.ENV_EPOCH: "5"})
    assert info.progress_file == "/x/y.json" and info.epoch == 5
    # ... and a ProgressReporter built from that env targets the file
    rep = ProgressReporter.from_env({gapi.ENV_PROGRESS_FILE: path,
                                     gapi.ENV_EPOCH: "9"})
    assert rep.path == path and rep.epoch == 9
    assert ProgressReporter.from_env({}) is None


# -- tier-1 smoke: the stream through real processes -------------------

def test_bench_goodput_smoke_mode():
    """`bench.py --goodput-smoke` drives worker progress -> agent
    collector -> GoodputReport over the wire -> store fold ->
    podgroup annotations through a REAL process control plane (state
    server + scheduler + controllers as OS processes), mirroring
    --wire-smoke — the goodput stream guarded on every commit."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--goodput-smoke"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert proc.returncode == 0, \
        proc.stdout[-2000:] + proc.stderr[-2000:]
    line = next(l for l in reversed(proc.stdout.strip().splitlines())
                if l.startswith("{"))
    out = json.loads(line)
    assert out["ok"] is True, out
    assert out["fold_ok"] and out["steps_per_s"] > 0
    assert 0 < out["goodput"] <= 1
