"""Scale headroom regression (VERDICT r1 item 2).

5,000 simulated TPU hosts: a full scheduling cycle must stay inside the
1s schedule period, and a 1024-host gang must allocate in one cycle
well under the period.  Bounds here are CI-safe multiples of the
measured numbers (idle ~0.2s, 1024-gang ~0.45s on the dev box); the
precise figures are bench.py's job.
"""

import time

from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.uthelper import gang_job


def build_5k_cluster(busy_fraction=0.6):
    # ONE occupancy-shape definition shared with the 5k/10k/20k
    # benchmarks — the test and bench must measure the same cluster
    from bench import _build_scale_cluster
    return _build_scale_cluster(78, busy_fraction)   # 4992 hosts


def test_5k_hosts_cycle_under_schedule_period():
    cluster = build_5k_cluster()
    assert len(cluster.nodes) == 4992
    sched = Scheduler(cluster)
    sched.run_once()            # warm-up (imports, first session)

    t0 = time.time()
    sched.run_once()
    idle_cycle = time.time() - t0
    assert idle_cycle < 1.0, f"idle cycle {idle_cycle:.2f}s at 5k hosts"

    # 1024-host gang fills 16 v5e-256 slices in ONE cycle
    pg, pods = gang_job("g1024", replicas=1024, min_available=1024,
                        requests={"cpu": 8, TPU: 4})
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    t0 = time.time()
    sched.run_once()
    gang_cycle = time.time() - t0
    bound = sum(1 for key, _ in cluster.binds if key.startswith("default/g1024"))
    assert bound == 1024, f"gang bound {bound}/1024"
    assert gang_cycle < 2.0, f"1024-gang cycle {gang_cycle:.2f}s"


def test_port_multiset_accounting():
    """The ports predicate uses NodeInfo.occupied_ports, maintained
    across add/remove/update transitions."""
    from volcano_tpu.api.job_info import TaskInfo
    from volcano_tpu.api.node_info import Node, NodeInfo

    ni = NodeInfo(Node(name="n0", allocatable={"cpu": "8"}))
    pod_a = make_pod("a", requests={"cpu": 1}, phase=TaskStatus.RUNNING,
                     node_name="n0")
    pod_a.containers[0].ports = [8470]
    pod_b = make_pod("b", requests={"cpu": 1}, phase=TaskStatus.RUNNING,
                     node_name="n0")
    pod_b.containers[0].ports = [8470, 9000]
    ta, tb = TaskInfo(pod_a), TaskInfo(pod_b)
    ni.add_task(ta)
    ni.add_task(tb)
    assert ni.occupied_ports == {8470: 2, 9000: 1}
    ni.remove_task(ta)
    assert ni.occupied_ports == {8470: 1, 9000: 1}
    ni.update_task_status(tb, TaskStatus.RELEASING)
    assert ni.occupied_ports == {8470: 1, 9000: 1}
    ni.remove_task(tb)
    assert ni.occupied_ports == {}


def test_10k_hosts_gang_cycle_under_target():
    """The 10k-host probe shape (bench_10k_host_scale): a 2048-host
    gang fully places in one cycle under the 2s driver target, and an
    idle cycle stays sub-second.  Guards the scale path the bench
    measures (machine-speed tolerant: 3x headroom on the assert)."""
    from bench import bench_10k_host_scale
    out = bench_10k_host_scale()
    assert out["hosts"] == 10048
    assert out["idle_cycle_s"] < 1.0, out
    assert out["gang2048_cycle_s"] < 6.0, out   # 3x the 2s target
