"""Job controller lifecycle + job plugins + webhooks.

Mirrors the reference's job_controller_test.go + e2e jobseq plugin env
contracts (pytorch_plugin.go, tensorflow_plugin.go) with the jax plugin
as the TPU-native star.
"""

import json

import pytest

from volcano_tpu.api.pod import Container, Pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (
    JobAction,
    JobEvent,
    JobPhase,
    PodGroupPhase,
    TaskStatus,
)
from volcano_tpu.api.vcjob import LifecyclePolicy, TaskSpec, VCJob
from volcano_tpu.cache.fake_cluster import FakeCluster
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.controllers.job.controller import JobController
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.webhooks import AdmissionError, default_admission


def mk_cluster(slices=(("sa", "v5e-16"),)):
    cluster = make_tpu_cluster(list(slices))
    cluster.admission = default_admission()
    return cluster


def mk_job(name="train", tasks=None, plugins=None, **kwargs):
    tasks = tasks or [TaskSpec(
        name="worker", replicas=4,
        template=Pod(name="t", containers=[
            Container(requests={"cpu": 4, TPU: 4})]))]
    return VCJob(name=name, tasks=tasks, min_available=kwargs.pop(
        "min_available", sum(t.replicas for t in tasks)),
        plugins=dict(plugins or {}), **kwargs)


def run_all(cluster, mgr, sched, cycles=3):
    for _ in range(cycles):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()


def test_vcjob_end_to_end_lifecycle():
    """vcjob -> webhook admit -> controller materializes pods+podgroup ->
    scheduler gang-binds -> controller tracks Running -> completion."""
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job", "queue",
                                              "garbagecollector"])
    sched = Scheduler(cluster, schedule_period=0)
    job = cluster.add_vcjob(mk_job(plugins={"env": [], "svc": [],
                                            "jax": []}))

    run_all(cluster, mgr, sched)
    job = cluster.vcjobs[job.key]
    assert job.phase is JobPhase.RUNNING
    assert len(cluster.binds) == 4
    assert cluster.podgroups[job.key].phase is PodGroupPhase.RUNNING

    # all pods succeed -> job completes
    for pod in list(cluster.pods.values()):
        if pod.owner == job.uid:
            cluster.complete_pod(pod.key)
    mgr.sync_all()
    mgr.sync_all()
    assert cluster.vcjobs[job.key].phase is JobPhase.COMPLETED


def test_jax_plugin_env_contract():
    """Every worker pod gets the JAX bootstrap env the workloads'
    bootstrap module consumes."""
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    job = cluster.add_vcjob(mk_job(plugins={"jax": [], "svc": []}))
    mgr.sync_all()

    workers = [p for p in cluster.pods.values() if p.owner == job.uid]
    assert len(workers) == 4
    for pod in workers:
        env = pod.containers[0].env
        assert env["TPU_WORKER_ID"] == str(pod.task_index)
        assert env["NUM_PROCESSES"] == "4"
        hostnames = env["TPU_WORKER_HOSTNAMES"].split(",")
        assert len(hostnames) == 4
        assert env["COORDINATOR_ADDRESS"] == f"{hostnames[0]}:8476"
        # TPU toleration injected for chip-requesting pods
        assert any(t.key == TPU for t in pod.tolerations)

    # the workloads bootstrap parses exactly this env
    from volcano_tpu.workloads.bootstrap import from_env
    env = workers[2].containers[0].env
    info = from_env(env)
    assert info.process_id == workers[2].task_index
    assert info.num_processes == 4


def test_pytorch_plugin_env_contract():
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    tasks = [
        TaskSpec(name="master", replicas=1,
                 template=Pod(name="t", containers=[Container()])),
        TaskSpec(name="worker", replicas=2,
                 template=Pod(name="t", containers=[Container()])),
    ]
    job = cluster.add_vcjob(mk_job(name="ddp", tasks=tasks,
                                   plugins={"pytorch": []}))
    mgr.sync_all()
    pods = {p.name: p for p in cluster.pods.values() if p.owner == job.uid}
    master_env = pods["ddp-master-0"].containers[0].env
    assert master_env["RANK"] == "0"
    assert master_env["WORLD_SIZE"] == "3"
    worker_env = pods["ddp-worker-1"].containers[0].env
    assert worker_env["RANK"] == "2"
    assert worker_env["MASTER_ADDR"].startswith("ddp-master-0.")


def test_tensorflow_plugin_tf_config():
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    tasks = [
        TaskSpec(name="ps", replicas=1,
                 template=Pod(name="t", containers=[Container()])),
        TaskSpec(name="worker", replicas=2,
                 template=Pod(name="t", containers=[Container()])),
    ]
    job = cluster.add_vcjob(mk_job(name="tfjob", tasks=tasks,
                                   plugins={"tensorflow": []}))
    mgr.sync_all()
    pods = {p.name: p for p in cluster.pods.values() if p.owner == job.uid}
    cfg = json.loads(pods["tfjob-worker-1"].containers[0].env["TF_CONFIG"])
    assert cfg["task"] == {"type": "worker", "index": 1}
    assert len(cfg["cluster"]["worker"]) == 2
    assert len(cfg["cluster"]["ps"]) == 1


def test_mpi_plugin_creates_hostfile_and_ssh_secret():
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    tasks = [
        TaskSpec(name="master", replicas=1,
                 template=Pod(name="t", containers=[Container()])),
        TaskSpec(name="worker", replicas=2,
                 template=Pod(name="t", containers=[Container()])),
    ]
    job = cluster.add_vcjob(mk_job(name="horovod", tasks=tasks,
                                   plugins={"mpi": [], "ssh": [],
                                            "svc": []}))
    mgr.sync_all()
    assert "default/horovod-ssh" in cluster.secrets
    hostfile = cluster.config_maps["default/horovod-mpi-hostfile"]["hostfile"]
    assert hostfile.count("slots=1") == 2
    assert "default/horovod" in cluster.services


def test_restart_policy_on_pod_failure():
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    sched = Scheduler(cluster, schedule_period=0)
    job = mk_job(policies=[LifecyclePolicy(action=JobAction.RESTART_JOB,
                                           event=JobEvent.POD_FAILED)],
                 max_retry=2)
    job = cluster.add_vcjob(job)
    run_all(cluster, mgr, sched)
    assert cluster.vcjobs[job.key].phase is JobPhase.RUNNING

    victim = next(p for p in cluster.pods.values() if p.owner == job.uid)
    cluster.complete_pod(victim.key, succeeded=False)
    mgr.sync_all()   # policy fires -> Restarting, old pods deleted
    j = cluster.vcjobs[job.key]
    assert j.phase in (JobPhase.RESTARTING, JobPhase.PENDING)
    assert j.retry_count == 1
    run_all(cluster, mgr, sched, cycles=4)
    j = cluster.vcjobs[job.key]
    assert j.phase is JobPhase.RUNNING
    assert all(p.labels["volcano-tpu.io/job-version"] == "1"
               for p in cluster.pods.values() if p.owner == j.uid)


def test_abort_policy():
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    sched = Scheduler(cluster, schedule_period=0)
    job = cluster.add_vcjob(
        mk_job(policies=[LifecyclePolicy(action=JobAction.ABORT_JOB,
                                         event=JobEvent.POD_FAILED)]))
    run_all(cluster, mgr, sched)
    victim = next(p for p in cluster.pods.values() if p.owner == job.uid)
    cluster.complete_pod(victim.key, succeeded=False)
    mgr.sync_all()
    mgr.sync_all()
    assert cluster.vcjobs[job.key].phase is JobPhase.ABORTED
    assert not [p for p in cluster.pods.values() if p.owner == job.uid]


def test_garbage_collector_ttl():
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job", "garbagecollector"])
    job = cluster.add_vcjob(mk_job(ttl_seconds_after_finished=0))
    mgr.sync_all()
    for pod in list(cluster.pods.values()):
        if pod.owner == job.uid:
            cluster.complete_pod(pod.key)
    mgr.sync_all()  # -> completed
    mgr.sync_all()  # gc removes
    assert job.key not in cluster.vcjobs


def test_webhook_rejects_bad_jobs():
    cluster = mk_cluster()
    with pytest.raises(AdmissionError, match="minAvailable"):
        cluster.add_vcjob(mk_job(min_available=99))
    with pytest.raises(AdmissionError, match="duplicate"):
        cluster.add_vcjob(mk_job(tasks=[
            TaskSpec(name="a", replicas=1), TaskSpec(name="a", replicas=1)]))
    with pytest.raises(AdmissionError, match="unknown job plugin"):
        cluster.add_vcjob(mk_job(plugins={"nosuch": []}))
    with pytest.raises(AdmissionError, match="queue"):
        cluster.add_vcjob(mk_job(queue="ghost"))


def test_webhook_mutates_defaults():
    cluster = mk_cluster()
    job = VCJob(name="defaulted", min_available=0,
                tasks=[TaskSpec(name="", replicas=2)])
    job = cluster.add_vcjob(job)
    assert job.tasks[0].name == "task-0"
    assert job.min_available == 2
    assert job.queue == "default"


def test_podgroup_controller_wraps_bare_pods():
    from volcano_tpu.api.pod import make_pod
    cluster = FakeCluster()
    mgr = ControllerManager(cluster, enabled=["podgroup"])
    pod = make_pod("loner", requests={"cpu": 1})
    cluster.add_pod(pod)
    mgr.sync_all()
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION
    group = pod.annotations[GROUP_NAME_ANNOTATION]
    assert f"default/{group}" in cluster.podgroups
    assert cluster.podgroups[f"default/{group}"].min_member == 1


def test_task_depends_on_gates_materialization():
    """tasks[].dependsOn: workers start only after the master runs
    ('any' iteration); 'all' waits for every target replica."""
    from volcano_tpu.api.vcjob import DependsOn
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    sched = Scheduler(cluster, schedule_period=0)
    tasks = [
        TaskSpec(name="master", replicas=2,
                 template=Pod(name="t", containers=[
                     Container(requests={"cpu": 1})])),
        TaskSpec(name="worker", replicas=2,
                 depends_on=DependsOn(name=["master"], iteration="all"),
                 template=Pod(name="t", containers=[
                     Container(requests={"cpu": 1})])),
    ]
    job = cluster.add_vcjob(mk_job(name="dag", tasks=tasks,
                                   min_available=2))
    mgr.sync_all()
    names = {p.name for p in cluster.pods.values() if p.owner == job.uid}
    assert names == {"dag-master-0", "dag-master-1"}  # workers gated

    # one master running is NOT enough for iteration=all (phases set
    # manually — no scheduler cycles, so state stays exactly as written)
    cluster.pods["default/dag-master-0"].phase = TaskStatus.RUNNING
    mgr.sync_all()
    names = {p.name for p in cluster.pods.values() if p.owner == job.uid}
    assert "dag-worker-0" not in names

    cluster.pods["default/dag-master-1"].phase = TaskStatus.RUNNING
    mgr.sync_all()
    names = {p.name for p in cluster.pods.values() if p.owner == job.uid}
    assert {"dag-worker-0", "dag-worker-1"} <= names

    # dependency degrading later never deletes started workers
    cluster.complete_pod("default/dag-master-0", succeeded=False)
    mgr.sync_all()
    names = {p.name for p in cluster.pods.values() if p.owner == job.uid}
    assert {"dag-worker-0", "dag-worker-1"} <= names


def test_depends_on_any_across_target_list():
    """iteration='any' with two targets: ONE satisfied target unblocks
    (an unschedulable sibling must not deadlock the dependent)."""
    from volcano_tpu.api.vcjob import DependsOn
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    tasks = [
        TaskSpec(name="a", replicas=1, min_available=1,
                 template=Pod(name="t", containers=[
                     Container(requests={"cpu": 1})])),
        TaskSpec(name="b", replicas=1, min_available=1,
                 template=Pod(name="t", containers=[
                     Container(requests={"cpu": 999})])),  # never fits
        TaskSpec(name="dep", replicas=1,
                 depends_on=DependsOn(name=["a", "b"], iteration="any"),
                 template=Pod(name="t", containers=[
                     Container(requests={"cpu": 1})])),
    ]
    job = cluster.add_vcjob(mk_job(name="anyjob", tasks=tasks,
                                   min_available=1))
    mgr.sync_all()
    cluster.pods["default/anyjob-a-0"].phase = TaskStatus.RUNNING
    mgr.sync_all()
    names = {p.name for p in cluster.pods.values() if p.owner == job.uid}
    assert "anyjob-dep-0" in names   # a satisfied; b irrelevant


def test_jax_plugin_multislice_env_contract():
    """Subgrouped worker tasks = one jax.distributed job spanning
    slices: global worker ids, hostnames across every slice, plus
    TPU_SLICE_ID / TPU_NUM_SLICES feeding make_hybrid_mesh."""
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    tasks = [
        TaskSpec(name="slice-a", replicas=2, subgroup="slice-a",
                 template=Pod(name="t", containers=[
                     Container(requests={"cpu": 4, TPU: 4})])),
        TaskSpec(name="slice-b", replicas=2, subgroup="slice-b",
                 template=Pod(name="t", containers=[
                     Container(requests={"cpu": 4, TPU: 4})])),
    ]
    job = cluster.add_vcjob(mk_job(tasks=tasks,
                                   plugins={"jax": [], "svc": []}))
    mgr.sync_all()

    workers = sorted((p for p in cluster.pods.values()
                      if p.owner == job.uid),
                     key=lambda p: (p.task_spec, p.task_index))
    assert len(workers) == 4
    seen_ids = set()
    for pod in workers:
        env = pod.containers[0].env
        assert env["NUM_PROCESSES"] == "4"
        assert len(env["TPU_WORKER_HOSTNAMES"].split(",")) == 4
        assert env["TPU_NUM_SLICES"] == "2"
        expected_slice = 0 if pod.task_spec == "slice-a" else 1
        assert env["TPU_SLICE_ID"] == str(expected_slice)
        # global process id = slice offset + index within slice
        assert env["TPU_WORKER_ID"] == \
            str(expected_slice * 2 + pod.task_index)
        seen_ids.add(env["TPU_WORKER_ID"])
    assert seen_ids == {"0", "1", "2", "3"}

    from volcano_tpu.workloads.bootstrap import from_env
    info = from_env(workers[3].containers[0].env)
    assert info.is_multislice and info.num_slices == 2
    assert info.slice_id == 1 and info.process_id == 3


def test_jax_plugin_shared_subgroup_tasks_one_slice():
    """Multiple tasks sharing a subgroup are ONE slice (controller
    dedups subgroups into one SubGroupPolicy each): slice ids key on
    distinct subgroup names and same-slice ranks stay contiguous."""
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    tmpl = lambda: Pod(name="t", containers=[
        Container(requests={"cpu": 4, TPU: 4})])
    tasks = [TaskSpec(name="w0", replicas=1, subgroup="s1",
                      template=tmpl()),
             TaskSpec(name="w1", replicas=1, subgroup="s1",
                      template=tmpl()),
             TaskSpec(name="w2", replicas=1, subgroup="s2",
                      template=tmpl()),
             TaskSpec(name="w3", replicas=1, subgroup="s2",
                      template=tmpl())]
    job = cluster.add_vcjob(mk_job(tasks=tasks,
                                   plugins={"jax": [], "svc": []}))
    mgr.sync_all()
    workers = sorted((p for p in cluster.pods.values()
                      if p.owner == job.uid), key=lambda p: p.task_spec)
    envs = {p.task_spec: p.containers[0].env for p in workers}
    assert all(e["TPU_NUM_SLICES"] == "2" for e in envs.values())
    assert [envs[w]["TPU_SLICE_ID"] for w in ["w0", "w1", "w2", "w3"]] \
        == ["0", "0", "1", "1"]
    ids = [envs[w]["TPU_WORKER_ID"] for w in ["w0", "w1", "w2", "w3"]]
    assert ids == ["0", "1", "2", "3"]     # same-slice ranks contiguous


def test_jax_plugin_one_shared_subgroup_spans_all_its_tasks():
    """All worker tasks ganged into ONE subgroup still form one
    process grid across every task (no slice env — it's a single
    slice — but global ids and full hostname list)."""
    cluster = mk_cluster()
    mgr = ControllerManager(cluster, enabled=["job"])
    tmpl = lambda: Pod(name="t", containers=[
        Container(requests={"cpu": 4, TPU: 4})])
    tasks = [TaskSpec(name="w0", replicas=2, subgroup="s1",
                      template=tmpl()),
             TaskSpec(name="w1", replicas=2, subgroup="s1",
                      template=tmpl())]
    job = cluster.add_vcjob(mk_job(tasks=tasks,
                                   plugins={"jax": [], "svc": []}))
    mgr.sync_all()
    workers = sorted((p for p in cluster.pods.values()
                      if p.owner == job.uid),
                     key=lambda p: (p.task_spec, p.task_index))
    assert len(workers) == 4
    ids = []
    for pod in workers:
        env = pod.containers[0].env
        assert env["NUM_PROCESSES"] == "4"
        assert len(env["TPU_WORKER_HOSTNAMES"].split(",")) == 4
        assert "TPU_NUM_SLICES" not in env      # one slice: no dcn tier
        ids.append(env["TPU_WORKER_ID"])
    assert sorted(ids) == ["0", "1", "2", "3"]
