"""TopologyManager policy framework (reference plugins/numaaware/
policy/policy_{best_effort,restricted,single_numa_node}_test.go
translated to the cell-vector hint model in plugins/numa_policy.py).
"""

from volcano_tpu.api.numatopology import (
    POLICY_BEST_EFFORT,
    POLICY_NONE,
    POLICY_RESTRICTED,
    POLICY_SINGLE_NUMA,
)
from volcano_tpu.plugins.numa_policy import (
    TopologyHint,
    admit,
    merge_hints,
    merged_hint_for,
    resource_hints,
)


def hint(cells, preferred):
    return TopologyHint(None if cells is None else frozenset(cells),
                        preferred)


def test_resource_hints_prefer_minimal_width():
    # one cell satisfies -> width-1 hints preferred, wider unpreferred
    hints = resource_hints([4.0, 4.0], 3.0)
    assert hint([0], True) in hints and hint([1], True) in hints
    assert hint([0, 1], False) in hints
    # nothing fits a single cell -> the PAIR is the minimal width and
    # therefore preferred (kubelet cpumanager semantics)
    hints = resource_hints([4.0, 4.0], 6.0)
    assert hints == [hint([0, 1], True)]
    # unsatisfiable -> no hints
    assert resource_hints([4.0, 4.0], 100.0) == []
    # zero need -> any-cell preference
    assert resource_hints([4.0, 4.0], 0.0) == [hint(None, True)]


def test_merge_intersects_and_narrowest_preferred_wins():
    # cpu fits either cell, tpu only cell 1 -> merged {1} preferred
    merged = merge_hints(2, [
        [hint([0], True), hint([1], True), hint([0, 1], False)],
        [hint([1], True)],
    ])
    assert merged == hint([1], True)
    # disjoint single-cell prefs, RAW kubelet AND semantics (no
    # validator): the narrowest non-empty intersection wins even
    # though it under-covers one provider — merged_hint_for adds the
    # satisfiability validator on top for admission decisions
    merged = merge_hints(2, [
        [hint([0], True), hint([0, 1], False)],
        [hint([1], True), hint([0, 1], False)],
    ])
    assert merged.preferred is False and len(merged.mask) == 1
    # with a validator the under-covering masks are dropped
    merged = merge_hints(2, [
        [hint([0], True), hint([0, 1], False)],
        [hint([1], True), hint([0, 1], False)],
    ], validate=lambda m: len(m) == 2)
    assert merged == hint([0, 1], False)
    # an unsatisfiable provider poisons preference but not viability
    merged = merge_hints(2, [[hint([0], True)], []])
    assert merged.preferred is False


def test_policy_admission_matrix():
    one_preferred = hint([0], True)
    pair_preferred = hint([0, 1], True)
    pair_unpreferred = hint([0, 1], False)
    for policy in (POLICY_NONE, POLICY_BEST_EFFORT):
        assert admit(policy, one_preferred)
        assert admit(policy, pair_unpreferred)
    # restricted: preferred at ANY width admits; unpreferred never
    assert admit(POLICY_RESTRICTED, one_preferred)
    assert admit(POLICY_RESTRICTED, pair_preferred)
    assert not admit(POLICY_RESTRICTED, pair_unpreferred)
    # single-numa: exactly one preferred cell
    assert admit(POLICY_SINGLE_NUMA, one_preferred)
    assert not admit(POLICY_SINGLE_NUMA, pair_preferred)
    assert not admit(POLICY_SINGLE_NUMA, pair_unpreferred)


def test_restricted_distinct_from_single_numa():
    """The case the old ladder model got wrong: a request that MUST
    span two NUMA nodes at minimal width is restricted-admissible but
    single-numa-rejected."""
    cells = [[4000.0, 2.0], [4000.0, 2.0]]      # (cpu_millis, chips)
    merged, viable = merged_hint_for(cells, (6000.0, 3.0))
    assert viable and merged == hint([0, 1], True)
    assert admit(POLICY_RESTRICTED, merged)
    assert not admit(POLICY_SINGLE_NUMA, merged)


def test_cross_resource_intersection_denies_restricted():
    """cpu's minimal home is cell 0, tpu's is cell 1 -> merged pair is
    NOT preferred: restricted rejects even though each resource fits
    some single cell."""
    cells = [[4000.0, 0.0], [1000.0, 4.0]]
    merged, viable = merged_hint_for(cells, (3000.0, 2.0))
    assert viable and merged == hint([0, 1], False)
    assert not admit(POLICY_RESTRICTED, merged)
    assert admit(POLICY_BEST_EFFORT, merged)
