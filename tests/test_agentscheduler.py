"""Agent scheduler fast path + sharding + HyperJob."""

import time

from volcano_tpu.agentscheduler import AgentScheduler
from volcano_tpu.api.node_info import Node
from volcano_tpu.api.pod import Container, Pod, make_pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.shard import AGENT_SCHEDULER, SHARD_MODE_HARD, \
    SHARD_MODE_SOFT
from volcano_tpu.api.types import JobPhase, TaskStatus
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.cache.fake_cluster import FakeCluster
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.controllers.hyperjob import HyperJob, ReplicatedJob
from volcano_tpu.controllers.sharding import SHARD_LABEL, ShardingController
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.webhooks import default_admission


def agent_pod(name, cpu="1"):
    pod = make_pod(name, requests={"cpu": cpu})
    pod.scheduler_name = AGENT_SCHEDULER
    return pod


def test_agent_scheduler_binds_pods_fast():
    cluster = FakeCluster()
    for i in range(4):
        cluster.add_node(Node(name=f"n{i}",
                              allocatable={"cpu": 8, "pods": 110}))
    sched = AgentScheduler(cluster)
    for i in range(20):
        cluster.add_pod(agent_pod(f"a{i}"))
    bound = sched.run_until_drained()
    assert bound == 20
    assert len(cluster.binds) == 20


def test_agent_scheduler_parks_unschedulable_and_retries():
    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": 1}))
    sched = AgentScheduler(cluster)
    cluster.add_pod(agent_pod("big", cpu="4"))
    sched.run_until_drained()
    assert len(cluster.binds) == 0
    assert len(sched.queue.unschedulable) == 1
    # capacity arrives -> parked pod reactivates and binds
    cluster.add_node(Node(name="n1", allocatable={"cpu": 8}))
    sched.refresh()
    sched.queue.activate_unschedulable()
    sched.run_until_drained()
    assert ("default/big", "n1") in cluster.binds


def test_agent_scheduler_bind_generation_conflict():
    """Simulate a racing worker committing between candidate selection
    and bind: the stale-generation node must be skipped and the pod
    retried rather than double-booked."""
    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": 8}))
    cluster.add_node(Node(name="n1", allocatable={"cpu": 8}))
    sched = AgentScheduler(cluster, candidates=2)
    cluster.add_pod(agent_pod("p0"))

    orig = sched._select_candidates
    raced = {}

    def sabotaged(task):
        candidates = orig(task)
        if candidates and not raced:
            # another worker commits onto the top candidate AFTER the
            # generation was read: stored generation is now stale
            node, _ = candidates[0]
            raced["node"] = node.name
            node.bind_generation += 1
        return candidates

    sched._select_candidates = sabotaged
    sched.run_until_drained()
    # bound exactly once, on the runner-up node
    assert len(cluster.binds) == 1
    assert cluster.binds[0][1] != raced["node"]


def test_agent_scheduler_racing_instances_never_overbind():
    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": 2, "pods": 110}))
    s1, s2 = AgentScheduler(cluster), AgentScheduler(cluster)
    for i in range(4):
        cluster.add_pod(agent_pod(f"p{i}"))
    b1 = s1.run_until_drained()
    s2.refresh()
    b2 = s2.run_until_drained()
    assert b1 + b2 == 2               # capacity is 2 cpu
    assert len({k for k, _ in cluster.binds}) == len(cluster.binds)


def test_agent_scheduler_node_events_update_cache():
    """A node added after startup becomes schedulable WITHOUT a manual
    refresh (incremental cache honesty)."""
    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": 1}))
    sched = AgentScheduler(cluster)
    cluster.add_pod(agent_pod("big", cpu="4"))
    sched.run_until_drained()
    assert len(cluster.binds) == 0
    cluster.add_node(Node(name="n1", allocatable={"cpu": 8}))
    sched.run_until_drained()
    assert ("default/big", "n1") in cluster.binds


def test_sharding_fraction_policy_keeps_tpu_with_batch():
    cluster = make_tpu_cluster([("sa", "v5e-16")],
                               extra_nodes=[
                                   Node(name=f"cpu{i}",
                                        allocatable={"cpu": 16})
                                   for i in range(4)])
    ctrl = ShardingController(policy="fraction", agent_fraction=0.5)
    ctrl.initialize(cluster)
    ctrl.sync()
    agent_shard = cluster.nodeshards["agent"].nodes
    batch_shard = cluster.nodeshards["batch"].nodes
    assert len(agent_shard) == 2
    assert all(n.startswith("cpu") for n in agent_shard)
    assert all(n in batch_shard for n in
               [f"sa-w{i}" for i in range(4)])


def test_agent_scheduler_hard_shard_mode():
    cluster = FakeCluster()
    cluster.add_node(Node(name="agent0", allocatable={"cpu": 8},
                          labels={SHARD_LABEL: "agent"}))
    cluster.add_node(Node(name="batch0", allocatable={"cpu": 8}))
    ctrl = ShardingController(policy="label")
    ctrl.initialize(cluster)
    ctrl.sync()
    sched = AgentScheduler(cluster, shard_mode=SHARD_MODE_HARD)
    cluster.add_pod(agent_pod("p0"))
    sched.run_until_drained()
    assert cluster.binds == [("default/p0", "agent0")]


def test_hyperjob_members_and_gang():
    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=["job", "hyperjob"])
    sched = Scheduler(cluster, schedule_period=0)

    template = VCJob(name="member", min_available=4,
                     tasks=[TaskSpec(name="w", replicas=4,
                                     template=Pod(name="t", containers=[
                                         Container(requests={"cpu": 8,
                                                             TPU: 4})]))])
    hj = HyperJob(name="multislice", min_available=2,
                  replicated_jobs=[ReplicatedJob(name="rep", replicas=2,
                                                 template=template)],
                  max_domains=2)
    cluster.hyperjobs = {hj.key: hj}

    for _ in range(4):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()

    assert "default/multislice-rep-0" in cluster.vcjobs
    assert "default/multislice-rep-1" in cluster.vcjobs
    from volcano_tpu.controllers.hyperjob import HyperJobPhase
    assert hj.phase is HyperJobPhase.RUNNING
    # maxDomains forced slice-local members: each fills one slice
    slices = {}
    for key, node in cluster.binds:
        member = key.split("/")[1].rsplit("-w-", 1)[0]
        slices.setdefault(member, set()).add(node.rsplit("-w", 1)[0])
    assert all(len(s) == 1 for s in slices.values())


def test_hyperjob_max_domains_caps_spread():
    """3 members with max_domains=1: ALL land in the single allowed DCN
    pod, members beyond its capacity wait."""
    cluster = make_tpu_cluster(
        [("sa", "v5e-16"), ("sb", "v5e-16"), ("sc", "v5e-16")],
        dcn_pods={"sa": "dcnA", "sb": "dcnA", "sc": "dcnB"})
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=["job", "hyperjob"])
    sched = Scheduler(cluster, schedule_period=0)
    template = VCJob(name="member", min_available=4,
                     tasks=[TaskSpec(name="w", replicas=4,
                                     template=Pod(name="t", containers=[
                                         Container(requests={"cpu": 8,
                                                             TPU: 4})]))])
    hj = HyperJob(name="capped", min_available=2,
                  replicated_jobs=[ReplicatedJob(name="rep", replicas=3,
                                                 template=template)],
                  max_domains=1)
    cluster.hyperjobs = {hj.key: hj}
    for _ in range(4):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    # only dcnA (= slices sa+sb, 2 members' worth) may host members
    used_slices = {n.rsplit("-w", 1)[0] for _, n in cluster.binds}
    assert used_slices <= {"sa", "sb"}
    assert len(used_slices) == 2


def test_batch_scheduler_hard_shard_mode():
    """With hard sharding, batch allocate never touches agent nodes."""
    cluster = FakeCluster()
    cluster.add_node(Node(name="agent0", allocatable={"cpu": 64},
                          labels={SHARD_LABEL: "agent"}))
    cluster.add_node(Node(name="batch0", allocatable={"cpu": 8}))
    ctrl = ShardingController(policy="label")
    ctrl.initialize(cluster)
    ctrl.sync()
    from volcano_tpu.uthelper import gang_job
    from volcano_tpu.api.podgroup import PodGroup
    pg, pods = gang_job("batchjob", replicas=2, min_available=1,
                        requests={"cpu": 4})
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    conf = {"actions": "enqueue, allocate, backfill",
            "configurations": {"allocate": {"shard-mode": "hard"}},
            "tiers": [{"plugins": [{"name": "gang"},
                                   {"name": "predicates"},
                                   {"name": "nodeorder"}]}]}
    Scheduler(cluster, conf=conf, schedule_period=0).run_once()
    assert all(n == "batch0" for _, n in cluster.binds)
    assert len(cluster.binds) == 2  # both fit batch0


# -- plugin framework (VERDICT r1 weak 3) -----------------------------

def test_agent_enforces_tpu_shape_rules():
    """A 2-chip request on a multi-host slice host (whole-host = 4
    chips) must be REJECTED by the fast path, exactly like the batch
    path's device filter."""
    cluster = make_tpu_cluster([("sa", "v5e-16")])  # 4 hosts x 4 chips
    sched = AgentScheduler(cluster)
    bad = agent_pod("subhost", cpu="1")
    bad.containers[0].requests[TPU] = 2
    cluster.add_pod(bad)
    assert sched.run_until_drained() == 0
    assert "default/subhost" in sched.queue.unschedulable

    good = agent_pod("whole", cpu="1")
    good.containers[0].requests[TPU] = 4
    cluster.add_pod(good)
    assert sched.run_until_drained() == 1
    assert cluster.pods["default/whole"].node_name.startswith("sa-")


def test_agent_enforces_affinity_terms_and_ports():
    cluster = FakeCluster()
    cluster.add_node(Node(name="gpu0", labels={"pool": "infer"},
                          allocatable={"cpu": 8, "pods": 10}))
    cluster.add_node(Node(name="cpu0", labels={"pool": "web"},
                          allocatable={"cpu": 8, "pods": 10}))
    sched = AgentScheduler(cluster)

    affine = agent_pod("affine")
    affine.affinity_node_terms = [{"pool": ["infer"]}]
    cluster.add_pod(affine)
    sched.run_until_drained()
    assert cluster.pods["default/affine"].node_name == "gpu0"

    # host-port conflict: second pod with the same port avoids gpu0
    p1 = agent_pod("port1")
    p1.containers[0].ports = [8080]
    p1.affinity_node_terms = [{"pool": ["infer"]}]
    cluster.add_pod(p1)
    sched.run_until_drained()
    assert cluster.pods["default/port1"].node_name == "gpu0"
    sched.refresh()
    p2 = agent_pod("port2")
    p2.containers[0].ports = [8080]
    cluster.add_pod(p2)
    sched.run_until_drained()
    assert cluster.pods["default/port2"].node_name == "cpu0"


def test_agent_gated_pod_parks():
    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": 8, "pods": 10}))
    sched = AgentScheduler(cluster)
    gated = agent_pod("gated")
    gated.scheduling_gates = ["volcano-tpu.io/queue-admission"]
    cluster.add_pod(gated)
    assert sched.run_until_drained() == 0
    assert "default/gated" in sched.queue.unschedulable

    # lifting the gate updates the pod; the watch event must reactivate
    # the parked pod even with no node churn
    gated.scheduling_gates = []
    cluster.put_object("pod", gated)
    assert "default/gated" not in sched.queue.unschedulable
    sched.run_until_drained()
    assert cluster.pods["default/gated"].node_name == "n0"


def test_agent_custom_plugin_chain():
    """Operators can extend the fast path: a custom scorer flips node
    preference; a custom filter can veto."""
    from volcano_tpu.agentscheduler import AgentPlugin, \
        register_agent_plugin

    @register_agent_plugin("prefer-n1")
    class PreferN1(AgentPlugin):
        def score(self, task, node):
            return 1000.0 if node.name == "n1" else 0.0

    cluster = FakeCluster()
    for i in range(2):
        cluster.add_node(Node(name=f"n{i}",
                              allocatable={"cpu": 8, "pods": 10}))
    sched = AgentScheduler(cluster, plugins=["predicates", "resources",
                                             "prefer-n1"])
    cluster.add_pod(agent_pod("picky"))
    sched.run_until_drained()
    assert cluster.pods["default/picky"].node_name == "n1"


def test_agent_plugin_signature_extra_prevents_verdict_leak():
    """A plugin whose filter_static reads a field OUTSIDE the default
    spec signature must not share verdicts between pods that differ
    there (ADVICE r3: memoization contract escape hatch)."""
    from volcano_tpu.agentscheduler import AgentPlugin, \
        register_agent_plugin

    @register_agent_plugin("label-gate")
    class LabelGate(AgentPlugin):
        """Rejects every node for pods labeled blocked=yes — a field
        the default signature does NOT cover."""
        name = "label-gate"

        def signature_extra(self, pod):
            return (pod.labels.get("blocked", ""),)

        def filter_static(self, task, node):
            if task.pod.labels.get("blocked") == "yes":
                return "blocked by label"
            return None

    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": 8, "pods": 10}))
    sched = AgentScheduler(cluster, plugins=["predicates", "resources",
                                             "label-gate"])
    # identical spec except the label: first pod primes the cache
    ok = agent_pod("ok")
    blocked = agent_pod("blocked")
    blocked.labels["blocked"] = "yes"
    cluster.add_pod(ok)
    cluster.add_pod(blocked)
    sched.run_until_drained()
    assert cluster.pods["default/ok"].node_name == "n0"
    assert cluster.pods["default/blocked"].node_name == "", \
        "blocked pod reused the ok pod's memoized verdict"


def test_agent_batched_bind_lane_over_the_wire():
    """run_until_drained(bind_batch=N): reservations commit as ONE
    /bind_batch request per wave instead of a POST per pod — the lane
    the wire agent process (__main__) runs — with placements identical
    to the per-pod lane's discipline."""
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.server.state_server import serve

    httpd, state = serve(port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    mirror = RemoteCluster(url)
    try:
        for i in range(4):
            mirror.add_node(Node(name=f"n{i}",
                                 allocatable={"cpu": 8, "pods": 110}))
        sched = AgentScheduler(mirror)
        for i in range(20):
            mirror.add_pod(agent_pod(f"b{i}"))
        calls = []
        orig = mirror._request
        mirror._request = lambda m, p, *a, **kw: (
            calls.append(p), orig(m, p, *a, **kw))[1]
        assert sched.run_until_drained(bind_batch=8) == 20
        assert calls.count("/bind_batch") <= 3      # ceil(20/8)
        assert "/bind" not in calls
        server_pods = state.cluster.pods
        assert sum(1 for p in server_pods.values()
                   if p.phase is TaskStatus.BOUND) == 20
        # capacity respected: no node over its pod/cpu budget
        per_node = {}
        for p in server_pods.values():
            per_node[p.node_name] = per_node.get(p.node_name, 0) + 1
        assert all(v <= 8 for v in per_node.values()), per_node
    finally:
        mirror.close()
        httpd.shutdown()


def test_agent_batched_bind_conflict_rolls_back_reservation():
    """A per-item bind failure in the batched lane rolls back exactly
    like the per-pod lane: reservation released (node capacity
    restored), pod requeued urgent, conflict counted."""
    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": 2, "pods": 110}))
    sched = AgentScheduler(cluster)
    cluster.add_pod(agent_pod("c0", cpu="2"))
    placed = sched._place_one()
    assert placed is not None
    pod, task, node, attempt, t0, ts_alloc = placed
    used_before = node.used.clone()
    sched._commit_bind(pod, task, node, attempt, t0, ts_alloc,
                       "bind conflict")
    assert node.used.res.get("cpu", 0) < used_before.res.get("cpu", 0)
    # requeued urgent: the next drain (per-pod lane) binds it
    assert sched.run_until_drained() == 1
    assert cluster.pods["default/c0"].node_name == "n0"
