import os
import sys

# Workload tests run on a virtual 8-device CPU mesh; must be set before
# jax is imported anywhere in the test process.  Force cpu even when the
# environment points JAX at a real accelerator (JAX_PLATFORMS=axon) —
# multi-device sharding tests need 8 virtual devices, not 1 real chip.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

# The image's sitecustomize force-registers the axon TPU platform via
# jax.config, which overrides the env var — override it back before any
# backend initialization.
try:
    import jax

    jax.config.update("jax_platforms", "cpu")
except Exception:  # pragma: no cover - jax-less environments
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
