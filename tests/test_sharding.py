"""Sharding both planes: the subtree partition map, the batched gang
commit drain, subtree-sharded schedulers, cross-shard conflict
arbitration on the wire, and the keyspace-partitioned write plane.

The partition key is one deliberate choice tested here from both
sides: scheduler shards and write-leader groups split by the SAME
topology subtrees (shardmap.py), so a gang's binds land on the leader
group owning its slice and two shards can only collide where one of
them deliberately spilled.
"""

import time

import pytest

from volcano_tpu import metrics
from volcano_tpu import shardmap
from volcano_tpu.api.node_info import Node
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.queue import Queue
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (GROUP_NAME_ANNOTATION, PodGroupPhase,
                                   TaskStatus)
from volcano_tpu.cache.remote_cluster import RemoteCluster
from volcano_tpu.server.state_server import serve
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.uthelper import TestContext, gang_job


# ---------------------------------------------------------------- map

def _subtrees(names_per_subtree):
    out = {}
    for subtree, names in names_per_subtree.items():
        for n in names:
            out[n] = subtree
    return out


def test_plan_partition_disjoint_exhaustive_deterministic():
    subtrees = _subtrees({
        "sa": [f"sa-w{i}" for i in range(4)],
        "sb": [f"sb-w{i}" for i in range(4)],
        "sc": [f"sc-w{i}" for i in range(2)],
        shardmap.FLAT_SUBTREE: ["cpu0"],
    })
    plan = shardmap.plan_partition(subtrees, 3)
    assert [row["shard"] for row in plan] == [0, 1, 2]
    owned = [set(row["nodes"]) for row in plan]
    # disjoint ...
    for i in range(3):
        for j in range(i + 1, 3):
            assert not owned[i] & owned[j]
    # ... exhaustive ...
    assert set().union(*owned) == set(subtrees)
    # ... never splits a subtree ...
    for row in plan:
        for name in row["nodes"]:
            assert subtrees[name] in row["subtrees"]
    # ... and deterministic (the routing table every process derives
    # independently must agree)
    assert plan == shardmap.plan_partition(dict(reversed(
        list(subtrees.items()))), 3)
    # owner_index is the inverse view of the same plan
    owners = shardmap.owner_index(subtrees, 3)
    for row in plan:
        assert all(owners[n] == row["shard"] for n in row["nodes"])
    for idx in range(3):
        assert shardmap.owned_nodes(subtrees, 3, idx) == owned[idx]


def test_home_shard_stable_and_in_range():
    keys = [f"default/job-{i}" for i in range(64)]
    homes = [shardmap.home_shard(k, 4) for k in keys]
    assert homes == [shardmap.home_shard(k, 4) for k in keys]
    assert set(homes) <= set(range(4))
    # every shard gets some jobs at this scale (the hash spreads)
    assert len(set(homes)) == 4
    assert shardmap.home_shard("default/x", 1) == 0


def test_subtree_of_flat_fallback():
    assert shardmap.subtree_of(None) == shardmap.FLAT_SUBTREE
    assert shardmap.subtree_of({}) == shardmap.FLAT_SUBTREE
    assert shardmap.subtree_of(
        {shardmap.TPU_SLICE_LABEL: "sa"}) == "sa"


# --------------------------------------------- batched gang commit

def _gang_ctx(gang_commit, slices=(("sa", "v5e-16"), ("sb", "v5e-16")),
              jobs=(("ga", 8),)):
    cluster = make_tpu_cluster(list(slices))
    cluster.add_queue(Queue(name="default"))
    for name, replicas in jobs:
        pg, pods = gang_job(name, replicas=replicas,
                            requests={"cpu": 1, TPU: 4},
                            pg_phase=PodGroupPhase.INQUEUE)
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
    # the bench tier stack (incl. the topology scorer): the batch
    # drain's fill-to-capacity contract is placement-identical to the
    # walk under binpack/topology-compact scoring, and that is the
    # stack the drain exists for
    conf = {"actions": "enqueue, allocate, backfill",
            "tiers": [
                {"plugins": [{"name": "priority"}, {"name": "gang"},
                             {"name": "conformance"}]},
                {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                             {"name": "predicates"},
                             {"name": "proportion"},
                             {"name": "nodeorder"},
                             {"name": "binpack"},
                             {"name": "deviceshare"},
                             {"name": "network-topology-aware"}]},
            ],
            "configurations": {"allocate": {"gangCommit": gang_commit}}}
    return TestContext(cluster=cluster, conf=conf)


def test_batch_commit_places_identically_to_walk():
    # replicas of one spec are interchangeable (that is the batch
    # contract), so identity means the same node multiset — which
    # pod name lands on which of the equivalent hosts tracks task
    # iteration order, not placement quality
    walk = _gang_ctx("walk")
    walk.run()
    batch = _gang_ctx("batch")
    batch.run()
    assert sorted(walk.bind_map.values()) == \
        sorted(batch.bind_map.values())
    assert len(batch.bind_map) == 8


def test_batch_commit_gang_all_or_nothing():
    # 5 x 4 chips > one 16-chip slice: the gang cannot seat, the
    # statement must discard — no partial binds leak
    ctx = _gang_ctx("batch", slices=(("sa", "v5e-16"),),
                    jobs=(("ga", 5),))
    ctx.run()
    ctx.expect_bind_num(0)
    job = next(iter(ctx.last_session.jobs.values()))
    assert job.fit_errors, "leftover tasks must carry fit errors"


def test_batch_commit_multi_spec_and_bare_pods():
    # two specs plus a spec-less bare pod in one podgroup: specs drain
    # batched, the bare pod falls back to the walk — all seated
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.add_queue(Queue(name="default"))
    pg, pods = gang_job("ga", replicas=2, requests={"cpu": 1, TPU: 4},
                        pg_phase=PodGroupPhase.INQUEUE)
    for i, p in enumerate(pods):
        p.task_spec = f"spec{i}"
    bare = make_pod("ga-bare", requests={"cpu": 1},
                    annotations={GROUP_NAME_ANNOTATION: "ga"})
    pg.min_member = 3
    cluster.add_podgroup(pg)
    for p in pods + [bare]:
        cluster.add_pod(p)
    conf = {"actions": "enqueue, allocate, backfill",
            "tiers": [
                {"plugins": [{"name": "priority"}, {"name": "gang"},
                             {"name": "conformance"}]},
                {"plugins": [{"name": "overcommit"},
                             {"name": "predicates"},
                             {"name": "proportion"},
                             {"name": "nodeorder"},
                             {"name": "binpack"}]},
            ],
            "configurations": {"allocate": {"gangCommit": "batch"}}}
    ctx = TestContext(cluster=cluster, conf=conf)
    ctx.run()
    ctx.expect_bind_num(3)


# --------------------------------------------- subtree-sharded plane

def _shard_ctx(cluster, idx, count, spill="soft"):
    conf = {"actions": "enqueue, allocate, backfill",
            "tiers": [
                {"plugins": [{"name": "priority"}, {"name": "gang"},
                             {"name": "conformance"}]},
                {"plugins": [{"name": "overcommit"},
                             {"name": "predicates"},
                             {"name": "proportion"},
                             {"name": "nodeorder"},
                             {"name": "binpack"}]},
            ],
            "configurations": {"allocate": {
                "shard-mode": "subtree", "shard-index": idx,
                "shard-count": count, "shard-spill": spill}}}
    return TestContext(cluster=cluster, conf=conf)


def test_two_shards_own_disjoint_subtrees_and_split_jobs():
    # home_shard("default/ga", 2) == 0, ("default/gb") == 1 (stable
    # hash); plan gives slice sa -> shard 0, sb -> shard 1
    assert shardmap.home_shard("default/ga", 2) == 0
    assert shardmap.home_shard("default/gb", 2) == 1

    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_queue(Queue(name="default"))
    for name in ("ga", "gb"):
        pg, pods = gang_job(name, replicas=4,
                            requests={"cpu": 1, TPU: 4},
                            pg_phase=PodGroupPhase.INQUEUE)
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)

    shard0 = _shard_ctx(cluster, 0, 2)
    shard0.run()
    # shard 0 schedules ONLY its homed gang, onto its owned subtree
    bound0 = dict(shard0.bind_map)
    assert set(bound0) == {f"default/ga-{i}" for i in range(4)}
    assert all(n.startswith("sa-") for n in bound0.values())

    shard1 = _shard_ctx(cluster, 1, 2)
    shard1.run()
    bound1 = {k: v for k, v in shard1.bind_map.items()
              if k not in bound0}
    assert set(bound1) == {f"default/gb-{i}" for i in range(4)}
    assert all(n.startswith("sb-") for n in bound1.values())


def test_shard_soft_spill_crosses_subtree_when_home_is_full():
    # gb is homed to shard 1 whose subtree (sb) is too small for it;
    # soft spill lets the shard place the tail optimistically on
    # foreign nodes — the server's check-and-bind arbitrates for real
    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_queue(Queue(name="default"))
    pg, pods = gang_job("gb", replicas=8, requests={"cpu": 1, TPU: 4},
                        pg_phase=PodGroupPhase.INQUEUE)
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    shard1 = _shard_ctx(cluster, 1, 2, spill="soft")
    shard1.run()
    nodes_used = set(shard1.bind_map.values())
    assert len(shard1.bind_map) == 8
    assert any(n.startswith("sa-") for n in nodes_used), \
        "spill must reach the foreign subtree"

    # hard spill: the same gang must NOT cross; it cannot seat at all
    cluster2 = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster2.add_queue(Queue(name="default"))
    pg, pods = gang_job("gb", replicas=8, requests={"cpu": 1, TPU: 4},
                        pg_phase=PodGroupPhase.INQUEUE)
    cluster2.add_podgroup(pg)
    for p in pods:
        cluster2.add_pod(p)
    hard = _shard_ctx(cluster2, 1, 2, spill="hard")
    hard.run()
    hard.expect_bind_num(0)


# ------------------------------------- cross-shard races on the wire

@pytest.fixture()
def wire():
    httpd, state = serve(port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    clients = []

    def client(**kw):
        c = RemoteCluster(url, **kw)
        clients.append(c)
        return c

    yield type("Wire", (), {"url": url, "state": state,
                            "client": staticmethod(client)})
    for c in clients:
        c.close()
    httpd.shutdown()


def test_cross_shard_bind_race_exactly_one_winner(wire):
    """Two shards race overlapping chips through /bind_batch: the
    server's atomic check-and-bind admits exactly one, the other
    collects a per-item 409 — never both, never neither."""
    a = wire.client()
    b = wire.client()
    a.add_node(Node(name="sa-w0", allocatable={"cpu": "8", TPU: "4",
                                               "pods": 110}))
    a.add_pod(make_pod("ra", requests={"cpu": 1, TPU: 4}))
    a.add_pod(make_pod("rb", requests={"cpu": 1, TPU: 4}))
    time.sleep(0.2)   # let b's mirror see both pods
    errs_a = a.bind_pods([("default", "ra", "sa-w0")])
    errs_b = b.bind_pods([("default", "rb", "sa-w0")])
    verdicts = [errs_a[0] is None, errs_b[0] is None]
    assert verdicts.count(True) == 1, (errs_a, errs_b)
    loser_err = errs_b[0] if verdicts[0] else errs_a[0]
    assert "overcommit" in loser_err
    # exactly one pod holds the chips server-side
    bound = [p for p in wire.state.cluster.pods.values()
             if p.phase is TaskStatus.BOUND]
    assert len(bound) == 1


def test_cross_shard_conflict_slug_metrics_and_requeue(wire):
    """The losing shard's flush_binds brands the refusal with the
    bounded cross-shard-conflict slug, counts refused per item and
    requeued per job, and leaves the pods Pending for its next cycle."""
    from volcano_tpu.api.job_info import TaskInfo
    from volcano_tpu.cache.cache import SchedulerCache
    from volcano_tpu.trace import normalize_reason

    metrics.reset()
    a = wire.client()
    b = wire.client()
    a.add_node(Node(name="sa-w0", allocatable={"cpu": "8", TPU: "4",
                                               "pods": 110}))
    a.add_pod(make_pod("wa", requests={"cpu": 1, TPU: 4}))
    pods_b = [make_pod(f"wb-{i}", requests={"cpu": 1, TPU: 2},
                       annotations={GROUP_NAME_ANNOTATION: "gb"})
              for i in range(2)]
    for p in pods_b:
        a.add_pod(p)
    time.sleep(0.2)
    # shard 0 wins the chips first
    assert a.bind_pods([("default", "wa", "sa-w0")]) == [None]
    # shard 1's optimistic flush loses both items of one gang
    cache = SchedulerCache(b)
    cache.shard_plan = "1/2"
    for p in pods_b:
        t = TaskInfo(p)
        t.node_name = "sa-w0"
        cache.add_bind_task(t)
    assert cache.flush_binds() == 0
    assert len(cache.bind_failures) == 2
    for _key, err in cache.bind_failures:
        assert err.startswith("cross-shard conflict (shard 1/2): ")
        assert normalize_reason(err) == "cross-shard-conflict"
    assert metrics.get_counter("sched_cross_shard_conflicts_total",
                               outcome="refused") == 2
    # one requeue per JOB, not per item — the retry unit is the gang
    assert metrics.get_counter("sched_cross_shard_conflicts_total",
                               outcome="requeued") == 1
    # loser's pods remain pending server-side for the next cycle
    for p in pods_b:
        assert wire.state.cluster.pods[p.key].phase \
            is TaskStatus.PENDING


def test_cross_shard_metric_family_is_enum_bounded():
    from volcano_tpu.bundle import FAMILIES, FAMILY_LABELS
    assert FAMILIES["sched_cross_shard_conflicts_total"] == "counter"
    assert set(FAMILY_LABELS["sched_cross_shard_conflicts_total"]
               ["outcome"]) == {"refused", "requeued"}
    from volcano_tpu.trace import REASON_ENUM
    assert "cross-shard-conflict" in REASON_ENUM


# ----------------------------------- keyspace-partitioned write plane

@pytest.fixture()
def part():
    srvs = [serve(port=0) for _ in range(3)]
    from volcano_tpu.cache.partitioned import PartitionedCluster
    urls = ";".join(f"http://127.0.0.1:{h.server_address[1]}"
                    for h, _ in srvs)
    pc = PartitionedCluster(urls)
    yield type("Part", (), {"pc": pc, "srvs": srvs})
    pc.close()
    for h, _ in srvs:
        h.shutdown()


def _push_topology(pc, n_slices=6):
    src = make_tpu_cluster([(f"s{i}", "v5e-16") for i in range(n_slices)])
    for n in src.nodes.values():
        pc.add_node(n)
    for hn in src.hypernodes.values():
        pc.add_hypernode(hn)
    return src


def test_partitioned_nodes_split_by_subtree(part):
    src = _push_topology(part.pc)
    layout = part.pc.shard_layout()
    assert sum(r["nodes"] for r in layout) == len(src.nodes)
    assert all(r["nodes"] > 0 for r in layout), layout
    # hypernodes (meta kind) all live on group 0
    assert len(part.pc.groups[0].hypernodes) == len(src.hypernodes)
    for g in part.pc.groups[1:]:
        assert not g.hypernodes
    # no node is mirrored by two groups
    for i, g in enumerate(part.pc.groups):
        for j in range(i + 1, len(part.pc.groups)):
            assert not set(g.nodes) & set(part.pc.groups[j].nodes)
    # merged read surface sees the whole fleet
    assert len(part.pc.nodes) == len(src.nodes)
    assert len(part.pc.list_all().nodes) == len(src.nodes)


def test_partitioned_bind_relocates_pod_to_owner_group(part):
    _push_topology(part.pc)
    pc = part.pc
    pod = make_pod("p0", requests={"cpu": 1})
    pc.add_pod(pod)
    assert "default/p0" in pc.meta.pods, "pending pods live in meta"

    # bind onto a node owned by a NON-meta group
    tgt_group = next(i for i, g in enumerate(pc.groups)
                     if i != 0 and g.nodes)
    target = sorted(pc.groups[tgt_group].nodes)[0]
    assert pc.bind_pods([("default", "p0", target)]) == [None]
    # the pod followed its node: owner group's mirror + server have it
    assert "default/p0" in pc.groups[tgt_group].pods
    merged = pc.pods["default/p0"]
    assert merged.node_name == target
    assert merged.phase is TaskStatus.BOUND

    deadline = time.time() + 5
    while time.time() < deadline:
        srv_meta = part.srvs[0][1].cluster.pods
        srv_tgt = part.srvs[tgt_group][1].cluster.pods
        if ("default/p0" not in srv_meta
                and "default/p0" in srv_tgt):
            break
        time.sleep(0.02)
    assert "default/p0" not in part.srvs[0][1].cluster.pods
    assert "default/p0" in part.srvs[tgt_group][1].cluster.pods

    # a second bind of the relocated pod conflicts per-item
    other = next(n for n in pc.nodes if n != target)
    errs = pc.bind_pods([("default", "p0", other)])
    assert errs[0] is not None

    # status flush for the bound pod routes to its owner group
    merged.status_message = "running along"
    pc.put_object("pod", merged)
    assert part.pc.groups[tgt_group].pods[
        "default/p0"].status_message == "running along"


def test_partitioned_gang_bind_splits_one_batch_per_group(part):
    _push_topology(part.pc)
    pc = part.pc
    calls = []
    for g in pc.groups:
        orig = g._request

        def counting(m, p, *args, _orig=orig, _g=g, **kw):
            if p == "/bind_batch":
                calls.append(_g)
            return _orig(m, p, *args, **kw)

        g._request = counting
    pods, binds = [], []
    # one pod per group's first node: a cross-group gang
    for g in pc.groups:
        node = sorted(g.nodes)[0]
        p = make_pod(f"gp-{node}", requests={"cpu": 1})
        pc.add_pod(p)
        pods.append(p)
        binds.append(("default", p.name, node))
    assert pc.bind_pods(binds) == [None, None, None]
    assert len(calls) == len(pc.groups), \
        "one /bind_batch per touched leader group"
    assert len({id(g) for g in calls}) == len(pc.groups)


def test_vtpctl_shards_view(part, capsys):
    """`vtpctl shards` against the partitioned endpoints: subtree
    ownership table, (empty) per-shard cycle section, and one write-
    QPS row per leader group."""
    from volcano_tpu.cli.vtpctl import main as vtpctl

    _push_topology(part.pc, n_slices=4)
    endpoints = ";".join(g.endpoints[0] for g in part.pc.groups)
    rc = vtpctl(["--server", endpoints, "shards", "--interval", "0.1"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "SHARD" in out and "OWNS" in out
    assert "WRITE-QPS" in out
    assert "meta+nodes" in out
    # 3 groups -> 3 write-QPS rows, shard plan covers all 4 subtrees
    assert out.count("\ns") >= 0  # smoke only; detailed below
    lines = [l for l in out.splitlines() if l.strip()]
    qps_rows = [l for l in lines if l.split() and
                l.split()[0] in ("0", "1", "2") and
                ("meta+nodes" in l or "nodes" in l)]
    assert len(qps_rows) >= 3, out


def test_bench_shard_smoke_mode():
    """`bench.py --shard-smoke` boots 2 scheduler shards + 2 leader
    groups as real OS processes, runs one cross-shard gang, and
    asserts placements identical to the single-shard plane — the
    sharded-plane drill guarded on every commit, mirroring
    --wire-smoke/--crash-smoke."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--shard-smoke"],
        capture_output=True, text=True, timeout=240, env=env, cwd=repo)
    assert proc.returncode == 0, \
        proc.stdout[-2000:] + proc.stderr[-2000:]
    line = next(l for l in reversed(proc.stdout.strip().splitlines())
                if l.startswith("{"))
    out = json.loads(line)
    assert out["ok"] is True, out
    assert out["placements_identical"] is True
    assert out["sharded"]["sched_shards_traced"] == ["0/2", "1/2"]
    assert all(d > 0 for d in out["sharded"]["leader_group_rv_delta"])
    assert out["sharded"]["jobs"] == out["single"]["jobs"]


def test_partitioned_meta_kinds_and_commands_stay_on_meta(part):
    pc = part.pc
    _push_topology(pc)
    from volcano_tpu.api.podgroup import PodGroup
    pc.add_podgroup(PodGroup(name="pgx", min_member=1))
    pc.add_queue(Queue(name="tenant"))
    assert "default/pgx" in pc.groups[0].podgroups
    assert "tenant" in pc.groups[0].queues
    pc.add_command("default/pgx", "requeue")
    assert [c["action"] for c in
            pc.drain_commands("default/pgx")] == ["requeue"]
    # merged views expose them too
    assert "default/pgx" in pc.podgroups
    assert "tenant" in pc.queues
