"""Node-local enforcement e2e: agent decision -> OS mutation -> revert
(VERDICT r2 item 4; reference: cgroup handlers under
pkg/agent/events/handlers/, tc/eBPF shaping pkg/networkqos/tc/
tc_linux.go:48-60)."""

from volcano_tpu.agent import FakeUsageProvider, NodeAgent
from volcano_tpu.agent.enforcer import (
    CgroupV2Enforcer,
    CompositeEnforcer,
    RecordingEnforcer,
    TcEnforcer,
    build_enforcer,
)
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.simulator import make_tpu_cluster

BE = {"volcano-tpu.io/qos-level": "BE"}


def be_pod(name, node, mem=None):
    req = {"cpu": "500m"}
    if mem:
        req["memory"] = mem
    return make_pod(name, node_name=node, phase=TaskStatus.RUNNING,
                    requests=req, annotations=dict(BE))


def test_cgroup_v2_real_writes_and_revert(tmp_path):
    """The REAL cgroup write path against a tmpdir root: burst and
    memory.high land in the interface files, throttling clamps
    cpu.max, and a departed pod's subtree is removed."""
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pod = be_pod("busy", "sa-w0", mem="1Gi")
    cluster.add_pod(pod)
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=0.2, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    cg = CgroupV2Enforcer(str(tmp_path / "kubepods"))
    agent = NodeAgent(cluster, "sa-w0", provider, enforcer=cg)

    agent.sync()
    # unthrottled BE: cpu.max open, burst sized from node idle
    assert cg.read(pod.uid, "cpu.max") == "max 100000"
    burst_us = int(cg.read(pod.uid, "cpu.max.burst"))
    assert burst_us > 0
    assert cg.read(pod.uid, "memory.high") == str(1024 ** 3)

    # pressure: throttle clamps quota to the request, zeroes burst
    provider.set("sa-w0", cpu_fraction=0.93, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    agent.sync()
    quota, period = cg.read(pod.uid, "cpu.max").split()
    assert int(quota) == 500 * 100000 // 1000    # request clamp
    assert cg.read(pod.uid, "cpu.max.burst") == "0"

    # config change reverts: pressure gone -> quota reopened
    provider.set("sa-w0", cpu_fraction=0.2, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    agent.sync()
    assert cg.read(pod.uid, "cpu.max") == "max 100000"

    # pod leaves the node -> enforcement subtree removed
    cluster.delete_pod(pod.key)
    agent.sync()
    assert cg.read(pod.uid, "cpu.max") is None


def test_tc_program_shape_idempotence_and_revert():
    """The HTB program: online/offline split classes + one class per
    BE pod; unchanged decisions re-run NOTHING; a departed pod's class
    is deleted; an online-pressure flip reprograms the split."""
    runs = []
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pod = be_pod("shaped", "sa-w0")
    cluster.add_pod(pod)
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=0.2, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    tc = TcEnforcer("eth0", runner=runs.append)
    agent = NodeAgent(cluster, "sa-w0", provider, enforcer=tc)

    agent.sync()
    flat = ["\x20".join(argv) for argv in runs]
    assert any("qdisc replace dev eth0 root" in c for c in flat)
    # offline ceil = 40% of the 100G default = 40000mbit
    assert any("classid 1:20" in c and "ceil 40000mbit" in c
               for c in flat)
    assert any("parent 1:20" in c for c in flat)   # per-pod class
    n = len(runs)

    agent.sync()                      # identical decisions
    assert len(runs) == n, "unchanged program must not re-run tc"

    # online pressure flips the split to 10% offline
    provider.set("sa-w0", cpu_fraction=0.85, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    agent.sync()
    flat = ["\x20".join(argv) for argv in runs[n:]]
    assert any("classid 1:20" in c and "ceil 10000mbit" in c
               for c in flat)

    # pod leaves -> class deleted
    n = len(runs)
    cluster.delete_pod(pod.key)
    agent.sync()
    flat = ["\x20".join(argv) for argv in runs[n:]]
    assert any(c.startswith("class del dev eth0") for c in flat)


def test_tc_class_removed_when_pod_promoted_out_of_be():
    """A pod that stops being best-effort while STAYING on the node
    must lose its kernel cap class, matching the annotation removal."""
    runs = []
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pod = be_pod("promoted", "sa-w0")
    cluster.add_pod(pod)
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=0.2, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    tc = TcEnforcer("eth0", runner=runs.append)
    agent = NodeAgent(cluster, "sa-w0", provider, enforcer=tc)
    agent.sync()
    assert any("parent 1:20" in "\x20".join(a) for a in runs)

    n = len(runs)
    del pod.annotations["volcano-tpu.io/qos-level"]   # promotion
    agent.sync()
    flat = ["\x20".join(a) for a in runs[n:]]
    assert any(c.startswith("class del dev eth0") for c in flat)
    assert "networkqos.volcano-tpu.io/pod-limit-mbps" \
        not in pod.annotations


def test_recording_enforcer_full_loop():
    """decision -> recorded mutation -> revert on pod departure, via
    the test-double enforcer the e2e deployments use."""
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pod = be_pod("ledger", "sa-w0")
    cluster.add_pod(pod)
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=0.3, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    rec = RecordingEnforcer()
    agent = NodeAgent(cluster, "sa-w0", provider, enforcer=rec)

    agent.sync()
    assert pod.uid in rec.pods and not rec.pods[pod.uid].throttled
    online, offline, limits = rec.network
    assert online + offline == 100_000 and pod.uid in limits

    agent.sync()
    ledger_len = len(rec.log)
    agent.sync()                      # steady state: no ledger noise
    assert len(rec.log) == ledger_len

    cluster.delete_pod(pod.key)
    agent.sync()
    assert pod.uid not in rec.pods
    assert ("remove", pod.uid) in rec.log


def test_build_enforcer_factory(tmp_path):
    from volcano_tpu.agent.enforcer import NullEnforcer
    assert isinstance(build_enforcer("none"), NullEnforcer)
    assert isinstance(build_enforcer("record"), RecordingEnforcer)
    root = str(tmp_path / "cg")
    e = build_enforcer(f"cgroup:{root},tc:eth1")
    assert isinstance(e, CompositeEnforcer)
    kinds = {type(x).__name__ for x in e.enforcers}
    assert kinds == {"CgroupV2Enforcer", "TcEnforcer"}
    # both halves share ONE class allocator — the classid the cgroup
    # half writes is the class the tc half creates
    cg, tc = sorted(e.enforcers, key=lambda x: type(x).__name__)
    assert cg.classids is tc.classids


def test_traffic_classification_pod_to_class_steering(tmp_path):
    """The classification half (VERDICT r3 missing #1): an offline
    pod's cgroup gets a net_cls.classid naming EXACTLY the HTB class
    tc created for it, the tc program includes the cgroup classifier
    filter, and promotion out of BE clears the tag."""
    runs = []
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pod = be_pod("steered", "sa-w0")
    cluster.add_pod(pod)
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=0.2, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    root = str(tmp_path / "kubepods")
    enf = build_enforcer(f"cgroup:{root},tc:eth0")
    tc = next(x for x in enf.enforcers if type(x).__name__ == "TcEnforcer")
    cg = next(x for x in enf.enforcers
              if type(x).__name__ == "CgroupV2Enforcer")
    tc.runner = runs.append
    agent = NodeAgent(cluster, "sa-w0", provider, enforcer=enf)

    agent.sync()
    flat = ["\x20".join(a) for a in runs]
    # the classifier filter is in the program
    assert any("filter replace dev eth0" in c and "cgroup" in c
               for c in flat), flat
    # the pod's class exists under the offline parent...
    cls = tc.classids.peek(pod.uid)
    assert cls is not None
    assert any(f"classid 1:{cls}" in c and "parent 1:20" in c
               for c in flat)
    # ...and the cgroup tag names that exact class (hex major:minor)
    assert cg.read(pod.uid, "net_cls.classid") == \
        f"0x{(1 << 16) | cls:08x}"

    # promotion out of BE: class deleted AND tag cleared to default
    del pod.annotations["volcano-tpu.io/qos-level"]
    agent.sync()
    assert tc.classids.peek(pod.uid) is None
    assert cg.read(pod.uid, "net_cls.classid") == "0x00000000"


def test_agent_restart_reconciles_stale_enforcement(tmp_path):
    """Pods that leave while the agent is DOWN: a fresh agent seeded
    from the enforcer's on-disk state reverts them on first sync, and
    a fresh TcEnforcer tears down the stale root qdisc before
    programming (ADVICE r3)."""
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pod = be_pod("ghost", "sa-w0", mem="1Gi")
    cluster.add_pod(pod)
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=0.2, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    root = str(tmp_path / "kubepods")
    cg = CgroupV2Enforcer(root)
    agent = NodeAgent(cluster, "sa-w0", provider, enforcer=cg)
    agent.sync()
    assert cg.read(pod.uid, "cpu.max") is not None

    # agent dies; pod leaves while it is down
    cluster.delete_pod(pod.key)
    cg2 = CgroupV2Enforcer(root)            # fresh process
    agent2 = NodeAgent(cluster, "sa-w0", provider, enforcer=cg2)
    assert pod.uid in agent2._enforced_uids   # seeded from disk
    agent2.sync()
    assert cg.read(pod.uid, "cpu.max") is None   # stale dir removed

    # tc half: first apply tears down whatever a dead agent left
    runs = []
    tc = TcEnforcer("eth0", runner=runs.append)
    tc.apply_network(60_000, 40_000, {})
    assert runs[0] == ["qdisc", "del", "dev", "eth0", "root"]


def test_reconcile_confined_to_owned_subtree(tmp_path):
    """ADVICE r4 medium: the restart sweep must never touch foreign
    cgroups on a shared hierarchy.  A shared-looking root is narrowed
    to {root}/volcano, so pre-existing init.scope / kubelet dirs
    beside the owned subtree are invisible to enforced_uids and
    survive a reconciling first sync."""
    import os

    root = tmp_path / "sys-fs-cgroup"
    for foreign in ["init.scope", "kubepods-burstable.slice",
                    "some-kubelet-pod-uid"]:
        os.makedirs(root / foreign)
    # unprefixed dir INSIDE the owned subtree (e.g. an operator's
    # own nesting under a shared 'volcano' dir): the vtp- prefix is
    # the ownership mark, so it must be invisible to the sweep too
    os.makedirs(root / "volcano" / "operator-dir")
    cg = CgroupV2Enforcer(str(root))
    assert cg.root == str(root / "volcano")
    assert cg.enforced_uids() == set()          # foreign dirs invisible

    cluster = make_tpu_cluster([("sa", "v5e-16")])
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=0.2, tpu_chips_detected=4,
                 tpu_chips_healthy=4)
    agent = NodeAgent(cluster, "sa-w0", provider, enforcer=cg)
    agent.sync()                                # reconciling first sync
    for foreign in ["init.scope", "kubepods-burstable.slice",
                    "some-kubelet-pod-uid"]:
        assert (root / foreign).is_dir()        # untouched
    assert (root / "volcano" / "operator-dir").is_dir()

    # a root already inside a volcano subtree is taken as-is
    owned = tmp_path / "volcano" / "pods"
    assert CgroupV2Enforcer(str(owned)).root == str(owned)


def test_offline_class_allocator_recycles_minors():
    """ADVICE r4 low: released HTB minors are reused lowest-first so
    a long-lived agent never walks off the 16-bit minor space."""
    from volcano_tpu.agent.enforcer import (
        FIRST_POD_CLASS,
        OfflineClassAllocator,
    )

    alloc = OfflineClassAllocator()
    a, b, c = (alloc.classid(u) for u in ["a", "b", "c"])
    assert (a, b, c) == (FIRST_POD_CLASS, FIRST_POD_CLASS + 1,
                         FIRST_POD_CLASS + 2)
    alloc.release("b")
    alloc.release("a")
    assert alloc.classid("d") == a              # lowest freed first
    assert alloc.classid("e") == b
    assert alloc.classid("f") == FIRST_POD_CLASS + 3
    # idempotent per uid
    assert alloc.classid("d") == a


def test_tc_reprograms_when_recycled_minor_yields_identical_argv():
    """A new pod that inherits a departed pod's recycled minor and
    limit produces byte-identical tc argv right after that class was
    deleted — the program cache must still reprogram (it keys on
    uid->class, not argv alone)."""
    runs = []
    tc = TcEnforcer("eth0", runner=runs.append)
    tc.apply_network(60_000, 40_000, {"pod-a": 100})
    assert ["class", "del", "dev", "eth0", "classid", "1:21"] not in runs

    # pod-a leaves, pod-b arrives with the SAME limit in one sync
    tc.remove_pod("pod-a")
    assert ["class", "del", "dev", "eth0", "classid", "1:21"] in runs
    n = len(runs)
    tc.apply_network(60_000, 40_000, {"pod-b": 100})
    # pod-b recycled minor 21: the class MUST be recreated
    recreated = [r for r in runs[n:]
                 if r[:2] == ["class", "replace"] and "1:21" in r]
    assert recreated, runs[n:]
    assert tc.enforced_uids() == {"pod-b"}


def test_tc_cache_invalidated_on_remove_even_after_failed_reprogram():
    """Demote -> class deleted -> reprogram FAILS transiently ->
    readmit with the recycled minor: the cache was invalidated by the
    delete, so the class is recreated (an argv-identical key must not
    mask the kernel mutation)."""
    calls = []
    fail = {"on": False}

    def runner(argv):
        calls.append(argv)
        if fail["on"] and argv[0] == "qdisc":
            raise RuntimeError("transient tc failure")

    tc = TcEnforcer("eth0", runner=runner)
    tc.apply_network(60_000, 40_000, {"pod-a": 100})
    # promote pod-a out; the base reprogram fails transiently
    fail["on"] = True
    tc.apply_network(60_000, 40_000, {})
    fail["on"] = False
    assert ["class", "del", "dev", "eth0", "classid", "1:21"] in calls
    n = len(calls)
    # demote pod-a back: same uid, recycled minor, identical argv
    tc.apply_network(60_000, 40_000, {"pod-a": 100})
    recreated = [r for r in calls[n:]
                 if r[:2] == ["class", "replace"] and "1:21" in r]
    assert recreated, calls[n:]


def test_legacy_unprefixed_dirs_warn_at_startup(tmp_path, caplog):
    """ADVICE r5 #3: a pre-prefix agent wrote pod dirs as {root}/{uid}
    (no 'vtp-'), which the prefixed sweep deliberately never touches —
    an in-place upgrade must WARN about the orphaned state instead of
    silently letting stale cpu/memory/net_cls limits persist."""
    import logging
    import os

    root = tmp_path / "kubepods" / "volcano"
    old = root / "old-uid-1"
    old.mkdir(parents=True)
    (old / "cpu.max").write_text("5000 100000\n")
    # a dir with no enforcer knob files is NOT ours (foreign entry on
    # a shared hierarchy): must not be flagged
    (root / "init.scope").mkdir()

    with caplog.at_level(logging.WARNING, "volcano_tpu.agent.enforcer"):
        cg = CgroupV2Enforcer(str(root))
    msgs = [r.message for r in caplog.records
            if "legacy unprefixed" in r.message]
    assert len(msgs) == 1 and "old-uid-1" in msgs[0]
    assert "init.scope" not in msgs[0]
    # the legacy dir is detected, never swept
    assert (old / "cpu.max").exists()
    # current-layout pods are unaffected
    assert cg.enforced_uids() == set()

    # narrowed-root upgrade shape: the configured root lacked a
    # 'volcano' component, so the pre-upgrade agent wrote pod dirs
    # DIRECTLY under it while the upgraded enforcer owns
    # {root}/volcano — the scan must cover the pre-narrowing root
    caplog.clear()
    shared = tmp_path / "shared-kubepods"
    legacy2 = shared / "old-uid-2"
    legacy2.mkdir(parents=True)
    (legacy2 / "memory.high").write_text("1073741824\n")
    with caplog.at_level(logging.WARNING, "volcano_tpu.agent.enforcer"):
        cg2 = CgroupV2Enforcer(str(shared))
    assert cg2.root.endswith("volcano")
    msgs2 = [r.message for r in caplog.records
             if "legacy unprefixed" in r.message]
    assert len(msgs2) == 1 and "old-uid-2" in msgs2[0]
    # the owned subtree itself is never reported as legacy
    assert "volcano" not in msgs2[0].split("(")[1].split(")")[0]

    # a clean root (only vtp- dirs) stays silent
    caplog.clear()
    clean = tmp_path / "clean" / "volcano"
    (clean / "vtp-abc").mkdir(parents=True)
    with caplog.at_level(logging.WARNING, "volcano_tpu.agent.enforcer"):
        CgroupV2Enforcer(str(clean))
    assert not [r for r in caplog.records
                if "legacy unprefixed" in r.message]
