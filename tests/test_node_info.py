"""NodeInfo accounting invariants (reference: node_info_test.go)."""

import pytest

from volcano_tpu.api.job_info import TaskInfo
from volcano_tpu.api.node_info import Node, NodeInfo
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.resource import CPU, TPU
from volcano_tpu.api.types import (
    TPU_COORDS_LABEL,
    TPU_SLICE_LABEL,
    TPU_WORKER_ID_LABEL,
    TaskStatus,
)


def mk_node(name="n0", cpu="8", tpu=4, labels=None):
    return NodeInfo(Node(name=name, labels=dict(labels or {}),
                         allocatable={"cpu": cpu, TPU: tpu}))


def mk_task(name, cpu="1", tpu=0, status=TaskStatus.PENDING):
    req = {"cpu": cpu}
    if tpu:
        req[TPU] = tpu
    return TaskInfo(make_pod(name, requests=req, phase=status))


def test_add_remove_task_balances():
    ni = mk_node()
    t = mk_task("p0", cpu="2", tpu=4, status=TaskStatus.RUNNING)
    ni.add_task(t)
    assert ni.idle.get(CPU) == 6000 and ni.idle.tpu == 0
    assert ni.used.tpu == 4
    ni.remove_task(t)
    assert ni.idle.equal(ni.allocatable) and ni.used.is_empty()


def test_overcommit_rejected_for_scheduler_placements():
    ni = mk_node(cpu="1")
    with pytest.raises(ValueError):
        ni.add_task(mk_task("p0", cpu="2", status=TaskStatus.ALLOCATED))


def test_replayed_running_pod_clamps_instead_of_crashing():
    # Cache rebuild: node allocatable shrank under an existing pod; the
    # node must absorb it (idle clamped at 0), not abort construction.
    ni = mk_node(cpu="1")
    ni.add_task(mk_task("p0", cpu="2", status=TaskStatus.RUNNING))
    assert ni.idle.get(CPU) == 0
    assert ni.used.get(CPU) == 2000


def test_node_holds_clone_so_job_mutation_cannot_desync():
    ni = mk_node(cpu="8")
    t = mk_task("p", cpu="2", status=TaskStatus.PIPELINED)
    ni.add_task(t)
    # Job-side mutation of the caller's object must not affect node copy.
    t.status = TaskStatus.ALLOCATED
    ni.remove_task(t)
    assert ni.pipelined.is_empty()
    assert ni.idle.get(CPU) == 8000 and ni.used.is_empty()


def test_future_idle_with_releasing_and_pipelined():
    ni = mk_node(cpu="8")
    running = mk_task("r", cpu="4", status=TaskStatus.RUNNING)
    ni.add_task(running)
    ni.update_task_status(running, TaskStatus.RELEASING)
    assert ni.idle.get(CPU) == 4000
    assert ni.future_idle().get(CPU) == 8000

    ni.add_task(mk_task("pipe", cpu="3", status=TaskStatus.PIPELINED))
    assert ni.future_idle().get(CPU) == 5000
    # pipelined doesn't consume idle now
    assert ni.idle.get(CPU) == 4000


def test_status_transition_pipelined_to_bound():
    ni = mk_node(cpu="8")
    t = mk_task("p", cpu="2", status=TaskStatus.PIPELINED)
    ni.add_task(t)
    ni.update_task_status(t, TaskStatus.BOUND)
    assert ni.idle.get(CPU) == 6000 and ni.pipelined.is_empty()


def test_tpu_identity_from_labels():
    ni = mk_node(labels={TPU_SLICE_LABEL: "slice-a",
                         TPU_WORKER_ID_LABEL: "7",
                         TPU_COORDS_LABEL: "1,2,0"})
    assert ni.tpu_slice == "slice-a"
    assert ni.tpu_worker_id == 7
    assert ni.ici_coords == (1, 2, 0)


def test_clone_independent_accounting():
    ni = mk_node()
    c = ni.clone()
    c.add_task(mk_task("p", cpu="1", status=TaskStatus.RUNNING))
    assert ni.idle.get(CPU) == 8000 and c.idle.get(CPU) == 7000
