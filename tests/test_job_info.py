"""JobInfo/TaskInfo/SubJobInfo gang accounting (reference: job_info_test.go)."""

from volcano_tpu.api.job_info import JobInfo, SubJobInfo, TaskInfo
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.podgroup import PodGroup, SubGroupPolicy
from volcano_tpu.api.types import SUBGROUP_LABEL, TaskStatus


def mk_job(min_member=3, min_task_member=None, subgroups=()):
    pg = PodGroup(name="job1", min_member=min_member,
                  min_task_member=dict(min_task_member or {}),
                  sub_group_policies=list(subgroups))
    return JobInfo(uid="j1", podgroup=pg)


def mk_task(name, status=TaskStatus.PENDING, cpu="1", spec="worker",
            labels=None, priority=0):
    pod = make_pod(name, requests={"cpu": cpu}, phase=status,
                   labels=labels, priority=priority)
    pod.task_spec = spec
    return TaskInfo(pod, job_uid="j1")


def test_add_remove_task_accounting():
    job = mk_job()
    t = mk_task("p0")
    job.add_task(t)
    assert job.total_request.milli_cpu == 1000
    assert len(job.tasks_in_status(TaskStatus.PENDING)) == 1
    job.remove_task(t)
    assert job.total_request.is_empty()
    assert not job.tasks


def test_ready_and_pipelined_counting():
    job = mk_job(min_member=3)
    for i, st in enumerate([TaskStatus.RUNNING, TaskStatus.ALLOCATED,
                            TaskStatus.PIPELINED, TaskStatus.PENDING]):
        job.add_task(mk_task(f"p{i}", status=st))
    assert job.ready_task_num() == 2
    assert job.waiting_task_num() == 1
    assert not job.is_ready()
    assert job.is_pipelined()          # 2 ready + 1 pipelined >= 3
    # pipelined reservations count against starvation (job_info.go:1210)
    assert not job.is_starving()
    job.update_task_status(job.tasks_in_status(TaskStatus.PIPELINED)[0],
                           TaskStatus.PENDING)
    assert job.is_starving()           # 2 ready + 0 waiting < 3


def test_update_task_status_moves_index():
    job = mk_job(min_member=1)
    t = mk_task("p0")
    job.add_task(t)
    job.update_task_status(t, TaskStatus.ALLOCATED)
    assert job.ready_task_num() == 1
    assert not job.tasks_in_status(TaskStatus.PENDING)
    assert job.is_ready()


def test_task_min_available():
    # minAvailable >= sum of task minima: the per-task check binds
    # (below the sum it is skipped entirely, job_info.go:1026-1029)
    job = mk_job(min_member=3, min_task_member={"ps": 1, "worker": 2})
    job.add_task(mk_task("ps0", spec="ps", status=TaskStatus.RUNNING))
    job.add_task(mk_task("w0", spec="worker", status=TaskStatus.RUNNING))
    assert not job.check_task_min_available_ready()   # worker has 1 of 2
    job.add_task(mk_task("w1", spec="worker", status=TaskStatus.ALLOCATED))
    assert job.check_task_min_available_ready()
    assert job.check_task_min_available()


def test_task_min_available_skipped_below_sum():
    """minAvailable below the per-task total: per-task minima do not
    bind (what lets dependsOn jobs gang on their first stage)."""
    job = mk_job(min_member=1, min_task_member={"ps": 1, "worker": 2})
    job.add_task(mk_task("ps0", spec="ps", status=TaskStatus.RUNNING))
    assert job.check_task_min_available()
    assert job.check_task_min_available_ready()


def test_subjob_gang():
    sg = SubGroupPolicy(name="sliceA", min_member=2)
    job = mk_job(min_member=4, subgroups=[sg])
    for i in range(2):
        job.add_task(mk_task(f"a{i}", status=TaskStatus.ALLOCATED,
                             labels={SUBGROUP_LABEL: "sliceA"}))
    job.add_task(mk_task("b0", status=TaskStatus.PENDING))
    sub = job.sub_jobs["sliceA"]
    assert sub.ready_task_num() == 2 and sub.is_ready()
    root = job.sub_jobs[""]
    assert len(root.tasks) == 1


def test_clone_is_deep_for_tasks():
    job = mk_job(min_member=1)
    t = mk_task("p0")
    job.add_task(t)
    c = job.clone()
    c.update_task_status(list(c.tasks.values())[0], TaskStatus.ALLOCATED)
    assert t.status is TaskStatus.PENDING
    assert job.ready_task_num() == 0 and c.ready_task_num() == 1


def test_min_request_uses_cheapest_tasks():
    job = mk_job(min_member=2)
    job.add_task(mk_task("big", cpu="4"))
    job.add_task(mk_task("s1", cpu="1"))
    job.add_task(mk_task("s2", cpu="1"))
    assert job.min_request().milli_cpu == 2000
