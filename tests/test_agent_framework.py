"""Agent event framework + collectors + memoryqosv2 (VERDICT r4
missing #1/#2; reference pkg/agent/events/framework/factory.go,
pkg/agent/events/handlers/registry.go + memoryqosv2/,
pkg/metriccollect).
"""

import pytest

from volcano_tpu.agent import (
    CompositeUsageProvider,
    FakeUsageProvider,
    NodeAgent,
    build_provider,
    registered_handlers,
)
from volcano_tpu.agent.enforcer import CgroupV2Enforcer
from volcano_tpu.agent.framework import (
    EVENT_PODS,
    EVENT_PRESSURE,
    EVENT_USAGE,
    Handler,
    register_handler,
)
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.simulator import make_tpu_cluster

BE = {"volcano-tpu.io/qos-level": "BE"}


def mk_agent(tmp_path, pods=(), usage=None):
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    for p in pods:
        cluster.add_pod(p)
    provider = FakeUsageProvider()
    provider.set("sa-w0", **(usage or dict(
        cpu_fraction=0.2, tpu_chips_detected=4, tpu_chips_healthy=4)))
    cg = CgroupV2Enforcer(str(tmp_path / "cg"))
    return cluster, NodeAgent(cluster, "sa-w0", provider,
                              enforcer=cg), cg


def test_default_pipeline_has_twelve_registered_handlers():
    """The sync loop owns no concerns: everything is a registered
    handler (adding one = registering, not editing the loop).
    netaccounting dispatches AFTER networkqos (same-sync caps are its
    watermarks) and before enforcement; goodput and serving ride the
    same pods event after it."""
    names = [cls.name for cls in registered_handlers()]
    assert names == [
        "usagereporter", "tpuhealth", "oversubscription", "cpuqos",
        "memoryqosv2", "networkqos", "netaccounting", "goodput",
        "serving", "numaexporter", "enforcement", "eviction"]
    # subscriptions are typed: eviction never sees plain usage events
    by_name = {cls.name: cls for cls in registered_handlers()}
    assert by_name["eviction"].events == (EVENT_PRESSURE,)
    assert by_name["tpuhealth"].events == (EVENT_USAGE,)
    assert by_name["enforcement"].events == (EVENT_PODS,)
    assert by_name["netaccounting"].events == (EVENT_PODS,)
    assert by_name["goodput"].events == (EVENT_PODS,)
    assert by_name["serving"].events == (EVENT_PODS,)


def test_custom_handler_registers_and_dispatches(tmp_path):
    """A new concern plugs in via @register_handler without touching
    the agent: it sees the same typed events as the built-ins."""
    seen = []

    @register_handler
    class ProbeWitnessHandler(Handler):
        name = "probewitness"
        events = (EVENT_USAGE, EVENT_PODS)

        def handle(self, event):
            seen.append((event.type, len(event.pods)))

    try:
        pod = make_pod("w", node_name="sa-w0", phase=TaskStatus.RUNNING,
                       requests={"cpu": "500m"}, annotations=dict(BE))
        _, agent, _ = mk_agent(tmp_path, pods=[pod])
        agent.sync()
        assert (EVENT_USAGE, 0) in seen
        assert (EVENT_PODS, 1) in seen
    finally:
        from volcano_tpu.agent import framework
        framework._REGISTRY.remove(ProbeWitnessHandler)


def test_memoryqosv2_knobs_per_qos_class(tmp_path):
    """Online pods get the kernel guarantee (memory.min = request,
    memory.low above it); BE pods get the memory.high cap — and a
    promotion BE -> online flips the knobs on the SAME cgroup."""
    be = make_pod("batch", node_name="sa-w0", phase=TaskStatus.RUNNING,
                  requests={"cpu": "500m", "memory": "1Gi"},
                  annotations=dict(BE))
    online = make_pod("serve", node_name="sa-w0",
                      phase=TaskStatus.RUNNING,
                      requests={"cpu": "1", "memory": "2Gi"})
    _, agent, cg = mk_agent(tmp_path, pods=[be, online])
    agent.sync()

    gib = 1024 ** 3
    assert cg.read(be.uid, "memory.high") == str(gib)
    assert cg.read(be.uid, "memory.min") == "0"
    assert cg.read(online.uid, "memory.min") == str(2 * gib)
    assert cg.read(online.uid, "memory.low") == str(int(2 * gib * 1.25))
    assert cg.read(online.uid, "memory.high") == "max"

    # promotion: BE annotation removed -> guarantee replaces the cap
    del be.annotations["volcano-tpu.io/qos-level"]
    agent.sync()
    assert cg.read(be.uid, "memory.min") == str(gib)
    assert cg.read(be.uid, "memory.high") == "max"


def test_composite_provider_merges_and_degrades():
    """Collectors contribute partial samples; later ones override per
    key; a raising collector degrades to nothing instead of killing
    the sync."""
    class Cpu:
        name = "cpu"

        def collect(self, node):
            return {"cpu_fraction": 0.5, "memory_fraction": 0.3}

    class Tpu:
        name = "tpu"

        def collect(self, node):
            return {"tpu_chips_detected": 4, "tpu_chips_healthy": 3}

    class Broken:
        name = "broken"

        def collect(self, node):
            raise RuntimeError("backend down")

    u = CompositeUsageProvider([Cpu(), Tpu(), Broken()]).usage("n0")
    assert u.cpu_fraction == 0.5 and u.memory_fraction == 0.3
    assert u.tpu_chips_detected == 4 and u.tpu_chips_healthy == 3


def test_local_proc_collector_parses_kernel_format(tmp_path):
    """The REAL /proc parse against injected files: cpu fraction from
    stat deltas (no sample on first call), memory from MemAvailable/
    MemTotal."""
    from volcano_tpu.agent.collect import LocalProcCollector

    stat = tmp_path / "stat"
    meminfo = tmp_path / "meminfo"
    meminfo.write_text("MemTotal:       16000000 kB\n"
                       "MemFree:         2000000 kB\n"
                       "MemAvailable:    4000000 kB\n")
    stat.write_text("cpu  100 0 100 800 0 0 0 0 0 0\n")
    c = LocalProcCollector(str(stat), str(meminfo))
    first = c.collect("n0")
    assert "cpu_fraction" not in first       # no delta yet
    assert first["memory_fraction"] == pytest.approx(0.75)
    # 100 more busy jiffies, 100 more idle -> 50% over the window
    stat.write_text("cpu  200 0 100 900 0 0 0 0 0 0\n")
    second = c.collect("n0")
    assert second["cpu_fraction"] == pytest.approx(0.5)


def test_build_provider_by_name(tmp_path):
    prov = build_provider("local,tpu")
    names = [c.name for c in prov.collectors]
    assert names == ["local", "tpu"]
    with pytest.raises(ValueError):
        build_provider("nonexistent")


def test_oversubscription_not_fabricated_without_cpu_sample(tmp_path):
    """A collector set with no cpu source must not read the 0.0
    default as 'fully idle' and publish phantom reclaimable capacity
    (the same guard __main__ applies to the no-backend case)."""
    from volcano_tpu.agent.agent import OVERSUB_ANNOTATION

    class TpuOnly:
        name = "tpuonly"

        def collect(self, node):
            return {"tpu_chips_detected": 4, "tpu_chips_healthy": 4}

    cluster = make_tpu_cluster([("sa", "v5e-16")])
    agent = NodeAgent(cluster, "sa-w0",
                      CompositeUsageProvider([TpuOnly()]))
    agent.sync()
    node = cluster.nodes["sa-w0"]
    assert node.annotations[OVERSUB_ANNOTATION] == "0"

    # with a cpu sample the same pipeline publishes real slack
    class Cpu(TpuOnly):
        name = "cpu"

        def collect(self, node):
            return {"cpu_fraction": 0.2}

    agent2 = NodeAgent(cluster, "sa-w0",
                       CompositeUsageProvider([TpuOnly(), Cpu()]))
    agent2.sync()
    assert int(node.annotations[OVERSUB_ANNOTATION]) > 0


def test_local_collector_keeps_per_node_delta_windows(tmp_path):
    """One provider serving several agents: each node keeps its own
    /proc/stat delta window (a shared window would tear to zero-jiffy
    deltas for every node after the first)."""
    from volcano_tpu.agent.collect import LocalProcCollector

    stat = tmp_path / "stat"
    meminfo = tmp_path / "meminfo"
    meminfo.write_text("MemTotal: 1000 kB\nMemAvailable: 500 kB\n")
    stat.write_text("cpu  100 0 100 800 0 0 0 0 0 0\n")
    c = LocalProcCollector(str(stat), str(meminfo))
    c.collect("n0")
    c.collect("n1")
    stat.write_text("cpu  200 0 100 900 0 0 0 0 0 0\n")
    assert c.collect("n0")["cpu_fraction"] == pytest.approx(0.5)
    assert c.collect("n1")["cpu_fraction"] == pytest.approx(0.5)


def test_cpu_qos_level_class_knobs(tmp_path):
    """cpuqos qos-level analogue (reference cpuqos_linux.go writes a
    kernel cpu.qos_level int): the class ladder LC/HLS > LS > BE maps
    to cgroup-v2 cpu.weight 400/100/1, with BE additionally parked in
    SCHED_IDLE via cpu.idle — and a promotion rewrites the knobs."""
    lc = make_pod("critical", node_name="sa-w0",
                  phase=TaskStatus.RUNNING, requests={"cpu": "1"},
                  annotations={"volcano-tpu.io/qos-level": "LC"})
    ls = make_pod("serve", node_name="sa-w0", phase=TaskStatus.RUNNING,
                  requests={"cpu": "1"})      # unannotated -> LS
    be = make_pod("batch", node_name="sa-w0", phase=TaskStatus.RUNNING,
                  requests={"cpu": "500m"}, annotations=dict(BE))
    _, agent, cg = mk_agent(tmp_path, pods=[lc, ls, be])
    agent.sync()

    assert cg.read(lc.uid, "cpu.weight") == "400"
    assert cg.read(lc.uid, "cpu.idle") == "0"
    assert cg.read(ls.uid, "cpu.weight") == "100"
    assert cg.read(be.uid, "cpu.idle") == "1"
    # the real kernel rejects weight writes on idle groups (EINVAL),
    # so the enforcer must NOT touch cpu.weight while idle is set
    assert cg.read(be.uid, "cpu.weight") is None

    # promotion BE -> LS flips the class knobs on the same cgroup
    del be.annotations["volcano-tpu.io/qos-level"]
    agent.sync()
    assert cg.read(be.uid, "cpu.weight") == "100"
    assert cg.read(be.uid, "cpu.idle") == "0"
