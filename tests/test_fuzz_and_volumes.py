"""Job-controller fuzz (reference: job/fuzz_test.go) + volumebinding,
pod-topology-spread, oversubscription, jobflow probes."""

import random

from volcano_tpu.api.node_info import Node
from volcano_tpu.api.pod import Container, Pod
from volcano_tpu.api.types import (
    FINISHED_JOB_PHASES,
    JobAction,
    JobEvent,
    JobPhase,
    TaskStatus,
)
from volcano_tpu.api.vcjob import LifecyclePolicy, TaskSpec, VCJob
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.uthelper import TestContext, gang_job
from volcano_tpu.webhooks import default_admission


def test_job_controller_fuzz_random_events():
    """Random pod failures/completions/deletions + commands must never
    crash the controller, violate pod-count invariants, or wedge a job
    in a non-terminal phase forever."""
    rng = random.Random(42)
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=["job", "garbagecollector"])
    sched = Scheduler(cluster, schedule_period=0)

    jobs = []
    for i in range(4):
        job = VCJob(
            name=f"fuzz{i}", min_available=2, max_retry=2,
            tasks=[TaskSpec(name="w", replicas=3,
                            template=Pod(name="t", containers=[
                                Container(requests={"cpu": 1})]))],
            policies=[LifecyclePolicy(action=rng.choice(
                [JobAction.RESTART_JOB, JobAction.RESTART_TASK,
                 JobAction.ABORT_JOB]),
                event=JobEvent.POD_FAILED)])
        jobs.append(cluster.add_vcjob(job))

    for step in range(60):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
        op = rng.random()
        pods = [p for p in cluster.pods.values()
                if p.phase is TaskStatus.RUNNING]
        if op < 0.3 and pods:
            cluster.complete_pod(rng.choice(pods).key, succeeded=False,
                                 exit_code=rng.choice([1, 137, 255]))
        elif op < 0.5 and pods:
            cluster.complete_pod(rng.choice(pods).key, succeeded=True)
        elif op < 0.6 and pods:
            cluster.delete_pod(rng.choice(pods).key)
        elif op < 0.7 and jobs:
            target = rng.choice(jobs)
            cluster.add_command(target.key, rng.choice(
                ["AbortJob", "ResumeJob", "RestartJob", "CompleteJob"]))

        # invariants after every step
        for job in jobs:
            live = cluster.vcjobs.get(job.key)
            if live is None:
                continue
            owned = [p for p in cluster.pods.values()
                     if p.owner == live.uid]
            assert len(owned) <= live.total_replicas(), \
                f"{live.key} has {len(owned)} pods > replicas"
            assert live.retry_count <= live.max_retry + 1
            if live.phase in FINISHED_JOB_PHASES and \
                    live.phase is not JobPhase.COMPLETED:
                # failed/aborted jobs release resources eventually
                pass

    # drain: stop injecting chaos, let everything settle
    for _ in range(10):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    for job in jobs:
        live = cluster.vcjobs.get(job.key)
        if live is not None:
            assert live.phase in (JobPhase.RUNNING, JobPhase.PENDING,
                                  *FINISHED_JOB_PHASES), \
                f"{live.key} wedged in {live.phase}"


def test_volumebinding_zone_affinity_and_assume_cache():
    zone_a = Node(name="za", allocatable={"cpu": 8},
                  labels={"topology.kubernetes.io/zone": "z-a"})
    zone_b = Node(name="zb", allocatable={"cpu": 8},
                  labels={"topology.kubernetes.io/zone": "z-b"})
    pg, pods = gang_job("dbjob", replicas=1, requests={"cpu": 1})
    pods[0].annotations["volume.volcano-tpu.io/claims"] = "pvc-data"
    ctx = TestContext(nodes=[zone_a, zone_b], podgroups=[pg], pods=pods,
                      conf={"actions": "enqueue, allocate",
                            "tiers": [{"plugins": [
                                {"name": "gang"}, {"name": "predicates"},
                                {"name": "volumebinding"}]}]})
    ctx.cluster.put_object(
        "pv", {"capacity_gi": 100, "zone": "z-b", "claimed_by": ""},
        key="pv-1")
    ctx.cluster.put_object(
        "pvc", {"request_gi": 10, "bound_pv": ""}, key="pvc-data")
    ctx.run()
    ctx.expect_bind("default/dbjob-0", "zb")   # volume gravity
    # binding committed at session close
    assert ctx.cluster.pvcs["pvc-data"]["bound_pv"] == "pv-1"
    assert ctx.cluster.pvs["pv-1"]["claimed_by"] == "pvc-data"


def test_pod_topology_spread():
    nodes = [Node(name=f"n{i}", allocatable={"cpu": 32, "pods": 110},
                  labels={"zone": f"z{i % 2}"}) for i in range(4)]
    pg, pods = gang_job("spread", replicas=4, requests={"cpu": 1})
    for p in pods:
        p.annotations["spread.volcano-tpu.io/topology-key"] = "zone"
        p.annotations["spread.volcano-tpu.io/max-skew"] = "1"
    ctx = TestContext(nodes=nodes, podgroups=[pg], pods=pods,
                      conf={"actions": "enqueue, allocate",
                            "tiers": [{"plugins": [
                                {"name": "gang"}, {"name": "predicates"},
                                {"name": "pod-topology-spread"},
                                {"name": "binpack"}]}]})
    ctx.run()
    ctx.expect_bind_num(4)
    per_zone = {}
    for _, n in ctx.cluster.binds:
        zone = next(node.labels["zone"] for node in nodes
                    if node.name == n)
        per_zone[zone] = per_zone.get(zone, 0) + 1
    assert abs(per_zone.get("z0", 0) - per_zone.get("z1", 0)) <= 1


def test_oversubscription_serves_only_be_pods():
    node = Node(name="n0", allocatable={"cpu": 4},
                annotations={
                    "oversubscription.volcano-tpu.io/cpu-millis": "2000"})
    # node is full of guaranteed work
    pg_g, pods_g = gang_job("guaranteed", replicas=1, requests={"cpu": 4},
                            running_on=["n0"])
    from volcano_tpu.api.types import PodGroupPhase
    pg_g.phase = PodGroupPhase.RUNNING
    # a BE pod fits via the slack; a guaranteed pod does not
    pg_be, pods_be = gang_job("be", replicas=1, requests={"cpu": 1})
    pods_be[0].annotations["volcano-tpu.io/qos-level"] = "BE"
    pg_no, pods_no = gang_job("strict", replicas=1, requests={"cpu": 1})
    ctx = TestContext(nodes=[node], podgroups=[pg_g, pg_be, pg_no],
                      pods=pods_g + pods_be + pods_no)
    ctx.run()
    assert "default/be-0" in ctx.bind_map
    assert "default/strict-0" not in ctx.bind_map


def test_jobflow_probe_running_gate():
    from volcano_tpu.api.jobflow import Flow, FlowDependsOn, JobFlow
    from volcano_tpu.api.jobflow import JobTemplate
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=["job", "jobflow"])
    sched = Scheduler(cluster, schedule_period=0)

    def template(name):
        return JobTemplate(name=name, job=VCJob(
            name=name, min_available=1,
            tasks=[TaskSpec(name="w", replicas=1,
                            template=Pod(name="t", containers=[
                                Container(requests={"cpu": 1})]))]))

    cluster.jobtemplates = {"default/server": template("server"),
                            "default/client": template("client")}
    flow = JobFlow(name="svcflow", flows=[
        Flow(name="server"),
        Flow(name="client", depends_on=FlowDependsOn(
            targets=["server"], probes=[{"phase": "Running"}])),
    ])
    cluster.jobflows = {flow.key: flow}
    for _ in range(4):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    # server is Running (never Completed) yet client deployed
    assert cluster.vcjobs["default/svcflow-server"].phase is JobPhase.RUNNING
    assert "default/svcflow-client" in cluster.vcjobs
