"""Resource vector semantics (reference: resource_info_test.go)."""

import pytest

from volcano_tpu.api.resource import CPU, MEMORY, TPU, Resource, parse_quantity


def test_from_resource_list_parses_quantities():
    r = Resource.from_resource_list({"cpu": "250m", "memory": "1Gi",
                                     TPU: 4})
    assert r.milli_cpu == 250
    assert r.memory == 2**30
    assert r.tpu == 4


def test_cpu_cores_to_millicores():
    assert Resource.from_resource_list({"cpu": 2}).milli_cpu == 2000
    assert Resource.from_resource_list({"cpu": "1.5"}).milli_cpu == 1500


def test_parse_quantity_units():
    assert parse_quantity("4Gi") == 4 * 2**30
    assert parse_quantity("1k") == 1000
    assert parse_quantity(7) == 7.0


def test_add_sub():
    a = Resource({CPU: 1000, MEMORY: 100, TPU: 8})
    b = Resource({CPU: 400, TPU: 4})
    a.add(b)
    assert a.get(CPU) == 1400 and a.tpu == 12
    a.sub(b)
    assert a.get(CPU) == 1000 and a.tpu == 8


def test_sub_underflow_raises():
    a = Resource({CPU: 100})
    with pytest.raises(ValueError):
        a.sub(Resource({CPU: 200}))
    # unchecked clamps
    a.sub_unchecked(Resource({CPU: 200}))
    assert a.get(CPU) == 0


def test_less_equal_default_zero():
    small = Resource({CPU: 100, TPU: 1})
    big = Resource({CPU: 200, TPU: 4})
    assert small.less_equal(big)
    assert not big.less_equal(small)
    # missing dimension in other => treated as zero
    assert not Resource({TPU: 1}).less_equal(Resource({CPU: 100}))


def test_less_equal_default_infinity_for_capability():
    req = Resource({CPU: 100, TPU: 8})
    cap = Resource({CPU: 200})  # TPU dim unset => unlimited
    assert req.less_equal(cap, zero="defaultInfinity")
    assert not req.less_equal(cap, zero="defaultZero")


def test_fit_delta_and_diff():
    idle = Resource({CPU: 100, TPU: 2})
    req = Resource({CPU: 300, TPU: 2})
    missing = idle.fit_delta(req)
    assert missing.get(CPU) == 200 and missing.tpu == 0

    inc, dec = Resource({CPU: 100}).diff(Resource({CPU: 40, TPU: 4}))
    assert inc.get(CPU) == 60 and dec.tpu == 4


def test_set_max_and_min_dim():
    a = Resource({CPU: 100, TPU: 8})
    a.set_max(Resource({CPU: 300, MEMORY: 10}))
    assert a.get(CPU) == 300 and a.get(MEMORY) == 10 and a.tpu == 8
    a.min_dim(Resource({CPU: 200, TPU: 8}))
    assert a.get(CPU) == 200 and a.get(MEMORY) == 0


def test_empty_and_clone_independent():
    assert Resource().is_empty()
    a = Resource({TPU: 4})
    b = a.clone()
    b.add(Resource({TPU: 4}))
    assert a.tpu == 4 and b.tpu == 8


def test_equality():
    assert Resource({CPU: 100}) == Resource({CPU: 100.05})
    assert Resource({CPU: 100}) != Resource({CPU: 101})
