"""HyperJob multi-domain splitting + forwarding binder (VERDICT r1
item 8; reference training/v1alpha1/hyperjob.go:37-82 splitPolicy +
cache.go:400 podgroupBinder).
"""

from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.controllers.hyperjob import (FORWARD_DOMAIN_ANNOTATION,
                                              HyperJob, HyperJobController,
                                              ReplicatedJob, SplitPolicy)
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster


def training_template(pods=8, chips=4) -> VCJob:
    return VCJob(
        name="tmpl", min_available=pods,
        tasks=[TaskSpec(name="worker", replicas=pods,
                        template=make_pod("t", requests={
                            "cpu": 8, TPU: chips}))])


def two_pod_cluster():
    """Two DCN pods, one v5e-16 slice (4 hosts x 4 chips) each."""
    return make_tpu_cluster(
        [("sa", "v5e-16"), ("sb", "v5e-16")],
        dcn_pods={"sa": "pod-a", "sb": "pod-b"})


def test_static_split_across_domains():
    """8-pod/32-chip replica with static 16-chip splits -> two member
    jobs, forwarded to distinct DCN pods."""
    cluster = two_pod_cluster()
    hj = HyperJob(name="big", min_available=2, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=8, chips=4),
                      split_policy=SplitPolicy(mode="static",
                                               accelerators=16))])
    cluster.put_object("hyperjob", hj)
    ctrl = HyperJobController()
    ctrl.initialize(cluster)
    ctrl.sync()

    members = sorted(j for j in cluster.vcjobs if "big-train-0-s" in j)
    assert members == ["default/big-train-0-s0", "default/big-train-0-s1"]
    j0 = cluster.vcjobs["default/big-train-0-s0"]
    j1 = cluster.vcjobs["default/big-train-0-s1"]
    assert j0.tasks[0].replicas == 4 and j1.tasks[0].replicas == 4
    assert j0.min_available == 4 and j1.min_available == 4
    domains = {j.annotations[FORWARD_DOMAIN_ANNOTATION] for j in (j0, j1)}
    assert domains == {"pod-a", "pod-b"}
    assert cluster.hyperjobs["default/big"].split_count == 2
    # resync is idempotent: no member churn
    ctrl.sync()
    assert sorted(j for j in cluster.vcjobs
                  if "big-train-0-s" in j) == members


def test_auto_split_follows_free_capacity():
    """auto mode sizes splits by per-domain free chips: with pod-a half
    occupied (8 free) and pod-b empty (16 free), a 24-chip replica
    splits 16 (pod-b) + 8 (pod-a)."""
    cluster = two_pod_cluster()
    for i in (0, 1):   # occupy 2 of 4 hosts in sa
        cluster.add_pod(make_pod(f"occ-{i}", requests={TPU: 4},
                                 node_name=f"sa-w{i}",
                                 phase=TaskStatus.RUNNING))
    hj = HyperJob(name="auto", min_available=2, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=6, chips=4),
                      split_policy=SplitPolicy(mode="auto"))])
    cluster.put_object("hyperjob", hj)
    ctrl = HyperJobController()
    ctrl.initialize(cluster)
    ctrl.sync()

    members = {j.annotations[FORWARD_DOMAIN_ANNOTATION]:
               j.tasks[0].replicas
               for j in cluster.vcjobs.values()
               if "auto-train-0-s" in j.name}
    assert members == {"pod-b": 4, "pod-a": 2}, members


def test_split_members_schedule_into_their_domains():
    """End-to-end: split members gang-schedule, each entirely inside
    its forwarded DCN pod."""
    cluster = two_pod_cluster()
    hj = HyperJob(name="e2e", min_available=2, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=8, chips=4),
                      split_policy=SplitPolicy(mode="static",
                                               accelerators=16))])
    cluster.put_object("hyperjob", hj)
    mgr = ControllerManager(cluster, enabled=["hyperjob", "job",
                                              "podgroup", "queue"])
    sched = Scheduler(cluster)
    for _ in range(4):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    mgr.stop()

    placements = {}
    for pod in cluster.pods.values():
        if pod.node_name and "e2e-train" in pod.name:
            member = pod.name.rsplit("-worker-", 1)[0]
            placements.setdefault(member, set()).add(
                pod.node_name.rsplit("-w", 1)[0])
    assert len(placements) == 2, placements
    slices = [s for v in placements.values() for s in v]
    assert all(len(v) == 1 for v in placements.values()), placements
    assert set(slices) == {"sa", "sb"}
    # podgroups carry the forward annotation (binder seam)
    for member in placements:
        pg = cluster.podgroups[f"default/{member}"]
        assert FORWARD_DOMAIN_ANNOTATION in pg.annotations


def test_unsplit_replicated_jobs_unchanged():
    cluster = two_pod_cluster()
    hj = HyperJob(name="plain", min_available=1, replicated_jobs=[
        ReplicatedJob(name="m", replicas=2,
                      template=training_template(pods=2, chips=4))])
    cluster.put_object("hyperjob", hj)
    ctrl = HyperJobController()
    ctrl.initialize(cluster)
    ctrl.sync()
    assert "default/plain-m-0" in cluster.vcjobs
    assert "default/plain-m-1" in cluster.vcjobs
    assert cluster.hyperjobs["default/plain"].split_count == 2


def test_multicluster_binder_forwards_to_member_control_planes():
    """REAL multi-cluster forwarding (VERDICT r3 missing #3): the hub's
    HyperJob controller creates split members in TWO other state-server
    clusters through RemoteCluster clients; each member cluster's own
    job controller + scheduler run them, and the hub aggregates member
    phases back across the wire."""
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.api.types import JobPhase
    from volcano_tpu.controllers.hyperjob import (HyperJobPhase,
                                                  MultiClusterBinder)
    from volcano_tpu.server.state_server import serve
    from volcano_tpu.webhooks import default_admission

    planes = {}

    def member_plane(name):
        backing = make_tpu_cluster([(name[-1] * 2, "v5e-16")])
        backing.admission = default_admission()
        httpd, _ = serve(port=0, cluster=backing)
        client = RemoteCluster(
            f"http://127.0.0.1:{httpd.server_address[1]}")
        planes[name] = (backing, httpd, client,
                        ControllerManager(backing, enabled=["job",
                                                            "queue"]),
                        Scheduler(backing, schedule_period=0))
        return client

    remotes = {"cluster-b": member_plane("cluster-b"),
               "cluster-c": member_plane("cluster-c")}
    hub = make_tpu_cluster([("sa", "v5e-16")],
                           dcn_pods={"sa": "pod-a"})
    hj = HyperJob(name="fed", min_available=2, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=8, chips=4),
                      split_policy=SplitPolicy(mode="auto"))])
    hub.put_object("hyperjob", hj)
    ctrl = HyperJobController(binder=MultiClusterBinder(hub, remotes))
    ctrl.initialize(hub)
    try:
        ctrl.sync()
        # auto split against each member cluster's 16 free chips: the
        # 32-chip replica becomes one 16-chip member PER cluster
        assert not any("fed-train" in k for k in hub.vcjobs), \
            "members must live in the member clusters, not the hub"
        placement = {}
        for domain, (backing, *_rest) in planes.items():
            mine = [k for k in backing.vcjobs if "fed-train-0-s" in k]
            placement[domain] = mine
            for k in mine:
                assert backing.vcjobs[k].annotations[
                    FORWARD_DOMAIN_ANNOTATION] == domain
        assert sorted(len(v) for v in placement.values()) == [1, 1], \
            placement

        # each member cluster schedules its member like any local job
        for backing, _h, _c, mgr, sched in planes.values():
            for _ in range(4):
                mgr.sync_all()
                sched.run_once()
                backing.tick()
        for domain, keys in placement.items():
            backing = planes[domain][0]
            assert backing.vcjobs[keys[0]].phase is JobPhase.RUNNING

        # the hub observes member phases through the client mirrors
        # and turns the HyperJob Running
        for _b, _h, client, _m, _s in planes.values():
            client.resync()
        ctrl.sync()
        assert hub.hyperjobs[hj.key].phase is HyperJobPhase.RUNNING
        # re-sync never duplicates members across clusters
        ctrl.sync()
        total = sum(len([k for k in b.vcjobs if "fed-train-0-s" in k])
                    for b, *_ in planes.values())
        assert total == 2
    finally:
        for _b, httpd, client, mgr, _s in planes.values():
            client.close()
            mgr.stop()
            httpd.shutdown()


def test_partial_split_resumes_same_plan_after_domain_failure():
    """One member cluster briefly down: the deploy failure is retried
    on the NEXT sync from the persisted split plan — the partial set
    is never declared complete, and the retry keeps the same member
    names/sizes."""
    from volcano_tpu.controllers.hyperjob import MultiClusterBinder

    class FlakyBinder(MultiClusterBinder):
        def __init__(self, cluster, remotes):
            super().__init__(cluster, remotes)
            self.fail_domains = set()

        def submit(self, job, domain):
            if domain in self.fail_domains:
                raise ConnectionError(f"{domain} unreachable")
            super().submit(job, domain)

    from volcano_tpu.cache.fake_cluster import FakeCluster
    hub = make_tpu_cluster([("sa", "v5e-16")], dcn_pods={"sa": "pod-a"})
    b, c = FakeCluster(), FakeCluster()
    binder = FlakyBinder(hub, {"cluster-b": b, "cluster-c": c})
    hj = HyperJob(name="flaky", min_available=2, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=8, chips=4),
                      split_policy=SplitPolicy(mode="static",
                                               accelerators=16))])
    hub.put_object("hyperjob", hj)
    ctrl = HyperJobController(binder=binder)
    ctrl.initialize(hub)

    binder.fail_domains = {"cluster-c"}
    ctrl.sync()
    assert len(b.vcjobs) == 1 and len(c.vcjobs) == 0
    plan_after_first = dict(hub.hyperjobs[hj.key].split_plans)

    binder.fail_domains = set()
    ctrl.sync()
    # the missing member materialized in cluster-c with its planned
    # name; cluster-b's member was not duplicated or resized
    assert sorted(b.vcjobs) == ["default/flaky-train-0-s0"]
    assert sorted(c.vcjobs) == ["default/flaky-train-0-s1"]
    assert hub.hyperjobs[hj.key].split_plans == plan_after_first


def test_hierarchy_annotation_feeds_hdrf_queue_chain():
    """The queue mutate webhook's rooted hierarchy annotation is the
    hdrf tree: two annotated queues share the intermediate 'eng' node
    in their root-to-leaf chains."""
    from volcano_tpu.api.queue import Queue
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.plugins.drf import DRFPlugin
    from volcano_tpu.webhooks import default_admission
    from volcano_tpu.webhooks.admission import HIERARCHY_ANNOTATION, \
        HIERARCHY_WEIGHTS_ANNOTATION

    cluster = FakeCluster(admission=default_admission())
    cluster.put_object("queue", Queue(name="ml", annotations={
        HIERARCHY_ANNOTATION: "eng/ml",
        HIERARCHY_WEIGHTS_ANNOTATION: "2/1"}))
    cluster.put_object("queue", Queue(name="web", annotations={
        HIERARCHY_ANNOTATION: "eng/web",
        HIERARCHY_WEIGHTS_ANNOTATION: "2/1"}))
    plugin = DRFPlugin({"drf.enable-hierarchy": True})
    plugin._queues = cluster.queues
    assert plugin._queue_chain("ml") == ["ml", "eng", "root"]
    assert plugin._queue_chain("web") == ["web", "eng", "root"]


def test_auto_split_defers_until_capacity_visible():
    """A hub whose member mirrors are still blind (zero visible
    capacity) must NOT persist a degenerate one-domain plan — the
    HyperJob stays Pending and replans once capacity appears."""
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.controllers.hyperjob import (HyperJobPhase,
                                                  MultiClusterBinder)

    hub = make_tpu_cluster([("sa", "v5e-16")], dcn_pods={"sa": "pod-a"})
    b = FakeCluster()                       # EMPTY: mirror not synced
    hj = HyperJob(name="blind", min_available=1, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=4, chips=4),
                      split_policy=SplitPolicy(mode="auto"))])
    hub.put_object("hyperjob", hj)
    ctrl = HyperJobController(
        binder=MultiClusterBinder(hub, {"cluster-b": b}))
    ctrl.initialize(hub)
    ctrl.sync()
    live = hub.hyperjobs[hj.key]
    assert live.split_plans == {}, "blind plan must not persist"
    assert live.phase is HyperJobPhase.PENDING
    assert not b.vcjobs

    # capacity appears (mirror synced) -> plan lands normally
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.simulator import slice_nodes
    for node in slice_nodes(slice_for("sb", "v5e-16")):
        b.add_node(node)
    ctrl.sync()
    assert [k for k in b.vcjobs if "blind-train-0-s" in k]


def test_deferred_plan_keeps_previous_split_count():
    """ADVICE r4 low: a cycle where any split plan defers (blind
    member-mirror warmup) must not overwrite split_count with the
    partial total — status keeps the last known member count."""
    cluster = two_pod_cluster()
    hj = HyperJob(name="big", min_available=2, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=8, chips=4),
                      split_policy=SplitPolicy(mode="static",
                                               accelerators=16))])
    cluster.put_object("hyperjob", hj)
    ctrl = HyperJobController()
    ctrl.initialize(cluster)
    ctrl.sync()
    assert cluster.hyperjobs["default/big"].split_count == 2

    # next cycle defers (capacity view not ready): count must hold
    orig = ctrl._sync_split_replica
    ctrl._sync_split_replica = lambda *a, **k: ([], None)
    ctrl.sync()
    assert cluster.hyperjobs["default/big"].split_count == 2
    ctrl._sync_split_replica = orig
    ctrl.sync()
    assert cluster.hyperjobs["default/big"].split_count == 2
