"""HyperJob multi-domain splitting + forwarding binder (VERDICT r1
item 8; reference training/v1alpha1/hyperjob.go:37-82 splitPolicy +
cache.go:400 podgroupBinder).
"""

from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.controllers.hyperjob import (FORWARD_DOMAIN_ANNOTATION,
                                              HyperJob, HyperJobController,
                                              ReplicatedJob, SplitPolicy)
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster


def training_template(pods=8, chips=4) -> VCJob:
    return VCJob(
        name="tmpl", min_available=pods,
        tasks=[TaskSpec(name="worker", replicas=pods,
                        template=make_pod("t", requests={
                            "cpu": 8, TPU: chips}))])


def two_pod_cluster():
    """Two DCN pods, one v5e-16 slice (4 hosts x 4 chips) each."""
    return make_tpu_cluster(
        [("sa", "v5e-16"), ("sb", "v5e-16")],
        dcn_pods={"sa": "pod-a", "sb": "pod-b"})


def test_static_split_across_domains():
    """8-pod/32-chip replica with static 16-chip splits -> two member
    jobs, forwarded to distinct DCN pods."""
    cluster = two_pod_cluster()
    hj = HyperJob(name="big", min_available=2, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=8, chips=4),
                      split_policy=SplitPolicy(mode="static",
                                               accelerators=16))])
    cluster.put_object("hyperjob", hj)
    ctrl = HyperJobController()
    ctrl.initialize(cluster)
    ctrl.sync()

    members = sorted(j for j in cluster.vcjobs if "big-train-0-s" in j)
    assert members == ["default/big-train-0-s0", "default/big-train-0-s1"]
    j0 = cluster.vcjobs["default/big-train-0-s0"]
    j1 = cluster.vcjobs["default/big-train-0-s1"]
    assert j0.tasks[0].replicas == 4 and j1.tasks[0].replicas == 4
    assert j0.min_available == 4 and j1.min_available == 4
    domains = {j.annotations[FORWARD_DOMAIN_ANNOTATION] for j in (j0, j1)}
    assert domains == {"pod-a", "pod-b"}
    assert cluster.hyperjobs["default/big"].split_count == 2
    # resync is idempotent: no member churn
    ctrl.sync()
    assert sorted(j for j in cluster.vcjobs
                  if "big-train-0-s" in j) == members


def test_auto_split_follows_free_capacity():
    """auto mode sizes splits by per-domain free chips: with pod-a half
    occupied (8 free) and pod-b empty (16 free), a 24-chip replica
    splits 16 (pod-b) + 8 (pod-a)."""
    cluster = two_pod_cluster()
    for i in (0, 1):   # occupy 2 of 4 hosts in sa
        cluster.add_pod(make_pod(f"occ-{i}", requests={TPU: 4},
                                 node_name=f"sa-w{i}",
                                 phase=TaskStatus.RUNNING))
    hj = HyperJob(name="auto", min_available=2, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=6, chips=4),
                      split_policy=SplitPolicy(mode="auto"))])
    cluster.put_object("hyperjob", hj)
    ctrl = HyperJobController()
    ctrl.initialize(cluster)
    ctrl.sync()

    members = {j.annotations[FORWARD_DOMAIN_ANNOTATION]:
               j.tasks[0].replicas
               for j in cluster.vcjobs.values()
               if "auto-train-0-s" in j.name}
    assert members == {"pod-b": 4, "pod-a": 2}, members


def test_split_members_schedule_into_their_domains():
    """End-to-end: split members gang-schedule, each entirely inside
    its forwarded DCN pod."""
    cluster = two_pod_cluster()
    hj = HyperJob(name="e2e", min_available=2, replicated_jobs=[
        ReplicatedJob(name="train", replicas=1,
                      template=training_template(pods=8, chips=4),
                      split_policy=SplitPolicy(mode="static",
                                               accelerators=16))])
    cluster.put_object("hyperjob", hj)
    mgr = ControllerManager(cluster, enabled=["hyperjob", "job",
                                              "podgroup", "queue"])
    sched = Scheduler(cluster)
    for _ in range(4):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    mgr.stop()

    placements = {}
    for pod in cluster.pods.values():
        if pod.node_name and "e2e-train" in pod.name:
            member = pod.name.rsplit("-worker-", 1)[0]
            placements.setdefault(member, set()).add(
                pod.node_name.rsplit("-w", 1)[0])
    assert len(placements) == 2, placements
    slices = [s for v in placements.values() for s in v]
    assert all(len(v) == 1 for v in placements.values()), placements
    assert set(slices) == {"sa", "sb"}
    # podgroups carry the forward annotation (binder seam)
    for member in placements:
        pg = cluster.podgroups[f"default/{member}"]
        assert FORWARD_DOMAIN_ANNOTATION in pg.annotations


def test_unsplit_replicated_jobs_unchanged():
    cluster = two_pod_cluster()
    hj = HyperJob(name="plain", min_available=1, replicated_jobs=[
        ReplicatedJob(name="m", replicas=2,
                      template=training_template(pods=2, chips=4))])
    cluster.put_object("hyperjob", hj)
    ctrl = HyperJobController()
    ctrl.initialize(cluster)
    ctrl.sync()
    assert "default/plain-m-0" in cluster.vcjobs
    assert "default/plain-m-1" in cluster.vcjobs
    assert cluster.hyperjobs["default/plain"].split_count == 2
