"""Elastic gangs: transparent shrink/grow/migrate as a scheduler
decision (ISSUE 6).

The subsystem spans four layers:

  admission   (webhooks/admission.py): min/max-slices validated, the
      submit size defaults to the floor, replicas must divide into an
      integral pods-per-slice;
  scheduler   (actions/elastic.py + plugins/elastic.py): after
      allocate, grow running elastic jobs into idle slices; under
      pressure, shrink them toward the floor BEFORE gangpreempt
      evicts anyone (jobStarving veto while capacity is en route),
      victims picked topology-aware; pending elastic gangs resize
      down to fit idle capacity, and a gang parked at its floor
      publishes the bounded `elastic-waiting-for-capacity` reason;
  controller  (controllers/elastic.py): executes decisions by
      generalizing the failover drain — scale replicas, stamp
      floor-guarded resume metadata + generation, ONE job-level
      RestartJob, re-place, observe elastic_* latencies;
  workload    (jax plugin -> worker): TPU_NUM_SLICES follows the
      resize so the hybrid mesh rebuilds at the new world size; a
      dp-dimension resize with a constant global batch is
      loss-continuous (the dryrun below proves it end-to-end).

Race coverage (satellite): a slice failure arriving mid-resize must
not double-drain the gang or regress VTP_RESUME_STEP.
"""

import json
import os
import pickle
import subprocess
import sys
import time

import pytest

from volcano_tpu import metrics, trace
from volcano_tpu.api import elastic as eapi
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.slicehealth import (
    LAST_STEP_ANNOTATION,
    NODE_QUARANTINED_UNTIL_ANNOTATION,
    REQUEUED_ANNOTATION,
    RESUME_STEP_ANNOTATION,
)
from volcano_tpu.api.types import JobPhase, TPU_SLICE_LABEL
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import fail_host, make_tpu_cluster
from volcano_tpu.webhooks import default_admission
from volcano_tpu.webhooks.admission import AdmissionError, mutate_job, \
    validate_job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ELASTIC_CONF = {
    "actions": "enqueue, allocate, elastic, gangpreempt, backfill",
    "tiers": [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "failover"}, {"name": "elastic"},
                     {"name": "conformance"}]},
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"}, {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "binpack"},
                     {"name": "deviceshare"},
                     {"name": "network-topology-aware"}]},
    ],
    # tests drive synchronous cycles: no resize damping wanted
    "configurations": {"elastic": {"elastic.cooldownSeconds": 0}},
}


def elastic_job(name="etrain", slices=1, lo=1, hi=2, pods_per_slice=4,
                annotations=None):
    ann = {
        eapi.ELASTIC_MIN_SLICES_ANNOTATION: str(lo),
        eapi.ELASTIC_MAX_SLICES_ANNOTATION: str(hi),
        eapi.ELASTIC_SLICES_ANNOTATION: str(slices),
    }
    ann.update(annotations or {})
    return VCJob(
        name=name, min_available=slices * pods_per_slice,
        annotations=ann, plugins={"jax": []},
        tasks=[TaskSpec(name="worker",
                        replicas=slices * pods_per_slice,
                        template=make_pod("t",
                                          requests={"cpu": 8, TPU: 4}))])


def fixed_job(name="fixed", replicas=4, run_ticks=None):
    from volcano_tpu.api.types import RUN_TICKS_ANNOTATION
    ann = {} if run_ticks is None else \
        {RUN_TICKS_ANNOTATION: str(run_ticks)}
    return VCJob(
        name=name, min_available=replicas,
        tasks=[TaskSpec(name="worker", replicas=replicas,
                        template=make_pod("t", annotations=ann,
                                          requests={"cpu": 8, TPU: 4}))])


def plane(slices, dcn_pods=None):
    cluster = make_tpu_cluster(slices, dcn_pods=dcn_pods)
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=[
        "job", "podgroup", "queue", "failover", "elastic"])
    sched = Scheduler(cluster, conf=ELASTIC_CONF, schedule_period=0)
    return cluster, mgr, sched


def drive(cluster, mgr, sched, n=1):
    for _ in range(n):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()


def slices_of(cluster, job):
    return sorted({cluster.nodes[p.node_name].labels[TPU_SLICE_LABEL]
                   for p in cluster.pods.values()
                   if p.owner == job.uid and p.node_name})


# -- admission ---------------------------------------------------------

def test_admission_validates_elastic_range():
    bad = [
        # min > max
        {eapi.ELASTIC_MIN_SLICES_ANNOTATION: "4",
         eapi.ELASTIC_MAX_SLICES_ANNOTATION: "2"},
        # non-integer
        {eapi.ELASTIC_MIN_SLICES_ANNOTATION: "one",
         eapi.ELASTIC_MAX_SLICES_ANNOTATION: "2"},
        # min < 1
        {eapi.ELASTIC_MIN_SLICES_ANNOTATION: "0",
         eapi.ELASTIC_MAX_SLICES_ANNOTATION: "2"},
        # half a contract
        {eapi.ELASTIC_MAX_SLICES_ANNOTATION: "2"},
        # size outside the range
        {eapi.ELASTIC_MIN_SLICES_ANNOTATION: "1",
         eapi.ELASTIC_MAX_SLICES_ANNOTATION: "2",
         eapi.ELASTIC_SLICES_ANNOTATION: "3"},
    ]
    for ann in bad:
        job = VCJob(name="e", annotations=dict(ann),
                    tasks=[TaskSpec(name="w", replicas=4,
                                    template=make_pod(
                                        "t", requests={TPU: 4}))])
        with pytest.raises(AdmissionError):
            validate_job(mutate_job(job))

    # replicas must divide into the slice count
    job = VCJob(name="e", annotations={
        eapi.ELASTIC_MIN_SLICES_ANNOTATION: "3",
        eapi.ELASTIC_MAX_SLICES_ANNOTATION: "4"},
        tasks=[TaskSpec(name="w", replicas=4,
                        template=make_pod("t", requests={TPU: 4}))])
    with pytest.raises(AdmissionError, match="pods-per-slice"):
        validate_job(mutate_job(job))

    # subgrouped gangs cannot be elastic (static subgroup count pins
    # the slice topology; the resize machinery scales ONE grid)
    sub = VCJob(name="e", annotations={
        eapi.ELASTIC_MIN_SLICES_ANNOTATION: "1",
        eapi.ELASTIC_MAX_SLICES_ANNOTATION: "2"},
        tasks=[TaskSpec(name="w", replicas=2, subgroup="s0",
                        template=make_pod("t", requests={TPU: 4}))])
    with pytest.raises(AdmissionError, match="subgrouped"):
        validate_job(mutate_job(sub))

    # good: slices defaults to the floor
    job = mutate_job(VCJob(name="e", annotations={
        eapi.ELASTIC_MIN_SLICES_ANNOTATION: "2",
        eapi.ELASTIC_MAX_SLICES_ANNOTATION: "4"},
        tasks=[TaskSpec(name="w", replicas=8,
                        template=make_pod("t", requests={TPU: 4}))]))
    validate_job(job)
    assert job.annotations[eapi.ELASTIC_SLICES_ANNOTATION] == "2"


# -- grow --------------------------------------------------------------

def test_grow_absorbs_idle_slices():
    """An elastic gang submitted at its floor grows into the idle
    slice: one drain, doubled world, workers re-env'd for the new
    mesh, history + generation + metrics recorded — zero evictions."""
    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_vcjob(elastic_job())
    drive(cluster, mgr, sched, 12)
    job = cluster.vcjobs["default/etrain"]
    pg = cluster.podgroups["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    assert slices_of(cluster, job) == ["sa", "sb"]
    assert eapi.current_slices(pg) == 2
    assert pg.annotations[eapi.ELASTIC_GENERATION_ANNOTATION] == "1"
    assert pg.min_member == 8
    hist = eapi.resize_history(pg)
    assert hist[-1]["kind"] == "grow"
    assert (hist[-1]["from"], hist[-1]["to"]) == (1, 2)
    assert not cluster.evictions
    assert metrics.get_observations("elastic_resize_seconds",
                                    kind="grow")
    # the rebuilt workers see the new world: 8 processes over 2
    # dcn slices (jax plugin keyed on the CURRENT slice count)
    pod = next(p for p in cluster.pods.values() if p.owner == job.uid)
    env = pod.containers[0].env
    assert env["NUM_PROCESSES"] == "8"
    assert env["TPU_NUM_SLICES"] == "2"
    assert env["TPU_SLICE_ID"] in ("0", "1")
    # the global batch is pinned to the FLOOR world (1 slice x 4 pods
    # x 4 chips) — resize-invariant, so the trajectory stays
    # loss-continuous at any size
    assert env["WORKER_GLOBAL_BATCH"] == "16"
    # steady state: no further decisions pending
    drive(cluster, mgr, sched, 2)
    assert eapi.desired_slices(cluster.podgroups["default/etrain"]) \
        is None


def test_grow_race_with_new_demand_self_corrects():
    """A grow decision that raced brand-new fixed demand (decided
    when the cluster was idle, executed after the fixed gang claimed
    a slice) must not leave EITHER side starving: the fixed gang
    keeps its slice and the elastic gang re-fits to what remains —
    the wedge is temporary by construction."""
    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16"),
                                 ("sc", "v5e-16")])
    cluster.add_vcjob(elastic_job(hi=3))
    drive(cluster, mgr, sched, 2)     # grow-to-3 decided/executing
    cluster.add_vcjob(fixed_job())    # races into the drain window
    drive(cluster, mgr, sched, 14)
    job = cluster.vcjobs["default/etrain"]
    fixed = cluster.vcjobs["default/fixed"]
    assert fixed.phase is JobPhase.RUNNING
    assert job.phase is JobPhase.RUNNING
    # elastic settled on what was left (2 of 3 slices)
    assert eapi.current_slices(cluster.podgroups["default/etrain"]) == 2
    assert len(slices_of(cluster, job)) == 2


# -- shrink ------------------------------------------------------------

def test_shrink_frees_capacity_before_gangpreempt_evicts():
    """A pending fixed gang takes the slice an elastic shrink frees:
    the gang schedules WITHOUT a single eviction although gangpreempt
    runs every cycle (the elastic plugin's jobStarving veto holds it
    while capacity is en route)."""
    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_vcjob(elastic_job())   # grows to 2 slices first
    drive(cluster, mgr, sched, 12)
    assert eapi.current_slices(cluster.podgroups["default/etrain"]) == 2
    before = len(metrics.get_observations("elastic_shrink_seconds"))

    cluster.add_vcjob(fixed_job())
    drive(cluster, mgr, sched, 14)
    job = cluster.vcjobs["default/etrain"]
    fixed = cluster.vcjobs["default/fixed"]
    pg = cluster.podgroups["default/etrain"]
    assert fixed.phase is JobPhase.RUNNING
    assert job.phase is JobPhase.RUNNING
    assert eapi.current_slices(pg) == 1
    assert len(slices_of(cluster, job)) == 1
    assert eapi.resize_history(pg)[-1]["kind"] == "shrink"
    assert not cluster.evictions       # shrink pre-empted the preempt
    assert len(metrics.get_observations("elastic_shrink_seconds")) \
        > before


def test_shrink_stops_at_the_floor():
    """Demand beyond what shrinking to min-slices can free leaves the
    elastic gang at its floor — an elastic range is a contract, not a
    suggestion."""
    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_vcjob(elastic_job(slices=2, lo=2, hi=2))
    drive(cluster, mgr, sched, 4)
    assert cluster.vcjobs["default/etrain"].phase is JobPhase.RUNNING
    cluster.add_vcjob(fixed_job())
    drive(cluster, mgr, sched, 8)
    pg = cluster.podgroups["default/etrain"]
    assert eapi.current_slices(pg) == 2          # floor held
    assert eapi.resize_history(pg) == []
    assert cluster.vcjobs["default/fixed"].phase is JobPhase.PENDING


def test_pending_elastic_gang_resizes_down_to_fit():
    """A PENDING elastic gang sized beyond available capacity starts
    at what fits (spec-only resize — nothing ran, nothing drains)."""
    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_vcjob(fixed_job())               # occupies one slice
    drive(cluster, mgr, sched, 2)
    cluster.add_vcjob(elastic_job(slices=2, lo=1, hi=2))
    version_probe = cluster.vcjobs["default/etrain"].version
    drive(cluster, mgr, sched, 10)
    job = cluster.vcjobs["default/etrain"]
    pg = cluster.podgroups["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    assert eapi.current_slices(pg) == 1
    assert len(slices_of(cluster, job)) == 1
    assert job.version == version_probe          # no restart happened
    assert eapi.resize_history(pg)[-1]["kind"] == "shrink"


# -- topology-aware victim selection -----------------------------------

def test_shrink_victim_chosen_in_the_idle_rich_domain():
    """Two elastic gangs in different DCN pods; the pending 2-slice
    hard-topology gang needs a CONTIGUOUS block.  The shrink victim
    must be the gang co-located with the idle slice, so freed + idle
    form one domain-local block."""
    from volcano_tpu.api.podgroup import NetworkTopologySpec
    from volcano_tpu.api.types import NetworkTopologyMode

    cluster, mgr, sched = plane(
        [("pa1", "v5e-16"), ("pa2", "v5e-16"), ("pa3", "v5e-16"),
         ("pb1", "v5e-16"), ("pb2", "v5e-16")],
        dcn_pods={"pa1": "pod-a", "pa2": "pod-a", "pa3": "pod-a",
                  "pb1": "pod-b", "pb2": "pod-b"})
    # ea: 2 slices in pod-a (one more slice idle there)
    # eb: 2 slices in pod-b (its pod is full)
    from volcano_tpu.controllers.hypernode import DCN_POD_LABEL
    ja = elastic_job("ea", slices=2, lo=1, hi=2)
    jb = elastic_job("eb", slices=2, lo=1, hi=2)
    ja.tasks[0].template.node_selector = {DCN_POD_LABEL: "pod-a"}
    jb.tasks[0].template.node_selector = {DCN_POD_LABEL: "pod-b"}
    cluster.add_vcjob(ja)
    cluster.add_vcjob(jb)
    drive(cluster, mgr, sched, 6)
    assert cluster.vcjobs["default/ea"].phase is JobPhase.RUNNING
    assert cluster.vcjobs["default/eb"].phase is JobPhase.RUNNING

    # pending gang: 2 slices, hard topology (one domain)
    want = VCJob(
        name="twoslice", min_available=8,
        network_topology=NetworkTopologySpec(
            NetworkTopologyMode.HARD, highest_tier_allowed=2),
        tasks=[TaskSpec(name="worker", replicas=8,
                        template=make_pod(
                            "t", requests={"cpu": 8, TPU: 4}))])
    cluster.add_vcjob(want)
    drive(cluster, mgr, sched, 16)
    pga = cluster.podgroups["default/ea"]
    pgb = cluster.podgroups["default/eb"]
    # the victim was ea (pod-a already held the idle slice) — eb, in
    # the full domain, kept its world
    assert eapi.current_slices(pga) == 1
    assert eapi.current_slices(pgb) == 2
    tw = cluster.vcjobs["default/twoslice"]
    assert tw.phase is JobPhase.RUNNING
    homes = {cluster.nodes[p.node_name].labels[DCN_POD_LABEL]
             for p in cluster.pods.values()
             if p.owner == tw.uid and p.node_name}
    assert homes == {"pod-a"}


# -- resume metadata + races vs failover -------------------------------

def test_resize_stamps_resume_step_and_never_regresses():
    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_vcjob(elastic_job(annotations={
        LAST_STEP_ANNOTATION: "42"}))
    drive(cluster, mgr, sched, 12)    # grow executed
    job = cluster.vcjobs["default/etrain"]
    pg = cluster.podgroups["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    assert pg.annotations[RESUME_STEP_ANNOTATION] == "42"
    pod = next(p for p in cluster.pods.values() if p.owner == job.uid)
    assert pod.containers[0].env["VTP_RESUME_STEP"] == "42"

    # a stale last-checkpoint-step must not rewind the stamp
    pg.annotations[LAST_STEP_ANNOTATION] = "7"
    job.annotations[LAST_STEP_ANNOTATION] = "7"
    cluster.add_vcjob(fixed_job())    # forces a shrink
    drive(cluster, mgr, sched, 14)
    pg = cluster.podgroups["default/etrain"]
    assert eapi.current_slices(pg) == 1
    assert int(pg.annotations[RESUME_STEP_ANNOTATION]) >= 42


def test_slice_failure_mid_resize_single_drain_no_step_regress():
    """The race satellite: a slice dies while an elastic shrink is
    draining the same gang.  The failover controller must ADOPT the
    in-flight drain (no second RestartJob) and neither controller may
    regress the resume step; the gang ends RUNNING off the
    quarantined slice at its decided size."""
    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16"),
                                 ("sc", "v5e-16"), ("sd", "v5e-16")])
    cluster.add_vcjob(elastic_job(slices=2, lo=1, hi=2, annotations={
        LAST_STEP_ANNOTATION: "100"}))
    drive(cluster, mgr, sched, 4)
    job = cluster.vcjobs["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    homes = slices_of(cluster, job)
    assert len(homes) == 2

    # three fixed gangs over the two idle slices force a shrink
    # decision; the controller executes it — the job enters
    # RESTARTING.  The fixed gangs are finite (run_ticks) so the
    # post-quarantine cluster has room for everyone again.
    cluster.add_vcjob(fixed_job("f1", run_ticks=6))
    cluster.add_vcjob(fixed_job("f2", run_ticks=6))
    cluster.add_vcjob(fixed_job("f3", run_ticks=6))
    drive(cluster, mgr, sched, 2)
    job = cluster.vcjobs["default/etrain"]
    v_after_decision = job.version
    gen = job.annotations.get(eapi.ELASTIC_GENERATION_ANNOTATION)
    assert gen == "1"                  # shrink executed

    # now one of its (old) slices dies mid-drain; drive until the
    # gang is RUNNING again and assert the invariants AT recovery
    # (later cycles may legitimately re-grow it once the finite
    # fixed gangs complete)
    fail_host(cluster, f"{homes[0]}-w0")
    for _ in range(30):
        drive(cluster, mgr, sched, 1)
        job = cluster.vcjobs["default/etrain"]
        if job.phase is JobPhase.RUNNING:
            break
    pg = cluster.podgroups["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    # exactly one drain tore the gang down: the failover controller
    # adopted the elastic restart instead of issuing its own
    assert job.version - v_after_decision <= 1
    assert int(pg.annotations[RESUME_STEP_ANNOTATION]) >= 100
    assert eapi.current_slices(pg) == 1
    # and the survivor landed off the quarantined slice
    assert homes[0] not in slices_of(cluster, job)
    assert all(
        NODE_QUARANTINED_UNTIL_ANNOTATION in n.annotations
        for n in cluster.nodes.values()
        if n.labels[TPU_SLICE_LABEL] == homes[0])


def test_failover_requeued_defers_elastic_resize():
    """While a failover episode owns the gang (REQUEUED set), a
    stamped resize decision must wait — the controller defers instead
    of double-draining."""
    from volcano_tpu.controllers.elastic import ElasticController

    cluster, _, _ = plane([("sa", "v5e-16")])
    cluster.add_vcjob(elastic_job())
    mgr = ControllerManager(cluster, enabled=["job", "podgroup",
                                              "queue"])
    sched = Scheduler(cluster, conf=ELASTIC_CONF, schedule_period=0)
    drive(cluster, mgr, sched, 4)
    job = cluster.vcjobs["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    pg = cluster.podgroups["default/etrain"]
    pg.annotations[REQUEUED_ANNOTATION] = "true"   # failover owns it
    pg.annotations[eapi.ELASTIC_DESIRED_SLICES_ANNOTATION] = "2"
    v0 = job.version
    ctrl = ElasticController()
    ctrl.initialize(cluster)
    ctrl.sync()
    assert cluster.vcjobs["default/etrain"].version == v0
    assert eapi.desired_slices(pg) == 2            # decision retained
    pg.annotations.pop(REQUEUED_ANNOTATION)
    ctrl.sync()
    assert eapi.current_slices(pg) == 2            # now executed
    mgr.stop()


def test_controller_restart_mid_resize_adopts_and_completes():
    """The durable `resizing` marker outlives the controller's
    in-memory episode: a FRESH controller process (restart mid-drain)
    must adopt the in-flight resize, complete it, clear the marker —
    and the decision loop must not stay frozen behind it."""
    from volcano_tpu.controllers.elastic import ElasticController

    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_vcjob(elastic_job())
    drive(cluster, mgr, sched, 2)
    # grow decided + executed; kill the manager BEFORE resume
    pg = cluster.podgroups["default/etrain"]
    for _ in range(10):
        drive(cluster, mgr, sched, 1)
        if eapi.ELASTIC_RESIZING_ANNOTATION in pg.annotations:
            break
    assert pg.annotations.get(eapi.ELASTIC_RESIZING_ANNOTATION) == \
        eapi.RESIZE_GROW
    mgr.stop()

    # a brand-new controller set (empty episode dict) takes over
    mgr2 = ControllerManager(cluster, enabled=[
        "job", "podgroup", "queue", "failover", "elastic"])
    drive(cluster, mgr2, sched, 12)
    job = cluster.vcjobs["default/etrain"]
    pg = cluster.podgroups["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    assert eapi.ELASTIC_RESIZING_ANNOTATION not in pg.annotations
    assert REQUEUED_ANNOTATION not in pg.annotations
    assert eapi.current_slices(pg) == 2
    # the adopted episode was observed (resize latency recorded)
    assert metrics.get_observations("elastic_resize_seconds",
                                    kind="grow")
    # and the guard is unfrozen: a later shrink decision still lands
    cluster.add_vcjob(fixed_job())
    drive(cluster, mgr2, sched, 14)
    assert cluster.vcjobs["default/fixed"].phase is JobPhase.RUNNING
    assert eapi.current_slices(
        cluster.podgroups["default/etrain"]) == 1
    mgr2.stop()


# -- migration ---------------------------------------------------------

def test_migration_drains_and_replaces_on_other_slices():
    """Policy-initiated live migration: same world size, different
    slices, one drain, MTTR observed, avoid marker cleared."""
    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16"),
                                 ("sc", "v5e-16")])
    cluster.add_vcjob(elastic_job(hi=1))   # pinned to 1 slice
    drive(cluster, mgr, sched, 4)
    job = cluster.vcjobs["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    old = slices_of(cluster, job)
    before = len(metrics.get_observations(
        "elastic_migration_mttr_seconds"))

    pg = cluster.podgroups["default/etrain"]
    pg.annotations[eapi.ELASTIC_DESIRED_SLICES_ANNOTATION] = "1"
    pg.annotations[eapi.ELASTIC_RESIZE_REASON_ANNOTATION] = \
        eapi.RESIZE_MIGRATE
    pg.annotations[eapi.ELASTIC_AVOID_SLICES_ANNOTATION] = old[0]
    drive(cluster, mgr, sched, 14)
    job = cluster.vcjobs["default/etrain"]
    pg = cluster.podgroups["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    new = slices_of(cluster, job)
    assert new and new != old
    assert eapi.current_slices(pg) == 1
    assert eapi.ELASTIC_AVOID_SLICES_ANNOTATION not in pg.annotations
    assert eapi.resize_history(pg)[-1]["kind"] == "migrate"
    assert len(metrics.get_observations(
        "elastic_migration_mttr_seconds")) > before


def test_stale_decision_expires_without_a_controller():
    """A desired-slices decision nobody executes (elastic controller
    down/disabled) must EXPIRE: the in-flight guard releases, the
    preempt veto drops, and the action may re-decide — the subsystem
    degrades to a no-op instead of freezing the fleet."""
    from volcano_tpu.actions.elastic import ElasticAction
    from volcano_tpu.api.podgroup import PodGroup

    pg = PodGroup(name="e", annotations={
        eapi.ELASTIC_MIN_SLICES_ANNOTATION: "1",
        eapi.ELASTIC_MAX_SLICES_ANNOTATION: "2",
        eapi.ELASTIC_SLICES_ANNOTATION: "2",
        eapi.ELASTIC_DESIRED_SLICES_ANNOTATION: "1",
        eapi.ELASTIC_DECIDED_TS_ANNOTATION: f"{time.time():.3f}"})
    now = time.time()
    assert ElasticAction._in_flight(pg, now)            # fresh: held
    assert not eapi.decision_stale(pg, now)
    stale_ts = now - eapi.STALE_DECISION_S - 1
    pg.annotations[eapi.ELASTIC_DECIDED_TS_ANNOTATION] = \
        f"{stale_ts:.3f}"
    assert eapi.decision_stale(pg, now)
    assert not ElasticAction._in_flight(pg, now)        # expired


def test_resize_preserves_partial_gang_min_available():
    """A job that declared minAvailable < replicas (partial gang) must
    keep that RATIO across resizes — a resize changes the size, never
    the readiness policy."""
    from volcano_tpu.controllers.elastic import ElasticController

    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16")])
    job = elastic_job(slices=2, lo=1, hi=2)
    job.min_available = 6                   # 6 of 8 suffice
    job.tasks[0].min_available = 6
    cluster.add_vcjob(job)
    drive(cluster, mgr, sched, 4)
    assert cluster.vcjobs["default/etrain"].phase is JobPhase.RUNNING

    # real pending demand forces the shrink AND keeps the freed slice
    # occupied (otherwise the zero-cooldown action would re-grow)
    cluster.add_vcjob(fixed_job())
    drive(cluster, mgr, sched, 14)
    job = cluster.vcjobs["default/etrain"]
    assert cluster.vcjobs["default/fixed"].phase is JobPhase.RUNNING
    assert job.phase is JobPhase.RUNNING
    assert job.tasks[0].replicas == 4
    assert job.tasks[0].min_available == 3  # ceil(6 * 1/2)
    assert job.min_available == 3
    assert cluster.podgroups["default/etrain"].min_member == 3
    mgr.stop()


def test_migration_with_no_destination_yields_instead_of_starving():
    """A migration stamped against a full cluster has nowhere to go:
    after MIGRATE_YIELD_ROUNDS drained-but-unplaced sync rounds the
    avoid-slices preference must yield so the gang lands back on its
    old slices — steering is a preference, starving is not."""
    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_vcjob(elastic_job(hi=1))
    cluster.add_vcjob(fixed_job())       # fills the other slice
    drive(cluster, mgr, sched, 4)
    job = cluster.vcjobs["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    old = slices_of(cluster, job)
    pg = cluster.podgroups["default/etrain"]
    pg.annotations[eapi.ELASTIC_DESIRED_SLICES_ANNOTATION] = "1"
    pg.annotations[eapi.ELASTIC_RESIZE_REASON_ANNOTATION] = \
        eapi.RESIZE_MIGRATE
    pg.annotations[eapi.ELASTIC_AVOID_SLICES_ANNOTATION] = old[0]
    drive(cluster, mgr, sched, 40)
    job = cluster.vcjobs["default/etrain"]
    assert job.phase is JobPhase.RUNNING
    assert slices_of(cluster, job) == old    # landed back home
    assert any(r == "ElasticMigrationYielded"
               for _, r, _ in cluster.events)
    pg = cluster.podgroups["default/etrain"]
    assert eapi.ELASTIC_AVOID_SLICES_ANNOTATION not in pg.annotations
    assert eapi.ELASTIC_RESIZING_ANNOTATION not in pg.annotations


# -- why-pending: the bounded reason -----------------------------------

def test_elastic_waiting_reason_is_bounded_and_published():
    assert "elastic-waiting-for-capacity" in trace.REASON_ENUM
    assert trace.normalize_reason(
        "elastic: waiting for capacity — 0 idle slice(s) for a min "
        "2-slice gang") == "elastic-waiting-for-capacity"

    cluster, mgr, sched = plane([("sa", "v5e-16")])
    cluster.add_vcjob(fixed_job())          # fills the only slice
    drive(cluster, mgr, sched, 2)
    cluster.add_vcjob(elastic_job())        # floor cannot fit
    drive(cluster, mgr, sched, 3)
    pg = cluster.podgroups["default/etrain"]
    doc = trace.parse_annotation(
        pg.annotations.get(trace.PENDING_REASONS_ANNOTATION, ""))
    assert doc and "elastic-waiting-for-capacity" in doc["reasons"]
    assert "waiting for capacity" in \
        doc["detail"]["elastic-waiting-for-capacity"]


def test_vtpctl_explain_and_elastic_views(tmp_path, capsys):
    from volcano_tpu.cli.vtpctl import main as vtpctl

    cluster, mgr, sched = plane([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.add_vcjob(fixed_job("fa"))
    cluster.add_vcjob(fixed_job("fb"))
    drive(cluster, mgr, sched, 2)
    cluster.add_vcjob(elastic_job())        # parked at the floor
    drive(cluster, mgr, sched, 3)
    mgr.stop()
    path = str(tmp_path / "c.pkl")
    with open(path, "wb") as f:
        pickle.dump(cluster, f)

    assert vtpctl(["--state", path, "explain", "etrain"]) == 0
    out = capsys.readouterr().out
    assert "elastic-waiting-for-capacity" in out

    assert vtpctl(["--state", path, "elastic"]) == 0
    out = capsys.readouterr().out
    row = next(l for l in out.splitlines()
               if l.startswith("default/etrain"))
    assert "1" in row                        # current/min at the floor

    # --migrate stamps the decision + avoid list
    assert vtpctl(["--state", path, "elastic",
                   "--migrate", "default/etrain"]) == 0
    with open(path, "rb") as f:
        back = pickle.load(f)
    pg = back.podgroups["default/etrain"]
    assert eapi.desired_slices(pg) == eapi.current_slices(pg)
    assert pg.annotations[eapi.ELASTIC_RESIZE_REASON_ANNOTATION] == \
        eapi.RESIZE_MIGRATE


# metric-label cardinality: the per-family copy of this test moved to
# tests/test_lint.py::test_live_exposition_honours_label_schema — one
# linter-driven check over the WHOLE exposition against
# bundle.FAMILY_LABELS (the elastic_* kind enum included).


# -- workload: dp-dimension resize is loss-continuous ------------------

def test_dryrun_dp_resize_loss_continuity(tmp_path):
    """The acceptance dryrun: train at world size 8 (dp=2) with a
    fixed GLOBAL batch, checkpoint, 'resize' to world size 4 (dp=1 —
    half the devices, the dp dimension shrunk) and resume from the
    stamped env.  The resume step never rewinds and the post-resize
    losses match the uninterrupted fixed-size run within tolerance —
    the same trajectory, computed by fewer chips."""
    import jax

    from volcano_tpu.workloads import checkpoint, model as model_lib, \
        train
    from volcano_tpu.workloads.mesh import make_mesh

    devices = jax.devices()
    assert len(devices) >= 8
    mesh_big = make_mesh({"dp": 2, "fsdp": 2, "tp": 2, "sp": 1},
                         devices[:8])
    mesh_small = make_mesh({"dp": 1, "fsdp": 2, "tp": 2, "sp": 1},
                           devices[:4])
    cfg = model_lib.tiny_config()
    opt = train.make_optimizer(lr=1e-2, warmup_steps=1)
    params, state, _ = train.init_sharded(jax.random.key(0), cfg,
                                          mesh_big, opt)
    step_big = train.make_train_step(cfg, mesh_big, opt)
    # GLOBAL batch fixed at 4 sequences: world size changes, the
    # data seen per step does not — that is what makes the resize
    # loss-continuous (worker.py: WORKER_GLOBAL_BATCH)
    batch_big = train.synthetic_batch(jax.random.key(1), cfg, 4, 64,
                                      mesh_big)
    ckpt = str(tmp_path / "ckpt")
    losses = {}
    for step in range(1, 6):
        params, state, m = step_big(params, state, batch_big)
        losses[step] = float(m["loss"])
        if step == 3:
            checkpoint.save(ckpt, step=step, params=params,
                            opt_state=state)

    # the controller shrinks the gang: a fresh worker boots at HALF
    # the world size with the env the elastic drain stamped
    env = {"VTP_CHECKPOINT_DIR": ckpt, "VTP_RESUME_STEP": "3"}
    p2, s2, _ = train.init_sharded(jax.random.key(99), cfg,
                                   mesh_small, opt)
    p2, s2, start = checkpoint.resume_state(p2, s2, environ=env)
    assert start == 3                      # never rewinds
    step_small = train.make_train_step(cfg, mesh_small, opt)
    batch_small = train.synthetic_batch(jax.random.key(1), cfg, 4, 64,
                                        mesh_small)
    resumed = {}
    for step in range(start + 1, 6):
        p2, s2, m = step_small(p2, s2, batch_small)
        resumed[step] = float(m["loss"])
    for step in (4, 5):
        assert resumed[step] == pytest.approx(losses[step],
                                              rel=1e-3, abs=1e-4), \
            (step, resumed[step], losses[step])
    # and the continuity assert is not vacuous: the resumed losses
    # are NOT the from-scratch steps 1..2
    assert resumed[4] != pytest.approx(losses[1], rel=1e-3)


# -- tier-1 smoke: one grow + one shrink through real processes --------

def test_bench_elastic_smoke_mode():
    """`bench.py --elastic-smoke` runs one grow and one shrink
    through the REAL process control plane (state server + scheduler
    + controllers as OS processes), mirroring --wire-smoke — the
    elastic loop guarded on every commit."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--elastic-smoke"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert proc.returncode == 0, \
        proc.stdout[-2000:] + proc.stderr[-2000:]
    line = next(l for l in reversed(proc.stdout.strip().splitlines())
                if l.startswith("{"))
    out = json.loads(line)
    assert out["ok"] is True, out
    assert out["grow_ok"] and out["shrink_ok"]
    assert out["utilization"] > 0
