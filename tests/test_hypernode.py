"""HyperNode tree / LCA / ICI distance (reference: hyper_node_info_test.go)."""

from volcano_tpu.api.hypernode import VIRTUAL_ROOT, HyperNode, HyperNodesInfo


def build_two_pod_topology():
    """2 DCN pods, each with 2 ICI slices of 2 hosts:

        tier2: pod0 (slice00 slice01)   pod1 (slice10 slice11)
        tier1: slice00={n0,n1} slice01={n2,n3} slice10={n4,n5} slice11={n6,n7}
    """
    nodes = [f"n{i}" for i in range(8)]
    hns = [
        HyperNode.of_nodes("slice00", 1, ["n0", "n1"]),
        HyperNode.of_nodes("slice01", 1, ["n2", "n3"]),
        HyperNode.of_nodes("slice10", 1, ["n4", "n5"]),
        HyperNode.of_nodes("slice11", 1, ["n6", "n7"]),
        HyperNode.of_children("pod0", 2, ["slice00", "slice01"]),
        HyperNode.of_children("pod1", 2, ["slice10", "slice11"]),
    ]
    return HyperNodesInfo(hns, nodes), nodes


def test_tree_structure_and_real_nodes():
    info, nodes = build_two_pod_topology()
    assert info.tiers == [1, 2]
    assert info.real_nodes("pod0") == {"n0", "n1", "n2", "n3"}
    assert info.real_nodes("slice11") == {"n6", "n7"}
    assert info.real_nodes(VIRTUAL_ROOT) == set(nodes)
    assert info.members["slice00"].parent == "pod0"
    assert info.members["pod1"].parent == VIRTUAL_ROOT


def test_lca():
    info, _ = build_two_pod_topology()
    assert info.lca("slice00", "slice01") == "pod0"
    assert info.lca("slice00", "slice11") == VIRTUAL_ROOT
    assert info.lca("slice10", "pod1") == "pod1"


def test_ici_distance_between_nodes():
    info, _ = build_two_pod_topology()
    # same slice: tier 1 (full ICI bandwidth)
    assert info.lca_tier_of_nodes("n0", "n1") == 1
    # same pod, different slice: tier 2 (DCN within pod)
    assert info.lca_tier_of_nodes("n0", "n2") == 2
    # different pods: virtual root tier (3)
    assert info.lca_tier_of_nodes("n0", "n6") == 3


def test_hypernodes_covering():
    info, _ = build_two_pod_topology()
    cover = info.hypernodes_covering({"n0", "n1"})
    assert cover[0] == "slice00"          # tightest first
    assert "pod0" in cover
    assert info.hypernodes_covering({"n0", "n4"}) == []  # only root covers


def test_regex_members_and_uncovered_nodes():
    hns = [HyperNode(name="sl", tier=1,
                     members=[__import__("volcano_tpu.api.hypernode",
                                         fromlist=["HyperNodeMember"])
                              .HyperNodeMember(kind="Node", regex=r"n[01]")])]
    info = HyperNodesInfo(hns, ["n0", "n1", "stray"])
    assert info.real_nodes("sl") == {"n0", "n1"}
    assert info.leaf_of_node("stray") is None
    assert "stray" in info.real_nodes(VIRTUAL_ROOT)


# -- fabric-inventory discovery (UFM analogue, discovery/ufm/ufm.go) ---

def _fabric_records():
    return [
        # slice-a: h0-h1-h2 chained ici links, consistent fabric name
        {"kind": "ici", "a": "h0", "b": "h1", "fabric": "slice-a"},
        {"kind": "ici", "a": "h1", "b": "h2", "fabric": "slice-a"},
        # slice with conflicting fabric names -> named by smallest host
        {"kind": "ici", "a": "h3", "b": "h4", "fabric": "x"},
        {"kind": "ici", "a": "h4", "b": "h5", "fabric": "y"},
        # dcn attachments: slice-a majority pod-1, other slice pod-2
        {"kind": "dcn", "host": "h0", "pod": "pod-1"},
        {"kind": "dcn", "host": "h1", "pod": "pod-1"},
        {"kind": "dcn", "host": "h2", "pod": "pod-2"},
        {"kind": "dcn", "host": "h3", "pod": "pod-2"},
        # malformed records are skipped
        {"kind": "ici", "a": "h9"},
        "not-a-dict",
    ]


def test_fabric_discoverer_builds_components():
    from volcano_tpu.controllers.hypernode import FabricDiscoverer
    hns = {hn.name: hn for hn in FabricDiscoverer.build(_fabric_records())}
    a = hns["slice-a"]
    assert a.tier == 1
    assert sorted(m.exact for m in a.members) == ["h0", "h1", "h2"]
    b = hns["fabric-h3"]          # conflicting names -> smallest host
    assert sorted(m.exact for m in b.members) == ["h3", "h4", "h5"]
    p1, p2 = hns["pod-1"], hns["pod-2"]
    assert p1.tier == p2.tier == 2
    assert [m.exact for m in p1.members] == ["slice-a"]
    assert [m.exact for m in p2.members] == ["fabric-h3"]


def test_fabric_discoverer_live_endpoint_and_reconcile():
    import http.server
    import json as _json
    import threading

    class FabricAPI(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path != "/fabric/v1/links":
                self.send_response(404); self.end_headers(); return
            assert self.headers.get("Authorization") == "Bearer s3cret"
            body = _json.dumps(_fabric_records()).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FabricAPI)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        from volcano_tpu.cache.fake_cluster import FakeCluster
        from volcano_tpu.controllers.hypernode import (
            HyperNodeController, make_discoverer,
        )
        disc = make_discoverer(
            f"fabric:http://127.0.0.1:{server.server_port}#s3cret")
        cluster = FakeCluster()
        ctrl = HyperNodeController(discoverer=disc)
        ctrl.initialize(cluster)
        ctrl.sync()
        names = {hn.name for hn in cluster.list_all().hypernodes}
        assert {"slice-a", "fabric-h3", "pod-1", "pod-2"} <= names
    finally:
        server.shutdown()


def test_fabric_discoverer_degrades_without_gc():
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.api.hypernode import HyperNode
    from volcano_tpu.controllers.hypernode import (
        FabricDiscoverer, HyperNodeController,
    )
    cluster = FakeCluster()
    cluster.add_hypernode(HyperNode.of_nodes("slice-z", 1, ["h9"],
                                             tier_name="ici-slice"))
    # endpoint that never answers: sync must NOT GC the existing tree
    ctrl = HyperNodeController(
        discoverer=FabricDiscoverer("http://127.0.0.1:1", timeout_s=0.2))
    ctrl.initialize(cluster)
    try:
        ctrl.sync()
    except RuntimeError:
        pass                       # expected: no data yet
    assert [hn.name for hn in cluster.list_all().hypernodes] == ["slice-z"]


def test_fabric_duplicate_names_stay_distinct():
    from volcano_tpu.controllers.hypernode import FabricDiscoverer
    hns = FabricDiscoverer.build([
        {"kind": "ici", "a": "h0", "b": "h1", "fabric": "f"},
        {"kind": "ici", "a": "h2", "b": "h3", "fabric": "f"},
        {"kind": "dcn", "host": "h0", "pod": "f"},   # pod collides too
    ])
    names = [hn.name for hn in hns]
    assert len(names) == len(set(names)), names
    hosts = sorted(m.exact for hn in hns if hn.tier == 1
                   for m in hn.members)
    assert hosts == ["h0", "h1", "h2", "h3"]


def test_make_discoverer_rejects_empty_endpoint():
    import pytest
    from volcano_tpu.controllers.hypernode import make_discoverer
    with pytest.raises(ValueError):
        make_discoverer("fabric:")
    with pytest.raises(ValueError):
        make_discoverer("fabric:#tok")
