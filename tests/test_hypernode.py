"""HyperNode tree / LCA / ICI distance (reference: hyper_node_info_test.go)."""

from volcano_tpu.api.hypernode import VIRTUAL_ROOT, HyperNode, HyperNodesInfo


def build_two_pod_topology():
    """2 DCN pods, each with 2 ICI slices of 2 hosts:

        tier2: pod0 (slice00 slice01)   pod1 (slice10 slice11)
        tier1: slice00={n0,n1} slice01={n2,n3} slice10={n4,n5} slice11={n6,n7}
    """
    nodes = [f"n{i}" for i in range(8)]
    hns = [
        HyperNode.of_nodes("slice00", 1, ["n0", "n1"]),
        HyperNode.of_nodes("slice01", 1, ["n2", "n3"]),
        HyperNode.of_nodes("slice10", 1, ["n4", "n5"]),
        HyperNode.of_nodes("slice11", 1, ["n6", "n7"]),
        HyperNode.of_children("pod0", 2, ["slice00", "slice01"]),
        HyperNode.of_children("pod1", 2, ["slice10", "slice11"]),
    ]
    return HyperNodesInfo(hns, nodes), nodes


def test_tree_structure_and_real_nodes():
    info, nodes = build_two_pod_topology()
    assert info.tiers == [1, 2]
    assert info.real_nodes("pod0") == {"n0", "n1", "n2", "n3"}
    assert info.real_nodes("slice11") == {"n6", "n7"}
    assert info.real_nodes(VIRTUAL_ROOT) == set(nodes)
    assert info.members["slice00"].parent == "pod0"
    assert info.members["pod1"].parent == VIRTUAL_ROOT


def test_lca():
    info, _ = build_two_pod_topology()
    assert info.lca("slice00", "slice01") == "pod0"
    assert info.lca("slice00", "slice11") == VIRTUAL_ROOT
    assert info.lca("slice10", "pod1") == "pod1"


def test_ici_distance_between_nodes():
    info, _ = build_two_pod_topology()
    # same slice: tier 1 (full ICI bandwidth)
    assert info.lca_tier_of_nodes("n0", "n1") == 1
    # same pod, different slice: tier 2 (DCN within pod)
    assert info.lca_tier_of_nodes("n0", "n2") == 2
    # different pods: virtual root tier (3)
    assert info.lca_tier_of_nodes("n0", "n6") == 3


def test_hypernodes_covering():
    info, _ = build_two_pod_topology()
    cover = info.hypernodes_covering({"n0", "n1"})
    assert cover[0] == "slice00"          # tightest first
    assert "pod0" in cover
    assert info.hypernodes_covering({"n0", "n4"}) == []  # only root covers


def test_regex_members_and_uncovered_nodes():
    hns = [HyperNode(name="sl", tier=1,
                     members=[__import__("volcano_tpu.api.hypernode",
                                         fromlist=["HyperNodeMember"])
                              .HyperNodeMember(kind="Node", regex=r"n[01]")])]
    info = HyperNodesInfo(hns, ["n0", "n1", "stray"])
    assert info.real_nodes("sl") == {"n0", "n1"}
    assert info.leaf_of_node("stray") is None
    assert "stray" in info.real_nodes(VIRTUAL_ROOT)
