"""End-to-end slice: gang scheduling through enqueue+allocate+backfill.

Mirrors the reference's allocate_test.go / uthelper-driven action tests.
"""

from volcano_tpu.api.node_info import Node
from volcano_tpu.api.pod import Taint, make_pod
from volcano_tpu.api.queue import Queue
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import PodGroupPhase
from volcano_tpu.uthelper import TestContext, gang_job


def nodes(n, cpu="8", tpu=0, prefix="n"):
    alloc = {"cpu": cpu, "pods": 110}
    if tpu:
        alloc[TPU] = tpu
    return [Node(name=f"{prefix}{i}", allocatable=alloc) for i in range(n)]


def test_gang_job_schedules_when_it_fits():
    """3-task vcjob with minAvailable=3 gang-schedules onto fake nodes
    (BASELINE.json config #1)."""
    pg, pods = gang_job("job1", replicas=3, requests={"cpu": 1})
    ctx = TestContext(nodes=nodes(3), podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(3)
    ctx.expect_podgroup_phase("default/job1", PodGroupPhase.RUNNING)


def test_gang_all_or_nothing():
    """minAvailable=3 but cluster only fits 2 -> nothing binds."""
    pg, pods = gang_job("job1", replicas=3, requests={"cpu": 6})
    ctx = TestContext(nodes=nodes(2), podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(0)
    pg2 = ctx.cluster.podgroups["default/job1"]
    assert any(c.type == "Unschedulable" for c in pg2.conditions) or \
        pg2.phase is PodGroupPhase.PENDING


def test_partial_gang_min_available_subset():
    """replicas=4, minAvailable=2, room for 2 -> 2 bind."""
    pg, pods = gang_job("job1", replicas=4, min_available=2,
                        requests={"cpu": 6})
    ctx = TestContext(nodes=nodes(2), podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(2)


def test_tpu_resource_dimension_gates_fit():
    """Tasks requesting google.com/tpu only fit TPU nodes."""
    pg, pods = gang_job("tpujob", replicas=2,
                        requests={"cpu": 1, TPU: 4})
    cluster_nodes = nodes(2, tpu=4, prefix="tpu") + nodes(2, prefix="cpu")
    ctx = TestContext(nodes=cluster_nodes, podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(2)
    for _, node_name in ctx.cluster.binds:
        assert node_name.startswith("tpu")


def test_enqueue_gates_oversized_jobs():
    """A job whose declared minResources exceed cluster capacity never
    leaves Pending (jobs without minResources always admit, matching
    the reference's 'MinResources == nil => Permit')."""
    from volcano_tpu.api.resource import Resource
    pg, pods = gang_job("big", replicas=4, requests={"cpu": 100})
    pg.min_resources = Resource({"cpu": 400_000})
    ctx = TestContext(nodes=nodes(2), podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(0)
    ctx.expect_podgroup_phase("default/big", PodGroupPhase.PENDING)


def test_taints_respected():
    tainted = Node(name="bad", allocatable={"cpu": 8},
                   taints=[Taint(key="dedicated", value="x",
                                 effect="NoSchedule")])
    ok = Node(name="good", allocatable={"cpu": 8})
    pg, pods = gang_job("j", replicas=1, requests={"cpu": 1})
    ctx = TestContext(nodes=[tainted, ok], podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind("default/j-0", "good")


def test_node_selector_respected():
    n0 = Node(name="n0", allocatable={"cpu": 8}, labels={"zone": "a"})
    n1 = Node(name="n1", allocatable={"cpu": 8}, labels={"zone": "b"})
    pg, pods = gang_job("j", replicas=1, requests={"cpu": 1})
    pods[0].node_selector = {"zone": "b"}
    ctx = TestContext(nodes=[n0, n1], podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind("default/j-0", "n1")


def test_backfill_binds_best_effort_pods():
    pg, pods = gang_job("be", replicas=2, requests={})
    ctx = TestContext(nodes=nodes(1), podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(2)


def test_priority_order_between_jobs():
    """Higher-priority job wins the scarce node."""
    from volcano_tpu.cache.cluster import PriorityClass
    pg_hi, pods_hi = gang_job("hi", replicas=1, requests={"cpu": 6},
                              priority_class="high")
    pg_lo, pods_lo = gang_job("lo", replicas=1, requests={"cpu": 6})
    ctx = TestContext(
        nodes=nodes(1), podgroups=[pg_lo, pg_hi], pods=pods_lo + pods_hi,
        priority_classes=[PriorityClass(name="high", value=1000)])
    ctx.run()
    ctx.expect_bind("default/hi-0")
    assert "default/lo-0" not in ctx.bind_map


def test_two_queue_weighted_share():
    """2-queue proportional share: heavier queue fits its whole job,
    both queues make progress (BASELINE.json config #4 precursor)."""
    q_a = Queue(name="qa", weight=3)
    q_b = Queue(name="qb", weight=1)
    pg_a, pods_a = gang_job("ja", queue="qa", replicas=3,
                            min_available=1, requests={"cpu": 2})
    pg_b, pods_b = gang_job("jb", queue="qb", replicas=3,
                            min_available=1, requests={"cpu": 2})
    ctx = TestContext(nodes=nodes(1, cpu="8"), queues=[q_a, q_b],
                      podgroups=[pg_a, pg_b], pods=pods_a + pods_b)
    ctx.run()
    binds = ctx.bind_map
    a_bound = sum(1 for k in binds if k.startswith("default/ja"))
    b_bound = sum(1 for k in binds if k.startswith("default/jb"))
    assert a_bound == 3          # deserved 6 cpu -> all 3 tasks
    assert b_bound == 1          # deserved 2 cpu -> 1 task


def test_multiple_cycles_converge():
    """Second cycle sees Bound pods as occupying and schedules the rest."""
    pg1, pods1 = gang_job("j1", replicas=2, requests={"cpu": 4})
    pg2, pods2 = gang_job("j2", replicas=2, requests={"cpu": 4})
    ctx = TestContext(nodes=nodes(2), podgroups=[pg1, pg2],
                      pods=pods1 + pods2)
    ctx.run()
    first = len(ctx.cluster.binds)
    ctx.cluster.tick()  # Bound -> Running
    ctx.run()
    assert len(ctx.cluster.binds) == 4
    assert first == 4 or first == 2
