"""Volumebinding parity: dynamic provisioning + passive assume cache
(VERDICT r1 item 10; reference capabilities/volumebinding/{binder,
passive_assume_cache}.go).
"""

from volcano_tpu.api.node_info import Node
from volcano_tpu.uthelper import TestContext, gang_job

CONF = {"actions": "enqueue, allocate",
        "tiers": [{"plugins": [{"name": "gang"}, {"name": "predicates"},
                               {"name": "volumebinding"}]}]}


def zone_node(name, zone):
    return Node(name=name, allocatable={"cpu": 8},
                labels={"topology.kubernetes.io/zone": zone})


def claiming_job(name, pvc):
    pg, pods = gang_job(name, replicas=1, requests={"cpu": 1})
    pods[0].annotations["volume.volcano-tpu.io/claims"] = pvc
    return pg, pods


def test_dynamic_provisioning_creates_pv_in_consumer_zone():
    """A storage-classed PVC with NO existing PV schedules anyway; at
    commit a volume is provisioned in the chosen node's zone
    (WaitForFirstConsumer)."""
    pg, pods = claiming_job("dyn", "pvc-dyn")
    ctx = TestContext(nodes=[zone_node("za", "z-a")],
                      podgroups=[pg], pods=pods, conf=CONF)
    ctx.cluster.put_object("pvc", {"request_gi": 20, "bound_pv": "",
                                   "storage_class": "standard"},
                           key="pvc-dyn")
    ctx.run()
    ctx.expect_bind("default/dyn-0", "za")
    pvc = ctx.cluster.pvcs["pvc-dyn"]
    assert pvc["bound_pv"], "dynamic PV not bound"
    pv = ctx.cluster.pvs[pvc["bound_pv"]]
    assert pv["provisioned"] and pv["zone"] == "z-a"
    assert pv["capacity_gi"] == 20
    assert pv["claimed_by"] == "pvc-dyn"


def test_no_storage_class_no_pv_stays_pending():
    pg, pods = claiming_job("stuck", "pvc-none")
    ctx = TestContext(nodes=[zone_node("za", "z-a")],
                      podgroups=[pg], pods=pods, conf=CONF)
    ctx.cluster.put_object("pvc", {"request_gi": 20, "bound_pv": ""},
                           key="pvc-none")
    ctx.run()
    ctx.expect_bind_num(0)


def test_passive_assume_cache_sees_external_bind_mid_session():
    """A PV bound by ANOTHER scheduler mid-session (observed through
    the cluster watch) must not be double-assumed by this session's
    predicate."""
    pg, pods = claiming_job("ours", "pvc-a")
    ctx = TestContext(nodes=[zone_node("za", "z-a")],
                      podgroups=[pg], pods=pods, conf=CONF)
    ctx.cluster.put_object("pv", {"capacity_gi": 50, "zone": "z-a",
                                  "claimed_by": ""}, key="pv-1")
    ctx.cluster.put_object("pvc", {"request_gi": 10, "bound_pv": ""},
                           key="pvc-a")

    from volcano_tpu.framework.framework import close_session, open_session
    ssn = open_session(ctx.cache, ctx.conf)
    try:
        plugin = ssn.plugins["volumebinding"]
        # an agent scheduler claims pv-1 for a DIFFERENT pvc while our
        # session is open: the event arrives over the watch
        ctx.cluster.put_object("pv", {"capacity_gi": 50, "zone": "z-a",
                                      "claimed_by": "pvc-other"},
                               key="pv-1")
        assert plugin.assumed.get("pv-1") == "pvc-other"
        task = next(iter(next(iter(ssn.jobs.values())).tasks.values()))
        node = ssn.nodes["za"]
        status = ssn.predicate(task, node)
        assert status is not None, \
            "externally-bound PV was double-assumed"
    finally:
        close_session(ssn)
    # and the passive watcher is detached after close
    assert plugin._passive_observe not in ctx.cluster._watchers


def test_two_claimants_one_pv_second_cycle_provisions_nothing():
    """Active assume-cache: two pods claiming distinct PVCs but only
    one matching PV — exactly one binds; the other stays pending (no
    phantom provisioning without a storage class)."""
    pg1, pods1 = claiming_job("j1", "pvc-1")
    pg2, pods2 = claiming_job("j2", "pvc-2")
    ctx = TestContext(nodes=[zone_node("za", "z-a")],
                      podgroups=[pg1, pg2], pods=pods1 + pods2,
                      conf=CONF)
    ctx.cluster.put_object("pv", {"capacity_gi": 50, "zone": "z-a",
                                  "claimed_by": ""}, key="pv-1")
    ctx.cluster.put_object("pvc", {"request_gi": 10, "bound_pv": ""},
                           key="pvc-1")
    ctx.cluster.put_object("pvc", {"request_gi": 10, "bound_pv": ""},
                           key="pvc-2")
    ctx.run()
    ctx.expect_bind_num(1)
    bound = [p for p in ctx.cluster.pvcs.values() if p["bound_pv"]]
    assert len(bound) == 1


def test_multi_claim_pod_binds_two_pvs():
    """A pod claiming TWO unbound PVCs reserves two distinct PVs in one
    placement (regression: 3-tuple reservations were unpacked as
    2-tuples, crashing the allocate event handler)."""
    pg, pods = claiming_job("multi", "pvc-a,pvc-b")
    ctx = TestContext(nodes=[zone_node("za", "z-a")],
                      podgroups=[pg], pods=pods, conf=CONF)
    for pv in ("pv-1", "pv-2"):
        ctx.cluster.put_object("pv", {"capacity_gi": 50, "zone": "z-a",
                                      "claimed_by": ""}, key=pv)
    for pvc in ("pvc-a", "pvc-b"):
        ctx.cluster.put_object("pvc", {"request_gi": 10, "bound_pv": ""},
                               key=pvc)
    ctx.run()
    ctx.expect_bind("default/multi-0", "za")
    bound = {ctx.cluster.pvcs[p]["bound_pv"] for p in ("pvc-a", "pvc-b")}
    assert bound == {"pv-1", "pv-2"}
    assert ctx.cluster.pvs["pv-1"]["claimed_by"] in ("pvc-a", "pvc-b")


def test_commit_never_steals_externally_claimed_pv_and_rebinds():
    """A PV bound by another scheduler between reservation and commit
    is NOT stolen; the claim rebinds to another live in-zone PV (and a
    deleted PV is never resurrected as a phantom)."""
    from volcano_tpu.api.types import TaskStatus
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.plugins.volumebinding import VolumeBindingPlugin

    cluster = FakeCluster()
    cluster.put_object("pv", {"capacity_gi": 10, "zone": "z",
                              "claimed_by": "pvc-other"}, key="pv-1")
    cluster.put_object("pv", {"capacity_gi": 10, "zone": "z",
                              "claimed_by": ""}, key="pv-2")
    cluster.put_object("pvc", {"request_gi": 5, "bound_pv": ""},
                       key="pvc-a")
    plug = VolumeBindingPlugin()
    plug._init_state(cluster)

    class Tsk:
        uid = "t1"
        status = TaskStatus.BINDING

    class Job:
        tasks = {"x": Tsk()}

    class Ssn:
        jobs = {"j": Job()}

    plug._task_pvs = {"t1": [("pvc-a", "pv-1", "z")]}
    plug._commit(Ssn, cluster)
    assert cluster.pvs["pv-1"]["claimed_by"] == "pvc-other"
    assert cluster.pvs["pv-2"]["claimed_by"] == "pvc-a"
    assert cluster.pvcs["pvc-a"]["bound_pv"] == "pv-2"

    # deleted PV, no replacement, no storage class => claim left
    # unbound and the phantom PV is NOT recreated
    cluster.put_object("pvc", {"request_gi": 5, "bound_pv": ""},
                       key="pvc-b")
    plug._task_pvs = {"t1": [("pvc-b", "pv-gone", "z")]}
    plug._commit(Ssn, cluster)
    assert "pv-gone" not in cluster.pvs
    assert not cluster.pvcs["pvc-b"]["bound_pv"]


def test_task_topology_admission_validation():
    """Task-level networkTopology needs a subGroup and a sane tier."""
    import pytest

    from volcano_tpu.cli.manifest import job_from_manifest
    from volcano_tpu.webhooks.admission import (AdmissionError,
                                                validate_job)

    def mk(task_patch):
        task = {"name": "w",
                "template": {"spec": {"containers": [
                    {"name": "c",
                     "resources": {"requests": {"cpu": 1}}}]}}}
        task.update(task_patch)
        return job_from_manifest({
            "kind": "Job", "metadata": {"name": "x"},
            "spec": {"tasks": [task]}})

    with pytest.raises(AdmissionError, match="requires subGroup"):
        validate_job(mk({"networkTopology": {"mode": "hard"}}))
    with pytest.raises(AdmissionError, match="must be >= 1"):
        validate_job(mk({"subGroup": "g0",
                         "networkTopology": {"mode": "hard",
                                             "highestTierAllowed": 0}}))
    validate_job(mk({"subGroup": "g0",
                     "networkTopology": {"mode": "hard",
                                         "highestTierAllowed": 2}}))
