"""Wire codec round-trips for every CRD-analogue kind."""

import dataclasses

from volcano_tpu.api import codec
from volcano_tpu.api.hypernode import HyperNode, HyperNodeMember
from volcano_tpu.api.jobflow import Flow, FlowDependsOn, JobFlow, JobTemplate
from volcano_tpu.api.node_info import Node
from volcano_tpu.api.numatopology import Numatopology
from volcano_tpu.api.pod import Container, Pod, Taint, Toleration, make_pod
from volcano_tpu.api.podgroup import (NetworkTopologySpec, PodGroup,
                                      SubGroupPolicy)
from volcano_tpu.api.queue import Queue
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.shard import NodeShard
from volcano_tpu.api.types import (JobAction, JobEvent, JobPhase,
                                   NetworkTopologyMode, PodGroupPhase,
                                   TaskStatus)
from volcano_tpu.api.vcjob import (DependsOn, LifecyclePolicy, TaskSpec,
                                   VCJob)
from volcano_tpu.cache.cluster import PriorityClass
from volcano_tpu.controllers.cronjob import CronJob
from volcano_tpu.controllers.hyperjob import HyperJob, ReplicatedJob


def roundtrip(obj):
    return codec.loads(codec.dumps(obj))


def assert_same(a, b):
    assert type(a) is type(b)
    if dataclasses.is_dataclass(a):
        for f in dataclasses.fields(a):
            va, vb = getattr(a, f.name), getattr(b, f.name)
            assert va == vb or (
                dataclasses.is_dataclass(va) or isinstance(va, Resource)
            ), f"{type(a).__name__}.{f.name}: {va!r} != {vb!r}"


def test_pod_roundtrip():
    pod = make_pod(
        "w-0", requests={"cpu": "4", "memory": "8Gi", "google.com/tpu": 4},
        labels={"volcano-tpu.io/task-spec": "worker"},
        annotations={"scheduling.volcano-tpu.io/group-name": "pg1"},
        phase=TaskStatus.RUNNING, node_name="host-0", priority=10,
        tolerations=[Toleration(key="tpu", operator="Exists",
                                effect="NoSchedule")],
        affinity_node_terms=[{"zone": ["us-central2-b"]}],
    )
    pod.containers[0].ports = [8470, 8471]
    pod.scheduling_gates = ["queue-admission"]
    got = roundtrip(pod)
    assert got.key == pod.key
    assert got.phase is TaskStatus.RUNNING
    assert got.resource_requests().res == pod.resource_requests().res
    assert got.tolerations[0].tolerates(Taint(key="tpu"))
    assert got.affinity_node_terms == [{"zone": ["us-central2-b"]}]
    assert got.containers[0].ports == [8470, 8471]
    assert got.scheduling_gates == ["queue-admission"]


def test_node_queue_podgroup_roundtrip():
    node = Node(name="host-0",
                labels={"cloud.google.com/gke-tpu-topology": "4x4"},
                allocatable={"cpu": "96", "google.com/tpu": 4},
                taints=[Taint(key="dedicated", value="tpu")])
    got = roundtrip(node)
    assert got.name == "host-0" and got.taints[0].key == "dedicated"

    q = Queue(name="tenant-a", weight=4,
              capability=Resource({"cpu": 1000}),
              guarantee=Resource({"google.com/tpu": 16}),
              parent="root", priority=5)
    gq = roundtrip(q)
    assert gq.capability.res == {"cpu": 1000.0}
    assert gq.guarantee.res == {"google.com/tpu": 16.0}
    assert gq.parent == "root"

    pg = PodGroup(
        name="pg1", min_member=4,
        min_task_member={"worker": 4},
        min_resources=Resource({"google.com/tpu": 16}),
        network_topology=NetworkTopologySpec(
            mode=NetworkTopologyMode.HARD, highest_tier_allowed=0),
        sub_group_policies=[SubGroupPolicy(name="sg0", min_member=2)],
        phase=PodGroupPhase.INQUEUE)
    gpg = roundtrip(pg)
    assert gpg.min_task_member == {"worker": 4}
    assert gpg.network_topology.mode is NetworkTopologyMode.HARD
    assert gpg.sub_group_policies[0].min_member == 2
    assert gpg.phase is PodGroupPhase.INQUEUE


def test_vcjob_roundtrip():
    job = VCJob(
        name="train", min_available=8, queue="tenant-a",
        tasks=[TaskSpec(name="worker", replicas=8,
                        template=make_pod("tmpl", requests={"cpu": 1}),
                        policies=[LifecyclePolicy(
                            action=JobAction.RESTART_JOB,
                            event=JobEvent.POD_FAILED)],
                        depends_on=DependsOn(name=["ps"]))],
        plugins={"jax": [], "svc": []},
        phase=JobPhase.RUNNING)
    got = roundtrip(job)
    assert got.tasks[0].policies[0].action is JobAction.RESTART_JOB
    assert got.tasks[0].depends_on.name == ["ps"]
    assert got.tasks[0].template.containers[0].requests == {"cpu": 1}
    assert got.plugins == {"jax": [], "svc": []}
    assert got.phase is JobPhase.RUNNING


def test_hypernode_flow_misc_roundtrip():
    hn = HyperNode.of_nodes("slice-0", 0, ["host-0", "host-1"])
    assert roundtrip(hn).members[0].exact == "host-0"
    assert roundtrip(hn).members[0].matches("host-0")

    flow = JobFlow(name="f", flows=[
        Flow(name="train",
             depends_on=FlowDependsOn(targets=["prep"]))])
    gf = roundtrip(flow)
    assert gf.flows[0].depends_on.targets == ["prep"]

    tmpl = JobTemplate(name="t", job=VCJob(name="tj"))
    assert roundtrip(tmpl).job.name == "tj"

    assert roundtrip(PriorityClass(name="high", value=100)).value == 100
    assert roundtrip(NodeShard(name="s0")).name == "s0"
    topo = Numatopology(name="host-0")
    assert roundtrip(topo).name == "host-0"

    cron = CronJob(name="nightly", schedule="0 2 * * *",
                   job_template=VCJob(name="cj"))
    gc = roundtrip(cron)
    assert gc.schedule == "0 2 * * *" and gc.job_template.name == "cj"

    hj = HyperJob(name="hj", min_available=2, replicated_jobs=[
        ReplicatedJob(name="rj", replicas=2, template=VCJob(name="m"))])
    ghj = roundtrip(hj)
    assert ghj.replicated_jobs[0].template.name == "m"


def test_plain_containers_and_tag_collision():
    assert roundtrip({"a": [1, 2.5, None, "x"], "b": {"c": True}}) == \
        {"a": [1, 2.5, None, "x"], "b": {"c": True}}
    # a user dict whose key collides with a codec tag must survive
    evil = {"#T": "not-a-type", "ok": 1}
    assert roundtrip(evil) == evil
    # non-string keys are stringified (JSON object keys are strings)
    assert roundtrip({1: "a"}) == {"1": "a"}


def test_decode_tolerates_unknown_fields():
    data = codec.encode(Queue(name="q"))
    data["f"]["some_future_field"] = 42
    q = codec.decode(data)
    assert q.name == "q"


def test_default_fields_elided_from_wire_body():
    """The wire fast lane: fields still equal to their dataclass
    default are omitted (decode restores them from the default — the
    compat contract the codec already promises), so a default-shaped
    pod ships a handful of keys, not ~30."""
    pod = make_pod("w-0", requests={"cpu": 1})
    enc = codec.encode(pod)
    total = len(dataclasses.fields(pod))
    assert len(enc["f"]) < total / 2, sorted(enc["f"])
    # non-defaults always present; empty-container defaults elided
    assert "name" in enc["f"] and "containers" in enc["f"]
    assert "labels" not in enc["f"] and "annotations" not in enc["f"]
    got = roundtrip(pod)
    for f in dataclasses.fields(pod):
        va, vb = getattr(pod, f.name), getattr(got, f.name)
        assert va == vb or type(va) is type(vb), (f.name, va, vb)
    # setting a field away from its default puts it back on the wire
    pod.labels["team"] = "ml"
    assert "labels" in codec.encode(pod)["f"]
    assert roundtrip(pod).labels == {"team": "ml"}


def test_default_elision_is_type_exact():
    """bool-vs-int (True == 1) and other equal-but-differently-typed
    values must still encode: elision compares type first."""
    @codec.register_class
    @dataclasses.dataclass
    class Flaggy:
        flag: bool = False
        n: int = 0

    assert codec.encode(Flaggy())["f"] == {}
    sneaky = Flaggy(flag=0, n=False)        # == defaults, wrong types
    assert set(codec.encode(sneaky)["f"]) == {"flag", "n"}
    got = roundtrip(sneaky)
    assert got.flag == 0 and type(got.flag) is int
    assert got.n is False


def test_enum_and_scalar_default_elision():
    pod = make_pod("w-0", requests={"cpu": 1})
    # phase default (PENDING enum) elided; non-default enum encodes
    assert "phase" not in codec.encode(pod)["f"]
    pod.phase = TaskStatus.RUNNING
    assert "phase" in codec.encode(pod)["f"]
    assert roundtrip(pod).phase is TaskStatus.RUNNING
    # a pod left default decodes back with the default phase
    fresh = make_pod("w-1", requests={"cpu": 1})
    assert roundtrip(fresh).phase is fresh.phase
