"""TPU device + topology-aware gang scheduling end-to-end.

These are the BASELINE.json config #5 scenarios: network-topology-aware
gang on multi-host TPU slices.
"""

from volcano_tpu.api.hypernode import VIRTUAL_ROOT
from volcano_tpu.api.podgroup import NetworkTopologySpec, SubGroupPolicy
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (
    SUBGROUP_LABEL,
    NetworkTopologyMode,
    PodGroupPhase,
)
from volcano_tpu.cache.cache import SchedulerCache
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.uthelper import TestContext, gang_job


def tpu_ctx(slices, podgroups=(), pods=(), conf=None, **kwargs):
    cluster = make_tpu_cluster(slices, **kwargs)
    return TestContext(
        cluster=cluster, podgroups=podgroups, pods=pods,
        conf=conf or {
            "actions": "enqueue, allocate, backfill",
            "tiers": [
                {"plugins": [{"name": "priority"}, {"name": "gang"},
                             {"name": "conformance"}]},
                {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                             {"name": "predicates"}, {"name": "proportion"},
                             {"name": "nodeorder"}, {"name": "binpack"},
                             {"name": "deviceshare"},
                             {"name": "network-topology-aware"}]},
            ]})


def test_hypernode_discovery_builds_slice_tree():
    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    assert set(cluster.hypernodes) == {"sa", "sb", "dcn-0"}
    assert cluster.hypernodes["sa"].tier == 1
    assert cluster.hypernodes["dcn-0"].tier == 2
    assert len(cluster.hypernodes["sa"].members) == 4  # 4 hosts


def test_hard_topology_job_lands_in_one_slice():
    """8-host gang with hard tier-1 topology must not straddle slices."""
    pg, pods = gang_job(
        "train", replicas=4, requests={"cpu": 8, TPU: 4},
        network_topology=NetworkTopologySpec(
            mode=NetworkTopologyMode.HARD, highest_tier_allowed=1))
    ctx = tpu_ctx([("sa", "v5e-16"), ("sb", "v5e-16")],
                  podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(4)
    slices_used = {node.rsplit("-w", 1)[0] for _, node in ctx.cluster.binds}
    assert len(slices_used) == 1


def test_hard_topology_rejects_when_no_slice_fits():
    """5 whole-host tasks cannot fit a 4-host slice at tier 1."""
    pg, pods = gang_job(
        "train", replicas=5, requests={"cpu": 8, TPU: 4},
        network_topology=NetworkTopologySpec(
            mode=NetworkTopologyMode.HARD, highest_tier_allowed=1))
    ctx = tpu_ctx([("sa", "v5e-16"), ("sb", "v5e-16")],
                  podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(0)
    pg2 = ctx.cluster.podgroups["default/train"]
    assert any("hypernode domain" in c.message for c in pg2.conditions)


def test_hard_topology_tier2_spans_slices():
    """Same 5-host job at highestTierAllowed=2 may span slices over DCN."""
    pg, pods = gang_job(
        "train", replicas=5, requests={"cpu": 8, TPU: 4},
        network_topology=NetworkTopologySpec(
            mode=NetworkTopologyMode.HARD, highest_tier_allowed=2))
    ctx = tpu_ctx([("sa", "v5e-16"), ("sb", "v5e-16")],
                  podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(5)


def test_multi_slice_job_subgroups_get_own_slices():
    """Two subgroups (DP replicas), each an ICI-local gang of 4 hosts ->
    each subgroup fills its own slice."""
    subgroups = [
        SubGroupPolicy(name="rep0", min_member=4,
                       network_topology=NetworkTopologySpec(
                           NetworkTopologyMode.HARD, 1)),
        SubGroupPolicy(name="rep1", min_member=4,
                       network_topology=NetworkTopologySpec(
                           NetworkTopologyMode.HARD, 1)),
    ]
    pg, pods = gang_job(
        "multislice", replicas=8, requests={"cpu": 8, TPU: 4},
        sub_group_policies=subgroups,
        labels_per_pod=lambda i: {SUBGROUP_LABEL: f"rep{i // 4}"})
    ctx = tpu_ctx([("sa", "v5e-16"), ("sb", "v5e-16")],
                  podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(8)
    by_slice = {}
    for pod_key, node in ctx.cluster.binds:
        by_slice.setdefault(node.rsplit("-w", 1)[0], set()).add(pod_key)
    assert len(by_slice) == 2
    for members in by_slice.values():
        assert len(members) == 4
        # a slice must hold exactly one subgroup, never a mix
        subgroup_ids = {int(k.rsplit("-", 1)[1]) // 4 for k in members}
        assert len(subgroup_ids) == 1, f"subgroup straddles slices: {members}"


def test_whole_host_request_enforced_on_multihost_slice():
    """Requesting 2 chips on a multi-host slice is rejected by the tpu
    device filter (must take the whole host: 4)."""
    pg, pods = gang_job("bad", replicas=1, requests={"cpu": 1, TPU: 2})
    ctx = tpu_ctx([("sa", "v5e-16")], podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(0)


def test_subhost_chips_allowed_on_single_host_slice():
    pg, pods = gang_job("small", replicas=2, requests={"cpu": 1, TPU: 2})
    ctx = tpu_ctx([("tiny", "v5e-4")], podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(2)  # two 2-chip pods pack one 4-chip host


def test_v5e_256_gang_allocation():
    """Full 64-host v5e-256 gang lands entirely in the slice."""
    pg, pods = gang_job(
        "big", replicas=64, requests={"cpu": 8, TPU: 4},
        network_topology=NetworkTopologySpec(
            mode=NetworkTopologyMode.HARD, highest_tier_allowed=1))
    ctx = tpu_ctx([("giant", "v5e-256"), ("spare", "v5e-16")],
                  podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(64)
    assert all(n.startswith("giant") for _, n in ctx.cluster.binds)
    ctx.expect_podgroup_phase("default/big", PodGroupPhase.RUNNING)


def test_soft_topology_prefers_colocation():
    """Soft topology: no hard constraint, but batch node order pulls
    tasks of the job toward one slice."""
    pg, pods = gang_job("soft", replicas=4, requests={"cpu": 8, TPU: 4})
    ctx = tpu_ctx([("sa", "v5e-16"), ("sb", "v5e-16")],
                  podgroups=[pg], pods=pods)
    ctx.run()
    ctx.expect_bind_num(4)
    slices_used = {n.rsplit("-w", 1)[0] for _, n in ctx.cluster.binds}
    assert len(slices_used) == 1


def test_1024_host_multislice_gang_scale():
    """4 x 256-host subgroups fill four v5p-1024 slices in one cycle
    (scale regression: must stay well under the 2s p50 target)."""
    import time as _time
    sg = [SubGroupPolicy(name=f"rep{i}", min_member=256,
                         network_topology=NetworkTopologySpec(
                             NetworkTopologyMode.HARD, 1))
          for i in range(4)]
    pg, pods = gang_job("mega", replicas=1024, requests={"cpu": 8, TPU: 4},
                        sub_group_policies=sg,
                        labels_per_pod=lambda i: {SUBGROUP_LABEL:
                                                  f"rep{i // 256}"})
    ctx = tpu_ctx([(f"pod{i}", "v5p-1024") for i in range(5)],
                  podgroups=[pg], pods=pods)
    cluster = ctx.cluster
    t0 = _time.perf_counter()
    ctx.run()
    elapsed = _time.perf_counter() - t0
    ctx.expect_bind_num(1024)
    assert elapsed < 5.0, f"1024-host cycle took {elapsed:.2f}s"
    used = {n.split("-w")[0] for _, n in cluster.binds}
    assert len(used) == 4  # one slice per subgroup
