"""Scheduling flight recorder (volcano_tpu/trace.py) + satellites.

Covers: span trees through real scheduler sessions, lifecycle phase
stamps and their telescoping reconciliation, unschedulable-reason
normalization + podgroup aggregation, `vtpctl explain` end-to-end
through a REAL HTTP state server (the acceptance e2e), the server's
/traces ring, the dumper's trace section, metrics label escaping and
summary-window monotonicity (strict Prometheus text-parser round
trip), and `bench.py --trace-smoke` as a tier-1 guard.
"""

import json
import math
import os
import re
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

from volcano_tpu import metrics, trace
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.uthelper import gang_job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_registries():
    metrics.reset()
    trace.reset()
    yield
    metrics.reset()
    trace.reset()


# -- reason normalization ----------------------------------------------

def test_normalize_reason_bounded_enum():
    cases = {
        "node's slice is quarantined after failure": "quarantined",
        "node(s) didn't match Pod's node selector":
            "node-affinity-mismatch",
        "node(s) had untolerated taint {dedicated: infra}":
            "taint-not-tolerated",
        "node is not ready": "node-not-ready",
        "Insufficient cpu, google.com/tpu": "insufficient-resources",
        "not enough free TPU chips": "tpu-shape-mismatch",
        "no hypernode domain within tier 1 can hold job default/x":
            "ici-shape-mismatch",
        "node(s) didn't have free ports": "port-conflict",
        "node(s) had too many pods": "pod-limit",
        "task would exceed queue q's deserved share":
            "queue-share-exceeded",
        "pod has unresolved scheduling gates ['g']":
            "scheduling-gated",
        "some totally novel failure text": "other",
    }
    for text, want in cases.items():
        assert trace.normalize_reason(text) == want, text
    # every output is a member of the bounded enum — the metric-label
    # cardinality contract
    for text in cases:
        assert trace.normalize_reason(text) in trace.REASON_ENUM


def test_phase_segments_reconcile_and_clamp():
    t0 = 1000.0
    pod = {}
    pg = {}
    trace.stamp_phase(pod, "created", t0)
    trace.stamp_phase(pg, "enqueued", t0 + 1.0)
    trace.stamp_phase(pod, "allocated", t0 + 1.5)
    trace.stamp_phase(pod, "bound", t0 + 1.6)
    trace.stamp_phase(pod, "admitted", t0 + 2.0)
    trace.stamp_phase(pod, "running", t0 + 2.25)
    segs = trace.phase_segments(pod, pg)
    assert segs == {"queue": 1.0, "schedule": 0.5, "bind": pytest.approx(0.1),
                    "admit": pytest.approx(0.4),
                    "start": pytest.approx(0.25)}
    # telescoping invariant: segments sum to running - created
    assert math.isclose(sum(segs.values()), 2.25)

    # stamps are first-writer-wins (a retried create can't move them)
    trace.stamp_phase(pod, "created", t0 + 99)
    assert trace.phase_ts(pod, "created") == t0

    # a missing middle stamp folds its gap into the next segment and
    # the sum still telescopes
    pod2 = {}
    trace.stamp_phase(pod2, "created", t0)
    trace.stamp_phase(pod2, "bound", t0 + 2.0)
    trace.stamp_phase(pod2, "admitted", t0 + 2.5)
    trace.stamp_phase(pod2, "running", t0 + 3.0)
    segs2 = trace.phase_segments(pod2, None)
    assert math.isclose(sum(segs2.values()), 3.0)

    # clock skew: an allocated stamp BEHIND created clamps to 0 and
    # pushes the skew forward — the sum is preserved, never negative
    pod3 = {}
    trace.stamp_phase(pod3, "created", t0)
    trace.stamp_phase(pod3, "allocated", t0 - 0.5)
    trace.stamp_phase(pod3, "bound", t0 + 1.0)
    trace.stamp_phase(pod3, "running", t0 + 1.5)
    segs3 = trace.phase_segments(pod3, None)
    assert all(v >= 0 for v in segs3.values())
    assert math.isclose(sum(segs3.values()), 1.5)


# -- span model --------------------------------------------------------

def test_span_tree_sampling_and_export():
    # sessions with unschedulable jobs are ALWAYS kept
    root = trace.begin_session(cycle=0)
    with trace.span("allocate", kind="action"):
        with trace.span("default/j1", kind="job", job="default/j1"):
            trace.add_plugin_time("predicate", "predicates", 0.002)
            trace.add_plugin_time("predicate", "predicates", 0.003)
            trace.add_plugin_time("nodeOrder", "binpack", 0.001)
    trace.note_pending("default/j1", {"quarantined": 3},
                       {"quarantined": "node's slice is quarantined"})
    doc = trace.end_session(root, jobs_pending=["default/j1"])
    assert doc is not None and doc["kept_because"] == "unschedulable"
    action = doc["root"]["children"][0]
    assert action["name"] == "allocate" and action["kind"] == "action"
    jobspan = action["children"][0]
    assert jobspan["labels"]["job"] == "default/j1"
    agg = {c["name"]: c for c in jobspan["children"]}
    assert agg["predicates"]["labels"] == {"point": "predicate",
                                           "calls": "2"}
    assert agg["predicates"]["dur"] == pytest.approx(0.005)
    assert trace.matches_job(doc, "default/j1")
    assert not trace.matches_job(doc, "default/other")

    # outside a session, span() and add_plugin_time are no-ops
    with trace.span("orphan") as s:
        assert s is None
    trace.add_plugin_time("predicate", "predicates", 1.0)

    # quiet sessions are 1-in-SAMPLE_EVERY sampled (seq 1 kept above;
    # the next SAMPLE_EVERY-1 quiet ones drop, then one keeps)
    trace.clear_pending("default/j1")
    kept = 0
    for _ in range(trace.SAMPLE_EVERY):
        r = trace.begin_session(cycle=1)
        kept += trace.end_session(r) is not None
    assert kept == 1
    assert len(trace.recent_traces()) == 2
    assert trace.recent_traces(job="default/j1")[0]["seq"] == doc["seq"]

    # renderers work off the kept doc
    lines = trace.render_waterfall(doc["root"])
    assert any("allocate" in ln for ln in lines)
    chrome = trace.to_chrome_trace([doc])
    names = {e["name"] for e in chrome["traceEvents"]}
    assert {"session", "allocate", "predicates"} <= names
    for e in chrome["traceEvents"]:
        if e.get("ph") == "X":
            assert e["dur"] >= 0 and e["ts"] > 0

    # a crash mid-span: end_session closes the dangling spans
    root = trace.begin_session(cycle=2)
    trace.span("allocate", kind="action").__enter__()
    trace.end_session(root)
    assert root.end is not None
    assert all(c.end is not None for c in root.children)


def test_span_child_cap():
    root = trace.begin_session(cycle=0)
    with trace.span("allocate", kind="action") as action:
        for i in range(trace.MAX_CHILDREN + 10):
            with trace.span(f"default/j{i}", kind="job"):
                pass
    assert len(action.children) == trace.MAX_CHILDREN
    assert action.dropped == 10
    trace.end_session(root)


# -- scheduler integration (in-process) --------------------------------

def _gang_cluster(stuck_selector=False):
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pg, pods = gang_job("demo", replicas=2, requests={"cpu": 1})
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    if stuck_selector:
        pg2, pods2 = gang_job("stuck", replicas=2,
                              requests={"cpu": 1})
        for p in pods2:
            p.node_selector = {"zone": "nowhere"}
        cluster.add_podgroup(pg2)
        for p in pods2:
            cluster.add_pod(p)
    return cluster


def test_session_trace_via_scheduler():
    cluster = _gang_cluster(stuck_selector=True)
    sched = Scheduler(cluster, schedule_period=0)
    sched.run_once()
    traces = trace.recent_traces()
    assert traces, "session with an unschedulable gang must be kept"
    root = traces[-1]["root"]
    actions = [c["name"] for c in root["children"]
               if c["kind"] == "action"]
    assert "allocate" in actions and "open_session" in actions
    alloc = next(c for c in root["children"]
                 if c["name"] == "allocate")
    jobs = [c["labels"]["job"] for c in alloc["children"]
            if c["kind"] == "job"]
    assert "default/stuck" in jobs
    # plugin aggregates landed somewhere under the tree, with call
    # counts — never one span per callback
    flat = []

    def walk(d):
        flat.append(d)
        for c in d.get("children", ()):
            walk(c)
    walk(root)
    plugin_spans = [d for d in flat if d["kind"] == "plugin"
                    and "calls" in d.get("labels", {})]
    assert plugin_spans
    # and sched_span_seconds is live with BOUNDED labels.  (The full
    # label-cardinality sweep — job keys never label the trace
    # families, values stay in their enums — moved to tests/
    # test_lint.py::test_live_exposition_honours_label_schema, the
    # linter-driven check over the whole exposition.)
    dumped = metrics.dump()
    assert 'sched_span_seconds_count{action="allocate"}' in dumped
    assert re.search(r'sched_span_seconds_count\{plugin=', dumped)


def test_pending_reasons_published_and_cleared():
    cluster = _gang_cluster(stuck_selector=True)
    sched = Scheduler(cluster, schedule_period=0)
    sched.run_once()
    pg = cluster.podgroups["default/stuck"]
    doc = trace.parse_annotation(
        pg.annotations[trace.PENDING_REASONS_ANNOTATION])
    assert doc["top"] == "node-affinity-mismatch"
    # distinct-NODE count: all 4 hosts of the v5e-16 slice
    assert doc["reasons"]["node-affinity-mismatch"] == 4
    assert "node selector" in doc["detail"]["node-affinity-mismatch"]
    assert trace.pending_reasons()["default/stuck"]["top"] == \
        "node-affinity-mismatch"
    # the placed gang carries no aggregate
    assert trace.PENDING_REASONS_ANNOTATION not in \
        cluster.podgroups["default/demo"].annotations

    # un-stick the job: selector now matches a real label
    for p in cluster.pods.values():
        if p.name.startswith("stuck-"):
            p.node_selector = {}
    sched.run_once()
    cluster.tick()
    sched.run_once()
    assert trace.PENDING_REASONS_ANNOTATION not in \
        cluster.podgroups["default/stuck"].annotations
    assert "default/stuck" not in trace.pending_reasons()


def test_phase_stamps_and_metrics_inprocess():
    cluster = _gang_cluster()
    sched = Scheduler(cluster, schedule_period=0)
    sched.run_once()
    cluster.tick()
    pod = cluster.pods["default/demo-0"]
    pg = cluster.podgroups["default/demo"]
    for phase in ("created", "allocated", "bound", "admitted",
                  "running"):
        assert trace.phase_ts(pod.annotations, phase) is not None, phase
    assert trace.phase_ts(pg.annotations, "enqueued") is not None
    segs = trace.phase_segments(pod.annotations, pg.annotations)
    e2e = trace.phase_ts(pod.annotations, "running") - \
        trace.phase_ts(pod.annotations, "created")
    assert math.isclose(sum(segs.values()), e2e, rel_tol=1e-9)
    assert pod.phase is TaskStatus.RUNNING
    # the cache observer fed sched_phase_seconds exactly once per pod
    assert metrics.get_observations("sched_phase_seconds",
                                    phase="e2e")
    count_before = len(metrics.get_observations(
        "sched_phase_seconds", phase="e2e"))
    # re-notifying the same pod must not double-observe
    cluster._notify("pod", pod)
    assert len(metrics.get_observations(
        "sched_phase_seconds", phase="e2e")) == count_before


def test_dumper_includes_trace_section(tmp_path):
    from volcano_tpu.dumper import Dumper
    cluster = _gang_cluster(stuck_selector=True)
    sched = Scheduler(cluster, schedule_period=0)
    sched.run_once()
    path = tmp_path / "dump.json"
    Dumper(sched, path=str(path)).dump()
    doc = json.loads(path.read_text())
    assert doc["trace"]["recent_traces"], "kept traces in the dump"
    assert doc["trace"]["pending_reasons"]["default/stuck"]["top"] == \
        "node-affinity-mismatch"


# -- metrics satellites ------------------------------------------------

_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"


def _parse_prometheus_text(text):
    """Strict Prometheus text-format parser: returns
    {(name, ((label, value), ...)): float}.  Raises on any malformed
    line — the round-trip guard for the exposition writer."""
    out = {}
    for line in text.splitlines():
        if not line:
            continue
        m = re.match(rf"^({_NAME_RE})(?:\{{(.*)\}})? (\S+)$", line)
        assert m, f"malformed exposition line: {line!r}"
        name, raw_labels, raw_value = m.groups()
        labels = []
        i = 0
        s = raw_labels or ""
        while i < len(s):
            lm = re.match(rf'({_NAME_RE})="', s[i:])
            assert lm, f"malformed labels at {s[i:]!r} in {line!r}"
            key = lm.group(1)
            i += lm.end()
            val = []
            while True:
                assert i < len(s), f"unterminated label value: {line!r}"
                c = s[i]
                if c == "\\":
                    esc = s[i + 1]
                    assert esc in ('\\', '"', 'n'), \
                        f"invalid escape \\{esc} in {line!r}"
                    val.append({"\\": "\\", '"': '"',
                                "n": "\n"}[esc])
                    i += 2
                elif c == '"':
                    i += 1
                    break
                else:
                    assert c != "\n"
                    val.append(c)
                    i += 1
            labels.append((key, "".join(val)))
            if i < len(s):
                assert s[i] == ",", f"expected ',' at {s[i:]!r}"
                i += 1
        out[(name, tuple(labels))] = float(raw_value)
    return out


def test_exposition_escapes_label_values():
    metrics.inc("sched_test_total", node='host"quoted"',
                reason="line1\nline2", path="c:\\cgroup")
    metrics.set_gauge("sched_test_gauge", 1.5, msg='say "hi"\n')
    metrics.observe("sched_test_seconds", 0.25, who="a\\b")
    parsed = _parse_prometheus_text(metrics.dump())
    assert parsed[("sched_test_total",
                   (("node", 'host"quoted"'), ("path", "c:\\cgroup"),
                    ("reason", "line1\nline2")))] == 1.0
    assert parsed[("sched_test_gauge",
                   (("msg", 'say "hi"\n'),))] == 1.5
    assert parsed[("sched_test_seconds_count",
                   (("who", "a\\b"),))] == 1.0
    # every line is single-line: the newline in a label value must not
    # produce an extra exposition line
    assert all(ln.count('"') % 2 == 0
               for ln in metrics.dump().splitlines() if ln)


def test_summary_window_trimming_stays_monotonic():
    total = metrics.MAX_OBSERVATIONS * 2 + 100
    prev_count, prev_sum = 0, 0.0
    expected_sum = 0.0
    for i in range(total):
        metrics.observe("trim_test_seconds", 0.001, op="x")
        expected_sum += 0.001
        if i % 4096 == 0 or i == total - 1:
            parsed = _parse_prometheus_text(metrics.dump())
            count = parsed[("trim_test_seconds_count",
                            (("op", "x"),))]
            ssum = parsed[("trim_test_seconds_sum", (("op", "x"),))]
            # cumulative count/sum NEVER regress across the window
            # halving (scrapers' rate() would see phantom resets)
            assert count >= prev_count and ssum >= prev_sum - 1e-9
            prev_count, prev_sum = count, ssum
    assert prev_count == total
    assert prev_sum == pytest.approx(expected_sum, rel=1e-6)
    # the quantile window really was trimmed (memory bound held)
    assert len(metrics.get_observations(
        "trim_test_seconds", op="x")) <= metrics.MAX_OBSERVATIONS


# -- e2e: vtpctl explain through a real HTTP state server --------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait(cond, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def test_explain_unplaceable_gang_e2e_wire(tmp_path):
    """The acceptance e2e: a deliberately unplaceable gang through the
    REAL multi-process control plane; `vtpctl explain` against the
    live server surfaces the correct top unschedulable reason, and the
    session traces that produced it are queryable at /traces."""
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.simulator import slice_nodes

    port = _free_port()
    url = f"http://127.0.0.1:{port}"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    procs = {}

    def spawn(name, *argv):
        logf = open(tmp_path / f"{name}.log", "w")
        procs[name] = subprocess.Popen(
            [sys.executable, *argv], stdout=logf, stderr=logf,
            env=env, cwd=REPO)

    kubectl = None
    try:
        spawn("server", "-m", "volcano_tpu.server", "--port",
              str(port), "--tick-period", "0.1")

        def up():
            try:
                with urllib.request.urlopen(url + "/healthz",
                                            timeout=1):
                    return True
            except OSError:
                return False
        _wait(up, 20, "server /healthz")
        spawn("plane", "-m", "volcano_tpu", "--cluster-url", url,
              "--components", "scheduler,controllers",
              "--period", "0.1")
        kubectl = RemoteCluster(url)
        for node in slice_nodes(slice_for("sa", "v5e-16"),
                                dcn_pod="d0"):
            kubectl.add_node(node)
        # unplaceable: the selector matches no node label anywhere
        tmpl = make_pod("t", requests={"cpu": 1})
        tmpl.node_selector = {"zone": "nowhere"}
        kubectl.add_vcjob(VCJob(
            name="doomed", min_available=2,
            tasks=[TaskSpec(name="w", replicas=2, template=tmpl)]))

        def aggregated():
            pg = kubectl.podgroups.get("default/doomed")
            return pg is not None and \
                trace.PENDING_REASONS_ANNOTATION in pg.annotations
        _wait(aggregated, 30, "pending-reasons annotation on the wire")

        out = subprocess.run(
            [sys.executable, "-m", "volcano_tpu.cli.vtpctl",
             "--server", url, "explain", "doomed"],
            capture_output=True, text=True, timeout=60, env=env,
            cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "top unschedulable reason: node-affinity-mismatch" in \
            out.stdout, out.stdout
        # node count: all 4 hosts of the slice failed the selector
        m = re.search(r"node-affinity-mismatch\s+(\d+)", out.stdout)
        assert m and int(m.group(1)) == 4, out.stdout
        assert "node selector" in out.stdout

        # the flight recorder flowed through the same wire: the
        # server's ring holds complete traces mentioning the job
        with urllib.request.urlopen(
                url + "/traces?job=default/doomed", timeout=5) as r:
            payload = json.loads(r.read())
        assert payload["traces"], "no session traces for the job"
        for t in payload["traces"]:
            assert "dur" in t["root"]
        assert any(t.get("pending", {}).get("default/doomed")
                   for t in payload["traces"])

        # vtpctl trace renders the span waterfall for the same job
        out = subprocess.run(
            [sys.executable, "-m", "volcano_tpu.cli.vtpctl",
             "--server", url, "trace", "doomed"],
            capture_output=True, text=True, timeout=60, env=env,
            cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "session seq=" in out.stdout, out.stdout
        assert "allocate" in out.stdout
    finally:
        if kubectl is not None:
            kubectl.close()
        for p in procs.values():
            if p.poll() is None:
                p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except Exception:  # noqa: BLE001
                p.kill()


def test_bench_trace_smoke_mode():
    """`bench.py --trace-smoke` runs a gang through the real process
    plane and asserts stamps, reconciliation (<5%) and trace flow —
    the flight-recorder drill guarded on every commit, mirroring
    --wire-smoke/--crash-smoke."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--trace-smoke"],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO)
    assert proc.returncode == 0, \
        proc.stdout[-2000:] + proc.stderr[-2000:]
    line = next(ln for ln in
                reversed(proc.stdout.strip().splitlines())
                if ln.startswith("{"))
    out = json.loads(line)
    assert out["ok"] is True, out
    assert out["reconcile_err_max_pct"] < 5.0
    assert out["traces_captured"] > 0
    assert set(out["phase_p50_s"]) == {"queue", "schedule", "bind",
                                       "admit", "start"}
