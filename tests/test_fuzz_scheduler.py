"""Whole-scheduler churn fuzz: random gang arrivals/deletions under the
FULL contention pipeline (enqueue, allocate, preempt, reclaim,
gangpreempt, backfill, shuffle) with accounting invariants asserted
after every cycle.

Reference analogue: the -race + fuzz posture of the Go suite
(Makefile:195, job/fuzz_test.go) applied to the scheduling core — the
invariants here are the ones that, historically, every scheduler bug
eventually violates: node over-allocation, orphan binds, broken gang
floors, and split multi-host TPU hosts.
"""

import random

from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.queue import Queue
from volcano_tpu.cache.cluster import PriorityClass
from volcano_tpu.api.resource import TPU, Resource
from volcano_tpu.api.types import (GROUP_NAME_ANNOTATION, PodGroupPhase,
                                   TaskStatus)
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster

FULL_CONF = {
    "actions": "enqueue, allocate, preempt, reclaim, gangpreempt, "
               "backfill, shuffle",
    "tiers": [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "conformance"}]},
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"}, {"name": "deviceshare"},
                     {"name": "proportion"}, {"name": "nodeorder"},
                     {"name": "binpack"}, {"name": "pdb"},
                     {"name": "cdp"}]},
    ],
}

OCCUPYING = (TaskStatus.RUNNING, TaskStatus.BOUND, TaskStatus.BINDING)


def check_invariants(cluster):
    # 1. every placed pod's node exists; per-node sums fit allocatable
    per_node = {}
    for pod in cluster.pods.values():
        if not pod.node_name:
            continue
        if pod.phase not in OCCUPYING:
            continue
        assert pod.node_name in cluster.nodes, \
            f"pod {pod.key} bound to unknown node {pod.node_name}"
        per_node.setdefault(pod.node_name, []).append(pod)
    for node_name, pods in per_node.items():
        alloc = Resource.from_resource_list(
            cluster.nodes[node_name].allocatable)
        used = Resource()
        for p in pods:
            used.add(p.resource_requests())
        assert used.less_equal(alloc), \
            f"node {node_name} over-allocated: {used} > {alloc}"
        # 2. every slice here is multi-host, so hosts are whole-host
        # atomic: at most ONE chip-holding pod per host
        tpu_pods = [p for p in pods if p.resource_requests().get(TPU)]
        assert len(tpu_pods) <= 1, \
            f"multi-host slice host {node_name} split between " \
            f"{[p.key for p in tpu_pods]}"
    # 3. a placed pod's node matches its LAST bind log entry (earlier
    # entries may differ legitimately after evict + re-place)
    last_bind = {}
    for key, node in cluster.binds:
        last_bind[key] = node
    for pod in cluster.pods.values():
        if pod.node_name and pod.phase in OCCUPYING and \
                pod.key in last_bind:
            assert pod.node_name == last_bind[pod.key], \
                f"{pod.key} on {pod.node_name} but last bound to " \
                f"{last_bind[pod.key]}"
    # 4. running gangs hold their minAvailable floor (the group
    # annotation may be the short name or the namespaced key)
    for pg in cluster.podgroups.values():
        if pg.phase is not PodGroupPhase.RUNNING:
            continue
        members = sum(
            1 for p in cluster.pods.values()
            if p.annotations.get(GROUP_NAME_ANNOTATION) in (pg.key,
                                                            pg.name)
            and p.phase in OCCUPYING and p.node_name)
        assert members >= pg.min_member, \
            f"gang {pg.key} nibbled below floor: " \
            f"{members}/{pg.min_member}"


def churn_episode(seed, steps=60, gang_sizes=(1, 2, 4, 4, 8),
                  p_new=0.55, p_del=0.75, p_prio=0.85,
                  p_weight=None):
    """One randomized contention episode with per-cycle invariants —
    shared by the CI fuzz (fixed seeds below) and the extended soak
    sweep (tools/fuzz_sweep.py), so new ops/invariants reach both.
    p_weight, when set, adds a queue-weight flip op driven through
    the real add_queue update path (upsert + notify — an in-place
    mutation would bypass the event-driven invalidation the op
    exists to stress)."""
    from volcano_tpu.api.podgroup import PodGroup

    rng = random.Random(seed)
    cluster = make_tpu_cluster(
        [("sa", "v5e-16"), ("sb", "v5e-16"), ("sc", "v5e-64")])
    cluster.add_queue(Queue(name="gold", weight=3))
    cluster.add_queue(Queue(name="dirt", weight=1))
    cluster.add_priority_class(PriorityClass(name="high", value=1000))
    cluster.add_priority_class(PriorityClass(name="low", value=10))
    sched = Scheduler(cluster, conf=FULL_CONF, schedule_period=0)

    live = []
    for step in range(steps):
        op = rng.random()
        if op < p_new:
            # new gang job: random size/queue/priority
            n = rng.choice(gang_sizes)
            name = f"j{seed}-{step}"
            pg = PodGroup(name=f"pg-{name}", min_member=n,
                          queue=rng.choice(("gold", "dirt")),
                          priority_class=rng.choice(("", "high",
                                                     "low")))
            cluster.add_podgroup(pg)
            for i in range(n):
                cluster.add_pod(make_pod(
                    f"{name}-{i}",
                    requests={"cpu": rng.choice((1, 4)),
                              TPU: rng.choice((0, 4, 4))},
                    annotations={GROUP_NAME_ANNOTATION: pg.key},
                    priority_class=pg.priority_class))
            live.append((pg, name, n))
        elif op < p_del and live:
            # delete a random live job (releases its resources) —
            # through delete_podgroup so the podgroup_deleted
            # invalidation path fires, not a silent dict pop
            pg, name, n = live.pop(rng.randrange(len(live)))
            for i in range(n):
                cluster.delete_pod(f"default/{name}-{i}")
            cluster.delete_podgroup(pg.key)
        elif op < p_prio:
            # control-kind churn: a priority class vanishes and
            # returns with a FLIPPED value mid-flight — the
            # incremental snapshot must rebuild job priorities,
            # never preempt/order on a stale one (r4 *_deleted
            # invalidation path)
            victim = rng.choice(("high", "low"))
            old = cluster.priority_classes[victim].value
            cluster.delete_object("priority_class", victim)
            cluster.add_priority_class(PriorityClass(
                name=victim, value=1010 - old))
        elif p_weight is not None and op < p_weight:
            # queue-weight flip mid-flight, through the notify path:
            # fair-share state must follow, never a stale weight
            name = rng.choice(("gold", "dirt"))
            cluster.add_queue(Queue(name=name,
                                    weight=rng.choice((1, 2, 3, 5))))
        sched.run_once()
        cluster.tick()
        check_invariants(cluster)


def test_fuzz_full_contention_pipeline():
    for seed in (7, 23, 404, 1719):
        churn_episode(seed)


def test_fuzz_gang_floor_protects_victims_from_plain_preempt():
    """A low-priority gang running exactly at its floor cannot be
    nibbled by the plain preempt action (gang Preemptable veto,
    reference gang.go:113-118) — the invariants hold while the
    high-priority gang waits."""
    from volcano_tpu.api.podgroup import PodGroup
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.add_queue(Queue(name="gold", weight=1))
    cluster.add_priority_class(PriorityClass(name="high", value=1000))
    cluster.add_priority_class(PriorityClass(name="low", value=10))
    sched = Scheduler(cluster, conf=FULL_CONF, schedule_period=0)

    pg_low = PodGroup(name="pg-low", min_member=4, queue="gold",
                      priority_class="low")
    cluster.add_podgroup(pg_low)
    for i in range(4):
        cluster.add_pod(make_pod(
            f"low-{i}", requests={"cpu": 4, TPU: 4},
            annotations={GROUP_NAME_ANNOTATION: pg_low.key},
            priority_class="low"))
    for _ in range(3):
        sched.run_once()
        cluster.tick()
    assert sum(1 for p in cluster.pods.values()
               if p.node_name and p.key.startswith("default/low")) == 4

    pg_hi = PodGroup(name="pg-hi", min_member=4, queue="gold",
                     priority_class="high")
    cluster.add_podgroup(pg_hi)
    for i in range(4):
        cluster.add_pod(make_pod(
            f"hi-{i}", requests={"cpu": 4, TPU: 4},
            annotations={GROUP_NAME_ANNOTATION: pg_hi.key},
            priority_class="high"))
    for _ in range(4):
        sched.run_once()
        cluster.tick()
        check_invariants(cluster)
    # the victim gang's floor held: no partial eviction happened
    assert sum(1 for p in cluster.pods.values()
               if p.node_name and p.key.startswith("default/low")) == 4


def test_fuzz_hard_topology_gang_displaces_via_gangpreempt():
    """A high-priority HARD-topology gang displaces a low-priority
    elastic tenant (whole-bundle eviction + two-cycle nomination), with
    invariants checked every cycle of the handshake."""
    from volcano_tpu.api.podgroup import NetworkTopologySpec
    from volcano_tpu.api.types import NetworkTopologyMode
    from volcano_tpu.uthelper import gang_job

    cluster = make_tpu_cluster([("target", "v5e-16")])
    cluster.add_priority_class(PriorityClass(name="high", value=1000))
    # elastic tenant (floor 1) holds the whole slice
    pg_lo, pods_lo = gang_job(
        "tenant", replicas=4, min_available=1,
        requests={"cpu": 4, TPU: 4},
        running_on=[f"target-w{i}" for i in range(4)],
        pg_phase=PodGroupPhase.RUNNING)
    cluster.add_podgroup(pg_lo)
    for p in pods_lo:
        cluster.add_pod(p)
    pg_hi, pods_hi = gang_job(
        "train-hi", replicas=4, requests={"cpu": 4, TPU: 4},
        priority_class="high",
        network_topology=NetworkTopologySpec(NetworkTopologyMode.HARD, 1),
        pg_phase=PodGroupPhase.INQUEUE)
    conf = dict(FULL_CONF)
    conf["tiers"] = [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "conformance"}]},
        {"plugins": [{"name": "predicates"}, {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "deviceshare"},
                     {"name": "network-topology-aware"}]},
    ]
    sched = Scheduler(cluster, conf=conf, schedule_period=0)
    sched.run_once()
    cluster.add_podgroup(pg_hi)
    for p in pods_hi:
        cluster.add_pod(p)
    placed_hi = 0
    for _ in range(8):
        sched.run_once()
        cluster.tick()
        check_invariants(cluster)
        placed_hi = sum(1 for p in cluster.pods.values()
                        if p.node_name
                        and p.key.startswith("default/train-hi")
                        and p.phase in OCCUPYING)
        if placed_hi == 4:
            break
    assert placed_hi == 4, f"hard-topology gang stuck at {placed_hi}/4"
