"""Pipeline parallelism: GPipe schedule over the pp axis."""

import jax
import jax.numpy as jnp
import numpy as np

from volcano_tpu.workloads import model as model_lib, train
from volcano_tpu.workloads import pipeline


def cfg4():
    return model_lib.tiny_config(n_layers=4)


def test_pipelined_forward_exactly_matches_sequential():
    """The pipelined block stack must be bit-close to running the same
    blocks sequentially (same params, same inputs)."""
    cfg = cfg4()
    params = model_lib.init_params(jax.random.key(0), cfg)
    mesh = pipeline.make_pp_mesh(4)
    outer, stage_blocks = pipeline.stack_stage_params(params, 4)

    tokens = jax.random.randint(jax.random.key(1), (8, 32), 0,
                                cfg.vocab_size)
    x = params["embed"].astype(cfg.dtype)[tokens]
    positions = jnp.broadcast_to(jnp.arange(32)[None, :], (8, 32))

    piped = pipeline.pipelined_apply_blocks(
        x, stage_blocks, cfg, positions, mesh, n_microbatches=4)

    seq = x
    for blk in params["blocks"]:
        seq, _ = model_lib._block(seq, blk, cfg, positions, None)

    np.testing.assert_allclose(np.asarray(piped), np.asarray(seq),
                               atol=2e-5, rtol=2e-5)


def test_pipelined_loss_matches_model_loss():
    cfg = cfg4()
    params = model_lib.init_params(jax.random.key(0), cfg)
    mesh = pipeline.make_pp_mesh(4)
    outer, stage_blocks = pipeline.stack_stage_params(params, 4)
    tokens = jax.random.randint(jax.random.key(1), (8, 32), 0,
                                cfg.vocab_size)
    piped = pipeline.pipelined_loss(outer, stage_blocks, tokens, cfg,
                                    mesh, n_microbatches=4)
    ref = model_lib.loss_fn(params, {"tokens": tokens}, cfg)
    np.testing.assert_allclose(float(piped), float(ref), rtol=1e-5)


def test_pipelined_training_descends():
    cfg = cfg4()
    params = model_lib.init_params(jax.random.key(0), cfg)
    mesh = pipeline.make_pp_mesh(4)
    outer, stage_blocks = pipeline.stack_stage_params(params, 4)
    outer_sh, stage_sh = pipeline.stage_param_shardings(
        stage_blocks, outer, mesh)
    outer = jax.device_put(outer, outer_sh)
    stage_blocks = jax.device_put(stage_blocks, stage_sh)

    opt = train.make_optimizer(lr=1e-2, warmup_steps=1)
    opt_state = opt.init((outer, stage_blocks))
    step = pipeline.make_pipelined_train_step(cfg, mesh, opt,
                                              n_microbatches=4)
    tokens = jax.random.randint(jax.random.key(1), (8, 32), 0,
                                cfg.vocab_size)
    losses = []
    for _ in range(3):
        outer, stage_blocks, opt_state, m = step(
            outer, stage_blocks, opt_state, {"tokens": tokens})
        losses.append(float(m["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_stage_stacking_validation():
    import pytest
    cfg = model_lib.tiny_config(n_layers=3)
    params = model_lib.init_params(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="divisible"):
        pipeline.stack_stage_params(params, 4)


def test_pipeline_rejects_moe_stacks():
    import pytest
    cfg = model_lib.tiny_config(n_layers=4, n_experts=4)
    params = model_lib.init_params(jax.random.key(0), cfg)
    with pytest.raises(ValueError, match="dense block stacks"):
        pipeline.stack_stage_params(params, 2)


def test_pipeline_per_sample_positions_ride_the_ring():
    """Per-sample position ids (e.g. packed sequences) must travel with
    their microbatch, not be clobbered by microbatch 0's."""
    cfg = cfg4()
    params = model_lib.init_params(jax.random.key(0), cfg)
    mesh = pipeline.make_pp_mesh(4)
    outer, stages = pipeline.stack_stage_params(params, 4)
    b, t = 8, 32
    tokens = jax.random.randint(jax.random.key(1), (b, t), 0,
                                cfg.vocab_size)
    # each sample gets a different position offset
    positions = (jnp.arange(t)[None, :] +
                 10 * jnp.arange(b)[:, None]).astype(jnp.int32)
    x = params["embed"].astype(cfg.dtype)[tokens]
    piped = pipeline.pipelined_apply_blocks(x, stages, cfg, positions,
                                            mesh, n_microbatches=4)
    seq = x
    for blk in params["blocks"]:
        seq, _ = model_lib._block(seq, blk, cfg, positions, None)
    np.testing.assert_allclose(np.asarray(piped), np.asarray(seq),
                               atol=2e-5, rtol=2e-5)
