"""Scheduler -> real jax.distributed workers e2e (VERDICT r3 #4).

The whole point of the jax job plugin is that scheduled pods can form
a mesh.  Until now that was only ASSERTED (env-contract round-trip in
test_job_controller.py); here it is EXECUTED: a vcjob flows through
admission -> job controller -> gang scheduler, and then each bound
worker pod's controller-injected container env launches a REAL OS
process running `python -m volcano_tpu.workloads.worker`, which calls
bootstrap.from_env() -> jax.distributed.initialize (CPU backend) and
runs a cross-process collective plus sharded train steps.

Reference analogue: the pytorch-plugin e2e runs actual DDP jobs from
MASTER_ADDR/RANK/WORLD_SIZE (test/e2e/jobseq/pytorch_plugin.go:40).

Single-host stand-in for cluster DNS: the svc-plugin hostnames
(`<pod>.<job>.<ns>.svc`) are not resolvable outside a cluster, so the
coordinator HOST is rewritten to 127.0.0.1 with a free port; every
other injected variable (worker ids, process count) is consumed
verbatim.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from volcano_tpu.api.pod import Container, Pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import JobPhase
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.webhooks import default_admission

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# QUARANTINED (ISSUE 6 satellite): this image's jaxlib CPU backend
# cannot run cross-process collectives — every jax.distributed worker
# dies with `XlaRuntimeError: INVALID_ARGUMENT: Multiprocess
# computations aren't implemented on the CPU backend`, so the two
# real-subprocess mesh e2es below cannot pass here regardless of
# scheduler correctness.  The single-process contract (env injection,
# bootstrap parsing, mesh construction, resume) stays covered by
# test_job_controller.py / test_workloads.py / test_checkpoint.py /
# test_elastic.py dryruns.  Un-skip on an image whose jaxlib CPU
# backend (or a real TPU backend) supports multiprocess computations.
MULTIPROCESS_CPU_REASON = (
    "jaxlib CPU backend lacks multiprocess collectives in this image "
    "(XlaRuntimeError: Multiprocess computations aren't implemented "
    "on the CPU backend); quarantined per ISSUE 6")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.skip(reason=MULTIPROCESS_CPU_REASON)
def test_scheduled_pods_launch_real_jax_workers():
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=["job", "queue"])
    sched = Scheduler(cluster, schedule_period=0)
    job = cluster.add_vcjob(VCJob(
        name="mesh", min_available=2,
        tasks=[TaskSpec(name="worker", replicas=2,
                        template=Pod(name="t", containers=[
                            Container(requests={"cpu": 4, TPU: 4})]))],
        plugins={"jax": [], "svc": []},
    ))
    for _ in range(3):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    job = cluster.vcjobs[job.key]
    assert job.phase is JobPhase.RUNNING
    workers = sorted((p for p in cluster.pods.values()
                      if p.owner == job.uid),
                     key=lambda p: p.task_index)
    assert len(workers) == 2 and all(p.node_name for p in workers)

    # launch one REAL process per bound pod from ITS injected env
    port = free_port()
    procs = []
    for pod in workers:
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)          # 1 CPU device per process
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        env.update(pod.containers[0].env)   # the controller's contract
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"  # DNS stand-in
        env["WORKER_STEPS"] = "2"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "volcano_tpu.workloads.worker"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    results = []
    for proc in procs:
        out, err = proc.communicate(timeout=180)
        assert proc.returncode == 0, f"worker failed:\n{err[-2000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    # the mesh spanned both processes: 2 devices total, the collective
    # crossed the process boundary, and training produced a real loss
    for rank, res in enumerate(results):
        assert res["process_id"] == rank
        assert res["num_processes"] == 2
        assert res["device_count"] == 2
        assert res["collective_sum"] == 2.0
        assert res["loss"] == res["loss"] and res["loss"] > 0
    assert results[0]["loss"] == results[1]["loss"], \
        "ranks disagree on the globally-reduced loss"


@pytest.mark.skip(reason=MULTIPROCESS_CPU_REASON)
def test_multislice_job_trains_across_dcn_axis():
    """Multi-slice e2e (VERDICT r4 #3): two subgrouped worker tasks
    land on two DCN-separated slices; each bound pod's injected env
    launches a REAL jax.distributed process; the workers build the
    hybrid DCN x ICI mesh from TPU_SLICE_ID/TPU_NUM_SLICES and run
    train steps whose gradient psum crosses the dcn axis (process
    boundary = slice boundary here)."""
    # v5e-4 slices: each subgroup's 4-chip worker FILLS its slice, so
    # gang placement must spread the two subgroups across DCN pods
    cluster = make_tpu_cluster([("sa", "v5e-4"), ("sb", "v5e-4")],
                               dcn_pods={"sa": "pod-a", "sb": "pod-b"})
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=["job", "queue"])
    sched = Scheduler(cluster, schedule_period=0)
    job = cluster.add_vcjob(VCJob(
        name="multislice", min_available=2,
        tasks=[TaskSpec(name="slice-a", replicas=1, subgroup="slice-a",
                        template=Pod(name="t", containers=[
                            Container(requests={"cpu": 4, TPU: 4})])),
               TaskSpec(name="slice-b", replicas=1, subgroup="slice-b",
                        template=Pod(name="t", containers=[
                            Container(requests={"cpu": 4, TPU: 4})]))],
        plugins={"jax": [], "svc": []},
    ))
    for _ in range(3):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    job = cluster.vcjobs[job.key]
    assert job.phase is JobPhase.RUNNING
    workers = sorted((p for p in cluster.pods.values()
                      if p.owner == job.uid),
                     key=lambda p: p.task_spec)
    assert len(workers) == 2 and all(p.node_name for p in workers)
    # the gang landed one subgroup per slice
    assert {p.node_name.split("-w")[0] for p in workers} == {"sa", "sb"}

    port = free_port()
    procs = []
    for pod in workers:
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)          # 1 CPU device per process
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        env.update(pod.containers[0].env)   # the controller's contract
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"  # DNS stand-in
        env["WORKER_STEPS"] = "2"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "volcano_tpu.workloads.worker"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    results = []
    for proc in procs:
        out, err = proc.communicate(timeout=180)
        assert proc.returncode == 0, f"worker failed:\n{err[-2000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    for rank, res in enumerate(results):
        assert res["process_id"] == rank
        assert res["num_processes"] == 2
        assert res["num_slices"] == 2
        assert res["slice_id"] == rank          # one slice per process
        assert res["collective_sum"] == 2.0     # crossed the dcn axis
        assert res["loss"] == res["loss"] and res["loss"] > 0
    assert results[0]["loss"] == results[1]["loss"], \
        "slices disagree on the dcn-reduced loss"
