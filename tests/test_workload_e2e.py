"""Scheduler -> real jax.distributed workers e2e (VERDICT r3 #4).

The whole point of the jax job plugin is that scheduled pods can form
a mesh.  Until now that was only ASSERTED (env-contract round-trip in
test_job_controller.py); here it is EXECUTED: a vcjob flows through
admission -> job controller -> gang scheduler, and then each bound
worker pod's controller-injected container env launches a REAL OS
process running `python -m volcano_tpu.workloads.worker`, which calls
bootstrap.from_env() -> jax.distributed.initialize (CPU backend) and
runs a cross-process collective plus sharded train steps.

Reference analogue: the pytorch-plugin e2e runs actual DDP jobs from
MASTER_ADDR/RANK/WORLD_SIZE (test/e2e/jobseq/pytorch_plugin.go:40).

Single-host stand-in for cluster DNS: the svc-plugin hostnames
(`<pod>.<job>.<ns>.svc`) are not resolvable outside a cluster, so the
coordinator HOST is rewritten to 127.0.0.1 with a free port; every
other injected variable (worker ids, process count) is consumed
verbatim.
"""

import json
import os
import socket
import subprocess
import sys

import pytest

from volcano_tpu.api.pod import Container, Pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import JobPhase
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.webhooks import default_admission

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# CAPABILITY PROBE (ISSUE 9 satellite, un-quarantining ISSUE 6's
# skip): some jaxlib CPU backends cannot run cross-process
# collectives — every jax.distributed worker dies with
# `XlaRuntimeError: INVALID_ARGUMENT: Multiprocess computations
# aren't implemented on the CPU backend`.  Instead of an
# unconditional skip (which kept the e2es off even on capable
# images), a 2-process CPU collective is attempted ONCE per test
# session; the tests run whenever it succeeds and skip with the real
# failure otherwise — a capable jaxlib image re-enables them with no
# code change.

_PROBE_SNIPPET = """
import sys
import jax
jax.distributed.initialize(coordinator_address=sys.argv[1],
                           num_processes=2, process_id=int(sys.argv[2]))
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
mesh = Mesh(np.array(jax.devices()), ("i",))
ones = jax.jit(lambda: jnp.ones((jax.device_count(),)),
               out_shardings=NamedSharding(mesh, P("i")))()
total = jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(ones)
assert float(total) == jax.device_count(), float(total)
print("PROBE-OK")
"""

_probe_result = None        # None = not yet run; "" = capable


def multiprocess_cpu_reason() -> str:
    """'' when a 2-process CPU-backend collective works on this
    image; otherwise the skip reason (with the real backend error).
    The probe runs at most once per test session."""
    global _probe_result
    if _probe_result is None:
        _probe_result = _run_probe()
    return _probe_result


def _run_probe() -> str:
    port = free_port()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)          # 1 CPU device per process
    procs = [subprocess.Popen(
        [sys.executable, "-c", _PROBE_SNIPPET,
         f"127.0.0.1:{port}", str(rank)],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True) for rank in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=120)
            outs.append(out or "")
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        return ("multiprocess CPU collective probe timed out; "
                "skipping the real-worker mesh e2es")
    if all(p.returncode == 0 for p in procs) and \
            all("PROBE-OK" in o for o in outs):
        return ""
    tail = next((o for p, o in zip(procs, outs) if p.returncode != 0),
                outs[0] if outs else "")[-400:]
    return ("this image's jaxlib CPU backend cannot run 2-process "
            f"collectives (probe said: ...{tail})")


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_scheduled_pods_launch_real_jax_workers():
    reason = multiprocess_cpu_reason()
    if reason:
        pytest.skip(reason)
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=["job", "queue"])
    sched = Scheduler(cluster, schedule_period=0)
    job = cluster.add_vcjob(VCJob(
        name="mesh", min_available=2,
        tasks=[TaskSpec(name="worker", replicas=2,
                        template=Pod(name="t", containers=[
                            Container(requests={"cpu": 4, TPU: 4})]))],
        plugins={"jax": [], "svc": []},
    ))
    for _ in range(3):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    job = cluster.vcjobs[job.key]
    assert job.phase is JobPhase.RUNNING
    workers = sorted((p for p in cluster.pods.values()
                      if p.owner == job.uid),
                     key=lambda p: p.task_index)
    assert len(workers) == 2 and all(p.node_name for p in workers)

    # launch one REAL process per bound pod from ITS injected env
    port = free_port()
    procs = []
    for pod in workers:
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)          # 1 CPU device per process
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        env.update(pod.containers[0].env)   # the controller's contract
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"  # DNS stand-in
        env["WORKER_STEPS"] = "2"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "volcano_tpu.workloads.worker"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    results = []
    for proc in procs:
        out, err = proc.communicate(timeout=180)
        assert proc.returncode == 0, f"worker failed:\n{err[-2000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    # the mesh spanned both processes: 2 devices total, the collective
    # crossed the process boundary, and training produced a real loss
    for rank, res in enumerate(results):
        assert res["process_id"] == rank
        assert res["num_processes"] == 2
        assert res["device_count"] == 2
        assert res["collective_sum"] == 2.0
        assert res["loss"] == res["loss"] and res["loss"] > 0
    assert results[0]["loss"] == results[1]["loss"], \
        "ranks disagree on the globally-reduced loss"


def test_multislice_job_trains_across_dcn_axis():
    """Multi-slice e2e (VERDICT r4 #3): two subgrouped worker tasks
    land on two DCN-separated slices; each bound pod's injected env
    launches a REAL jax.distributed process; the workers build the
    hybrid DCN x ICI mesh from TPU_SLICE_ID/TPU_NUM_SLICES and run
    train steps whose gradient psum crosses the dcn axis (process
    boundary = slice boundary here)."""
    reason = multiprocess_cpu_reason()
    if reason:
        pytest.skip(reason)
    # v5e-4 slices: each subgroup's 4-chip worker FILLS its slice, so
    # gang placement must spread the two subgroups across DCN pods
    cluster = make_tpu_cluster([("sa", "v5e-4"), ("sb", "v5e-4")],
                               dcn_pods={"sa": "pod-a", "sb": "pod-b"})
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=["job", "queue"])
    sched = Scheduler(cluster, schedule_period=0)
    job = cluster.add_vcjob(VCJob(
        name="multislice", min_available=2,
        tasks=[TaskSpec(name="slice-a", replicas=1, subgroup="slice-a",
                        template=Pod(name="t", containers=[
                            Container(requests={"cpu": 4, TPU: 4})])),
               TaskSpec(name="slice-b", replicas=1, subgroup="slice-b",
                        template=Pod(name="t", containers=[
                            Container(requests={"cpu": 4, TPU: 4})]))],
        plugins={"jax": [], "svc": []},
    ))
    for _ in range(3):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()
    job = cluster.vcjobs[job.key]
    assert job.phase is JobPhase.RUNNING
    workers = sorted((p for p in cluster.pods.values()
                      if p.owner == job.uid),
                     key=lambda p: p.task_spec)
    assert len(workers) == 2 and all(p.node_name for p in workers)
    # the gang landed one subgroup per slice
    assert {p.node_name.split("-w")[0] for p in workers} == {"sa", "sb"}

    port = free_port()
    procs = []
    for pod in workers:
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)          # 1 CPU device per process
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = REPO
        env.update(pod.containers[0].env)   # the controller's contract
        env["COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"  # DNS stand-in
        env["WORKER_STEPS"] = "2"
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "volcano_tpu.workloads.worker"],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    results = []
    for proc in procs:
        out, err = proc.communicate(timeout=180)
        assert proc.returncode == 0, f"worker failed:\n{err[-2000:]}"
        results.append(json.loads(out.strip().splitlines()[-1]))

    for rank, res in enumerate(results):
        assert res["process_id"] == rank
        assert res["num_processes"] == 2
        assert res["num_slices"] == 2
        assert res["slice_id"] == rank          # one slice per process
        assert res["collective_sum"] == 2.0     # crossed the dcn axis
        assert res["loss"] == res["loss"] and res["loss"] > 0
    assert results[0]["loss"] == results[1]["loss"], \
        "slices disagree on the dcn-reduced loss"
