"""Concurrency stress — the `go test -race` posture of the reference
(Makefile:195): scheduler, agent scheduler, controllers, node agents
and clients all mutating one cluster from separate threads, with
invariants checked at the end.
"""

import itertools
import threading
import time
from collections import defaultdict

from volcano_tpu.agentscheduler import AgentScheduler
from volcano_tpu.api.node_info import Node
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.resource import Resource
from volcano_tpu.api.shard import AGENT_SCHEDULER
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.uthelper import gang_job


def test_concurrent_control_plane_stress():
    cluster = make_tpu_cluster(
        [("sa", "v5e-16")],
        extra_nodes=[Node(name=f"cpu{i}",
                          allocatable={"cpu": 32, "pods": 110})
                     for i in range(4)])
    sched = Scheduler(cluster, schedule_period=0.01)
    agent = AgentScheduler(cluster)
    mgr = ControllerManager(cluster, enabled=["job", "podgroup",
                                              "garbagecollector"])
    stop = threading.Event()
    errors = []

    def guard(fn):
        def run():
            try:
                while not stop.is_set():
                    fn()
            except Exception as e:  # noqa: BLE001
                errors.append(e)
        return run

    counter = itertools.count()  # unique names across client threads

    def client():
        i = next(counter)
        pod = make_pod(f"burst-{i}", requests={"cpu": "100m"})
        pod.scheduler_name = AGENT_SCHEDULER
        cluster.add_pod(pod)
        time.sleep(0.001)

    threads = [
        threading.Thread(target=guard(sched.run_once)),
        threading.Thread(target=guard(agent.run_until_drained)),
        threading.Thread(target=guard(mgr.sync_all)),
        threading.Thread(target=guard(cluster.tick)),
        threading.Thread(target=guard(client)),
        threading.Thread(target=guard(client)),
    ]
    for t in threads:
        t.start()

    # inject batch work mid-flight from the main thread
    for j in range(5):
        pg, pods = gang_job(f"gang{j}", replicas=2,
                            requests={"cpu": 4, "google.com/tpu": 4})
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
        time.sleep(0.05)

    time.sleep(1.0)
    stop.set()
    for t in threads:
        t.join(timeout=10)
        assert not t.is_alive(), f"worker {t.name} hung (deadlock?)"
    mgr.stop()

    assert not errors, f"concurrent errors: {errors!r}"
    # invariants: each pod key bound exactly once, to a known node
    seen = {}
    for key, node in cluster.binds:
        assert key not in seen, \
            f"{key} bound twice ({seen[key]} then {node})"
        seen[key] = node
        assert node in cluster.nodes, f"{key} bound to unknown {node}"
    # no node over its cpu allocatable among RUNNING pods
    used = defaultdict(float)
    for pod in cluster.pods.values():
        if pod.node_name and pod.phase in (TaskStatus.RUNNING,
                                           TaskStatus.BOUND):
            used[pod.node_name] += pod.resource_requests().milli_cpu
    for name, mcpu in used.items():
        node = cluster.nodes[name]   # existence asserted above
        alloc = Resource.from_resource_list(node.allocatable).milli_cpu
        assert mcpu <= alloc + 0.1, \
            f"node {name} overcommitted: {mcpu} > {alloc}"
    # progress happened on both paths
    assert any(k.startswith("default/gang") for k, _ in cluster.binds)
    assert any(k.startswith("default/burst") for k, _ in cluster.binds)
