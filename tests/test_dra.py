"""DRA: resource claims over structured device pools."""

from volcano_tpu.api.node_info import Node
from volcano_tpu.api.queue import Queue
from volcano_tpu.uthelper import TestContext, gang_job

CONF = {"actions": "enqueue, allocate",
        "tiers": [{"plugins": [{"name": "gang"}, {"name": "predicates"},
                               {"name": "dra"}]}]}


def dra_ctx(claims, slices, pods_claims, queues=(), queue_ann=None):
    nodes = [Node(name=n, allocatable={"cpu": 32, "pods": 110})
             for n in slices]
    pgs, pods = [], []
    for i, claim_list in enumerate(pods_claims):
        pg, ps = gang_job(f"j{i}", replicas=1, requests={"cpu": 1},
                          queue=queues[i] if queues else "default")
        ps[0].annotations["dra.volcano-tpu.io/claims"] = ",".join(claim_list)
        pgs.append(pg)
        pods.extend(ps)
    from volcano_tpu.api.queue import Queue as Q
    qs = []
    for qn in set(queues):
        q = Q(name=qn)
        if queue_ann and qn in queue_ann:
            q.annotations.update(queue_ann[qn])
        qs.append(q)
    ctx = TestContext(nodes=nodes, podgroups=pgs, pods=pods, queues=qs,
                      conf=CONF)
    ctx.cluster.resource_slices = dict(slices)
    ctx.cluster.resource_claims = dict(claims)
    return ctx


def test_claim_steers_to_node_with_devices_and_commits():
    ctx = dra_ctx(
        claims={"claim-a": {"class": "tpu-accel", "count": 1,
                            "allocated_node": "", "allocated_devices": []}},
        slices={"n0": [], "n1": [{"name": "d0", "class": "tpu-accel"}]},
        pods_claims=[["claim-a"]])
    ctx.run()
    ctx.expect_bind("default/j0-0", "n1")
    claim = ctx.cluster.resource_claims["claim-a"]
    assert claim["allocated_node"] == "n1"
    assert claim["allocated_devices"] == ["d0"]


def test_two_claims_cannot_share_one_device():
    ctx = dra_ctx(
        claims={"c1": {"class": "tpu-accel", "count": 1,
                       "allocated_node": "", "allocated_devices": []},
                "c2": {"class": "tpu-accel", "count": 1,
                       "allocated_node": "", "allocated_devices": []}},
        slices={"n0": [{"name": "d0", "class": "tpu-accel"}]},
        pods_claims=[["c1"], ["c2"]])
    ctx.run()
    ctx.expect_bind_num(1)   # only one claim can own d0


def test_allocated_claim_pins_node():
    ctx = dra_ctx(
        claims={"pinned": {"class": "tpu-accel", "count": 1,
                           "allocated_node": "n0",
                           "allocated_devices": ["d0"]}},
        slices={"n0": [{"name": "d0", "class": "tpu-accel"}],
                "n1": [{"name": "d1", "class": "tpu-accel"}]},
        pods_claims=[["pinned"]])
    ctx.run()
    ctx.expect_bind("default/j0-0", "n0")


def test_queue_device_quota():
    ctx = dra_ctx(
        claims={"c1": {"class": "tpu-accel", "count": 1,
                       "allocated_node": "", "allocated_devices": []},
                "c2": {"class": "tpu-accel", "count": 1,
                       "allocated_node": "", "allocated_devices": []}},
        slices={"n0": [{"name": "d0", "class": "tpu-accel"},
                       {"name": "d1", "class": "tpu-accel"}]},
        pods_claims=[["c1"], ["c2"]],
        queues=["limited", "limited"],
        queue_ann={"limited": {"dra.volcano-tpu.io/quota.tpu-accel": "1"}})
    ctx.run()
    ctx.expect_bind_num(1)   # quota of 1 device for the queue


def test_unknown_claim_rejected():
    ctx = dra_ctx(claims={}, slices={"n0": []}, pods_claims=[["ghost"]])
    ctx.run()
    ctx.expect_bind_num(0)


def test_device_taints_require_tolerations():
    """DRADeviceTaints: a tainted device is invisible to claims without
    a matching toleration and usable with one."""
    base = {"count": 1, "allocated_node": "", "allocated_devices": []}
    ctx = dra_ctx(
        claims={"plain": dict(base, **{"class": "accel"}),
                "tol": dict(base, **{
                    "class": "accel",
                    "tolerations": [{"key": "maintenance"}]})},
        slices={"n0": [{"name": "d0", "class": "accel",
                        "taints": [{"key": "maintenance",
                                    "value": "fw-upgrade"}]}]},
        pods_claims=[["plain"], ["tol"]])
    ctx.run()
    ctx.expect_bind_num(1)
    assert ctx.cluster.resource_claims["tol"]["allocated_devices"] == ["d0"]
    assert not ctx.cluster.resource_claims["plain"]["allocated_node"]


def test_prioritized_class_list_first_available():
    """DRAPrioritizedList: the claim prefers v5p devices but falls back
    to v5e where none exist; the winning class is recorded."""
    ctx = dra_ctx(
        claims={"flex": {"class_priorities": ["v5p-accel", "v5e-accel"],
                         "count": 1, "allocated_node": "",
                         "allocated_devices": []}},
        slices={"n0": [{"name": "e0", "class": "v5e-accel"}],
                "n1": []},
        pods_claims=[["flex"]])
    ctx.run()
    ctx.expect_bind("default/j0-0", "n0")
    claim = ctx.cluster.resource_claims["flex"]
    assert claim["allocated_class"] == "v5e-accel"

    # preferred class present on another node -> it wins over fallback
    ctx2 = dra_ctx(
        claims={"flex": {"class_priorities": ["v5p-accel", "v5e-accel"],
                         "count": 1, "allocated_node": "",
                         "allocated_devices": []}},
        slices={"n0": [{"name": "e0", "class": "v5e-accel"}],
                "n1": [{"name": "p0", "class": "v5p-accel"}]},
        pods_claims=[["flex"]])
    ctx2.run()
    # both nodes pass the predicate; scoring ties — either is legal,
    # but the allocated class must match the node's device class
    claim = ctx2.cluster.resource_claims["flex"]
    node = claim["allocated_node"]
    assert claim["allocated_class"] == (
        "v5p-accel" if node == "n1" else "v5e-accel")


def test_admin_access_attaches_without_consuming_capacity():
    """DRAAdminAccess (gated off by default): an admin claim from a
    flagged namespace rides along on an owned device; a regular claim
    still gets the device."""
    from volcano_tpu import features

    base = {"count": 1, "allocated_node": "", "allocated_devices": []}
    ctx = dra_ctx(
        claims={"work": dict(base, **{"class": "accel"}),
                "probe": dict(base, **{"class": "accel",
                                       "admin_access": True,
                                       "namespace": "monitoring"})},
        slices={"n0": [{"name": "d0", "class": "accel"}]},
        pods_claims=[["work"], ["probe"]])
    ctx.cluster.admin_namespaces = {"monitoring"}
    features.set_gate("DRAAdminAccess", True)
    try:
        ctx.run()
    finally:
        features.reset("DRAAdminAccess")
    ctx.expect_bind_num(2)
    work = ctx.cluster.resource_claims["work"]
    probe = ctx.cluster.resource_claims["probe"]
    assert work["allocated_devices"] == ["d0"]
    assert probe["allocated_node"] == "n0"
    assert probe["allocated_devices"] == ["d0"]   # rides along


def test_admin_access_denied_without_gate_or_namespace():
    """Admin access requires BOTH the feature gate and the namespace
    flag; otherwise the claim competes normally (and loses a taken
    device)."""
    base = {"count": 1, "allocated_node": "", "allocated_devices": []}
    ctx = dra_ctx(
        claims={"work": dict(base, **{"class": "accel"}),
                "probe": dict(base, **{"class": "accel",
                                       "admin_access": True,
                                       "namespace": "monitoring"})},
        slices={"n0": [{"name": "d0", "class": "accel"}]},
        pods_claims=[["work"], ["probe"]])
    # gate off (default): admin flag is inert -> normal contention
    ctx.run()
    ctx.expect_bind_num(1)


def test_taints_ignored_when_gate_off():
    """DRADeviceTaints=false restores pre-feature semantics: taints are
    ignored, tainted devices stay usable by toleration-less claims."""
    from volcano_tpu import features
    ctx = dra_ctx(
        claims={"plain": {"class": "accel", "count": 1,
                          "allocated_node": "", "allocated_devices": []}},
        slices={"n0": [{"name": "d0", "class": "accel",
                        "taints": [{"key": "maintenance"}]}]},
        pods_claims=[["plain"]])
    features.set_gate("DRADeviceTaints", False)
    try:
        ctx.run()
    finally:
        features.reset("DRADeviceTaints")
    ctx.expect_bind("default/j0-0", "n0")


def test_prioritized_class_respects_queue_quota_consistently():
    """A quota-exhausted preferred class falls through to the fallback
    class in BOTH predicate and allocation (the same picker runs in
    both, so allocated_class can never violate the quota the predicate
    enforced)."""
    ctx = dra_ctx(
        claims={"flex": {"class_priorities": ["v5p-accel", "v5e-accel"],
                         "count": 1, "allocated_node": "",
                         "allocated_devices": []}},
        slices={"n0": [{"name": "p0", "class": "v5p-accel"},
                       {"name": "e0", "class": "v5e-accel"}]},
        pods_claims=[["flex"]], queues=("q1",),
        queue_ann={"q1": {"dra.volcano-tpu.io/quota.v5p-accel": "0"}})
    ctx.run()
    ctx.expect_bind("default/j0-0", "n0")
    claim = ctx.cluster.resource_claims["flex"]
    assert claim["allocated_class"] == "v5e-accel"
    assert claim["allocated_devices"] == ["e0"]
