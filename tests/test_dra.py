"""DRA: resource claims over structured device pools."""

from volcano_tpu.api.node_info import Node
from volcano_tpu.api.queue import Queue
from volcano_tpu.uthelper import TestContext, gang_job

CONF = {"actions": "enqueue, allocate",
        "tiers": [{"plugins": [{"name": "gang"}, {"name": "predicates"},
                               {"name": "dra"}]}]}


def dra_ctx(claims, slices, pods_claims, queues=(), queue_ann=None):
    nodes = [Node(name=n, allocatable={"cpu": 32, "pods": 110})
             for n in slices]
    pgs, pods = [], []
    for i, claim_list in enumerate(pods_claims):
        pg, ps = gang_job(f"j{i}", replicas=1, requests={"cpu": 1},
                          queue=queues[i] if queues else "default")
        ps[0].annotations["dra.volcano-tpu.io/claims"] = ",".join(claim_list)
        pgs.append(pg)
        pods.extend(ps)
    from volcano_tpu.api.queue import Queue as Q
    qs = []
    for qn in set(queues):
        q = Q(name=qn)
        if queue_ann and qn in queue_ann:
            q.annotations.update(queue_ann[qn])
        qs.append(q)
    ctx = TestContext(nodes=nodes, podgroups=pgs, pods=pods, queues=qs,
                      conf=CONF)
    ctx.cluster.resource_slices = dict(slices)
    ctx.cluster.resource_claims = dict(claims)
    return ctx


def test_claim_steers_to_node_with_devices_and_commits():
    ctx = dra_ctx(
        claims={"claim-a": {"class": "tpu-accel", "count": 1,
                            "allocated_node": "", "allocated_devices": []}},
        slices={"n0": [], "n1": [{"name": "d0", "class": "tpu-accel"}]},
        pods_claims=[["claim-a"]])
    ctx.run()
    ctx.expect_bind("default/j0-0", "n1")
    claim = ctx.cluster.resource_claims["claim-a"]
    assert claim["allocated_node"] == "n1"
    assert claim["allocated_devices"] == ["d0"]


def test_two_claims_cannot_share_one_device():
    ctx = dra_ctx(
        claims={"c1": {"class": "tpu-accel", "count": 1,
                       "allocated_node": "", "allocated_devices": []},
                "c2": {"class": "tpu-accel", "count": 1,
                       "allocated_node": "", "allocated_devices": []}},
        slices={"n0": [{"name": "d0", "class": "tpu-accel"}]},
        pods_claims=[["c1"], ["c2"]])
    ctx.run()
    ctx.expect_bind_num(1)   # only one claim can own d0


def test_allocated_claim_pins_node():
    ctx = dra_ctx(
        claims={"pinned": {"class": "tpu-accel", "count": 1,
                           "allocated_node": "n0",
                           "allocated_devices": ["d0"]}},
        slices={"n0": [{"name": "d0", "class": "tpu-accel"}],
                "n1": [{"name": "d1", "class": "tpu-accel"}]},
        pods_claims=[["pinned"]])
    ctx.run()
    ctx.expect_bind("default/j0-0", "n0")


def test_queue_device_quota():
    ctx = dra_ctx(
        claims={"c1": {"class": "tpu-accel", "count": 1,
                       "allocated_node": "", "allocated_devices": []},
                "c2": {"class": "tpu-accel", "count": 1,
                       "allocated_node": "", "allocated_devices": []}},
        slices={"n0": [{"name": "d0", "class": "tpu-accel"},
                       {"name": "d1", "class": "tpu-accel"}]},
        pods_claims=[["c1"], ["c2"]],
        queues=["limited", "limited"],
        queue_ann={"limited": {"dra.volcano-tpu.io/quota.tpu-accel": "1"}})
    ctx.run()
    ctx.expect_bind_num(1)   # quota of 1 device for the queue


def test_unknown_claim_rejected():
    ctx = dra_ctx(claims={}, slices={"n0": []}, pods_claims=[["ghost"]])
    ctx.run()
    ctx.expect_bind_num(0)
