

def test_fuzz_numa_deduct_reversal_exact():
    """deduct_request records must reverse exactly: after any sequence
    of deducts and replayed reversals, cells return to their initial
    state bit-for-bit (the invariant the numaaware plugin and the
    statement rollback machinery rely on)."""
    import random
    from volcano_tpu.api.numatopology import deduct_request
    rng = random.Random(42)
    for _ in range(300):
        n = rng.randint(1, 4)
        cells = [[float(rng.randint(0, 8000)), float(rng.randint(0, 8))]
                 for _ in range(n)]
        initial = [list(c) for c in cells]
        log = []
        for _ in range(rng.randint(1, 6)):
            taken = deduct_request(cells, float(rng.randint(0, 6000)),
                                   float(rng.randint(0, 6)))
            log.append(taken)
            for c in cells:
                assert c[0] >= -1e-9 and c[1] >= -1e-9, \
                    f"negative cell after deduct: {cells}"
        for taken in reversed(log):
            for i, cpu, tpu in reversed(taken):
                cells[i][0] += cpu
                cells[i][1] += tpu
        assert cells == initial, (initial, cells)


def test_fuzz_numa_exporter_vs_plugin_agreement():
    """The exporter's recompute_free and the plugin's in-session
    deductions are the same algorithm: republishing after N bindings
    equals deducting those N requests in arrival (size-desc) order."""
    import random
    from volcano_tpu.api.numatopology import (
        Numatopology, deduct_request)
    rng = random.Random(7)
    for _ in range(100):
        ncells = rng.randint(1, 4)
        cap = {str(i): float(rng.randint(1000, 8000))
               for i in range(ncells)}
        chips = {str(i): float(rng.randint(0, 4)) for i in range(ncells)}
        topo = Numatopology(
            name="n", numa_res={},
            capacity_res={"cpu": dict(cap), "google.com/tpu": dict(chips)})
        reqs = [(float(rng.randint(0, 4000)), float(rng.randint(0, 2)))
                for _ in range(rng.randint(0, 5))]
        topo.recompute_free(reqs)
        cells = sorted(cap)
        manual = [[cap[c], chips[c]] for c in cells]
        for cpu, tpu in sorted(reqs, key=lambda r: -(r[0] + r[1])):
            deduct_request(manual, cpu, tpu)
        for i, c in enumerate(cells):
            assert topo.numa_res["cpu"][c] == manual[i][0]
            assert topo.numa_res["google.com/tpu"][c] == manual[i][1]
