"""Mixture-of-experts: routing, expert-parallel sharding, training."""

import jax
import jax.numpy as jnp
import numpy as np

from volcano_tpu.workloads import model as model_lib
from volcano_tpu.workloads import train
from volcano_tpu.workloads.mesh import make_mesh


def moe_config(**kw):
    return model_lib.tiny_config(n_experts=4, n_layers=2, **kw)


def test_moe_params_and_specs():
    cfg = moe_config()
    params = model_lib.init_params(jax.random.key(0), cfg)
    assert "router" not in params["blocks"][0]     # even layer dense
    assert "router" in params["blocks"][1]         # odd layer MoE
    assert params["blocks"][1]["moe_gate"].shape == (4, 64, 128)
    specs = model_lib.param_specs(params)
    gate_spec = specs["blocks"][1]["moe_gate"]
    assert gate_spec == jax.sharding.PartitionSpec("fsdp", None, "tp")


def test_moe_forward_finite_and_aux_positive():
    cfg = moe_config()
    params = model_lib.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0,
                                cfg.vocab_size)
    logits, aux = model_lib.forward_with_aux(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()
    # uniform-routing lower bound is 1.0 (E * sum(1/E * 1/E) * E)
    assert float(aux) >= 1.0 - 1e-3


def test_moe_routing_actually_selects_topk():
    """Zeroing one expert's weights must change only tokens routed to it."""
    cfg = moe_config(expert_top_k=1)
    params = model_lib.init_params(jax.random.key(0), cfg)
    tokens = jax.random.randint(jax.random.key(1), (1, 16), 0,
                                cfg.vocab_size)
    base = model_lib.forward(params, tokens, cfg)
    p2 = dict(params)
    p2["blocks"] = [dict(b) for b in params["blocks"]]
    p2["blocks"][1]["moe_down"] = params["blocks"][1]["moe_down"] * 0.0
    changed = model_lib.forward(p2, tokens, cfg)
    # zeroing the routed experts' down-projection must alter the output
    assert not np.allclose(np.asarray(base), np.asarray(changed))


def test_moe_sharded_training_descends():
    mesh = make_mesh({"dp": 1, "fsdp": 2, "tp": 2, "sp": 2})
    cfg = moe_config(use_ring_attention=True)
    opt = train.make_optimizer(lr=1e-2, warmup_steps=1)
    params, state, _ = train.init_sharded(jax.random.key(0), cfg, mesh,
                                          opt)
    step = train.make_train_step(cfg, mesh, opt)
    batch = train.synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    losses = []
    for _ in range(3):
        params, state, metrics = step(params, state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_capacity_dispatch_matches_dense_when_roomy():
    """With capacity generous enough that nothing drops, the GShard
    dispatch path must equal the dense path exactly."""
    from volcano_tpu.workloads.moe import moe_mlp, init_moe_params
    d, f, E = 32, 64, 4
    params = init_moe_params(jax.random.key(0), d, f, E, 0.1)
    x = jax.random.normal(jax.random.key(1), (2, 16, d))
    dense, aux_d = moe_mlp(x, params, E, top_k=2, capacity_factor=0.0)
    # cf covering the worst case (all tokens to one expert)
    roomy, aux_c = moe_mlp(x, params, E, top_k=2,
                           capacity_factor=float(E))
    np.testing.assert_allclose(np.asarray(dense), np.asarray(roomy),
                               atol=1e-5, rtol=1e-5)
    assert float(aux_d) == float(aux_c)


def test_capacity_dispatch_drops_overflow_finite():
    from volcano_tpu.workloads.moe import moe_mlp, init_moe_params
    d, f, E = 32, 64, 4
    params = init_moe_params(jax.random.key(0), d, f, E, 0.1)
    x = jax.random.normal(jax.random.key(1), (2, 64, d))
    tight, _ = moe_mlp(x, params, E, top_k=2, capacity_factor=0.5)
    assert np.isfinite(np.asarray(tight)).all()
    # tight capacity must differ from dense (some tokens dropped)
    dense, _ = moe_mlp(x, params, E, top_k=2, capacity_factor=0.0)
    assert not np.allclose(np.asarray(tight), np.asarray(dense))


def test_capacity_moe_sharded_training_descends():
    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2, "sp": 1})
    cfg = moe_config(moe_capacity_factor=1.25)
    opt = train.make_optimizer(lr=1e-2, warmup_steps=1)
    params, state, _ = train.init_sharded(jax.random.key(0), cfg, mesh,
                                          opt)
    step = train.make_train_step(cfg, mesh, opt)
    batch = train.synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)
    losses = []
    for _ in range(3):
        params, state, metrics = step(params, state, batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
