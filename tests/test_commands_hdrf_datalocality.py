"""Command bus (suspend/resume), hdrf, data locality, colocation
config, metrics endpoint."""

import urllib.request

from volcano_tpu.api.node_info import Node
from volcano_tpu.api.pod import Container, Pod
from volcano_tpu.api.queue import Queue
from volcano_tpu.api.types import JobPhase, PodGroupPhase
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.cache.fake_cluster import FakeCluster
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster
from volcano_tpu.uthelper import TestContext, gang_job
from volcano_tpu.webhooks import default_admission


def stack():
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.admission = default_admission()
    mgr = ControllerManager(cluster, enabled=["job"])
    sched = Scheduler(cluster, schedule_period=0)
    return cluster, mgr, sched


def simple_job(name="j", replicas=2):
    return VCJob(name=name, min_available=replicas,
                 tasks=[TaskSpec(name="w", replicas=replicas,
                                 template=Pod(name="t", containers=[
                                     Container(requests={"cpu": 1})]))])


def pump(cluster, mgr, sched, n=3):
    for _ in range(n):
        mgr.sync_all()
        sched.run_once()
        cluster.tick()


def test_suspend_resume_via_command_bus():
    cluster, mgr, sched = stack()
    job = cluster.add_vcjob(simple_job())
    pump(cluster, mgr, sched)
    assert cluster.vcjobs[job.key].phase is JobPhase.RUNNING

    cluster.add_command(job.key, "AbortJob")     # vtpctl job suspend
    pump(cluster, mgr, sched)
    assert cluster.vcjobs[job.key].phase is JobPhase.ABORTED
    assert not [p for p in cluster.pods.values() if p.owner == job.uid]

    cluster.add_command(job.key, "ResumeJob")    # vtpctl job resume
    pump(cluster, mgr, sched, n=4)
    j = cluster.vcjobs[job.key]
    assert j.phase is JobPhase.RUNNING
    assert j.version == 1


def test_hdrf_orders_by_queue_path_share():
    """Hierarchical DRF: jobs in the less-consumed subtree go first."""
    from volcano_tpu.cache.cache import SchedulerCache
    from volcano_tpu.conf import load_conf
    from volcano_tpu.framework.framework import close_session, open_session

    cluster = FakeCluster()
    for i in range(2):
        cluster.add_node(Node(name=f"n{i}", allocatable={"cpu": 8}))
    cluster.add_queue(Queue(name="org-a"))
    cluster.add_queue(Queue(name="team-a1", parent="org-a"))
    cluster.add_queue(Queue(name="org-b"))
    # org-a already consumes half the cluster
    pg_run, pods_run = gang_job("hog", queue="team-a1", replicas=2,
                                requests={"cpu": 4},
                                running_on=["n0", "n1"],
                                pg_phase=PodGroupPhase.RUNNING)
    pg_a, pods_a = gang_job("next-a", queue="team-a1", replicas=1,
                            requests={"cpu": 4})
    pg_b, pods_b = gang_job("next-b", queue="org-b", replicas=1,
                            requests={"cpu": 4})
    for pg, pods in [(pg_run, pods_run), (pg_a, pods_a), (pg_b, pods_b)]:
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
    conf = load_conf({
        "actions": "enqueue, allocate",
        "tiers": [{"plugins": [
            {"name": "gang"},
            {"name": "drf", "arguments": {"drf.enable-hierarchy": True}},
            {"name": "predicates"}, {"name": "nodeorder"}]}]})
    ssn = open_session(SchedulerCache(cluster), conf)
    job_a = next(j for j in ssn.jobs.values() if j.name == "next-a")
    job_b = next(j for j in ssn.jobs.values() if j.name == "next-b")
    # org-b's path share (0) < org-a's (0.5): next-b sorts first
    assert ssn.job_order_fn(job_b, job_a)
    assert not ssn.job_order_fn(job_a, job_b)
    close_session(ssn)


def test_hdrf_hierarchy_weights_divide_level_shares():
    """A weight-3 subtree tolerates 3x the share of a weight-1
    sibling (drf.go:174,462-470): eng consumes MORE raw share than
    sci but still orders first because 0.5/3 < 0.25/1."""
    from volcano_tpu.cache.cache import SchedulerCache
    from volcano_tpu.conf import load_conf
    from volcano_tpu.framework.framework import close_session, open_session
    from volcano_tpu.webhooks.admission import (
        HIERARCHY_ANNOTATION, HIERARCHY_WEIGHTS_ANNOTATION)

    cluster = FakeCluster()
    for i in range(2):
        cluster.add_node(Node(name=f"n{i}", allocatable={"cpu": 8}))
    cluster.add_queue(Queue(name="eng", annotations={
        HIERARCHY_ANNOTATION: "root/eng",
        HIERARCHY_WEIGHTS_ANNOTATION: "1/3"}))
    cluster.add_queue(Queue(name="sci", annotations={
        HIERARCHY_ANNOTATION: "root/sci",
        HIERARCHY_WEIGHTS_ANNOTATION: "1/1"}))
    pg_e, pods_e = gang_job("eng-hog", queue="eng", replicas=2,
                            requests={"cpu": 4}, running_on=["n0", "n0"],
                            pg_phase=PodGroupPhase.RUNNING)
    pg_s, pods_s = gang_job("sci-hog", queue="sci", replicas=1,
                            requests={"cpu": 4}, running_on=["n1"],
                            pg_phase=PodGroupPhase.RUNNING)
    pg_a, pods_a = gang_job("next-eng", queue="eng", replicas=1,
                            requests={"cpu": 2})
    pg_b, pods_b = gang_job("next-sci", queue="sci", replicas=1,
                            requests={"cpu": 2})
    for pg, pods in [(pg_e, pods_e), (pg_s, pods_s),
                     (pg_a, pods_a), (pg_b, pods_b)]:
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
    conf = load_conf({
        "actions": "enqueue, allocate",
        "tiers": [{"plugins": [
            {"name": "gang"},
            {"name": "drf", "arguments": {"drf.enable-hierarchy": True}},
            {"name": "predicates"}, {"name": "nodeorder"}]}]})
    ssn = open_session(SchedulerCache(cluster), conf)
    job_a = next(j for j in ssn.jobs.values() if j.name == "next-eng")
    job_b = next(j for j in ssn.jobs.values() if j.name == "next-sci")
    assert ssn.job_order_fn(job_a, job_b)      # eng first despite 0.5 raw
    assert not ssn.job_order_fn(job_b, job_a)
    close_session(ssn)


def test_hdrf_weights_key_by_path_not_segment_name():
    """Two subtrees reusing a child segment NAME with different
    weights ('root/a/team' 1/1/5 vs 'root/b/team' 1/1/1) must not
    collide: weights key on the full path prefix (reference drf.go
    buildHierarchy keys per hierarchy node).  With the old bare-name
    map, first declaration won and both 'team' nodes shared one
    weight, making this ordering a tie."""
    from volcano_tpu.cache.cache import SchedulerCache
    from volcano_tpu.conf import load_conf
    from volcano_tpu.framework.framework import close_session, open_session
    from volcano_tpu.webhooks.admission import (
        HIERARCHY_ANNOTATION, HIERARCHY_WEIGHTS_ANNOTATION)

    cluster = FakeCluster()
    for i in range(2):
        cluster.add_node(Node(name=f"n{i}", allocatable={"cpu": 8}))
    # equal raw consumption in both subtrees: every level of the two
    # path-share vectors ties EXCEPT the reused 'team' segment, whose
    # weight (5 vs 1) is the only discriminator left
    cluster.add_queue(Queue(name="qa", annotations={
        HIERARCHY_ANNOTATION: "root/a/team",
        HIERARCHY_WEIGHTS_ANNOTATION: "1/1/5"}))
    cluster.add_queue(Queue(name="qb", annotations={
        HIERARCHY_ANNOTATION: "root/b/team",
        HIERARCHY_WEIGHTS_ANNOTATION: "1/1/1"}))
    pg_a, pods_a = gang_job("hog-a", queue="qa", replicas=1,
                            requests={"cpu": 4}, running_on=["n0"],
                            pg_phase=PodGroupPhase.RUNNING)
    pg_b, pods_b = gang_job("hog-b", queue="qb", replicas=1,
                            requests={"cpu": 4}, running_on=["n1"],
                            pg_phase=PodGroupPhase.RUNNING)
    pg_na, pods_na = gang_job("next-a", queue="qa", replicas=1,
                              requests={"cpu": 2})
    pg_nb, pods_nb = gang_job("next-b", queue="qb", replicas=1,
                              requests={"cpu": 2})
    for pg, pods in [(pg_a, pods_a), (pg_b, pods_b),
                     (pg_na, pods_na), (pg_nb, pods_nb)]:
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
    conf = load_conf({
        "actions": "enqueue, allocate",
        "tiers": [{"plugins": [
            {"name": "gang"},
            {"name": "drf", "arguments": {"drf.enable-hierarchy": True}},
            {"name": "predicates"}, {"name": "nodeorder"}]}]})
    ssn = open_session(SchedulerCache(cluster), conf)
    job_a = next(j for j in ssn.jobs.values() if j.name == "next-a")
    job_b = next(j for j in ssn.jobs.values() if j.name == "next-b")
    # a's team node tolerates 5x the share: next-a orders strictly
    # first despite equal raw consumption everywhere
    assert ssn.job_order_fn(job_a, job_b)
    assert not ssn.job_order_fn(job_b, job_a)
    close_session(ssn)


def test_datalocality_scores_and_hard_mode():
    nodes = [Node(name="data0", allocatable={"cpu": 8}),
             Node(name="far0", allocatable={"cpu": 8})]
    pg, pods = gang_job("trainer", replicas=1, requests={"cpu": 1})
    pods[0].annotations["data.volcano-tpu.io/claims"] = "imagenet"
    ctx = TestContext(nodes=nodes, podgroups=[pg], pods=pods,
                      conf={"actions": "enqueue, allocate",
                            "tiers": [{"plugins": [
                                {"name": "gang"}, {"name": "predicates"},
                                {"name": "datalocality"}]}]})
    ctx.cluster.datasources = {"imagenet": {"nodes": ["data0"]}}
    ctx.run()
    ctx.expect_bind("default/trainer-0", "data0")

    # hard mode: no local node -> unschedulable
    pg2, pods2 = gang_job("strict", replicas=1, requests={"cpu": 1})
    pods2[0].annotations["data.volcano-tpu.io/claims"] = "imagenet"
    pods2[0].annotations["data.volcano-tpu.io/claim-mode"] = "hard"
    ctx2 = TestContext(nodes=[Node(name="far0", allocatable={"cpu": 8})],
                       podgroups=[pg2], pods=pods2,
                       conf={"actions": "enqueue, allocate",
                             "tiers": [{"plugins": [
                                 {"name": "gang"}, {"name": "predicates"},
                                 {"name": "datalocality"}]}]})
    ctx2.cluster.datasources = {"imagenet": {"nodes": ["data0"]}}
    ctx2.run()
    ctx2.expect_bind_num(0)


def test_colocation_config_pushes_to_agents():
    from volcano_tpu.agent import NodeAgent
    from volcano_tpu.controllers.colocation import ColocationConfigController
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    agent = NodeAgent(cluster, "sa-w0")
    ctrl = ColocationConfigController()
    ctrl.initialize(cluster)
    ctrl.register_agent(agent)
    cluster.config_maps["colocation/config"] = {
        "oversub-factor": "0.9", "eviction-threshold": "0.8"}
    ctrl.sync()
    assert agent.oversub_factor == 0.9
    assert agent.eviction_threshold == 0.8


def test_metrics_http_endpoint():
    from volcano_tpu import metrics
    metrics.inc("test_requests_total", 3)
    server = metrics.serve(port=0)
    try:
        port = server.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "test_requests_total 3" in body
    finally:
        server.shutdown()
