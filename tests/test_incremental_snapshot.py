"""Incremental-snapshot equivalence (VERDICT r2 item 7).

The dirty-tracked snapshot (cache.py _build_incremental) must be
indistinguishable — for every piece of state the scheduler reads —
from a from-scratch rebuild, under arbitrary interleavings of job
churn, binds, ticks, completions, evictions, node add/remove and
agent-style annotation patches.  A divergence here is the
"silently double-counts resources" failure mode SURVEY §7 warns
about, so the fuzzer compares EVERY cycle.
"""

import random

from volcano_tpu import features
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import RUN_TICKS_ANNOTATION, TaskStatus
from volcano_tpu.cache.cache import SchedulerCache
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import make_tpu_cluster, slice_nodes
from volcano_tpu.api.devices.tpu.topology import slice_for
from volcano_tpu.uthelper import gang_job


def snapshot_state(snap):
    """Everything the scheduler reads, in comparable form."""
    nodes = {}
    for name, ni in snap.nodes.items():
        nodes[name] = {
            "idle": dict(ni.idle.res),
            "used": dict(ni.used.res),
            "releasing": dict(ni.releasing.res),
            "pipelined": dict(ni.pipelined.res),
            "oversub": dict(ni.oversubscription.res),
            "tasks": sorted((uid, t.status.value)
                            for uid, t in ni.tasks.items()),
            "ports": dict(ni.occupied_ports),
            "unschedulable": ni.node.unschedulable if ni.node else False,
        }
    jobs = {}
    for uid, job in snap.jobs.items():
        jobs[uid] = {
            "queue": job.queue,
            "min_available": job.min_available,
            "tasks": sorted((t_uid, t.status.value, t.node_name)
                            for t_uid, t in job.tasks.items()),
        }
    return {"nodes": nodes, "jobs": jobs,
            "queues": sorted(snap.queues),
            "total": dict(snap.total_resource().res)}


def assert_equivalent(cluster, sched, context):
    incremental = sched.cache.snapshot()       # next cycle's view
    fresh = SchedulerCache(cluster)            # no history: full build
    full = fresh.snapshot()
    cluster.unwatch(fresh._on_cluster_event)
    a, b = snapshot_state(incremental), snapshot_state(full)
    assert a == b, f"divergence after {context}"


def test_incremental_snapshot_fuzz_equivalence():
    assert features.enabled("IncrementalSnapshot")
    rng = random.Random(20260729)
    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    sched = Scheduler(cluster)
    next_job = [0]
    extra_nodes = []

    def submit_job():
        j = next_job[0]
        next_job[0] += 1
        replicas = rng.choice([1, 2, 4])
        pg, pods = gang_job(f"fz{j}", replicas=replicas,
                            requests={"cpu": 4, TPU: rng.choice([0, 4])})
        cluster.add_podgroup(pg)
        for p in pods:
            if rng.random() < 0.5:
                p.annotations[RUN_TICKS_ANNOTATION] = \
                    str(rng.randint(1, 3))
            cluster.add_pod(p)

    def complete_pod():
        running = [p for p in cluster.pods.values()
                   if p.phase is TaskStatus.RUNNING]
        if running:
            cluster.complete_pod(rng.choice(running).key,
                                 succeeded=rng.random() < 0.9)

    def evict_pod():
        running = [p for p in cluster.pods.values()
                   if p.phase is TaskStatus.RUNNING]
        if running:
            p = rng.choice(running)
            cluster.evict_pod(p.namespace, p.name, "fuzz")

    def delete_group():
        keys = [k for k in cluster.podgroups if k.startswith("default/fz")]
        if keys:
            key = rng.choice(keys)
            for p in [p for p in cluster.pods.values()
                      if p.annotations.get(
                          "scheduling.volcano-tpu.io/group-name")
                      == key.split("/", 1)[1]]:
                cluster.delete_pod(p.key)
            cluster.delete_podgroup(key)

    def patch_node():
        # agent-style annotation write (usage/oversubscription)
        name = rng.choice(sorted(cluster.nodes))
        node = cluster.nodes[name]
        node.annotations[
            "oversubscription.volcano-tpu.io/cpu-millis"] = \
            str(rng.choice([0, 8000, 16000]))
        cluster.put_object("node", node)

    def add_node():
        i = len(extra_nodes)
        fresh = slice_nodes(slice_for(f"x{i}", "v5e-4"))
        for n in fresh:
            cluster.add_node(n)
            extra_nodes.append(n.name)

    def remove_node():
        if extra_nodes:
            cluster.remove_node(extra_nodes.pop())

    ops = [submit_job, submit_job, complete_pod, evict_pod,
           delete_group, patch_node, cluster.tick, add_node,
           remove_node]
    for step in range(60):
        for _ in range(rng.randint(1, 4)):
            rng.choice(ops)()
        sched.run_once()
        cluster.tick()
        assert_equivalent(cluster, sched, f"step {step}")


def test_incremental_idle_cycles_reuse_everything():
    """Steady state: after the first build, an idle cycle must reuse
    every node and every steady job object (the perf contract)."""
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pg, pods = gang_job("steady", replicas=4,
                        requests={"cpu": 4, TPU: 4})
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    sched = Scheduler(cluster)
    sched.run_once()            # schedules the gang
    cluster.tick()              # Bound -> Running
    sched.run_once()            # settles status flushes
    cluster.tick()

    first = sched.cache.snapshot()
    second = sched.cache.snapshot()
    assert all(second.nodes[n] is first.nodes[n] for n in first.nodes)
    assert all(second.jobs[j] is first.jobs[j] for j in first.jobs)


def test_control_kind_deletion_forces_rebuild():
    """Deleting a priority class (or any control kind) must invalidate
    steady jobs — a stale job.priority would skew preemption ordering
    indefinitely (ADVICE r3 medium)."""
    from volcano_tpu.cache.cluster import PriorityClass
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.add_priority_class(PriorityClass("gold", value=1000))
    pg, pods = gang_job("vip", replicas=2, requests={"cpu": 2},
                        priority_class="gold")
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    sched = Scheduler(cluster)
    sched.run_once()
    cluster.tick()
    snap = sched.cache.snapshot()
    job = next(j for j in snap.jobs.values() if j.name == "vip")
    assert job.priority == 1000

    cluster.delete_object("priority_class", "gold")
    snap2 = sched.cache.snapshot()
    job2 = next(j for j in snap2.jobs.values() if j.name == "vip")
    assert job2.priority == 0, \
        "priority_class deletion left a stale job.priority"
    assert_equivalent(cluster, sched, "priority_class deletion")


def test_incremental_gate_off_matches():
    """The escape hatch: IncrementalSnapshot=false forces full rebuild
    every cycle."""
    features.set_gate("IncrementalSnapshot", False)
    try:
        cluster = make_tpu_cluster([("sa", "v5e-16")])
        sched = Scheduler(cluster)
        sched.run_once()
        a = sched.cache.snapshot()
        b = sched.cache.snapshot()
        assert all(b.nodes[n] is not a.nodes[n] for n in a.nodes)
    finally:
        features.reset("IncrementalSnapshot")
