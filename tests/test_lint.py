"""vtplint + LockAudit: the invariant gate, in tier-1.

Four layers, each pinned so the linter itself cannot rot:

  1. the full tree lints clean — ``tools/vtplint.py --strict``
     semantics in-process (AST rules + flakes + registry checks),
     with ZERO unsuppressed findings and ZERO unexplained
     suppressions;
  2. per-rule broken fixtures — one minimal violating snippet per
     shipped rule, asserted to be CAUGHT (a rule that silently stops
     firing is worse than no rule);
  3. the metric label schema over a LIVE exposition — one real
     scheduling session covering the trace/elastic/goodput families,
     validated wholesale against bundle.FAMILY_LABELS.  This is the
     linter-driven replacement for the three per-PR label-cardinality
     tests (test_trace/test_elastic/test_goodput) it deduplicated;
  4. the runtime lock-order auditor — synthetic inversion/guard
     fixtures plus a real in-process server+scheduler drive under
     audit with an empty violation report (the chaos conductor's
     ``--lock-audit`` runs the same audit across the process plane).
"""

import json
import os
import subprocess
import sys

import pytest

from volcano_tpu import metrics, trace
from volcano_tpu.analysis import (astlint, flakes, freezeaudit,
                                  lockaudit, racecheck, registry)
from volcano_tpu.analysis.schema import check_exposition

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT_PATHS = [os.path.join(REPO, "volcano_tpu"),
              os.path.join(REPO, "tools")]


@pytest.fixture(autouse=True)
def _clean_registries():
    metrics.reset()
    trace.reset()
    yield
    metrics.reset()
    trace.reset()


# The full-tree passes are pure functions of the working tree: run
# each ONCE per pytest session and let every assertion share the
# result — the growing rule set must not grow the gate's wall time
# (the CLI run below additionally exercises the on-disk
# .vtplint_cache/ increment).
@pytest.fixture(scope="module")
def tree_findings():
    return astlint.lint_paths(LINT_PATHS)


@pytest.fixture(scope="module")
def race_pass():
    prog = racecheck.build_program(LINT_PATHS)
    return prog, prog.analyze()


@pytest.fixture(scope="module")
def race_findings(race_pass):
    return race_pass[1]


# -- 1. the tree is clean ----------------------------------------------

def test_vtplint_strict_tree_is_clean(tree_findings):
    active = [f for f in tree_findings if f.suppressed is None]
    assert not active, "\n".join(f.format() for f in active)


def test_racecheck_tree_is_clean(race_findings):
    active = [f for f in race_findings if f.suppressed is None]
    assert not active, "\n".join(f.format() for f in active)


def test_racecheck_classifies_the_reader_trees(race_pass):
    """The ownership pass must actually see the sweep: the predicate/
    score plugin callbacks and the sweep machinery classify as
    snapshot-readers (an empty reader set would make rule silence
    vacuous)."""
    prog, _ = race_pass
    readers = set(prog.readers())
    for needle in (
            "volcano_tpu/actions/util.py:fit_class",
            "volcano_tpu/actions/util.py:predicate_nodes",
            "volcano_tpu/actions/sweep.py:sweep_shard",
            "volcano_tpu/plugins/predicates.py:"
            "PredicatesPlugin._predicate",
            "volcano_tpu/plugins/nodeorder.py:"
            "NodeOrderPlugin._score",
            "volcano_tpu/framework/session.py:"
            "Session._run_predicates"):
        assert any(r.endswith(needle) for r in readers), needle
    # ...and the mutation seams are NOT readers
    assert not any(r.endswith("Session.allocate") for r in readers)
    assert not any(r.endswith("SpecCache.invalidate")
                   for r in readers)


def test_racecheck_waivers_name_their_reason(race_findings):
    waived = [f for f in race_findings if f.suppressed is not None]
    assert waived, "the burn-down inventory must be non-empty"
    for f in waived:
        assert f.suppressed, f.format()


def test_flakes_tree_is_clean():
    findings = flakes.check_paths(LINT_PATHS)
    assert not findings, "\n".join(f.format() for f in findings)


def test_registry_checks_pass():
    findings = registry.check_all()
    assert not findings, "\n".join(f.format() for f in findings)


def test_suppression_inventory_is_fully_explained(tree_findings):
    findings = tree_findings
    unexplained = [f for f in findings
                   if f.rule == "unexplained-suppression"]
    assert not unexplained, \
        "\n".join(f.format() for f in unexplained)
    # and the inventory itself is non-empty: the waivers ARE the
    # documented exceptions to the rules (wire wall-expiry rebases,
    # state-compare-safe POSTs, best-effort probes)
    suppressed = [f for f in findings if f.suppressed]
    assert suppressed


def test_vtplint_cli_strict_exits_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "vtplint.py"),
         "--strict", "--json"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["findings"] == 0
    assert all(s["reason"] for s in doc["suppressions"])


# -- 2. broken fixtures: every rule still fires ------------------------

def _lint(src, path="volcano_tpu/server/state_server.py"):
    return astlint.Linter().lint_source(src, path)


def _rules(findings):
    return {f.rule for f in findings if f.suppressed is None}


def test_rule_req_id_fires():
    src = ("class C:\n"
           "    def create(self, body):\n"
           "        return self._request('POST', '/objects/vcjob',"
           " body)\n")
    assert "req-id" in _rules(_lint(src, "volcano_tpu/cache/x.py"))


def test_rule_req_id_satisfied_by_key():
    src = ("class C:\n"
           "    def create(self, body):\n"
           "        return self._request('POST', '/objects/vcjob',"
           " body, idempotency_key=True)\n")
    assert "req-id" not in _rules(_lint(src, "volcano_tpu/cache/x.py"))


def test_rule_wall_clock_fires_in_scoped_file():
    src = "import time\ndeadline = time.time() + 5\n"
    assert "wall-clock" in _rules(_lint(src))


def test_rule_wall_clock_fires_in_lease_function_anywhere():
    src = ("import time\n"
           "def renew_lease():\n"
           "    return time.time() + 15\n")
    assert "wall-clock" in _rules(
        _lint(src, "volcano_tpu/somewhere.py"))
    # ...but ordinary timing code outside the scope is untouched
    src2 = ("import time\n"
            "def measure():\n"
            "    return time.time()\n")
    assert "wall-clock" not in _rules(
        _lint(src2, "volcano_tpu/somewhere.py"))


def test_rule_metric_family_fires():
    src = ("from volcano_tpu import metrics\n"
           "metrics.inc('totally_unregistered_total')\n")
    assert "metric-family" in _rules(
        _lint(src, "volcano_tpu/actions/x.py"))


def test_rule_metric_labels_fires_on_undeclared_key():
    src = ("from volcano_tpu import metrics\n"
           "metrics.inc('elastic_decisions_total', job='ns/j')\n")
    assert "metric-labels" in _rules(
        _lint(src, "volcano_tpu/actions/x.py"))


def test_rule_metric_labels_fires_on_out_of_enum_value():
    src = ("from volcano_tpu import metrics\n"
           "metrics.inc('elastic_decisions_total', kind='explode')\n")
    assert "metric-labels" in _rules(
        _lint(src, "volcano_tpu/actions/x.py"))
    # a member of the bounded enum is fine
    src2 = ("from volcano_tpu import metrics\n"
            "metrics.inc('elastic_decisions_total', kind='grow')\n")
    assert "metric-labels" not in _rules(
        _lint(src2, "volcano_tpu/actions/x.py"))


def test_rule_append_lock_fires():
    src = ("class S:\n"
           "    def record(self, rec):\n"
           "        self.durable.append(rec)\n")
    assert "append-lock" in _rules(_lint(src))
    src2 = ("class S:\n"
            "    def record(self, rec):\n"
            "        with self._lock:\n"
            "            self.durable.append(rec)\n")
    assert "append-lock" not in _rules(_lint(src2))


def test_rule_process_ship_purity_fires():
    # a pipe send outside the ship seam, in a module touching
    # multiprocessing, is a purity hole: whatever it pickles skips
    # the callable-refusing pickler
    src = ("import multiprocessing\n"
           "def leak(conn, obj):\n"
           "    conn.send(obj)\n")
    assert "process-ship-purity" in _rules(
        _lint(src, "volcano_tpu/actions/x.py"))
    # the designated seams are the allowed senders
    src2 = ("import multiprocessing\n"
            "def post_bytes(conn, data):\n"
            "    conn.send_bytes(data)\n")
    assert "process-ship-purity" not in _rules(
        _lint(src2, "volcano_tpu/actions/x.py"))
    # modules that never touch multiprocessing are out of scope
    # (send() on an arbitrary object is not a pipe)
    src3 = ("def notify(ch, obj):\n"
            "    ch.send(obj)\n")
    assert "process-ship-purity" not in _rules(
        _lint(src3, "volcano_tpu/actions/x.py"))


def test_procpool_ship_refuses_callables():
    # the runtime half of the purity contract: the seam's pickler
    # refuses anything callable, however deeply nested
    import pytest as _pytest

    from volcano_tpu.actions import procpool
    assert procpool.unship(procpool.ship({"n": 1}))["n"] == 1
    with _pytest.raises(procpool.PicklePurityError):
        procpool.ship(lambda x: x)
    with _pytest.raises(procpool.PicklePurityError):
        procpool.ship({"cb": [1, 2, (print,)]})
    import functools
    with _pytest.raises(procpool.PicklePurityError):
        procpool.ship(functools.partial(int, "3"))


def test_rule_except_pass_fires():
    src = ("def poke(path):\n"
           "    try:\n"
           "        open(path).read()\n"
           "    except Exception:\n"
           "        pass\n")
    assert "except-pass" in _rules(_lint(src, "volcano_tpu/x.py"))
    # a narrow, non-I/O or handled except is not flagged
    src2 = ("def poke(d):\n"
            "    try:\n"
            "        return d['k']\n"
            "    except KeyError:\n"
            "        pass\n")
    assert "except-pass" not in _rules(_lint(src2, "volcano_tpu/x.py"))


def test_rule_episode_propagation_fires():
    # a mutating federation RPC whose enclosing function never
    # references the episode API: the hop would be invisible to
    # GET /fleet_trace?episode=
    src = ("class R:\n"
           "    def _move(self, h, job):\n"
           "        self.rpc.call('ra', 'add_vcjob',\n"
           "                      lambda: h.client.add_vcjob(job))\n")
    assert "episode-propagation" in _rules(
        _lint(src, "volcano_tpu/federation/router.py"))
    # threading the ID (any episode-API reference) satisfies it
    src2 = ("from volcano_tpu.api import federation as fedapi\n"
            "class R:\n"
            "    def _move(self, h, job):\n"
            "        fedapi.ensure_episode(job)\n"
            "        self.rpc.call('ra', 'add_vcjob',\n"
            "                      lambda: h.client.add_vcjob(job))\n")
    assert "episode-propagation" not in _rules(
        _lint(src2, "volcano_tpu/federation/router.py"))
    # fence plumbing is term bookkeeping, not a causal hop
    src3 = ("class R:\n"
            "    def _fence(self, adv):\n"
            "        self.rpc.call('ra', 'advance_fence', adv)\n")
    assert "episode-propagation" not in _rules(
        _lint(src3, "volcano_tpu/federation/router.py"))


def test_rule_episode_propagation_covers_controller_episodes():
    src = ("class C:\n"
           "    def _decide(self, pg, now):\n"
           "        self._episodes[pg.key] = ResizeEpisode(\n"
           "            pg.key, 'grow', now)\n")
    assert "episode-propagation" in _rules(
        _lint(src, "volcano_tpu/controllers/elastic.py"))
    src2 = ("from volcano_tpu.api import federation as fedapi\n"
            "class C:\n"
            "    def _decide(self, pg, now):\n"
            "        self._episodes[pg.key] = ResizeEpisode(\n"
            "            pg.key, 'grow', now,\n"
            "            episode=fedapi.episode_of(pg) or '')\n")
    assert "episode-propagation" not in _rules(
        _lint(src2, "volcano_tpu/controllers/elastic.py"))
    # a reasoned waiver is honoured (and inventoried, not silent)
    src3 = ("class C:\n"
            "    def _decide(self, pg, now):\n"
            "        # vtplint: disable=episode-propagation "
            "(fixture: pre-federation local resize)\n"
            "        self._episodes[pg.key] = ResizeEpisode(\n"
            "            pg.key, 'grow', now)\n")
    fs = _lint(src3, "volcano_tpu/controllers/elastic.py")
    assert "episode-propagation" not in _rules(fs)
    assert any(f.rule == "episode-propagation" and f.suppressed
               for f in fs)


def test_suppression_with_reason_waives_and_is_inventoried():
    src = ("import time\n"
           "# vtplint: disable=wall-clock (fixture: wire carries "
           "wall time)\n"
           "deadline = time.time() + 5\n")
    findings = _lint(src)
    assert "wall-clock" not in _rules(findings)
    assert any(f.rule == "wall-clock" and f.suppressed
               for f in findings)


def test_unexplained_suppression_is_itself_a_finding():
    src = ("import time\n"
           "# vtplint: disable=wall-clock\n"
           "deadline = time.time() + 5\n")
    assert "unexplained-suppression" in _rules(_lint(src))


def test_flakes_unused_import_fires():
    findings = flakes.check_source("import os\nx = 1\n",
                                   "volcano_tpu/x.py")
    assert any(f.rule in ("unused-import", "pyflakes")
               for f in findings)


def test_flakes_skips_type_checking_and_try_imports():
    src = ("from typing import TYPE_CHECKING\n"
           "if TYPE_CHECKING:\n"
           "    from volcano_tpu.framework.session import Session\n"
           "try:\n"
           "    import optional_dep\n"
           "except ImportError:\n"
           "    optional_dep = None\n")
    findings = flakes.check_source(src, "volcano_tpu/x.py")
    assert not [f for f in findings if f.rule == "unused-import"]


def test_flakes_syntax_error_fires():
    findings = flakes.check_source("def broken(:\n",
                                   "volcano_tpu/x.py")
    assert any(f.rule in ("syntax-error", "pyflakes")
               for f in findings)


def test_schema_checker_fixtures():
    # undeclared family
    assert check_exposition("bogus_family_total 1\n")
    # undeclared label key on a declared family
    assert check_exposition(
        'elastic_decisions_total{job="ns/j"} 1\n')
    # out-of-enum value on a bounded label
    assert check_exposition(
        'elastic_decisions_total{kind="explode"} 1\n')
    # the happy path is silent
    assert not check_exposition(
        'elastic_decisions_total{kind="grow"} 1\n'
        'frag_index{generation="v5e"} 0.25\n'
        "goodput_jobs 3\n")


# -- 2b. racecheck broken fixtures: the ownership rules still fire -----

FAKE_PLUGIN_PATH = "volcano_tpu/plugins/fixture_plugin.py"


def _race(src, path=FAKE_PLUGIN_PATH):
    findings = racecheck.check_sources({path: src})
    return {f.rule for f in findings if f.suppressed is None}


def test_rule_snapshot_write_fires_on_attribute_write():
    src = ("class P:\n"
           "    def on_session_open(self, ssn):\n"
           "        ssn.add_predicate_fn('p', self._predicate)\n"
           "    def _predicate(self, task, node):\n"
           "        task.node_name = node.name\n"
           "        return None\n")
    assert "snapshot-write" in _race(src)


def test_rule_snapshot_write_fires_on_mutator_call():
    src = ("class P:\n"
           "    def on_session_open(self, ssn):\n"
           "        ssn.add_predicate_fn('p', self._predicate)\n"
           "    def _predicate(self, task, node):\n"
           "        node.idle.sub(task.resreq)\n"
           "        return None\n")
    assert "snapshot-write" in _race(src)


def test_rule_snapshot_write_fires_on_item_write_via_taint():
    src = ("class P:\n"
           "    def on_session_open(self, ssn):\n"
           "        ssn.add_node_order_fn('p', self._score)\n"
           "    def _score(self, task, node):\n"
           "        owner = node.tasks.get(task.uid)\n"
           "        node.tasks[task.uid] = task\n"
           "        return 0.0\n")
    assert "snapshot-write" in _race(src)


def test_rule_snapshot_write_clean_reader_is_silent():
    src = ("class P:\n"
           "    def on_session_open(self, ssn):\n"
           "        ssn.add_predicate_fn('p', self._predicate)\n"
           "    def _predicate(self, task, node):\n"
           "        fresh = node.idle.clone()\n"
           "        fresh.sub(task.resreq)\n"
           "        counts = {}\n"
           "        counts[node.name] = 1\n"
           "        return None\n")
    assert not _race(src)


def test_rule_shared_cache_unkeyed_fires():
    src = ("class P:\n"
           "    def on_session_open(self, ssn):\n"
           "        ssn.add_predicate_fn('p', self._predicate)\n"
           "    def _predicate(self, task, node):\n"
           "        self._memo[task.uid] = node.name\n"
           "        return None\n")
    assert "shared-cache-unkeyed" in _race(src)


def test_rule_shared_cache_waiver_is_honoured_and_inventoried():
    src = ("class P:\n"
           "    def on_session_open(self, ssn):\n"
           "        ssn.add_predicate_fn('p', self._predicate)\n"
           "    def _predicate(self, task, node):\n"
           "        # vtplint: disable=shared-cache-unkeyed "
           "(idempotent memo under plugin lock)\n"
           "        self._memo[task.uid] = node.name\n"
           "        return None\n")
    findings = racecheck.check_sources({FAKE_PLUGIN_PATH: src})
    assert not [f for f in findings if f.suppressed is None]
    assert any(f.rule == "shared-cache-unkeyed" and f.suppressed
               for f in findings)


def test_racecheck_reachability_propagates_through_helpers():
    src = ("class P:\n"
           "    def on_session_open(self, ssn):\n"
           "        ssn.add_predicate_fn('p', self._predicate)\n"
           "    def _predicate(self, task, node):\n"
           "        return self._helper(task, node)\n"
           "    def _helper(self, task, node):\n"
           "        node.bind_generation = 0\n"
           "        return None\n")
    assert "snapshot-write" in _race(src)


# -- 2c. runtime freeze/race broken fixtures ---------------------------

@pytest.fixture
def race_runtime():
    freezeaudit.install()
    freezeaudit.reset()
    yield freezeaudit
    freezeaudit.reset()
    freezeaudit.uninstall()


def _frozen_session(race_runtime, tmp_scenario=None):
    from volcano_tpu.framework.framework import open_session
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pg, pods = gang_job("frozen", replicas=2, requests={"cpu": 1})
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    sched = Scheduler(cluster, schedule_period=0)
    return open_session(sched.cache, sched.conf)


def test_runtime_freeze_violation_fires(race_runtime):
    """A bare attribute write to a frozen snapshot object before the
    first commit is recorded (the write the static pass would flag,
    caught live)."""
    ssn = _frozen_session(race_runtime)
    node = next(iter(ssn.nodes.values()))
    node.bind_generation = 99          # not in any seam
    viols = race_runtime.report()["violations"]
    assert any(v["kind"] == "frozen-write"
               and "bind_generation" in v["target"] for v in viols)


def test_runtime_freeze_seam_writes_are_clean(race_runtime):
    """The same mutation through the designated seams (Statement ->
    Session primitives) records nothing."""
    from volcano_tpu.api.types import TaskStatus
    ssn = _frozen_session(race_runtime)
    task = next(t for j in ssn.jobs.values()
                for t in j.tasks_in_status(TaskStatus.PENDING))
    node = next(iter(ssn.nodes.values()))
    stmt = ssn.statement()
    stmt.allocate(task, node)
    stmt.commit()
    assert not race_runtime.report()["violations"]


def test_runtime_freeze_window_closes_at_first_commit(race_runtime):
    from volcano_tpu.api.types import TaskStatus
    ssn = _frozen_session(race_runtime)
    task = next(t for j in ssn.jobs.values()
                for t in j.tasks_in_status(TaskStatus.PENDING))
    node = next(iter(ssn.nodes.values()))
    stmt = ssn.statement()
    stmt.allocate(task, node)
    stmt.commit()
    # post-commit owner-thread writes are the mutation phase
    node.bind_generation += 1
    assert not race_runtime.report()["violations"]


def test_runtime_fanout_write_fires_even_after_commit(race_runtime):
    from volcano_tpu.api.types import TaskStatus
    ssn = _frozen_session(race_runtime)
    task = next(t for j in ssn.jobs.values()
                for t in j.tasks_in_status(TaskStatus.PENDING))
    node = next(iter(ssn.nodes.values()))
    stmt = ssn.statement()
    stmt.allocate(task, node)
    stmt.commit()
    race_runtime.fanout_begin()
    try:
        node.bind_generation += 1
    finally:
        race_runtime.fanout_end()
    viols = race_runtime.report()["violations"]
    assert any(v["kind"] == "frozen-write" and
               "parallel sweep" in v["reason"] for v in viols)


def test_runtime_seam_in_fanout_fires(race_runtime):
    """Entering a mutation seam while workers are in flight is a
    violation even though seams are otherwise sanctioned."""
    from volcano_tpu.api.types import TaskStatus
    ssn = _frozen_session(race_runtime)
    task = next(t for j in ssn.jobs.values()
                for t in j.tasks_in_status(TaskStatus.PENDING))
    node = next(iter(ssn.nodes.values()))
    race_runtime.fanout_begin()
    try:
        ssn.allocate(task, node)
    finally:
        race_runtime.fanout_end()
    viols = race_runtime.report()["violations"]
    assert any(v["kind"] == "seam-in-fanout" for v in viols)


def test_runtime_cross_thread_unsync_pair_fires(race_runtime):
    """A tracked store written by one thread and read by another with
    no common lock held -> unsync-pair (ThreadSanitizer-lite)."""
    import threading
    store = race_runtime.track({}, "test.shared")

    def writer():
        store["k"] = 1

    t = threading.Thread(target=writer)
    t.start()
    t.join()
    _ = store.get("k")
    viols = race_runtime.report()["violations"]
    assert any(v["kind"] == "unsync-pair"
               and v["store"] == "test.shared" for v in viols)


def test_runtime_locked_cross_thread_access_is_clean(race_runtime):
    """The same pattern under ONE common audited lock is ordered:
    held-sets intersect, no pair."""
    import threading
    lockaudit.install()
    lockaudit.reset()
    try:
        lk = lockaudit.make_lock("SHARED")
        store = race_runtime.track({}, "test.locked")

        def writer():
            with lk:
                store["k"] = 1

        t = threading.Thread(target=writer)
        t.start()
        t.join()
        with lk:
            _ = store.get("k")
        viols = race_runtime.report()["violations"]
        assert not [v for v in viols
                    if v.get("store") == "test.locked"], viols
    finally:
        lockaudit.reset()
        lockaudit.uninstall()


# -- 2d. the incremental cache -----------------------------------------

def test_lintcache_roundtrip_and_invalidation(tmp_path):
    from volcano_tpu.analysis.lintcache import LintCache
    import time as _time
    src = tmp_path / "volcano_tpu"
    src.mkdir()
    f = src / "mod.py"
    f.write_text("import os\nx = 1\n")
    # mirror the toolchain files the version digest stats
    cache = LintCache(REPO, cache_dir=str(tmp_path / ".vtplint_cache"))
    findings = flakes.check_source(f.read_text(), str(f))
    assert findings                      # the unused import
    cache.put_file("flakes", str(f), findings)
    cache.save()

    reloaded = LintCache(REPO,
                         cache_dir=str(tmp_path / ".vtplint_cache"))
    hit = reloaded.get_file("flakes", str(f))
    assert hit is not None
    assert [(x.rule, x.line) for x in hit] == \
        [(x.rule, x.line) for x in findings]
    # an edit invalidates: new mtime/size => miss
    _time.sleep(0.01)
    f.write_text("import os\nimport sys\nx = 1\n")
    assert reloaded.get_file("flakes", str(f)) is None


def test_lintcache_tree_sig_tracks_any_file(tmp_path):
    from volcano_tpu.analysis.lintcache import LintCache
    a = tmp_path / "a.py"
    b = tmp_path / "b.py"
    a.write_text("x = 1\n")
    b.write_text("y = 2\n")
    cache = LintCache(REPO, cache_dir=str(tmp_path / ".c"))
    sig = cache.tree_sig([str(a), str(b)])
    cache.put_tree("race", sig, [])
    assert cache.get_tree("race", sig) == []
    import time as _time
    _time.sleep(0.01)
    b.write_text("y = 3\n")
    assert cache.tree_sig([str(a), str(b)]) != sig


# -- 3. live exposition vs the label schema (the deduped test) ---------

def _elastic_job(name="etrain", slices=1, lo=1, hi=2):
    from volcano_tpu.api import elastic as eapi
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    return VCJob(
        name=name, min_available=slices * 4,
        annotations={
            eapi.ELASTIC_MIN_SLICES_ANNOTATION: str(lo),
            eapi.ELASTIC_MAX_SLICES_ANNOTATION: str(hi),
            eapi.ELASTIC_SLICES_ANNOTATION: str(slices)},
        plugins={"jax": []},
        tasks=[TaskSpec(name="worker", replicas=slices * 4,
                        template=make_pod(
                            "t", requests={"cpu": 8, TPU: 4}))])


def test_live_exposition_honours_label_schema():
    """One compact control-plane drive lighting up the trace,
    elastic, goodput, fairness and scheduler families — then the
    WHOLE exposition is validated against bundle.FAMILY_LABELS.
    Replaces the three per-PR cardinality tests (PR 5/6/7): any
    family ANY subsystem emits with a job key, a free-text reason or
    an out-of-enum label value fails here, without a per-subsystem
    copy of the assertion."""
    from volcano_tpu.api import goodput as gapi
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.resource import TPU
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    from volcano_tpu.controllers import ControllerManager
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job
    from volcano_tpu.webhooks import default_admission

    conf = {
        "actions": "enqueue, allocate, elastic, backfill",
        "tiers": [
            {"plugins": [{"name": "priority"}, {"name": "gang"},
                         {"name": "failover"}, {"name": "elastic"},
                         {"name": "conformance"}]},
            {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                         {"name": "predicates"},
                         {"name": "proportion"},
                         {"name": "nodeorder"}, {"name": "binpack"}]},
        ],
        "configurations": {"elastic": {"elastic.cooldownSeconds": 0}},
    }
    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    cluster.admission = default_admission()
    # a stuck gang: unschedulable-reason + pending families
    pg, pods = gang_job("stuck", replicas=2, requests={"cpu": 1})
    for p in pods:
        p.node_selector = {"zone": "nowhere"}
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    # an elastic gang that grows into the idle slice
    cluster.add_vcjob(_elastic_job())
    # a goodput report folding into podgroup annotations
    cluster.put_object("goodputreport", gapi.GoodputReport(
        node="sa-w0", ts=1.0, usages=[gapi.PodGoodput(
            pod_key="default/p", uid="u1", job="default/etrain",
            generation="v5e", step=10, steps_per_s=2.0,
            allocated_s=1.0, productive_s=1.0)]))
    mgr = ControllerManager(cluster, enabled=[
        "job", "podgroup", "queue", "failover", "elastic"])
    sched = Scheduler(cluster, conf=conf, schedule_period=0)
    try:
        for _ in range(12):
            mgr.sync_all()
            sched.run_once()
            cluster.tick()
    finally:
        mgr.stop()
    metrics.inc("goodput_gated_grows_total", decision="declined")

    dumped = metrics.dump()
    # the families this drive must have lit (guards against the test
    # going quietly vacuous)
    for prefix in ("sched_span_seconds", "sched_phase_seconds",
                   "sched_unschedulable_reasons_total",
                   "elastic_decisions_total", "frag_index",
                   "action_latency_seconds", "queue_share"):
        assert any(line.startswith(prefix)
                   for line in dumped.splitlines()), prefix
    violations = check_exposition(dumped)
    assert not violations, "\n".join(violations)
    # and the cardinality spot-checks the old tests pinned: job keys
    # never label the bounded families
    for line in dumped.splitlines():
        if line.startswith(("sched_", "elastic_", "goodput_",
                            "frag_", "starvation_")):
            assert "etrain" not in line, line
            assert "default/stuck" not in line, line
            assert "sa-w0" not in line, line


class _LintMirror:
    """Minimal always-fresh mirror for the federation plane drive."""

    def __init__(self, cluster):
        self.cluster = cluster

    def age_s(self):
        return 0.1

    def read_checked(self, max_age_s=None):
        return self.cluster

    def stop(self):
        pass


def _region_exposition(attainment):
    # a synthetic regional /metrics scrape: SLO indicator families
    # plus one family outside the schema (the rollup must DROP it,
    # never re-export it fleet-wide)
    return "\n".join([
        f"serving_slo_attainment_min {attainment}",
        "e2e_scheduling_latency_seconds_count 10",
        "e2e_scheduling_latency_seconds_sum 4.0",
        'failover_mttr_seconds_count{slice="s0"} 2',
        'failover_mttr_seconds_sum{slice="s0"} 100.0',
        "not_a_registered_family_total 7",
        ""])


def test_live_exposition_federation_observability_plane():
    """A 2-region + router in-process plane drives the fleet
    observability families — mirror staleness, breaker detail,
    rollups, SLO burn, stitched traces — then the WHOLE exposition is
    validated against the label schema.  Region IDs come from a
    bounded test enum; episode IDs are asserted to NEVER appear in
    the exposition (they are annotation/trace-label values only)."""
    from volcano_tpu.api import federation as fedapi
    from volcano_tpu.api.pod import Container, Pod
    from volcano_tpu.api.vcjob import TaskSpec, VCJob
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.federation.retry import (BREAKER_THRESHOLD,
                                              FedRPCError)
    from volcano_tpu.federation.router import FederationRouter

    g = FakeCluster()
    t = [1000.0]
    router = FederationRouter(g, now=lambda: t[0],
                              start_mirrors=False)
    texts = {"ra": _region_exposition(0.999),
             "rb": _region_exposition(0.42)}  # rb burns its budget
    router._rollup_fetch = (
        lambda url, token="", timeout=None:
        texts[url.rsplit("/", 1)[-1]])
    for name in ("ra", "rb"):       # bounded test region enum
        rc = FakeCluster()
        router.attach_region(
            fedapi.region_record(
                name, f"fake://{name}",
                metrics_url=f"fake://metrics/{name}"),
            client=rc, mirror=_LintMirror(rc))
    # a cpu-only global gang: admission mints the causal episode
    job = VCJob(name="fedjob", min_available=1,
                tasks=[TaskSpec(name="w", replicas=1,
                                template=Pod(name="w", containers=[
                                    Container(requests={"cpu": 1})]))])
    g.add_vcjob(job)
    for _ in range(3):
        router.sync()
        t[0] += 5.0
    episode = fedapi.episode_of(g.vcjobs[job.key])
    assert episode and episode.startswith("ep-")
    # the stitched doc landed durably in the global store
    assert episode in g.fleet_traces
    # trip rb's breaker: transient failures past the threshold light
    # the detail gauges and persist the snapshot (failover adoption)
    def _boom():
        raise ConnectionError("partition")
    for _ in range(BREAKER_THRESHOLD):
        with pytest.raises(FedRPCError):
            router.rpc.call("rb", "add_vcjob", _boom)
    router._gauges()
    assert "rb" in g.router_breakers
    dumped = metrics.dump()
    for prefix in ("federation_mirror_staleness_seconds",
                   "federation_router_breaker_failures",
                   "federation_router_breaker_last_trip_ts",
                   "federation_rollup_sum",
                   "federation_rollup_count",
                   "slo_burn_rate",
                   "federation_stitched_traces_total"):
        assert any(line.startswith(prefix)
                   for line in dumped.splitlines()), prefix
    violations = check_exposition(dumped)
    assert not violations, "\n".join(violations)
    # episode IDs never reach the exposition — not as a label value,
    # not anywhere
    assert "ep-" not in dumped
    assert "fedjob" not in dumped


# -- 4. the runtime lock-order auditor ---------------------------------

@pytest.fixture
def audit():
    lockaudit.install()
    lockaudit.reset()
    yield lockaudit
    lockaudit.reset()
    lockaudit.uninstall()


def test_lockaudit_detects_inversion(audit):
    a, b = audit.make_lock("A"), audit.make_lock("B")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    rep = audit.report()
    kinds = [v["kind"] for v in rep["violations"]]
    assert "inversion" in kinds
    assert ["A", "B"] in rep["cycles"]
    inv = next(v for v in rep["violations"]
               if v["kind"] == "inversion")
    assert inv["stack_forward"] and inv["stack_reverse"]


def test_lockaudit_consistent_order_is_clean(audit):
    a, b = audit.make_lock("A"), audit.make_lock("B")
    for _ in range(3):
        with a:
            with b:
                pass
    rep = audit.report()
    assert not rep["violations"]
    assert not rep["cycles"]
    assert ["A", "B", 3] in rep["edges"]


def test_lockaudit_condition_wait_keeps_bookkeeping(audit):
    import threading
    import time as _time
    lk = audit.make_lock("CV")
    cv = threading.Condition(lk)
    woke = []

    def waiter():
        with cv:
            cv.wait(timeout=1.0)
            woke.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    _time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join()
    assert woke
    assert not audit.report()["violations"]


def test_lockaudit_guarded_store(audit):
    lk = audit.make_lock("G")
    store = audit.guard_store({}, lk, "test.store")
    with lk:
        store["ok"] = 1                  # under the lock: clean
    assert not audit.report()["violations"]
    store["bad"] = 2                     # without the lock: violation
    viols = audit.report()["violations"]
    assert any(v["kind"] == "unguarded-mutation"
               and v["store"] == "test.store" for v in viols)


def test_lockaudit_in_process_plane_is_clean(audit, tmp_path):
    """The tier-1 half of the acceptance smoke: a real StateServer
    (durable, snapshotting) + scheduler sessions + lease CAS churn
    under the armed auditor — the acquisition graph must hold zero
    inversions/cycles/self-deadlocks.  (The chaos conductor's
    --lock-audit repeats this across the real process plane.)"""
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.server.durability import DurableStore
    from volcano_tpu.server.state_server import StateServer
    from volcano_tpu.simulator import make_tpu_cluster
    from volcano_tpu.uthelper import gang_job

    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pg, pods = gang_job("demo", replicas=2, requests={"cpu": 1})
    st = StateServer(cluster,
                     durable=DurableStore(str(tmp_path / "state")))
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    sched = Scheduler(cluster, schedule_period=0)
    for i in range(3):
        sched.run_once()
        cluster.tick()
        st.lease("scheduler", f"holder-{i % 2}", ttl=0.01)
        st.commit()
    st.write_snapshot()
    rep = audit.report()
    assert rep["locks"], "the plane must actually exercise locks"
    assert not rep["violations"], json.dumps(
        rep["violations"], indent=1, default=str)[:4000]
    assert not rep["cycles"], rep["cycles"]
