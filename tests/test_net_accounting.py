"""Per-pod DCN bandwidth accounting — measure what the enforcer shapes.

The subsystem under test closes the enforce→measure→react loop
(VERDICT r5 missing #5 / next-round #7; reference: pinned eBPF
watermark maps, pkg/networkqos/utils/ebpf/map.go):

  collector  (agent/collect.py NetAccountingCollector): per-cgroup
      tx/rx counters keyed by the enforcer's net_cls classids, EWMA
      rates, counter-reset handling — tested against a fake cgroup fs;
  handler    (agent/handlers.py netaccounting): watermark comparison
      with hysteresis, BandwidthViolation events, BandwidthReport
      posting, store-side fold into node annotations;
  scheduler  (plugins/rescheduling.py bandwidthPressure + nodeorder
      bandwidth scorer): chronic violators evicted, saturated hosts
      penalized for new online pods;
  wire e2e   : the full lifecycle through a real HTTP state server —
      agent measures over its wire mirror, the violation reaches the
      server and a wire-mirrored scheduler evicts the violator.
"""

import os
import time

import pytest

from volcano_tpu.agent.agent import (
    DCN_BANDWIDTH_ANNOTATION,
    DCN_POD_LIMIT_ANNOTATION,
    NodeAgent,
    FakeUsageProvider,
)
from volcano_tpu.agent.collect import NetAccountingCollector
from volcano_tpu.agent.enforcer import CgroupV2Enforcer
from volcano_tpu.api.netusage import (
    NODE_MEASURED_OFFLINE_ANNOTATION,
    NODE_MEASURED_ONLINE_ANNOTATION,
    NODE_SATURATED_ANNOTATION,
    POD_TX_ANNOTATION,
    POD_VIOLATING_ANNOTATION,
    POD_VIOLATIONS_ANNOTATION,
)
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.types import TaskStatus
from volcano_tpu.simulator import make_tpu_cluster

BE = {"volcano-tpu.io/qos-level": "BE"}


class Clock:
    """Injectable monotonic time for deterministic EWMA windows."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def tick(self, dt: float = 1.0) -> None:
        self.t += dt


def write_counters(root: str, uid: str, tx: int, rx: int = 0) -> None:
    d = os.path.join(root, "vtp-" + uid)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "net_stat.tx_bytes"), "w") as f:
        f.write(f"{tx}\n")
    with open(os.path.join(d, "net_stat.rx_bytes"), "w") as f:
        f.write(f"{rx}\n")


# -- collector unit tests (fake cgroup filesystem) ---------------------

def test_collector_classid_mapping_and_ewma(tmp_path):
    """tx-byte counters advancing across windows yield mbps rates;
    the classid the ENFORCER wrote is what keys the measurement."""
    root = str(tmp_path)
    clock = Clock()
    col = NetAccountingCollector(root, now=clock)
    d = os.path.join(root, "vtp-u1")
    os.makedirs(d)
    with open(os.path.join(d, "net_cls.classid"), "w") as f:
        f.write("0x00010015\n")                  # 1:21
    write_counters(root, "u1", 0)
    col.collect("n0")                            # baseline reading
    clock.tick(1.0)
    write_counters(root, "u1", 125_000_000)      # 1e9 bits over 1s
    totals = col.collect("n0")
    r = col.rates()["u1"]
    assert r.classid == 0x15 == 21
    assert r.tx_mbps == pytest.approx(1000.0)    # 1 Gbps
    assert totals["dcn_tx_mbps"] == pytest.approx(1000.0)
    # EWMA: a second window at zero traffic halves (alpha 0.5)
    clock.tick(1.0)
    write_counters(root, "u1", 125_000_000)
    assert col.collect("n0")["dcn_tx_mbps"] == pytest.approx(500.0)


def test_collector_counter_reset_handling(tmp_path):
    """A reading BELOW the last one (exporter/kernel restart) is a
    reset: the new absolute value counts as the delta — never a
    negative rate, never a skipped window."""
    root = str(tmp_path)
    clock = Clock()
    col = NetAccountingCollector(root, now=clock)
    write_counters(root, "u1", 1_000_000)
    col.collect("n0")
    clock.tick(1.0)
    write_counters(root, "u1", 2_000_000)
    col.collect("n0")
    before = col.rates()["u1"].tx_mbps
    assert before > 0
    clock.tick(1.0)
    write_counters(root, "u1", 250_000)          # reset: 250k since
    col.collect("n0")
    r = col.rates()["u1"]
    assert r.tx_mbps >= 0
    # 250_000 bytes/1s = 2 mbps folded into the EWMA, not negative
    assert r.tx_mbps == pytest.approx(0.5 * 2.0 + 0.5 * before)


def test_collector_drops_departed_pods_and_double_sample(tmp_path):
    root = str(tmp_path)
    clock = Clock()
    col = NetAccountingCollector(root, now=clock)
    write_counters(root, "gone", 1_000)
    col.collect("n0")
    assert "gone" in col.rates()
    # a second collect inside MIN_INTERVAL_S is a cached no-op (the
    # handler and the composite provider may both sample one sync)
    write_counters(root, "gone", 9_999_999)
    col.collect("n0")
    assert col.rates()["gone"].tx_bytes == 1_000
    # dir removed -> state dropped (classids recycle)
    import shutil
    shutil.rmtree(os.path.join(root, "vtp-gone"))
    clock.tick(1.0)
    col.collect("n0")
    assert "gone" not in col.rates()


def test_collector_one_sided_read_failure_keeps_rates_honest(tmp_path):
    """An exporter mid-rewrite can fail ONE direction's read; the
    other direction's window must not be torn — the returning counter
    averages its delta over its own (longer) window instead of
    reading ~2x hot over a single window's dt."""
    root = str(tmp_path)
    clock = Clock()
    col = NetAccountingCollector(root, now=clock)
    write_counters(root, "u1", 0, rx=0)
    col.collect("n0")                    # baseline both directions
    # window 1: rx file unreadable, tx advances at 1000 mbps
    rx_path = os.path.join(root, "vtp-u1", "net_stat.rx_bytes")
    os.unlink(rx_path)
    clock.tick(1.0)
    write_counters(root, "u1", 125_000_000)
    os.unlink(rx_path)                   # write_counters recreated it
    col.collect("n0")
    assert col.rates()["u1"].tx_mbps == pytest.approx(1000.0)
    # window 2: rx returns having accumulated 2 windows of 500 mbps
    clock.tick(1.0)
    write_counters(root, "u1", 250_000_000, rx=125_000_000)
    col.collect("n0")
    r = col.rates()["u1"]
    assert r.tx_mbps == pytest.approx(1000.0)
    # 125e6 bytes over the 2s window it actually spans = 500 mbps,
    # not 1000 (the inflation a shared timestamp would produce)
    assert r.rx_mbps == pytest.approx(500.0)


def test_node_put_cannot_erase_folded_annotations():
    """The store-side fold must be STICKY: a whole-node write from a
    mirror that predates the fold (the agent's own persist) re-applies
    the stored report's summary instead of erasing it."""
    from volcano_tpu.api.netusage import BandwidthReport
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.cache.fake_cluster import FakeCluster

    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": "8"}))
    cluster.put_object("bandwidthreport", BandwidthReport(
        node="n0", offline_tx_mbps=700.0, online_tx_mbps=200.0,
        total_mbps=1000.0, violations=2, saturated=True))
    assert cluster.nodes["n0"].annotations[
        NODE_SATURATED_ANNOTATION] == "true"
    # a stale mirror's whole-node persist (no folded keys on it)
    stale = Node(name="n0", allocatable={"cpu": "8"},
                 annotations={"somebody": "else"})
    cluster.put_object("node", stale)
    ann = cluster.nodes["n0"].annotations
    assert ann["somebody"] == "else"
    assert ann[NODE_SATURATED_ANNOTATION] == "true"
    assert float(ann[NODE_MEASURED_OFFLINE_ANNOTATION]) == 700.0


def test_node_delete_drops_report_no_stale_resurrection():
    """A node's report dies with the node: a REPLACEMENT host
    registering under the same name must not be born saturated from
    the dead host's last report."""
    from volcano_tpu.api.netusage import BandwidthReport
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.cache.fake_cluster import FakeCluster

    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": "8"}))
    cluster.put_object("bandwidthreport", BandwidthReport(
        node="n0", offline_tx_mbps=700.0, total_mbps=1000.0,
        violations=2, saturated=True))
    cluster.delete_object("node", "n0")
    assert "n0" not in cluster.bandwidthreports
    cluster.put_object("node", Node(name="n0",
                                    allocatable={"cpu": "8"}))
    assert NODE_SATURATED_ANNOTATION not in \
        cluster.nodes["n0"].annotations


# -- handler: watermarks, hysteresis, report fold ----------------------

def mk_accounting_agent(tmp_path, pods, total_mbps=1000,
                        cpu_fraction=0.2):
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.nodes["sa-w0"].annotations[DCN_BANDWIDTH_ANNOTATION] = \
        str(total_mbps)
    for p in pods:
        cluster.add_pod(p)
    provider = FakeUsageProvider()
    provider.set("sa-w0", cpu_fraction=cpu_fraction,
                 tpu_chips_detected=4, tpu_chips_healthy=4)
    cg = CgroupV2Enforcer(str(tmp_path / "cg"))
    clock = Clock()
    col = NetAccountingCollector(cg.root, now=clock)
    agent = NodeAgent(cluster, "sa-w0", provider, enforcer=cg,
                      net_collector=col)
    return cluster, agent, cg, col, clock


def test_violation_fires_with_hysteresis_and_clears(tmp_path):
    """Over-watermark EWMA rates must persist FIRE_SYNCS windows to
    raise the violation (a single burst never flaps) and stay under
    CLEAR_MARGIN x watermark for CLEAR_SYNCS windows to clear it."""
    hog = make_pod("hog", node_name="sa-w0", phase=TaskStatus.RUNNING,
                   requests={"cpu": "1"}, annotations=dict(BE))
    cluster, agent, cg, col, clock = mk_accounting_agent(
        tmp_path, [hog])
    agent.sync()                       # enforcer tags the cgroup
    # offline share of 1000 mbps at low cpu = 400; one BE pod -> 400
    assert hog.annotations[DCN_POD_LIMIT_ANNOTATION] == "400"
    assert cg.read(hog.uid, "net_cls.classid") not in (None, "0x00000000")

    tx = 0
    write_counters(cg.root, hog.uid, tx)
    clock.tick()
    agent.sync()                       # baseline counter reading

    def run_sync(bytes_per_s):
        nonlocal tx
        tx += bytes_per_s
        write_counters(cg.root, hog.uid, tx)
        clock.tick()
        agent.sync()

    # 900 mbps against a 400 mbps watermark: 2 windows is NOT enough
    run_sync(112_500_000)
    run_sync(112_500_000)
    assert POD_VIOLATING_ANNOTATION not in hog.annotations
    assert not any(r == "BandwidthViolation" for _, r, _ in
                   cluster.events)
    # third consecutive window fires exactly once
    run_sync(112_500_000)
    assert hog.annotations[POD_VIOLATING_ANNOTATION] == "true"
    assert [r for _, r, _ in cluster.events].count(
        "BandwidthViolation") == 1
    assert float(hog.annotations[POD_TX_ANNOTATION]) > 400
    # cumulative violating-sync count grows while the state holds
    run_sync(112_500_000)
    assert int(hog.annotations[POD_VIOLATIONS_ANNOTATION]) >= 2

    # report reached the store and the STORE folded node annotations
    rep = cluster.bandwidthreports["sa-w0"]
    assert rep.violations == 1 and rep.saturated   # 900 >= 0.85*1000
    node = cluster.nodes["sa-w0"]
    assert node.annotations[NODE_SATURATED_ANNOTATION] == "true"
    assert float(node.annotations[
        NODE_MEASURED_OFFLINE_ANNOTATION]) > 400

    # traffic stops: EWMA decays under 0.9*400=360, and after
    # CLEAR_SYNCS windows the violation clears (with an event)
    for _ in range(8):
        run_sync(0)
    assert POD_VIOLATING_ANNOTATION not in hog.annotations
    assert any(r == "BandwidthViolationCleared"
               for _, r, _ in cluster.events)
    assert not cluster.bandwidthreports["sa-w0"].saturated
    assert NODE_SATURATED_ANNOTATION not in node.annotations


def test_online_pod_declared_watermark(tmp_path):
    """Online pods have no enforced cap; a DECLARED watermark
    annotation is what their measured rate verifies against."""
    from volcano_tpu.api.netusage import POD_WATERMARK_ANNOTATION
    srv = make_pod("srv", node_name="sa-w0", phase=TaskStatus.RUNNING,
                   requests={"cpu": "1"},
                   annotations={POD_WATERMARK_ANNOTATION: "100"})
    cluster, agent, cg, col, clock = mk_accounting_agent(
        tmp_path, [srv])
    agent.sync()
    # online pod: no net_cls tag, but the collector still accounts the
    # cgroup dir the cpu/memory knobs created
    write_counters(cg.root, srv.uid, 0)
    clock.tick()
    agent.sync()
    tx = 0
    for _ in range(3):
        tx += 25_000_000               # 200 mbps > declared 100
        write_counters(cg.root, srv.uid, tx)
        clock.tick()
        agent.sync()
    assert srv.annotations[POD_VIOLATING_ANNOTATION] == "true"
    rep = cluster.bandwidthreports["sa-w0"]
    assert rep.usages[0].tier == "online"
    assert rep.online_tx_mbps > 100 and rep.offline_tx_mbps == 0


def test_steady_rates_generate_no_churn(tmp_path):
    """EWMA jitter inside the publish dead-band must not defeat the
    change-elision: with steady traffic, repeated syncs produce no new
    pod writes and no new report posts (O(pods x mirrors) watch
    traffic otherwise)."""
    hog = make_pod("hog", node_name="sa-w0", phase=TaskStatus.RUNNING,
                   requests={"cpu": "1"}, annotations=dict(BE))
    cluster, agent, cg, col, clock = mk_accounting_agent(
        tmp_path, [hog])
    agent.sync()
    tx = 0
    def run_sync(bytes_per_s):
        nonlocal tx
        tx += bytes_per_s
        write_counters(cg.root, hog.uid, tx)
        clock.tick()
        agent.sync()
    run_sync(0)
    for _ in range(6):                  # ~40 mbps, well under the cap
        run_sync(5_000_000)
    events = []
    cluster.watch(lambda kind, obj: events.append(kind))
    for _ in range(4):                  # jitter-free steady state
        run_sync(5_000_000)
    assert "pod" not in events, events
    assert "bandwidthreport" not in events, events


def test_crowded_host_floor_keeps_watermark_live(tmp_path):
    """When BE pods outnumber offline mbps the per-pod cap floors at
    1 (matching the tc clamp) instead of publishing a literal 0 that
    the verifier would read as 'no watermark' — violations must stay
    detectable exactly where the host is most crowded."""
    pods = [make_pod(f"be{i}", node_name="sa-w0",
                     phase=TaskStatus.RUNNING, requests={"cpu": "100m"},
                     annotations=dict(BE)) for i in range(5)]
    cluster, agent, cg, col, clock = mk_accounting_agent(
        tmp_path, pods, total_mbps=10)   # offline share: 4 mbps / 5 BE
    agent.sync()
    assert all(p.annotations[DCN_POD_LIMIT_ANNOTATION] == "1"
               for p in pods)
    tx = 0
    write_counters(cg.root, pods[0].uid, tx)
    clock.tick(); agent.sync()
    for _ in range(3):
        tx += 1_000_000                  # 8 mbps >> 1 mbps watermark
        write_counters(cg.root, pods[0].uid, tx)
        clock.tick(); agent.sync()
    assert pods[0].annotations[POD_VIOLATING_ANNOTATION] == "true"


def test_no_collector_is_a_noop(tmp_path):
    """Deployments without accounting (no collector wired) keep the
    exact pre-subsystem behavior: no annotations, no reports."""
    pod = make_pod("w", node_name="sa-w0", phase=TaskStatus.RUNNING,
                   requests={"cpu": "1"}, annotations=dict(BE))
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    cluster.add_pod(pod)
    agent = NodeAgent(cluster, "sa-w0", FakeUsageProvider())
    agent.sync()
    assert POD_TX_ANNOTATION not in pod.annotations
    assert not cluster.bandwidthreports


# -- scheduler: bandwidthPressure + nodeorder --------------------------

def _saturated_annotations(offline="700", online="200"):
    return {DCN_BANDWIDTH_ANNOTATION: "1000",
            NODE_SATURATED_ANNOTATION: "true",
            NODE_MEASURED_OFFLINE_ANNOTATION: offline,
            NODE_MEASURED_ONLINE_ANNOTATION: online}


def test_bandwidth_pressure_evicts_chronic_violator():
    """On a saturated host the chronic offline violator is the victim;
    the compliant BE pod and the online pod stay."""
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.api.types import PodGroupPhase
    from volcano_tpu.uthelper import TestContext, gang_job

    node = Node(name="hot", allocatable={"cpu": 64, "pods": 110},
                annotations=_saturated_annotations())
    pgs, pods = [], []
    for name, ann in (
            ("hog", dict(BE, **{POD_VIOLATING_ANNOTATION: "true",
                                POD_VIOLATIONS_ANNOTATION: "7"})),
            ("meek", dict(BE)),                       # compliant BE
            ("serve", {})):                           # online tier
        pg, ps = gang_job(name, replicas=1, min_available=0,
                          requests={"cpu": 4}, running_on=["hot"],
                          pg_phase=PodGroupPhase.RUNNING)
        for p in ps:
            p.annotations.update(ann)
        pgs.append(pg)
        pods.extend(ps)
    conf = {"actions": "shuffle", "tiers": [{"plugins": [
        {"name": "gang"},
        {"name": "rescheduling", "arguments": {
            "rescheduling.interval": 0,
            "rescheduling.strategies": "bandwidthPressure"}}]}]}
    ctx = TestContext(nodes=[node], podgroups=pgs, pods=pods,
                      conf=conf)
    ctx.run(["shuffle"])
    ctx.expect_evict_num(1)
    assert ctx.cluster.evictions == ["default/hog-0"]


def test_bandwidth_pressure_respects_chronic_floor_and_saturation():
    """A still-young violator (count below the chronic floor) and any
    violator on an UNsaturated host are left to the enforcer's caps."""
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.api.types import PodGroupPhase
    from volcano_tpu.uthelper import TestContext, gang_job

    hot = Node(name="hot", allocatable={"cpu": 64, "pods": 110},
               annotations=_saturated_annotations())
    cool = Node(name="cool", allocatable={"cpu": 64, "pods": 110},
                annotations={DCN_BANDWIDTH_ANNOTATION: "1000"})
    pgs, pods = [], []
    for name, where, count in (("young", "hot", "2"),
                               ("chronic", "cool", "9")):
        pg, ps = gang_job(name, replicas=1, min_available=0,
                          requests={"cpu": 4}, running_on=[where],
                          pg_phase=PodGroupPhase.RUNNING)
        for p in ps:
            p.annotations.update(dict(
                BE, **{POD_VIOLATING_ANNOTATION: "true",
                       POD_VIOLATIONS_ANNOTATION: count}))
        pgs.append(pg)
        pods.extend(ps)
    conf = {"actions": "shuffle", "tiers": [{"plugins": [
        {"name": "gang"},
        {"name": "rescheduling", "arguments": {
            "rescheduling.interval": 0,
            "rescheduling.strategies": "bandwidthPressure",
            "bandwidthPressure.chronicViolations": 3}}]}]}
    ctx = TestContext(nodes=[hot, cool], podgroups=pgs, pods=pods,
                      conf=conf)
    ctx.run(["shuffle"])
    ctx.expect_evict_num(0)


def test_nodeorder_steers_online_pods_off_saturated_hosts():
    """Two otherwise-identical hosts: the online pod lands on the
    unsaturated one; a BE pod is indifferent (caps shape it anywhere),
    proving the penalty is tier-scoped."""
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.uthelper import TestContext, gang_job

    sat = Node(name="sat", allocatable={"cpu": 8, "pods": 110},
               annotations=_saturated_annotations())
    ok = Node(name="ok", allocatable={"cpu": 8, "pods": 110})
    pg, pods = gang_job("serve", replicas=1, requests={"cpu": 1})
    conf = {"actions": "enqueue, allocate", "tiers": [{"plugins": [
        {"name": "gang"}, {"name": "predicates"},
        {"name": "nodeorder"}]}]}
    ctx = TestContext(nodes=[sat, ok], podgroups=[pg], pods=pods,
                      conf=conf)
    ctx.run()
    ctx.expect_bind("default/serve-0", "ok")


# -- wire e2e: the acceptance-criterion lifecycle ----------------------

def wait_for(cond, timeout=10.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def test_violation_event_lifecycle_over_wire(tmp_path):
    """End-to-end proof through the REAL wire control plane: an
    over-watermark offline pod's traffic is measured by the agent
    collector (agent on a wire mirror), the BandwidthViolation +
    usage report reach the state server (folded node annotations,
    /bandwidth GET route), a second wire mirror (the scheduler's)
    converges on them, and bandwidthPressure selects the pod for
    eviction — executed through the wire."""
    import json
    import urllib.request

    from volcano_tpu.api.node_info import Node
    from volcano_tpu.api.types import PodGroupPhase
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.scheduler import Scheduler
    from volcano_tpu.server.state_server import serve
    from volcano_tpu.uthelper import gang_job

    httpd, state = serve(port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    mirrors = []

    def client(**kw):
        c = RemoteCluster(url, **kw)
        mirrors.append(c)
        return c

    try:
        kubectl = client()
        kubectl.add_node(Node(
            name="n0", allocatable={"cpu": "64", "pods": 110},
            annotations={DCN_BANDWIDTH_ANNOTATION: "1000"}))
        pg, pods = gang_job("hog", replicas=1, min_available=0,
                            requests={"cpu": 4}, running_on=["n0"],
                            pg_phase=PodGroupPhase.RUNNING)
        hog = pods[0]
        hog.annotations.update(BE)
        kubectl.add_podgroup(pg)
        kubectl.add_pod(hog)

        # the agent lives on ITS OWN wire mirror, like a real node
        agent_view = client()
        wait_for(lambda: hog.key in agent_view.pods,
                 msg="agent mirror sees the pod")
        provider = FakeUsageProvider()
        provider.set("n0", cpu_fraction=0.2, tpu_chips_detected=0)
        cg = CgroupV2Enforcer(str(tmp_path / "cg"))
        clock = Clock()
        col = NetAccountingCollector(cg.root, now=clock)
        agent = NodeAgent(agent_view, "n0", provider, enforcer=cg,
                          net_collector=col)

        uid = agent_view.pods[hog.key].uid
        agent.sync()                   # tag cgroup, publish the split
        tx = 0
        write_counters(cg.root, uid, tx)
        clock.tick()
        agent.sync()                   # baseline counter reading
        for _ in range(7):             # 900 mbps vs 400 mbps watermark
            tx += 112_500_000
            write_counters(cg.root, uid, tx)
            clock.tick()
            agent.sync()

        # the violation reached the SERVER: report stored, node
        # annotations folded, pod annotations persisted
        server = state.cluster
        wait_for(lambda: server.bandwidthreports.get("n0") is not None
                 and server.bandwidthreports["n0"].violations == 1,
                 msg="report on server")
        assert server.nodes["n0"].annotations[
            NODE_SATURATED_ANNOTATION] == "true"
        assert server.pods[hog.key].annotations[
            POD_VIOLATING_ANNOTATION] == "true"
        assert int(server.pods[hog.key].annotations[
            POD_VIOLATIONS_ANNOTATION]) >= 3
        assert any(r == "BandwidthViolation"
                   for _, r, _ in server.events)
        # ... and over the GET route
        with urllib.request.urlopen(url + "/bandwidth?node=n0",
                                    timeout=5) as resp:
            body = json.load(resp)
        assert body["reports"]["n0"]["f"]["violations"] == 1

        # the scheduler's own wire mirror converges and evicts
        sched_view = client()
        wait_for(lambda: sched_view.pods.get(hog.key) is not None
                 and sched_view.pods[hog.key].annotations.get(
                     POD_VIOLATING_ANNOTATION) == "true"
                 and sched_view.nodes["n0"].annotations.get(
                     NODE_SATURATED_ANNOTATION) == "true",
                 msg="scheduler mirror convergence")
        conf = {"actions": "shuffle", "tiers": [{"plugins": [
            {"name": "gang"},
            {"name": "rescheduling", "arguments": {
                "rescheduling.interval": 0,
                "rescheduling.strategies": "bandwidthPressure"}}]}]}
        Scheduler(sched_view, conf=conf, schedule_period=0).run_once()
        wait_for(lambda: hog.key in server.evictions,
                 msg="bandwidthPressure eviction on server")
        assert server.pods[hog.key].phase is TaskStatus.RELEASING
    finally:
        for m in mirrors:
            m.close()
        httpd.shutdown()


# -- codec / CLI surfaces ----------------------------------------------

def test_bandwidth_report_codec_roundtrip():
    from volcano_tpu.api import codec
    from volcano_tpu.api.netusage import (BandwidthReport,
                                          PodBandwidthUsage)
    rep = BandwidthReport(
        node="n0", total_mbps=1000.0, offline_tx_mbps=700.0,
        online_tx_mbps=100.0, violations=1, saturated=True,
        usages=[PodBandwidthUsage(
            pod_key="default/hog", uid="u1", classid=21,
            tier="offline", tx_mbps=700.0, watermark_mbps=400.0,
            violating=True, violations=5)])
    back = codec.decode(codec.encode(rep))
    assert back.node == "n0" and back.saturated
    assert back.usages[0].classid == 21
    assert back.usages[0].violating and back.usages[0].violations == 5


def test_vtpctl_bandwidth_view(tmp_path, capsys):
    from volcano_tpu.api.netusage import (BandwidthReport,
                                          PodBandwidthUsage)
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.cli.vtpctl import main as vtpctl
    import pickle

    cluster = FakeCluster()
    cluster.bandwidthreports["n0"] = BandwidthReport(
        node="n0", total_mbps=1000.0, offline_tx_mbps=700.0,
        online_tx_mbps=100.0, violations=1, saturated=True,
        usages=[PodBandwidthUsage(
            pod_key="default/hog", uid="u1", classid=21,
            tier="offline", tx_mbps=700.0, watermark_mbps=400.0,
            violating=True, violations=5)])
    path = str(tmp_path / "c.pkl")
    with open(path, "wb") as f:
        pickle.dump(cluster, f)
    assert vtpctl(["--state", path, "bandwidth"]) == 0
    out = capsys.readouterr().out
    assert "default/hog" in out and "VIOLATING" in out
    assert "1:21" in out and "yes" in out       # classid + saturated
