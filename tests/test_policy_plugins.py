"""The long tail of policy plugins: sla/pdb/cdp/tdm/nodegroup/usage/
resourcequota/task-topology/resource-strategy-fit/numaaware/extender/
rescheduling + shuffle."""

import json
import time

from volcano_tpu.api.node_info import Node
from volcano_tpu.api.queue import Queue
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.types import (
    NODEGROUP_LABEL,
    PodGroupPhase,
    REVOCABLE_ZONE_ANNOTATION,
)
from volcano_tpu.uthelper import TestContext, gang_job


def conf_with(*plugin_specs, actions="enqueue, allocate, backfill"):
    plugins = [{"name": "gang"}, {"name": "predicates"},
               {"name": "nodeorder"}]
    plugins += [p if isinstance(p, dict) else {"name": p}
                for p in plugin_specs]
    return {"actions": actions, "tiers": [{"plugins": plugins}]}


def nodes(n, cpu="8", labels=None, annotations=None):
    return [Node(name=f"n{i}", allocatable={"cpu": cpu, "pods": 110},
                 labels=dict(labels or {}),
                 annotations=dict(annotations or {}))
            for i in range(n)]


def test_sla_breached_job_jumps_admission():
    pg, pods = gang_job("waiting", replicas=1, requests={"cpu": 1})
    pg.creation_time = time.time() - 3600
    pg.annotations["sla.volcano-tpu.io/waiting-time"] = "60"
    ctx = TestContext(nodes=nodes(1), podgroups=[pg], pods=pods,
                      conf=conf_with("sla"))
    ctx.run()
    ctx.expect_bind_num(1)


def test_pdb_blocks_eviction_below_min_available():
    from volcano_tpu.cache.cluster import PriorityClass
    pg_lo, pods_lo = gang_job("lo", replicas=2, min_available=1,
                              requests={"cpu": 4},
                              running_on=["n0", "n1"],
                              pg_phase=PodGroupPhase.RUNNING)
    for p in pods_lo:
        p.annotations["volcano-tpu.io/disruption-group"] = "db"
        p.annotations["volcano-tpu.io/min-available"] = "2"
    pg_hi, pods_hi = gang_job("hi", replicas=1, requests={"cpu": 4},
                              priority_class="high",
                              pg_phase=PodGroupPhase.INQUEUE)
    ctx = TestContext(
        nodes=nodes(2), podgroups=[pg_lo, pg_hi],
        pods=pods_lo + pods_hi,
        priority_classes=[PriorityClass("high", 1000)],
        conf=conf_with("priority", "pdb",
                       actions="enqueue, allocate, preempt"))
    ctx.run()
    ctx.expect_evict_num(0)  # PDB floor (2) vetoes the eviction


def test_cdp_shields_fresh_pods():
    from volcano_tpu.cache.cluster import PriorityClass
    pg_lo, pods_lo = gang_job("lo", replicas=2, min_available=1,
                              requests={"cpu": 4},
                              running_on=["n0", "n1"],
                              pg_phase=PodGroupPhase.RUNNING)
    for p in pods_lo:
        p.annotations["volcano-tpu.io/start-time"] = str(time.time())
    pg_hi, pods_hi = gang_job("hi", replicas=1, requests={"cpu": 4},
                              priority_class="high",
                              pg_phase=PodGroupPhase.INQUEUE)
    ctx = TestContext(
        nodes=nodes(2), podgroups=[pg_lo, pg_hi],
        pods=pods_lo + pods_hi,
        priority_classes=[PriorityClass("high", 1000)],
        conf=conf_with("priority", "cdp",
                       actions="enqueue, allocate, preempt"))
    ctx.run()
    ctx.expect_evict_num(0)  # still cooling down


def test_tdm_revocable_node_gating_and_shuffle():
    revocable = Node(name="rev0", allocatable={"cpu": 8},
                     labels={"volcano-tpu.io/revocable-zone": "night"})
    pg, pods = gang_job("batch", replicas=1, requests={"cpu": 1})
    pods[0].annotations[REVOCABLE_ZONE_ANNOTATION] = "night"
    conf = conf_with({"name": "tdm", "arguments":
                      {"tdm.revocable-zone.night": "*"}})
    ctx = TestContext(nodes=[revocable], podgroups=[pg], pods=pods,
                      conf=conf)
    ctx.run()
    ctx.expect_bind("default/batch-0", "rev0")

    # non-revocable pod cannot use the revocable node
    pg2, pods2 = gang_job("normal", replicas=1, requests={"cpu": 1})
    ctx2 = TestContext(nodes=[revocable], podgroups=[pg2], pods=pods2,
                       conf=conf)
    ctx2.run()
    ctx2.expect_bind_num(0)

    # window closed -> shuffle evicts the revocable pod
    pg3, pods3 = gang_job("evictme", replicas=1, min_available=0,
                          requests={"cpu": 1}, running_on=["rev0"],
                          pg_phase=PodGroupPhase.RUNNING)
    pods3[0].annotations[REVOCABLE_ZONE_ANNOTATION] = "night"
    conf3 = conf_with({"name": "tdm", "arguments":
                       {"tdm.revocable-zone.night": "23:59-23:59"}},
                      actions="shuffle")
    ctx3 = TestContext(nodes=[revocable], podgroups=[pg3], pods=pods3,
                       conf=conf3)
    ctx3.run(["shuffle"])
    ctx3.expect_evict_num(1)


def test_nodegroup_affinity():
    q = Queue(name="mlq")
    q.annotations["nodegroup.volcano-tpu.io/affinity"] = "ml-nodes"
    cluster_nodes = nodes(1, labels={NODEGROUP_LABEL: "ml-nodes"}) + \
        [Node(name="other", allocatable={"cpu": 8},
              labels={NODEGROUP_LABEL: "web"})]
    pg, pods = gang_job("mljob", queue="mlq", replicas=1,
                        requests={"cpu": 1})
    ctx = TestContext(nodes=cluster_nodes, queues=[q], podgroups=[pg],
                      pods=pods, conf=conf_with("nodegroup"))
    ctx.run()
    ctx.expect_bind("default/mljob-0", "n0")


def test_usage_threshold_filters_hot_nodes():
    hot = Node(name="hot", allocatable={"cpu": 8},
               annotations={"usage.volcano-tpu.io/cpu": "0.95"})
    cool = Node(name="cool", allocatable={"cpu": 8},
                annotations={"usage.volcano-tpu.io/cpu": "0.1"})
    pg, pods = gang_job("j", replicas=1, requests={"cpu": 1})
    ctx = TestContext(nodes=[hot, cool], podgroups=[pg], pods=pods,
                      conf=conf_with("usage"))
    ctx.run()
    ctx.expect_bind("default/j-0", "cool")


def test_resourcequota_blocks_over_quota_namespace():
    pg, pods = gang_job("quotajob", replicas=4, requests={"cpu": 4})
    ctx = TestContext(nodes=nodes(4), podgroups=[pg], pods=pods,
                      conf=conf_with("resourcequota"))
    ctx.cluster.config_maps["resourcequota/default"] = {"cpu": 8}
    ctx.run()
    ctx.expect_bind_num(0)
    ctx.expect_podgroup_phase("default/quotajob", PodGroupPhase.PENDING)


def test_task_topology_affinity_colocates():
    pg, pods = gang_job("pair", replicas=2, requests={"cpu": 1})
    pg.annotations["task-topology.volcano-tpu.io/affinity"] = \
        "worker/worker"
    ctx = TestContext(nodes=nodes(2, cpu="8"), podgroups=[pg], pods=pods,
                      conf=conf_with("task-topology"))
    ctx.run()
    bound_nodes = {n for _, n in ctx.cluster.binds}
    assert len(bound_nodes) == 1  # both workers co-located


def test_resource_strategy_fit_packs_tpu():
    tpu_nodes = [Node(name=f"t{i}", allocatable={"cpu": 8, TPU: 4})
                 for i in range(2)]
    # pre-load t1 with a 2-chip pod
    pg0, pods0 = gang_job("seed", replicas=1, requests={TPU: 2},
                          running_on=["t1"],
                          pg_phase=PodGroupPhase.RUNNING)
    pg, pods = gang_job("packme", replicas=1, requests={TPU: 2})
    ctx = TestContext(nodes=tpu_nodes, podgroups=[pg0, pg],
                      pods=pods0 + pods,
                      conf=conf_with({"name": "resource-strategy-fit",
                                      "arguments":
                                      {"resourceStrategyFitWeight": 5}}))
    ctx.run()
    ctx.expect_bind("default/packme-0", "t1")  # MostAllocated on chips


def test_numaaware_single_numa_policy():
    inventory = json.dumps({"0": {"cpu": 4, "tpu": 0},
                            "1": {"cpu": 4, "tpu": 0}})
    small_numa = Node(name="split", allocatable={"cpu": 8},
                      annotations={"numa.volcano-tpu.io/nodes": inventory})
    big_numa = Node(name="fat", allocatable={"cpu": 8},
                    annotations={"numa.volcano-tpu.io/nodes":
                                 json.dumps({"0": {"cpu": 8, "tpu": 0}})})
    pg, pods = gang_job("numajob", replicas=1, requests={"cpu": 6})
    pods[0].annotations["numa.volcano-tpu.io/policy"] = "single-numa-node"
    ctx = TestContext(nodes=[small_numa, big_numa], podgroups=[pg],
                      pods=pods, conf=conf_with("numaaware"))
    ctx.run()
    ctx.expect_bind("default/numajob-0", "fat")


def test_extender_in_process_hooks():
    from volcano_tpu.plugins.extender import _EXTENDERS, register_extender

    class VetoN0:
        def predicate(self, task, node):
            return "n0 is cursed" if node.name == "n0" else None

    register_extender("test-veto", VetoN0())
    try:
        pg, pods = gang_job("extjob", replicas=1, requests={"cpu": 1})
        ctx = TestContext(nodes=nodes(2), podgroups=[pg], pods=pods,
                          conf=conf_with("extender"))
        ctx.run()
        ctx.expect_bind("default/extjob-0", "n1")
    finally:
        _EXTENDERS.pop("test-veto", None)


def test_rescheduling_feeds_shuffle():
    busy = Node(name="busy", allocatable={"cpu": 8})
    idle = Node(name="idle", allocatable={"cpu": 8})
    pg, pods = gang_job("spread", replicas=2, min_available=0,
                        requests={"cpu": 4}, running_on=["busy"],
                        pg_phase=PodGroupPhase.RUNNING)
    conf = conf_with({"name": "rescheduling", "arguments":
                      {"rescheduling.interval": 0}}, actions="shuffle")
    ctx = TestContext(nodes=[busy, idle], podgroups=[pg], pods=pods,
                      conf=conf)
    ctx.run(["shuffle"])
    ctx.expect_evict_num(1)


def test_rescheduling_interval_scoped_per_scheduler():
    """Two schedulers in ONE process must not share the rescheduling
    rate limiter (VERDICT r2 weak 6: the limiter used to be a module
    global, so scheduler A's pass silenced scheduler B for a whole
    interval).  With a long interval, each scheduler still gets its own
    first pass; a second pass on the SAME scheduler is suppressed."""
    conf = conf_with({"name": "rescheduling", "arguments":
                      {"rescheduling.interval": 3600}}, actions="shuffle")

    def make_ctx():
        busy = Node(name="busy", allocatable={"cpu": 8})
        idle = Node(name="idle", allocatable={"cpu": 8})
        pg, pods = gang_job("spread", replicas=2, min_available=0,
                            requests={"cpu": 4}, running_on=["busy"],
                            pg_phase=PodGroupPhase.RUNNING)
        return TestContext(nodes=[busy, idle], podgroups=[pg],
                           pods=pods, conf=conf)

    a, b = make_ctx(), make_ctx()
    a.run(["shuffle"])
    a.expect_evict_num(1)
    # a fresh scheduler's own limiter starts at zero — A's pass must
    # not have consumed B's budget
    b.run(["shuffle"])
    b.expect_evict_num(1)
    # but the SAME scheduler within its interval stays quiet
    a.run(["shuffle"])
    a.expect_evict_num(1)


def test_numatopology_object_node_policy_gates_without_pod_optin():
    """A Numatopology with kubelet TopologyManagerPolicy=single-numa-node
    gates ALL pods on that node (reference numaaware: node policy rules),
    steering a 6-cpu task to the node whose cell can hold it."""
    from volcano_tpu.api.numatopology import tpu_host_numatopology
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    for node in nodes(2):
        cluster.add_node(node)
    # n0: 2 cells x 4 cpu (cannot hold 6 in one cell); n1: 1 cell x 8
    cluster.add_numatopology(tpu_host_numatopology(
        "n0", cpu_millis=8000, tpu_chips=0, numa_cells=2,
        policy="single-numa-node"))
    cluster.add_numatopology(tpu_host_numatopology(
        "n1", cpu_millis=8000, tpu_chips=0, numa_cells=1,
        policy="single-numa-node"))
    pg, pods = gang_job("numacrd", replicas=1, requests={"cpu": 6})
    ctx = TestContext(cluster=cluster, podgroups=[pg], pods=pods,
                      conf=conf_with("numaaware"))
    ctx.run()
    ctx.expect_bind("default/numacrd-0", "n1")


def test_numatopology_tpu_chip_split_and_pod_policy_escalation():
    """4-chip host split 2+2 across cells: a 4-chip single-numa pod is
    unschedulable there, and the pod annotation escalates over a
    best-effort node policy."""
    from volcano_tpu.api.numatopology import tpu_host_numatopology
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    cluster.add_node(Node(name="host", allocatable={
        "cpu": 112, "google.com/tpu": 4, "pods": 110}))
    topo = tpu_host_numatopology("host", cpu_millis=112000, tpu_chips=4,
                                 numa_cells=2, policy="best-effort")
    assert topo.numa_res["google.com/tpu"] == {"0": 2.0, "1": 2.0}
    cluster.add_numatopology(topo)
    pg, pods = gang_job("chips", replicas=1,
                        requests={"cpu": 8, TPU: 4})
    pods[0].annotations["numa.volcano-tpu.io/policy"] = "single-numa-node"
    ctx = TestContext(cluster=cluster, podgroups=[pg], pods=pods,
                      conf=conf_with("numaaware"))
    ctx.run()
    ctx.expect_bind_num(0)  # 4 chips can't come from one cell
    # best-effort alone (node policy) must NOT gate: drop the opt-in
    pods[0].annotations.pop("numa.volcano-tpu.io/policy")
    ctx2 = TestContext(cluster=cluster, podgroups=[pg], pods=pods,
                       conf=conf_with("numaaware"))
    ctx2.run()
    ctx2.expect_bind("default/chips-0", "host")


def test_numatopology_live_deduction_within_session():
    """numa_res is FREE space, and in-session placements are deducted:
    a 20-cpu node publishing two 8-cpu-free cells admits two 6-cpu
    single-numa pods (one per cell) but gates the third, even though
    the node still has 8 cpu idle overall."""
    from volcano_tpu.api.numatopology import Numatopology
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    cluster.add_node(Node(name="host",
                          allocatable={"cpu": 20, "pods": 110}))
    cluster.add_numatopology(Numatopology(
        name="host",
        numa_res={"cpu": {"0": 8000.0, "1": 8000.0}},
        policies={"TopologyManagerPolicy": "single-numa-node"}))
    pg, pods = gang_job("three", replicas=3, min_available=1,
                        requests={"cpu": 6})
    ctx = TestContext(cluster=cluster, podgroups=[pg], pods=pods,
                      conf=conf_with("numaaware"))
    ctx.run()
    ctx.expect_bind_num(2)


def test_numatopology_res_reserved_shrinks_cells():
    """res_reserved is spread across cells and subtracted from free."""
    from volcano_tpu.api.numatopology import Numatopology
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    cluster.add_node(Node(name="host",
                          allocatable={"cpu": 16, "pods": 110}))
    cluster.add_numatopology(Numatopology(
        name="host",
        numa_res={"cpu": {"0": 8000.0, "1": 8000.0}},
        policies={"TopologyManagerPolicy": "single-numa-node"},
        res_reserved={"cpu": 6000.0}))  # 3000 off each cell -> 5000 free
    pg, pods = gang_job("rsv", replicas=1, requests={"cpu": 6})
    ctx = TestContext(cluster=cluster, podgroups=[pg], pods=pods,
                      conf=conf_with("numaaware"))
    ctx.run()
    ctx.expect_bind_num(0)


def test_numatopology_agent_republish_across_cycles():
    """The node agent is the exporter: pods bound in earlier cycles
    shrink the published free cells, so a third 6-cpu single-numa pod
    is gated in cycle 3 even though sessions are fresh each cycle."""
    from volcano_tpu.agent import FakeUsageProvider, NodeAgent
    from volcano_tpu.api.numatopology import Numatopology
    from volcano_tpu.api.types import TaskStatus
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.scheduler import Scheduler
    cluster = FakeCluster()
    cluster.add_node(Node(name="host",
                          allocatable={"cpu": 20, "pods": 110}))
    cap = {"cpu": {"0": 8000.0, "1": 8000.0},
           "google.com/tpu": {"0": 0.0, "1": 0.0}}
    cluster.add_numatopology(Numatopology(
        name="host", numa_res={k: dict(v) for k, v in cap.items()},
        policies={"TopologyManagerPolicy": "single-numa-node"},
        capacity_res=cap))
    agent = NodeAgent(cluster, "host", FakeUsageProvider())
    sched = Scheduler(cluster, schedule_period=0, conf=conf_with(
        "numaaware"))
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION
    pg, _ = gang_job("one", replicas=0, min_available=1)
    cluster.add_podgroup(pg)
    for cycle in range(3):
        cluster.add_pod(make_pod(
            f"one-{cycle}", requests={"cpu": 6},
            annotations={GROUP_NAME_ANNOTATION: "one"}))
        sched.run_once()
        cluster.tick()          # bound -> running
        agent.sync()            # exporter republishes free cells
    assert len(cluster.binds) == 2, cluster.binds
    free = cluster.numatopologies["host"].numa_res["cpu"]
    assert sorted(free.values()) == [2000.0, 2000.0]


def test_numaaware_discarded_preempt_leaves_cells_intact():
    """evict(victim) -> unevict on statement discard must net to zero
    cell deduction: a later single-numa pod that fits must still fit."""
    from volcano_tpu.api.numatopology import Numatopology
    from volcano_tpu.cache.cache import SchedulerCache
    from volcano_tpu.cache.fake_cluster import FakeCluster
    from volcano_tpu.conf import load_conf
    from volcano_tpu.framework.framework import close_session, \
        open_session
    from volcano_tpu.framework.statement import Statement
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION, TaskStatus
    cluster = FakeCluster()
    cluster.add_node(Node(name="host",
                          allocatable={"cpu": 20, "pods": 110}))
    cluster.add_numatopology(Numatopology(
        name="host", numa_res={"cpu": {"0": 8000.0}},
        policies={"TopologyManagerPolicy": "single-numa-node"}))
    pg_v, _ = gang_job("victim", replicas=0, min_available=1)
    pg_n, _ = gang_job("newcomer", replicas=0, min_available=1)
    cluster.add_podgroup(pg_v)
    cluster.add_podgroup(pg_n)
    vic = make_pod("victim-0", requests={"cpu": 6}, node_name="host",
                   phase=TaskStatus.RUNNING,
                   annotations={GROUP_NAME_ANNOTATION: "victim"})
    new = make_pod("newcomer-0", requests={"cpu": 6},
                   annotations={GROUP_NAME_ANNOTATION: "newcomer"})
    cluster.add_pod(vic)
    cluster.add_pod(new)
    ssn = open_session(SchedulerCache(cluster), load_conf(
        conf_with("numaaware")))
    tasks = {t.name: t for j in ssn.jobs.values()
             for t in j.tasks.values()}
    node = ssn.nodes["host"]
    stmt = Statement(ssn)
    stmt.evict(tasks["victim-0"], "trial")
    stmt.discard()   # abandoned preemption: unevict fires allocate
    assert ssn.predicate(tasks["newcomer-0"], node) is None, \
        "discarded preempt leaked a phantom NUMA deduction"
    close_session(ssn)


def test_numaaware_preemption_frees_occupied_cell():
    """A high-priority single-numa pod preempts a BE victim out of a
    fully-occupied cell: the resolvable gate lets preempt try the
    node, eviction credits the victim's cell, the preemptor lands."""
    from volcano_tpu.api.numatopology import Numatopology
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION, \
        PodGroupPhase, TaskStatus
    from volcano_tpu.cache.cluster import PriorityClass
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    cluster.add_node(Node(name="host",
                          allocatable={"cpu": 8, "pods": 110}))
    # exporter already accounted the running victim: cell free = 2000
    cluster.add_numatopology(Numatopology(
        name="host", numa_res={"cpu": {"0": 2000.0}},
        policies={"TopologyManagerPolicy": "single-numa-node"}))
    cluster.add_priority_class(PriorityClass("high", 1000))
    # min_available=0: an elastic victim whose gang floor survives
    # the eviction (gang's preemptable veto protects the floor)
    pg_v, _ = gang_job("victim", replicas=0, min_available=0,
                       pg_phase=PodGroupPhase.RUNNING)
    pg_h, _ = gang_job("hi", replicas=0, min_available=1,
                       priority_class="high",
                       pg_phase=PodGroupPhase.INQUEUE)
    cluster.add_podgroup(pg_v)
    cluster.add_podgroup(pg_h)
    vic = make_pod("victim-0", requests={"cpu": 6}, node_name="host",
                   phase=TaskStatus.RUNNING,
                   annotations={GROUP_NAME_ANNOTATION: "victim",
                                "volcano-tpu.io/preemptable": "true"})
    hi = make_pod("hi-0", requests={"cpu": 6},
                  annotations={GROUP_NAME_ANNOTATION: "hi"})
    cluster.add_pod(vic)
    cluster.add_pod(hi)
    ctx = TestContext(cluster=cluster, conf=conf_with(
        "priority", "numaaware", actions="enqueue, allocate, preempt"))
    ctx.run()
    assert cluster.evictions == ["default/victim-0"], cluster.evictions


def test_numaaware_oversized_request_never_triggers_eviction():
    """A request bigger than EVERY cell's capacity is unresolvable:
    preempt must not churn victims it can never benefit from."""
    from volcano_tpu.api.numatopology import Numatopology
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION, \
        PodGroupPhase, TaskStatus
    from volcano_tpu.cache.cluster import PriorityClass
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    cluster.add_node(Node(name="host",
                          allocatable={"cpu": 8, "pods": 110}))
    cap = {"cpu": {"0": 4000.0, "1": 4000.0},
           "google.com/tpu": {"0": 0.0, "1": 0.0}}
    cluster.add_numatopology(Numatopology(
        name="host", numa_res={"cpu": {"0": 1000.0, "1": 1000.0}},
        policies={"TopologyManagerPolicy": "single-numa-node"},
        capacity_res=cap))
    cluster.add_priority_class(PriorityClass("high", 1000))
    pg_v, _ = gang_job("victim", replicas=0, min_available=0,
                       pg_phase=PodGroupPhase.RUNNING)
    pg_h, _ = gang_job("hi", replicas=0, min_available=1,
                       priority_class="high",
                       pg_phase=PodGroupPhase.INQUEUE)
    cluster.add_podgroup(pg_v)
    cluster.add_podgroup(pg_h)
    for i in range(2):
        cluster.add_pod(make_pod(
            f"victim-{i}", requests={"cpu": 3}, node_name="host",
            phase=TaskStatus.RUNNING,
            annotations={GROUP_NAME_ANNOTATION: "victim",
                         "volcano-tpu.io/preemptable": "true"}))
    cluster.add_pod(make_pod(
        "hi-0", requests={"cpu": 6},
        annotations={GROUP_NAME_ANNOTATION: "hi"}))
    ctx = TestContext(cluster=cluster, conf=conf_with(
        "priority", "numaaware", actions="enqueue, allocate, preempt"))
    ctx.run()
    ctx.expect_evict_num(0)     # 6000m can never fit a 4000m cell
    ctx.expect_bind_num(0)


def test_preempt_rolls_back_uncured_evictions():
    """Victims whose eviction does NOT cure the waved-through failure
    are rolled back, not committed: cells [1000,1000] with capacity
    [4000,4000], 3500m preemptor, but the only victims are 500m each —
    evicting all of them still leaves no 3500m cell, so nothing is
    evicted."""
    from volcano_tpu.api.numatopology import Numatopology
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION, \
        PodGroupPhase, TaskStatus
    from volcano_tpu.cache.cluster import PriorityClass
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    cluster.add_node(Node(name="host",
                          allocatable={"cpu": 8, "pods": 110}))
    cap = {"cpu": {"0": 4000.0, "1": 4000.0},
           "google.com/tpu": {"0": 0.0, "1": 0.0}}
    cluster.add_numatopology(Numatopology(
        name="host", numa_res={"cpu": {"0": 1000.0, "1": 1000.0}},
        policies={"TopologyManagerPolicy": "single-numa-node"},
        capacity_res=cap))
    cluster.add_priority_class(PriorityClass("high", 1000))
    pg_v, _ = gang_job("victim", replicas=0, min_available=0,
                       pg_phase=PodGroupPhase.RUNNING)
    pg_h, _ = gang_job("hi", replicas=0, min_available=1,
                       priority_class="high",
                       pg_phase=PodGroupPhase.INQUEUE)
    cluster.add_podgroup(pg_v)
    cluster.add_podgroup(pg_h)
    for i in range(2):
        cluster.add_pod(make_pod(
            f"victim-{i}", requests={"cpu": 0.5}, node_name="host",
            phase=TaskStatus.RUNNING,
            annotations={GROUP_NAME_ANNOTATION: "victim",
                         "volcano-tpu.io/preemptable": "true"}))
    cluster.add_pod(make_pod(
        "hi-0", requests={"cpu": 3.5},
        annotations={GROUP_NAME_ANNOTATION: "hi"}))
    ctx = TestContext(cluster=cluster, conf=conf_with(
        "priority", "numaaware", actions="enqueue, allocate, preempt"))
    ctx.run()
    ctx.expect_evict_num(0)     # uncured evictions rolled back


def test_preempt_skips_non_evict_curable_resolvable_failures():
    """A usage-threshold failure is resolvable but not curable by
    eviction: preempt must skip the node (no victim churn) exactly as
    it did before predicate_for_preempt existed."""
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION, \
        PodGroupPhase, TaskStatus
    from volcano_tpu.cache.cluster import PriorityClass
    from volcano_tpu.cache.fake_cluster import FakeCluster
    cluster = FakeCluster()
    cluster.add_node(Node(
        name="hot", allocatable={"cpu": 8, "pods": 110},
        annotations={"usage.volcano-tpu.io/cpu": "0.99"}))
    cluster.add_priority_class(PriorityClass("high", 1000))
    pg_v, _ = gang_job("victim", replicas=0, min_available=0,
                       pg_phase=PodGroupPhase.RUNNING)
    pg_h, _ = gang_job("hi", replicas=0, min_available=1,
                       priority_class="high",
                       pg_phase=PodGroupPhase.INQUEUE)
    cluster.add_podgroup(pg_v)
    cluster.add_podgroup(pg_h)
    cluster.add_pod(make_pod(
        "victim-0", requests={"cpu": 6}, node_name="hot",
        phase=TaskStatus.RUNNING,
        annotations={GROUP_NAME_ANNOTATION: "victim",
                     "volcano-tpu.io/preemptable": "true"}))
    cluster.add_pod(make_pod(
        "hi-0", requests={"cpu": 6},
        annotations={GROUP_NAME_ANNOTATION: "hi"}))
    ctx = TestContext(cluster=cluster, conf=conf_with(
        "priority", "usage", actions="enqueue, allocate, preempt"))
    ctx.run()
    ctx.expect_evict_num(0)   # over-threshold node: skip, don't churn


def test_reclaim_cross_queue_numa_cure_and_rollback():
    """Reclaim's eviction-cure guard, cross-queue: queue-b reclaims a
    queue-a victim out of an occupied cell when (a) proportion's
    deserved math allows the eviction and (b) the freed cell cures the
    NUMA gate; an oversized request triggers no evictions at all."""
    from volcano_tpu.api.numatopology import Numatopology
    from volcano_tpu.api.pod import make_pod
    from volcano_tpu.api.types import GROUP_NAME_ANNOTATION, TaskStatus
    from volcano_tpu.cache.fake_cluster import FakeCluster

    def build(need, cell_cap, qb_weight=1):
        cluster = FakeCluster()
        cluster.add_node(Node(name="host",
                              allocatable={"cpu": 6, "pods": 110}))
        cap = {"cpu": dict(cell_cap),
               "google.com/tpu": {k: 0.0 for k in cell_cap}}
        cluster.add_numatopology(Numatopology(
            name="host",
            numa_res={"cpu": {k: 0.0 for k in cell_cap},
                      "google.com/tpu": {k: 0.0 for k in cell_cap}},
            policies={"TopologyManagerPolicy": "single-numa-node"},
            capacity_res=cap))
        cluster.add_queue(Queue(name="qa", weight=1))
        cluster.add_queue(Queue(name="qb", weight=qb_weight))
        pg_v, _ = gang_job("victim", queue="qa", replicas=0,
                           min_available=0,
                           pg_phase=PodGroupPhase.RUNNING)
        pg_r, _ = gang_job("reclaimer", queue="qb", replicas=0,
                           min_available=1,
                           pg_phase=PodGroupPhase.INQUEUE)
        cluster.add_podgroup(pg_v)
        cluster.add_podgroup(pg_r)
        # qa fills the whole 6-cpu node with four 1.5-cpu pods;
        # proportion deserved: qa 4.5, qb 1.5 -> exactly one victim
        # may be reclaimed before qa dips below deserved
        for i in range(4):
            cluster.add_pod(make_pod(
                f"victim-{i}", requests={"cpu": 1.5}, node_name="host",
                phase=TaskStatus.RUNNING,
                annotations={GROUP_NAME_ANNOTATION: "victim",
                             "volcano-tpu.io/preemptable": "true"}))
        cluster.add_pod(make_pod(
            "reclaimer-0", requests={"cpu": need},
            annotations={GROUP_NAME_ANNOTATION: "reclaimer"}))
        return cluster

    conf = conf_with("proportion", "numaaware",
                     actions="enqueue, allocate, reclaim")
    # curable: one 6000m cell, fully used; evicting one victim credits
    # 1500m which exactly cures the gate for a 1500m reclaimer
    ctx = TestContext(cluster=build(1.5, {"0": 6000.0}), conf=conf)
    ctx.run()
    ctx.expect_evict_num(1)
    # oversized: a 3000m request vs two 2500m cells is unresolvable.
    # With qb weight 3, proportion's deserved math WOULD permit the two
    # 1.5-cpu victims reclaim needs (verified: dropping numaaware from
    # the conf makes this scenario evict) — only the NUMA capacity
    # gate blocks the node, so the assertion is on numaaware alone.
    conf_no_numa = conf_with("proportion",
                             actions="enqueue, allocate, reclaim")
    ctx_ctl = TestContext(cluster=build(3, {"0": 2500.0, "1": 2500.0},
                                        qb_weight=3),
                          conf=conf_no_numa)
    ctx_ctl.run()
    assert len(ctx_ctl.cluster.evictions) > 0, \
        "control: proportion alone must permit this reclaim"
    ctx2 = TestContext(cluster=build(3, {"0": 2500.0, "1": 2500.0},
                                     qb_weight=3), conf=conf)
    ctx2.run()
    ctx2.expect_evict_num(0)


# -- nodeorder scorer parity (nodeorder.go:51-66) ----------------------

def test_nodeorder_preferred_node_affinity():
    from volcano_tpu.api.pod import PreferredNodeTerm
    pg, pods = gang_job("pref", replicas=1, requests={"cpu": 1})
    pods[0].preferred_node_affinity = [
        PreferredNodeTerm(weight=10, term={"disk": ["ssd"]})]
    ns = nodes(3)
    ns[2].labels["disk"] = "ssd"
    ctx = TestContext(nodes=ns, podgroups=[pg], pods=pods,
                      conf=conf_with())
    ctx.run()
    ctx.expect_bind("default/pref-0", "n2")


def test_nodeorder_taint_toleration_prefers_untainted():
    from volcano_tpu.api.pod import Taint, Toleration
    pg, pods = gang_job("tt", replicas=1, requests={"cpu": 1})
    ns = nodes(2)
    ns[0].taints = [Taint(key="maint", value="yes",
                          effect="PreferNoSchedule")]
    ctx = TestContext(nodes=ns, podgroups=[pg], pods=pods,
                      conf=conf_with())
    ctx.run()
    ctx.expect_bind("default/tt-0", "n1")

    # a toleration neutralizes the penalty: the scorer ranks the
    # tainted node at full score for a tolerating pod
    from volcano_tpu.api.node_info import NodeInfo
    from volcano_tpu.plugins.nodeorder import MAX_SCORE, NodeOrderPlugin
    plug = NodeOrderPlugin({})
    tainted = NodeInfo(Node(name="t", allocatable={"cpu": "8"},
                            taints=[Taint(key="maint", value="yes",
                                          effect="PreferNoSchedule")]))
    pg2, pods2 = gang_job("tt2", replicas=1, requests={"cpu": 1})
    from volcano_tpu.api.job_info import TaskInfo
    task = TaskInfo(pods2[0])
    assert plug._taint_toleration_score(task, tainted) == 0.0
    pods2[0].tolerations = [Toleration(key="maint", value="yes",
                                       effect="PreferNoSchedule")]
    assert plug._taint_toleration_score(TaskInfo(pods2[0]),
                                        tainted) == MAX_SCORE


def test_nodeorder_image_locality():
    pg, pods = gang_job("img", replicas=1, requests={"cpu": 1})
    pods[0].containers[0].image = "trainer:v3"
    ns = nodes(3)
    ns[1].images = ["trainer:v3", "base:latest"]
    ctx = TestContext(
        nodes=ns, podgroups=[pg], pods=pods,
        conf=conf_with({"name": "nodeorder",
                        "arguments": {"imagelocality.weight": 50}}))
    ctx.run()
    ctx.expect_bind("default/img-0", "n1")


def test_sra_keeps_cpu_pods_off_tpu_hosts():
    pg, pods = gang_job("cpuonly", replicas=1, requests={"cpu": 1})
    tpu_host = Node(name="tpuhost",
                    allocatable={"cpu": "8", "pods": 110, TPU: "4"})
    cpu_host = Node(name="cpuhost",
                    allocatable={"cpu": "8", "pods": 110})
    ctx = TestContext(
        nodes=[tpu_host, cpu_host], podgroups=[pg], pods=pods,
        conf=conf_with({"name": "resource-strategy-fit",
                        "arguments": {"sra.weight": 20,
                                      "sra.resources": TPU}}))
    ctx.run()
    ctx.expect_bind("default/cpuonly-0", "cpuhost")


def test_pod_topology_spread_scorer_prefers_sparse_domain():
    # BOTH replicas pending: after sp-0 lands in one zone, the scorer
    # must steer sp-1 to the other — this exercises the in-session
    # placement sensitivity that a cached per-spec NodeOrder score
    # would get wrong (the scorer is a per-task BatchNodeOrder fn)
    pg, pods = gang_job("sp", replicas=2, requests={"cpu": 1})
    for p in pods:
        p.annotations["spread.volcano-tpu.io/topology-key"] = "zone"
        p.annotations["spread.volcano-tpu.io/max-skew"] = "2"
    ns = nodes(4)
    for i, n in enumerate(ns):
        n.labels["zone"] = "a" if i < 2 else "b"
    ctx = TestContext(
        nodes=ns, podgroups=[pg], pods=pods,
        conf=conf_with({"name": "pod-topology-spread",
                        "arguments": {"podtopologyspread.weight": 50}}))
    ctx.run()
    zones = {"n0": "a", "n1": "a", "n2": "b", "n3": "b"}
    bound = [zones[ctx.bind_map[f"default/sp-{i}"]] for i in range(2)]
    assert sorted(bound) == ["a", "b"]


def test_normal_pod_hypernode_binpack_packs_busy_slice():
    from volcano_tpu.api.hypernode import HyperNode
    # two 2-host slices under one pod-tier domain; slice s0 is busy
    ns = nodes(4, cpu="8")
    filler_pg, filler = gang_job("filler", replicas=1,
                                 requests={"cpu": 4},
                                 running_on=["n0"],
                                 pg_phase=PodGroupPhase.RUNNING)
    pg, pods = gang_job("normal", replicas=1, requests={"cpu": 1})
    hns = [HyperNode.of_nodes("s0", 1, ["n0", "n1"]),
           HyperNode.of_nodes("s1", 1, ["n2", "n3"]),
           HyperNode.of_children("pod0", 2, ["s0", "s1"])]
    ctx = TestContext(
        nodes=ns, podgroups=[filler_pg, pg], pods=filler + pods,
        hypernodes=hns,
        conf=conf_with({"name": "network-topology-aware",
                        "arguments": {"weight": 50}}))
    ctx.run()
    assert ctx.bind_map["default/normal-0"] in ("n0", "n1")

    # disabled -> the normal-pod scorer contributes nothing
    from volcano_tpu.plugins.topology import NetworkTopologyAwarePlugin
    off = NetworkTopologyAwarePlugin(
        {"hypernode.binpack.normal-pod.enable": False})
    off.ssn = ctx.last_session
    assert off._normal_pod_binpack_scores() == {}
    on = NetworkTopologyAwarePlugin({})
    on.ssn = ctx.last_session
    scores = on._normal_pod_binpack_scores()
    assert scores["s0"] > scores["s1"]


def test_binpack_reference_key_aliases():
    from volcano_tpu.plugins.binpack import BinpackPlugin
    p = BinpackPlugin({"binpack.cpu": 7, "binpack.memory": 3,
                       "binpack.resources.google.com/tpu": 11})
    assert p.dim_weights["cpu"] == 7.0
    assert p.dim_weights["memory"] == 3.0
    assert p.dim_weights[TPU] == 11.0


def test_rescheduling_tpu_fragmentation_defrag():
    """tpuFragmentation strategy: two half-used TPU hosts exist; the
    emptier donor's sub-host pack is victimized so re-allocation can
    pack the receiver and free a whole host for slice gangs."""
    hosts = [Node(name=f"h{i}", allocatable={"cpu": 16, TPU: 4,
                                             "pods": 110})
             for i in range(3)]
    # h0: 1 chip used (donor), h1: 2 chips used (receiver), h2: free
    pg_a, pods_a = gang_job("packa", replicas=1, min_available=0,
                            requests={"cpu": 1, TPU: 1},
                            running_on=["h0"],
                            pg_phase=PodGroupPhase.RUNNING)
    pg_b, pods_b = gang_job("packb", replicas=1, min_available=0,
                            requests={"cpu": 1, TPU: 2},
                            running_on=["h1"],
                            pg_phase=PodGroupPhase.RUNNING)
    conf = conf_with(
        {"name": "rescheduling",
         "arguments": {"rescheduling.interval": 0,
                       "rescheduling.strategies": "tpuFragmentation"}},
        actions="shuffle")
    ctx = TestContext(nodes=hosts, podgroups=[pg_a, pg_b],
                      pods=pods_a + pods_b, conf=conf)
    ctx.run(["shuffle"])
    # the 1-chip pack on the emptier host is the victim; the 2-chip
    # receiver pack stays put
    ctx.expect_evict_num(1)
    assert ctx.cluster.evictions[0] == "default/packa-0"


def test_rescheduling_victim_cap_and_priority_threshold():
    """maxVictims bounds a pass; tasks at/above thresholdPriority are
    never victimized even on hot nodes."""
    busy = [Node(name=f"b{i}", allocatable={"cpu": 8}) for i in range(3)]
    idle = Node(name="idle", allocatable={"cpu": 64})
    pgs, pods = [], []
    for i, n in enumerate(busy):
        pg, ps = gang_job(f"hot{i}", replicas=2, min_available=0,
                          requests={"cpu": 4}, running_on=[n.name],
                          pg_phase=PodGroupPhase.RUNNING)
        if i == 0:
            for p in ps:
                p.priority = 5_000_000_000          # protected
        pgs.append(pg)
        pods.extend(ps)
    conf = conf_with(
        {"name": "rescheduling",
         "arguments": {"rescheduling.interval": 0,
                       "rescheduling.maxVictims": 1,
                       "rescheduling.thresholdPriority": 1_000_000}},
        actions="shuffle")
    ctx = TestContext(nodes=busy + [idle], podgroups=pgs, pods=pods,
                      conf=conf)
    ctx.run(["shuffle"])
    ctx.expect_evict_num(1)                        # capped at 1
    assert not ctx.cluster.evictions[0].startswith("default/hot0"), \
        "priority-protected task was victimized"
