"""Slice-failure failover: detect → drain → reschedule → resume.

The subsystem under test (ISSUE 3) connects pieces that previously
existed in isolation:

  agent      (agent/handlers.py TpuHealthHandler): chip health with
      K-consecutive-ticks hysteresis BOTH directions + SliceHealthReport
      wire objects (api/slicehealth.py), folded into node annotations
      by the store;
  controller (controllers/failover.py): declares the SLICE failed,
      drains the resident gang with ONE job-level restart, stamps
      resume metadata, quarantines behind a flap-damping TTL, and
      times every phase into the failover_* metric families;
  scheduler  (plugins/failover.py): quarantined hosts filtered,
      requeued gangs get allocation priority, optional warm spares;
  workload   (jax plugin → bootstrap → checkpoint.resume_state):
      VTP_RESUME_STEP / VTP_CHECKPOINT_DIR carry the resume contract
      into the worker, which restores from orbax instead of
      recomputing from step 0;
  wire e2e   : the full loop through a real HTTP state server.
"""

import time

import pytest

from volcano_tpu.agent.agent import FakeUsageProvider, NodeAgent
from volcano_tpu.agent.handlers import TpuHealthHandler
from volcano_tpu.api.pod import make_pod
from volcano_tpu.api.podgroup import NetworkTopologySpec
from volcano_tpu.api.resource import TPU
from volcano_tpu.api.slicehealth import (
    CHECKPOINT_DIR_ANNOTATION,
    FAILOVER_GENERATION_ANNOTATION,
    LAST_STEP_ANNOTATION,
    NODE_HEALTH_ANNOTATION,
    NODE_QUARANTINED_UNTIL_ANNOTATION,
    REQUEUED_ANNOTATION,
    RESUME_STEP_ANNOTATION,
    SliceHealthReport,
    VERDICT_FAILED,
    VERDICT_HEALTHY,
    VERDICT_SUSPECT,
)
from volcano_tpu.api.types import (
    JobPhase,
    NetworkTopologyMode,
    TPU_SLICE_LABEL,
    TaskStatus,
)
from volcano_tpu.api.vcjob import TaskSpec, VCJob
from volcano_tpu.controllers import ControllerManager
from volcano_tpu.controllers.failover import FailoverController
from volcano_tpu.scheduler import Scheduler
from volcano_tpu.simulator import fail_host, heal_host, make_tpu_cluster

FAILOVER_CONF = {
    "actions": "enqueue, allocate, backfill",
    "tiers": [
        {"plugins": [{"name": "priority"}, {"name": "gang"},
                     {"name": "failover"}, {"name": "conformance"}]},
        {"plugins": [{"name": "overcommit"}, {"name": "drf"},
                     {"name": "predicates"}, {"name": "proportion"},
                     {"name": "nodeorder"}, {"name": "binpack"},
                     {"name": "deviceshare"},
                     {"name": "network-topology-aware"}]},
    ],
}


def tpu_gang_job(name="train", replicas=4, annotations=None,
                 run_ticks=None):
    from volcano_tpu.api.types import RUN_TICKS_ANNOTATION
    pod_ann = {}
    if run_ticks is not None:
        pod_ann[RUN_TICKS_ANNOTATION] = str(run_ticks)
    return VCJob(
        name=name, min_available=replicas,
        annotations=dict(annotations or {}),
        network_topology=NetworkTopologySpec(
            NetworkTopologyMode.HARD, 1),
        plugins={"jax": []},
        tasks=[TaskSpec(name="worker", replicas=replicas,
                        template=make_pod(
                            "t", requests={"cpu": 8, TPU: 4},
                            annotations=pod_ann))])


# -- agent: K-consecutive-ticks verdict + SliceHealthReport ------------

def test_health_hysteresis_verdict_ladder_and_report():
    """One bad sample -> Suspect (report posted, NOT cordoned); K bad
    -> Failed (cordon + event, exactly once); one good sample resets
    nothing visible; K good -> Healthy (uncordon + event)."""
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    provider = FakeUsageProvider()
    agent = NodeAgent(cluster, "sa-w0", provider)
    node = cluster.nodes["sa-w0"]

    fail_host(cluster, "sa-w0", provider=provider, chips_healthy=3)
    agent.sync()
    rep = cluster.slicehealthreports["sa-w0"]
    assert rep.verdict == VERDICT_SUSPECT
    assert rep.slice == "sa" and rep.chips_healthy == 3
    assert rep.first_bad_ts > 0
    assert node.unschedulable is False
    # store folded the verdict into node annotations for every mirror
    assert node.annotations[NODE_HEALTH_ANNOTATION] == VERDICT_SUSPECT

    for _ in range(TpuHealthHandler.FAIL_SYNCS - 1):
        agent.sync()
    rep = cluster.slicehealthreports["sa-w0"]
    assert rep.verdict == VERDICT_FAILED
    assert node.unschedulable is True
    assert node.annotations[NODE_HEALTH_ANNOTATION] == VERDICT_FAILED
    assert [r for _, r, _ in cluster.events].count("TPUUnhealthy") == 1

    heal_host(cluster, "sa-w0", provider=provider)
    agent.sync()
    assert node.unschedulable is True          # one good tick: hold
    assert cluster.slicehealthreports["sa-w0"].verdict == VERDICT_FAILED
    for _ in range(TpuHealthHandler.RECOVER_SYNCS - 1):
        agent.sync()
    assert node.unschedulable is False
    assert cluster.slicehealthreports["sa-w0"].verdict == VERDICT_HEALTHY
    assert NODE_HEALTH_ANNOTATION not in node.annotations
    assert any(r == "TPURecovered" for _, r, _ in cluster.events)


def test_health_flap_never_reaches_failed():
    """Alternating bad/good samples (the flappiness the old handler
    cordoned on) never escalate past Suspect and never cordon."""
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    provider = FakeUsageProvider()
    agent = NodeAgent(cluster, "sa-w0", provider)
    for _ in range(4):
        fail_host(cluster, "sa-w0", provider=provider, chips_healthy=3)
        agent.sync()
        heal_host(cluster, "sa-w0", provider=provider)
        agent.sync()
    node = cluster.nodes["sa-w0"]
    assert node.unschedulable is False
    assert not any(r == "TPUUnhealthy" for _, r, _ in cluster.events)


def test_slicehealth_codec_roundtrip():
    from volcano_tpu.api import codec
    rep = SliceHealthReport(node="sa-w0", slice="sa",
                            verdict=VERDICT_FAILED, chips_detected=4,
                            chips_healthy=1, consecutive_bad=3,
                            first_bad_ts=123.5)
    back = codec.decode(codec.encode(rep))
    assert back.node == "sa-w0" and back.slice == "sa"
    assert back.verdict == VERDICT_FAILED
    assert back.consecutive_bad == 3 and back.first_bad_ts == 123.5


def test_health_fold_sticky_and_dies_with_node():
    """A whole-node write from a stale mirror cannot erase the folded
    verdict; a node delete drops the report so a replacement host is
    not born Failed."""
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.cache.fake_cluster import FakeCluster

    cluster = FakeCluster()
    cluster.add_node(Node(name="n0", allocatable={"cpu": "8"}))
    cluster.put_object("slicehealthreport", SliceHealthReport(
        node="n0", slice="sa", verdict=VERDICT_FAILED))
    assert cluster.nodes["n0"].annotations[
        NODE_HEALTH_ANNOTATION] == VERDICT_FAILED
    stale = Node(name="n0", allocatable={"cpu": "8"},
                 annotations={"somebody": "else"})
    cluster.put_object("node", stale)
    ann = cluster.nodes["n0"].annotations
    assert ann["somebody"] == "else"
    assert ann[NODE_HEALTH_ANNOTATION] == VERDICT_FAILED
    cluster.delete_object("node", "n0")
    assert "n0" not in cluster.slicehealthreports
    cluster.put_object("node", Node(name="n0",
                                    allocatable={"cpu": "8"}))
    assert NODE_HEALTH_ANNOTATION not in \
        cluster.nodes["n0"].annotations


# -- controller: declare -> drain -> quarantine ------------------------

def drive(cluster, mgr, sched, n=1, agent=None):
    for _ in range(n):
        if agent is not None:
            agent.sync()
        mgr.sync_all()
        sched.run_once()
        cluster.tick()


def start_running_gang(annotations=None):
    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    mgr = ControllerManager(cluster, enabled=["job", "podgroup",
                                              "queue", "failover"])
    sched = Scheduler(cluster, conf=FAILOVER_CONF, schedule_period=0)
    job = tpu_gang_job(annotations=annotations or {
        CHECKPOINT_DIR_ANNOTATION: "/ckpt/train",
        LAST_STEP_ANNOTATION: "42"})
    cluster.add_vcjob(job)
    drive(cluster, mgr, sched, 4)
    job = cluster.vcjobs["default/train"]
    assert job.phase is JobPhase.RUNNING
    victim = sorted(p.node_name for p in cluster.pods.values()
                    if p.owner == job.uid)[0]
    return cluster, mgr, sched, job, victim


def gang_slices(cluster, job):
    return {cluster.nodes[p.node_name].labels[TPU_SLICE_LABEL]
            for p in cluster.pods.values()
            if p.owner == job.uid and p.node_name}


def test_failover_drains_with_one_job_restart_and_stamps_resume():
    """Slice failure -> ONE RestartJob (no per-pod policy cascade, no
    maxRetry burn), podgroup + job stamped with generation/resume
    metadata, every slice host quarantined, gang re-placed off the
    failed slice, MTTR metrics observed, requeued marker cleared."""
    from volcano_tpu import metrics

    cluster, mgr, sched, job, victim = start_running_gang()
    victim_slice = cluster.nodes[victim].labels[TPU_SLICE_LABEL]
    retries_before = job.retry_count

    fail_host(cluster, victim)         # direct mode: agent-equivalent
    drive(cluster, mgr, sched, 12)

    job = cluster.vcjobs["default/train"]
    assert job.phase is JobPhase.RUNNING
    assert job.annotations[FAILOVER_GENERATION_ANNOTATION] == "1"
    assert job.annotations[RESUME_STEP_ANNOTATION] == "42"
    assert job.retry_count == retries_before   # not a policy retry
    assert gang_slices(cluster, job) == {"sb" if victim_slice == "sa"
                                         else "sa"}
    pg = cluster.podgroups["default/train"]
    assert pg.annotations[FAILOVER_GENERATION_ANNOTATION] == "1"
    assert pg.annotations[RESUME_STEP_ANNOTATION] == "42"
    assert pg.annotations[CHECKPOINT_DIR_ANNOTATION] == "/ckpt/train"
    assert REQUEUED_ANNOTATION not in pg.annotations  # episode done
    for node in cluster.nodes.values():
        quarantined = NODE_QUARANTINED_UNTIL_ANNOTATION in \
            node.annotations
        assert quarantined == (
            node.labels[TPU_SLICE_LABEL] == victim_slice)
    # the whole loop was timed
    assert metrics.get_observations("failover_mttr_seconds",
                                    slice=victim_slice)
    assert metrics.get_observations("failover_detect_seconds",
                                    slice=victim_slice)
    reasons = [r for _, r, _ in cluster.events]
    assert "SliceFailed" in reasons and "FailoverDrain" in reasons
    assert "FailoverComplete" in reasons
    # new workers carry the resume contract (jax plugin injection)
    pod = next(p for p in cluster.pods.values() if p.owner == job.uid)
    assert pod.containers[0].env["VTP_RESUME_STEP"] == "42"
    assert pod.containers[0].env["VTP_CHECKPOINT_DIR"] == "/ckpt/train"


def test_quarantine_ttl_lifts_only_after_healthy():
    """Quarantined -> Healthy requires BOTH the TTL served and the
    host verdicts back to Healthy (a sick slice stays out past its
    TTL; a healed one re-enters only after the TTL)."""
    cluster = make_tpu_cluster([("sa", "v5e-16")])
    clock = {"t": 1000.0}
    ctrl = FailoverController(quarantine_ttl=60.0,
                              now=lambda: clock["t"])
    ctrl.initialize(cluster)
    fail_host(cluster, "sa-w0")
    ctrl.sync()
    n0 = cluster.nodes["sa-w0"]
    assert float(n0.annotations[
        NODE_QUARANTINED_UNTIL_ANNOTATION]) == pytest.approx(1060.0)
    # TTL served but the host is still Failed: quarantine re-arms
    # WITHOUT re-declaring (one hardware death = one SliceFailed, not
    # one per TTL expiry)
    clock["t"] = 1070.0
    ctrl.sync()
    assert float(n0.annotations[NODE_QUARANTINED_UNTIL_ANNOTATION]) \
        == pytest.approx(1130.0)
    assert [r for _, r, _ in cluster.events].count("SliceFailed") == 1
    # host heals: quarantine holds until the NEW TTL is served...
    heal_host(cluster, "sa-w0")
    clock["t"] = 1100.0
    ctrl.sync()
    assert NODE_QUARANTINED_UNTIL_ANNOTATION in n0.annotations
    # ...then lifts, with an event
    clock["t"] = 1131.0
    ctrl.sync()
    for node in cluster.nodes.values():
        assert NODE_QUARANTINED_UNTIL_ANNOTATION not in node.annotations
    assert any(r == "SliceRecovered" for _, r, _ in cluster.events)


def test_bare_podgroup_gang_is_evicted_whole():
    """A podgroup with no vcjob owner still gets a gang-level drain
    (evictions) + resume stamp — not silently skipped."""
    from volcano_tpu.uthelper import gang_job
    from volcano_tpu.api.types import PodGroupPhase

    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pg, pods = gang_job("bare", replicas=2,
                        requests={"cpu": 4, TPU: 4},
                        running_on=["sa-w0", "sa-w1"],
                        pg_phase=PodGroupPhase.RUNNING)
    pg.annotations[LAST_STEP_ANNOTATION] = "7"
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    ctrl = FailoverController()
    ctrl.initialize(cluster)
    fail_host(cluster, "sa-w0")
    ctrl.sync()
    assert sorted(cluster.evictions) == ["default/bare-0",
                                         "default/bare-1"]
    pg = cluster.podgroups["default/bare"]
    assert pg.annotations[FAILOVER_GENERATION_ANNOTATION] == "1"
    assert pg.annotations[RESUME_STEP_ANNOTATION] == "7"
    assert pg.annotations[REQUEUED_ANNOTATION] == "true"


def test_active_quarantine_sticky_across_stale_node_write():
    """A whole-node persist from a mirror that predates the stamp (the
    victim's own agent) must not erase an ACTIVE quarantine; an
    expired one is removable — that is how the controller lifts it."""
    from volcano_tpu.api.node_info import Node
    from volcano_tpu.cache.fake_cluster import FakeCluster

    cluster = FakeCluster()
    active = time.time() + 300
    cluster.add_node(Node(name="n0", allocatable={"cpu": "8"},
                          annotations={
                              NODE_QUARANTINED_UNTIL_ANNOTATION:
                              f"{active:.3f}"}))
    stale = Node(name="n0", allocatable={"cpu": "8"},
                 annotations={"agent": "write"})
    cluster.put_object("node", stale)
    ann = cluster.nodes["n0"].annotations
    assert ann["agent"] == "write"
    assert float(ann[NODE_QUARANTINED_UNTIL_ANNOTATION]) == \
        pytest.approx(active, abs=1e-3)
    # expired: the removal (controller lift) lands
    ann[NODE_QUARANTINED_UNTIL_ANNOTATION] = f"{time.time() - 5:.3f}"
    cluster.put_object("node", cluster.nodes["n0"])
    lifted = Node(name="n0", allocatable={"cpu": "8"})
    cluster.put_object("node", lifted)
    assert NODE_QUARANTINED_UNTIL_ANNOTATION not in \
        cluster.nodes["n0"].annotations


def test_episode_abandoned_when_drained_job_terminates():
    """A drained gang that never resumes (user abort post-drain) must
    retire its episode — no MTTR observation, no forever-scan."""
    from volcano_tpu import metrics
    from volcano_tpu.api.types import JobAction

    cluster, mgr, sched, job, victim = start_running_gang()
    victim_slice = cluster.nodes[victim].labels[TPU_SLICE_LABEL]
    before = len(metrics.get_observations("failover_mttr_seconds",
                                          slice=victim_slice))
    fail_host(cluster, victim)
    drive(cluster, mgr, sched, 2)      # declared + drain issued
    cluster.add_command("default/train", JobAction.ABORT_JOB.value)
    drive(cluster, mgr, sched, 8)
    ctrl = next(c for c in mgr.controllers if c.name == "failover")
    assert not ctrl._episodes
    assert len(metrics.get_observations("failover_mttr_seconds",
                                        slice=victim_slice)) == before
    assert any(r == "FailoverAbandoned" for _, r, _ in cluster.events)


# -- scheduler plugin --------------------------------------------------

def test_quarantined_slice_filtered_for_all_tasks():
    from volcano_tpu.uthelper import TestContext, gang_job

    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    until = time.time() + 300
    for name, node in cluster.nodes.items():
        if node.labels[TPU_SLICE_LABEL] == "sa":
            node.annotations[NODE_QUARANTINED_UNTIL_ANNOTATION] = \
                f"{until:.3f}"
    pg, pods = gang_job("j", replicas=4,
                        requests={"cpu": 8, TPU: 4})
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    sched = Scheduler(cluster, conf=FAILOVER_CONF, schedule_period=0)
    sched.run_once()
    homes = {cluster.pods[k].node_name for k in cluster.pods
             if cluster.pods[k].node_name}
    assert homes and all(
        cluster.nodes[h].labels[TPU_SLICE_LABEL] == "sb"
        for h in homes)
    # an EXPIRED quarantine is no filter
    past = time.time() - 5
    for node in cluster.nodes.values():
        if NODE_QUARANTINED_UNTIL_ANNOTATION in node.annotations:
            node.annotations[NODE_QUARANTINED_UNTIL_ANNOTATION] = \
                f"{past:.3f}"
    pg2, pods2 = gang_job("j2", replicas=4,
                          requests={"cpu": 8, TPU: 4})
    cluster.add_podgroup(pg2)
    for p in pods2:
        cluster.add_pod(p)
    sched.run_once()
    assert all(p.node_name for p in cluster.pods.values()
               if p.name.startswith("j2-"))


def test_requeued_gang_gets_allocation_priority():
    """Two gangs contend for the one free slice; the requeued
    (failover) gang wins although it is YOUNGER than the other."""
    from volcano_tpu.uthelper import gang_job

    cluster = make_tpu_cluster([("sa", "v5e-16")])
    pg_old, pods_old = gang_job("older", replicas=4,
                                requests={"cpu": 8, TPU: 4})
    pg_new, pods_new = gang_job("requeued", replicas=4,
                                requests={"cpu": 8, TPU: 4})
    pg_old.creation_time = 100.0
    pg_new.creation_time = 200.0       # younger: FIFO would lose
    pg_new.annotations[REQUEUED_ANNOTATION] = "true"
    for pg, pods in ((pg_old, pods_old), (pg_new, pods_new)):
        cluster.add_podgroup(pg)
        for p in pods:
            cluster.add_pod(p)
    sched = Scheduler(cluster, conf=FAILOVER_CONF, schedule_period=0)
    sched.run_once()
    placed = {p.name.rsplit("-", 1)[0] for p in cluster.pods.values()
              if p.node_name}
    assert placed == {"requeued"}


def test_warm_spares_reserved_for_failover_traffic():
    """warmSpares=1 holds one idle slice per shape: an ordinary gang
    is steered to the other slice; a requeued gang may take the
    spare."""
    from volcano_tpu.uthelper import gang_job

    conf = {
        "actions": "enqueue, allocate, backfill",
        "tiers": [
            {"plugins": [{"name": "priority"}, {"name": "gang"},
                         {"name": "failover", "arguments": {
                             "failover.warmSpares": 1}},
                         {"name": "conformance"}]},
            FAILOVER_CONF["tiers"][1],
        ],
    }
    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    pg, pods = gang_job("normal", replicas=4,
                        requests={"cpu": 8, TPU: 4})
    cluster.add_podgroup(pg)
    for p in pods:
        cluster.add_pod(p)
    sched = Scheduler(cluster, conf=conf, schedule_period=0)
    sched.run_once()
    homes = {cluster.nodes[p.node_name].labels[TPU_SLICE_LABEL]
             for p in cluster.pods.values() if p.node_name}
    assert homes == {"sb"}             # sa (sorted first) is the spare

    pg2, pods2 = gang_job("rq", replicas=4,
                          requests={"cpu": 8, TPU: 4})
    pg2.annotations[REQUEUED_ANNOTATION] = "true"
    cluster.add_podgroup(pg2)
    for p in pods2:
        cluster.add_pod(p)
    sched.run_once()
    rq_homes = {cluster.nodes[p.node_name].labels[TPU_SLICE_LABEL]
                for p in cluster.pods.values()
                if p.node_name and p.name.startswith("rq-")}
    assert rq_homes == {"sa"}          # the spare serves failover


# -- workload resume contract ------------------------------------------

def test_bootstrap_parses_resume_env():
    from volcano_tpu.workloads import bootstrap
    info = bootstrap.from_env({
        "TPU_WORKER_ID": "0",
        "VTP_CHECKPOINT_DIR": "/ckpt/j",
        "VTP_RESUME_STEP": "42"})
    assert info.checkpoint_dir == "/ckpt/j"
    assert info.resume_step == 42
    assert bootstrap.from_env({}).resume_step is None
    assert bootstrap.from_env(
        {"VTP_RESUME_STEP": "junk"}).resume_step is None


def test_resume_state_guards(tmp_path):
    """A stamped resume step with no checkpoint is an error (silent
    step-0 recompute is the failure mode this subsystem exists to
    kill); no stamp + no checkpoint = fresh start."""
    from volcano_tpu.workloads import checkpoint
    p, o, step = checkpoint.resume_state("params", "opt", environ={})
    assert (p, o, step) == ("params", "opt", 0)
    with pytest.raises(FileNotFoundError):
        checkpoint.resume_state(
            "params", "opt",
            environ={"VTP_CHECKPOINT_DIR": str(tmp_path / "none"),
                     "VTP_RESUME_STEP": "5"})


def test_dryrun_kill_and_resume_loss_continuity(tmp_path):
    """The acceptance dryrun: train to step 3 (checkpointing), kill
    the 'gang', resume a fresh worker from the stamped env — the
    post-resume losses are IDENTICAL to the uninterrupted run's steps
    4..5 (no recompute from step 0, no trajectory change)."""
    import jax

    from volcano_tpu.workloads import checkpoint, model as model_lib, train
    from volcano_tpu.workloads.mesh import make_mesh

    mesh = make_mesh({"dp": 1, "fsdp": 2, "tp": 2, "sp": 2})
    cfg = model_lib.tiny_config()
    opt = train.make_optimizer(lr=1e-2, warmup_steps=1)
    params, state, _ = train.init_sharded(jax.random.key(0), cfg,
                                          mesh, opt)
    step_fn = train.make_train_step(cfg, mesh, opt)
    batch = train.synthetic_batch(jax.random.key(1), cfg, 4, 64, mesh)

    ckpt = str(tmp_path / "ckpt")
    losses = {}
    for step in range(1, 6):
        params, state, m = step_fn(params, state, batch)
        losses[step] = float(m["loss"])
        if step == 3:
            checkpoint.save(ckpt, step=step, params=params,
                            opt_state=state)

    # "slice dies" — a fresh worker process boots with the env the
    # failover controller stamped and the jax plugin injected
    env = {"VTP_CHECKPOINT_DIR": ckpt, "VTP_RESUME_STEP": "3"}
    p2, s2, _ = train.init_sharded(jax.random.key(99), cfg, mesh, opt)
    p2, s2, start = checkpoint.resume_state(p2, s2, environ=env)
    assert start == 3                  # >= the stamped floor
    resumed = {}
    for step in range(start + 1, 6):
        p2, s2, m = step_fn(p2, s2, batch)
        resumed[step] = float(m["loss"])
    assert resumed[4] == losses[4] and resumed[5] == losses[5]
    # and the trajectory is NOT the from-scratch one (the continuity
    # assert would pass vacuously if steps 4,5 were scratch steps 1,2)
    assert resumed[4] != losses[1]


# -- CLI surfaces ------------------------------------------------------

def test_vtpctl_slices_and_failover_views(tmp_path, capsys):
    import pickle

    from volcano_tpu.cli.vtpctl import main as vtpctl

    cluster = make_tpu_cluster([("sa", "v5e-16"), ("sb", "v5e-16")])
    fail_host(cluster, "sa-w0")
    until = 2_000_000_000.0
    for node in cluster.nodes.values():
        if node.labels[TPU_SLICE_LABEL] == "sa":
            node.annotations[NODE_QUARANTINED_UNTIL_ANNOTATION] = \
                f"{until:.3f}"
    from volcano_tpu.uthelper import gang_job
    pg, pods = gang_job("g", replicas=1)
    pg.annotations.update({FAILOVER_GENERATION_ANNOTATION: "2",
                           REQUEUED_ANNOTATION: "true",
                           RESUME_STEP_ANNOTATION: "42",
                           CHECKPOINT_DIR_ANNOTATION: "/ckpt/g"})
    cluster.add_podgroup(pg)
    path = str(tmp_path / "c.pkl")
    with open(path, "wb") as f:
        pickle.dump(cluster, f)

    assert vtpctl(["--state", path, "slices"]) == 0
    out = capsys.readouterr().out
    sa_row = next(l for l in out.splitlines() if l.startswith("sa"))
    assert "Failed" in sa_row and "2033" in sa_row   # until year
    sb_row = next(l for l in out.splitlines() if l.startswith("sb"))
    assert "Healthy" in sb_row and "-" in sb_row

    assert vtpctl(["--state", path, "failover"]) == 0
    out = capsys.readouterr().out
    assert "sa-w0" in out and "Failed" in out
    assert "default/g" in out and "42" in out and "/ckpt/g" in out


# -- e2e: the full loop through the real HTTP state server -------------

def wait_for(cond, timeout=20.0, msg="condition"):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def test_failover_loop_over_wire(tmp_path):
    """Acceptance e2e: agent posts SliceHealthReport over the wire →
    failover controller (own mirror) drains the gang → scheduler (own
    mirror) re-places it on a healthy slice with the quarantined one
    filtered → the rebuilt workers' env carries VTP_RESUME_STEP ≥ the
    last checkpointed step."""
    from volcano_tpu.api.devices.tpu.topology import slice_for
    from volcano_tpu.cache.remote_cluster import RemoteCluster
    from volcano_tpu.server.state_server import serve
    from volcano_tpu.simulator import slice_nodes

    httpd, state = serve(port=0)
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    mirrors = []

    def client(**kw):
        c = RemoteCluster(url, **kw)
        mirrors.append(c)
        return c

    mgr = None
    try:
        kubectl = client()
        for sname in ("sa", "sb"):
            for node in slice_nodes(slice_for(sname, "v5e-16"),
                                    dcn_pod="dcn-0"):
                kubectl.add_node(node)

        ctrl_view = client()
        mgr = ControllerManager(ctrl_view, enabled=[
            "job", "podgroup", "queue", "hypernode", "failover"])
        sched_view = client()
        sched = Scheduler(sched_view, conf=FAILOVER_CONF,
                          schedule_period=0)

        def cycle():
            mgr.sync_all()
            sched.run_once()
            state.cluster.tick()

        kubectl.add_vcjob(tpu_gang_job(annotations={
            CHECKPOINT_DIR_ANNOTATION: "/ckpt/train",
            LAST_STEP_ANNOTATION: "42"}))

        def running():
            cycle()
            j = kubectl.vcjobs.get("default/train")
            return j is not None and j.phase is JobPhase.RUNNING
        wait_for(running, msg="gang running over the wire")
        job = kubectl.vcjobs["default/train"]
        victim = sorted(p.node_name for p in kubectl.pods.values()
                        if p.owner == job.uid)[0]
        victim_slice = kubectl.nodes[victim].labels[TPU_SLICE_LABEL]
        healthy_slice = "sb" if victim_slice == "sa" else "sa"

        # the agent lives on ITS OWN wire mirror, like a real node
        agent_view = client()
        provider = FakeUsageProvider()
        agent = NodeAgent(agent_view, victim, provider)
        fail_host(agent_view, victim, provider=provider)
        for _ in range(TpuHealthHandler.FAIL_SYNCS):
            agent.sync()
        # the report reached the SERVER and was folded
        wait_for(lambda: (state.cluster.slicehealthreports.get(victim)
                          or SliceHealthReport()).verdict
                 == VERDICT_FAILED, msg="Failed report on server")

        def recovered():
            cycle()
            j = kubectl.vcjobs.get("default/train")
            if j is None or j.phase is not JobPhase.RUNNING or \
                    j.annotations.get(
                        FAILOVER_GENERATION_ANNOTATION) != "1":
                return False
            placed = [p for p in kubectl.pods.values()
                      if p.owner == j.uid and p.node_name
                      and p.phase in (TaskStatus.BOUND,
                                      TaskStatus.RUNNING)]
            return len(placed) >= 4 and all(
                kubectl.nodes[p.node_name].labels[TPU_SLICE_LABEL]
                == healthy_slice for p in placed)
        wait_for(recovered, timeout=40,
                 msg="gang re-placed on the healthy slice")

        job = kubectl.vcjobs["default/train"]
        # quarantine visible on every mirror via folded node events
        assert all(
            NODE_QUARANTINED_UNTIL_ANNOTATION in n.annotations
            for n in kubectl.nodes.values()
            if n.labels[TPU_SLICE_LABEL] == victim_slice)
        # resume contract on the rebuilt workers: env stamped from the
        # controller's resume-step snapshot
        pod = next(p for p in state.cluster.pods.values()
                   if p.owner == job.uid)
        assert int(pod.containers[0].env["VTP_RESUME_STEP"]) >= 42
        assert pod.containers[0].env["VTP_CHECKPOINT_DIR"] == \
            "/ckpt/train"
        assert any(r == "SliceFailed" for _, r, _ in
                   state.cluster.events)
    finally:
        if mgr is not None:
            mgr.stop()
        for m in mirrors:
            m.close()
        httpd.shutdown()


def test_bench_failover_smoke_mode():
    """`bench.py --failover-smoke` kills one fake host and asserts the
    gang re-reaches Running with a bumped failover generation inside
    the cycle budget — the failover loop guarded on every commit,
    mirroring --wire-smoke."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"),
         "--failover-smoke"],
        capture_output=True, text=True, timeout=180, env=env, cwd=repo)
    assert proc.returncode == 0, \
        proc.stdout[-2000:] + proc.stderr[-2000:]
    line = next(l for l in reversed(proc.stdout.strip().splitlines())
                if l.startswith("{"))
    out = json.loads(line)
    assert out["ok"] is True, out
    assert out["mttr_p50_s"] > 0
    assert out["breakdown_p50_s"]["detect"] >= 0
    assert out["cycles_to_recover"] and \
        all(c <= 40 for c in out["cycles_to_recover"])
